"""Branchless decision math for every in-kernel algorithm — shared by both
kernel generations (ops/kernel.py v1 f32-carrier planes, ops/kernel2.py v2
packed rows).

Token and leaky bucket are the exact decision tables of the reference's
algorithms.go, expressed as masked vector arithmetic over per-row stored
state + request fields; all file:line citations are
/root/reference/algorithms.go unless noted. GCRA, sliding-window counters
and concurrency leases are this repo's extensions (docs/algorithms.md has
the per-algorithm derivations); GCRA follows the ATM Forum virtual-
scheduling formulation as popularized by brandur/throttled (one
theoretical-arrival-time compare-and-advance per row, integer-ms exact).
The deliberate divergences are documented in ops/kernel2.py's module
docstring.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from gubernator_tpu.types import Algorithm, Behavior, Status

i64 = jnp.int64
f64 = jnp.float64


class StoredState(NamedTuple):
    """Per-row stored slot state, as int64/float64 (already decoded from
    whichever table layout the kernel uses)."""

    limit: jnp.ndarray  # int64
    burst: jnp.ndarray  # int64
    rem_i: jnp.ndarray  # int64 (remaining-style integer lane; see below)
    algo: jnp.ndarray  # int32
    status: jnp.ndarray  # int32
    duration: jnp.ndarray  # int64
    stamp: jnp.ndarray  # int64 (CreatedAt / UpdatedAt; window start for
    # SLIDING_WINDOW rows)
    exp: jnp.ndarray  # int64 (ExpireAt, ms exact)
    rem_f: jnp.ndarray  # float64 (leaky remaining — REMF lane pair as f32+f32)
    # int64 (REMF lane pair RAW: GCRA theoretical arrival time;
    # SLIDING_WINDOW previous-window count; 0 otherwise). Defaults to None
    # for legacy token/leaky-only callers (the v1 oracle kernel), which is
    # treated as all-zeros.
    aux: jnp.ndarray = None


# Integer-lane storage convention (docs/algorithms.md "State layout"): the
# REM_I lane always stores a REMAINING-style value — token remaining,
# sliding-window `limit - current_count`, lease `limit - inflight` — so the
# conservative-merge rule `remaining = min` (kernel2.merge2) and the
# checkpoint-replay bound tighten admission for EVERY algorithm without
# per-algo cases on the merge's integer lane. The REMF pair is algorithm-
# typed: leaky splits its float64 remainder into two f32 lanes; GCRA and
# sliding-window store a raw int64 (TAT / previous-window count) in the
# same two cells — `aux` above.


class Decision(NamedTuple):
    """Everything the write + response phases need."""

    # stored-state writeback
    status_out: jnp.ndarray  # int32
    rem_i_out: jnp.ndarray  # int64
    rem_f_out: jnp.ndarray  # float64
    stamp_out: jnp.ndarray  # int64
    dur_out: jnp.ndarray  # int64
    exp_out: jnp.ndarray  # int64
    burst_out: jnp.ndarray  # int64
    flags_out: jnp.ndarray  # int32 (algo | status << 8)
    remove: jnp.ndarray  # bool — slot is removed (RESET_REMAINING)
    aux_out: jnp.ndarray  # int64 — raw REMF pair writeback (GCRA TAT /
    # sliding-window previous count; 0 for token/leaky/lease)
    # response
    resp_status: jnp.ndarray  # int32
    resp_rem: jnp.ndarray  # int64
    resp_reset: jnp.ndarray  # int64


def bucket_math(
    s: StoredState, req, exists: jnp.ndarray, *, mode: str = "mixed"
) -> Decision:
    """One decision per row. `req` is a ReqBatch (ops/batch.py); `exists` marks
    rows whose slot held a live matching item (lazy-expiry already applied).

    `mode` is a STATIC specialization picked host-side per dispatch
    (engine._math_mode):

    * "token" — every row is a token bucket (the common case): no other
      algorithm's lanes are traced, and in particular no emulated-float64
      op is emitted.
    * "gcra" — every ACTIVE row is GCRA: only the TAT compare-and-advance
      lanes are traced (padding rows carry algo=0 and ride them harmlessly
      — inactive rows are never written or counted). The single-algorithm
      specialization that makes GCRA's smaller decision table actually
      pay at the headline geometry.
    * "int" — token + GCRA + sliding-window + lease lanes (all int64), but
      no leaky float64 path.
    * "mixed" — everything, including the leaky f64 lanes TPUs emulate in
      software.

    A runtime `lax.cond` was measured WORSE than the branchless merge
    (+~2.6 ms at 131K rows): the HLO conditional materializes its operand
    tuple (the gathered slots among them) and blocks fusion across the
    boundary."""
    if mode not in ("token", "gcra", "int", "mixed"):
        raise ValueError(f"unknown math mode {mode!r}")
    return _bucket_math_impl(s, req, exists, mode=mode)


def _bucket_math_impl(
    s: StoredState, req, exists: jnp.ndarray, *, mode: str
) -> Decision:
    now = req.created_at
    is_greg = (req.behavior & int(Behavior.DURATION_IS_GREGORIAN)) != 0
    is_reset = (req.behavior & int(Behavior.RESET_REMAINING)) != 0
    is_drain = (req.behavior & int(Behavior.DRAIN_OVER_LIMIT)) != 0
    is_token = req.algo == int(Algorithm.TOKEN_BUCKET)
    h = req.hits

    # Existing-item path applies only when algorithms agree; a stored item of
    # the other algorithm is discarded and recreated ("client switched
    # algorithms", go:96-105,307-317).
    algo_match = exists & (s.algo == req.algo)

    # Negative hits against a key with NO live matching state are a no-op,
    # not an install, for the EXTENSION algorithms (GCRA, sliding-window,
    # concurrency-lease — algo >= 2): the thing being released no longer
    # exists (expired, evicted, or never seen), so writing a fresh slot
    # would resurrect state from a pure return — a fresh lease row
    # installed by a release would hold a full TTL for nothing. Such rows
    # answer a full bucket and REMOVE (write an empty slot), the same
    # writeback RESET_REMAINING uses; live-state releases are separately
    # clamped per extension lane (docs/leases.md "Miss-safe returns").
    # Token and leaky buckets are deliberately EXCLUDED: the reference's
    # negative hits bank credit — remaining may exceed the limit, even on
    # a fresh key (functional_test.go:297, TestGlobalNegativeHits) — and
    # that wire behavior is pinned by the parity suite.
    neg_miss = (h < 0) & ~algo_match & (req.algo >= 2)

    OVER = jnp.int32(int(Status.OVER_LIMIT))
    UNDER = jnp.int32(int(Status.UNDER_LIMIT))

    # ==================================================== GCRA
    # Virtual scheduling (ATM Forum / brandur-throttled formulation), all
    # int64 ms arithmetic over ONE stored field — the theoretical arrival
    # time (TAT, StoredState.aux). Emission interval T = duration/limit;
    # tolerance tau = T·burst (burst defaults to limit at pack, so tau ≈
    # duration). A request of h hits advances TAT by h·T from max(TAT, now)
    # and conforms iff the advanced TAT stays within tau of now. State is
    # self-expiring: once now ≥ TAT the bucket is indistinguishable from a
    # fresh one, so ExpireAt = TAT and TTL eviction reclaims exactly the
    # rows whose state no longer matters (docs/algorithms.md "GCRA").
    # Factored out because it serves TWO static modes: the all-GCRA
    # specialization below (only these lanes traced — the headline
    # single-algorithm graph) and the int/mixed merges further down.
    def gcra_lanes():
        s_aux = s.aux if s.aux is not None else jnp.zeros_like(s.stamp)
        g_T = jnp.maximum(req.duration // jnp.maximum(req.limit, 1), i64(1))
        g_tau = g_T * req.burst
        # fresh/expired/switched-algo rows behave as TAT = now — the
        # new-item rule and the existing-item rule are the same
        # compare-and-advance
        g_tat0 = jnp.maximum(jnp.where(algo_match, s_aux, now), now)
        # releases (h < 0) rewind the TAT but never below `now` — a fresh
        # bucket is the most a return can restore (remaining ≤ burst), the
        # GCRA analog of the token clamp at `limit`
        g_tat1 = jnp.maximum(g_tat0 + h * g_T, now)
        g_deny = (h > 0) & (g_tat1 - g_tau > now)
        # deny: rejected hits don't advance (unless DRAIN_OVER_LIMIT, which
        # consumes the whole tolerance — the "drain to empty" analog of
        # token's remaining=0 rule)
        g_tat_out = jnp.where(
            g_deny, jnp.where(is_drain, now + g_tau, g_tat0), g_tat1
        )
        g_rem = jnp.clip((now + g_tau - g_tat_out) // g_T, 0, req.burst)
        # fully-available time; with the default burst == limit this is
        # exactly the TAT (tau = limit·T), mirroring token's "reset =
        # window expiry"
        g_reset = g_tat_out - g_tau + g_T * req.limit
        # DENIED rows (without DRAIN) report the EXACT conforming instant
        # instead: the earliest now' with tat0 + h·T - tau ≤ now' — the
        # TAT-derived retry_after bound clients back off to (PR-11).
        # reset > now by the deny condition itself; internal rebuilds
        # (GLOBAL installs from reset_time) only ever read zero-hit or
        # DRAIN-forced responses, which keep the TAT meaning above.
        g_reset = jnp.where(g_deny & ~is_drain, g_tat1 - g_tau, g_reset)
        g_status = jnp.where(g_deny, OVER, UNDER)
        # RESET_REMAINING removes the item outright and reports a full
        # bucket (token semantics, go:82-94); a miss-release removes too
        # (see neg_miss above — a return must never install fresh state)
        g_rm = (exists & is_reset) | neg_miss
        return dict(
            tat=g_tat_out,
            exp=jnp.maximum(g_tat_out, now),
            status=g_status,
            remove=g_rm,
            resp_status=jnp.where(g_rm, UNDER, g_status),
            resp_rem=jnp.where(g_rm, req.burst, g_rem),
            resp_reset=jnp.where(g_rm, i64(0), g_reset),
        )

    if mode == "gcra":
        # every active row is GCRA (engine._math_mode): no token lanes, no
        # f64, no window/lease arithmetic — padding rows (algo=0) ride the
        # TAT lanes harmlessly (never written, never counted)
        g = gcra_lanes()
        return Decision(
            status_out=g["status"],
            rem_i_out=jnp.zeros_like(s.rem_i),
            rem_f_out=jnp.zeros_like(s.rem_f),
            stamp_out=jnp.broadcast_to(now, s.stamp.shape),
            dur_out=req.duration,
            exp_out=g["exp"],
            burst_out=req.burst,
            flags_out=req.algo | (g["status"] << 8),
            remove=g["remove"],
            aux_out=g["tat"],
            resp_status=g["resp_status"],
            resp_rem=g["resp_rem"],
            resp_reset=g["resp_reset"],
        )

    # ==================================================== token bucket
    # reference algorithms.go:37-252

    # --- existing item (go:107-194)
    # limit change: add the delta to remaining, clamp at 0 (go:108-115)
    t_rem = jnp.where(
        s.limit != req.limit, jnp.maximum(s.rem_i + req.limit - s.limit, 0), s.rem_i
    )
    # duration change (go:125-146): recompute expiry from the item's CreatedAt;
    # if that would place us already expired, renew the bucket.
    dur_changed = s.duration != req.duration
    expire_dc = jnp.where(is_greg, req.expire_new, s.stamp + req.duration)
    renew = dur_changed & (expire_dc <= now)
    expire_dc = jnp.where(renew, now + req.duration, expire_dc)
    t_created = jnp.where(renew, now, s.stamp)
    t_rem = jnp.where(renew, req.limit, t_rem)
    t_exp = jnp.where(dur_changed, expire_dc, s.exp)
    t_reset = t_exp

    zero_hits = h == 0
    at_limit = (t_rem == 0) & (h > 0)  # go:161-168
    exact = ~zero_hits & ~at_limit & (t_rem == h)  # go:171-175
    overask = ~zero_hits & ~at_limit & ~exact & (h > t_rem)  # go:179-190
    consume = ~zero_hits & ~at_limit & ~exact & ~overask  # go:192-194

    # negative hits add back through the consume branch WITHOUT a top
    # clamp: remaining may exceed the limit, matching the reference's
    # credit-banking semantics (functional_test.go:297)
    tok_rem_out = jnp.where(
        exact | (overask & is_drain), i64(0), jnp.where(consume, t_rem - h, t_rem)
    )
    # response status starts from the stored (sticky) status (go:117-122); only
    # the at-limit branch persists OVER back to the item (go:165-166).
    tok_resp_status = jnp.where(at_limit | overask, OVER, s.status)
    tok_stored_status = jnp.where(at_limit, OVER, s.status)
    tok_resp_rem = tok_rem_out
    tok_resp_reset = t_reset

    # --- new item (go:202-252)
    new_over = h > req.limit
    # h < 0 on a fresh slot banks credit past the limit (reference rule)
    tokn_rem = jnp.where(new_over, req.limit, req.limit - h)
    tokn_status = jnp.where(new_over, OVER, UNDER)
    tokn_exp = req.expire_new

    tok_is_new = ~algo_match
    tok_status_out = jnp.where(tok_is_new, UNDER, tok_stored_status)
    tok_rem_store = jnp.where(tok_is_new, tokn_rem, tok_rem_out)
    tok_created_out = jnp.where(tok_is_new, now, t_created)
    tok_exp_out = jnp.where(tok_is_new, tokn_exp, t_exp)
    tok_resp_status = jnp.where(tok_is_new, tokn_status, tok_resp_status)
    tok_resp_rem = jnp.where(tok_is_new, tokn_rem, tok_resp_rem)
    tok_resp_reset = jnp.where(tok_is_new, tokn_exp, tok_resp_reset)

    # RESET_REMAINING on an existing item removes it outright and reports a
    # full bucket (go:82-94) — modeled as writing back an empty slot.
    # (neg_miss never marks token rows — see its algo >= 2 scope.)
    tok_reset_rm = exists & is_reset
    tok_resp_status = jnp.where(tok_reset_rm, UNDER, tok_resp_status)
    tok_resp_rem = jnp.where(tok_reset_rm, req.limit, tok_resp_rem)
    tok_resp_reset = jnp.where(tok_reset_rm, i64(0), tok_resp_reset)

    if mode == "token":
        # all request rows are token buckets: every other algorithm's lanes
        # collapse to constants and no float64 op is emitted on this branch
        zero_f = jnp.zeros_like(s.rem_f)
        return Decision(
            status_out=tok_status_out,
            rem_i_out=tok_rem_store,
            rem_f_out=zero_f,
            stamp_out=tok_created_out,
            dur_out=req.duration,
            exp_out=tok_exp_out,
            burst_out=jnp.zeros_like(s.burst),
            flags_out=req.algo | (tok_status_out << 8),
            remove=tok_reset_rm,
            aux_out=jnp.zeros_like(s.stamp),
            resp_status=tok_resp_status,
            resp_rem=tok_resp_rem,
            resp_reset=tok_resp_reset,
        )

    # ==================================================== GCRA (shared
    # lanes — see gcra_lanes above)
    s_aux = s.aux if s.aux is not None else jnp.zeros_like(s.stamp)
    _g = gcra_lanes()
    g_tat_out, g_exp, g_status = _g["tat"], _g["exp"], _g["status"]
    g_reset_rm = _g["remove"]
    g_resp_status, g_resp_rem, g_resp_reset = (
        _g["resp_status"], _g["resp_rem"], _g["resp_reset"]
    )

    # ==================================================== sliding window
    # Previous+current window interpolation (docs/algorithms.md "Sliding
    # window"): windows align to duration boundaries (ws = now - now % dur);
    # the stored stamp is the window start, REM_I stores limit - current
    # count (remaining-style — see the storage convention above) and the
    # previous window's count rides the aux lane. The previous window
    # contributes pro-rata for the fraction of it the sliding window still
    # covers; deny iff weighted_prev + current + h > limit.
    w_dur = jnp.maximum(req.duration_eff, i64(1))
    w_ws = now - now % w_dur
    w_elapsed = now - w_ws
    w_same = algo_match & (s.stamp == w_ws)
    w_roll1 = algo_match & (s.stamp == w_ws - w_dur)
    w_cur_s = s.limit - s.rem_i  # stored count, decoded from remaining-style
    w_prev = jnp.where(w_same, s_aux, jnp.where(w_roll1, w_cur_s, i64(0)))
    w_cur = jnp.where(w_same, w_cur_s, i64(0))
    w_used = w_cur + (w_prev * (w_dur - w_elapsed)) // w_dur
    w_deny = (h > 0) & (w_used + h > req.limit)
    w_take = jnp.where(w_deny & ~is_drain, i64(0), h)
    # releases (h < 0) clamp at an empty window — a return can never drive
    # the stored count negative (remaining past `limit`)
    w_cur_out = jnp.maximum(w_cur + w_take, i64(0))
    w_rem = jnp.clip(req.limit - (w_used + w_take), 0, req.limit)
    w_reset = w_ws + w_dur
    w_status = jnp.where(w_deny, OVER, UNDER)
    w_reset_rm = (exists & is_reset) | neg_miss
    w_resp_status = jnp.where(w_reset_rm, UNDER, w_status)
    w_resp_rem = jnp.where(w_reset_rm, req.limit, w_rem)
    w_resp_reset = jnp.where(w_reset_rm, i64(0), w_reset)

    # ==================================================== concurrency lease
    # Inflight acquire/release (docs/algorithms.md "Concurrency leases"):
    # hits > 0 acquires that many leases (deny iff inflight + h > limit),
    # hits < 0 releases (clamped at zero), hits == 0 queries. REM_I stores
    # limit - inflight (remaining-style). Acquires refresh ExpireAt to
    # now + duration; a slot that expires reclaims every outstanding lease
    # — the table's TTL eviction IS the abandoned-lease reclamation.
    l_inflight_s = jnp.where(algo_match, s.limit - s.rem_i, i64(0))
    l_deny = (h > 0) & (l_inflight_s + h > req.limit)
    l_take = jnp.where(l_deny & ~is_drain, i64(0), h)
    l_inflight = jnp.maximum(l_inflight_s + l_take, i64(0))
    l_refresh = (h > 0) & ~(l_deny & ~is_drain)
    l_exp = jnp.where(
        algo_match & ~l_refresh, s.exp, now + req.duration_eff
    )
    l_rem = jnp.clip(req.limit - l_inflight, 0, req.limit)
    l_status = jnp.where(l_deny, OVER, UNDER)
    # a release (or RESET) of a lease key with no live state removes rather
    # than installs — the headline miss-safety case: a crashed client's
    # late release must not resurrect an already-TTL-reclaimed lease slot
    # with a fresh TTL and zero inflight
    l_reset_rm = (exists & is_reset) | neg_miss
    l_resp_status = jnp.where(l_reset_rm, UNDER, l_status)
    l_resp_rem = jnp.where(l_reset_rm, req.limit, l_rem)
    l_resp_reset = jnp.where(l_reset_rm, i64(0), l_exp)

    # ------------------------------------------------ int-algo select masks
    is_gcra = req.algo == int(Algorithm.GCRA)
    is_win = req.algo == int(Algorithm.SLIDING_WINDOW)
    is_lease = req.algo == int(Algorithm.CONCURRENCY_LEASE)

    def pick5(tok, g, w, le, lk):
        """Per-row algorithm select: token / gcra / window / lease / leaky
        (front-door validation guarantees no sixth value reaches the
        kernel; inactive padding rows carry algo=0 → token)."""
        return jnp.where(
            is_token,
            tok,
            jnp.where(is_gcra, g, jnp.where(is_win, w, jnp.where(is_lease, le, lk))),
        )

    w_rem_store = req.limit - w_cur_out
    l_rem_store = req.limit - l_inflight
    # the gcra/window/lease rm flags fold neg_miss (miss-releases remove
    # for the extension lanes); token/leaky keep the reference's
    # credit-banking install on negative hits
    remove_all = (
        (tok_reset_rm & is_token)
        | (g_reset_rm & is_gcra)
        | (w_reset_rm & is_win)
        | (l_reset_rm & is_lease)
    )

    if mode == "int":
        # no leaky row in the batch: the f64 lanes are never traced — the
        # leaky slot of each pick5 reuses the token value (unreachable)
        status_out = pick5(tok_status_out, g_status, w_status, l_status,
                           tok_status_out)
        return Decision(
            status_out=status_out,
            rem_i_out=pick5(tok_rem_store, i64(0), w_rem_store, l_rem_store,
                            tok_rem_store),
            rem_f_out=jnp.zeros_like(s.rem_f),
            stamp_out=pick5(tok_created_out, now, w_ws, now, tok_created_out),
            dur_out=req.duration,
            exp_out=pick5(tok_exp_out, g_exp, w_ws + 2 * w_dur, l_exp,
                          tok_exp_out),
            burst_out=jnp.where(is_gcra, req.burst, i64(0)),
            flags_out=req.algo | (status_out << 8),
            remove=remove_all,
            aux_out=jnp.where(
                is_gcra, g_tat_out, jnp.where(is_win, w_prev, i64(0))
            ),
            resp_status=pick5(tok_resp_status, g_resp_status, w_resp_status,
                              l_resp_status, tok_resp_status),
            resp_rem=pick5(tok_resp_rem, g_resp_rem, w_resp_rem, l_resp_rem,
                           tok_resp_rem),
            resp_reset=pick5(tok_resp_reset, g_resp_reset, w_resp_reset,
                             l_resp_reset, tok_resp_reset),
        )

    # ==================================================== leaky bucket
    # reference algorithms.go:255-492. Remaining is float64 (store.go:32);
    # comparisons truncate toward zero exactly like Go's int64(float64).
    lk_is_new = ~algo_match
    rate = jnp.where(is_greg, req.greg_interval, req.duration).astype(
        f64
    ) / jnp.maximum(req.limit, 1).astype(f64)
    irate = rate.astype(i64)

    # --- existing item (go:304-430)
    b_rem = jnp.where(is_reset, s.burst.astype(f64), s.rem_f)  # go:319-321
    burst_changed = s.burst != req.burst
    b_rem = jnp.where(  # go:324-329
        burst_changed & (req.burst > b_rem.astype(i64)), req.burst.astype(f64), b_rem
    )
    # leak since UpdatedAt; only applied once a whole token has leaked
    # (go:359-366: `if int64(leak) > 0`)
    elapsed = (now - s.stamp).astype(f64)
    leak = elapsed / rate
    leak_applies = leak.astype(i64) > 0
    b_rem = jnp.where(leak_applies, b_rem + leak, b_rem)
    lk_stamp = jnp.where(leak_applies, now, s.stamp)
    # clamp to burst (go:368-370)
    b_rem = jnp.where(b_rem.astype(i64) > req.burst, req.burst.astype(f64), b_rem)

    lk_rem_now = b_rem.astype(i64)
    lk_at_limit = (lk_rem_now == 0) & (h > 0)  # go:388-394
    lk_exact = ~lk_at_limit & (lk_rem_now == h)  # go:397-402 (catches h==0,rem==0)
    lk_overask = ~lk_at_limit & ~lk_exact & (h > lk_rem_now)  # go:406-419
    lk_zero = ~lk_at_limit & ~lk_exact & ~lk_overask & (h == 0)  # go:422-424
    lk_consume = ~lk_at_limit & ~lk_exact & ~lk_overask & ~lk_zero

    # negative hits refill past the burst like token's credit banking (the
    # reference's leaky path has no top clamp either)
    lk_rem_out = jnp.where(
        lk_exact | (lk_overask & is_drain),
        f64(0.0),
        jnp.where(lk_consume, b_rem - h.astype(f64), b_rem),
    )
    lk_resp_status = jnp.where(lk_at_limit | lk_overask, OVER, UNDER)
    lk_resp_rem = jnp.where(lk_overask & ~is_drain, lk_rem_now, lk_rem_out.astype(i64))
    # reset_time is computed from the PRE-hit remaining (go:372-377) and only
    # recomputed by the exact/consume branches (go:400,428) — a DRAIN_OVER_LIMIT
    # rejection keeps the pre-drain reset_time.
    lk_reset_basis = jnp.where(
        lk_exact, i64(0), jnp.where(lk_consume, lk_rem_out.astype(i64), lk_rem_now)
    )
    lk_resp_reset = now + (req.limit - lk_reset_basis) * irate
    # hits≠0 refreshes expiry before any verdict (go:355-357)
    lk_exp = jnp.where(h != 0, now + req.duration_eff, s.exp)

    # --- new item (go:436-492)
    lkn_over = h > req.burst
    lkn_rem = jnp.where(lkn_over, f64(0.0), (req.burst - h).astype(f64))
    lkn_resp_rem = jnp.where(lkn_over, i64(0), req.burst - h)
    lkn_status = jnp.where(lkn_over, OVER, UNDER)
    lkn_reset = now + (req.limit - lkn_resp_rem) * irate
    lkn_exp = now + req.duration_eff

    lk_rem_store = jnp.where(lk_is_new, lkn_rem, lk_rem_out)
    lk_stamp_out = jnp.where(lk_is_new, now, lk_stamp)
    lk_exp_out = jnp.where(lk_is_new, lkn_exp, lk_exp)
    # stored duration: new items persist the effective (Gregorian-resolved)
    # duration (go:452-458); existing items persist the raw request duration
    # (go:332).
    lk_dur_out = jnp.where(lk_is_new, req.duration_eff, req.duration)
    lk_resp_status = jnp.where(lk_is_new, lkn_status, lk_resp_status)
    lk_resp_rem = jnp.where(lk_is_new, lkn_resp_rem, lk_resp_rem)
    lk_resp_reset = jnp.where(lk_is_new, lkn_reset, lk_resp_reset)

    # ==================================================== merge
    is_leaky = req.algo == int(Algorithm.LEAKY_BUCKET)
    status_out = pick5(tok_status_out, g_status, w_status, l_status, UNDER)
    return Decision(
        status_out=status_out,
        rem_i_out=pick5(tok_rem_store, i64(0), w_rem_store, l_rem_store,
                        i64(0)),
        rem_f_out=jnp.where(is_leaky, lk_rem_store, f64(0.0)),
        stamp_out=pick5(tok_created_out, now, w_ws, now, lk_stamp_out),
        dur_out=jnp.where(is_leaky, lk_dur_out, req.duration),
        exp_out=pick5(tok_exp_out, g_exp, w_ws + 2 * w_dur, l_exp,
                      lk_exp_out),
        burst_out=jnp.where(is_leaky | is_gcra, req.burst, i64(0)),
        flags_out=req.algo | (status_out << 8),
        remove=remove_all,
        aux_out=jnp.where(
            is_gcra, g_tat_out, jnp.where(is_win, w_prev, i64(0))
        ),
        resp_status=pick5(tok_resp_status, g_resp_status, w_resp_status,
                          l_resp_status, lk_resp_status),
        resp_rem=pick5(tok_resp_rem, g_resp_rem, w_resp_rem, l_resp_rem,
                       lk_resp_rem),
        resp_reset=pick5(tok_resp_reset, g_resp_reset, w_resp_reset,
                         l_resp_reset, lk_resp_reset),
    )
