"""Branchless token/leaky bucket decision math — shared by both kernel
generations (ops/kernel.py v1 f32-carrier planes, ops/kernel2.py v2 packed
rows).

This is the exact decision table of the reference's algorithms.go, expressed
as masked vector arithmetic over per-row stored state + request fields. All
file:line citations are /root/reference/algorithms.go unless noted. The
deliberate divergences are documented in ops/kernel2.py's module docstring.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from gubernator_tpu.types import Algorithm, Behavior, Status

i64 = jnp.int64
f64 = jnp.float64


class StoredState(NamedTuple):
    """Per-row stored slot state, as int64/float64 (already decoded from
    whichever table layout the kernel uses)."""

    limit: jnp.ndarray  # int64
    burst: jnp.ndarray  # int64
    rem_i: jnp.ndarray  # int64 (token remaining)
    algo: jnp.ndarray  # int32
    status: jnp.ndarray  # int32
    duration: jnp.ndarray  # int64
    stamp: jnp.ndarray  # int64 (CreatedAt / UpdatedAt)
    exp: jnp.ndarray  # int64 (ExpireAt, ms exact)
    rem_f: jnp.ndarray  # float64 (leaky remaining)


class Decision(NamedTuple):
    """Everything the write + response phases need."""

    # stored-state writeback
    status_out: jnp.ndarray  # int32
    rem_i_out: jnp.ndarray  # int64
    rem_f_out: jnp.ndarray  # float64
    stamp_out: jnp.ndarray  # int64
    dur_out: jnp.ndarray  # int64
    exp_out: jnp.ndarray  # int64
    burst_out: jnp.ndarray  # int64
    flags_out: jnp.ndarray  # int32 (algo | status << 8)
    remove: jnp.ndarray  # bool — slot is removed (token RESET_REMAINING)
    # response
    resp_status: jnp.ndarray  # int32
    resp_rem: jnp.ndarray  # int64
    resp_reset: jnp.ndarray  # int64


def bucket_math(
    s: StoredState, req, exists: jnp.ndarray, *, token_only: bool = False
) -> Decision:
    """One decision per row. `req` is a ReqBatch (ops/batch.py); `exists` marks
    rows whose slot held a live matching item (lazy-expiry already applied).

    `token_only` is a STATIC specialization: the leaky path runs on float64,
    which TPUs emulate in software, and the branchless merge pays that for
    every row even in all-token traffic. The serving engine checks the
    batch's algorithms host-side (free) and dispatches the token-only graph
    — no leaky lanes, no f64 ops — when no leaky row is present. A runtime
    `lax.cond` was measured WORSE than the branchless merge (+~2.6 ms at
    131K rows): the HLO conditional materializes its operand tuple (the
    gathered slots among them) and blocks fusion across the boundary."""
    return _bucket_math_impl(s, req, exists, token_only=token_only)


def _bucket_math_impl(
    s: StoredState, req, exists: jnp.ndarray, *, token_only: bool
) -> Decision:
    now = req.created_at
    is_greg = (req.behavior & int(Behavior.DURATION_IS_GREGORIAN)) != 0
    is_reset = (req.behavior & int(Behavior.RESET_REMAINING)) != 0
    is_drain = (req.behavior & int(Behavior.DRAIN_OVER_LIMIT)) != 0
    is_token = req.algo == int(Algorithm.TOKEN_BUCKET)
    h = req.hits

    # Existing-item path applies only when algorithms agree; a stored item of
    # the other algorithm is discarded and recreated ("client switched
    # algorithms", go:96-105,307-317).
    algo_match = exists & (s.algo == req.algo)

    # ==================================================== token bucket
    # reference algorithms.go:37-252
    OVER = jnp.int32(int(Status.OVER_LIMIT))
    UNDER = jnp.int32(int(Status.UNDER_LIMIT))

    # --- existing item (go:107-194)
    # limit change: add the delta to remaining, clamp at 0 (go:108-115)
    t_rem = jnp.where(
        s.limit != req.limit, jnp.maximum(s.rem_i + req.limit - s.limit, 0), s.rem_i
    )
    # duration change (go:125-146): recompute expiry from the item's CreatedAt;
    # if that would place us already expired, renew the bucket.
    dur_changed = s.duration != req.duration
    expire_dc = jnp.where(is_greg, req.expire_new, s.stamp + req.duration)
    renew = dur_changed & (expire_dc <= now)
    expire_dc = jnp.where(renew, now + req.duration, expire_dc)
    t_created = jnp.where(renew, now, s.stamp)
    t_rem = jnp.where(renew, req.limit, t_rem)
    t_exp = jnp.where(dur_changed, expire_dc, s.exp)
    t_reset = t_exp

    zero_hits = h == 0
    at_limit = (t_rem == 0) & (h > 0)  # go:161-168
    exact = ~zero_hits & ~at_limit & (t_rem == h)  # go:171-175
    overask = ~zero_hits & ~at_limit & ~exact & (h > t_rem)  # go:179-190
    consume = ~zero_hits & ~at_limit & ~exact & ~overask  # go:192-194

    tok_rem_out = jnp.where(
        exact | (overask & is_drain), i64(0), jnp.where(consume, t_rem - h, t_rem)
    )
    # response status starts from the stored (sticky) status (go:117-122); only
    # the at-limit branch persists OVER back to the item (go:165-166).
    tok_resp_status = jnp.where(at_limit | overask, OVER, s.status)
    tok_stored_status = jnp.where(at_limit, OVER, s.status)
    tok_resp_rem = tok_rem_out
    tok_resp_reset = t_reset

    # --- new item (go:202-252)
    new_over = h > req.limit
    tokn_rem = jnp.where(new_over, req.limit, req.limit - h)
    tokn_status = jnp.where(new_over, OVER, UNDER)
    tokn_exp = req.expire_new

    tok_is_new = ~algo_match
    tok_status_out = jnp.where(tok_is_new, UNDER, tok_stored_status)
    tok_rem_store = jnp.where(tok_is_new, tokn_rem, tok_rem_out)
    tok_created_out = jnp.where(tok_is_new, now, t_created)
    tok_exp_out = jnp.where(tok_is_new, tokn_exp, t_exp)
    tok_resp_status = jnp.where(tok_is_new, tokn_status, tok_resp_status)
    tok_resp_rem = jnp.where(tok_is_new, tokn_rem, tok_resp_rem)
    tok_resp_reset = jnp.where(tok_is_new, tokn_exp, tok_resp_reset)

    # RESET_REMAINING on an existing item removes it outright and reports a
    # full bucket (go:82-94) — modeled as writing back an empty slot.
    tok_reset_rm = exists & is_reset
    tok_resp_status = jnp.where(tok_reset_rm, UNDER, tok_resp_status)
    tok_resp_rem = jnp.where(tok_reset_rm, req.limit, tok_resp_rem)
    tok_resp_reset = jnp.where(tok_reset_rm, i64(0), tok_resp_reset)

    if token_only:
        # all request rows are token buckets: the leaky lanes of the merge
        # collapse to constants and no float64 op is emitted on this branch
        zero_f = jnp.zeros_like(s.rem_f)
        return Decision(
            status_out=tok_status_out,
            rem_i_out=tok_rem_store,
            rem_f_out=zero_f,
            stamp_out=tok_created_out,
            dur_out=req.duration,
            exp_out=tok_exp_out,
            burst_out=jnp.zeros_like(s.burst),
            flags_out=req.algo | (tok_status_out << 8),
            remove=tok_reset_rm,
            resp_status=tok_resp_status,
            resp_rem=tok_resp_rem,
            resp_reset=tok_resp_reset,
        )

    # ==================================================== leaky bucket
    # reference algorithms.go:255-492. Remaining is float64 (store.go:32);
    # comparisons truncate toward zero exactly like Go's int64(float64).
    lk_is_new = ~algo_match
    rate = jnp.where(is_greg, req.greg_interval, req.duration).astype(
        f64
    ) / jnp.maximum(req.limit, 1).astype(f64)
    irate = rate.astype(i64)

    # --- existing item (go:304-430)
    b_rem = jnp.where(is_reset, s.burst.astype(f64), s.rem_f)  # go:319-321
    burst_changed = s.burst != req.burst
    b_rem = jnp.where(  # go:324-329
        burst_changed & (req.burst > b_rem.astype(i64)), req.burst.astype(f64), b_rem
    )
    # leak since UpdatedAt; only applied once a whole token has leaked
    # (go:359-366: `if int64(leak) > 0`)
    elapsed = (now - s.stamp).astype(f64)
    leak = elapsed / rate
    leak_applies = leak.astype(i64) > 0
    b_rem = jnp.where(leak_applies, b_rem + leak, b_rem)
    lk_stamp = jnp.where(leak_applies, now, s.stamp)
    # clamp to burst (go:368-370)
    b_rem = jnp.where(b_rem.astype(i64) > req.burst, req.burst.astype(f64), b_rem)

    lk_rem_now = b_rem.astype(i64)
    lk_at_limit = (lk_rem_now == 0) & (h > 0)  # go:388-394
    lk_exact = ~lk_at_limit & (lk_rem_now == h)  # go:397-402 (catches h==0,rem==0)
    lk_overask = ~lk_at_limit & ~lk_exact & (h > lk_rem_now)  # go:406-419
    lk_zero = ~lk_at_limit & ~lk_exact & ~lk_overask & (h == 0)  # go:422-424
    lk_consume = ~lk_at_limit & ~lk_exact & ~lk_overask & ~lk_zero

    lk_rem_out = jnp.where(
        lk_exact | (lk_overask & is_drain),
        f64(0.0),
        jnp.where(lk_consume, b_rem - h.astype(f64), b_rem),
    )
    lk_resp_status = jnp.where(lk_at_limit | lk_overask, OVER, UNDER)
    lk_resp_rem = jnp.where(lk_overask & ~is_drain, lk_rem_now, lk_rem_out.astype(i64))
    # reset_time is computed from the PRE-hit remaining (go:372-377) and only
    # recomputed by the exact/consume branches (go:400,428) — a DRAIN_OVER_LIMIT
    # rejection keeps the pre-drain reset_time.
    lk_reset_basis = jnp.where(
        lk_exact, i64(0), jnp.where(lk_consume, lk_rem_out.astype(i64), lk_rem_now)
    )
    lk_resp_reset = now + (req.limit - lk_reset_basis) * irate
    # hits≠0 refreshes expiry before any verdict (go:355-357)
    lk_exp = jnp.where(h != 0, now + req.duration_eff, s.exp)

    # --- new item (go:436-492)
    lkn_over = h > req.burst
    lkn_rem = jnp.where(lkn_over, f64(0.0), (req.burst - h).astype(f64))
    lkn_resp_rem = jnp.where(lkn_over, i64(0), req.burst - h)
    lkn_status = jnp.where(lkn_over, OVER, UNDER)
    lkn_reset = now + (req.limit - lkn_resp_rem) * irate
    lkn_exp = now + req.duration_eff

    lk_rem_store = jnp.where(lk_is_new, lkn_rem, lk_rem_out)
    lk_stamp_out = jnp.where(lk_is_new, now, lk_stamp)
    lk_exp_out = jnp.where(lk_is_new, lkn_exp, lk_exp)
    # stored duration: new items persist the effective (Gregorian-resolved)
    # duration (go:452-458); existing items persist the raw request duration
    # (go:332).
    lk_dur_out = jnp.where(lk_is_new, req.duration_eff, req.duration)
    lk_resp_status = jnp.where(lk_is_new, lkn_status, lk_resp_status)
    lk_resp_rem = jnp.where(lk_is_new, lkn_resp_rem, lk_resp_rem)
    lk_resp_reset = jnp.where(lk_is_new, lkn_reset, lk_resp_reset)

    # ==================================================== merge
    status_out = jnp.where(is_token, tok_status_out, UNDER)
    return Decision(
        status_out=status_out,
        rem_i_out=jnp.where(is_token, tok_rem_store, i64(0)),
        rem_f_out=jnp.where(is_token, f64(0.0), lk_rem_store),
        stamp_out=jnp.where(is_token, tok_created_out, lk_stamp_out),
        dur_out=jnp.where(is_token, req.duration, lk_dur_out),
        exp_out=jnp.where(is_token, tok_exp_out, lk_exp_out),
        burst_out=jnp.where(is_token, i64(0), req.burst),
        flags_out=req.algo | (status_out << 8),
        remove=tok_reset_rm & is_token,
        resp_status=jnp.where(is_token, tok_resp_status, lk_resp_status),
        resp_rem=jnp.where(is_token, tok_resp_rem, lk_resp_rem),
        resp_reset=jnp.where(is_token, tok_resp_reset, lk_resp_reset),
    )
