"""Device-resident ring consumer: drain K published slots per XLA launch.

The request ring (service/ring.py) removed the per-batch *enqueue* cost
from the serving plane, but its host issue loop still paid one full XLA
launch round-trip per published slot — steady-state serving throughput was
launch-bound, not kernel-bound. This module moves the CONSUME side onto the
device in two tiers:

**Tier A — fused multi-slot drain (this file's `drain_ring`, live on every
backend).** The whole ring of compact wire-grid slots plus the
`seq_in`/`seq_out` fence words stays device-resident (`DeviceRing`), and
one jitted bounded `lax.while_loop` launch reads the ingress fences
IN-TRACE, decodes and decides up to `k` published slots through the
existing `decide2_wire_cols` walk (the donated table threaded through the
carry), writes each slot's compact egress bank, and publishes `seq_out` —
exactly the pattern ops/loop.py proved for the bench harness, applied to
the serving path. The launch round-trip amortizes k× and the per-launch
cost is ∝ published work: an unpublished slot is a fence compare and a
no-op branch (the loop exits). `k` and the start ticket are *traced*
scalars, so one compile per (ring geometry × math mode) serves every
group size.

**Tier B — persistent issue kernel (`fence_claim`, staged for the TPU
run).** A Pallas kernel that polls `seq_in` and claims published slots
with the async-copy/DMA-semaphore pattern — the device-side half of the
protocol that makes steady state pay ZERO XLA launches (the kernel never
exits; the host only stages grids and polls egress fences). The CPU build
validates the fence protocol in interpreter mode
(tests/test_ring_drain.py) against `fence_claim_ref`; the service keeps
`GUBER_RING_ISSUE=persistent` on the fused drain launches until the
device run validates the resident loop (watchdog re-launch on preemption
is the service's job — service/ring.py counts `watchdog_relaunches`).

Threading contract: every `DeviceRing` mutation (slot staging, fence
publish, drain launch) happens on the ENGINE THREAD — the buffers are
donated through jitted in-place updates, and a second writer would race
the donation. The host mirrors in service/ring.py remain the submitters'
view; this module is the device's.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from gubernator_tpu.ops.wire import WIRE_LANES, decide2_wire_cols_impl

i32 = jnp.int32
i64 = jnp.int64


def default_ring_issue() -> str:
    """Backend default for GUBER_RING_ISSUE: the fused drain on real TPU
    (launch round-trips are the cost it exists to amortize), the host
    issue loop on CPU builds (byte-parity oracle; per-launch overhead is
    microseconds there, and the host loop keeps the per-slot pad sizing)."""
    return "fused" if jax.default_backend() == "tpu" else "host"


def egress_rows(width: int, evictees: bool) -> int:
    """Rows of one slot's compact egress bank: the (W+2, 4) encode_wire_out
    image, or (5W+2, 4) with the raw evictee sidecar rows interleaved
    (kernel2.attach_evictees_wire — static per engine config)."""
    return 5 * width + 2 if evictees else width + 2


def _drain_impl(
    table, grids, seq_in, seq_out, start, k, *,
    k_max, write, math, cascade, probe, evictees,
):
    """One fused drain launch: walk tickets from `start`, decide every
    published slot (≤ k ≤ k_max), publish egress fences. Returns
    (table', seq_out', bank, drained) where bank[i] is the i-th drained
    ticket's egress image and `drained` is the in-trace claim count — the
    host asserts it equals the group it published (fence-protocol proof,
    not a recovery path)."""
    S = grids.shape[0]
    E = egress_rows(grids.shape[2] - 1, evictees)
    start = jnp.asarray(start, dtype=i64)
    k = jnp.minimum(jnp.asarray(k, dtype=i64), i64(k_max))
    bank0 = jnp.zeros((k_max, E, 4), dtype=i32)

    def cond(carry):
        _table, _seq_out, _bank, t, n = carry
        # ingress fence, read in-trace: slot t%S must carry exactly
        # ticket t (fence word t+1 — never 0, so an unused slot can't
        # alias). An unpublished slot ends the drain: cost ∝ published
        # work, not slot count.
        return (n < k) & (seq_in[jax.lax.rem(t, S)] == t + 1)

    def body(carry):
        table, seq_out, bank, t, n = carry
        slot = jax.lax.rem(t, S)
        grid = jax.lax.dynamic_index_in_dim(grids, slot, 0, keepdims=False)
        table, out = decide2_wire_cols_impl(
            table, grid, write=write, math=math, cascade=cascade,
            probe=probe, evictees=evictees,
        )
        # dense egress bank indexed by drain POSITION, not slot: one fetch
        # covers the whole launch. (The true device ring / persistent tier
        # writes per-slot banks the host polls individually; the dense
        # bank is the pipelined-fetch shape the CPU-provable tier wants.)
        bank = jax.lax.dynamic_update_index_in_dim(bank, out, n, 0)
        # egress fence AFTER the slot's outputs exist in the bank — same
        # store ordering the host finish loop keeps
        seq_out = seq_out.at[slot].set(t + 1)
        return table, seq_out, bank, t + 1, n + 1

    table, seq_out, bank, _t, n = jax.lax.while_loop(
        cond, body, (table, seq_out, bank0, start, i64(0))
    )
    return table, seq_out, bank, n


# table and seq_out are donated (in-place across launches); grids/seq_in
# are read-only residents the staging updates below replace. The bank is a
# FRESH output each launch — donating it would let launch j+1 reuse the
# buffer a fetch thread is still reading from launch j.
drain_ring = functools.partial(
    jax.jit, donate_argnums=(0, 3),
    static_argnames=("k_max", "write", "math", "cascade", "probe",
                     "evictees"),
)(_drain_impl)


@functools.partial(jax.jit, donate_argnums=(0,))
def _store_slot(grids, grid, slot):
    """In-place slot refresh (donated): the emulation's stand-in for the
    host→HBM DMA into slot `slot`. `slot` is traced — one compile serves
    the whole ring."""
    return jax.lax.dynamic_update_index_in_dim(grids, grid, slot, 0)


@functools.partial(jax.jit, donate_argnums=(0,))
def _publish_fence(seq, slot, val):
    return seq.at[slot].set(val)


class DeviceRing:
    """The device-resident half of the request ring: S wire-grid slots of
    one FIXED width plus the seq_in/seq_out fence words, mutated only on
    the engine thread (donated buffers). Chunks wider than `width` keep
    riding the host per-slot path — the fixed width is what makes the
    drain graph a single compile (docs/latency.md "Launch budget")."""

    def __init__(self, slots: int, width: int, drain_k: int,
                 evictees: bool = False):
        if slots < 2 or drain_k < 1 or width < 1:
            raise ValueError("DeviceRing needs slots>=2, drain_k>=1, width>=1")
        self.slots = int(slots)
        self.width = int(width)
        self.drain_k = int(min(drain_k, slots))
        self.evictees = bool(evictees)
        self.grids = jnp.zeros(
            (self.slots, WIRE_LANES, self.width + 1), dtype=i32
        )
        self.seq_in = jnp.zeros((self.slots,), dtype=i64)
        self.seq_out = jnp.zeros((self.slots,), dtype=i64)

    def stage(self, slot: int, grid: np.ndarray, ticket: int) -> None:
        """ENGINE THREAD. Stage one slot's (5, width+1) grid and publish
        its ingress fence — STAGE before PUBLISH, the same store ordering
        the host mirror keeps (a device consumer polling seq_in must never
        observe the fence before the payload)."""
        self.grids = _store_slot(
            self.grids, jnp.asarray(grid, dtype=i32), np.int32(slot)
        )
        self.seq_in = _publish_fence(
            self.seq_in, np.int32(slot), np.int64(ticket + 1)
        )

    def drain(self, engine, start: int, k: int, math: str, cascade: bool):
        """ENGINE THREAD. One fused drain launch over tickets
        [start, start+k): threads the engine's donated table through the
        while_loop carry and advances the device egress fences. Returns
        (bank, drained) un-fetched device handles — the finish half
        materializes them on a fetch thread."""
        table, self.seq_out, bank, n = drain_ring(
            engine.table, self.grids, self.seq_in, self.seq_out,
            np.int64(start), np.int64(k),
            k_max=self.drain_k, write=engine.write_mode, math=math,
            cascade=cascade, probe=engine.probe_mode,
            evictees=bool(engine._evictees),
        )
        engine.table = table
        return bank, n


# --------------------------------------------------------------------------
# Tier B: persistent issue kernel (staged for the TPU run)
# --------------------------------------------------------------------------


def _fence_claim_kernel(seq_in_ref, _seq_out_in, grids_ref, ctl_ref,
                        seq_out_ref, bank_ref, n_ref, sem):
    """Pallas fence-claim loop: the persistent issue kernel's inner step.

    Walks tickets from ctl[0], and for each CONTIGUOUSLY published slot
    (seq_in[t%S] == t+1 — a gap stops the claim, preserving strict ticket
    order) async-copies the slot's wire grid into the claim bank and bumps
    the egress-side fence, up to ctl[1] claims. This is the SNIPPETS
    async-copy/DMA-semaphore recipe applied to slot claiming; the resident
    production loop wraps this step in an outer poll that never exits.
    Fence words are int32 here (tickets wrap at 2^31 — years of uptime at
    serving rates; the host remaps before wrap)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    start = ctl_ref[0]
    k = ctl_ref[1]
    S = seq_in_ref.shape[0]

    def body(i, n):
        t = start + i
        # i32(S): a bare python int promotes to i64 under jax_enable_x64,
        # and lax.rem refuses mixed-width operands
        slot = jax.lax.rem(t, i32(S))
        published = seq_in_ref[slot] == t + 1
        live = (i < k) & (i == n) & published

        @pl.when(live)
        def _claim():
            cp = pltpu.make_async_copy(
                grids_ref.at[slot], bank_ref.at[i], sem
            )
            cp.start()
            cp.wait()
            # egress fence AFTER the DMA completed — the claim ordering
            # the host's result poll relies on
            seq_out_ref[slot] = t + 1

        return n + live.astype(i32)

    n = jax.lax.fori_loop(0, bank_ref.shape[0], body, i32(0))
    n_ref[0] = n


def make_fence_claim(slots: int, width: int, k_max: int, *,
                     interpret: bool = False):
    """Build the fence-claim pallas_call for one ring geometry. Returns
    fn(seq_in i32 (S,), seq_out i32 (S,), grids i32 (S, 5, W+1),
    ctl i32 (2,)=[start, k]) → (seq_out', bank (k_max, 5, W+1), n (1,)).
    `interpret=True` runs the CPU interpreter — the parity surface
    tests/test_ring_drain.py pins against `fence_claim_ref`."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    out_shape = (
        jax.ShapeDtypeStruct((slots,), jnp.int32),
        jax.ShapeDtypeStruct((k_max, WIRE_LANES, width + 1), jnp.int32),
        jax.ShapeDtypeStruct((1,), jnp.int32),
    )
    return pl.pallas_call(
        _fence_claim_kernel,
        out_shape=out_shape,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # seq_in
            pl.BlockSpec(memory_space=pltpu.SMEM),  # seq_out (aliased)
            pl.BlockSpec(memory_space=pl.ANY),      # grids (HBM)
            pl.BlockSpec(memory_space=pltpu.SMEM),  # ctl [start, k]
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ),
        scratch_shapes=[pltpu.SemaphoreType.DMA(())],
        input_output_aliases={1: 0},
        interpret=interpret,
    )


def fence_claim_ref(seq_in: np.ndarray, seq_out: np.ndarray,
                    grids: np.ndarray, start: int, k: int):
    """Numpy reference of the fence-claim protocol — the oracle the
    interpreter-mode kernel test compares against. Claims contiguously
    published tickets from `start` (a gap or k stops it), copies each
    claimed slot's grid, bumps its egress fence."""
    S = seq_in.shape[0]
    seq_out = seq_out.copy()
    claimed = []
    n = 0
    while n < k:
        t = start + n
        slot = t % S
        if int(seq_in[slot]) != t + 1:
            break
        claimed.append(grids[slot].copy())
        seq_out[slot] = t + 1
        n += 1
    bank = (
        np.stack(claimed)
        if claimed
        else np.zeros((0,) + grids.shape[1:], dtype=grids.dtype)
    )
    return n, bank, seq_out
