"""On-device dispatch loop: K kernel iterations in ONE XLA launch.

The benchmark's headline must measure chip compute, not transport. On the
tunneled dev TPU every host-visible op (launch, fetch) serializes into its
own ~30-350 ms round trip whose duration swings with "tunnel weather", so a
host-timed loop of K separate dispatches measures K round trips, not the
kernel (round 4's recorded headline collapsed 24x from exactly this). The
fix is structural: run the K iterations *inside* one jitted
`lax.fori_loop`, threading the donated table through the carry, so a whole
timed window costs exactly one launch + one scalar fetch and the RTT
amortizes to nothing.

The trip count `k` is a *traced* scalar (fori_loop lowers to a while loop),
so one compile serves every window length — the adaptive sizing in bench.py
can grow K until device time dominates RTT jitter without paying a
multi-minute tunnel recompile per K.

This is a measurement harness for the same `decide2_impl` graph the serving
engine dispatches (ops/kernel2.py); it adds no semantics. The reference's
analog is the b.N loop of its Go benchmarks (benchmark_test.go:30-148) —
there the harness overhead is nanoseconds so the loop can live on the host;
here the loop must live on the device for the same number to mean anything.
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp

from gubernator_tpu.ops.batch import ReqBatch
from gubernator_tpu.ops.kernel2 import decide2_impl
from gubernator_tpu.ops.table2 import Table2

i64 = jnp.int64


def stack_batches(batches: List[ReqBatch]) -> ReqBatch:
    """Stack N same-shape request batches along a new leading axis → one
    device-resident pytree the loop cycles through with a dynamic slice.
    (One stacked (N, B) buffer per column beats N live batch pytrees: the
    loop body's gather is a contiguous dynamic-slice, and there is exactly
    one host→device staging op per column.)"""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *batches)


@functools.partial(
    jax.jit, donate_argnums=(0,), static_argnames=("write", "math", "probe")
)
def decide_loop(
    table: Table2,
    stacked: ReqBatch,
    k: jnp.ndarray,
    *,
    write: str = "sweep",
    math: str = "mixed",
    probe: str = "xla",
) -> Tuple[Table2, jnp.ndarray]:
    """Run `k` decide2 dispatches on-device, cycling over the stacked
    batches; returns (table', [hits, misses, over, dropped] i64 totals).

    The totals are the proof of work: bench.py asserts
    hits + misses == k * active_rows before publishing any rate derived
    from this loop, so a wedged transport or a silently-skipped iteration
    can never masquerade as throughput.
    """
    n = stacked.fp.shape[0]

    def body(i, carry):
        table, acc = carry
        b = jax.tree.map(
            lambda x: jax.lax.dynamic_index_in_dim(x, i % n, keepdims=False),
            stacked,
        )
        table, _resp, stats = decide2_impl(
            table, b, write=write, math=math, probe=probe
        )
        acc = acc + jnp.stack(
            [stats.cache_hits, stats.cache_misses, stats.over_limit,
             stats.dropped]
        )
        return table, acc

    table, acc = jax.lax.fori_loop(
        0, k.astype(jnp.int32), body, (table, jnp.zeros((4,), dtype=i64))
    )
    return table, acc
