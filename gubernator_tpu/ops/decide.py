"""The vectorized rate-limit decision kernel.

One call replaces the reference's whole per-request inner stack — worker
channel → LRU map lookup → token/leaky bucket state machine (reference
workers.go:195-330 → lrucache.go:88-128 → algorithms.go:37-492) — with a single
jitted batch update over the HBM table:

    table', responses, stats = decide(table, batch)

Phases (all batch-parallel, static shapes, no host sync):
 1. probe     — K linear probes per row; classify slots (live match / expired /
                empty / foreign).
 2. claim     — insertion rows resolve slot contention with a scatter-max
                "compare-and-swap" loop (K rounds); eviction prefers expired
                slots then the soonest-expiring live slot (expiry-stamp
                eviction ≈ the reference's LRU evict, lrucache.go:138-149).
 3. apply     — branchless token + leaky bucket math under masks, reproducing
                the exact decision tables of reference algorithms.go (see
                per-step citations inline).
 4. scatter   — write back every per-slot field at the claimed slots; build
                responses in original row order.

Correctness contract: fingerprints must be unique among active rows (the pass
planner, ops/plan.py, guarantees it). This reproduces the reference's per-key
serialization: gubernator's worker hash-ring ensures same-key requests apply
sequentially (workers.go:185-189); here "sequentially" = "in separate passes".

Deliberate divergences from the reference (documented, not cargo-culted):
* Expiry uses the request's `created_at` as "now" instead of a wall-clock read
  (reference cache.go:43-57 reads MillisecondNow()); the front door stamps
  created_at at ingress, and tests get frozen time for free.
* New-item leaky-bucket rate under DURATION_IS_GREGORIAN uses the Gregorian
  interval length, where the reference divides by the raw enum value
  (algorithms.go:438-449) yielding a nonsense reset_time — a known reference
  quirk we fix (SURVEY.md §7 watch list).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from gubernator_tpu.ops.batch import BatchStats, ReqBatch, RespBatch
from gubernator_tpu.ops.table import Table
from gubernator_tpu.types import Algorithm, Behavior, Status

# Slot-preference sort keys for the claim phase.
_KEY_LOCKED = jnp.int64(1) << 62  # slot owned/claimed by another row: unusable
_KEY_EVICT = jnp.int64(1) << 45  # live foreign slot: usable at eviction cost


@partial(jax.jit, static_argnames=("probes",), donate_argnums=(0,))
def decide(
    table: Table, req: ReqBatch, probes: int = 8
) -> Tuple[Table, RespBatch, BatchStats]:
    """Apply one batch of rate-limit checks to the table. See module docstring."""
    C = table.fp.shape[0]
    B = req.fp.shape[0]
    K = probes
    i64 = jnp.int64

    now = req.created_at  # per-row "now" (epoch ms)

    # ------------------------------------------------------------------ probe
    base = (req.fp % jnp.uint64(C)).astype(jnp.int32)
    offs = jnp.arange(K, dtype=jnp.int32)
    idx = (base[:, None] + offs[None, :]) % C  # (B, K) int32
    slot_fp = table.fp[idx]
    slot_exp = table.expire_at[idx]
    slot_inv = table.invalid_at[idx]

    # Expired ⇔ the reference's lazy IsExpired() removal on read
    # (cache.go:43-57: ExpireAt < now, or InvalidAt ∈ (0, now)).
    expired = (slot_exp < now[:, None]) | ((slot_inv != 0) & (slot_inv < now[:, None]))
    empty = slot_fp == jnp.uint64(0)
    fpm = (slot_fp == req.fp[:, None]) & ~empty & req.active[:, None]
    match_live = fpm & ~expired
    has_live = match_live.any(axis=1)
    j_live = jnp.argmax(match_live, axis=1)
    match_exp = fpm & expired
    has_matchexp = match_exp.any(axis=1) & ~has_live
    j_matchexp = jnp.argmax(match_exp, axis=1)

    owns = has_live | has_matchexp  # row already has a slot with its fp
    own_j = jnp.where(has_live, j_live, j_matchexp)
    own_slot = jnp.take_along_axis(idx, own_j[:, None], axis=1)[:, 0]

    # ------------------------------------------------------------------ claim
    # Slots any row owns are off-limits to other rows' insert/evict.
    DROP = jnp.int32(C)  # out-of-range scatter index → mode="drop"
    locked = jnp.zeros(C, dtype=bool)
    locked = locked.at[jnp.where(owns, own_slot, DROP)].set(True, mode="drop")

    vacant = empty | expired
    # Per-probe preference key (ascending better): vacant slots in probe order,
    # then live foreign slots by soonest expiry, locked slots last.
    pref_key = jnp.where(
        vacant,
        offs[None, :].astype(i64),
        _KEY_EVICT + jnp.clip(slot_exp, 0, _KEY_EVICT - 1),
    )
    pref_key = jnp.where(locked[idx], _KEY_LOCKED + offs[None, :].astype(i64), pref_key)
    order = jnp.argsort(pref_key, axis=1)  # (B, K) probe indices, best first
    sorted_slots = jnp.take_along_axis(idx, order, axis=1)
    sorted_keys = jnp.take_along_axis(pref_key, order, axis=1)

    need = req.active & ~owns
    ptr = jnp.zeros(B, dtype=jnp.int32)
    assigned = jnp.where(owns, own_slot, DROP)
    resolved = owns
    taken = locked
    # K rounds of claim-or-advance: each unresolved row bids its best remaining
    # slot via a scatter-max of its fingerprint; the max fp wins the slot.
    for _ in range(K):
        cand_slot = jnp.take_along_axis(sorted_slots, ptr[:, None], axis=1)[:, 0]
        cand_key = jnp.take_along_axis(sorted_keys, ptr[:, None], axis=1)[:, 0]
        usable = cand_key < _KEY_LOCKED
        free = ~taken[cand_slot]
        trying = need & ~resolved & usable & free
        bids = jnp.zeros(C, dtype=jnp.uint64)
        bids = bids.at[jnp.where(trying, cand_slot, DROP)].max(req.fp, mode="drop")
        won = trying & (bids[cand_slot] == req.fp)
        assigned = jnp.where(won, cand_slot, assigned)
        resolved = resolved | won
        taken = taken.at[jnp.where(won, cand_slot, DROP)].set(True, mode="drop")
        advance = need & ~resolved
        ptr = jnp.minimum(ptr + advance.astype(jnp.int32), K - 1)

    dropped = req.active & ~resolved
    # Eviction of a live foreign slot (key ≥ _KEY_EVICT ⇒ the claimed slot was
    # not vacant) — the reference's "unexpired evictions" alarm counter
    # (lrucache.go:138-149).
    claimed_key = jnp.take_along_axis(sorted_keys, ptr[:, None], axis=1)[:, 0]
    evicted_unexpired = need & resolved & (claimed_key >= _KEY_EVICT)

    safe_slot = jnp.minimum(assigned, C - 1)
    exists = has_live  # live fp match ⇒ the reference's cache hit

    # ---------------------------------------------------------------- gather
    s_algo = table.algo[safe_slot]
    s_status = table.status[safe_slot]
    s_limit = table.limit[safe_slot]
    s_duration = table.duration[safe_slot]
    s_rem_i = table.remaining_i[safe_slot]
    s_rem_f = table.remaining_f[safe_slot]
    s_stamp = table.stamp[safe_slot]
    s_burst = table.burst[safe_slot]
    s_exp = table.expire_at[safe_slot]

    is_greg = (req.behavior & int(Behavior.DURATION_IS_GREGORIAN)) != 0
    is_reset = (req.behavior & int(Behavior.RESET_REMAINING)) != 0
    is_drain = (req.behavior & int(Behavior.DRAIN_OVER_LIMIT)) != 0
    is_token = req.algo == int(Algorithm.TOKEN_BUCKET)
    h = req.hits

    # Existing-item path applies only when algorithms agree; a stored item of
    # the other algorithm is discarded and recreated ("client switched
    # algorithms", reference algorithms.go:96-105,307-317).
    algo_match = exists & (s_algo == req.algo)

    # ==================================================== token bucket
    # reference algorithms.go:37-252
    OVER = jnp.int32(int(Status.OVER_LIMIT))
    UNDER = jnp.int32(int(Status.UNDER_LIMIT))

    # --- existing item (algorithms.go:107-194)
    # limit change: add the delta to remaining, clamp at 0 (go:108-115)
    t_rem = jnp.where(
        s_limit != req.limit, jnp.maximum(s_rem_i + req.limit - s_limit, 0), s_rem_i
    )
    # duration change (go:125-146): recompute expiry from the item's CreatedAt;
    # if that would place us already expired, renew the bucket.
    dur_changed = s_duration != req.duration
    expire_dc = jnp.where(is_greg, req.expire_new, s_stamp + req.duration)
    renew = dur_changed & (expire_dc <= now)
    expire_dc = jnp.where(renew, now + req.duration, expire_dc)
    t_created = jnp.where(renew, now, s_stamp)
    t_rem = jnp.where(renew, req.limit, t_rem)
    t_exp = jnp.where(dur_changed, expire_dc, s_exp)
    t_reset = t_exp

    zero_hits = h == 0
    at_limit = (t_rem == 0) & (h > 0)  # go:161-168
    exact = ~zero_hits & ~at_limit & (t_rem == h)  # go:171-175
    overask = ~zero_hits & ~at_limit & ~exact & (h > t_rem)  # go:179-190
    consume = ~zero_hits & ~at_limit & ~exact & ~overask  # go:192-194

    tok_rem_out = jnp.where(
        exact | (overask & is_drain), i64(0), jnp.where(consume, t_rem - h, t_rem)
    )
    # response status starts from the stored (sticky) status (go:117-122); only
    # the at-limit branch persists OVER back to the item (go:165-166).
    tok_resp_status = jnp.where(at_limit | overask, OVER, s_status)
    tok_stored_status = jnp.where(at_limit, OVER, s_status)
    tok_resp_rem = tok_rem_out
    tok_resp_reset = t_reset

    # --- new item (algorithms.go:202-252)
    new_over = h > req.limit
    tokn_rem = jnp.where(new_over, req.limit, req.limit - h)
    tokn_status = jnp.where(new_over, OVER, UNDER)
    tokn_exp = req.expire_new

    tok_is_new = ~algo_match
    tok_fp_out = req.fp
    tok_status_out = jnp.where(tok_is_new, UNDER, tok_stored_status)
    tok_rem_store = jnp.where(tok_is_new, tokn_rem, tok_rem_out)
    tok_created_out = jnp.where(tok_is_new, now, t_created)
    tok_exp_out = jnp.where(tok_is_new, tokn_exp, t_exp)
    tok_resp_status = jnp.where(tok_is_new, tokn_status, tok_resp_status)
    tok_resp_rem = jnp.where(tok_is_new, tokn_rem, tok_resp_rem)
    tok_resp_reset = jnp.where(tok_is_new, tokn_exp, tok_resp_reset)

    # RESET_REMAINING on an existing item removes it outright and reports a
    # full bucket (go:82-94) — modeled as writing back an empty slot.
    tok_reset_rm = exists & is_reset
    tok_fp_out = jnp.where(tok_reset_rm, jnp.uint64(0), tok_fp_out)
    tok_resp_status = jnp.where(tok_reset_rm, UNDER, tok_resp_status)
    tok_resp_rem = jnp.where(tok_reset_rm, req.limit, tok_resp_rem)
    tok_resp_reset = jnp.where(tok_reset_rm, i64(0), tok_resp_reset)

    # ==================================================== leaky bucket
    # reference algorithms.go:255-492. Remaining is float64 (store.go:32);
    # comparisons truncate toward zero exactly like Go's int64(float64).
    f64 = jnp.float64
    lk_is_new = ~algo_match
    rate = jnp.where(is_greg, req.greg_interval, req.duration).astype(f64) / jnp.maximum(
        req.limit, 1
    ).astype(f64)
    irate = rate.astype(i64)

    # --- existing item (go:304-430)
    b_rem = jnp.where(is_reset, s_burst.astype(f64), s_rem_f)  # go:319-321
    burst_changed = s_burst != req.burst
    b_rem = jnp.where(  # go:324-329
        burst_changed & (req.burst > b_rem.astype(i64)), req.burst.astype(f64), b_rem
    )
    # leak since UpdatedAt; only applied once a whole token has leaked
    # (go:359-366: `if int64(leak) > 0`)
    elapsed = (now - s_stamp).astype(f64)
    leak = elapsed / rate
    leak_applies = leak.astype(i64) > 0
    b_rem = jnp.where(leak_applies, b_rem + leak, b_rem)
    lk_stamp = jnp.where(leak_applies, now, s_stamp)
    # clamp to burst (go:368-370)
    b_rem = jnp.where(b_rem.astype(i64) > req.burst, req.burst.astype(f64), b_rem)

    lk_rem_now = b_rem.astype(i64)
    lk_at_limit = (lk_rem_now == 0) & (h > 0)  # go:388-394
    lk_exact = ~lk_at_limit & (lk_rem_now == h)  # go:397-402 (note: catches h==0,rem==0)
    lk_overask = ~lk_at_limit & ~lk_exact & (h > lk_rem_now)  # go:406-419
    lk_zero = ~lk_at_limit & ~lk_exact & ~lk_overask & (h == 0)  # go:422-424
    lk_consume = ~lk_at_limit & ~lk_exact & ~lk_overask & ~lk_zero

    lk_rem_out = jnp.where(
        lk_exact | (lk_overask & is_drain),
        f64(0.0),
        jnp.where(lk_consume, b_rem - h.astype(f64), b_rem),
    )
    lk_resp_status = jnp.where(lk_at_limit | lk_overask, OVER, UNDER)
    lk_resp_rem = jnp.where(lk_overask & ~is_drain, lk_rem_now, lk_rem_out.astype(i64))
    # reset_time is computed from the PRE-hit remaining (go:372-377) and only
    # recomputed by the exact/consume branches (go:400,428) — a DRAIN_OVER_LIMIT
    # rejection keeps the pre-drain reset_time.
    lk_reset_basis = jnp.where(
        lk_exact, i64(0), jnp.where(lk_consume, lk_rem_out.astype(i64), lk_rem_now)
    )
    lk_resp_reset = now + (req.limit - lk_reset_basis) * irate
    # hits≠0 refreshes expiry before any verdict (go:355-357)
    lk_exp = jnp.where(h != 0, now + req.duration_eff, s_exp)

    # --- new item (go:436-492)
    lkn_over = h > req.burst
    lkn_rem = jnp.where(lkn_over, f64(0.0), (req.burst - h).astype(f64))
    lkn_resp_rem = jnp.where(lkn_over, i64(0), req.burst - h)
    lkn_status = jnp.where(lkn_over, OVER, UNDER)
    lkn_reset = now + (req.limit - lkn_resp_rem) * irate
    lkn_exp = now + req.duration_eff

    lk_fp_out = req.fp
    lk_rem_store = jnp.where(lk_is_new, lkn_rem, lk_rem_out)
    lk_stamp_out = jnp.where(lk_is_new, now, lk_stamp)
    lk_exp_out = jnp.where(lk_is_new, lkn_exp, lk_exp)
    # stored duration: new items persist the effective (Gregorian-resolved)
    # duration (go:452-458); existing items persist the raw request duration
    # (go:332).
    lk_dur_out = jnp.where(lk_is_new, req.duration_eff, req.duration)
    lk_resp_status = jnp.where(lk_is_new, lkn_status, lk_resp_status)
    lk_resp_rem = jnp.where(lk_is_new, lkn_resp_rem, lk_resp_rem)
    lk_resp_reset = jnp.where(lk_is_new, lkn_reset, lk_resp_reset)

    # ==================================================== merge + scatter
    fp_out = jnp.where(is_token, tok_fp_out, lk_fp_out)
    status_out = jnp.where(is_token, tok_status_out, UNDER)
    rem_i_out = jnp.where(is_token, tok_rem_store, i64(0))
    rem_f_out = jnp.where(is_token, f64(0.0), lk_rem_store)
    stamp_out = jnp.where(is_token, tok_created_out, lk_stamp_out)
    dur_out = jnp.where(is_token, req.duration, lk_dur_out)
    exp_out = jnp.where(is_token, tok_exp_out, lk_exp_out)
    burst_out = jnp.where(is_token, i64(0), req.burst)

    w = jnp.where(req.active & resolved, assigned, DROP)
    table = table._replace(
        fp=table.fp.at[w].set(fp_out, mode="drop"),
        algo=table.algo.at[w].set(req.algo, mode="drop"),
        status=table.status.at[w].set(status_out, mode="drop"),
        limit=table.limit.at[w].set(req.limit, mode="drop"),
        duration=table.duration.at[w].set(dur_out, mode="drop"),
        remaining_i=table.remaining_i.at[w].set(rem_i_out, mode="drop"),
        remaining_f=table.remaining_f.at[w].set(rem_f_out, mode="drop"),
        stamp=table.stamp.at[w].set(stamp_out, mode="drop"),
        burst=table.burst.at[w].set(burst_out, mode="drop"),
        expire_at=table.expire_at.at[w].set(exp_out, mode="drop"),
        invalid_at=table.invalid_at.at[w].set(i64(0), mode="drop"),
    )

    resp_status = jnp.where(is_token, tok_resp_status, lk_resp_status)
    resp_rem = jnp.where(is_token, tok_resp_rem, lk_resp_rem)
    resp_reset = jnp.where(is_token, tok_resp_reset, lk_resp_reset)

    resp = RespBatch(
        status=jnp.where(req.active, resp_status, UNDER),
        limit=jnp.where(req.active, req.limit, i64(0)),
        remaining=jnp.where(req.active, resp_rem, i64(0)),
        reset_time=jnp.where(req.active, resp_reset, i64(0)),
        cache_hit=exists,
        dropped=dropped,
    )
    stats = BatchStats(
        cache_hits=exists.sum(dtype=i64),
        cache_misses=(req.active & ~exists).sum(dtype=i64),
        over_limit=(req.active & (resp.status == OVER)).sum(dtype=i64),
        evicted_unexpired=evicted_unexpired.sum(dtype=i64),
        dropped=dropped.sum(dtype=i64),
    )
    return table, resp, stats
