"""Decision kernel v2: packed-row table, sort-based claim, Pallas sweep write.

Replaces the v1 kernel's memory strategy (ops/kernel.py — 15 f32-carrier plane
scatters + 12 flat gathers + a multi-round scatter-max claim auction, ~74 ms
per 131K-row dispatch on v5e) with the design measured fastest on real TPU
(exp/exp_mem*.py):

  1. **fetch** — ONE (B, 128) row gather brings each request's whole bucket
     (all 8 slots, full state) into registers: ~1.3 ms.
  2. **claim** — pure vector math, no device auction: requests are sorted by
     bucket (lax.sort of int32 operands, ~0.1 ms); each inserting row takes a
     rank among its bucket's inserters via segmented prefix sums, and rank r
     picks the r-th lane in (vacant-first, then soonest-expiring) order.
     Insert-vs-owner lane collisions are resolved by a second sort over target
     slots (owners win; losers are answered but flagged dropped → the engine
     retries them, cf. v1's auction losers).
  3. **apply** — the shared branchless decision table (ops/math.py) on the
     chosen lane's state.
  4. **write** — the update set becomes (payload, lane-mask) rows composed into
     bucket rows by a **Pallas sweep**: the table streams through VMEM in
     (BLK, 128) blocks while int8 one-hot matmuls on the MXU scatter each
     block's updates into place; blocks whose update run fits their first
     u-window skip the second half's matmuls via a scalar-prefetched
     predicate (~4.2 ms for a 1 GiB table at headline batch,
     exp/exp_sweep5.py). `write="sparse"` launches the SAME sweep grid only
     over the batch's dirty blocks via scalar-prefetched block indices
     (_write_sparse) — write cost ∝ batch, not table size — and resolves
     back to the full sweep past a coverage crossover (resolve_write /
     GUBER_WRITE_SPARSE_CROSSOVER). XLA scatter fallback (`write="xla"`)
     keeps identical semantics for CPU meshes/tests.

Dispatches are additionally specialized host-side by
`math="token"|"int"|"mixed"` (engine._math_mode): all-token batches — the
common case — compile a decision graph with ONLY the token lanes; batches
mixing in GCRA / sliding-window / concurrency-lease rows compile the
all-integer graph; only a leaky row forces the emulated-float64 lanes
(see ops/math.bucket_math).

Same decision semantics as v1 (reference algorithms.go:37-492 via
ops/math.py). Documented divergence from v1: slot-vacancy uses the exact
millisecond expiry (the whole bucket is already in registers) instead of v1's
conservative coarse-expiry probe plane, and a burst of inserts into one full
bucket may evict several soonest-expiring lanes at once (v1 evicted at most
one per dispatch round; the reference's LRU evicts as many as needed,
lrucache.go:138-149). The leaky remainder is stored as a double-single f32
pair (REMF_HI/LO, ~48-bit mantissa) vs the reference's float64 (store.go:32):
exact for every integer remainder in the accepted config range — limits and
bursts are validated to int32 (pack_columns ERR_LIMIT_I32/ERR_BURST_I32), so
integer parts are ≤ 2^31 ≪ 2^48 — with fractional-refill resolution ≥ 2^-17
tokens at the i32 extreme (measured worst roundtrip error 2^-19; bounds
asserted in tests/test_leaky_bucket.py). Configs beyond i32, which COULD
quantize, are rejected rather than served imprecisely; in-kernel math is
float64 throughout (ops/math.py).
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gubernator_tpu.ops.batch import BatchStats, ReqBatch, RespBatch
from gubernator_tpu.ops.math import StoredState, bucket_math
from gubernator_tpu.ops.table2 import (
    BURST,
    DUR_HI,
    DUR_LO,
    EXP_HI,
    EXP_LO,
    F,
    FLAGS,
    FP_HI,
    FP_LO,
    K,
    LIMIT,
    REM_I,
    REMF_HI,
    REMF_LO,
    ROW,
    STAMP_HI,
    STAMP_LO,
    Table2,
)
from gubernator_tpu.types import Status

i64 = jnp.int64
i32 = jnp.int32
f64 = jnp.float64
f32 = jnp.float32

# x64-disable context across jax versions: top-level on new jax,
# jax.experimental on 0.4.x
if hasattr(jax, "enable_x64"):
    _enable_x64 = jax.enable_x64
else:
    from jax.experimental import enable_x64 as _enable_x64


def _sweep_x64_ctx(interpret: bool):
    """Trace the sweep/sparse pallas_call with x64 OFF on real TPU (Mosaic
    rejects the x64-promoted scalars the surrounding graph traces with) but
    leave the config ALONE under the CPU interpreter: flipping x64 mid-trace
    makes the interpreter's grid loop emit mixed i32/i64 scalar helpers that
    collide in the 0.4.x lowering cache ('func.call operand type mismatch')."""
    import contextlib

    return contextlib.nullcontext() if interpret else _enable_x64(False)


def _lo32(x):
    return (x & 0xFFFFFFFF).astype(i32)


def _hi32(x):
    return (x >> 32).astype(i32)


def _join64(lo32, hi32):
    return (hi32.astype(i64) << 32) | (lo32.astype(i64) & 0xFFFFFFFF)


def _biased(x_i32):
    """Map int32 bit patterns to an order-preserving signed key for the
    unsigned value (flip the sign bit): sorting the result as int32 sorts the
    original as uint32."""
    return x_i32 ^ jnp.int32(-0x80000000)


def _cummax(x):
    return jax.lax.cummax(x, axis=0)


def sweep_geometry(n_buckets: int, batch: int) -> Tuple[int, int]:
    """(BLK bucket-rows per Pallas block, U update window per block).

    U covers the expected per-block update count plus a ~5-sigma Poisson tail
    (overflow rows are dropped → engine retry, so the tail bound is a perf
    knob, not correctness). BLK stays as LARGE as VMEM allows: the sweep's
    cost is dominated by per-block pipeline overhead, not the one-hot MXU
    work — exp/exp_sweep5.py measured 4.20 ms at (2048, 256) vs 6.34 ms at
    (1024, 128) for the same headline update set, even though the smaller
    window runs half the matmul MACs. BLK shrinks only until the (BLK, U)
    one-hot operand fits VMEM comfortably."""
    blk = min(2048, n_buckets)
    if n_buckets % blk:
        # tables built by new_table2 are always conforming (power-of-two below
        # 2048 buckets, multiple of 2048 above); a hand-built table with a
        # non-dividing bucket count would leave tail rows outside the Pallas
        # grid with undefined content under input_output_aliasing
        raise ValueError(
            f"n_buckets={n_buckets} not divisible by sweep block {blk}; "
            "build tables with new_table2()"
        )
    while True:
        nblk = n_buckets // blk
        mean = batch / nblk
        u = int(mean + 5.0 * mean**0.5) + 64
        p = 64  # power of two so the window count divides the (pow2) batch —
        # the sweep's dynamic index maps address u-aligned payload blocks
        while p < u:
            p *= 2
        u = min(p, batch)
        # VMEM stack bound: the two-half kernel holds ~6 (blk,128) i32
        # temps + 2 (blk,u) onehots; blk*u ≤ 2^19 keeps the scoped
        # allocation under the 16 MiB limit (measured: u=512 × blk=2048
        # overflows at 21.4 MiB)
        if blk * u <= (1 << 19) or blk <= 256:
            # the blk floor must not re-open the VMEM bound (a small table
            # under a huge batch otherwise walks to blk=256, u=batch): cap u
            # hard — window-overflow rows just drop to the engine's retry
            u = min(u, max(64, (1 << 19) // blk))
            return blk, u
        blk //= 2


def _sparse_blk() -> int:
    """Block rows per sparse-write grid step (GUBER_WRITE_SPARSE_BLK).

    Small on purpose: the sparse path's HBM traffic is (dirty blocks) × BLK
    rows, and dirty blocks ≈ min(batch, n_buckets/BLK) for hash-spread
    targets — so BLK is the knob trading per-step pipeline overhead against
    bytes touched per dirty block. Read per trace (host-side), so tuning
    runs can flip it between compiles without a restart."""
    return int(os.environ.get("GUBER_WRITE_SPARSE_BLK", "64"))


def sparse_write_crossover() -> float:
    """Coverage bound gating the sparse write (GUBER_WRITE_SPARSE_CROSSOVER):
    `write="sparse"` resolves to the full sweep unless the sparse grid's
    worst-case coverage (grid steps × BLK bucket rows) times this factor
    still fits under n_buckets — i.e. sparse only runs when it provably
    touches ≤ 1/crossover of the table, where its batch-proportional cost
    beats the table-streaming sweep."""
    return float(os.environ.get("GUBER_WRITE_SPARSE_CROSSOVER", "4"))


def sparse_geometry(n_buckets: int, batch: int) -> Tuple[int, int, int]:
    """(BLK bucket-rows per sparse block, U update window, G grid steps).

    Unlike the dense sweep (BLK as large as VMEM allows — per-block overhead
    amortizes over the whole-table stream), the sparse grid visits only
    dirty blocks, so BLK stays SMALL: each of the ≤ min(batch, n_buckets/BLK)
    dirty blocks costs BLK·512 B of HBM traffic regardless of how many
    updates it holds. U follows the same Poisson-tail policy as
    sweep_geometry (overflow rows drop to the engine's retry), and the VMEM
    stack bound blk·u ≤ 2^19 is inherited unchanged."""
    blk = min(_sparse_blk(), n_buckets)
    while blk > 1 and n_buckets % blk:
        # conforming tables (new_table2) are pow2 below 2048 buckets or a
        # multiple of 2048 above — some pow2 ≤ blk always divides
        blk //= 2
    nblk = n_buckets // blk
    mean = batch / nblk
    u = int(mean + 5.0 * mean**0.5) + 64
    p = 64
    while p < u:
        p *= 2
    u = min(p, batch)
    u = min(u, max(64, (1 << 19) // blk))
    return blk, u, min(nblk, batch)


def resolve_write(write: str, n_buckets: int, batch: int, layout=None) -> str:
    """Per-dispatch (static-shape) write-mode resolution. `"sparse"` falls
    back to the full sweep when the worst-case dirty coverage crosses
    GUBER_WRITE_SPARSE_CROSSOVER — a 131K-row headline dispatch on a 1 GiB
    table resolves to the sweep, a 4K serving dispatch to the sparse grid.
    Runs host-side at trace time (batch and table shapes are static), so the
    jit cache key (the `write` string) stays stable per call site.

    The crossover is BYTE-denominated, not row-denominated: the sweep's
    cost is the table's bytes streamed through VMEM while the sparse grid's
    dominant cost is per-block pipeline overhead (byte-count-independent at
    its small BLK). A packed 32 B layout halves the bytes both sides touch
    per row but not the sparse grid's per-block overhead, so the coverage
    fraction where sparse still wins DOUBLES — the worst-case dirty
    coverage is scaled by layout.F / 16 before the crossover compare, i.e.
    the knob's value keeps meaning "sparse must touch ≤ 1/crossover of a
    FULL-layout table's bytes". `layout=None` (or full) preserves the
    pre-layout behavior bit-for-bit."""
    if write not in ("sweep", "sparse", "xla"):
        raise ValueError(
            f"unknown write mode {write!r}; expected 'sweep', 'sparse' or 'xla'"
        )
    if write != "sparse":
        return write
    if layout is None:
        from gubernator_tpu.ops.layout import FULL as layout
    blk, _u, g = sparse_geometry(n_buckets, batch)
    coverage_bytes_scaled = g * blk * (layout.F / float(F))
    if coverage_bytes_scaled * sparse_write_crossover() >= n_buckets:
        return "sweep"
    return "sparse"


class Claim2(NamedTuple):
    bucket: jnp.ndarray  # (B,) i32
    chosen: jnp.ndarray  # (B,) i32 lane in [0, K)
    got: jnp.ndarray  # (B,) bool — row has a lane (pre-dedup)
    owns: jnp.ndarray  # (B,) bool — lane holds this row's fp
    written: jnp.ndarray  # (B,) bool — row survives dedup (+ window overflow)
    evict_live: jnp.ndarray  # (B,) bool — claimed lane held a live item
    slots: jnp.ndarray  # (B, K, F) i32 — the gathered bucket contents
    # sweep-write routing (sorted-by-target domain)
    order: jnp.ndarray  # (B,) i32 original index at each sorted position
    tgt_sorted: jnp.ndarray  # (B,) i32 target slot at each sorted position
    written_sorted: jnp.ndarray  # (B,) bool — written flag at sorted position


def _probe_claim2(
    rows_tbl: jnp.ndarray, fp, now, active, blk: int, u: int, layout=None
) -> Claim2:
    """Probe + claim. `layout` (ops/layout.py) is the table's slot layout:
    the row gather fetches layout.row lanes per bucket — HALF the HBM
    bytes for the 32 B packed layouts — and the packed fields unpack to
    the canonical 16-field slots in registers, so every consumer below
    (claim ordering, decision math, merge rules) stays layout-blind."""
    if layout is None:
        from gubernator_tpu.ops.layout import FULL as layout
    NB = rows_tbl.shape[0]
    B = fp.shape[0]
    if NB * K * 2 >= 2**31:
        raise ValueError("table too large for int32 slot ids")

    bucket = (fp % NB).astype(i32)
    my_lo = _lo32(fp)
    my_hi = _hi32(fp)

    rows = rows_tbl[bucket]  # (B, ROW_layout) row gather — the only table read
    slots = layout.unpack(rows.reshape(B, K, layout.F))  # (B, K, 16) canonical
    s_fp_lo = slots[:, :, FP_LO]
    s_fp_hi = slots[:, :, FP_HI]

    empty = (s_fp_lo == 0) & (s_fp_hi == 0)
    match = (s_fp_lo == my_lo[:, None]) & (s_fp_hi == my_hi[:, None]) & ~empty
    match = match & active[:, None]
    owns = match.any(axis=1)
    own_j = jnp.argmax(match, axis=1).astype(i32)

    # exact lazy expiry (reference lrucache.go:111-128): expired slots are
    # reclaimable by any key probing the bucket. Compared in the split
    # (hi, lo-as-unsigned) domain — int64 on TPU is emulated, and this is
    # the kernel's only (B, K)-shaped 64-bit computation
    exp_lo = slots[:, :, EXP_LO]
    exp_hi = slots[:, :, EXP_HI]
    now_hi = _hi32(now)
    now_lo_b = _biased(_lo32(now))
    dead = ~empty & (
        (exp_hi < now_hi[:, None])
        | ((exp_hi == now_hi[:, None]) & (_biased(exp_lo) < now_lo_b[:, None]))
    )
    vacant = empty | dead
    live = ~vacant

    # ---- rank among inserting rows of the same bucket (sorted domain)
    need = active & ~owns
    NBs = jnp.int32(NB)
    bkey = jnp.where(active, bucket, NBs)
    idx = jnp.arange(B, dtype=i32)
    bkey_s, need_s, idx_s1 = jax.lax.sort(
        (bkey, need.astype(i32), idx), num_keys=1
    )
    first = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), bkey_s[1:] != bkey_s[:-1]]
    )
    csum = jnp.cumsum(need_s)
    c_excl = csum - need_s
    seg_base = _cummax(jnp.where(first, c_excl, -1))
    rank_s = (c_excl - seg_base).astype(i32)
    # un-sort: rank back to original row order
    _, rank = jax.lax.sort((idx_s1, rank_s), num_keys=1)

    # ---- candidate lane order: vacant lanes first (by index), then live
    # lanes by soonest expiry — expiry-stamp eviction, v1 semantics
    lane_iota = jnp.broadcast_to(jnp.arange(K, dtype=i32), (B, K))
    exp_hi_k = slots[:, :, EXP_HI]
    exp_lo_k = _biased(slots[:, :, EXP_LO])
    _, _, _, cand = jax.lax.sort(
        (live.astype(i32), exp_hi_k, exp_lo_k, lane_iota), num_keys=3, dimension=1
    )
    rank_c = jnp.clip(rank, 0, K - 1)
    ins_lane = jnp.take_along_axis(cand, rank_c[:, None], axis=1)[:, 0]
    claim_ok = need & (rank < K)

    chosen = jnp.where(owns, own_j, ins_lane)
    got = active & (owns | claim_ok)
    lane_live = jnp.take_along_axis(live, chosen[:, None], axis=1)[:, 0]
    evict_live = claim_ok & lane_live

    # ---- conflict dedup + sweep window assignment over target slots
    NBK = jnp.int32(NB * K)
    target = jnp.where(got, bucket * K + chosen, NBK)
    # owners sort ahead of inserters on equal targets, so dedup keeps them
    skey = target * 2 + jnp.where(owns, 0, 1).astype(i32)
    skey_s, idx_s2 = jax.lax.sort((skey, idx), num_keys=1)
    tgt_s = skey_s >> 1
    dup = jnp.concatenate([jnp.zeros((1,), dtype=bool), tgt_s[1:] == tgt_s[:-1]])

    # window overflow: position within the target's sweep block run
    pos_i = jnp.arange(B, dtype=i32)
    blk_of = tgt_s // jnp.int32(K * blk)
    first_blk = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), blk_of[1:] != blk_of[:-1]]
    )
    blk_start = _cummax(jnp.where(first_blk, pos_i, -1))
    overflow = (pos_i - blk_start) >= u

    written_s = (tgt_s < NBK) & ~dup & ~overflow
    _, written_i = jax.lax.sort((idx_s2, written_s.astype(i32)), num_keys=1)
    written = written_i.astype(bool)

    return Claim2(
        bucket=bucket,
        chosen=chosen,
        got=got,
        owns=owns,
        written=written,
        evict_live=evict_live & written,
        slots=slots,
        order=idx_s2,
        tgt_sorted=tgt_s,
        written_sorted=written_s,
    )


# --------------------------------------------------------------------- write


def _make_sweep_kernel(nwin: int, blk: int, u: int, fl: int = F,
                       sparse: bool = False):
    """Kernel factory for the scalar-prefetch sweep (closes over geometry).

    Windowing lives IN the kernel: updates stay in target-sorted order; the
    grid's dynamic block index maps (PrefetchScalarGridSpec) DMA the two
    u-aligned payload blocks covering this table block's update run, and
    slot/lane-mask/liveness derive from the raw sorted targets. Each half is
    composed into the block rows via int8 one-hot matmuls (MXU — the
    scatter-as-matmul trick); unique targets (claim dedup) mean the sums
    place, never add. A run never extends past start+u (the probe's window
    overflow marks the tail dropped), so two aligned u-blocks always cover
    it; the second half is masked off when its block index clamps (window at
    the array end).

    The previous design materialized (nblk·u) host-side window gathers —
    measured 8 ms of the 16 ms write at headline scale; in-kernel windowing
    plus one payload gather runs the same sweep in ~3.3 ms (≈600 GB/s through
    a 1 GiB table). The second half's matmuls only run when this block's
    update run actually crosses its first window boundary (`need2`, scalar-
    prefetched per block) — runs are ~mean-length and windows u-aligned, so
    most blocks take the single-half branch and the MXU work per sweep drops
    by roughly the non-straddle fraction.

    `sparse=True` builds the block-sparse variant (_write_sparse): grid step
    i composes the dirty block named by the scalar-prefetched `db_ref[i]`
    instead of block i — same body, data-dependent block base. `fl` is the
    table layout's fields-per-slot (ops/layout.py): payload rows are
    (u, fl) and table blocks (blk, K·fl) — the packed layouts stream half
    the bytes per block through VMEM."""
    KBLK = K * blk
    ROW_L = K * fl

    def body(i, blk_base, n2_ref, p1, p2, t1, t2, tbl_in, tbl_out):
        dot = functools.partial(
            jax.lax.dot_general,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=i32,
        )

        def half(pay_ref, tgt_ref):
            pay = pay_ref[:]  # (u, fl) i32 payload, sorted-by-target
            tgt = tgt_ref[:]  # (u, 1) i32 global slot target (sentinel NBK)
            rel = tgt - blk_base
            live = (rel >= 0) & (rel < KBLK)
            slot = jnp.where(live, rel % K, -1)  # (u, 1)
            lb = jnp.where(live, rel // K, -1)  # (u, 1)
            # lane l of a bucket row belongs to slot l//fl, field l%fl
            lane_slot = jax.lax.broadcasted_iota(i32, (u, ROW_L), 1) // fl
            upd = jnp.concatenate([pay] * K, axis=1)  # (u, K·fl)
            msk = (lane_slot == slot).astype(jnp.int8)
            iot = jax.lax.broadcasted_iota(i32, (blk, u), 0)
            onehot = (iot == lb[:, 0][None, :]).astype(jnp.int8)
            w = dot(onehot, msk)
            acc = None
            for s in range(4):
                plane = (((upd >> (8 * s)) & 0xFF) * msk.astype(i32)).astype(
                    jnp.int8
                )
                p = dot(onehot, plane)
                # one (sign-extended) byte per hit — re-mask, then place
                p = (p & 0xFF) << (8 * s)
                acc = p if acc is None else acc | p
            return acc, w

        # need2 ⇒ s+1 ≤ nwin-1 (a run never extends past the batch end), so
        # the second window's block index is always in range on this branch
        @pl.when(n2_ref[i] != 0)
        def _():
            acc1, w1 = half(p1, t1)
            acc2, w2 = half(p2, t2)
            tbl_out[:] = jnp.where(w1 + w2 > 0, acc1 | acc2, tbl_in[:])

        @pl.when(n2_ref[i] == 0)
        def _():
            acc1, w1 = half(p1, t1)
            tbl_out[:] = jnp.where(w1 > 0, acc1, tbl_in[:])

    if sparse:

        def kern_sparse(db_ref, s_ref, n2_ref, p1, p2, t1, t2, tbl_in, tbl_out):
            i = pl.program_id(0)
            body(i, db_ref[i] * KBLK, n2_ref, p1, p2, t1, t2, tbl_in, tbl_out)

        return kern_sparse

    def kern(s_ref, n2_ref, p1, p2, t1, t2, tbl_in, tbl_out):
        i = pl.program_id(0)
        body(i, i * KBLK, n2_ref, p1, p2, t1, t2, tbl_in, tbl_out)

    return kern


def _write_sweep(rows_tbl, new16, c: Claim2, blk: int, u: int, layout=None):
    """Pallas sweep write: stream the table through VMEM once, composing the
    target-sorted update run of each block in-kernel (see _make_sweep_kernel).
    Payload rows pack to the table's slot layout before the gather, so a
    packed table's sweep streams layout.row lanes per bucket — half the
    bytes for the 32 B layouts."""
    if layout is None:
        from gubernator_tpu.ops.layout import FULL as layout
    fl, rowl = layout.F, layout.row
    NB = rows_tbl.shape[0]
    B = new16.shape[0]
    nblk = NB // blk
    nwin = B // u
    assert nwin * u == B, f"batch {B} not divisible by window {u}"

    new_pk = layout.pack(new16)  # (B, fl)
    pay_s = new_pk[c.order]  # the ONE payload gather: original → sorted order
    tgt_eff = jnp.where(
        c.written_sorted, c.tgt_sorted, jnp.int32(NB * K)
    ).astype(i32)[:, None]
    starts = jnp.searchsorted(
        c.tgt_sorted, (jnp.arange(nblk, dtype=i32) * (K * blk)).astype(i32)
    ).astype(i32)
    ends = jnp.concatenate([starts[1:], jnp.full((1,), B, dtype=i32)])
    s_blk = jnp.clip(starts // u, 0, nwin - 1)
    # does block i's update run cross its first window's end? (ends ≤ B, so
    # need2 ⇒ s_blk+1 ≤ nwin-1; blocks whose run fits one window skip the
    # second half's matmuls entirely)
    need2 = (ends > (s_blk + 1) * u).astype(i32)

    second = lambda i, s, n2: (jnp.minimum(s[i] + 1, nwin - 1), 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((u, fl), lambda i, s, n2: (s[i], 0)),
            pl.BlockSpec((u, fl), second),
            pl.BlockSpec((u, 1), lambda i, s, n2: (s[i], 0)),
            pl.BlockSpec((u, 1), second),
            pl.BlockSpec((blk, rowl), lambda i, s, n2: (i, 0)),
        ],
        out_specs=pl.BlockSpec((blk, rowl), lambda i, s, n2: (i, 0)),
    )
    interpret = jax.default_backend() == "cpu"
    with _sweep_x64_ctx(interpret):
        out = pl.pallas_call(
            _make_sweep_kernel(nwin, blk, u, fl),
            interpret=interpret,
            out_shape=jax.ShapeDtypeStruct(rows_tbl.shape, rows_tbl.dtype),
            grid_spec=grid_spec,
            input_output_aliases={6: 0},
        )(s_blk, need2, pay_s, pay_s, tgt_eff, tgt_eff, rows_tbl)
    return out


def _write_sparse(rows_tbl, new16, c: Claim2, blk: int, u: int, g: int,
                  layout=None):
    """Block-sparse Pallas write: launch the sweep grid ONLY over dirty
    blocks, so the write's HBM traffic scales with the batch, not the table.

    The dirty-block set — the ≤ min(batch, nblk) unique `target // (K·blk)`
    values over WRITTEN rows — is computed in-trace (sort + unique, a few µs
    of vector work against the ms-scale sweep it replaces) and handed to the
    kernel as a scalar-prefetched block-index vector: grid step i DMAs block
    `db[i]` in and out, composing its update run exactly like the dense
    sweep. Unvisited blocks are untouched — `input_output_aliases` makes the
    output buffer the donated input, so their rows simply persist.

    Grid padding (g is static, the dirty count dynamic): padded steps target
    a provably-CLEAN block — the smallest block id absent from the dirty set
    (first index where the sorted unique list skips a value) — and compose an
    empty run, i.e. rewrite that block's unchanged content. Padding steps all
    name the SAME block and sit contiguously at the end of the sorted list,
    so Pallas' revisit rule (consecutive equal block indices share one VMEM
    buffer, fetched and flushed once) makes them write identical bytes — no
    read-after-write hazard, unlike duplicate DIRTY blocks, which is why the
    real entries are deduplicated rather than clamped."""
    if layout is None:
        from gubernator_tpu.ops.layout import FULL as layout
    fl, rowl = layout.F, layout.row
    NB = rows_tbl.shape[0]
    B = new16.shape[0]
    nblk = NB // blk
    KBLK = K * blk
    nwin = B // u
    assert nwin * u == B, f"batch {B} not divisible by window {u}"
    assert g >= 1

    new_pk = layout.pack(new16)  # (B, fl)
    pay_s = new_pk[c.order]  # the ONE payload gather: original → sorted order
    tgt_eff = jnp.where(
        c.written_sorted, c.tgt_sorted, jnp.int32(NB * K)
    ).astype(i32)[:, None]
    NBLK = jnp.int32(nblk)
    # dirty block per written row; sentinel nblk otherwise (merges with the
    # unique fill value — both mean "padding step")
    blk_w = jnp.where(c.written_sorted, c.tgt_sorted // jnp.int32(KBLK), NBLK)
    du = jnp.unique(blk_w, size=g, fill_value=nblk).astype(i32)
    # free (clean) block for padding steps: du is sorted unique, so the
    # first index i with du[i] > i is a block id absent from the dirty set
    # (padding entries du[i] = nblk > i always qualify, so when any padding
    # exists the min is < nblk; with zero written rows it degrades to 0)
    idxg = jnp.arange(g, dtype=i32)
    free = jnp.min(jnp.where(du > idxg, idxg, NBLK))
    db = jnp.where(du >= NBLK, free, du)

    starts = jnp.searchsorted(c.tgt_sorted, db * jnp.int32(KBLK)).astype(i32)
    ends = jnp.searchsorted(
        c.tgt_sorted, (db + 1) * jnp.int32(KBLK)
    ).astype(i32)
    s_blk = jnp.clip(starts // u, 0, nwin - 1)
    need2 = (ends > (s_blk + 1) * u).astype(i32)

    second = lambda i, db_, s, n2: (jnp.minimum(s[i] + 1, nwin - 1), 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(g,),
        in_specs=[
            pl.BlockSpec((u, fl), lambda i, db_, s, n2: (s[i], 0)),
            pl.BlockSpec((u, fl), second),
            pl.BlockSpec((u, 1), lambda i, db_, s, n2: (s[i], 0)),
            pl.BlockSpec((u, 1), second),
            pl.BlockSpec((blk, rowl), lambda i, db_, s, n2: (db_[i], 0)),
        ],
        out_specs=pl.BlockSpec((blk, rowl), lambda i, db_, s, n2: (db_[i], 0)),
    )
    interpret = jax.default_backend() == "cpu"
    with _sweep_x64_ctx(interpret):
        out = pl.pallas_call(
            _make_sweep_kernel(nwin, blk, u, fl, sparse=True),
            interpret=interpret,
            out_shape=jax.ShapeDtypeStruct(rows_tbl.shape, rows_tbl.dtype),
            grid_spec=grid_spec,
            input_output_aliases={7: 0},
        )(db, s_blk, need2, pay_s, pay_s, tgt_eff, tgt_eff, rows_tbl)
    return out


def _write_xla(rows_tbl, new16, c: Claim2, layout=None):
    """Semantically identical scatter write for backends without the Pallas
    TPU pipeline (CPU test meshes). Slot-granular, drop-mode."""
    if layout is None:
        from gubernator_tpu.ops.layout import FULL as layout
    NB = rows_tbl.shape[0]
    slot_view = rows_tbl.reshape(NB * K, layout.F)
    tgt = jnp.where(c.written, c.bucket * K + c.chosen, NB * K)
    out = slot_view.at[tgt].set(layout.pack(new16), mode="drop")
    return out.reshape(NB, layout.row)


# -------------------------------------------------------------------- decide


def decide_payload(lane16, req: ReqBatch, owns, *, math: str):
    """The per-row DECIDE stage, shared VERBATIM by the XLA path below and
    the fused Pallas probe kernel (ops/pallas_probe.py): the chosen lane's
    canonical (B, 16) stored fields + the request rows → (exists, Decision,
    canonical (B, 16) write-payload rows). Factoring it out is what makes
    the two probe kernels bit-identical by construction on everything
    downstream of the claim — algorithm math, payload packing, response
    fields — instead of by parallel maintenance."""
    now = req.created_at
    B = req.fp.shape[0]
    g = lambda f: lane16[:, f]
    s_exp = _join64(g(EXP_LO), g(EXP_HI))
    exists = owns & (s_exp >= now)
    s_flags = g(FLAGS)
    stored = StoredState(
        limit=g(LIMIT).astype(i64),
        burst=g(BURST).astype(i64),
        rem_i=g(REM_I).astype(i64),
        algo=s_flags & 0xFF,
        status=s_flags >> 8,
        duration=_join64(g(DUR_LO), g(DUR_HI)),
        stamp=_join64(g(STAMP_LO), g(STAMP_HI)),
        exp=s_exp,
        rem_f=jax.lax.bitcast_convert_type(g(REMF_HI), f32).astype(f64)
        + jax.lax.bitcast_convert_type(g(REMF_LO), f32).astype(f64),
        # the SAME lane pair reinterpreted as a raw int64 — GCRA's TAT and
        # the sliding window's previous count live here (ops/math.py
        # storage convention); dead code (DCE'd) under math="token"
        aux=_join64(g(REMF_LO), g(REMF_HI)),
    )
    d = bucket_math(stored, req, exists, mode=math)

    # ---- build update payload rows
    sat32 = lambda x: jnp.clip(x, -(2**31), 2**31 - 1).astype(i32)
    # REMF lane pair by algorithm family: zeros for token-only batches,
    # the raw aux int64 (GCRA TAT / window prev) for int batches, and the
    # leaky f64 split merged in per row for mixed ones
    if math == "token":
        remf_hi_i = jnp.zeros(B, dtype=i32)
        remf_lo_i = jnp.zeros(B, dtype=i32)
    elif math in ("gcra", "int"):
        remf_hi_i = _hi32(d.aux_out)
        remf_lo_i = _lo32(d.aux_out)
    else:
        f_hi = d.rem_f_out.astype(f32)
        f_lo = (d.rem_f_out - f_hi.astype(f64)).astype(f32)
        is_leaky = req.algo == 1
        remf_hi_i = jnp.where(
            is_leaky, jax.lax.bitcast_convert_type(f_hi, i32), _hi32(d.aux_out)
        )
        remf_lo_i = jnp.where(
            is_leaky, jax.lax.bitcast_convert_type(f_lo, i32), _lo32(d.aux_out)
        )
    my_lo = _lo32(req.fp)
    my_hi = _hi32(req.fp)
    zero = jnp.zeros_like(my_lo)
    new16 = jnp.stack(
        [
            jnp.where(d.remove, 0, my_lo),
            jnp.where(d.remove, 0, my_hi),
            sat32(req.limit),
            sat32(d.burst_out),
            sat32(d.rem_i_out),
            d.flags_out,
            _lo32(d.dur_out),
            _hi32(d.dur_out),
            _lo32(d.stamp_out),
            _hi32(d.stamp_out),
            jnp.where(d.remove, 0, _lo32(d.exp_out)),
            jnp.where(d.remove, 0, _hi32(d.exp_out)),
            remf_hi_i,
            remf_lo_i,
            zero,
            zero,
        ],
        axis=1,
    )  # (B, F)
    return exists, d, new16


def assemble_resp(req: ReqBatch, d, exists, written, evict_live):
    """Response + stats assembly shared by both probe kernels: the Decision
    rows plus the claim outcome flags → (RespBatch, BatchStats)."""
    active = req.active
    OVER = jnp.int32(int(Status.OVER_LIMIT))
    UNDER = jnp.int32(int(Status.UNDER_LIMIT))
    dropped = active & ~written
    resp = RespBatch(
        status=jnp.where(active, d.resp_status, UNDER),
        limit=jnp.where(active, req.limit, i64(0)),
        remaining=jnp.where(active, d.resp_rem, i64(0)),
        reset_time=jnp.where(active, d.resp_reset, i64(0)),
        cache_hit=exists,
        dropped=dropped,
        # stored-state echoes for full-fidelity GLOBAL broadcasts
        # (parallel/global_sync._sync_core): the raw aux (GCRA TAT /
        # sliding-window previous count) and the remaining-STYLE integer
        # lane. DCE'd in every serving graph (pack_outputs ignores them).
        aux=d.aux_out,
        rem_store=d.rem_i_out,
    )
    stats = BatchStats(
        cache_hits=exists.sum(dtype=i64),
        cache_misses=(active & ~exists).sum(dtype=i64),
        over_limit=(active & (resp.status == OVER)).sum(dtype=i64),
        evicted_unexpired=evict_live.sum(dtype=i64),
        dropped=dropped.sum(dtype=i64),
    )
    return resp, stats


def decide2_impl(
    table: Table2, req: ReqBatch, *, write: str = "sweep", math: str = "mixed",
    probe: str = "xla", evictees: bool = False,
) -> Tuple[Table2, RespBatch, BatchStats]:
    """Un-jitted v2 kernel body — call through `decide2` / `decide2_xla`.

    `math="token"` compiles the token-only decision graph (no emulated-f64
    leaky lanes — see ops/math.bucket_math); the engine selects it per
    dispatch after a host-side check that the batch carries no leaky row.
    `write="sparse"` resolves per dispatch shape (resolve_write): the
    block-sparse grid when its coverage is a small fraction of the table,
    the full sweep otherwise. The table's slot layout (ops/layout.py)
    threads through the probe gather and the write composition; packed
    layouts only serve their own math mode — the engine migrates a packed
    table to full before dispatching off-family traffic, so this guard
    firing means a caller skipped the engine layer.

    `probe="pallas"` routes the WHOLE decide path — bucket-row fetch,
    layout unpack, claim, algorithm math and dirty-row write-back — through
    the fused double-buffered Pallas megakernel (ops/pallas_probe.py,
    GUBER_PROBE_KERNEL) instead of the XLA gather + separate sweep/sparse
    write; `write` is then moot (the megakernel writes its own dirty rows).

    `evictees=True` (static — compiled only when a shadow tier is attached,
    gubernator_tpu/tier/) additionally returns the EVICTEE SIDECAR: a
    (B, 16) int32 array of the canonical full-width rows the claim
    displaced (`evict_live` rows' pre-dispatch lane state, zero rows
    elsewhere) — the state today's eviction silently discards, captured so
    the engine can demote it to the host-RAM shadow instead. The return
    grows a 4th element; `evictees=False` keeps the historic 3-tuple and
    a bit-identical trace.
    """
    layout = table.layout
    if not layout.supports_math(math):
        raise ValueError(
            f"table layout {layout.name!r} cannot serve math={math!r}; "
            "migrate the table to the full layout first (engine does this "
            "automatically)"
        )
    if probe not in ("xla", "pallas"):
        raise ValueError(
            f"unknown probe kernel {probe!r}; expected 'xla' or 'pallas'"
        )
    if probe == "pallas":
        from gubernator_tpu.ops.pallas_probe import decide2_pallas_impl

        return decide2_pallas_impl(table, req, math=math, evictees=evictees)
    B = req.fp.shape[0]
    NB = table.rows.shape[0]
    write = resolve_write(write, NB, B, layout)
    if write == "sparse":
        blk, u, gsteps = sparse_geometry(NB, B)
    else:
        blk, u = sweep_geometry(NB, B)
    now = req.created_at
    active = req.active

    c = _probe_claim2(table.rows, req.fp, now, active, blk, u, layout)

    # ---- apply: chosen lane's stored state (shared decide stage)
    lane16 = jnp.take_along_axis(c.slots, c.chosen[:, None, None], axis=1)[
        :, 0, :
    ]  # (B, F)
    exists, d, new16 = decide_payload(lane16, req, c.owns, math=math)

    if write == "sweep":
        rows_out = _write_sweep(table.rows, new16, c, blk, u, layout)
    elif write == "sparse":
        rows_out = _write_sparse(table.rows, new16, c, blk, u, gsteps, layout)
    else:
        rows_out = _write_xla(table.rows, new16, c, layout)

    resp, stats = assemble_resp(req, d, exists, c.written, c.evict_live)
    if evictees:
        ev16 = jnp.where(c.evict_live[:, None], lane16, 0).astype(i32)
        return Table2(rows=rows_out, layout=layout), resp, stats, ev16
    return Table2(rows=rows_out, layout=layout), resp, stats


decide2 = functools.partial(
    jax.jit, donate_argnums=(0,),
    static_argnames=("write", "math", "probe", "evictees"),
)(decide2_impl)


def pack_outputs(
    resp: RespBatch, stats: BatchStats, behavior=None
) -> jnp.ndarray:
    """Pack responses + stats into ONE (B+2, 4) i64 array.

    The serving engine reads kernel results with a single device→host
    transfer: each fetched array costs a full round trip on the tunneled axon
    platform (~100 ms), and even on a co-located TPU host one DMA beats six.
    Layout: row i < B = [limit, remaining, reset_time, flags] with
    flags = status | cache_hit<<1 | dropped<<2; row B = [cache_hits,
    cache_misses, over_limit, evicted_unexpired]; row B+1 = [dropped, 0, 0, 0].

    `behavior` (the request batch's behavior words, optional) echoes each
    row's priority tier (types.PRIORITY_SHIFT) into flags bits 5-6
    (FLAG_TIER_SHIFT) — the decision's QoS tier rides the same fetched
    array, so the batcher and the metrics plane read it without a
    host-side side table.
    """
    flags = (
        resp.status.astype(i64)
        | (resp.cache_hit.astype(i64) << 1)
        | (resp.dropped.astype(i64) << 2)
    )
    if behavior is not None:
        tier = (jnp.asarray(behavior).astype(i64) >> _BEH_PRIO_SHIFT) & 3
        flags = flags | (tier << FLAG_TIER_SHIFT)
    rows = jnp.stack([resp.limit, resp.remaining, resp.reset_time, flags], axis=1)
    z = jnp.zeros((), dtype=i64)
    srow0 = jnp.stack(
        [stats.cache_hits, stats.cache_misses, stats.over_limit,
         stats.evicted_unexpired]
    )[None, :]
    srow1 = jnp.stack([stats.dropped, z, z, z])[None, :]
    return jnp.concatenate([rows, srow0, srow1], axis=0)


# ------------------------------------------------------- evictee sidecar
#
# Hot-set tiering (gubernator_tpu/tier/, docs/tiering.md): when a shadow
# table is attached the decide dispatch also returns the canonical rows it
# evicted, riding the SAME fetched array as the responses and stats. The
# sidecar rows are inserted BETWEEN the response rows and the two stats
# rows, so every existing decoder (`arr[:n]` responses, `arr[-2]` stats)
# keeps working unchanged; only unpack_evictees knows the middle exists.
#   int64 packed outputs: each (16,) i32 row rides as 8 int64 lanes
#     ((hi<<32)|lo over adjacent field pairs) → 2 extra rows of 4 per
#     request → (3B+2, 4).
#   int32 compact-wire outputs: raw fields, 4 extra rows of 4 per request
#     → (5B+2, 4) (slot fields must NOT ride the clamped response
#     narrowing — they are raw bit patterns).


def attach_evictees(packed: jnp.ndarray, ev16: jnp.ndarray) -> jnp.ndarray:
    """Insert a (B, 16) i32 evictee sidecar into a full-width (B+2, 4)
    int64 pack_outputs array → (3B+2, 4)."""
    B = ev16.shape[0]
    ev64 = _join64(ev16[:, 0::2], ev16[:, 1::2]).reshape(2 * B, 4)
    return jnp.concatenate([packed[:B], ev64, packed[B:]], axis=0)


def attach_evictees_wire(enc: jnp.ndarray, ev16: jnp.ndarray) -> jnp.ndarray:
    """Insert a (B, 16) i32 evictee sidecar into a compact (B+2, 4) int32
    egress array → (5B+2, 4) (raw fields, dtype already int32)."""
    B = ev16.shape[0]
    return jnp.concatenate(
        [enc[:B], ev16.reshape(4 * B, 4), enc[B:]], axis=0
    )


def unpack_evictees(arr: np.ndarray):
    """Host-side sidecar decode: fetched output array (either wire format,
    evictees attached) → (fps (E,) i64, rows (E, 16) i32 canonical
    full-width) for the E nonzero-fingerprint evictee rows. The caller
    must KNOW the dispatch ran with evictees=True — a sidecar-less array
    is not self-distinguishing (a (3B+2)-row sidecar array and a plain
    (B'+2)-row array can share a shape)."""
    arr = np.asarray(arr)
    if arr.dtype == np.int32:
        B = (arr.shape[0] - 2) // 5
        ev = np.ascontiguousarray(arr[B:5 * B]).reshape(B, 16)
    else:
        B = (arr.shape[0] - 2) // 3
        ev64 = np.ascontiguousarray(arr[B:3 * B]).reshape(B, 8)
        lo_u = ev64 & 0xFFFFFFFF
        lo = np.where(lo_u >= (1 << 31), lo_u - (1 << 32), lo_u).astype(
            np.int32
        )
        hi = (ev64 >> 32).astype(np.int32)
        ev = np.empty((B, 16), dtype=np.int32)
        ev[:, 0::2] = lo
        ev[:, 1::2] = hi
    lo_f = ev[:, 0].astype(np.int64) & 0xFFFFFFFF
    hi_f = ev[:, 1].astype(np.int64)
    fps = (hi_f << 32) | lo_f
    keep = fps != 0
    return fps[keep], ev[keep]


# flag bits of pack_outputs' 4th column — the single source of truth for
# every host-side decoder (engine unpack, sharded un-route)
FLAG_STATUS = 1
FLAG_HIT = 2
FLAG_DROPPED = 4
# set ALONGSIDE FLAG_DROPPED for rows that never reached the kernel at all
# (a2a exchange-capacity overflow, parallel/a2a.py): such rows appear in no
# kernel stats row, so the engine counts their hit/miss/over outcome at the
# retry that finally processes them
FLAG_UNPROCESSED = 8
# set on rows whose response was fanned out from a same-key aggregation
# carrier by the in-trace dedup (dedup_packed_cols): such rows were merged
# INTO the carrier before the kernel ran, so host-side hit/miss/over
# accounting must skip them — exactly like the host planner's member rows,
# which serve_columns answers from the aggregate without counting
FLAG_MEMBER = 16
# bits 5-6: the row's priority tier (types.PRIORITY_SHIFT field of the
# request behavior word), echoed by pack_outputs so overload accounting
# reads the tier straight off the fetched array
FLAG_TIER_SHIFT = 5
FLAG_TIER_MASK = 0x3
# behavior-word priority field position (types.PRIORITY_SHIFT)
_BEH_PRIO_SHIFT = 6


def unpack_tiers(arr: np.ndarray, n: int) -> np.ndarray:
    """Per-row priority tiers from a fetched pack_outputs array (either
    wire format — the flags column layout is shared)."""
    return (
        (np.asarray(arr[:n, 3]).astype(np.int64) >> FLAG_TIER_SHIFT)
        & FLAG_TIER_MASK
    ).astype(np.int32)


def unpack_outputs(arr, n: int):
    """Decode a fetched pack_outputs array (host-side): (B+2, 4) i64 →
    ((status, limit, remaining, reset_time, dropped, hit), (cache_hits,
    cache_misses, over_limit, evicted_unexpired)). Response arrays are
    writable copies (retry fix-ups mutate them in place). Compact-wire
    outputs (int32, base-relative reset — ops/wire.py) are self-describing
    by dtype and decode through the wire module's twin."""
    if arr.dtype == np.int32:
        from gubernator_tpu.ops.wire import unpack_wire_out

        return unpack_wire_out(arr, n)
    st = (int(arr[-2, 0]), int(arr[-2, 1]), int(arr[-2, 2]), int(arr[-2, 3]))
    limit = arr[:n, 0].copy()
    remaining = arr[:n, 1].copy()
    reset = arr[:n, 2].copy()
    status = (arr[:n, 3] & FLAG_STATUS).astype(np.int32)
    hit = (arr[:n, 3] & FLAG_HIT) != 0
    dropped = (arr[:n, 3] & FLAG_DROPPED) != 0
    return (status, limit, remaining, reset, dropped, hit), st


def decide2_packed_impl(
    table: Table2, req: ReqBatch, *, write: str = "sweep", math: str = "mixed",
    probe: str = "xla", evictees: bool = False,
):
    """(table', packed (B+2, 4) i64[, evictee sidecar (B, 16) i32]) — the
    sidecar element exists only under evictees=True (see decide2_impl)."""
    if evictees:
        table, resp, stats, ev16 = decide2_impl(
            table, req, write=write, math=math, probe=probe, evictees=True
        )
        return table, pack_outputs(resp, stats, req.behavior), ev16
    table, resp, stats = decide2_impl(
        table, req, write=write, math=math, probe=probe
    )
    return table, pack_outputs(resp, stats, req.behavior)


def req_from_arr(arr: jnp.ndarray) -> ReqBatch:
    """Rebuild the ReqBatch from the single packed (12, B) int64 ingress
    array (batch.pack_host_batch) — traced inside the kernel jit so the
    casts fuse with the kernel instead of costing separate transfers."""
    return ReqBatch(
        fp=arr[0],
        algo=arr[1].astype(i32),
        behavior=arr[2].astype(i32),
        hits=arr[3],
        limit=arr[4],
        burst=arr[5],
        duration=arr[6],
        created_at=arr[7],
        expire_new=arr[8],
        greg_interval=arr[9],
        duration_eff=arr[10],
        active=arr[11] != 0,
    )


def decide2_packed_cols_impl(
    table: Table2, arr: jnp.ndarray, *, write: str = "sweep",
    math: str = "mixed", cascade: bool = False, probe: str = "xla",
    evictees: bool = False,
) -> Tuple[Table2, jnp.ndarray]:
    """Single-transfer serving entry: packed ingress array in, packed
    output array out — one host→device put and one device→host fetch per
    dispatch regardless of column count. `cascade=True` folds cascade
    groups' combined verdicts into their carrier rows in-trace (set by the
    engine for order-preserving single-device dispatches whose batch
    carries level bits — see fold_cascade_packed). `probe` selects the
    table-walk kernel (GUBER_PROBE_KERNEL): the XLA gather + sweep write,
    or the fused Pallas megakernel (ops/pallas_probe.py). `evictees=True`
    rides the evictee sidecar home in the same fetched array
    (attach_evictees; decoded host-side by unpack_evictees)."""
    if evictees:
        table, packed, ev16 = decide2_packed_impl(
            table, req_from_arr(arr), write=write, math=math, probe=probe,
            evictees=True,
        )
        if cascade:
            packed = fold_cascade_packed(packed, arr)
        return table, attach_evictees(packed, ev16)
    table, packed = decide2_packed_impl(
        table, req_from_arr(arr), write=write, math=math, probe=probe
    )
    if cascade:
        packed = fold_cascade_packed(packed, arr)
    return table, packed


decide2_packed_cols = functools.partial(
    jax.jit, donate_argnums=(0,),
    static_argnames=("write", "math", "cascade", "probe", "evictees"),
)(decide2_packed_cols_impl)


# --------------------------------------------------------- in-trace dedup
#
# The kernel's unique-fingerprint contract used to be discharged on the HOST:
# plan_passes runs an O(n log n) numpy group-by over every batch before any
# dispatch (ops/plan.py). On the mesh serving path that group-by sits on a
# single Python process's critical path while D devices idle — the staging
# bottleneck BENCH_r05 measured at 230× the device time. These helpers move
# the duplicate-key aggregation INTO the traced program (sort + segment-sum,
# the same vector recipe the GLOBAL collective already uses for cross-device
# hit merging, parallel/global_sync._sync_core), so the host ships raw
# arrival-order batches with zero planning work.
#
# Semantics: ALL duplicates aggregate — hits summed, RESET_REMAINING OR-ed,
# newest request's config wins, and every member row is answered with the
# aggregate's response (flagged FLAG_MEMBER). That is plan_passes'
# aggregated-tail rule applied from occurrence 0, i.e. the reference's own
# hot-key aggregation on the GLOBAL async path (global.go:109-123). The host
# planner's exact per-occurrence sequential passes remain available as the
# fallback and test oracle (ShardedEngine dedup="host" ≍ plan_passes;
# dedup="device" ≍ plan_passes(max_exact=1)).

RESET_REMAINING_BIT = 8  # Behavior.RESET_REMAINING (shared with ops/plan.py)
# cascade level field of the behavior word (types.CASCADE_LEVEL_SHIFT): the
# discriminator that keeps two LEVELS of one cascade from aggregating even
# when their keys collide on a fingerprint — dedup groups on (fp, level),
# and same-(fp, level) rows across different cascades still aggregate
# (tenant/global levels of many users' cascades collapse to one kernel row)
CASCADE_LEVEL_SHIFT = 8


def dedup_packed_cols(arr: jnp.ndarray):
    """Aggregate duplicate (fingerprint, cascade-level) groups of a packed
    (12, n) ingress array in-trace. Returns (deduped arr, carrier, member):

    * deduped arr — same shape/order; each group's CARRIER row (its newest
      member, plan_passes' config rule) stays active carrying the summed
      hits and OR-ed RESET_REMAINING bit; all other duplicates are
      deactivated (fp→0) so the kernel sees unique fingerprints;
    * carrier — (n,) i32, each row's carrier index (itself when unique);
    * member — (n,) bool, active rows whose response must be fanned out
      from their carrier (fanout_packed).

    Keying on (fp, level) instead of fp alone is what keeps the cascade
    machinery sound under key collisions: a user-level key that collides
    with a tenant-level key of the SAME cascade stays two kernel rows (they
    then conflict in the claim and the loser retries — sequential
    semantics), instead of silently merging two different limit configs.
    """
    fp = arr[0]
    active = arr[11] != 0
    n = fp.shape[0]
    idx = jnp.arange(n, dtype=i32)
    # inactive rows key to 0 (below every real fp, hashing.py keeps fps ≥ 1):
    # they sort into one leading segment that no active row can join
    key = jnp.where(active, fp, i64(0))
    lvl = jnp.where(
        active, (arr[2] >> CASCADE_LEVEL_SHIFT) & 0xFF, i64(0)
    ).astype(i32)
    key_s, lvl_s, idx_s = jax.lax.sort((key, lvl, idx), num_keys=2)
    first = jnp.concatenate(
        [
            jnp.ones((1,), dtype=bool),
            (key_s[1:] != key_s[:-1]) | (lvl_s[1:] != lvl_s[:-1]),
        ]
    )
    seg = jnp.cumsum(first.astype(i32)) - 1
    act_s = active[idx_s]
    hits_s = jnp.where(act_s, arr[3][idx_s], i64(0))
    seg_hits = jax.ops.segment_sum(hits_s, seg, num_segments=n)
    reset_s = jnp.where(
        act_s, arr[2][idx_s] & i64(RESET_REMAINING_BIT), i64(0)
    )
    seg_reset = jax.ops.segment_max(reset_s, seg, num_segments=n)
    # carrier = newest member = max original index (plan.py: "newest member
    # of each group carries the config")
    seg_carrier = jax.ops.segment_max(
        jnp.where(act_s, idx_s, i32(-1)), seg, num_segments=n
    )
    # un-sort each row's segment id back to original order
    _, seg_u = jax.lax.sort((idx_s, seg), num_keys=1)
    carrier = jnp.clip(seg_carrier[seg_u], 0, n - 1).astype(i32)
    is_carrier = active & (carrier == idx)
    member = active & ~is_carrier
    ded = jnp.concatenate(
        [
            jnp.where(is_carrier, fp, i64(0))[None],
            arr[1:2],
            (arr[2] | seg_reset[seg_u])[None],
            jnp.where(is_carrier, seg_hits[seg_u], i64(0))[None],
            arr[4:11],
            is_carrier.astype(i64)[None],
        ],
        axis=0,
    )
    return ded, carrier, member


def fanout_packed(
    packed: jnp.ndarray, carrier: jnp.ndarray, member: jnp.ndarray, n: int
) -> jnp.ndarray:
    """Fan each member row's response out from its aggregation carrier in
    the packed (n+2, 4) output array, marking it FLAG_MEMBER so host-side
    accounting skips it (the carrier already represents the whole group in
    the kernel's stats rows)."""
    rows = packed[:n]
    fan = rows[carrier]
    fan = fan.at[:, 3].set(fan[:, 3] | i64(FLAG_MEMBER))
    rows = jnp.where(member[:, None], fan, rows)
    return jnp.concatenate([rows, packed[n:]], axis=0)


# ------------------------------------------------------- cascade fold
#
# A CASCADE request expands into one row per limit level at the front door
# (level 0 = the carrier, levels ≥ 1 = member rows immediately following it
# — types.CASCADE_LEVEL_SHIFT). Every level is evaluated independently by
# the kernel in the SAME launch; the fold below then computes the combined
# verdict in-trace: the carrier row's status becomes OVER if ANY level
# denied, its remaining the minimum across levels, and its reset the
# latest reset among denying levels — while member rows keep their own
# per-level response (the "per-level remaining/reset" the response
# surfaces). This is the dedup/FLAG_MEMBER carrier machinery run in the
# opposite direction: members fold INTO their carrier's verdict instead of
# reading from it.
#
# The fold requires rows in ORIGINAL batch order (carrier adjacency), so it
# runs only in order-preserving traces — the single-device entries below
# with cascade=True, staged by the engine when the batch carries level bits.
# Mesh programs (routed/exchanged row order) skip it; the engine's shared
# response assembly applies the same fold host-side there
# (ops/engine._fold_cascades_host), and that host fold is idempotent over
# an already-folded carrier, so the two layers compose.


def cascade_groups(arr: jnp.ndarray):
    """(carrier, member) from a packed ingress array's behavior level bits.
    member rows are level > 0 regardless of activity (an errored member
    must not break its group's adjacency chain); carrier[i] is the nearest
    preceding level-0 row (itself for carriers/standalone rows)."""
    level = (arr[2] >> CASCADE_LEVEL_SHIFT) & 0xFF
    n = arr.shape[1]
    idx = jnp.arange(n, dtype=i32)
    member = level > 0
    carrier = _cummax(jnp.where(~member, idx, i32(-1)))
    # a leading orphan member (malformed input) folds onto itself
    carrier = jnp.where(carrier < 0, idx, carrier).astype(i32)
    return carrier, member


def fold_cascade_packed(packed: jnp.ndarray, arr: jnp.ndarray) -> jnp.ndarray:
    """Fold each cascade group's per-level verdicts into its carrier row of
    the packed (n+2, 4) output array: status OR (deny-if-any), remaining
    min, reset = latest reset among denying levels (the retry-after bound)
    when any level denies. Inactive rows (validation errors) are excluded
    from the reductions; member rows are untouched."""
    n = arr.shape[1]
    carrier, member = cascade_groups(arr)
    active = arr[11] != 0
    rows = packed[:n]
    flags = rows[:, 3]
    status = jnp.where(active, flags & i64(FLAG_STATUS), i64(0))
    over = jax.ops.segment_max(status, carrier, num_segments=n)
    big = jnp.int64(2**62)
    rem = jnp.where(active, rows[:, 1], big)
    rem_min = jax.ops.segment_min(rem, carrier, num_segments=n)
    deny_reset = jnp.where(active & (status != 0), rows[:, 2], i64(0))
    reset_max = jax.ops.segment_max(deny_reset, carrier, num_segments=n)
    is_carrier = ~member & active
    new_flags = jnp.where(is_carrier, flags | over, flags)
    new_rem = jnp.where(
        is_carrier & (rem_min < big), jnp.minimum(rows[:, 1], rem_min), rows[:, 1]
    )
    new_reset = jnp.where(
        is_carrier & (over != 0), jnp.maximum(rows[:, 2], reset_max), rows[:, 2]
    )
    rows = jnp.stack([rows[:, 0], new_rem, new_reset, new_flags], axis=1)
    return jnp.concatenate([rows, packed[n:]], axis=0)


def decide2_packed_dedup_impl(
    table: Table2, arr: jnp.ndarray, *, write: str = "sweep",
    math: str = "mixed", cascade: bool = False, probe: str = "xla",
) -> Tuple[Table2, jnp.ndarray]:
    """Single-transfer serving entry with IN-TRACE duplicate aggregation:
    raw (possibly duplicate-keyed) packed ingress in, packed outputs out
    with member rows answered from their aggregation carrier. The mesh
    engines build their per-device programs on this when dedup="device"
    (parallel/sharded.py, parallel/a2a.py), which lets the host skip
    plan_passes entirely (ops/plan.single_pass). `cascade=True`
    additionally folds cascade groups' verdicts into their carriers
    (order-preserving traces only — see fold_cascade_packed)."""
    ded, carrier, member = dedup_packed_cols(arr)
    table, packed = decide2_packed_cols_impl(
        table, ded, write=write, math=math, probe=probe
    )
    packed = fanout_packed(packed, carrier, member, arr.shape[1])
    if cascade:
        packed = fold_cascade_packed(packed, arr)
    return table, packed


# -------------------------------------------------------------------- install


def install_payload16(inst) -> jnp.ndarray:
    """The per-row INSTALL payload stage: InstallBatch columns → canonical
    (B, 16) i32 slot rows. A pure function of the incoming batch — it never
    reads table state — shared VERBATIM by the two-pass XLA path
    (install2_impl below) and the fused Pallas walk
    (ops/pallas_probe.walk2_pallas_impl, which precomputes these rows in
    its prologue and DMAs them through the megakernel). Factoring it out is
    what makes the two install paths bit-identical by construction, the
    same contract decide_payload discharges for the probe kernels."""
    from gubernator_tpu.types import Algorithm

    B = inst.fp.shape[0]
    is_token = inst.algo == int(Algorithm.TOKEN_BUCKET)
    is_leaky = inst.algo == int(Algorithm.LEAKY_BUCKET)
    is_gcra = inst.algo == int(Algorithm.GCRA)
    is_win = inst.algo == int(Algorithm.SLIDING_WINDOW)
    # full-fidelity window state when the broadcast carries it (the
    # PR-11 GLOBAL fidelity fix): `aux` = previous-window count,
    # `rem_store` = the stored-style remaining (limit - current count).
    # Legacy broadcasts (None) degrade to the CONSERVATIVE weighted
    # rebuild below.
    has_aux = inst.aux is not None
    inst_aux = inst.aux if has_aux else jnp.zeros_like(inst.remaining)
    inst_rem = inst.rem_store if inst.rem_store is not None else inst.remaining
    # REM_I is remaining-style for every integer algorithm (ops/math.py
    # storage convention), so the wire rebuild installs `remaining`
    # verbatim for token and lease rows; sliding windows take the
    # stored-style remaining when the wire carries it (else the weighted
    # client remaining — conservative: interpolated usage counts as
    # current); only leaky keeps its float lane and GCRA its TAT.
    rem_i = jnp.where(
        is_leaky | is_gcra, i64(0), jnp.where(is_win, inst_rem, inst.remaining)
    )
    rem_f = jnp.where(is_leaky, inst.remaining.astype(f64), f64(0.0))
    # GCRA: with the wire rebuild's burst == limit, reset_time IS the
    # authoritative TAT (tau = limit·T ⇒ reset = tat, ops/math.py) — the
    # owner's verdict rebuilds exactly. Sliding window: the previous-window
    # count rides the broadcast aux when present (replicas then interpolate
    # the same `used` as the owner); absent, 0 — the legacy permissive
    # rebuild, tightened by the next owner broadcast.
    aux = jnp.where(is_gcra, inst.reset_time, jnp.where(is_win, inst_aux, i64(0)))
    burst = jnp.where(is_token | is_win, i64(0), inst.burst)
    # expiry: token items expire at their authoritative reset (ExpireAt =
    # CreatedAt + Duration = reset, store.go:29-35); leaky items at
    # stamp + duration (UpdatedAt basis, cache.go:35-40) — NOT reset_time,
    # whose leaky meaning (createdAt + (limit-rem)*rate) can lie in the past
    # for a near-full bucket and would expire the install on arrival. GCRA
    # state self-expires at its TAT (= reset); window/lease keep the
    # stamp + duration rule (lease reset_time == expiry by construction).
    # Sliding windows store the WINDOW START as their stamp (the
    # interpolation key, ops/math.py w_same) and expire one full window
    # past the current one — matching the owner's own writeback.
    w_dur = jnp.maximum(inst.duration, i64(1))
    w_ws = inst.now - inst.now % w_dur
    exp = jnp.where(
        is_token | is_gcra,
        inst.reset_time,
        jnp.where(is_win, w_ws + 2 * w_dur, inst.stamp + inst.duration),
    )
    flags = inst.algo | (inst.status << 8)
    sat32 = lambda x: jnp.clip(x, -(2**31), 2**31 - 1).astype(i32)
    remf_hi_f = rem_f.astype(f32)
    remf_lo_f = (rem_f - remf_hi_f.astype(f64)).astype(f32)
    remf_hi = jnp.where(
        is_leaky, jax.lax.bitcast_convert_type(remf_hi_f, i32), _hi32(aux)
    )
    remf_lo = jnp.where(
        is_leaky, jax.lax.bitcast_convert_type(remf_lo_f, i32), _lo32(aux)
    )
    stamp_eff = jnp.where(is_win, w_ws, inst.stamp)
    zero = jnp.zeros((B,), dtype=i32)
    new16 = jnp.stack(
        [
            _lo32(inst.fp),
            _hi32(inst.fp),
            sat32(inst.limit),
            sat32(burst),
            sat32(rem_i),
            flags,
            _lo32(inst.duration),
            _hi32(inst.duration),
            _lo32(stamp_eff),
            _hi32(stamp_eff),
            _lo32(exp),
            _hi32(exp),
            remf_hi,
            remf_lo,
            zero,
            zero,
        ],
        axis=1,
    )
    return new16


def install2_impl(
    table: Table2, inst, *, write: str = "xla", probe: str = "xla"
) -> Tuple[Table2, jnp.ndarray]:
    """v2 analog of kernel.install_impl — install owner-authoritative GLOBAL
    statuses as fresh items (reference UpdatePeerGlobals, gubernator.go:434-474).
    Returns (table', installed_mask).

    `probe` (static) selects the table walk, mirroring decide2_impl:
    "xla" = the two-pass gather + sweep/sparse write below, "pallas" = the
    fused probe→install→write megakernel (ops/pallas_probe), which
    consumes the same install_payload16 rows and skips the `write` plan
    entirely (one coalesced DMA per distinct bucket per block)."""
    if probe == "pallas":
        from gubernator_tpu.ops.pallas_probe import walk2_pallas_impl

        return walk2_pallas_impl(
            table, inst.fp, install_payload16(inst), inst.now, inst.active,
            stage="install",
        )

    layout = table.layout
    B = inst.fp.shape[0]
    NB = table.rows.shape[0]
    write = resolve_write(write, NB, B, layout)
    if write == "sparse":
        blk, u, g = sparse_geometry(NB, B)
    else:
        blk, u = sweep_geometry(NB, B)
    c = _probe_claim2(table.rows, inst.fp, inst.now, inst.active, blk, u,
                      layout)
    new16 = install_payload16(inst)
    if write == "sweep":
        rows_out = _write_sweep(table.rows, new16, c, blk, u, layout)
    elif write == "sparse":
        rows_out = _write_sparse(table.rows, new16, c, blk, u, g, layout)
    else:
        rows_out = _write_xla(table.rows, new16, c, layout)
    return Table2(rows=rows_out, layout=layout), inst.active & c.written


install2 = functools.partial(
    jax.jit, donate_argnums=(0,), static_argnames=("write", "probe")
)(install2_impl)


# ------------------------------------------------------- conservative merge


def merge_payload16(fp, slots, lane16, owns, now):
    """The per-row MERGE payload stage: (incoming canonical slot, chosen
    stored lane, ownership mask, receiver clock) → (exists_mask, merged
    (B, 16) i32 slot rows). Implements every conservatism rule documented
    on merge2_impl — remaining=min, raw aux=max, expiry=max, OVER sticks,
    newest-stamp config — and is shared VERBATIM by the two-pass XLA path
    and the fused Pallas walk (ops/pallas_probe.walk2_pallas_impl calls it
    in-kernel against the VMEM-resident lane). Factoring it out is what
    makes the two merge paths bit-identical by construction."""
    g_i = lambda f: slots[:, f]
    g_s = lambda f: lane16[:, f]
    i_exp = _join64(g_i(EXP_LO), g_i(EXP_HI))
    s_exp = _join64(g_s(EXP_LO), g_s(EXP_HI))
    exists = owns & (s_exp >= now)

    i_stamp = _join64(g_i(STAMP_LO), g_i(STAMP_HI))
    s_stamp = _join64(g_s(STAMP_LO), g_s(STAMP_HI))
    i_flags = g_i(FLAGS)
    s_flags = g_s(FLAGS)
    # config carrier: the newer stamp's limit/burst/duration/algo
    keep_stored = exists & (s_stamp > i_stamp)
    pick32 = lambda i_f, s_f: jnp.where(keep_stored, s_f, i_f)
    limit = pick32(g_i(LIMIT), g_s(LIMIT))
    burst = pick32(g_i(BURST), g_s(BURST))
    algo = pick32(i_flags & 0xFF, s_flags & 0xFF)
    dur = jnp.where(
        keep_stored,
        _join64(g_s(DUR_LO), g_s(DUR_HI)),
        _join64(g_i(DUR_LO), g_i(DUR_HI)),
    )
    status = jnp.where(
        exists, jnp.maximum(i_flags >> 8, s_flags >> 8), i_flags >> 8
    )
    # REM_I is remaining-style for EVERY integer algorithm (ops/math.py
    # storage convention: token remaining, limit-current for sliding
    # windows, limit-inflight for leases), so min is uniformly the
    # tightening direction here
    rem_i = jnp.where(exists, jnp.minimum(g_i(REM_I), g_s(REM_I)), g_i(REM_I))
    to_f64 = lambda g: (
        jax.lax.bitcast_convert_type(g(REMF_HI), f32).astype(f64)
        + jax.lax.bitcast_convert_type(g(REMF_LO), f32).astype(f64)
    )
    rem_f = jnp.where(exists, jnp.minimum(to_f64(g_i), to_f64(g_s)), to_f64(g_i))
    # the raw-int REMF pair (GCRA TAT / sliding-window previous count): the
    # tightening direction is MAX — a later theoretical arrival time or a
    # larger previous-window count can only deny more. Replaying a STALE
    # checkpoint frame (smaller TAT) therefore under-grants, never over.
    # When the two sides disagree on the algorithm the config winner's raw
    # value is kept verbatim (cross-algorithm arithmetic is meaningless);
    # the float lane keeps its historical unconditional min, which for a
    # same-algo leaky pair is the conservative direction and for an algo
    # flip is "legitimately tighter than serving" (docs/durability.md).
    to_aux = lambda g: _join64(g(REMF_LO), g(REMF_HI))
    s_aux, i_aux = to_aux(g_s), to_aux(g_i)
    same_algo = exists & ((s_flags & 0xFF) == (i_flags & 0xFF))
    aux = jnp.where(
        same_algo,
        jnp.maximum(s_aux, i_aux),
        jnp.where(keep_stored, s_aux, i_aux),
    )
    exp = jnp.where(exists, jnp.maximum(s_exp, i_exp), i_exp)
    stamp = jnp.where(exists, jnp.maximum(s_stamp, i_stamp), i_stamp)

    remf_hi_f = rem_f.astype(f32)
    remf_lo_f = (rem_f - remf_hi_f.astype(f64)).astype(f32)
    from gubernator_tpu.types import Algorithm as _Algo

    aux_algo = (algo == int(_Algo.GCRA)) | (algo == int(_Algo.SLIDING_WINDOW))
    remf_hi = jnp.where(
        aux_algo, _hi32(aux), jax.lax.bitcast_convert_type(remf_hi_f, i32)
    )
    remf_lo = jnp.where(
        aux_algo, _lo32(aux), jax.lax.bitcast_convert_type(remf_lo_f, i32)
    )
    zero = jnp.zeros(fp.shape, dtype=i32)
    new16 = jnp.stack(
        [
            _lo32(fp),
            _hi32(fp),
            limit,
            burst,
            rem_i,
            algo | (status << 8),
            _lo32(dur),
            _hi32(dur),
            _lo32(stamp),
            _hi32(stamp),
            _lo32(exp),
            _hi32(exp),
            remf_hi,
            remf_lo,
            zero,
            zero,
        ],
        axis=1,
    )
    return exists, new16


def merge2_impl(
    table: Table2, fp, slots, now, active, *, write: str = "xla",
    evictees: bool = False, probe: str = "xla",
):
    """Conservative merge of transferred table slots (the TransferState
    receive path, docs/robustness.md "Topology change & drain").

    Incoming rows arrive in the CANONICAL full-width slot layout ((B, 16)
    i32): extract wires carry the sender's own layout, and the receiving
    host unpacks them through ops/layout before this kernel — the one
    full-width round-trip that keeps the conservatism rules below
    layout-independent. Against an existing live entry the merge can only
    ever TIGHTEN admission — the invariant that makes a retried,
    duplicated, or crossed transfer unable to grant extra capacity:

      * remaining  = min(stored, incoming)   (integer and leaky-float lanes;
        REM_I is remaining-style for every integer algorithm, so min
        uniformly tightens)
      * raw aux lane (GCRA TAT / sliding-window prev count) = max — a later
        TAT or larger previous count can only deny more
      * expiry     = max(stored, incoming)   (state lives at least as long)
      * OVER_LIMIT sticks (status = max)
      * config (limit/burst/duration/algo) — newest stamp wins

    Absent keys install the incoming slot verbatim (claim/evict machinery
    shared with install2). Incoming rows already expired at the receiver's
    clock are dropped — stale state must not resurrect. Returns
    (table', merged_mask).

    `evictees=True` (static — the tiering promote path) additionally
    returns the (B, 16) i32 canonical rows of LIVE entries this merge's
    installs displaced, so a shadow fault-back that lands in a full
    bucket demotes the victim instead of silently destroying it — the
    invariant that makes HBM + shadow a closed state set.

    `probe` (static) selects the table walk, mirroring decide2_impl:
    "xla" = the two-pass gather + sweep/sparse write below, "pallas" = the
    fused probe→merge→write megakernel (ops/pallas_probe), which calls
    merge_payload16 in-kernel against the VMEM-resident lane and skips the
    `write` plan entirely."""
    g_i = lambda f: slots[:, f]
    i_exp = _join64(g_i(EXP_LO), g_i(EXP_HI))
    active = active & (i_exp >= now)

    if probe == "pallas":
        from gubernator_tpu.ops.pallas_probe import walk2_pallas_impl

        return walk2_pallas_impl(
            table, fp, slots, now, active, stage="merge", evictees=evictees,
        )

    layout = table.layout
    B = fp.shape[0]
    NB = table.rows.shape[0]
    write = resolve_write(write, NB, B, layout)
    if write == "sparse":
        blk, u, gsteps = sparse_geometry(NB, B)
    else:
        blk, u = sweep_geometry(NB, B)

    c = _probe_claim2(table.rows, fp, now, active, blk, u, layout)
    lane16 = jnp.take_along_axis(c.slots, c.chosen[:, None, None], axis=1)[
        :, 0, :
    ]
    exists, new16 = merge_payload16(fp, slots, lane16, c.owns, now)
    if write == "sweep":
        rows_out = _write_sweep(table.rows, new16, c, blk, u, layout)
    elif write == "sparse":
        rows_out = _write_sparse(table.rows, new16, c, blk, u, gsteps, layout)
    else:
        rows_out = _write_xla(table.rows, new16, c, layout)
    if evictees:
        ev16 = jnp.where(c.evict_live[:, None], lane16, 0).astype(i32)
        return Table2(rows=rows_out, layout=layout), active & c.written, ev16
    return Table2(rows=rows_out, layout=layout), active & c.written


merge2 = functools.partial(
    jax.jit, donate_argnums=(0,), static_argnames=("write", "evictees", "probe")
)(merge2_impl)
