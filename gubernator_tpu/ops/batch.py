"""Device-side batch layout + host-side packing.

A `ReqBatch` is the SoA form of a slice of RateLimitRequests after host-side
resolution: strings → fingerprints, Gregorian durations → absolute expiries and
interval lengths, leaky burst defaulting (burst==0 → limit, reference
algorithms.go:259-261). The kernel (ops/kernel2.py) requires all fingerprints
within one batch to be distinct — the pass planner (ops/plan.py) guarantees
that, reproducing the reference's per-key sequential semantics (the worker
hash-ring serializes same-key requests, reference workers.go:185-189).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from gubernator_tpu import gregorian
from gubernator_tpu.hashing import fingerprint
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest, has_behavior

# front-door bound on limit/burst: stored table fields are int32 carriers
# (ops/table.py docstring); larger values get a per-request error instead of
# silently saturating device state.
INT32_MAX = 2**31 - 1
# client-supplied created_at is accepted (reference gubernator.go:225-227 only
# stamps when unset) but clamped to ingress now ± tolerance: the reference
# checks item expiry against the *server* clock (lrucache.go GetItem), so an
# arbitrarily skewed client timestamp must not be able to renew or expire live
# buckets. Frozen-time tests pass an explicit now_ms and matching created_at,
# which never clamps.
CREATED_AT_TOLERANCE_MS = 5 * 60 * 1000
_created_at_tolerance_ms = CREATED_AT_TOLERANCE_MS


def _max_algorithm() -> int:
    from gubernator_tpu.types import MAX_ALGORITHM

    return MAX_ALGORITHM


def set_created_at_tolerance_ms(ms: int) -> None:
    """Configure the accepted client clock skew (GUBER_CREATED_AT_TOLERANCE).
    Replayed/queued traffic with legitimately old timestamps can raise it."""
    global _created_at_tolerance_ms
    if ms <= 0:
        raise ValueError("created_at tolerance must be positive")
    _created_at_tolerance_ms = int(ms)


def created_at_tolerance_ms() -> int:
    return _created_at_tolerance_ms


class ReqBatch(NamedTuple):
    """All arrays shape (B,). Fingerprints must be unique among active rows."""

    fp: jnp.ndarray  # int64 (63-bit fingerprint; 0 reserved)
    algo: jnp.ndarray  # int32
    behavior: jnp.ndarray  # int32 bitflags
    hits: jnp.ndarray  # int64
    limit: jnp.ndarray  # int64
    burst: jnp.ndarray  # int64 (resolved: 0 → limit)
    duration: jnp.ndarray  # int64 raw request duration (ms, or Gregorian enum)
    created_at: jnp.ndarray  # int64 epoch ms ("now" for this request)
    expire_new: jnp.ndarray  # int64 absolute expiry for new/renewed token items
    greg_interval: jnp.ndarray  # int64 full Gregorian interval ms (0 ⇒ not gregorian)
    duration_eff: jnp.ndarray  # int64 effective duration for leaky expiry updates
    active: jnp.ndarray  # bool padding mask

    @property
    def size(self) -> int:
        return self.fp.shape[0]


class RespBatch(NamedTuple):
    """Kernel outputs, shape (B,), in the same row order as the ReqBatch."""

    status: jnp.ndarray  # int32
    limit: jnp.ndarray  # int64
    remaining: jnp.ndarray  # int64
    reset_time: jnp.ndarray  # int64
    cache_hit: jnp.ndarray  # bool — row found a live matching slot
    dropped: jnp.ndarray  # bool — no slot could be claimed (decision not persisted)
    # stored-state echoes for full-fidelity GLOBAL broadcasts
    # (kernel2.decide2_impl → global_sync._sync_core): the raw aux lane
    # writeback (GCRA TAT / sliding-window previous count) and the
    # remaining-STYLE integer lane (limit - current for windows). None on
    # legacy constructors (the v1 oracle kernel); DCE'd by every serving
    # graph (pack_outputs reads neither).
    aux: jnp.ndarray = None  # int64 | None
    rem_store: jnp.ndarray = None  # int64 | None


class BatchStats(NamedTuple):
    """Per-dispatch scalar counters feeding the Prometheus layer
    (reference lrucache.go:48-59, gubernator.go:76-80)."""

    cache_hits: jnp.ndarray  # int64
    cache_misses: jnp.ndarray  # int64
    over_limit: jnp.ndarray  # int64 — rows answered OVER_LIMIT
    evicted_unexpired: jnp.ndarray  # int64 — live slots evicted for new keys
    dropped: jnp.ndarray  # int64 — rows that failed slot claiming


class InstallBatch(NamedTuple):
    """SoA of authoritative global statuses (one owner-broadcast entry per
    row): what UpdatePeerGlobalsReq.Globals carries (reference peers.proto:50-73)."""

    fp: jnp.ndarray  # int64
    algo: jnp.ndarray  # int32
    status: jnp.ndarray  # int32
    limit: jnp.ndarray  # int64
    remaining: jnp.ndarray  # int64
    reset_time: jnp.ndarray  # int64
    duration: jnp.ndarray  # int64
    now: jnp.ndarray  # int64 (B,)
    active: jnp.ndarray  # bool
    # full-fidelity state (Store rehydrate): the leaky burst and the item's
    # UpdatedAt/CreatedAt stamp. The UpdatePeerGlobals wire path has neither
    # (reference rebuilds with Burst=Limit, CreatedAt=now,
    # gubernator.go:434-474) — its callers pass burst=limit, stamp=now.
    burst: jnp.ndarray  # int64
    stamp: jnp.ndarray  # int64
    # sliding-window broadcast fidelity (PR 11): the previous-window count
    # (raw aux lane) and the stored-style remaining (limit - current
    # count). None on legacy wire paths — install2 then falls back to the
    # conservative weighted rebuild (docs/algorithms.md "Sliding window").
    aux: jnp.ndarray = None  # int64 | None
    rem_store: jnp.ndarray = None  # int64 | None


class HostBatch(NamedTuple):
    """numpy staging form, built by pack_requests, before device transfer."""

    fp: np.ndarray
    algo: np.ndarray
    behavior: np.ndarray
    hits: np.ndarray
    limit: np.ndarray
    burst: np.ndarray
    duration: np.ndarray
    created_at: np.ndarray
    expire_new: np.ndarray
    greg_interval: np.ndarray
    duration_eff: np.ndarray
    active: np.ndarray


# ---------------------------------------------------------------- columns path
#
# The serving hot path (service/ front door, bench e2e) avoids per-request
# Python objects entirely: requests arrive as parallel columns (numpy arrays +
# one fingerprint pass over the key strings) and resolution/validation is
# vectorized. The object API (pack_requests below) is a thin wrapper kept for
# tests and embedding use.

ERR_OK = 0
ERR_EMPTY_KEY = 1
ERR_EMPTY_NAME = 2
ERR_LIMIT_I32 = 3
ERR_BURST_I32 = 4
ERR_GREGORIAN = 5
ERR_DROPPED = 6
# forward-compat: an `algorithm` enum value this build doesn't speak (a
# NEWER peer's request in a mixed-version cluster) is a per-item error row,
# never a failed batch — the reference isolates invalid items the same way
# (gubernator.go:215-237) and its algorithm switch rejects unknown values
# with this wording
ERR_ALGORITHM = 7
# a cascade request carrying more levels than GUBER_CASCADE_MAX_LEVELS —
# the daemon parameterizes the message with the configured cap
# (service/wire.cascade_too_deep_error); this entry is the generic default
ERR_CASCADE_DEEP = 8
# shed by the overload plane before reaching the engine (service/batcher.py
# deadline/priority shedding — docs/robustness.md "Overload & QoS"): the
# answer rides a fast per-item OVER_LIMIT-style row whose reset_time is the
# suggested retry instant, never an RPC failure
ERR_OVERLOAD = 9

# wording parity with the reference where it has fixed strings
# (gubernator.go:215-224); ERR_DROPPED is this design's own failure mode
ERROR_STRINGS = {
    ERR_OK: "",
    ERR_EMPTY_KEY: "field 'unique_key' cannot be empty",
    ERR_EMPTY_NAME: "field 'namespace' cannot be empty",
    ERR_LIMIT_I32: "field 'limit' must fit int32",
    ERR_BURST_I32: "field 'burst' must fit int32",
    ERR_GREGORIAN: "invalid gregorian duration",
    ERR_DROPPED: "rate limit state could not be persisted (contended table); retry",
    ERR_ALGORITHM: "invalid rate limit algorithm",
    ERR_CASCADE_DEEP: "cascade levels list too large",
    ERR_OVERLOAD: "request shed under overload; retry after reset_time",
}


class RequestColumns(NamedTuple):
    """Column-oriented request batch (pre-fingerprinted). `created_at == 0`
    means unset (stamped with ingress now, reference gubernator.go:225-227);
    `err` carries fingerprint-stage validation codes."""

    fp: np.ndarray  # int64; 0 where err != 0
    algo: np.ndarray  # int32
    behavior: np.ndarray  # int32
    hits: np.ndarray  # int64
    limit: np.ndarray  # int64
    burst: np.ndarray  # int64 (raw; 0 → limit resolved for leaky in pack)
    duration: np.ndarray  # int64
    created_at: np.ndarray  # int64; 0 = unset
    err: np.ndarray  # int8 error codes (ERR_*)


def fingerprint_columns(names, keys) -> "tuple[np.ndarray, np.ndarray]":
    """Fingerprint parallel name/key string sequences; returns (fp, err).
    The per-item xxhash call is the one irreducible Python loop on the ingress
    path (native/ replaces it with a C pass when built)."""
    n = len(names)
    fp = np.zeros(n, dtype=np.int64)
    err = np.zeros(n, dtype=np.int8)
    for i in range(n):
        k = keys[i]
        nm = names[i]
        if k == "":
            err[i] = ERR_EMPTY_KEY
        elif nm == "":
            err[i] = ERR_EMPTY_NAME
        else:
            fp[i] = fingerprint(nm, k)
    return fp, err


def pack_columns(
    cols: RequestColumns, now_ms: int, tolerance_ms: Optional[int] = None
) -> "tuple[HostBatch, np.ndarray]":
    """Vectorized resolution of a RequestColumns batch into a HostBatch.
    Mirrors pack_requests() semantics exactly (validation, created_at
    clamping, leaky burst defaulting, Gregorian resolution); returns
    (batch, err_codes). `tolerance_ms` overrides the process-default clock
    skew bound (engines thread their own configured value)."""
    tol = _created_at_tolerance_ms if tolerance_ms is None else tolerance_ms
    n = cols.fp.shape[0]
    err = cols.err.copy()
    ok = err == ERR_OK
    bad_limit = ok & ((cols.limit > INT32_MAX) | (cols.limit < -INT32_MAX))
    err[bad_limit] = ERR_LIMIT_I32
    bad_burst = (err == ERR_OK) & (
        (cols.burst > INT32_MAX) | (cols.burst < -INT32_MAX)
    )
    err[bad_burst] = ERR_BURST_I32
    # forward-compat: unknown algorithm enum values (a newer peer's traffic)
    # become per-item "invalid rate limit algorithm" rows, never a failed
    # batch and never a silent fall-through into some other algorithm's math
    from gubernator_tpu.types import MAX_ALGORITHM

    bad_algo = (err == ERR_OK) & (
        (cols.algo < 0) | (cols.algo > MAX_ALGORITHM)
    )
    err[bad_algo] = ERR_ALGORITHM

    created = np.where(cols.created_at == 0, now_ms, cols.created_at)
    created = np.clip(created, now_ms - tol, now_ms + tol)
    # burst defaults to limit for the tolerance-shaped algorithms: leaky
    # (reference algorithms.go:259-261) and GCRA, whose delay-variation
    # tolerance tau = T·burst degenerates to "deny everything" at burst 0
    bursty = (cols.algo == int(Algorithm.LEAKY_BUCKET)) | (
        cols.algo == int(Algorithm.GCRA)
    )
    burst = np.where(bursty & (cols.burst == 0), cols.limit, cols.burst)

    expire_new = created + cols.duration
    greg_interval = np.zeros(n, dtype=np.int64)
    duration_eff = cols.duration.astype(np.int64).copy()
    greg_rows = (cols.behavior & int(Behavior.DURATION_IS_GREGORIAN)) != 0
    if greg_rows.any():
        # Gregorian durations are an enum (≤6 distinct values) and the whole
        # batch shares one `now` — resolve once per distinct enum value
        for val in np.unique(cols.duration[greg_rows]):
            rows = greg_rows & (cols.duration == val)
            try:
                expire = gregorian.gregorian_expiration(now_ms, int(val))
                interval = gregorian.gregorian_duration(now_ms, int(val))
            except gregorian.GregorianError:
                err[rows & (err == ERR_OK)] = ERR_GREGORIAN
                continue
            expire_new[rows] = expire
            greg_interval[rows] = interval
            duration_eff[rows] = expire - now_ms

    active = err == ERR_OK
    b = HostBatch(
        fp=np.where(active, cols.fp, 0),
        algo=cols.algo.astype(np.int32),
        behavior=cols.behavior.astype(np.int32),
        hits=cols.hits.astype(np.int64),
        limit=cols.limit.astype(np.int64),
        burst=burst.astype(np.int64),
        duration=cols.duration.astype(np.int64),
        created_at=created.astype(np.int64),
        expire_new=expire_new.astype(np.int64),
        greg_interval=greg_interval,
        duration_eff=duration_eff,
        active=active,
    )
    return b, err


class ResponseColumns(NamedTuple):
    """Column-oriented responses, request order. `err` uses ERR_* codes;
    ERROR_STRINGS maps them to the wire strings."""

    status: np.ndarray  # int32
    limit: np.ndarray  # int64
    remaining: np.ndarray  # int64
    reset_time: np.ndarray  # int64
    err: np.ndarray  # int8


def columns_from_requests(
    requests: Sequence[RateLimitRequest],
) -> RequestColumns:
    """Object → columns edge conversion (per-item loop lives here only)."""
    n = len(requests)
    fp = np.zeros(n, dtype=np.int64)
    err = np.zeros(n, dtype=np.int8)
    algo = np.zeros(n, dtype=np.int32)
    behavior = np.zeros(n, dtype=np.int32)
    hits = np.zeros(n, dtype=np.int64)
    limit = np.zeros(n, dtype=np.int64)
    burst = np.zeros(n, dtype=np.int64)
    duration = np.zeros(n, dtype=np.int64)
    created_at = np.zeros(n, dtype=np.int64)
    for i, r in enumerate(requests):
        if r.unique_key == "":
            err[i] = ERR_EMPTY_KEY
            continue
        if r.name == "":
            err[i] = ERR_EMPTY_NAME
            continue
        fp[i] = fingerprint(r.name, r.unique_key)
        algo[i] = int(r.algorithm)
        behavior[i] = int(r.behavior)
        hits[i] = r.hits
        limit[i] = min(max(r.limit, -(2**62)), 2**62)  # pre-clip to avoid int64 overflow
        burst[i] = min(max(r.burst, -(2**62)), 2**62)
        duration[i] = r.duration
        created_at[i] = r.created_at if r.created_at else 0
    return RequestColumns(
        fp=fp, algo=algo, behavior=behavior, hits=hits, limit=limit,
        burst=burst, duration=duration, created_at=created_at, err=err,
    )


def pack_requests(
    requests: Sequence[RateLimitRequest],
    now_ms: int,
    pad_to: Optional[int] = None,
    tolerance_ms: Optional[int] = None,
) -> "tuple[HostBatch, List[Optional[str]]]":
    """Resolve and pack requests into numpy SoA (host hot path).

    Returns (batch, errors): errors[i] is a per-request error string — the row
    is left inactive and must be answered with RateLimitResponse.error, exactly
    as the reference isolates invalid items instead of failing the batch
    (reference gubernator.go:215-237).

    Resolution performed here, mirroring host-side work in the reference:
    * validation: empty unique_key / name rejected (reference gubernator.go:215-224,
      including its quirky "field 'namespace' cannot be empty" wording)
    * created_at stamped with `now_ms` when unset (reference gubernator.go:225-227)
    * leaky burst==0 → limit (reference algorithms.go:259-261)
    * Gregorian: expire_new = end-of-interval, greg_interval = interval length,
      duration_eff = expire_new - now (reference algorithms.go:337-353,440-449);
      invalid Gregorian durations become per-request errors
    * non-Gregorian: expire_new = created_at + duration, duration_eff = duration
    """
    n = len(requests)
    size = pad_to if pad_to is not None else n
    if size < n:
        raise ValueError("pad_to smaller than batch")
    errors: List[Optional[str]] = [None] * n
    b = HostBatch(
        fp=np.zeros(size, dtype=np.int64),
        algo=np.zeros(size, dtype=np.int32),
        behavior=np.zeros(size, dtype=np.int32),
        hits=np.zeros(size, dtype=np.int64),
        limit=np.zeros(size, dtype=np.int64),
        burst=np.zeros(size, dtype=np.int64),
        duration=np.zeros(size, dtype=np.int64),
        created_at=np.zeros(size, dtype=np.int64),
        expire_new=np.zeros(size, dtype=np.int64),
        greg_interval=np.zeros(size, dtype=np.int64),
        duration_eff=np.zeros(size, dtype=np.int64),
        active=np.zeros(size, dtype=bool),
    )
    tol = _created_at_tolerance_ms if tolerance_ms is None else tolerance_ms
    for i, r in enumerate(requests):
        if r.unique_key == "":
            errors[i] = "field 'unique_key' cannot be empty"
            continue
        if r.name == "":
            errors[i] = "field 'namespace' cannot be empty"
            continue
        if not (-INT32_MAX <= r.limit <= INT32_MAX):
            errors[i] = "field 'limit' must fit int32"
            continue
        if not (-INT32_MAX <= r.burst <= INT32_MAX):
            errors[i] = "field 'burst' must fit int32"
            continue
        if not (0 <= int(r.algorithm) <= _max_algorithm()):
            errors[i] = ERROR_STRINGS[ERR_ALGORITHM]
            continue
        created = r.created_at if r.created_at is not None and r.created_at != 0 else now_ms
        if created > now_ms + tol:
            created = now_ms + tol
        elif created < now_ms - tol:
            created = now_ms - tol
        b.fp[i] = fingerprint(r.name, r.unique_key)
        b.algo[i] = int(r.algorithm)
        b.behavior[i] = int(r.behavior)
        b.hits[i] = r.hits
        b.limit[i] = r.limit
        b.duration[i] = r.duration
        b.created_at[i] = created
        if (
            int(r.algorithm) in (Algorithm.LEAKY_BUCKET, Algorithm.GCRA)
            and r.burst == 0
        ):
            b.burst[i] = r.limit
        else:
            b.burst[i] = r.burst
        if has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN):
            try:
                expire = gregorian.gregorian_expiration(now_ms, r.duration)
                b.greg_interval[i] = gregorian.gregorian_duration(now_ms, r.duration)
            except gregorian.GregorianError as e:
                errors[i] = str(e)
                b.fp[i] = 0
                continue
            b.expire_new[i] = expire
            b.duration_eff[i] = expire - now_ms
        else:
            b.expire_new[i] = created + r.duration
            b.greg_interval[i] = 0
            b.duration_eff[i] = r.duration
        b.active[i] = True
    return b, errors


def pad_batch(b: HostBatch, to_size: int) -> HostBatch:
    """Zero-pad every field to `to_size` rows (inactive padding)."""
    n = b.fp.shape[0]
    if n == to_size:
        return b
    if n > to_size:
        raise ValueError("cannot pad smaller")
    return HostBatch(
        *[np.concatenate([f, np.zeros(to_size - n, dtype=f.dtype)]) for f in b]
    )


def pack_host_batch(b: HostBatch, out: Optional[np.ndarray] = None) -> np.ndarray:
    """Pack a HostBatch into ONE (12, B) int64 array for a single host→
    device transfer — the ingress mirror of kernel2.pack_outputs' single-
    fetch egress. On a tunneled device every device_put costs an RTT, so 12
    per-column puts dominated the dispatch-issue path; one put amortizes it.
    The device side reconstructs the ReqBatch inside the kernel's jit
    (kernel2.req_from_arr), costing a few casts that fuse into the kernel.

    `out` lets the mesh engines pack straight into a persistent staging
    buffer (parallel/sharded._StagingPool) — may be a strided view into the
    pooled (D, 12, c) ingress grid, so no fresh (12, B) allocation and no
    second scatter per dispatch."""
    n = b.fp.shape[0]
    if out is None:
        arr = np.empty((12, n), dtype=np.int64)
    else:
        assert out.shape == (12, n) and out.dtype == np.int64, out.shape
        arr = out
    arr[0] = b.fp
    arr[1] = b.algo
    arr[2] = b.behavior
    arr[3] = b.hits
    arr[4] = b.limit
    arr[5] = b.burst
    arr[6] = b.duration
    arr[7] = b.created_at
    arr[8] = b.expire_new
    arr[9] = b.greg_interval
    arr[10] = b.duration_eff
    arr[11] = b.active
    return arr


def to_device(b: HostBatch) -> ReqBatch:
    return ReqBatch(
        fp=jnp.asarray(b.fp),
        algo=jnp.asarray(b.algo),
        behavior=jnp.asarray(b.behavior),
        hits=jnp.asarray(b.hits),
        limit=jnp.asarray(b.limit),
        burst=jnp.asarray(b.burst),
        duration=jnp.asarray(b.duration),
        created_at=jnp.asarray(b.created_at),
        expire_new=jnp.asarray(b.expire_new),
        greg_interval=jnp.asarray(b.greg_interval),
        duration_eff=jnp.asarray(b.duration_eff),
        active=jnp.asarray(b.active),
    )
