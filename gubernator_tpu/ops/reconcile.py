"""Cross-region delta reconcile — remote hits applied through the
conservative merge (docs/robustness.md "Multi-region active-active").

The receive half of the region plane (service/region_manager.py ships, the
owner daemon in each remote region lands here). A replicated batch carries,
per key: the sending region's aggregated HIT DELTA since its last successful
sync, the request config (limit/duration/algorithm/created_at — the compact
lane image), and the sender's own stored slot row in the sender's slot
layout (ops/layout.py; zero row when the sender's slot was already evicted).

Reconcile builds one INCOMING canonical full-width row per key and hands it
to ``kernel2.merge2`` via ``engine.merge_rows`` — never the serving path, so
a replicated batch cannot answer requests, queue broadcasts, or re-replicate
(ping-pong is structurally impossible). The incoming row is derived from:

* the receiver's OWN live stored row with the delta applied (the common
  case): ``REM_I`` drops by the delta for every integer remaining-style
  algorithm, GCRA advances its stored TAT by ``delta·T``, leaky subtracts
  from the float remainder (no refill accrual — conservative);
* else the sender's row verbatim (bootstrap: the sender's state already
  embodies the delta, plus every older hit the receiver may have missed);
* else a fresh row synthesized from the wire config with the delta applied.

Because the incoming remaining is always ≤ what the receiver stored and the
merge keeps ``remaining=min / expiry=max / aux=max / OVER-sticks``, a
duplicated or crossed replication batch can only UNDER-grant — the same
pinned conservatism that covers checkpoint replay and handoff. Exactness:
with each delta delivered once, every region's per-key count converges to
the exact union of all regions' hits (the delta protocol is an op-based
CRDT; at-least-once delivery degrades to under-grant, never over).

Runs as ONE engine-thread job (EngineRunner.apply_region), so the
read→reconcile→merge triplet is atomic with respect to serving dispatches —
no concurrent hit can slip between the stored-state read and the merge.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from gubernator_tpu.ops.table2 import (
    BURST, DUR_HI, DUR_LO, EXP_HI, EXP_LO, F, FLAGS, FP_HI, FP_LO, LIMIT,
    REM_I, REMF_HI, REMF_LO, STAMP_HI, STAMP_LO,
)
from gubernator_tpu.types import Algorithm, Status

_M32 = 0xFFFFFFFF
_OVER = int(Status.OVER_LIMIT)
i64 = np.int64


def _lo32(x: np.ndarray) -> np.ndarray:
    return (x & _M32).astype(np.uint32).view(np.int32)


def _hi32(x: np.ndarray) -> np.ndarray:
    return ((x >> 32) & _M32).astype(np.uint32).view(np.int32)


def _join64(lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    return (lo.astype(i64) & _M32) | (hi.astype(i64) << 32)


def _f64_pair(hi_i32: np.ndarray, lo_i32: np.ndarray) -> np.ndarray:
    """REMF f32 pair → float64 (leaky remainder storage, kernel2 rule)."""
    hi = np.ascontiguousarray(hi_i32, dtype=np.int32).view(np.float32)
    lo = np.ascontiguousarray(lo_i32, dtype=np.int32).view(np.float32)
    return hi.astype(np.float64) + lo.astype(np.float64)


def _pair_f64(val: np.ndarray):
    hi = val.astype(np.float32)
    lo = (val - hi.astype(np.float64)).astype(np.float32)
    return hi.view(np.int32), lo.view(np.int32)


def reconcile_region_rows(
    fps: np.ndarray,
    deltas: np.ndarray,
    cfg: dict,
    local_slots: np.ndarray,
    local_found: np.ndarray,
    sender_slots: Optional[np.ndarray],
    now_ms: int,
) -> np.ndarray:
    """Incoming canonical rows for one replicated delta batch (module
    docstring). `cfg` is the decode_wire_host column dict (limit, duration,
    algo, created_at as int64); `local_slots`/`local_found` come from
    engine.read_state; `sender_slots` are the sender's stored rows ALREADY
    unpacked to canonical full width (or None). Returns (n, 16) int32."""
    n = int(fps.shape[0])
    deltas = np.asarray(deltas, dtype=i64)
    lim = np.asarray(cfg["limit"], dtype=i64)
    dur = np.asarray(cfg["duration"], dtype=i64)
    algo = np.asarray(cfg["algo"], dtype=i64)
    ca = np.asarray(cfg["created_at"], dtype=i64)
    if sender_slots is None or sender_slots.size == 0:
        sender_slots = np.zeros((n, F), dtype=np.int32)
    local = np.asarray(local_slots, dtype=np.int32)
    sender = np.asarray(sender_slots, dtype=np.int32)

    l_exp = _join64(local[:, EXP_LO], local[:, EXP_HI])
    l_algo = local[:, FLAGS].astype(i64) & 0xFF
    # live matching local row → apply the delta to OUR state (exact union);
    # an expired local row means the window/TAT it described is over — the
    # delta belongs to a bucket that no longer exists, so fall through to
    # the sender row / fresh synthesis (whose own expiry gates staleness).
    use_local = (
        np.asarray(local_found, dtype=bool) & (l_exp >= now_ms)
        & (l_algo == algo)
    )
    s_fp = _join64(sender[:, FP_LO], sender[:, FP_HI])
    s_algo = sender[:, FLAGS].astype(i64) & 0xFF
    s_found = (s_fp != 0) & (s_algo == algo)
    use_sender = ~use_local & s_found
    use_fresh = ~use_local & ~use_sender

    is_gcra = algo == int(Algorithm.GCRA)
    is_leaky = algo == int(Algorithm.LEAKY_BUCKET)
    is_lease = algo == int(Algorithm.CONCURRENCY_LEASE)
    is_int = ~is_gcra & ~is_leaky  # REM_I remaining-style families

    # ---------------- candidate 1: receiver's own row ⊕ delta
    b_rem = local[:, REM_I].astype(i64)
    b_aux = _join64(local[:, REMF_LO], local[:, REMF_HI])
    b_limit = local[:, LIMIT].astype(i64)
    b_burst = local[:, BURST].astype(i64)
    b_dur = _join64(local[:, DUR_LO], local[:, DUR_HI])
    b_status = (local[:, FLAGS].astype(i64) >> 8) & 0xFF
    T_l = np.maximum(b_dur // np.maximum(b_limit, 1), 1)
    tau_l = T_l * np.where(b_burst > 0, b_burst, b_limit)
    tat0 = np.maximum(b_aux, now_ms)
    tat1 = tat0 + deltas * T_l
    a_rem = np.maximum(b_rem - deltas, 0)
    a_over_int = deltas > b_rem
    b_remf = _f64_pair(local[:, REMF_HI], local[:, REMF_LO])
    a_remf = np.maximum(b_remf - deltas.astype(np.float64), 0.0)
    a_over_lk = deltas.astype(np.float64) > b_remf
    a_over_g = (tat1 - tau_l) > now_ms
    a_status = np.maximum(
        b_status,
        np.where(
            np.where(is_gcra, a_over_g, np.where(is_leaky, a_over_lk,
                                                 a_over_int)),
            _OVER, 0,
        ),
    )
    a_aux = np.where(is_gcra, tat1, b_aux)
    a_exp = np.where(
        is_gcra, np.maximum(l_exp, tat1),
        np.where(is_lease, np.maximum(l_exp, now_ms + b_dur), l_exp),
    )
    # sender-row fold where both sides hold the same live algorithm: the
    # sliding-window previous count and the expiry tighten by MAX (a larger
    # prev or longer-lived state only denies more). GCRA TATs are NOT
    # folded — the sender's TAT already embodies the hits its deltas carry,
    # and max-ing it on top of the delta advance would double-count.
    s_exp = _join64(sender[:, EXP_LO], sender[:, EXP_HI])
    s_aux = _join64(sender[:, REMF_LO], sender[:, REMF_HI])
    fold = use_local & s_found
    a_exp = np.where(fold & ~is_gcra, np.maximum(a_exp, s_exp), a_exp)
    a_aux = np.where(
        fold & (algo == int(Algorithm.SLIDING_WINDOW)),
        np.maximum(a_aux, s_aux), a_aux,
    )

    # ---------------- candidate 3: fresh row from the wire config
    T_c = np.maximum(dur // np.maximum(lim, 1), 1)
    tau_c = T_c * lim
    g_tat = ca + deltas * T_c
    f_rem = np.where(is_int, np.maximum(lim - deltas, 0), 0)
    f_remf = np.where(
        is_leaky, np.maximum(lim - deltas, 0).astype(np.float64), 0.0
    )
    f_over = np.where(is_gcra, (g_tat - tau_c) > now_ms, deltas > lim)
    f_status = np.where(f_over, _OVER, 0)
    f_aux = np.where(is_gcra, g_tat, 0)
    f_stamp = np.where(is_lease, now_ms, ca)
    f_exp = np.where(
        is_gcra, g_tat,
        np.where(
            is_lease, now_ms + dur,
            np.where(algo == int(Algorithm.SLIDING_WINDOW),
                     ca + 2 * dur, ca + dur),
        ),
    )

    # ---------------- select + pack to canonical int32 lanes
    def pick64(a, s, f):
        return np.where(use_local, a, np.where(use_sender, s, f))

    sel_rem = pick64(a_rem, sender[:, REM_I].astype(i64), f_rem)
    sel_aux = pick64(a_aux, s_aux, f_aux)
    sel_exp = pick64(a_exp, s_exp, f_exp)
    sel_stamp = pick64(
        _join64(local[:, STAMP_LO], local[:, STAMP_HI]),
        _join64(sender[:, STAMP_LO], sender[:, STAMP_HI]),
        f_stamp,
    )
    sel_limit = pick64(b_limit, sender[:, LIMIT].astype(i64), lim)
    sel_burst = pick64(b_burst, sender[:, BURST].astype(i64), lim)
    sel_dur = pick64(
        b_dur, _join64(sender[:, DUR_LO], sender[:, DUR_HI]), dur
    )
    sel_status = pick64(
        a_status, (sender[:, FLAGS].astype(i64) >> 8) & 0xFF, f_status
    )
    # float remainder lanes: leaky carries the f32 pair; GCRA/window carry
    # the raw aux int64 split (merge2's aux_algo rule re-derives which)
    remf_hi_f, remf_lo_f = _pair_f64(pick64(a_remf, 0.0, f_remf))
    s_remf = np.stack([sender[:, REMF_HI], sender[:, REMF_LO]], axis=-1)
    aux_lanes = is_gcra | (algo == int(Algorithm.SLIDING_WINDOW))
    remf_hi = np.where(
        aux_lanes, _hi32(sel_aux),
        np.where(use_sender, s_remf[:, 0], remf_hi_f),
    )
    remf_lo = np.where(
        aux_lanes, _lo32(sel_aux),
        np.where(use_sender, s_remf[:, 1], remf_lo_f),
    )

    out = np.zeros((n, F), dtype=np.int32)
    out[:, FP_LO] = _lo32(np.asarray(fps, dtype=i64))
    out[:, FP_HI] = _hi32(np.asarray(fps, dtype=i64))
    out[:, LIMIT] = sel_limit.astype(np.int32)
    out[:, BURST] = sel_burst.astype(np.int32)
    out[:, REM_I] = np.clip(sel_rem, -(1 << 31), (1 << 31) - 1).astype(
        np.int32
    )
    out[:, FLAGS] = (algo | (sel_status << 8)).astype(np.int32)
    out[:, DUR_LO] = _lo32(sel_dur)
    out[:, DUR_HI] = _hi32(sel_dur)
    out[:, STAMP_LO] = _lo32(sel_stamp)
    out[:, STAMP_HI] = _hi32(sel_stamp)
    out[:, EXP_LO] = _lo32(sel_exp)
    out[:, EXP_HI] = _hi32(sel_exp)
    out[:, REMF_HI] = remf_hi
    out[:, REMF_LO] = remf_lo
    return out


# per-source receive-ledger bound: ~1M keys per source before the ledger
# resets wholesale (a reset degrades re-shipped batches to the legacy
# under-grant rule, never over)
DEDUP_LEDGER_CAP = 1 << 20


def dedup_source_deltas(
    ledger: dict,
    fps: np.ndarray,
    deltas: np.ndarray,
    cums: Optional[np.ndarray],
) -> np.ndarray:
    """Receiver-side exact dedup of re-shipped region-sync batches.

    `ledger` is this receiver's per-SOURCE map fp → highest cumulative
    counter already APPLIED (committed by the caller only after the merge
    lands — see RegionManager.dedup_recv). `cums[i]` is the sender's total
    hits ever queued for `fps[i]` toward this region, INCLUDING this
    batch's `deltas[i]`. The effective delta to apply is::

        cum >  seen  →  min(delta, cum - seen)   (normal / partial overlap)
        cum == seen  →  0                        (exact duplicate: skip)
        cum <  seen  →  delta                    (sender restarted or its
                                                  ledger reset: its new
                                                  counter counts only new
                                                  hits — apply them, and
                                                  re-baseline below)

    The `min(delta, ·)` cap matters when the sender DROPPED batches
    (bounded requeue, GUBER_REGION_REQUEUE_RETRIES): the gap between
    counters then includes hits that were never shipped and never will be
    — applying more than this batch actually carries would fabricate them.
    Every branch errs toward applying less, so dedup can only remove the
    double-apply under-grant, never over-grant. Returns the effective
    delta array; does NOT touch `ledger` (commit after the merge lands so
    a failed/cancelled apply is re-appliable)."""
    deltas = np.asarray(deltas, dtype=i64)
    if cums is None:
        return deltas  # pre-dedup sender: legacy at-least-once rule
    cums = np.asarray(cums, dtype=i64)
    eff = deltas.copy()
    for i, fp in enumerate(np.asarray(fps, dtype=i64)):
        seen = ledger.get(int(fp))
        if seen is None:
            continue
        c = int(cums[i])
        if c > seen:
            eff[i] = min(int(deltas[i]), c - seen)
        elif c == seen:
            eff[i] = 0
        # c < seen: sender reset — apply the delta as shipped
    return eff


def commit_source_cums(
    ledger: dict, fps: np.ndarray, cums: Optional[np.ndarray]
) -> None:
    """Record a successfully MERGED batch's cumulative counters into the
    per-source ledger (the second half of dedup_source_deltas). A
    sender-reset (cum below the stored baseline) re-baselines downward so
    the sender's fresh counter stream keeps deduping."""
    if cums is None:
        return
    if len(ledger) + fps.shape[0] > DEDUP_LEDGER_CAP:
        ledger.clear()  # degrade to legacy under-grant, bounded memory
    cums = np.asarray(cums, dtype=i64)
    for i, fp in enumerate(np.asarray(fps, dtype=i64)):
        ledger[int(fp)] = int(cums[i])


def apply_region_sync(
    engine,
    fps: np.ndarray,
    deltas: np.ndarray,
    cfg: dict,
    sender_slots: Optional[np.ndarray],
    sender_layout=None,
    now_ms: Optional[int] = None,
) -> int:
    """Apply one received cross-region delta batch: read the receiver's
    stored state, build the reconciled incoming rows, and merge them through
    kernel2.merge2 (engine.merge_rows). The sender's slot rows arrive in
    the SENDER's layout and convert through the canonical full row here —
    the PR-11 single conversion point — so a packed (gcra32/token32) sender
    cannot corrupt or over-grant a full-layout receiver, or vice versa.

    MUST run as one engine-thread job (EngineRunner.apply_region) so no
    serving dispatch interleaves between the read and the merge. Returns
    the number of rows merged."""
    from gubernator_tpu.ops.engine import ms_now

    fps = np.asarray(fps, dtype=i64)
    n = int(fps.shape[0])
    if n == 0:
        return 0
    now = now_ms if now_ms is not None else ms_now()
    if sender_slots is not None and sender_slots.size:
        sender_full = engine._slots_to_full(sender_slots, sender_layout)
    else:
        sender_full = None
    # duplicate fps inside one batch would make the per-key delta rows
    # shadow each other in the min-merge (losing the smaller delta — the
    # OVER-granting direction); the sender aggregates per key, but fold
    # defensively anyway
    uniq, first, inv = np.unique(fps, return_index=True, return_inverse=True)
    if uniq.shape[0] != n:
        # keep each key's first occurrence for config/slots, sum the deltas
        agg = np.zeros(uniq.shape[0], dtype=i64)
        np.add.at(agg, inv, np.asarray(deltas, dtype=i64))
        fps = fps[first]
        deltas = agg
        cfg = {k: np.asarray(v)[first] for k, v in cfg.items()}
        if sender_full is not None:
            sender_full = sender_full[first]
        n = fps.shape[0]
    found, local = engine.read_state(fps)
    rows = reconcile_region_rows(
        fps, deltas, cfg, local, found, sender_full, now
    )
    return engine.merge_rows(fps, rows, now_ms=now)
