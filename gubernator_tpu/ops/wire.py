"""Compact host↔device wire codec for the serving hot path.

BENCH_r05 attributed the whole remaining sharded-dispatch gap (~2.3 s wall
vs ~10 ms device) to host↔device transport: every dispatch ships a
(12, B) int64 ingress grid (96 B/row) and fetches a (B+2, 4) int64 output
(32 B/row) over a link where bytes are the budget. This module shrinks both
directions with an in-trace-decoded packed layout:

**Ingress — 5 int32 lanes (20 B/row) + one trailing base column:**

  lane 0  fp_lo          low 32 bits of the fingerprint
  lane 1  fp_hi          high 32 bits (fp == 0 ⇒ inactive row — the packing
                         invariant every serving path already maintains)
  lane 2  limit          full int32 (front-door validated to int32)
  lane 3  duration[0:27] | algo << 27 (3 bits) | cascade_level << 30 (2 bits)
  lane 4  hits[0:18] | (created_delta + 512) << 18 | priority << 28
          | RESET << 30 | DRAIN << 31

  column B (the +1): cells [0, B], [1, B] carry the batch's created_at BASE
  (lo/hi int32) — every other per-row timestamp decodes as base-relative.

The decode (decode_wire_block) reconstructs the full 12-column int64 ingress
array INSIDE the kernel's jit, where the redundant fields are recomputed
instead of shipped: created_at = base + delta, expire_new = created +
duration, duration_eff = duration, greg_interval = 0, burst = limit for
leaky and GCRA rows (the burst==0→limit defaulting both algorithms' packs
apply), 0 otherwise (no other algorithm reads burst — ops/math.py).
Behavior ships as exactly the two bits the decision math consumes
(RESET_REMAINING, DRAIN_OVER_LIMIT) plus the 2-bit cascade level (levels
above CASCADE_WIRE_MAX_LEVEL ride full-width) and the 2-bit priority tier
(types.PRIORITY_SHIFT — the overload plane's QoS field, echoed back in the
egress flags); kernel-inert bits (NO_BATCHING, GLOBAL, MULTI_REGION) are
dropped on the wire.

The algo field grew from 2 to 3 bits (the five in-kernel algorithms) and
the cascade level took the remaining 2, paid for by narrowing the duration
budget from 2^30 to 2^27 ms (~37 hours — daily quotas still fit; multi-day
windows fall back to full-width, exactly like weekly ones always did).
The priority tier was paid for the same way: the created-at delta budget
narrowed from ±2047 to ±511 ms of the batch base — serving batches stamp
one ingress `now` over the whole batch (delta 0), so only client-supplied
created_at beyond half a second of skew falls back to full-width.

**Egress — (B+2, 4) int32 (16 B/row), same row layout as kernel2.pack_outputs:**

  row i < B   [limit, remaining (saturating i32), reset_delta, flags]
  row B       [cache_hits, cache_misses, over_limit, evicted]  (counts ≤ B)
  row B+1     [dropped, base_lo, base_hi, 0]

reset_delta = reset_time - base, with -2^31 reserved as the "reset==0"
sentinel so inactive/removed rows round-trip exactly; the base rides in the
spare stats cells, making the fetched array self-describing (unpack_outputs
dispatches on dtype alone). Host-side decode is vectorized numpy.

**Fallback contract.** Not every batch is representable (Gregorian
durations, hits ≥ 2^18, durations ≥ 2^30 ms, created_at skew beyond
±511 ms of the batch base, negative limits, explicit leaky bursts).
`wire_encodable` checks a batch host-side in a handful of vectorized
passes; non-encodable dispatches take the full-width path — identical
semantics, more bytes — and `GUBER_WIRE_COMPACT=0` forces full-width
everywhere, which is the parity oracle every compact test and bench smoke
compares against row-for-row.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from gubernator_tpu.ops.batch import HostBatch
from gubernator_tpu.ops.kernel2 import (
    FLAG_DROPPED,
    FLAG_HIT,
    FLAG_STATUS,
    _hi32,
    _join64,
    _lo32,
    decide2_packed_cols_impl,
    decide2_packed_dedup_impl,
)

i32 = jnp.int32
i64 = jnp.int64

WIRE_LANES = 5  # ingress int32 lanes per row (20 B) — + 1 base column/grid
WIRE_EGRESS_ROW_BYTES = 16  # (·, 4) int32 egress rows
DUR_BITS = 27  # duration < 2^27 ms (~37 hours); beyond → full-width
ALGO_BITS = 3  # five in-kernel algorithms (types.Algorithm)
LEVEL_SHIFT = DUR_BITS + ALGO_BITS  # cascade level, 2 bits (30, 31)
LEVEL_MAX = 3  # types.CASCADE_WIRE_MAX_LEVEL — deeper cascades → full-width
HITS_BITS = 18  # hits in [0, 2^18) — covers host-aggregated 131K-row carriers
DELTA_BITS = 10  # created_at - base in [-512, 511] ms
DELTA_BIAS = 1 << (DELTA_BITS - 1)
PRIO_SHIFT = HITS_BITS + DELTA_BITS  # priority tier, 2 bits (28, 29)
_DUR_MASK = (1 << DUR_BITS) - 1
_ALGO_MASK = (1 << ALGO_BITS) - 1
_HITS_MASK = (1 << HITS_BITS) - 1
_DELTA_MASK = (1 << DELTA_BITS) - 1
RESET_SENTINEL = -(2**31)  # egress reset_delta value for reset_time == 0
# behavior-word cascade level field (types.CASCADE_LEVEL_SHIFT)
_BEH_LEVEL_SHIFT = 8
# behavior-word priority tier field (types.PRIORITY_SHIFT)
_BEH_PRIO_SHIFT = 6
_MAX_ALGO = 4  # types.MAX_ALGORITHM — wire-encodable algorithm range

# Behavior bits (gubernator_tpu.types.Behavior values, frozen by the proto)
_RESET = 8  # RESET_REMAINING — consumed by the decision math
_DRAIN = 32  # DRAIN_OVER_LIMIT — consumed by the decision math
_GREG = 4  # DURATION_IS_GREGORIAN — host-resolved; forces full-width
# bits the kernel never reads (ops/math.py) — safe to drop on the wire
_INERT = 1 | 2 | 16  # NO_BATCHING | GLOBAL | MULTI_REGION
_PRIO_BEH = 0x3 << _BEH_PRIO_SHIFT  # priority tier — carried in lane 4
_ENCODABLE_BEHAVIOR = _RESET | _DRAIN | _INERT | _PRIO_BEH

I32_MAX = 2**31 - 1


def default_wire_mode() -> str:
    """Compact wire grids on real TPU (where host↔device bytes are the
    serving bottleneck), full-width elsewhere (CPU test meshes keep the
    seed suite's exact transfer shapes). GUBER_WIRE_COMPACT=1/0 forces
    either mode; per-engine `wire=` overrides both."""
    env = os.environ.get("GUBER_WIRE_COMPACT")
    if env is not None:
        return "compact" if env not in ("0", "false", "off") else "full"
    return "compact" if jax.default_backend() == "tpu" else "full"


# ------------------------------------------------------------- host encode


def pick_base(b: HostBatch) -> int:
    """The batch's created_at base: the first active row's stamp. Serving
    batches stamp every unset created_at with one ingress `now`
    (ops/batch.pack_columns), so per-row deltas are 0; rows skewed beyond
    the delta budget fail wire_encodable and take the full-width path."""
    act = np.asarray(b.active)
    if not act.any():
        return 0
    return int(b.created_at[int(np.argmax(act))])


def wire_encodable(b: HostBatch, base: int) -> bool:
    """Can this batch ride the compact wire exactly? A handful of
    vectorized passes over the active rows — cheap against the pack it
    gates. Every check guards a field the compact layout narrows or
    recomputes; failing any one falls the dispatch back to full-width
    (same semantics, more bytes), so this is a perf decision, never a
    correctness one."""
    act = np.asarray(b.active)
    if not act.any():
        return True
    fp = b.fp[act]
    if (fp == 0).any():
        return False  # active ⟺ fp != 0 is the decode's activity rule
    beh = b.behavior[act].astype(np.int64)
    # bits 0..7 are behavior flags, 8..15 the cascade level (compact lane
    # carries 2 level bits); anything above is unknown → full-width
    if (beh & ~np.int64((0xFF << _BEH_LEVEL_SHIFT) | _ENCODABLE_BEHAVIOR)).any():
        return False  # Gregorian (host-resolved calendar fields) or unknown
    lvl = (beh >> _BEH_LEVEL_SHIFT) & 0xFF
    if (lvl > LEVEL_MAX).any():
        return False  # cascade deeper than the 2-bit lane budget
    if (b.greg_interval[act] != 0).any():
        return False
    dur = b.duration[act]
    if ((dur < 0) | (dur > _DUR_MASK)).any():
        return False
    if (b.duration_eff[act] != dur).any():
        return False
    created = b.created_at[act]
    if (b.expire_new[act] != created + dur).any():
        return False  # expire recomputes in-trace only for the linear rule
    delta = created - base
    if ((delta < -DELTA_BIAS) | (delta > DELTA_BIAS - 1)).any():
        return False
    hits = b.hits[act]
    if ((hits < 0) | (hits > _HITS_MASK)).any():
        return False  # negative hits (lease releases) ride full-width
    limit = b.limit[act]
    if ((limit < 0) | (limit > I32_MAX)).any():
        return False  # negative limits keep the full-width path's exact
        # (pathological) arithmetic; positive is the serving domain
    algo = b.algo[act]
    if ((algo < 0) | (algo > _MAX_ALGO)).any():
        return False
    burst = b.burst[act]
    bursty = (algo == 1) | (algo == 2)  # leaky / GCRA: burst lane-derived
    if bursty.any() and (burst[bursty] != limit[bursty]).any():
        return False  # burst defaults to limit (pack rule); explicit
        # bursts are rare enough to ship full-width
    nob = (algo == 3) | (algo == 4)  # window / lease: burst unused, keep 0
    if nob.any() and (burst[nob] != 0).any():
        return False
    return True


def pack_wire_rows(
    b: HostBatch, base: int, out: "np.ndarray | None" = None
) -> np.ndarray:
    """Pack a (wire_encodable) HostBatch into (5, n) int32 data lanes.
    Inactive rows encode as all-zero columns (fp == 0 ⇒ inactive on
    decode). `out` packs straight into pooled staging memory."""
    n = b.fp.shape[0]
    if out is None:
        arr = np.empty((WIRE_LANES, n), dtype=np.int32)
    else:
        assert out.shape == (WIRE_LANES, n) and out.dtype == np.int32
        arr = out
    act = b.active
    fp = np.where(act, b.fp, 0)
    arr[0] = fp.astype(np.int64).astype(np.int32)  # low 32, wrap cast
    arr[1] = (fp >> 32).astype(np.int32)
    arr[2] = np.where(act, b.limit, 0).astype(np.int32)
    lvl = (b.behavior.astype(np.int64) >> _BEH_LEVEL_SHIFT) & 0xFF
    l3 = (
        (b.duration & _DUR_MASK)
        | (b.algo.astype(np.int64) << DUR_BITS)
        | (lvl << LEVEL_SHIFT)
    )
    arr[3] = np.where(act, l3, 0).astype(np.int64).astype(np.int32)
    reset = (b.behavior & _RESET) != 0
    drain = (b.behavior & _DRAIN) != 0
    prio = (b.behavior.astype(np.int64) >> _BEH_PRIO_SHIFT) & 0x3
    l4 = (
        (b.hits & _HITS_MASK)
        | (((b.created_at - base + DELTA_BIAS) & _DELTA_MASK) << HITS_BITS)
        | (prio << PRIO_SHIFT)
        | (reset.astype(np.int64) << 30)
        | (drain.astype(np.int64) << 31)
    )
    arr[4] = np.where(act, l4, 0).astype(np.int64).astype(np.int32)
    return arr


def pack_wire_full(
    b: HostBatch, base: int, out: "np.ndarray | None" = None
) -> np.ndarray:
    """(5, n+1) int32: data lanes plus the trailing base column — the
    single-device / single-block ingress form (mesh engines scatter
    pack_wire_rows into their own grids and stamp the base per block)."""
    n = b.fp.shape[0]
    if out is None:
        arr = np.zeros((WIRE_LANES, n + 1), dtype=np.int32)
    else:
        assert out.shape == (WIRE_LANES, n + 1) and out.dtype == np.int32
        arr = out
        arr[:, n] = 0
    pack_wire_rows(b, base, out=arr[:, :n])
    stamp_base(arr, base)
    return arr


def assemble_wire_grid(
    lane_parts: "list[np.ndarray]",
    created: np.ndarray,
    base: int,
    pad: int,
    active: np.ndarray,
) -> np.ndarray:
    """Fused front-door staging: scatter pre-packed per-request lane blocks
    (the native parser's (5, n_i) int32 images, created-delta bits zero)
    into ONE padded (5, pad+1) ingress grid, OR the batch-relative created
    deltas into lane 4, and stamp the base column. This single scatter IS
    the staging — no RequestColumns concat, no 12-column HostBatch pack, no
    second wire pack; the request bytes were traversed exactly once, by the
    parser. `created` holds the stamped absolute created_at over the
    concatenated rows; callers verify the delta budget (±511 ms of `base`)
    before assembling."""
    grid = np.zeros((WIRE_LANES, pad + 1), dtype=np.int32)
    off = 0
    for lanes in lane_parts:
        w = lanes.shape[1]
        grid[:, off : off + w] = lanes
        off += w
    delta32 = (
        ((created - base + DELTA_BIAS) & _DELTA_MASK) << HITS_BITS
    ).astype(np.int32)
    grid[4, :off] |= np.where(active, delta32, np.int32(0))
    stamp_base(grid, base)
    return grid


def grid_math_mode(grid: np.ndarray, n: int) -> str:
    """Static kernel math variant for an assembled wire grid — the
    lane-level twin of engine._math_mode: all-token → the token-only
    graph, a leaky row → the mixed (f64) graph, any other algorithm →
    the all-integer graph."""
    algo = (grid[3, :n].astype(np.int64) >> DUR_BITS) & _ALGO_MASK
    if (algo == 1).any():
        return "mixed"
    if not algo.any():
        return "token"
    # active rows are fp != 0 (lanes 0/1); inactive lanes are all-zero
    act = algo[(grid[0, :n] != 0) | (grid[1, :n] != 0)]
    if act.size and (act == 2).all():
        return "gcra"
    return "int"


def grid_has_cascade(grid: np.ndarray, n: int) -> bool:
    """Whether an assembled wire grid carries cascade level bits (lane 3
    bits 30-31) — the engine then compiles the in-trace verdict fold into
    the dispatch (kernel2.fold_cascade_packed)."""
    return bool(((grid[3, :n].astype(np.int64) >> LEVEL_SHIFT) & 3).any())


def stamp_base(block: np.ndarray, base: int) -> None:
    """Write the base into a wire block's trailing column (cells [0, -1]
    and [1, -1]) — shared by every grid builder so the cell assignment can
    never diverge from decode_wire_block's."""
    block[0, -1] = np.int64(base).astype(np.int32)
    block[1, -1] = np.int64(base >> 32).astype(np.int32)


# ------------------------------------------------------------ trace decode


def decode_wire_block(blk: jnp.ndarray):
    """In-trace decode of one (5, W+1) int32 wire block back to the full
    (12, W) int64 ingress array (kernel2.req_from_arr layout) plus the
    base scalar. Pure casts/shifts — fuses into the decision kernel, so
    the narrow wire costs a few vector ops instead of 76 B/row of PCIe/
    tunnel traffic."""
    W = blk.shape[1] - 1
    base = _join64(blk[0, W], blk[1, W])
    l0, l1, l2, l3, l4 = (blk[i, :W] for i in range(WIRE_LANES))
    fp = _join64(l0, l1)
    limit = l2.astype(i64)
    dur = (l3 & _DUR_MASK).astype(i64)
    algo = (l3 >> DUR_BITS) & _ALGO_MASK
    level = (l3 >> LEVEL_SHIFT) & 3
    hits = (l4 & _HITS_MASK).astype(i64)
    delta = (((l4 >> HITS_BITS) & _DELTA_MASK) - DELTA_BIAS).astype(i64)
    behavior = (
        ((l4 >> 30) & 1) * _RESET
        | ((l4 >> 31) & 1) * _DRAIN
        | (((l4 >> PRIO_SHIFT) & 3) << _BEH_PRIO_SHIFT)
        | (level << _BEH_LEVEL_SHIFT)
    )
    created = base + delta
    active = fp != 0
    # burst reconstructs to limit for the tolerance-shaped algorithms
    # (leaky, GCRA — the pack-side defaulting), 0 otherwise
    burst = jnp.where((algo == 1) | (algo == 2), limit, i64(0))
    arr12 = jnp.stack(
        [
            fp,
            algo.astype(i64),
            behavior.astype(i64),
            hits,
            limit,
            burst,
            dur,
            created,
            created + dur,  # expire_new (non-Gregorian by encodability)
            jnp.zeros_like(fp),  # greg_interval
            dur,  # duration_eff
            active.astype(i64),
        ]
    )
    return arr12, base


def encode_wire_out(packed: jnp.ndarray, base) -> jnp.ndarray:
    """In-trace egress narrowing: the (B+2, 4) int64 pack_outputs array →
    int32, reset as a base-relative delta (RESET_SENTINEL preserves
    reset==0 exactly), remaining/limit saturating-clamped to int32 (both
    are int32-bounded for every validated config — the clamp only moves
    values pathological configs could not re-read anyway), and the base
    stamped into the spare stats cells so the fetched array is
    self-describing."""
    B = packed.shape[0] - 2
    rows = packed[:B]
    sat = lambda x: jnp.clip(x, -(2**31), 2**31 - 1).astype(i32)
    reset = rows[:, 2]
    enc = jnp.where(
        reset == 0,
        jnp.int32(RESET_SENTINEL),
        jnp.clip(reset - base, -(2**31) + 1, 2**31 - 1).astype(i32),
    )
    body = jnp.stack([sat(rows[:, 0]), sat(rows[:, 1]), enc, sat(rows[:, 3])], axis=1)
    stats = jnp.clip(packed[B:], -(2**31), 2**31 - 1).astype(i32)
    stats = stats.at[1, 1].set(_lo32(base)).at[1, 2].set(_hi32(base))
    return jnp.concatenate([body, stats], axis=0)


# -------------------------------------------------------------- host decode


def decode_wire_host(lanes: np.ndarray, base: int) -> dict:
    """Vectorized HOST decode of a (5, n) int32 lane image (pack_wire_rows
    layout) back to full-width int64 columns — the receive half of the
    inter-slice GLOBAL sync codec (service/global_manager.py ships pending
    hits as one lane image instead of n proto messages; the owner daemon
    decodes them here before applying). The in-trace twin is
    decode_wire_block; the two must agree field-for-field, which
    tests/test_ring_exchange.py pins by round-tripping through both."""
    lanes = np.asarray(lanes, dtype=np.int32)
    l0, l1, l2, l3, l4 = (lanes[i].astype(np.int64) for i in range(WIRE_LANES))
    fp = (l0 & 0xFFFFFFFF) | (l1 << 32)
    dur = l3 & _DUR_MASK
    algo = (l3 >> DUR_BITS) & _ALGO_MASK
    level = (l3 >> LEVEL_SHIFT) & 3
    hits = l4 & _HITS_MASK
    delta = ((l4 >> HITS_BITS) & _DELTA_MASK) - DELTA_BIAS
    behavior = (
        ((l4 >> 30) & 1) * _RESET
        | ((l4 >> 31) & 1) * _DRAIN
        | (((l4 >> PRIO_SHIFT) & 3) << _BEH_PRIO_SHIFT)
        | (level << _BEH_LEVEL_SHIFT)
    )
    created = base + delta
    return {
        "fp": fp,
        "algo": algo.astype(np.int32),
        "behavior": behavior.astype(np.int32),
        "hits": hits,
        "limit": l2,
        "duration": dur,
        "created_at": created,
        "active": fp != 0,
    }


def wire_out_base(arr: np.ndarray) -> int:
    """The base stamped into a fetched compact egress array."""
    return (int(arr[-1, 1]) & 0xFFFFFFFF) | (int(arr[-1, 2]) << 32)


def decode_wire_rows(per: np.ndarray, base: int) -> np.ndarray:
    """Vectorized host decode of compact egress response rows ((n, 4)
    int32 → int64, absolute reset_time). Returns a fresh writable array
    (retry fix-ups mutate responses in place)."""
    out = per.astype(np.int64)
    d = out[:, 2]
    out[:, 2] = np.where(d == RESET_SENTINEL, 0, base + d)
    return out


def unpack_wire_out(arr: np.ndarray, n: int):
    """Compact counterpart of kernel2.unpack_outputs (same return shape);
    kernel2.unpack_outputs dispatches here on dtype, so every caller
    decodes both wire formats through one entry."""
    base = wire_out_base(arr)
    st = (int(arr[-2, 0]), int(arr[-2, 1]), int(arr[-2, 2]), int(arr[-2, 3]))
    per = decode_wire_rows(arr[:n], base)
    status = (per[:, 3] & FLAG_STATUS).astype(np.int32)
    hit = (per[:, 3] & FLAG_HIT) != 0
    dropped = (per[:, 3] & FLAG_DROPPED) != 0
    return (status, per[:, 0], per[:, 1], per[:, 2], dropped, hit), st


# --------------------------------------------------- single-device entries


def decide2_wire_cols_impl(
    table, carr, *, write="sweep", math="mixed", cascade=False, probe="xla",
    evictees=False,
):
    """Compact single-transfer serving entry: (5, B+1) int32 wire block in,
    (B+2, 4) int32 compact outputs out — the narrow-wire twin of
    kernel2.decide2_packed_cols_impl. `cascade=True` folds cascade verdicts
    in-trace on the wide packed array BEFORE the egress narrowing; `probe`
    selects the table-walk kernel (GUBER_PROBE_KERNEL). `evictees=True`
    appends the raw int32 evictee sidecar AFTER the narrowing (slot fields
    are bit patterns, never clamped — kernel2.attach_evictees_wire)."""
    arr12, base = decode_wire_block(carr)
    if evictees:
        from gubernator_tpu.ops.kernel2 import (
            attach_evictees_wire,
            decide2_packed_impl,
            fold_cascade_packed,
            req_from_arr,
        )

        table, packed, ev16 = decide2_packed_impl(
            table, req_from_arr(arr12), write=write, math=math, probe=probe,
            evictees=True,
        )
        if cascade:
            packed = fold_cascade_packed(packed, arr12)
        return table, attach_evictees_wire(encode_wire_out(packed, base), ev16)
    table, packed = decide2_packed_cols_impl(
        table, arr12, write=write, math=math, cascade=cascade, probe=probe
    )
    return table, encode_wire_out(packed, base)


def decide2_wire_dedup_impl(
    table, carr, *, write="sweep", math="mixed", cascade=False, probe="xla"
):
    """Compact entry with in-trace duplicate aggregation (the mesh
    engines' dedup="device" program built on the narrow wire)."""
    arr12, base = decode_wire_block(carr)
    table, packed = decide2_packed_dedup_impl(
        table, arr12, write=write, math=math, cascade=cascade, probe=probe
    )
    return table, encode_wire_out(packed, base)


decide2_wire_cols = functools.partial(
    jax.jit, donate_argnums=(0,),
    static_argnames=("write", "math", "cascade", "probe", "evictees"),
)(decide2_wire_cols_impl)
