from gubernator_tpu.ops.table2 import Table2, new_table2, live_count2
from gubernator_tpu.ops.batch import BatchStats, InstallBatch, ReqBatch, RespBatch
from gubernator_tpu.ops.kernel2 import decide2, install2
from gubernator_tpu.ops.engine import LocalEngine

__all__ = [
    "Table2",
    "new_table2",
    "live_count2",
    "BatchStats",
    "InstallBatch",
    "ReqBatch",
    "RespBatch",
    "decide2",
    "install2",
    "LocalEngine",
]
