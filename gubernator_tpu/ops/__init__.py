from gubernator_tpu.ops.table import Table, new_table
from gubernator_tpu.ops.batch import ReqBatch, RespBatch, BatchStats
from gubernator_tpu.ops.kernel import decide

__all__ = ["Table", "new_table", "ReqBatch", "RespBatch", "BatchStats", "decide"]
