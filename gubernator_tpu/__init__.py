"""gubernator_tpu — a TPU-native distributed rate-limiting framework.

A ground-up re-design of the capabilities of gubernator (reference:
/root/reference, pure Go) for TPU hardware:

* the counter hot path (token/leaky bucket mutation over millions of keys) runs
  as vectorized int64/f64 kernels over an HBM-resident hash-slotted
  struct-of-arrays state table (replaces reference algorithms.go + lrucache.go
  + workers.go);
* cluster key-ownership maps onto TPU mesh axes via shard_map/pjit (replaces
  reference replicated_hash.go node spread);
* GLOBAL-behavior hit aggregation + authoritative broadcast become mesh
  collectives over ICI/DCN (replaces reference global.go gRPC fan-out);
* a thin host front door keeps the gRPC/HTTP API surface, peer discovery,
  health and Prometheus metrics (reference daemon.go / gubernator.go).

int64 timestamps (epoch milliseconds) and float64 leaky-bucket remainders
require jax x64 mode, enabled at import.
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

# Honor an explicit JAX_PLATFORMS=cpu request. Some environments bootstrap a
# default accelerator platform via sitecustomize (e.g. the axon TPU tunnel
# force-sets jax_platforms AND exports JAX_PLATFORMS), which would silently
# override a user's CPU request — CPU-only deployments and tests must win.
# Only the cpu case is re-asserted; any accelerator value is left to the
# platform bootstrap, which knows how to initialize it.
if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    if jax.config.jax_platforms != "cpu":
        jax.config.update("jax_platforms", "cpu")

# Persistent kernel-compile cache: the decision kernel compiles per batch
# shape (~15-40 s each on TPU); caching across process restarts turns daemon
# boots and bench reruns into cache hits (measured 19.6 s → 7.5 s boot).
# Explicit settings win — env var OR a programmatic jax.config choice.
if (
    not os.environ.get("JAX_COMPILATION_CACHE_DIR")
    and not jax.config.jax_compilation_cache_dir
):
    _home = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    if not _home.startswith("~"):  # no HOME + no passwd entry: skip the cache
        _cache = os.path.join(_home, "gubernator_tpu_jit")
        try:
            os.makedirs(_cache, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", _cache)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except OSError:  # read-only cache home: run without the cache
            pass
        del _cache
    del _home

from gubernator_tpu.types import (  # noqa: E402
    Algorithm,
    Behavior,
    Status,
    RateLimitRequest,
    RateLimitResponse,
    has_behavior,
)

__version__ = "0.1.0"

__all__ = [
    "Algorithm",
    "Behavior",
    "Status",
    "RateLimitRequest",
    "RateLimitResponse",
    "has_behavior",
    "__version__",
]
