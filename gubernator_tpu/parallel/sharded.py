"""Sharded execution: the key table distributed over the TPU mesh.

The reference spreads keys across cluster nodes with a consistent-hash ring and
forwards requests to owners over gRPC (replicated_hash.go, peer_client.go).
Here the same ownership axis maps onto the device mesh: every device holds a
shard of the HBM table, the host routes each request's fingerprint to its
owning shard, and one shard_map dispatch executes the decision kernel on all
shards simultaneously — no forwarding hop, no N×N connection mesh; ICI does
what gRPC did.

Layout: the Table2 leaves gain a leading (D,) device axis sharded with
PartitionSpec("shard"); request batches travel as ONE (D, 12, b_local) packed
i64 ingress grid and come back as ONE (D, b_local+2, 4) packed output grid
(single put + single fetch per mesh dispatch, cf. batch.pack_host_batch /
kernel2.pack_outputs). Inside shard_map each device sees its (1, …) block and
runs the decision kernel on its local slice independently — embarrassingly
parallel, exactly like the reference's share-nothing workers (workers.go:19-37)
but across chips. Because dispatches route UNIQUE fingerprints (the pass
planner aggregates same-key duplicates first, ops/plan.py), the hash spread
over shards stays near-multinomial even under Zipf-skewed traffic — per-shard
padding is counts.max() over a balanced draw, not the hot key's count.

Two routing modes (ShardedEngine(route=...), GUBER_SHARD_ROUTE):
* "host": the host sorts rows into the ownership grid — simple and fast on
  a single-host mesh, and the exact-sequential-semantics fallback;
* "device" (TPU default): the host ships rows in ARRIVAL order and the mesh
  itself routes them with a capacity-bounded all_to_all exchange
  (parallel/a2a.py) — zero per-dispatch host routing work, the path that
  scales to multi-host slices where each host only feeds its local devices.

Two dedup modes (ShardedEngine(dedup=...), GUBER_SHARD_DEDUP) decide WHERE
the kernel's unique-fingerprint contract is discharged:
* "host": the pass planner's numpy group-by (ops/plan.plan_passes) — exact
  per-occurrence sequential semantics, O(n log n) single-process work on
  every dispatch's critical path;
* "device" (TPU default): duplicate keys aggregate IN-TRACE
  (kernel2.dedup_packed_cols — hits summed, RESET_REMAINING OR-ed, newest
  config wins, members answered from the carrier) and the host plans O(1)
  (ops/plan.single_pass). Same semantics as plan_passes(max_exact=1), i.e.
  the reference's GLOBAL hot-key aggregation applied from occurrence 0.

Ingress/egress staging is persistent: packed grids build in a ring of
reusable host buffers (_StagingPool), ship once, and are DONATED into the
mesh step; the packed output allocation aliases a recycled egress buffer
from an earlier dispatch (_take_egress). Steady-state serving therefore
allocates no fresh host or device staging memory, and the prepare/issue/
finish runner split double-buffers the ring: pack(N+1) fills one buffer
while N's transfer drains another.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gubernator_tpu.ops.batch import (
    ERROR_STRINGS,
    HostBatch,
    InstallBatch,
    RequestColumns,
    ResponseColumns,
    pack_host_batch,
)
from gubernator_tpu.ops.kernel2 import (
    FLAG_DROPPED,
    FLAG_HIT,
    FLAG_MEMBER,
    FLAG_STATUS,
    FLAG_UNPROCESSED,
    decide2_packed_cols_impl,
    decide2_packed_dedup_impl,
    install2_impl,
)
from gubernator_tpu.ops.engine import (
    EngineStats,
    _math_mode,
    _pad_size,
    batch_needs_full_layout,
    default_write_mode,
    effective_math,
    ms_now,
)
from gubernator_tpu.ops.plan import _subset, plan_passes, single_pass
from gubernator_tpu.ops.table2 import Table2, new_table2
from gubernator_tpu.parallel.mesh import (
    devices_per_host,
    mesh_hosts,
    shard_map_compat,
    shard_of,
    shard_spec,
)
from gubernator_tpu.types import RateLimitRequest, RateLimitResponse


def _staging_donate() -> tuple:
    """donate_argnums for the (table, ingress grid, egress buffer) mesh
    steps: everything on TPU — the ingress grid's HBM frees at launch, the
    egress buffer aliases the output allocation — but table-only on CPU,
    where device_put zero-copies aligned host numpy buffers and donating
    memory XLA doesn't own corrupts or crashes the process."""
    return (0, 1, 2) if jax.default_backend() == "tpu" else (0,)


def default_shard_route() -> str:
    """On-device routing (the a2a exchange) on real TPU meshes — zero host
    routing work per dispatch, ICI does what the host argsort did; the host
    ownership grid everywhere else (CPU test meshes keep the simple path
    and the seed tests' exact shapes)."""
    return "device" if jax.default_backend() == "tpu" else "host"


def default_shard_dedup() -> str:
    """In-trace duplicate aggregation on real TPU meshes — the host group-by
    (plan_passes' np.unique) leaves the dispatch critical path; host
    planning elsewhere, preserving exact sequential same-key semantics on
    the CPU test meshes. Overridable per engine (dedup=) or daemon-wide
    (GUBER_SHARD_DEDUP) — a TPU deployment that needs per-occurrence
    sequential responses for duplicate keys within one batch sets "host"."""
    return "device" if jax.default_backend() == "tpu" else "host"


def make_sharded_decide(
    mesh: Mesh, math: str = "mixed", write: Optional[str] = None,
    dedup: bool = False, wire: bool = False, probe: str = "xla",
):
    """Build the jitted all-shards decision step over the SINGLE-TRANSFER
    packed layout: (Table2[D,·], (D, 12, b) i64 ingress grid, (D, b+2, 4)
    recycled egress buffer) → (Table2', (D, b+2, 4) i64 packed outputs).
    Each device unpacks its ingress block in-kernel (kernel2.req_from_arr)
    and packs responses+stats on-device (kernel2.pack_outputs) — one host→
    device put and ONE device→host fetch per mesh dispatch, however many
    shards (the per-column transfer layout cost 12 puts + 6 grid fetches
    per dispatch). All inputs are DONATED: the ingress grid's HBM frees at
    launch and the egress buffer (a previous dispatch's fetched output,
    ShardedEngine._take_egress) aliases this dispatch's output allocation.
    Write mode defaults to the backend's (block-sparse Pallas on TPU with
    per-shape sweep fallback, XLA scatter on CPU test meshes) and is
    overridable for parity tests; `math` picks the token-only or mixed
    decision graph (engine._math_mode); `dedup` aggregates duplicate keys
    in-trace (kernel2.decide2_packed_dedup_impl — duplicates share a
    fingerprint, so the host grid colocates them on the owning device);
    `wire` takes the compact 5-lane int32 ingress grid (trailing base
    column per device block) and returns int32 compact outputs — the
    decode/encode fuse into the kernel (ops/wire.py), so the narrow wire
    costs vector ops instead of 76 B/row of transport."""
    write = write or default_write_mode()

    def per_device(table: Table2, arr: jnp.ndarray, out_buf: jnp.ndarray):
        from gubernator_tpu.ops.wire import decode_wire_block, encode_wire_out

        table = jax.tree.map(lambda x: x[0], table)
        impl = decide2_packed_dedup_impl if dedup else decide2_packed_cols_impl
        if wire:
            arr12, base = decode_wire_block(arr[0])
            table, packed = impl(
                table, arr12, write=write, math=math, probe=probe
            )
            packed = encode_wire_out(packed, base)
        else:
            table, packed = impl(
                table, arr[0], write=write, math=math, probe=probe
            )
        expand = lambda t: jax.tree.map(lambda x: x[None], t)
        return expand(table), packed[None]

    spec = shard_spec(mesh)
    fn = shard_map_compat(
        per_device, mesh=mesh, in_specs=(spec, spec, spec),
        # check_vma=False: the Pallas sweep's out_shape carries no vma
        # annotation, which the checker (jax>=0.9) rejects inside shard_map
        out_specs=(spec, spec), check_vma=False
    )
    # keep_unused: out_buf exists only to donate its allocation into the
    # same-shape output (XLA aliases donated inputs to matching outputs);
    # jit would otherwise prune the unused arg and the aliasing with it.
    # Staging donation is TPU-only: XLA:CPU zero-copies host numpy buffers
    # into device arrays, and donating memory the process still owns
    # segfaults / corrupts advanced tables (CPU meshes donate the table
    # alone, the seed behavior).
    return jax.jit(fn, donate_argnums=_staging_donate(), keep_unused=True)


def make_sharded_install(mesh: Mesh, write: Optional[str] = None,
                         probe: str = "xla"):
    """All-shards install step for owner-authoritative GLOBAL statuses —
    the UpdatePeerGlobals receive path on a sharded daemon. `probe`
    (static) selects the per-shard table walk — the two-pass gather +
    write or the fused Pallas walk (GUBER_WALK_KERNEL); like decide, the
    megakernel composes with shard_map for free because it runs per
    device shard."""
    write = write or default_write_mode()

    def per_device(table: Table2, inst: InstallBatch):
        table = jax.tree.map(lambda x: x[0], table)
        inst = jax.tree.map(lambda x: x[0], inst)
        table, installed = install2_impl(table, inst, write=write,
                                         probe=probe)
        expand = lambda t: jax.tree.map(lambda x: x[None], t)
        return expand(table), expand(installed)

    spec = shard_spec(mesh)
    fn = shard_map_compat(
        per_device, mesh=mesh, in_specs=(spec, spec),
        # check_vma=False: the Pallas sweep's out_shape carries no vma
        # annotation, which the checker (jax>=0.9) rejects inside shard_map
        out_specs=(spec, spec), check_vma=False
    )
    return jax.jit(fn, donate_argnums=(0,))


def make_sharded_merge(mesh: Mesh, write: Optional[str] = None,
                       evictees: bool = False, probe: str = "xla"):
    """All-shards conservative-merge step (kernel2.merge2_impl) — the
    TransferState receive path on a sharded daemon: transferred slot rows
    are routed to their owning shard and merged with remaining=min /
    expiry=max / newest-config-wins semantics per device. `evictees=True`
    (the tiering promote path) additionally yields each shard's displaced
    live rows as canonical (b, 16) grids."""
    write = write or default_write_mode()

    def per_device(table: Table2, fp, slots, now, active):
        from gubernator_tpu.ops.kernel2 import merge2_impl

        table = jax.tree.map(lambda x: x[0], table)
        expand = lambda t: jax.tree.map(lambda x: x[None], t)
        if evictees:
            table, merged, ev = merge2_impl(
                table, fp[0], slots[0], now[0], active[0], write=write,
                evictees=True, probe=probe,
            )
            return expand(table), expand(merged), expand(ev)
        table, merged = merge2_impl(
            table, fp[0], slots[0], now[0], active[0], write=write,
            probe=probe,
        )
        return expand(table), expand(merged)

    spec = shard_spec(mesh)
    n_out = 3 if evictees else 2
    fn = shard_map_compat(
        per_device, mesh=mesh, in_specs=(spec, spec, spec, spec, spec),
        out_specs=(spec,) * n_out, check_vma=False
    )
    return jax.jit(fn, donate_argnums=(0,))


def make_sharded_extract_dirty(mesh: Mesh, blk: int, layout=None):
    """All-shards dirty-block extract step (incremental checkpointing,
    ops/checkpoint.py): each device gathers ITS dirty blocks' bucket rows,
    filters live slots and packs them to the front — no slot row ever
    crosses a device boundary; the host fetches only per-shard live
    prefixes (ShardedEngine.checkpoint_finish). `bidx` is a (D, G) grid of
    per-shard LOCAL block ids padded with the out-of-range sentinel
    nblk_local (jnp.take mode="fill" zero-fills, and fp == 0 rows are
    never live)."""

    def per_device(rows, bidx, now):
        from gubernator_tpu.ops.checkpoint import _extract_blocks_core

        slots, fp, cnt = _extract_blocks_core(
            rows[0], bidx[0], now[0], blk, layout
        )
        return slots[None], fp[None], cnt[None]

    spec = shard_spec(mesh)
    fn = shard_map_compat(
        per_device, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(spec, spec, spec), check_vma=False
    )
    return jax.jit(fn)


def make_sharded_extract_idle(mesh: Mesh, layout=None):
    """All-shards idle-row extract step (hot-set tiering,
    gubernator_tpu/tier/): each device filters ITS shard's live slots
    whose last-activity reference is idle past the horizon and packs them
    to the front (table2._extract_idle_core) — no slot row crosses a
    device boundary; the host fetches only per-shard idle prefixes
    (ShardedEngine.extract_idle)."""

    def per_device(rows, now, idle):
        from gubernator_tpu.ops.table2 import _extract_idle_core

        slots, fp, cnt = _extract_idle_core(
            rows[0], now[0], idle[0], layout
        )
        return slots[None], fp[None], cnt[None]

    spec = shard_spec(mesh)
    fn = shard_map_compat(
        per_device, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(spec, spec, spec), check_vma=False
    )
    return jax.jit(fn)


def make_sharded_gather(mesh: Mesh, layout=None):
    """All-shards stored-state read (table2.gather_slots_impl): full-width
    slots for routed fingerprints, no mutation (nothing donated)."""

    def per_device(rows, fp, active):
        from gubernator_tpu.ops.table2 import gather_slots_impl

        slots, found = gather_slots_impl(rows[0], fp[0], active[0], layout)
        return slots[None], found[None]

    spec = shard_spec(mesh)
    fn = shard_map_compat(
        per_device, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(spec, spec), check_vma=False
    )
    return jax.jit(fn)


def make_sharded_tombstone(mesh: Mesh):
    """All-shards tombstone step (table2.tombstone_rows_impl): zero the
    slots holding acked handed-off fingerprints, routed per owning shard."""

    def per_device(table: Table2, fp, active):
        from gubernator_tpu.ops.table2 import tombstone_rows_impl

        rows = table.rows[0]
        rows, found = tombstone_rows_impl(rows, fp[0], active[0])
        return Table2(rows=rows[None], layout=table.layout), found[None]

    spec = shard_spec(mesh)
    fn = shard_map_compat(
        per_device, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(spec, spec), check_vma=False
    )
    return jax.jit(fn, donate_argnums=(0,))


class _StagingPool:
    """Ring of persistent host-side staging buffers, keyed by shape.

    Per-dispatch ingress staging used to allocate (and zero) a fresh
    (D, 12, b) grid — 12+ MB of alloc + fault-in on every 131K-row mesh
    dispatch. The pool hands out the same `depth` buffers round-robin per
    shape instead: pages stay warm, the allocator never churns, and callers
    only rewrite the bytes the batch actually covers. `depth` must cover
    the pipeline's in-flight bound (a buffer is only rewritten after the
    dispatch that device_put it has been issued `depth` dispatches ago —
    the same staging-lifetime assumption the runner's double-buffered
    prepare/issue/finish split already makes)."""

    def __init__(self, depth: int = 6):
        self.depth = depth
        self._rings: Dict[tuple, list] = {}
        self._lock = threading.Lock()  # stage_pass runs on concurrent prep threads

    def get(self, shape: tuple, zero: bool = False, dtype=np.int64) -> np.ndarray:
        key = (shape, np.dtype(dtype).str)
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                ring = self._rings[key] = [[], 0]
            bufs, idx = ring
            if len(bufs) < self.depth:
                buf = np.zeros(shape, dtype=dtype)  # fresh → already zero
                bufs.append(buf)
                return buf
            ring[1] = idx + 1
            buf = bufs[idx % self.depth]
        if zero:
            buf.fill(0)
        return buf


def new_sharded_table(mesh: Mesh, capacity_per_shard: int, layout=None) -> Table2:
    """A (D, n_buckets, ROW_layout) packed-row table placed shard-per-device
    (the slot layout travels as Table2 pytree aux through every tree.map)."""
    D = mesh.devices.size
    local = new_table2(capacity_per_shard, layout=layout)
    stacked = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (D,) + x.shape), local)
    sharding = NamedSharding(mesh, shard_spec(mesh))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), stacked)


class ShardedEngine:
    """Multi-device analog of LocalEngine: one table shard per mesh device.

    Host-side routing (fingerprint → shard) replaces the reference's
    GetPeer/asyncRequest forwarding (gubernator.go:243-263); since every shard
    participates in every dispatch, "forwarding" costs nothing extra.
    """

    def __init__(
        self,
        mesh: Mesh,
        capacity_per_shard: int = 50_000,
        max_exact_passes: int = 8,
        created_at_tolerance_ms=None,
        store=None,
        route: Optional[str] = None,
        write_mode: Optional[str] = None,
        dedup: Optional[str] = None,
        wire: Optional[str] = None,
        a2a: Optional[str] = None,
        layout: Optional[str] = None,
        probe: Optional[str] = None,
        walk: Optional[str] = None,
    ):
        from gubernator_tpu.ops.layout import resolve_layout
        from gubernator_tpu.ops.plan import (
            default_probe_kernel,
            default_walk_kernel,
        )
        from gubernator_tpu.ops.wire import default_wire_mode
        from gubernator_tpu.parallel.ring import a2a_impl

        route = route or default_shard_route()
        if route not in ("host", "device"):
            raise ValueError(f"route must be 'host' or 'device', got {route!r}")
        dedup = dedup or default_shard_dedup()
        if dedup not in ("host", "device"):
            raise ValueError(f"dedup must be 'host' or 'device', got {dedup!r}")
        if wire is not None and wire not in ("compact", "full"):
            raise ValueError(f"wire must be 'compact' or 'full', got {wire!r}")
        self.mesh = mesh
        # per-engine clock-skew bound; None = the ops.batch process default
        self.created_at_tolerance_ms = created_at_tolerance_ms
        self.n_shards = int(mesh.devices.size)
        # pod topology: host rows × devices per host (1 × D on single-host
        # meshes) — introspection for the debug plane and the bench; the
        # shard id ↔ (host, device) mapping itself is mesh.py's host-major
        # linearization, so no routing code below reads these
        self.n_hosts = mesh_hosts(mesh)
        self.devices_per_host = devices_per_host(mesh)
        # ownership-exchange schedule for route="device" dispatches
        # (parallel/ring.py): "ring" | "collective", resolved once from the
        # override / GUBER_A2A_IMPL / backend auto rule
        self.a2a_impl = a2a_impl(a2a)
        # slot layout (ops/layout.py): full by default, packed 32 B rows
        # for single-algorithm fleets (GUBER_SLOT_LAYOUT / layout=); off-
        # family traffic migrates the shards to full in place
        self._layout = resolve_layout(layout)
        self.table = new_sharded_table(
            mesh, capacity_per_shard, layout=self._layout
        )
        # routing mode: "host" sorts rows into an ownership grid on the host;
        # "device" ships arrival-order rows and routes on-mesh with an
        # all_to_all exchange (parallel/a2a.py) — zero host routing work,
        # the multi-host-scale path (default on TPU backends)
        self.route = route
        # dedup mode: where the kernel's unique-fingerprint contract is
        # discharged — "host" = plan_passes group-by (exact sequential
        # same-key semantics), "device" = in-trace aggregation + O(1) host
        # planning (module docstring; default on TPU backends)
        self.dedup = dedup
        # one write mode for every mesh step (decide, install, GLOBAL sync);
        # None = the backend default (kernel2.resolve_write still falls the
        # sparse mode back to the full sweep per dispatch shape)
        self.write_mode = write_mode or default_write_mode()
        # table-walk kernel for decide dispatches (GUBER_PROBE_KERNEL):
        # the per-shard programs thread it into decide2_* unchanged — the
        # PR-8 shard_map mesh path composes with the Pallas megakernel for
        # free because the kernel runs per device shard inside shard_map
        if probe is not None and probe not in ("xla", "pallas"):
            raise ValueError(f"probe must be 'xla' or 'pallas', got {probe!r}")
        self.probe_mode = probe or default_probe_kernel()
        # table-walk kernel for the install/merge walks (GUBER_WALK_KERNEL):
        # threaded into the per-shard install/merge programs exactly like
        # probe_mode into decide — the walks run per device shard inside
        # shard_map, so the fused megakernel composes for free
        if walk is not None and walk not in ("xla", "pallas"):
            raise ValueError(f"walk must be 'xla' or 'pallas', got {walk!r}")
        self.walk_mode = walk or default_walk_kernel()
        # host↔device wire format for decide dispatches and the GLOBAL sync
        # outbox: "compact" ships 5-lane int32 ingress grids + int32 egress
        # (ops/wire.py — the TPU default, GUBER_WIRE_COMPACT), "full" the
        # 12-lane int64 grids (the parity oracle). Per-dispatch
        # encodability still falls compact batches back to full-width.
        self.wire = wire or default_wire_mode()
        self._decide_fns = {}  # (kind, …, math) → jitted mesh step (lazy)
        self._install = make_sharded_install(
            mesh, write=self.write_mode, probe=self.walk_mode
        )
        # handoff mesh steps, built lazily (most engines never rebalance)
        self._merge_fn = None
        self._tombstone_fn = None
        # incremental-checkpoint plane (ops/checkpoint.py): epoch tracker
        # attached by the daemon's CheckpointManager (None = zero marking
        # cost), per-shard extract step built lazily on first checkpoint
        self.ckpt = None
        self._extract_dirty_fn = None
        # hot-set tiering (gubernator_tpu/tier/): host-RAM shadow attached
        # by the daemon's TierManager. Mesh engines participate through
        # the idle sweep (extract_idle below) and the fault-back merge;
        # the per-request evictee sidecar is a single-device surface today
        # (the routed per-shard programs don't thread the flag — demote-
        # on-evict on meshes is a documented follow-up, docs/tiering.md)
        self.shadow = None
        self._extract_idle_fn = None
        self._batch_sharding = NamedSharding(mesh, shard_spec(mesh))
        self.max_exact_passes = max_exact_passes
        self.store = store  # write-through hook (gubernator_tpu.store.Store)
        self.stats = EngineStats()
        # persistent ingress staging (module docstring). CPU backends MUST
        # NOT pool: XLA:CPU zero-copies an aligned numpy buffer into the
        # device array, so with donation the advanced TABLE can end up
        # aliased into pool memory a later dispatch rewrites (observed as
        # corrupted remaining counts on the 8-device test mesh). TPU
        # host→HBM transfers always copy, which is what makes buffer reuse
        # sound there — exactly where the alloc+zero cost matters.
        self._pool: Optional[_StagingPool] = (
            _StagingPool() if jax.default_backend() != "cpu" else None
        )
        # recycled egress buffers per output shape: finish hands fetched
        # output arrays back, _take_egress donates them into the next
        # same-shape dispatch where XLA aliases the output allocation
        self._egress: Dict[tuple, list] = {}
        self._egress_lock = threading.Lock()
        # host-staging cost accounting (the bench's host-stage/device split
        # and the shard_*/wire_* stage_duration series): cumulative ms per
        # stage — wire_pack is the compact encode, wire_decode the compact
        # egress decode (both 0 on full-width dispatches)
        self.stage_ms = {
            "route": 0.0, "pack": 0.0, "put": 0.0,
            "wire_pack": 0.0, "wire_decode": 0.0,
        }
        self.stage_dispatches = 0
        self._stage_taken = dict(self.stage_ms)
        self._stage_lock = threading.Lock()
        # bytes actually crossing the host↔device boundary on the decide
        # path (the gubernator_tpu_wire_bytes_total series): ingress grid
        # nbytes at stage time, fetched output nbytes at finish time —
        # counted whichever wire format ran, so bytes/decision is
        # scrapeable rather than bench-computed
        self.wire_bytes = {"put": 0, "fetch": 0}
        self._wire_taken = dict(self.wire_bytes)
        # rows the a2a exchange capacity-dropped before they reached the
        # kernel (FLAG_UNPROCESSED on a device-routed dispatch) — the
        # per-engine source of gubernator_tpu_a2a_overflow_total{impl}.
        # Counted at every depth: a row that overflows twice was twice a
        # symptom of undersized pair capacity (GUBER_A2A_CAPACITY_SIGMA)
        self.a2a_overflow = 0
        self._a2a_overflow_taken = 0
        # per-shard ingress transfers issued concurrently (TPU: each
        # device_put is a serialized round trip on tunneled transports;
        # overlapping them makes the put cost max-of-shards, not
        # sum-of-shards). CPU keeps the single zero-copy put.
        self._put_pool: Optional[ThreadPoolExecutor] = None
        put_env = os.environ.get("GUBER_SHARD_PUT", "auto")
        if put_env not in ("auto", "single", "concurrent"):
            raise ValueError(
                f"GUBER_SHARD_PUT must be auto, single or concurrent, "
                f"got {put_env!r}"
            )
        self._put_concurrent = (
            put_env == "concurrent"
            or (put_env == "auto" and jax.default_backend() == "tpu")
        ) and self.n_shards > 1
        # set (with a reason) when a donated collective launch failed after
        # state was popped/donated: the tables may be poisoned, serving must
        # surface unhealthy (daemon health_check reads this)
        self.poisoned: Optional[str] = None

    def check(
        self,
        requests: Sequence[RateLimitRequest],
        now_ms: Optional[int] = None,
    ) -> List[RateLimitResponse]:
        """Object-API wrapper over the columns fast path (same shape as
        LocalEngine.check) so the Store write-through/rehydrate contract
        holds on BOTH serving surfaces."""
        if not requests:
            return []
        from gubernator_tpu.ops.batch import columns_from_requests

        cols = columns_from_requests(requests)
        rc = self.check_columns(cols, now_ms=now_ms)
        return [
            RateLimitResponse(
                status=int(rc.status[i]),
                limit=int(rc.limit[i]),
                remaining=int(rc.remaining[i]),
                reset_time=int(rc.reset_time[i]),
                error=ERROR_STRINGS[int(rc.err[i])],
            )
            for i in range(len(requests))
        ]

    # ----------------------------------------------- daemon serving surface
    # The same columns-in/columns-out API as LocalEngine, so the daemon's
    # Batcher/EngineRunner serve a whole mesh through one engine object
    # (GUBER_ENGINE=sharded).

    def check_columns(
        self, cols: RequestColumns, now_ms: Optional[int] = None
    ) -> ResponseColumns:
        from gubernator_tpu.ops.engine import serve_columns

        def dispatch(pass_batch, n_rows: int, cascade: bool = False):
            # mesh programs never fold cascades in-trace (routed/exchanged
            # row order breaks carrier adjacency); serve_columns' host fold
            # computes the combined verdicts instead
            _, vals = self._dispatch(pass_batch)
            return vals

        return serve_columns(self, cols, now_ms, dispatch)

    def plan(self, hb: HostBatch):
        """Pass plan for one packed batch (serve_columns/prepare hook):
        O(1) when duplicates aggregate in-trace, the host group-by planner
        otherwise (exact sequential same-key semantics — fallback/oracle)."""
        if self.dedup == "device":
            return single_pass(hb)
        return plan_passes(hb, max_exact=self.max_exact_passes)

    def _mark_dirty(self, fps) -> None:
        """Checkpoint hook: record touched fingerprints' (shard, block)
        pairs in the epoch tracker — engine thread, same job as the
        mutation (ops/checkpoint.py ordering contract)."""
        if self.ckpt is not None:
            self.ckpt.mark(np.asarray(fps))

    # -------------------------------------------- staging cost accounting

    def _stage_time(self, key: str, dt_s: float) -> None:
        with self._stage_lock:
            self.stage_ms[key] += dt_s * 1e3

    def take_stage_deltas(self) -> Dict[str, float]:
        """Host-staging ms per stage since the last take (EngineRunner
        feeds these into the shard_*/wire_* stage_duration series)."""
        with self._stage_lock:
            d = {
                k: self.stage_ms[k] - self._stage_taken[k]
                for k in self.stage_ms
            }
            self._stage_taken = dict(self.stage_ms)
        return d

    def _wire_count(self, direction: str, nbytes: int) -> None:
        with self._stage_lock:
            self.wire_bytes[direction] += int(nbytes)

    def take_wire_deltas(self) -> Dict[str, int]:
        """Bytes over the host↔device boundary per direction since the
        last take (EngineRunner feeds the wire_bytes_total counter)."""
        with self._stage_lock:
            d = {
                k: self.wire_bytes[k] - self._wire_taken[k]
                for k in self.wire_bytes
            }
            self._wire_taken = dict(self.wire_bytes)
        return d

    def take_a2a_overflow_delta(self) -> "tuple[str, int]":
        """(exchange impl, overflow rows since the last take) —
        EngineRunner feeds gubernator_tpu_a2a_overflow_total{impl} so
        capacity pressure is scrapeable instead of test-only."""
        with self._stage_lock:
            d = self.a2a_overflow - self._a2a_overflow_taken
            self._a2a_overflow_taken = self.a2a_overflow
        return self.a2a_impl, d

    # ------------------------------------------------ egress buffer recycling

    def _take_egress(self, shape: tuple, dtype=np.int64):
        """A donated egress buffer for one mesh dispatch: a previously
        fetched output array of the same shape/dtype when one is banked
        (its allocation will alias the new output), else a fresh zeroed
        grid (first dispatches of a shape, before the ring primes). Keyed
        by dtype too: compact-wire dispatches fetch int32 grids and full-
        width ones int64, and XLA only aliases exact matches."""
        key = (shape, np.dtype(dtype).str)
        with self._egress_lock:
            bank = self._egress.get(key)
            if bank:
                return bank.pop()
        return jax.device_put(
            np.zeros(shape, dtype=dtype), self._batch_sharding
        )

    def _recycle_egress(self, out) -> None:
        """Bank a fetched output array for reuse as a donated egress buffer.
        Fused multi-pass fetches hand finish_staged a numpy slice instead of
        the device array (engine._stack_pass_outputs) — nothing to bank."""
        if isinstance(out, np.ndarray):
            return
        with self._egress_lock:
            bank = self._egress.setdefault((out.shape, out.dtype.str), [])
            if len(bank) < 8:
                bank.append(out)

    def install_columns(
        self,
        fp: np.ndarray,
        algo: np.ndarray,
        status: np.ndarray,
        limit: np.ndarray,
        remaining: np.ndarray,
        reset_time: np.ndarray,
        duration: np.ndarray,
        now_ms: Optional[int] = None,
        burst: Optional[np.ndarray] = None,
        stamp: Optional[np.ndarray] = None,
        aux: Optional[np.ndarray] = None,
        rem_store: Optional[np.ndarray] = None,
    ) -> int:
        """Install owner-authoritative GLOBAL statuses, routed to each
        fingerprint's owning shard (UpdatePeerGlobals receive path).
        `burst`/`stamp` default to the wire path's lossy rebuild;
        `aux`/`rem_store` carry sliding-window broadcast fidelity (cf.
        LocalEngine.install_columns)."""
        now = now_ms if now_ms is not None else ms_now()
        n = fp.shape[0]
        if n == 0:
            return 0
        if burst is None:
            burst = np.asarray(limit, dtype=np.int64)
        if stamp is None:
            stamp = np.full(n, now, dtype=np.int64)
        if not self.table.layout.supports_algos(algo):
            self.migrate_layout_full("install of off-family algorithms")
        self._mark_dirty(fp)
        D = self.n_shards
        routed = shard_of(fp, D)
        order, rs, offset, b_local = _route_plan(routed, D)

        def grid(field, dtype):
            return jnp.asarray(
                _to_grid(field[order].astype(dtype), rs, offset, D, b_local)
            )

        inst = InstallBatch(
            fp=grid(fp, np.int64),
            algo=grid(algo, np.int32),
            status=grid(status, np.int32),
            limit=grid(limit, np.int64),
            remaining=grid(remaining, np.int64),
            reset_time=grid(reset_time, np.int64),
            duration=grid(duration, np.int64),
            now=grid(np.full(n, now, dtype=np.int64), np.int64),
            active=grid(np.ones(n, dtype=bool), bool),
            burst=grid(burst, np.int64),
            stamp=grid(stamp, np.int64),
            aux=None if aux is None else grid(aux, np.int64),
            rem_store=(
                None if rem_store is None else grid(rem_store, np.int64)
            ),
        )
        inst = jax.tree.map(
            lambda x: jax.device_put(x, self._batch_sharding), inst
        )
        self.table, installed = self._install(self.table, inst)
        self.stats.dispatches += 1
        return int(np.asarray(installed).sum())

    # ------------------------------------------------- maintenance surface

    def snapshot(self) -> np.ndarray:
        """(D, NB, 128) device→host copy of every shard (Loader.Save analog)."""
        return np.asarray(self.table.rows)

    def restore(self, rows: np.ndarray, layout=None) -> None:
        lay = self.table.layout
        if layout is not None and layout is not lay:
            if rows.shape[:-1] != tuple(self.table.rows.shape[:-1]):
                raise ValueError(
                    f"snapshot geometry {rows.shape} incompatible with "
                    f"table {tuple(self.table.rows.shape)}"
                )
            rows = np.asarray(lay.pack_rows(layout.unpack_rows(rows)))
        if rows.shape != tuple(self.table.rows.shape):
            raise ValueError(
                f"snapshot shape {rows.shape} != table {tuple(self.table.rows.shape)}"
            )
        sharding = NamedSharding(self.mesh, shard_spec(self.mesh))
        self.table = Table2(
            rows=jax.device_put(jnp.asarray(rows, dtype=jnp.int32), sharding),
            layout=lay,
        )
        if self.ckpt is not None:
            # mid-life restore: state of unknown provenance — next delta
            # epoch captures the whole live set (cf. LocalEngine.restore)
            self.ckpt.mark_all()

    def live_count(self, now_ms: Optional[int] = None) -> int:
        from gubernator_tpu.ops.table2 import live_count2

        # live_count2 reshapes (-1, K, F), so the leading shard axis folds in
        return live_count2(self.table, now_ms if now_ms is not None else ms_now())

    # ----------------------------------------------------------- handoff
    # Same surface as LocalEngine (extract_live / merge_rows /
    # tombstone_fps): the mesh pays for the full-table partition pass, the
    # host stages only the transferred rows — batch-proportional, like the
    # install path.

    def extract_live(self, now_ms: Optional[int] = None):
        from gubernator_tpu.ops.table2 import extract_live_rows

        now = now_ms if now_ms is not None else ms_now()
        return extract_live_rows(
            self.table.rows, now, layout=self.table.layout
        )

    def _slots_to_full(self, slots: np.ndarray, layout=None) -> np.ndarray:
        """Normalize incoming slot rows to the canonical full layout (cf.
        LocalEngine._slots_to_full — same inference rules)."""
        from gubernator_tpu.ops import layout as layout_mod

        if layout is None:
            if slots.shape[1] == layout_mod.FULL.F:
                layout = layout_mod.FULL
            elif slots.shape[1] == self.table.layout.F:
                layout = self.table.layout
            else:
                raise ValueError(
                    f"cannot infer slot layout for width {slots.shape[1]}"
                )
        return np.asarray(layout.unpack(slots))

    def merge_rows(
        self, fps: np.ndarray, slots: np.ndarray,
        now_ms: Optional[int] = None, layout=None, collect: bool = False,
    ):
        n = fps.shape[0]
        if n == 0:
            if collect:
                return 0, np.zeros(0, dtype=bool), np.empty(
                    0, dtype=np.int64
                ), np.empty((0, 16), dtype=np.int32)
            return 0
        from gubernator_tpu.ops.engine import _occurrence_rank
        from gubernator_tpu.ops.table2 import FLAGS

        slots = self._slots_to_full(slots, layout)
        rank = _occurrence_rank(fps)
        if rank.max() > 0:  # unique-fp contract (cf. LocalEngine.merge_rows)
            if collect:
                raise ValueError(
                    "merge_rows(collect=True) requires unique fingerprints"
                )
            return sum(
                self.merge_rows(fps[rank == r], slots[rank == r], now_ms)
                for r in range(int(rank.max()) + 1)
            )
        if not self.table.layout.supports_algos(slots[:, FLAGS] & 0xFF):
            self.migrate_layout_full("merge of off-family rows")
        now = now_ms if now_ms is not None else ms_now()
        self._mark_dirty(fps)
        D = self.n_shards
        routed = shard_of(fps, D)
        order, rs, offset, b_local = _route_plan(routed, D)
        fp_g = _to_grid(fps[order].astype(np.int64), rs, offset, D, b_local)
        now_g = np.full((D, b_local), now, dtype=np.int64)
        act_g = _to_grid(np.ones(n, dtype=bool), rs, offset, D, b_local)
        slots_g = np.zeros((D, b_local, slots.shape[1]), dtype=np.int32)
        slots_g[rs, offset] = slots[order]
        put = lambda x: jax.device_put(x, self._batch_sharding)
        if collect:
            fn = getattr(self, "_merge_ev_fn", None)
            if fn is None:
                fn = self._merge_ev_fn = make_sharded_merge(
                    self.mesh, write=self.write_mode, evictees=True,
                    probe=self.walk_mode,
                )
            self.table, merged, ev = fn(
                self.table, put(fp_g), put(slots_g), put(now_g), put(act_g)
            )
            self.stats.dispatches += 1
            merged_h = np.asarray(merged)
            mask = np.zeros(n, dtype=bool)
            mask[order] = merged_h[rs, offset]
            ev_h = np.asarray(ev).reshape(-1, 16)
            ev_lo = ev_h[:, 0].astype(np.int64) & 0xFFFFFFFF
            ev_fp = (ev_h[:, 1].astype(np.int64) << 32) | ev_lo
            keep = ev_fp != 0
            return int(mask.sum()), mask, ev_fp[keep], ev_h[keep].copy()
        if self._merge_fn is None:
            self._merge_fn = make_sharded_merge(
                self.mesh, write=self.write_mode, probe=self.walk_mode
            )
        self.table, merged = self._merge_fn(
            self.table, put(fp_g), put(slots_g), put(now_g), put(act_g)
        )
        self.stats.dispatches += 1
        return int(np.asarray(merged).sum())

    def read_state(self, fps: np.ndarray, raw: bool = False):
        """(found, full-width slots) for `fps` — the ShardedEngine analog
        of LocalEngine.read_state (routed shard_map gather, no mutation).
        `raw=True` re-packs the gathered rows into the table's own slot
        layout (the region-sync staging form, cf. LocalEngine)."""
        from gubernator_tpu.ops.table2 import F as F_FULL

        n = fps.shape[0]
        if n == 0:
            width = self.table.layout.F if raw else F_FULL
            return (
                np.zeros(0, dtype=bool), np.zeros((0, width), dtype=np.int32)
            )
        D = self.n_shards
        routed = shard_of(fps, D)
        order, rs, offset, b_local = _route_plan(routed, D)
        fp_g = _to_grid(fps[order].astype(np.int64), rs, offset, D, b_local)
        act_g = _to_grid(np.ones(n, dtype=bool), rs, offset, D, b_local)
        fn = getattr(self, "_gather_fn", None)
        if fn is None or getattr(self, "_gather_layout", None) is not (
            self.table.layout
        ):
            fn = self._gather_fn = make_sharded_gather(
                self.mesh, layout=self.table.layout
            )
            self._gather_layout = self.table.layout
        put = lambda x: jax.device_put(x, self._batch_sharding)
        slots_g, found_g = fn(self.table.rows, put(fp_g), put(act_g))
        slots_h = np.asarray(slots_g)
        found_h = np.asarray(found_g)
        slots = np.zeros((n, F_FULL), dtype=np.int32)
        found = np.zeros(n, dtype=bool)
        slots[order] = slots_h[rs, offset]
        found[order] = found_h[rs, offset]
        if raw:
            slots = np.asarray(self.table.layout.pack(slots))
        return found, slots

    def tombstone_fps(self, fps: np.ndarray) -> int:
        n = fps.shape[0]
        if n == 0:
            return 0
        self._mark_dirty(fps)
        D = self.n_shards
        routed = shard_of(fps, D)
        order, rs, offset, b_local = _route_plan(routed, D)
        fp_g = _to_grid(fps[order].astype(np.int64), rs, offset, D, b_local)
        act_g = _to_grid(np.ones(n, dtype=bool), rs, offset, D, b_local)
        put = lambda x: jax.device_put(x, self._batch_sharding)
        if self._tombstone_fn is None:
            self._tombstone_fn = make_sharded_tombstone(self.mesh)
        self.table, found = self._tombstone_fn(self.table, put(fp_g), put(act_g))
        self.stats.dispatches += 1
        return int(np.asarray(found).sum())

    # ------------------------------------------------------- checkpointing
    # Same begin/finish split as LocalEngine (launch on the engine thread,
    # fetch off it), but the extract runs PER SHARD under shard_map so no
    # slot row crosses a device boundary; the tracker's global block ids
    # (shard-major: gid = shard · nblk_local + local_block) regroup into a
    # per-shard local-block grid here.

    def checkpoint_begin(self, gids: np.ndarray, now_ms: Optional[int] = None):
        now = now_ms if now_ms is not None else ms_now()
        blk, nblk = self.ckpt.blk, self.ckpt.nblk
        D = self.n_shards
        shard = gids // nblk
        local = gids % nblk
        counts = np.bincount(shard, minlength=D)
        G = _pad_size(int(max(counts.max(), 1)), floor=8)
        bidx = np.full((D, G), nblk, dtype=np.int64)  # sentinel: zero-fill
        order = np.argsort(shard, kind="stable")
        rs = shard[order]
        offset = np.arange(gids.shape[0]) - np.searchsorted(rs, rs)
        bidx[rs, offset] = local[order]
        if self._extract_dirty_fn is None:
            self._extract_dirty_fn = make_sharded_extract_dirty(
                self.mesh, blk, layout=self.table.layout
            )
        put = lambda x: jax.device_put(x, self._batch_sharding)
        return self._extract_dirty_fn(
            self.table.rows, put(bidx),
            put(np.full(D, now, dtype=np.int64)),
        )

    def checkpoint_finish(self, pending):
        """Fetch per-shard live prefixes (pow2-padded — the
        extract_live_rows fetch rule, per shard) and concatenate."""
        F = self.table.layout.F

        slots_g, fp_g, cnt_g = pending
        counts = np.asarray(cnt_g)
        width = int(fp_g.shape[1])
        fps_l, slots_l = [], []
        for d in range(self.n_shards):
            n = int(counts[d])
            if n == 0:
                continue
            pad = 256
            while pad < n:
                pad *= 2
            pad = min(pad, width)
            fps_l.append(np.asarray(fp_g[d, :pad])[:n])
            slots_l.append(np.asarray(slots_g[d, :pad])[:n])
        if not fps_l:
            return (
                np.empty(0, dtype=np.int64),
                np.empty((0, F), dtype=np.int32),
            )
        return np.concatenate(fps_l), np.concatenate(slots_l)

    # ------------------------------------------------------------- tiering

    def extract_idle(self, now_ms: int, idle_ms: int,
                     max_rows: int = 1 << 16):
        """Live rows idle past `idle_ms` across every shard: (fps (N,)
        i64, slots (N, F_layout) i32), N ≤ max_rows. The filter + pack
        runs PER SHARD under shard_map (make_sharded_extract_idle — no
        slot row crosses a device boundary); the host fetches only
        per-shard idle prefixes, the checkpoint_finish fetch rule. The
        cap slices shard-major — the remainder stays for the next
        sweep."""
        fn = self._extract_idle_fn
        if fn is None or getattr(self, "_extract_idle_layout", None) is not (
            self.table.layout
        ):
            fn = self._extract_idle_fn = make_sharded_extract_idle(
                self.mesh, layout=self.table.layout
            )
            self._extract_idle_layout = self.table.layout
        D = self.n_shards
        put = lambda x: jax.device_put(x, self._batch_sharding)
        slots_g, fp_g, cnt_g = fn(
            self.table.rows,
            put(np.full(D, now_ms, dtype=np.int64)),
            put(np.full(D, idle_ms, dtype=np.int64)),
        )
        counts = np.asarray(cnt_g)
        width = int(fp_g.shape[1])
        F_l = self.table.layout.F
        fps_l, slots_l = [], []
        left = int(max_rows)
        for d in range(D):
            n = min(int(counts[d]), left)
            if n <= 0:
                continue
            pad = 256
            while pad < n:
                pad *= 2
            pad = min(pad, width)
            fps_l.append(np.asarray(fp_g[d, :pad])[:n].copy())
            slots_l.append(np.asarray(slots_g[d, :pad])[:n].copy())
            left -= n
        if not fps_l:
            return (
                np.empty(0, dtype=np.int64),
                np.empty((0, F_l), dtype=np.int32),
            )
        return np.concatenate(fps_l), np.concatenate(slots_l)

    # ----------------------------------------------------------- telemetry

    def telemetry_begin(self, now_ms: Optional[int] = None):
        """Launch the per-shard telemetry scan (parallel/telemetry.py)
        without fetching; additionally yields per-shard live counts so hot
        shards are observable (cf. LocalEngine.telemetry_begin)."""
        from gubernator_tpu.parallel.telemetry import sharded_scan_begin

        return sharded_scan_begin(
            self, now_ms if now_ms is not None else ms_now()
        )

    supports_grow = False  # the daemon must not start an auto-grow loop

    def maybe_grow(self, **kw) -> bool:
        """Sharded tables are sized at mesh construction; growth means a mesh
        re-plan (host-orchestrated, like the reference's fixed CacheSize per
        node). Not auto-grown."""
        return False

    # ------------------------------------------------------ pipelined surface
    # The same prepare/issue/finish protocol as LocalEngine (ops/engine.py):
    # stage_pass routes + packs + stages the ingress grid on ANY thread,
    # issue_staged advances the sharded table on the engine thread without
    # fetching, finish_staged materializes the ONE packed output grid on a
    # fetch thread — so a sharded daemon's front door overlaps host routing
    # of dispatch N+1 with mesh execution of N exactly like the local one.

    supports_pipeline = True

    def stage_pass(self, pass_batch: HostBatch, n: int, cascade: bool = False):
        """(padded batch, staged route) for one unique-fp pass. No row
        padding is needed: the compiled shape depends only on the pow2
        per-shard width b_local, not on n. `cascade` is accepted for
        protocol parity and ignored — mesh programs rely on the host-side
        verdict fold (engine._fold_cascades_host)."""
        staged = self._stage(pass_batch, None)
        return pass_batch, staged

    def migrate_layout_full(self, reason: str = "off-family traffic") -> bool:
        """Migrate the authoritative shards to the canonical full layout in
        place (engine thread only; cf. LocalEngine.migrate_layout_full).
        One jitted per-shard row unpack — the shard axis is untouched, so
        the sharding survives the conversion."""
        from gubernator_tpu.ops.layout import FULL

        if self.table.layout is FULL:
            return False
        self.table = self._table_to_full(self.table)
        self._layout = FULL
        return True

    def _table_to_full(self, table: Table2) -> Table2:
        from gubernator_tpu.ops.layout import FULL

        import logging

        logging.getLogger("gubernator_tpu.engine").warning(
            "migrating sharded table layout %s -> full", table.layout.name
        )
        rows_full = jax.jit(table.layout.unpack_rows)(table.rows)
        rows_full = jax.device_put(rows_full, self._batch_sharding)
        self.stats.layout_migrations += 1
        return Table2(rows=rows_full, layout=FULL)

    def _decide(self, table: Table2, staged):
        from gubernator_tpu.ops.layout import FULL

        if getattr(staged, "needs_full", False) and table.layout is not FULL:
            # engine thread (_decide only runs from issue/dispatch): convert
            # whichever table this dispatch targets before launching
            table = self._table_to_full(table)
        dedup = self.dedup == "device"
        if isinstance(staged, _StagedA2A):
            from gubernator_tpu.parallel.a2a import make_a2a_decide

            key = ("a2a", staged.c, staged.math, staged.wire, self.a2a_impl)
            fn = self._decide_fns.get(key)
            if fn is None:
                fn = self._decide_fns[key] = make_a2a_decide(
                    self.mesh, staged.c, math=staged.math,
                    write=self.write_mode, dedup=dedup, wire=staged.wire,
                    impl=self.a2a_impl, probe=self.probe_mode,
                )
            rows = staged.c
        else:
            key = ("host", staged.math, staged.wire)
            fn = self._decide_fns.get(key)
            if fn is None:
                fn = self._decide_fns[key] = make_sharded_decide(
                    self.mesh, math=staged.math, write=self.write_mode,
                    dedup=dedup, wire=staged.wire, probe=self.probe_mode,
                )
            rows = staged.b_local
        out_buf = self._take_egress(
            (self.n_shards, rows + 2, 4),
            np.int32 if staged.wire else np.int64,
        )
        return fn(table, staged.dev, out_buf)

    def issue_staged(self, staged: "_Staged", batch_rows: int):
        # dispatch count is folded in via the finish delta (engine thread)
        self.last_dispatch_rows = batch_rows
        table, out = self._decide(self.table, staged)
        self.table = table
        return staged, out

    def hbm_bytes_per_decision_estimate(self) -> float:
        """Per-shard table-walk bytes/decision at the last dispatch
        geometry (the LocalEngine twin; rows here are PER-SHARD rows)."""
        from gubernator_tpu.ops.pallas_probe import hbm_bytes_per_decision

        rows = getattr(self, "last_dispatch_rows", 0) or 4096
        per_shard = max(1, rows // self.n_shards)
        return hbm_bytes_per_decision(
            self.table.layout, per_shard, int(self.table.rows.shape[-2]),
            self.write_mode, self.probe_mode,
        )

    def finish_staged(self, pending, n: int):
        staged, out = pending
        outh = np.asarray(out)
        self._recycle_egress(out)
        s, l, r, t, dropped, hit, unproc, member, evicted = self._unroute(
            staged, outh, n
        )
        # per-row accounting over the rows the kernel actually processed
        # (pass rows are all active; a2a capacity drops count at their
        # retry; dedup member rows are represented by their carrier)
        counted = ~unproc & ~member
        st = (
            int(hit[counted].sum()),
            int((~hit[counted]).sum()),
            int((s[counted] == 1).sum()),
            evicted,
        )
        return (s, l, r, t, dropped, hit), st, unproc

    def _redispatch_rows(self, batch: HostBatch, n: int, uncounted=None):
        """Pipelined-retry hook (engine thread): depth=1 counts evictions and
        dispatches, plus the hit/miss/over outcome of `uncounted` rows —
        those the phase-1 pass never processed (a2a capacity drops); rows
        the phase-1 kernel DID probe were already counted there (cf.
        LocalEngine._redispatch_rows)."""
        _, (s, l, r, t, d, h) = self._dispatch(batch, depth=1, count=uncounted)
        return s[:n], l[:n], r[:n], t[:n], d[:n], h[:n]

    # ------------------------------------------------------- dispatch core

    def _stage(self, batch: HostBatch, shard: Optional[np.ndarray]):
        """Host half of one mesh dispatch. route="host": sort rows by owning
        shard and scatter the packed (12, n) columns into ONE (D, 12,
        b_local) ownership grid. route="device": NO routing work — rows ship
        in arrival order and the mesh exchanges them over ICI
        (parallel/a2a.py). Explicit `shard` pins (the GLOBAL replica path)
        always take the host grid: a2a routes by ownership hash only.
        Grids build in the persistent staging ring (_StagingPool) and each
        phase's host cost accumulates into stage_ms (route/pack/put)."""
        if self.route == "device" and shard is None:
            return self._stage_a2a(batch)
        D = self.n_shards
        t0 = time.perf_counter()
        routed = shard if shard is not None else shard_of(batch.fp, D)
        order, rs, offset, b_local = _route_plan(routed, D)
        t1 = time.perf_counter()
        wired, base = self._wire_plan(batch)
        if wired:
            from gubernator_tpu.ops import wire as wire_mod

            # compact grid: one trailing column per device block carries
            # the base (decode_wire_block reads cells [0, -1], [1, -1])
            shape = (D, wire_mod.WIRE_LANES, b_local + 1)
            grid = (
                self._pool.get(shape, zero=True, dtype=np.int32)
                if self._pool is not None
                else np.zeros(shape, dtype=np.int32)
            )
            packed = wire_mod.pack_wire_rows(batch, base)
            grid[rs, :, offset] = packed[:, order].T
            for d in range(D):
                wire_mod.stamp_base(grid[d], base)
            stage = "wire_pack"
        else:
            packed = pack_host_batch(batch)  # (12, n)
            shape = (D, 12, b_local)
            grid = (
                self._pool.get(shape, zero=True)
                if self._pool is not None
                else np.zeros(shape, dtype=np.int64)
            )
            grid[rs, :, offset] = packed[:, order].T
            stage = "pack"
        t2 = time.perf_counter()
        dev = self._put_grid(grid)
        t3 = time.perf_counter()
        self._stage_time("route", t1 - t0)
        self._stage_time(stage, t2 - t1)
        self._stage_time("put", t3 - t2)
        self._wire_count("put", grid.nbytes)
        with self._stage_lock:
            self.stage_dispatches += 1
        math = effective_math(self.table.layout, batch)
        return _Staged(
            order=order, rs=rs, offset=offset, b_local=b_local, dev=dev,
            math=math, wire=wired, base=base,
            needs_full=batch_needs_full_layout(self.table.layout, math, batch),
        )

    def _wire_plan(self, batch: HostBatch) -> "tuple[bool, int]":
        """Per-dispatch wire decision: (compact?, base). Compact only when
        the engine is in compact mode AND the batch is representable in the
        narrow layout (ops/wire.wire_encodable) — otherwise the dispatch
        ships full-width with identical semantics."""
        if self.wire != "compact":
            return False, 0
        from gubernator_tpu.ops import wire as wire_mod

        base = wire_mod.pick_base(batch)
        return wire_mod.wire_encodable(batch, base), base

    def _put_grid(self, grid: np.ndarray):
        """One staged ingress grid → sharded device array. On meshes where
        each device transfer is a serialized round trip (the tunneled TPU
        transport), per-shard puts issue CONCURRENTLY and assemble with
        make_array_from_single_device_arrays — put cost becomes
        max-of-shards instead of sum-of-shards. CPU meshes keep the single
        zero-copy put (GUBER_SHARD_PUT overrides either way)."""
        if not self._put_concurrent:
            return jax.device_put(grid, self._batch_sharding)
        if self._put_pool is None:
            self._put_pool = ThreadPoolExecutor(
                max_workers=min(self.n_shards, 8), thread_name_prefix="put"
            )
        devs = list(self.mesh.devices.flat)
        futs = [
            self._put_pool.submit(jax.device_put, grid[d : d + 1], devs[d])
            for d in range(self.n_shards)
        ]
        return jax.make_array_from_single_device_arrays(
            grid.shape, self._batch_sharding, [f.result() for f in futs]
        )

    def _stage_a2a(self, batch: HostBatch) -> "_StagedA2A":
        """Arrival-order staging: pack the columns straight into a pooled
        flat buffer and strided-copy it into the pooled ingress grid — row
        i lands on device i // c. O(1) routing work on the host, zero
        fresh allocations in steady state. Compact-wire dispatches build
        the 5-lane int32 grid with one trailing base column per device
        (20 B/row on the put vs the full layout's 96)."""
        D = self.n_shards
        n = batch.fp.shape[0]
        c = _pad_size(max(1, -(-n // D)), floor=8)
        t0 = time.perf_counter()
        wired, base = self._wire_plan(batch)
        if wired:
            from gubernator_tpu.ops import wire as wire_mod

            L = wire_mod.WIRE_LANES
            if self._pool is not None:
                flat = self._pool.get((L, D * c), dtype=np.int32)
                flat[:, n:] = 0  # stale tail from the buffer's last use
                grid = self._pool.get((D, L, c + 1), dtype=np.int32)
            else:
                flat = np.zeros((L, D * c), dtype=np.int32)
                grid = np.empty((D, L, c + 1), dtype=np.int32)
            wire_mod.pack_wire_rows(batch, base, out=flat[:, :n])
            np.copyto(
                grid[:, :, :c], flat.reshape(L, D, c).transpose(1, 0, 2)
            )
            grid[:, :, c] = 0
            for d in range(D):
                wire_mod.stamp_base(grid[d], base)
            stage = "wire_pack"
        else:
            if self._pool is not None:
                flat = self._pool.get((12, D * c))
                flat[:, n:] = 0  # stale tail from the buffer's last use
                grid = self._pool.get((D, 12, c))
            else:
                flat = np.zeros((12, D * c), dtype=np.int64)
                grid = np.empty((D, 12, c), dtype=np.int64)
            pack_host_batch(batch, out=flat[:, : n])
            # one strided copy rearranges (12, D·c) → (D, 12, c); every grid
            # byte is overwritten, so the pooled buffer needs no zeroing
            np.copyto(grid, flat.reshape(12, D, c).transpose(1, 0, 2))
            stage = "pack"
        t1 = time.perf_counter()
        dev = self._put_grid(grid)
        t2 = time.perf_counter()
        self._stage_time(stage, t1 - t0)
        self._stage_time("put", t2 - t1)
        self._wire_count("put", grid.nbytes)
        with self._stage_lock:
            self.stage_dispatches += 1
        math = effective_math(self.table.layout, batch)
        return _StagedA2A(
            c=c, dev=dev, math=math, wire=wired, base=base,
            needs_full=batch_needs_full_layout(self.table.layout, math, batch),
        )

    def _unroute(self, staged, outh: np.ndarray, n: int):
        """Decode the fetched (D, rows+2, 4) packed output grid back to
        pass-row order: per-row responses, the `unprocessed` mask (rows the
        a2a exchange capacity-dropped before they reached the kernel), the
        `member` mask (rows answered from an in-trace dedup carrier —
        excluded from per-row accounting), and the summed per-device
        evicted_unexpired (the only stat that cannot be derived per row).
        Flag bits shared with the single-device decoder
        (kernel2.FLAG_*/unpack_outputs). Compact-wire outputs (int32 —
        ops/wire.py) decode here with vectorized numpy: the reset lane is
        base-relative, everything else widens to int64."""
        self._wire_count("fetch", outh.nbytes)
        if isinstance(staged, _StagedA2A):
            st = outh[:, staged.c, :].astype(np.int64).sum(axis=0)
            per = outh[:, : staged.c, :].reshape(-1, 4)[:n]
            per = per.copy() if per.dtype == np.int64 else per
        else:
            st = outh[:, staged.b_local, :].astype(np.int64).sum(axis=0)
            per = np.empty((n, 4), dtype=outh.dtype)
            per[staged.order] = outh[staged.rs, staged.offset]
        if staged.wire:
            from gubernator_tpu.ops.wire import decode_wire_rows

            t0 = time.perf_counter()
            per = decode_wire_rows(per, staged.base)
            self._stage_time("wire_decode", time.perf_counter() - t0)
        status = (per[:, 3] & FLAG_STATUS).astype(np.int32)
        hit = (per[:, 3] & FLAG_HIT) != 0
        dropped = (per[:, 3] & FLAG_DROPPED) != 0
        unproc = (per[:, 3] & FLAG_UNPROCESSED) != 0
        member = (per[:, 3] & FLAG_MEMBER) != 0
        if isinstance(staged, _StagedA2A):
            # capacity overflow: exchanged rows that never reached a kernel
            # this dispatch (members inherit their carrier's flags without
            # having been exchanged — not counted)
            over = int((unproc & ~member).sum())
            if over:
                with self._stage_lock:
                    self.a2a_overflow += over
        return (
            status, per[:, 0], per[:, 1], per[:, 2], dropped, hit, unproc,
            member, int(st[3]),
        )

    def _dispatch(
        self,
        batch: HostBatch,
        depth: int = 0,
        shard: Optional[np.ndarray] = None,
        table_attr: str = "table",
        count: Optional[np.ndarray] = None,
    ):
        """Route one unique-fp pass across shards, run, and un-route responses
        back to pass-row order. Rows dropped by the claim auction are
        re-dispatched (cf. LocalEngine._dispatch_with_retry).

        `shard` overrides ownership routing (used by the GLOBAL path to pin
        requests to their home device's replica table); `table_attr` picks the
        state table ("table" = authoritative shards, "replica" = GLOBAL
        read-replicas). `count` masks the rows whose hit/miss/over outcome
        this call should account (None = all active at depth 0, none at
        retry depths): each row is counted exactly once, at the dispatch
        that first PROCESSES it — claim-dropped rows were probed and count
        immediately; a2a capacity-dropped rows (never probed, FLAG_UNPROCESSED)
        count at the retry that finally reaches the kernel. Rows that
        exhaust retries without ever being probed are not counted, matching
        the host path where such rows cannot exist."""
        n = batch.fp.shape[0]
        self._mark_dirty(batch.fp)
        staged = self._stage(batch, shard)
        table, out = self._decide(getattr(self, table_attr), staged)
        setattr(self, table_attr, table)
        self.stats.dispatches += 1
        outh = np.asarray(out)
        self._recycle_egress(out)
        status, limit, remaining, reset, dropped, hit, unproc, member, evicted = (
            self._unroute(staged, outh, n)
        )
        if count is None:
            count = np.asarray(batch.active) if depth == 0 else np.zeros(n, bool)
        counted = count & ~unproc & ~member
        self.stats.cache_hits += int(hit[counted].sum())
        self.stats.cache_misses += int((~hit[counted]).sum())
        self.stats.over_limit += int((status[counted] == 1).sum())
        self.stats.evicted_unexpired += evicted
        if dropped.any() and depth < 3:
            rows = np.nonzero(dropped)[0]
            sub_shard = shard[rows] if shard is not None else None
            if sub_shard is None and self.route == "device" and depth == 2:
                # FINAL retry falls back to host ownership routing: the
                # reference never rejects a valid request on internal
                # capacity, and the a2a exchange's bounded capacity must not
                # either — the host grid has no capacity to exceed, so
                # residual rows can only fail on (rare) claim contention
                sub_shard = shard_of(batch.fp[rows], self.n_shards)
            _, (s2, l2, r2, t2, d2, h2) = self._dispatch(
                _subset(batch, rows),
                depth=depth + 1,
                shard=sub_shard,
                table_attr=table_attr,
                count=(count & unproc)[rows],
            )
            status = status.copy(); limit = limit.copy()
            remaining = remaining.copy(); reset = reset.copy()
            dropped = dropped.copy(); hit = hit.copy()
            status[rows], limit[rows], remaining[rows], reset[rows] = s2, l2, r2, t2
            dropped[rows] = d2
            hit[rows] = h2
        elif dropped.any():
            # exhausted retries: decision was never persisted — callers
            # surface ERR_NOT_PERSISTED per item instead of failing open.
            # Rows that ALSO never reached a kernel (still FLAG_UNPROCESSED
            # at terminal failure) are counted separately: they are absent
            # from hits/misses/over, and this counter is what keeps that
            # absence observable instead of silent drift
            self.stats.dropped += int(dropped.sum())
            self.stats.unprocessed_dropped += int((dropped & unproc).sum())
        return np.arange(n), (status, limit, remaining, reset, dropped, hit)


class _Staged(NamedTuple):
    """One staged mesh dispatch: the routing plan + the on-device ingress
    grid. Carried from stage (any thread) to issue (engine thread) to finish
    (fetch thread) on the pipelined path."""

    order: np.ndarray  # (n,) original row index at each sorted position
    rs: np.ndarray  # (n,) owning shard, sorted
    offset: np.ndarray  # (n,) position within the shard's grid row
    b_local: int  # padded per-shard width
    dev: object  # (D, 12, b_local) i64 — or compact (D, 5, b_local+1) i32
    math: str  # static decision-graph mode ("token" | "mixed")
    wire: bool = False  # compact 5-lane int32 wire grids (ops/wire.py)
    base: int = 0  # created_at base of the compact encoding
    needs_full: bool = False  # batch unservable by a packed table layout


class _StagedA2A(NamedTuple):
    """One staged device-routed dispatch (parallel/a2a.py): arrival-order
    grid; the mesh does the ownership exchange (capacity derives from c and
    the mesh size inside make_a2a_decide)."""

    c: int  # rows per device (pow2)
    dev: object  # (D, 12, c) i64 — or compact (D, 5, c+1) i32, arrival order
    math: str  # static decision-graph mode ("token" | "mixed")
    wire: bool = False  # compact 5-lane int32 wire grids (ops/wire.py)
    base: int = 0  # created_at base of the compact encoding
    needs_full: bool = False  # batch unservable by a packed table layout


def _route_plan(routed: np.ndarray, D: int):
    """Shared shard-routing plan: rows grouped by shard, each row's position
    within its shard, and the padded per-shard width. Used by both the decide
    and install paths so their grid geometry can never diverge."""
    n = routed.shape[0]
    order = np.argsort(routed, kind="stable")
    counts = np.bincount(routed, minlength=D)
    b_local = _pad_size(int(counts.max()))
    rs = routed[order]
    offset = np.arange(n) - np.searchsorted(rs, rs)
    return order, rs, offset, b_local


def _to_grid(field: np.ndarray, shard_sorted, offset, D: int, b_local: int) -> np.ndarray:
    grid = np.zeros((D, b_local), dtype=field.dtype)
    grid[shard_sorted, offset] = field
    return grid
