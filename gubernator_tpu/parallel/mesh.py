"""Device mesh construction — single-host and pod-scale (multi-host).

The TPU mesh replaces the reference's cluster of gRPC peers for key
ownership: where gubernator consistent-hashes each key to one of N nodes
(reference replicated_hash.go:104-119), we hash each key to one of D devices
on the mesh. On one host that is a 1-D axis "shard" over the local devices.
On a pod slice the mesh is 2-D — ("host", "device") — with the SAME linear
shard numbering laid out host-major: shard s lives on host s // dl, local
device s % dl (dl = devices per host). Collectives address the pair of axes
jointly, so ICI does the exchange within a host row and DCN across rows,
and shard ownership (mesh.shard_of — pure fingerprint arithmetic) is stable
under (host, device) addressing: re-meshing the same D devices from 1 host
to H hosts moves no keys.

Multi-host resolution (make_mesh): an explicit `hosts=` argument wins, then
GUBER_MESH_HOSTS (the simulated multi-process mode — CI/test meshes fold
xla_force_host_platform_device_count CPU devices into H "hosts" inside one
process), then `jax.process_count()` when the runtime really is
multi-process (each process contributes its local devices to its own host
row). Cross-region stays on the host peer plane (peers/).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec

SHARD_AXIS = "shard"  # the 1-D single-host axis (seed layout)
HOST_AXIS = "host"  # pod meshes: leading axis, one row per host
DEVICE_AXIS = "device"  # pod meshes: trailing axis, devices within a host


def shard_map_compat(f, *, mesh: Mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` across the jax versions this repo runs on: new jax
    exposes it top-level with the `check_vma` flag; 0.4.x has
    `jax.experimental.shard_map.shard_map` where the same knob is named
    `check_rep`. Every shard_map call site routes through here so version
    drift stays in one place."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def env_mesh_hosts() -> Optional[int]:
    """GUBER_MESH_HOSTS: fold the device pool into this many simulated hosts
    (2-D mesh in ONE process — the CI/test stand-in for a real multi-process
    pod slice). Unset/empty → topology from the runtime."""
    raw = os.environ.get("GUBER_MESH_HOSTS", "").strip()
    if not raw:
        return None
    hosts = int(raw)
    if hosts < 1:
        raise ValueError(f"GUBER_MESH_HOSTS must be >= 1, got {hosts}")
    return hosts


def make_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence] = None,
    hosts: Optional[int] = None,
) -> Mesh:
    """Mesh over `n_devices` (default: all addressable devices). 1-D
    ("shard",) for a single host; 2-D ("host", "device") when the topology
    is multi-host — explicit `hosts=`, then GUBER_MESH_HOSTS (simulated),
    then jax.process_count() (real pod slices). Devices are ordered
    host-major (process_index, id) so the linear shard id s ↔ (s // dl,
    s % dl) addressing is stable whichever host enumerates them."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    D = len(devices)
    if hosts is None:
        hosts = env_mesh_hosts()
    if hosts is None:
        hosts = jax.process_count() if jax.process_count() > 1 else 1
    if hosts <= 1:
        return Mesh(np.asarray(devices), (SHARD_AXIS,))
    if D % hosts != 0:
        raise ValueError(
            f"mesh of {D} devices cannot split over {hosts} hosts evenly"
        )
    grid = np.asarray(devices).reshape(hosts, D // hosts)
    return Mesh(grid, (HOST_AXIS, DEVICE_AXIS))


# ------------------------------------------------- topology introspection
# Every mesh consumer (sharded.py, a2a.py, ring.py, global_sync.py,
# parallel/telemetry.py) addresses the shard dimension through these, so
# the 1-D and 2-D layouts stay interchangeable at every call site.


def shard_axes(mesh: Mesh):
    """The axis name(s) the leading shard dimension spans: "shard" on 1-D
    meshes, ("host", "device") on pod meshes. Valid as the `axis_name` of
    every collective used here (all_to_all / all_gather / ppermute /
    axis_index flatten tuples host-major)."""
    names = tuple(mesh.axis_names)
    return names if len(names) > 1 else names[0]


def shard_spec(mesh: Mesh) -> PartitionSpec:
    """PartitionSpec sharding an array's leading axis over every mesh axis
    jointly — the drop-in replacement for the seed's P("shard")."""
    axes = shard_axes(mesh)
    return PartitionSpec(axes)


def mesh_hosts(mesh: Mesh) -> int:
    """Host rows in the mesh (1 on single-host meshes)."""
    return int(mesh.shape[HOST_AXIS]) if HOST_AXIS in mesh.shape else 1


def devices_per_host(mesh: Mesh) -> int:
    dl = mesh.shape.get(DEVICE_AXIS) if HOST_AXIS in mesh.shape else None
    return int(dl) if dl is not None else int(mesh.devices.size)


def host_of_shard(mesh: Mesh, shard: np.ndarray) -> np.ndarray:
    """Owning host row for linear shard ids — the host-major addressing
    contract (shard s ↔ host s // dl)."""
    return np.asarray(shard) // devices_per_host(mesh)


def shard_of(fp: np.ndarray, n_shards: int) -> np.ndarray:
    """Owning shard for each fingerprint. Uses high bits so the shard choice is
    independent of the in-table slot (fp mod capacity uses low bits) — the
    analog of the reference using separate hashes for peer ownership and
    worker sharding (replicated_hash.go:78-91 vs workers.go:185-189)."""
    return ((fp >> 32) % n_shards).astype(np.int64)
