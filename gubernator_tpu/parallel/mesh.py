"""Device mesh construction.

The TPU mesh replaces the reference's cluster of gRPC peers for key ownership:
where gubernator consistent-hashes each key to one of N nodes
(reference replicated_hash.go:104-119), we hash each key to one of D devices on
a 1-D mesh axis "shard". Multi-host TPU slices extend the same axis across
hosts over ICI; cross-region stays on the host peer plane (peers/).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

SHARD_AXIS = "shard"


def shard_map_compat(f, *, mesh: Mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` across the jax versions this repo runs on: new jax
    exposes it top-level with the `check_vma` flag; 0.4.x has
    `jax.experimental.shard_map.shard_map` where the same knob is named
    `check_rep`. Every shard_map call site routes through here so version
    drift stays in one place."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def make_mesh(n_devices: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over `n_devices` (default: all local devices)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SHARD_AXIS,))


def shard_of(fp: np.ndarray, n_shards: int) -> np.ndarray:
    """Owning shard for each fingerprint. Uses high bits so the shard choice is
    independent of the in-table slot (fp mod capacity uses low bits) — the
    analog of the reference using separate hashes for peer ownership and
    worker sharding (replicated_hash.go:78-91 vs workers.go:185-189)."""
    return ((fp >> 32) % n_shards).astype(np.int64)
