"""Device-side request routing: ownership exchange as an ICI all_to_all.

The host-routed path (sharded.py `_stage`) sorts rows by owning shard on the
host and scatters them into a (D, b_local) grid — O(n) host work (argsort +
grid scatter) on every dispatch's critical path, run by a single Python
process feeding the whole mesh. That is fine on one host, but on a real
multi-host slice each host only feeds its local devices, and per-dispatch
host routing becomes the scaling bottleneck the r3 review flagged.

This module moves routing ONTO the mesh, MoE-dispatch style (the same
capacity-factor pattern expert-parallel layers use — see PAPERS.md; the
scaling-book recipe: annotate, exchange, let ICI do the work):

 1. the host ships arrival-order rows, zero routing work: the packed (12, n)
    columns reshape into a (D, 12, c) grid (row i → device i//c);
 2. each device computes owners for its c rows (the same high-bits hash as
    `mesh.shard_of`), sorts locally, and GATHERS rows into a (D, C, 12) send
    buffer — C is the per-(src,dst) capacity, mean + 5σ of the multinomial
    per-pair count; rows past a pair's capacity are marked dropped (claim
    retry re-dispatches them, the MoE "token dropping" analog);
 3. ONE exchange delivers every row to its owning device over the
    interconnect (the reference's N×N gRPC forwarding mesh, peer_client.go,
    collapsed into a collective) — either a monolithic `lax.all_to_all` or
    the hand-rolled per-hop ring schedule (parallel/ring.py,
    GUBER_A2A_IMPL), byte-identical by contract;
 4. the owner runs the decision kernel on its received (D·C) rows;
 5. a second all_to_all returns responses to each row's arrival device,
    which un-sorts them to arrival order.

Output layout matches the host-routed path: (D, c+2, 4) per device — c
response rows (kernel2.pack_outputs flags) then the 2 stats rows — so the
engine decodes both paths with the same machinery and ONE fetch.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from gubernator_tpu.ops.kernel2 import (
    FLAG_DROPPED,
    FLAG_MEMBER,
    FLAG_UNPROCESSED,
    decide2_packed_cols_impl,
    decide2_packed_dedup_impl,
    dedup_packed_cols,
)
from gubernator_tpu.ops.engine import default_write_mode
from gubernator_tpu.ops.table2 import Table2
from gubernator_tpu.parallel.mesh import shard_map_compat, shard_of, shard_spec
from gubernator_tpu.parallel.ring import a2a_impl, exchange

i32 = jnp.int32
i64 = jnp.int64


def a2a_capacity_sigma() -> float:
    """Multinomial tail bound for the per-pair exchange capacity
    (GUBER_A2A_CAPACITY_SIGMA, default 5.0 standard deviations). Read
    host-side at trace time like the sparse-write knobs, so tuning runs can
    flip it between compiles without a restart. Lower values shrink the
    exchanged (D, C) buffers (less ICI traffic per dispatch) at the price of
    more capacity-overflow drops → engine retries; the overflow contract
    (FLAG_DROPPED|FLAG_UNPROCESSED → retry, never a lost request) is pinned
    by tests/test_a2a_capacity.py and does not change with the knob."""
    return float(os.environ.get("GUBER_A2A_CAPACITY_SIGMA", "5.0"))


def pair_capacity(c: int, D: int) -> int:
    """Per-(src,dst) row capacity: mean + σ·sqrt(mean) of the multinomial
    count of c hash-routed rows over D destinations (σ from
    a2a_capacity_sigma, default 5) plus a small-c slack of 8, rounded up to
    a power of two ≥ 8 for shape reuse. Overflow is dropped → engine retry
    (a perf knob, not correctness), exactly like the sweep's update-window
    bound (kernel2.sweep_geometry)."""
    mean = c / D
    cap = int(mean + a2a_capacity_sigma() * mean**0.5) + 8
    p = 8
    while p < cap:
        p *= 2
    return p


def make_a2a_decide(
    mesh: Mesh, c: int, math: str = "mixed", write=None, dedup: bool = False,
    wire: bool = False, impl: "str | None" = None, probe: str = "xla",
):
    """Jitted all-shards decide with ON-DEVICE routing: (Table2[D,·],
    (D, 12, c) arrival-order grid, (D, c+2, 4) recycled egress buffer) →
    (Table2', (D, c+2, 4) packed outputs in arrival order). `c` rows per
    device; the per-pair exchange capacity derives from (c, mesh size) —
    pair_capacity is the single source of truth for the exchange geometry.

    All three inputs are DONATED: the table advances in place as before, the
    ingress grid's HBM is reclaimed at launch (the engine's staging pool
    re-puts into it next dispatch instead of growing the heap), and the
    egress buffer — a previous dispatch's already-fetched output, recycled
    by the engine (ShardedEngine._take_egress) — aliases this dispatch's
    output allocation, so steady-state serving allocates nothing.

    `dedup=True` aggregates duplicate keys IN-TRACE at both ends of the
    exchange (kernel2.dedup_packed_cols): once per source block before owner
    computation — local duplicates collapse to one exchanged row, so a
    Zipf-hot key costs ≤ 1 slot of each pair's capacity instead of flooding
    its owner's — and once on the owner over the received rows, merging the
    ≤ D cross-source carriers. Member rows answer from their carrier with
    FLAG_MEMBER, exactly like the host-grid dedup program.

    `wire=True` takes the compact 5-lane int32 ingress grid (trailing base
    column per device, ops/wire.py) and returns int32 compact outputs; the
    HOST boundary is what the narrow layout shrinks — the decode runs
    before the exchange, so the ICI legs still move the full 12-lane rows
    (ICI bandwidth is not the bottleneck the wire budget targets) and the
    exchange/dedup machinery below is shared byte-for-byte.

    `impl` picks the exchange schedule (parallel/ring.py): "collective" =
    one lax.all_to_all per direction (the seed path — and the parity
    oracle), "ring" = the hand-rolled per-hop schedule with double-buffered
    remote DMA on TPU / ppermute shifts elsewhere; None resolves through
    GUBER_A2A_IMPL (auto = ring on TPU). The two produce byte-identical
    grids — impl is a schedule knob, never a semantics one."""
    write = write or default_write_mode()
    impl = a2a_impl(impl)
    D = int(mesh.devices.size)
    C = pair_capacity(c, D)

    def per_device(table: Table2, arr: jnp.ndarray, out_buf: jnp.ndarray):
        from gubernator_tpu.ops.wire import decode_wire_block, encode_wire_out

        table = jax.tree.map(lambda x: x[0], table)
        if wire:
            a, wire_base = decode_wire_block(arr[0])  # (12, c) i64
        else:
            a = arr[0]  # (12, c) i64, arrival order
        if dedup:
            # source-local merge: duplicate keys within this device's block
            # collapse onto their carrier; members deactivate (not sent)
            a, carrier0, member0 = dedup_packed_cols(a)
        fp = a[0]
        active = a[11] != 0
        # mesh.shard_of traces fine on jnp values — one ownership hash
        owner = jnp.where(active, shard_of(fp, D), D).astype(i32)
        idx = jnp.arange(c, dtype=i32)
        o_s, idx_s = jax.lax.sort((owner, idx), num_keys=1)
        gstart = jnp.searchsorted(o_s, o_s).astype(i32)
        rank = idx - gstart  # position within the destination's group
        ok_s = (rank < C) & (o_s < D)

        # send buffer by GATHER (scatters are slow on TPU): slot (d, j) takes
        # sorted row searchsorted(o_s, d) + j when j < count(d)
        d_iota = jnp.arange(D * C, dtype=i32) // C
        j_iota = jnp.arange(D * C, dtype=i32) % C
        g0 = jnp.searchsorted(o_s, d_iota).astype(i32)
        g1 = jnp.searchsorted(o_s, d_iota, side="right").astype(i32)
        src = g0 + j_iota
        valid = src < g1
        rows_sorted = a[:, idx_s]  # (12, c)
        send = jnp.where(
            valid[None, :], rows_sorted[:, jnp.clip(src, 0, c - 1)], i64(0)
        )  # (12, D*C); zeroed slots are inactive (fp=0, active=0)
        send3 = send.reshape(12, D, C).transpose(1, 0, 2)  # (D, 12, C)

        # ---- ICI: deliver rows to owners; leading axis src↔dst swaps
        recv = exchange(send3, mesh, impl)  # (D, 12, C), leading = source
        local = recv.transpose(1, 0, 2).reshape(12, D * C)

        if dedup:
            # owner-side merge: the same key can arrive from up to D source
            # carriers; aggregate them before the kernel (its unique-fp
            # contract) and fan the response back to every received row
            table, packed = decide2_packed_dedup_impl(
                table, local, write=write, math=math, probe=probe
            )
        else:
            table, packed = decide2_packed_cols_impl(
                table, local, write=write, math=math, probe=probe
            )
        resp = packed[: D * C].reshape(D, C, 4)
        stats_rows = packed[D * C :]  # (2, 4)

        # ---- ICI: responses ride back to each row's arrival device
        back = exchange(resp, mesh, impl).reshape(D * C, 4)

        # un-sort to arrival order: arrival row idx_s[p] sat in slot
        # o_s[p]*C + rank[p]
        slot_s = jnp.where(ok_s, o_s * C + rank, 0)
        _, slot_u, ok_u = jax.lax.sort(
            (idx_s, slot_s, ok_s.astype(i32)), num_keys=1
        )
        out = back[slot_u]  # (c, 4)
        sent = ok_u == 1
        # capacity-overflow rows: dropped + unprocessed flags — the engine's
        # claim-retry path re-dispatches them AND counts their hit/miss
        # outcome there (they appear in no kernel stats row)
        drop_flags = jnp.where(
            active, i64(FLAG_DROPPED | FLAG_UNPROCESSED), i64(0)
        )
        out = jnp.where(sent[:, None], out, i64(0))
        out = out.at[:, 3].set(jnp.where(sent, out[:, 3], drop_flags))
        if dedup:
            # source-local members were never exchanged: they answer from
            # their carrier's (aggregate) response. A capacity-dropped
            # carrier hands its members the drop flags too, so the engine's
            # retry re-dispatches the whole group and re-aggregates it.
            fan = out[carrier0]
            fan = fan.at[:, 3].set(fan[:, 3] | i64(FLAG_MEMBER))
            out = jnp.where(member0[:, None], fan, out)

        packed_out = jnp.concatenate([out, stats_rows], axis=0)
        if wire:
            packed_out = encode_wire_out(packed_out, wire_base)
        expand = lambda t: jax.tree.map(lambda x: x[None], t)
        return expand(table), packed_out[None]

    spec = shard_spec(mesh)
    fn = shard_map_compat(
        per_device, mesh=mesh, in_specs=(spec, spec, spec),
        # check_vma=False: the Pallas sweep's out_shape carries no vma
        # annotation, which the checker (jax>=0.9) rejects inside shard_map
        out_specs=(spec, spec), check_vma=False
    )
    # keep_unused: out_buf exists only to donate its buffer into the
    # same-shape output allocation (XLA aliases donated inputs to outputs
    # with matching shape/dtype); jit would otherwise prune the unused arg
    # and drop the aliasing with it. Staging donation is TPU-only
    # (sharded._staging_donate): XLA:CPU zero-copies host numpy buffers and
    # donating memory it doesn't own corrupts the process.
    from gubernator_tpu.parallel.sharded import _staging_donate

    return jax.jit(fn, donate_argnums=_staging_donate(), keep_unused=True)
