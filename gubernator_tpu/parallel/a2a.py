"""Device-side request routing: ownership exchange as an ICI all_to_all.

The host-routed path (sharded.py `_stage`) sorts rows by owning shard on the
host and scatters them into a (D, b_local) grid — O(n) host work (argsort +
grid scatter) on every dispatch's critical path, run by a single Python
process feeding the whole mesh. That is fine on one host, but on a real
multi-host slice each host only feeds its local devices, and per-dispatch
host routing becomes the scaling bottleneck the r3 review flagged.

This module moves routing ONTO the mesh, MoE-dispatch style (the same
capacity-factor pattern expert-parallel layers use — see PAPERS.md; the
scaling-book recipe: annotate, exchange, let ICI do the work):

 1. the host ships arrival-order rows, zero routing work: the packed (12, n)
    columns reshape into a (D, 12, c) grid (row i → device i//c);
 2. each device computes owners for its c rows (the same high-bits hash as
    `mesh.shard_of`), sorts locally, and GATHERS rows into a (D, C, 12) send
    buffer — C is the per-(src,dst) capacity, mean + 5σ of the multinomial
    per-pair count; rows past a pair's capacity are marked dropped (claim
    retry re-dispatches them, the MoE "token dropping" analog);
 3. ONE `lax.all_to_all` delivers every row to its owning device over ICI
    (the reference's N×N gRPC forwarding mesh, peer_client.go, collapsed
    into a collective);
 4. the owner runs the decision kernel on its received (D·C) rows;
 5. a second all_to_all returns responses to each row's arrival device,
    which un-sorts them to arrival order.

Output layout matches the host-routed path: (D, c+2, 4) per device — c
response rows (kernel2.pack_outputs flags) then the 2 stats rows — so the
engine decodes both paths with the same machinery and ONE fetch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from gubernator_tpu.ops.kernel2 import (
    FLAG_DROPPED,
    FLAG_UNPROCESSED,
    decide2_packed_cols_impl,
)
from gubernator_tpu.ops.engine import default_write_mode
from gubernator_tpu.ops.table2 import Table2
from gubernator_tpu.parallel.mesh import SHARD_AXIS, shard_map_compat, shard_of

i32 = jnp.int32
i64 = jnp.int64


def pair_capacity(c: int, D: int) -> int:
    """Per-(src,dst) row capacity: mean + 5σ of the multinomial count of c
    hash-routed rows over D destinations, pow2 for shape reuse. Overflow is
    dropped → engine retry (a perf knob, not correctness), exactly like the
    sweep's update-window bound (kernel2.sweep_geometry)."""
    mean = c / D
    cap = int(mean + 5.0 * mean**0.5) + 8
    p = 8
    while p < cap:
        p *= 2
    return p


def make_a2a_decide(mesh: Mesh, c: int, math: str = "mixed", write=None):
    """Jitted all-shards decide with ON-DEVICE routing: (Table2[D,·],
    (D, 12, c) arrival-order grid) → (Table2', (D, c+2, 4) packed outputs in
    arrival order). `c` rows per device; the per-pair exchange capacity
    derives from (c, mesh size) — pair_capacity is the single source of
    truth for the exchange geometry."""
    write = write or default_write_mode()
    D = int(mesh.devices.size)
    C = pair_capacity(c, D)

    def per_device(table: Table2, arr: jnp.ndarray):
        table = jax.tree.map(lambda x: x[0], table)
        a = arr[0]  # (12, c) i64, arrival order
        fp = a[0]
        active = a[11] != 0
        # mesh.shard_of traces fine on jnp values — one ownership hash
        owner = jnp.where(active, shard_of(fp, D), D).astype(i32)
        idx = jnp.arange(c, dtype=i32)
        o_s, idx_s = jax.lax.sort((owner, idx), num_keys=1)
        gstart = jnp.searchsorted(o_s, o_s).astype(i32)
        rank = idx - gstart  # position within the destination's group
        ok_s = (rank < C) & (o_s < D)

        # send buffer by GATHER (scatters are slow on TPU): slot (d, j) takes
        # sorted row searchsorted(o_s, d) + j when j < count(d)
        d_iota = jnp.arange(D * C, dtype=i32) // C
        j_iota = jnp.arange(D * C, dtype=i32) % C
        g0 = jnp.searchsorted(o_s, d_iota).astype(i32)
        g1 = jnp.searchsorted(o_s, d_iota, side="right").astype(i32)
        src = g0 + j_iota
        valid = src < g1
        rows_sorted = a[:, idx_s]  # (12, c)
        send = jnp.where(
            valid[None, :], rows_sorted[:, jnp.clip(src, 0, c - 1)], i64(0)
        )  # (12, D*C); zeroed slots are inactive (fp=0, active=0)
        send3 = send.reshape(12, D, C).transpose(1, 0, 2)  # (D, 12, C)

        # ---- ICI: deliver rows to owners; leading axis src↔dst swaps
        recv = jax.lax.all_to_all(
            send3, SHARD_AXIS, split_axis=0, concat_axis=0
        )  # (D, 12, C), leading = source device
        local = recv.transpose(1, 0, 2).reshape(12, D * C)

        table, packed = decide2_packed_cols_impl(
            table, local, write=write, math=math
        )
        resp = packed[: D * C].reshape(D, C, 4)
        stats_rows = packed[D * C :]  # (2, 4)

        # ---- ICI: responses ride back to each row's arrival device
        back = jax.lax.all_to_all(
            resp, SHARD_AXIS, split_axis=0, concat_axis=0
        ).reshape(D * C, 4)

        # un-sort to arrival order: arrival row idx_s[p] sat in slot
        # o_s[p]*C + rank[p]
        slot_s = jnp.where(ok_s, o_s * C + rank, 0)
        _, slot_u, ok_u = jax.lax.sort(
            (idx_s, slot_s, ok_s.astype(i32)), num_keys=1
        )
        out = back[slot_u]  # (c, 4)
        sent = ok_u == 1
        # capacity-overflow rows: dropped + unprocessed flags — the engine's
        # claim-retry path re-dispatches them AND counts their hit/miss
        # outcome there (they appear in no kernel stats row)
        drop_flags = jnp.where(
            active, i64(FLAG_DROPPED | FLAG_UNPROCESSED), i64(0)
        )
        out = jnp.where(sent[:, None], out, i64(0))
        out = out.at[:, 3].set(jnp.where(sent, out[:, 3], drop_flags))

        expand = lambda t: jax.tree.map(lambda x: x[None], t)
        return expand(table), jnp.concatenate([out, stats_rows], axis=0)[None]

    spec = P(SHARD_AXIS)
    fn = shard_map_compat(
        per_device, mesh=mesh, in_specs=(spec, spec),
        # check_vma=False: the Pallas sweep's out_shape carries no vma
        # annotation, which the checker (jax>=0.9) rejects inside shard_map
        out_specs=(spec, spec), check_vma=False
    )
    return jax.jit(fn, donate_argnums=(0,))
