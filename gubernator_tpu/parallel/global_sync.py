"""GLOBAL behavior as mesh collectives — the reference's globalManager
(reference global.go:31-307) re-designed for the TPU interconnect.

In the reference, a GLOBAL rate limit has one owning node; every other node
answers from a local read-replica immediately and asynchronously ships its
accumulated hits to the owner (runAsyncHits, 100 ms cadence), which applies
them with DRAIN_OVER_LIMIT forced and broadcasts the authoritative status to
every peer (runBroadcasts → UpdatePeerGlobals). Worst case 3+N gRPC messages
per hit, amortized by two batching stages (docs/architecture.md:84-105).

Here the mesh replaces the peer group: every device keeps
* its authoritative table shard (ShardedEngine), and
* a **replica table** holding installed statuses of remote-owned GLOBAL keys,
* a host-side pending-hit accumulator per device (sum hits, OR RESET_REMAINING
  — exactly the reference aggregation, global.go:109-123).

`sync()` is ONE jitted collective step (the 3+N message dance collapses into
two all_gathers over ICI):
 1. all_gather every device's outbox of aggregated hits;
 2. each device filters entries it owns, segment-aggregates duplicates from
    different devices, applies them through the decision kernel with
    DRAIN_OVER_LIMIT forced (reference gubernator.go:526-532);
 3. all_gather the resulting authoritative statuses; every device installs
    entries it does NOT own into its replica table (install kernel =
    UpdatePeerGlobals semantics, reference gubernator.go:434-474).

GLOBAL requests are answered from the home device's replica table immediately
("process like we own it" with GLOBAL stripped and NO_BATCHING forced,
reference gubernator.go:401-429) — eventual consistency bounded by the sync
cadence, identical to the reference's contract.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from gubernator_tpu.ops.batch import (
    ERR_DROPPED,
    HostBatch,
    InstallBatch,
    ReqBatch,
    RequestColumns,
    ResponseColumns,
    pack_columns,
    pack_requests,
    pad_batch,
)
from gubernator_tpu.ops.kernel2 import decide2_impl, install2_impl
from gubernator_tpu.ops.plan import _subset
from gubernator_tpu.ops.table2 import Table2
from gubernator_tpu.parallel.mesh import shard_axes, shard_map_compat, shard_of, shard_spec
from gubernator_tpu.parallel.sharded import ShardedEngine, new_sharded_table
from gubernator_tpu.types import (
    Behavior,
    RateLimitRequest,
    RateLimitResponse,
    has_behavior,
)
from gubernator_tpu.ops.engine import ERR_NOT_PERSISTED, _pad_size, default_write_mode, ms_now


class PendingHits:
    """Columnar per-home accumulator of GLOBAL hits awaiting the sync tick.

    The merge is the reference's async-hit aggregation (global.go:109-123:
    sum Hits, OR RESET_REMAINING, newest request's config wins) as ONE numpy
    group-by per batch instead of a Python dict update per row — at 131K-row
    batches the per-row loop was µs-per-row host work against a ms-per-batch
    kernel. Entry order only affects which sync round an entry rides in
    (sync() drains fully every tick), never the reconciled result."""

    __slots__ = ("hb", "hits", "reset", "oldest_ts")

    def __init__(self):
        self.hb: Optional[HostBatch] = None  # unique-fp config carrier rows
        self.hits: Optional[np.ndarray] = None  # (n,) i64 accumulated hits
        self.reset: Optional[np.ndarray] = None  # (n,) i32 RESET bits OR-ed
        # monotonic ts of the oldest entry still in the accumulator: set
        # when the first entry lands in an empty queue, cleared only on a
        # FULL drain (a partial take keeps it — the remainder is no newer,
        # so staleness stays an upper bound). Feeds the
        # gubernator_global_sync_staleness_seconds gauge.
        self.oldest_ts: Optional[float] = None

    def age_s(self) -> float:
        """Seconds the oldest pending entry has waited (0 when empty)."""
        if self.oldest_ts is None or self.hb is None:
            return 0.0
        import time as _time

        return max(0.0, _time.monotonic() - self.oldest_ts)

    def __len__(self) -> int:
        # single read of self.hb: has_pending() is called from the event-loop
        # thread while the engine thread's take() may set hb=None — two reads
        # (check then use) would race
        hb = self.hb
        return 0 if hb is None else int(hb.fp.shape[0])

    def merge(
        self, hb: HostBatch, rows: np.ndarray, hits: np.ndarray,
        reset: np.ndarray,
    ) -> None:
        """Fold batch rows `rows` of `hb` in (hits pre-zeroed for owner-side
        rows that only mark a broadcast)."""
        if self.hb is None:
            import time as _time

            self.oldest_ts = _time.monotonic()
        new = _subset(hb, rows)
        if self.hb is not None:
            new = HostBatch(
                *[np.concatenate([a, b]) for a, b in zip(self.hb, new)]
            )
            hits = np.concatenate([self.hits, hits])
            reset = np.concatenate([self.reset, reset])
        uniq, inv = np.unique(new.fp, return_inverse=True)
        m = uniq.size
        h = np.zeros(m, dtype=np.int64)
        np.add.at(h, inv, hits)
        r = np.zeros(m, dtype=np.int32)
        np.bitwise_or.at(r, inv, reset.astype(np.int32))
        # newest config wins: highest concatenated position per key (existing
        # entries precede the new batch's rows, which are in request order)
        pos = np.full(m, -1, dtype=np.int64)
        np.maximum.at(pos, inv, np.arange(new.fp.shape[0]))
        self.hb = _subset(new, pos)
        self.hits, self.reset = h, r

    def take(self, k: int):
        """Pop up to k entries → (config rows, hits, reset) columns.

        The POPPED columns are copies: the outbox builder stamps
        hits/behavior/created_at into them in place, and a popped box that
        shared storage with the accumulator would write through into
        whatever still aliases the same base buffer. The REMAINDER stays a
        slice view — a sync tick drains a deep queue in Q/k rounds, and
        copying the remainder each round would make the drain O(Q²) in
        queue depth (copying the popped k is O(Q) total)."""
        n = len(self)
        k = min(k, n)
        out = (
            HostBatch(*[f[:k].copy() for f in self.hb]),
            self.hits[:k].copy(),
            self.reset[:k].copy(),
        )
        if k == n:
            self.hb = self.hits = self.reset = None
            self.oldest_ts = None
        else:
            self.hb = HostBatch(*[f[k:] for f in self.hb])
            self.hits = self.hits[k:]
            self.reset = self.reset[k:]
        return out

    def clear(self) -> None:
        """Drop every pending entry (bench/test harness reset — modeling a
        steady state where the sync tick keeps the accumulator drained)."""
        self.hb = self.hits = self.reset = None
        self.oldest_ts = None


@dataclass
class _QueuedHits:
    """Queue-merge inputs computed at PREPARE time, applied at ISSUE time —
    the accumulator mutation must stay on the engine thread (single-writer),
    while prepare runs on the pipeline's prep pool."""

    hb: HostBatch  # the GLOBAL sub-batch (config carrier rows)
    rows: np.ndarray  # rows to queue (active, nonzero hits)
    hits: np.ndarray  # per-row hits (0 for owner-side broadcast markers)
    reset: np.ndarray  # RESET_REMAINING bits
    home: int  # the batch's rotating home device
    n_remote: int  # non-owner rows (hits_queued metric delta)


@dataclass
class GlobalPending:
    """In-flight pipelined GLOBAL check (the mesh-global engine's analog of
    ops/engine.PendingCheck): staged replica/owner/plain dispatches plus the
    deferred hit-queue merge."""

    hb: HostBatch
    err: np.ndarray
    now: int
    queue: _QueuedHits
    # [Pass, n_rows, batch, staged→(staged, out), table_attr, home_pin, rowmap]
    passes: list
    clamped: int
    stacked: object = None  # same-shape pass outputs fused for ONE fetch


@dataclass
class GlobalStats:
    """Counters mirroring the reference's global-behavior metric family
    (global.go:53-79) — load-bearing for convergence tests (§4 SURVEY.md)."""

    hits_queued: int = 0
    sync_rounds: int = 0
    broadcasts_applied: int = 0  # entries applied+broadcast as owner
    updates_installed: int = 0  # entries installed into replica tables
    send_queue_length: int = 0


def _sync_core(primary, replica, outbox: ReqBatch, me, D: int, write: str,
               axes="shard"):
    """One collective sync round, per-device body (shared by the
    single-round and fused multi-round steps): exchange outboxes, owner
    applies aggregated hits, broadcast + replica install. Returns
    (primary', replica', counters(2,) i64, bc InstallBatch)."""
    # sentinel OUTSIDE the fingerprint domain (real fps are in [1, 2^63-1],
    # hashing.py): non-owned/inactive outbox rows sort into their own leading
    # segment and can never merge with a real key's aggregation
    DROP_FP = jnp.int64(-1)
    RESET = int(Behavior.RESET_REMAINING)
    DRAIN = int(Behavior.DRAIN_OVER_LIMIT)

    # ---- stage 1: exchange hit outboxes (runAsyncHits → sendHits analog)
    gath = jax.lax.all_gather(outbox, axes)  # leaves (D, OUT)
    flat = jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]), gath)
    N = flat.fp.shape[0]
    owner = ((flat.fp >> 32) % D).astype(jnp.int32)
    mine = flat.active & (owner == me)

    # ---- stage 2: aggregate same-key hits from different devices
    key = jnp.where(mine, flat.fp, DROP_FP)
    order = jnp.argsort(key)
    sfp = key[order]
    first = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), sfp[1:] != sfp[:-1]]
    )
    seg = jnp.cumsum(first) - 1
    hits = jax.ops.segment_sum(flat.hits[order], seg, num_segments=N)
    reset_bit = jax.ops.segment_max(
        (flat.behavior[order] & RESET), seg, num_segments=N
    )
    pos = jnp.arange(N)
    # config carrier = newest contributing entry of the segment
    carrier_pos = jax.ops.segment_max(
        jnp.where(mine[order], pos, -1), seg, num_segments=N
    )
    valid = carrier_pos >= 0
    carrier = order[jnp.clip(carrier_pos, 0, N - 1)]
    cfg = jax.tree.map(lambda x: x[carrier], flat)
    agg = cfg._replace(
        hits=hits,
        # owner applies accumulated global hits with DRAIN forced
        # (reference gubernator.go:526-532) and RESET OR-ed in
        behavior=cfg.behavior | DRAIN | reset_bit,
        active=valid,
    )
    primary, resp, stats = decide2_impl(primary, agg, write=write)

    # ---- stage 3: broadcast authoritative statuses (runBroadcasts analog)
    bc = InstallBatch(
        fp=jnp.where(valid, agg.fp, jnp.int64(0)),
        algo=agg.algo,
        status=resp.status,
        limit=resp.limit,
        remaining=resp.remaining,
        reset_time=resp.reset_time,
        duration=agg.duration,
        now=agg.created_at,
        active=valid,
        burst=agg.burst,  # real config burst — richer than the wire
        stamp=agg.created_at,  # path's Burst=Limit rebuild
        # sliding-window fidelity (PR 11): the owner's previous-window
        # count and stored-style remaining ride the broadcast so replicas
        # interpolate the SAME `used` as the owner instead of the
        # permissive aux=0 rebuild
        aux=resp.aux,
        rem_store=resp.rem_store,
    )
    bc_all = jax.lax.all_gather(bc, axes)
    bc_flat = jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]), bc_all)
    bc_owner = ((bc_flat.fp >> 32) % D).astype(jnp.int32)
    theirs = bc_flat.active & (bc_owner != me)
    inst = bc_flat._replace(active=theirs)
    replica, installed = install2_impl(replica, inst, write=write)

    counters = jnp.stack(
        [
            valid.sum(dtype=jnp.int64),  # broadcasts applied as owner
            installed.sum(dtype=jnp.int64),  # replica installs
        ]
    )
    return primary, replica, counters, bc


def _mk_sync_step(
    mesh, n_shards: int, out_size: int, write: Optional[str] = None,
    wire: bool = False,
):
    """Build the jitted single-round collective sync step. `wire=True`
    takes the outbox as ONE compact (D, 5, OUT+1) int32 wire grid
    (ops/wire.py) decoded in-trace instead of a 12-leaf HostBatch pytree —
    one device put per round instead of twelve, at 20 B/entry instead of
    96 (PendingHits rounds were put-bound: BENCH_r05 measured 110 ms per
    16K-entry round against ~16 ms of compute)."""
    D = n_shards
    write = write or default_write_mode()
    axes = shard_axes(mesh)

    def per_device(primary, replica, outbox):
        primary = jax.tree.map(lambda x: x[0], primary)
        replica = jax.tree.map(lambda x: x[0], replica)
        if wire:
            from gubernator_tpu.ops.kernel2 import req_from_arr
            from gubernator_tpu.ops.wire import decode_wire_block

            arr12, _base = decode_wire_block(outbox[0])
            outbox = req_from_arr(arr12)
        else:
            outbox = jax.tree.map(lambda x: x[0], outbox)
        me = jax.lax.axis_index(axes)
        primary, replica, counters, bc = _sync_core(
            primary, replica, outbox, me, D, write, axes=axes
        )
        expand = lambda t: jax.tree.map(lambda x: x[None], t)
        # bc (this device's owner-applied rows) returns to the host so a
        # configured Store can write the reconciled state through — the
        # reference's OnChange fires on owner-side GLOBAL applies too
        return expand(primary), expand(replica), counters[None], expand(bc)

    spec = shard_spec(mesh)
    fn = shard_map_compat(
        per_device,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=(spec, spec, spec, spec),
        # check_vma=False: the Pallas sweep's out_shape carries no vma
        # annotation, which the checker (jax>=0.9) rejects inside shard_map
        check_vma=False,
    )
    return jax.jit(fn, donate_argnums=(0, 1))


def _mk_sync_step_multi(
    mesh, n_shards: int, rounds: int, write: Optional[str] = None,
    wire: bool = False,
):
    """Fused R-round sync step: a fori_loop over R stacked outboxes inside
    ONE launch. A deep drain (sync() after a burst) otherwise pays the
    put + launch + fetch transport cost per round — on RTT-bound links
    that is the whole cost (measured 730-870 ms/round on the dev tunnel vs
    ~16 ms of compute). Rounds with all-inactive outboxes are no-ops, so
    the host pads the round count to a fixed R and one compile serves
    every backlog ≤ R. Store-configured engines never use this step: the
    per-round bc must reach the Store write-through, so they stay on the
    single-round path."""
    D = n_shards
    write = write or default_write_mode()
    axes = shard_axes(mesh)

    def per_device(primary, replica, outboxes):
        primary = jax.tree.map(lambda x: x[0], primary)
        replica = jax.tree.map(lambda x: x[0], replica)
        # pytree: leaves (R, OUT); wire: ONE (R, 5, OUT+1) int32 grid
        outboxes = jax.tree.map(lambda x: x[0], outboxes)
        me = jax.lax.axis_index(axes)

        def body(i, carry):
            primary, replica, counters = carry
            outbox = jax.tree.map(
                lambda x: jax.lax.dynamic_index_in_dim(x, i, keepdims=False),
                outboxes,
            )
            if wire:
                from gubernator_tpu.ops.kernel2 import req_from_arr
                from gubernator_tpu.ops.wire import decode_wire_block

                arr12, _base = decode_wire_block(outbox)
                outbox = req_from_arr(arr12)
            primary, replica, c, _bc = _sync_core(
                primary, replica, outbox, me, D, write, axes=axes
            )
            return primary, replica, counters + c

        primary, replica, counters = jax.lax.fori_loop(
            0, rounds, body,
            (primary, replica, jnp.zeros((2,), dtype=jnp.int64)),
        )
        expand = lambda t: jax.tree.map(lambda x: x[None], t)
        return expand(primary), expand(replica), counters[None]

    spec = shard_spec(mesh)
    fn = shard_map_compat(
        per_device,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=(spec, spec, spec),
        check_vma=False,
    )
    return jax.jit(fn, donate_argnums=(0, 1))


class GlobalShardedEngine(ShardedEngine):
    """ShardedEngine + GLOBAL-behavior replicas and collective sync.

    `home_shard` models which node a client connected to (the reference's
    non-owner): GLOBAL requests are answered from that device's replica table
    and their hits accumulate until the next sync tick (GlobalSyncWait analog,
    default 100 ms, reference config.go:142-146).

    The daemon serving surface (`check_columns`) assigns each GLOBAL batch a
    rotating home device: successive front-door dispatches land on successive
    devices, modeling clients spread over the peer group — the replica plane
    absorbs the reads/hits and the collective sync reconciles them, which is
    the BASELINE #3 topology (8-peer cluster ↦ v5e-8 mesh over ICI)."""

    mesh_global = True  # daemon marker: this engine serves the GLOBAL
    # behavior through replica tables + collective sync

    def __init__(
        self,
        mesh,
        capacity_per_shard: int = 50_000,
        max_exact_passes: int = 8,
        sync_out: int = 256,
        created_at_tolerance_ms=None,
        store=None,
        route: Optional[str] = None,
        write_mode: Optional[str] = None,
        dedup: Optional[str] = None,
        wire: Optional[str] = None,
        a2a: Optional[str] = None,
        layout: Optional[str] = None,
        probe: Optional[str] = None,
        walk: Optional[str] = None,
    ):
        super().__init__(
            mesh,
            capacity_per_shard=capacity_per_shard,
            max_exact_passes=max_exact_passes,
            created_at_tolerance_ms=created_at_tolerance_ms,
            store=store,
            route=route,
            write_mode=write_mode,
            dedup=dedup,
            wire=wire,
            a2a=a2a,
            layout=layout,
            probe=probe,
            walk=walk,
        )
        # the replica table + collective step materialize on first GLOBAL
        # use: clustered daemons route GLOBAL over the host peer plane and
        # must not pay a second table's HBM or the sync-step compile
        self._capacity_per_shard = capacity_per_shard
        self.replica: Optional[Table2] = None
        self._sync_step = None
        self._sync_step_wire = None  # compact-outbox single-round step
        self._sync_multi = {}  # fused-drain steps, keyed by (rounds R, wire)
        self.sync_out = sync_out
        self.pending: List[PendingHits] = [
            PendingHits() for _ in range(self.n_shards)
        ]
        self.global_stats = GlobalStats()
        self._rr = 0  # rotating home-device assignment for served batches
        # the home counter is the one piece of engine state the pipelined
        # PREPARE stage touches (prep threads run concurrently)
        self._rr_lock = threading.Lock()

    def _ensure_global_plane(self) -> None:
        # the collective reconcile runs the mixed decision graph over
        # whatever algorithms GLOBAL keys use — a packed single-algorithm
        # primary cannot serve it; replicas are always full for the same
        # reason (installs carry arbitrary algos)
        self.migrate_layout_full("GLOBAL collective sync needs mixed math")
        if self.replica is None:
            self.replica = new_sharded_table(self.mesh, self._capacity_per_shard)
        if self._sync_step is None:
            self._sync_step = _mk_sync_step(
                self.mesh, self.n_shards, self.sync_out, write=self.write_mode
            )

    def _next_home(self) -> int:
        with self._rr_lock:
            h = self._rr % self.n_shards
            self._rr += 1
            return h

    def has_pending(self) -> bool:
        return any(len(p) for p in self.pending)

    def oldest_pending_age_s(self) -> float:
        """Age of the oldest un-synced mesh-GLOBAL hit across every home
        device's outbox (the in-mesh half of the staleness gauge)."""
        return max((p.age_s() for p in self.pending), default=0.0)

    # ------------------------------------------------------------------ check
    def check(
        self,
        requests: Sequence[RateLimitRequest],
        now_ms: Optional[int] = None,
        home_shard: int = 0,
    ) -> List[RateLimitResponse]:
        now = now_ms if now_ms is not None else ms_now()
        glob = [
            i
            for i, r in enumerate(requests)
            if has_behavior(r.behavior, Behavior.GLOBAL)
        ]
        if not glob:
            return super().check(requests, now_ms=now)
        rest = [i for i in range(len(requests)) if i not in set(glob)]
        out: List[Optional[RateLimitResponse]] = [None] * len(requests)
        if rest:
            sub = super().check([requests[i] for i in rest], now_ms=now)
            for i, r in zip(rest, sub):
                out[i] = r
        gsub = self._check_global([requests[i] for i in glob], now, home_shard)
        for i, r in zip(glob, gsub):
            out[i] = r
        return out  # type: ignore[return-value]

    def _check_global(
        self, requests: Sequence[RateLimitRequest], now: int, home: int
    ) -> List[RateLimitResponse]:
        """GLOBAL dispatch (object API). Array core shared with the daemon's
        columns path (`check_columns`)."""
        hb, errors = pack_requests(requests, now, tolerance_ms=self.created_at_tolerance_ms)
        out: List[Optional[RateLimitResponse]] = [None] * len(requests)
        for i, err in enumerate(errors):
            if err is not None:
                out[i] = RateLimitResponse(error=err)
        status, limit, remaining, reset, dropped = self._global_hb(hb, home, now)
        for i in range(len(requests)):
            if out[i] is None:
                out[i] = RateLimitResponse(
                    status=int(status[i]),
                    limit=int(limit[i]),
                    remaining=int(remaining[i]),
                    reset_time=int(reset[i]),
                    error=ERR_NOT_PERSISTED if dropped[i] else "",
                )
        self.stats.checks += len(requests)
        return out  # type: ignore[return-value]

    # ------------------------------------------------ daemon serving surface
    def check_columns(
        self, cols: RequestColumns, now_ms: Optional[int] = None
    ) -> ResponseColumns:
        """Columns-in/columns-out with the GLOBAL behavior honored on-mesh:
        GLOBAL rows are answered from a rotating home device's replica table
        (non-owner semantics, reference gubernator.go:401-429) with their hits
        accumulated for the collective sync tick; everything else takes the
        ownership-routed authoritative path. Store write-through/rehydrate
        fires on the authoritative paths (non-GLOBAL, GLOBAL owner rows, and
        the collective sync's reconciled state) — never on replica answers,
        which are transient by contract and would write stale state over the
        owner's."""
        gmask = (np.asarray(cols.behavior) & np.int32(Behavior.GLOBAL)) != 0
        if not gmask.any():
            return super().check_columns(cols, now_ms=now_ms)
        now = now_ms if now_ms is not None else ms_now()
        n = cols.fp.shape[0]
        status = np.zeros(n, dtype=np.int32)
        limit = np.zeros(n, dtype=np.int64)
        remaining = np.zeros(n, dtype=np.int64)
        reset = np.zeros(n, dtype=np.int64)
        err = np.zeros(n, dtype=np.int8)
        rest = np.nonzero(~gmask)[0]
        if rest.size:
            rc = super().check_columns(
                RequestColumns(*[f[rest] for f in cols]), now_ms=now
            )
            status[rest] = rc.status
            limit[rest] = rc.limit
            remaining[rest] = rc.remaining
            reset[rest] = rc.reset_time
            err[rest] = rc.err
        g = np.nonzero(gmask)[0]
        hb, perr = pack_columns(
            RequestColumns(*[f[g] for f in cols]),
            now,
            tolerance_ms=self.created_at_tolerance_ms,
        )
        err[g] = perr
        g_created = cols.created_at[g]
        self.stats.created_at_clamped += int(
            ((g_created != 0) & (hb.created_at != g_created)).sum()
        )
        s, l, r, t, dropped = self._global_hb(hb, self._next_home(), now)
        status[g] = s
        limit[g] = l
        remaining[g] = r
        reset[g] = t
        err[g[dropped]] = ERR_DROPPED
        self.stats.checks += int(g.size)
        from gubernator_tpu.ops.engine import _fold_cascades_host

        # host fold over the REASSEMBLED batch order (the GLOBAL/local
        # split above preserves original row positions)
        _fold_cascades_host(
            np.asarray(cols.behavior), status, remaining, reset, err
        )
        return ResponseColumns(
            status=status, limit=limit, remaining=remaining,
            reset_time=reset, err=err,
        )

    # ------------------------------------------------- pipelined GLOBAL path
    # The generic prepare/issue/finish split (ops/engine.py) can't express
    # the GLOBAL fork (replica answers + owner applies + hit queueing), so
    # this engine provides its own pending type through the
    # `prepare_columns`/`issue_pending`/`finish_pending` hooks — GLOBAL
    # batches ride the SAME pipeline as everything else instead of
    # serializing the front door (the round-4 `can_pipeline` veto): the prep
    # thread stages replica+owner+plain dispatches, the engine thread merges
    # queued hits and launches all of them back-to-back, and the fetch
    # thread materializes the outputs while the engine thread stages the
    # next batch. Store-configured engines never reach these hooks
    # (EngineRunner serializes them for write-through ordering).

    def _global_fork(self, hb: HostBatch, home: int):
        """Shared construction of the GLOBAL fork — the ONE place the queue
        rules live (serial `_global_hb` and pipelined `prepare_columns` both
        call it): zero-hit requests are never queued (global.go:85-95),
        owner-side hits queue as hits=0 broadcast markers (QueueUpdate →
        runBroadcasts), non-owner hits accumulate for the owner; non-owner
        rows answer from the home replica with GLOBAL stripped and
        NO_BATCHING forced (reference gubernator.go:416-422), owner rows run
        the authoritative table."""
        owner = shard_of(hb.fp, self.n_shards)
        is_owner_here = (owner == home) & hb.active
        q = np.nonzero(hb.active & (hb.hits != 0))[0]
        queue = _QueuedHits(
            hb=hb,
            rows=q,
            hits=np.where(is_owner_here[q], 0, hb.hits[q]).astype(np.int64),
            reset=hb.behavior[q] & np.int32(Behavior.RESET_REMAINING),
            home=home,
            n_remote=int((~is_owner_here[q]).sum()),
        )
        hb_replica = hb._replace(
            behavior=(hb.behavior & ~np.int32(Behavior.GLOBAL))
            | np.int32(Behavior.NO_BATCHING),
            active=hb.active & ~is_owner_here,
        )
        hb_owner = hb._replace(active=is_owner_here)
        return is_owner_here, queue, hb_replica, hb_owner

    def _apply_queue(self, qu: "_QueuedHits") -> None:
        """Fold prepared queue-merge inputs into the sync accumulator
        (engine thread only — single-writer)."""
        if qu.rows.size:
            self.pending[qu.home].merge(qu.hb, qu.rows, qu.hits, qu.reset)
            self.global_stats.hits_queued += qu.n_remote
        self.global_stats.send_queue_length = sum(len(p) for p in self.pending)

    def prepare_columns(self, cols: RequestColumns, now_ms=None):
        """Prepare hook (any thread): returns a GlobalPending for batches
        carrying GLOBAL rows, or None to route pure-local batches through
        the generic pipelined path."""
        gmask = (np.asarray(cols.behavior) & np.int32(Behavior.GLOBAL)) != 0
        if not gmask.any():
            return None
        now = now_ms if now_ms is not None else ms_now()
        hb, err = pack_columns(
            cols, now, tolerance_ms=self.created_at_tolerance_ms
        )
        clamped = int(
            ((cols.created_at != 0) & (hb.created_at != cols.created_at)).sum()
        )
        home = self._next_home()
        passes = []

        def plan_into(batch, table_attr, home_pin, rowmap):
            if not batch.active.any():
                return
            for p in self.plan(batch):
                if len(p.rows) == 0:
                    continue
                shard = (
                    np.full(p.batch.fp.shape[0], home_pin, dtype=np.int64)
                    if home_pin is not None
                    else None
                )
                staged = self._stage(p.batch, shard)
                passes.append(
                    [p, len(p.rows), p.batch, staged, table_attr, home_pin,
                     rowmap]
                )

        rest = np.nonzero(~gmask)[0]
        if rest.size:
            plan_into(_subset(hb, rest), "table", None, rest)
        g = np.nonzero(gmask)[0]
        hbg = _subset(hb, g)
        _owner_here, queue, hb_replica, hb_owner = self._global_fork(hbg, home)
        plan_into(hb_replica, "replica", home, g)
        plan_into(hb_owner, "table", None, g)
        return GlobalPending(
            hb=hb, err=err, now=now, queue=queue, passes=passes,
            clamped=clamped,
        )

    def issue_pending(self, pending: "GlobalPending") -> "GlobalPending":
        """Issue hook (engine thread): fold the queued hits into the sync
        accumulator, then launch every staged dispatch without fetching."""
        from gubernator_tpu.ops.engine import _stack_pass_outputs

        self._ensure_global_plane()
        # checkpoint marking for the pipelined GLOBAL fork: replica-pinned
        # rows are a harmless superset (dirty blocks only cost extract
        # bytes), and marking here — the engine-thread job that launches —
        # keeps the mark→mutate / take→extract FIFO contract
        self._mark_dirty(pending.hb.fp)
        self._apply_queue(pending.queue)
        for entry in pending.passes:
            staged, table_attr = entry[3], entry[4]
            table, out = self._decide(getattr(self, table_attr), staged)
            setattr(self, table_attr, table)
            entry[3] = (staged, out)
        pending.stacked = _stack_pass_outputs(
            [entry[3][1] for entry in pending.passes]
        )
        return pending

    def finish_pending(self, pending: "GlobalPending", fixup):
        """Finish hook (fetch thread): materialize every pass's output and
        assemble the full response; claim-drop retries run on the engine
        thread via `fixup` against the same table (replica pins preserved)."""
        from gubernator_tpu.ops.engine import EngineStats

        if pending.stacked is not None:
            # ONE fetch for every pass's output (cf. finish_check_columns)
            fetched = np.asarray(pending.stacked)
            for i, entry in enumerate(pending.passes):
                entry[3] = (entry[3][0], fetched[i])
        hb, err = pending.hb, pending.err
        n = hb.fp.shape[0]
        status = np.zeros(n, dtype=np.int32)
        limit_o = np.zeros(n, dtype=np.int64)
        remaining = np.zeros(n, dtype=np.int64)
        reset = np.zeros(n, dtype=np.int64)
        delta = EngineStats(created_at_clamped=pending.clamped, checks=n)
        for p, np_, batch, pend, table_attr, home_pin, rowmap in pending.passes:
            (s, l, r, t, dropped, hit), st, uncounted = self.finish_staged(
                pend, np_
            )
            delta.cache_hits += st[0]
            delta.cache_misses += st[1]
            delta.over_limit += st[2]
            delta.evicted_unexpired += st[3]
            delta.dispatches += 1
            if dropped.any():
                rows = np.nonzero(dropped)[0]

                def retry(rows=rows, batch=batch, uncounted=uncounted,
                          table_attr=table_attr, home_pin=home_pin):
                    sub = _subset(batch, rows)
                    shard = (
                        np.full(rows.size, home_pin, dtype=np.int64)
                        if home_pin is not None
                        else None
                    )
                    _, vals = self._dispatch(
                        sub, depth=1, shard=shard, table_attr=table_attr,
                        count=uncounted[rows] if uncounted is not None else None,
                    )
                    return vals

                s2, l2, r2, t2, d2, h2 = fixup(retry)
                s[rows], l[rows], r[rows], t[rows] = s2, l2, r2, t2
                dropped[rows] = d2
                hit[rows] = h2
            if p.member_rows:
                members = rowmap[np.concatenate(p.member_rows)]
                src = np.repeat(
                    np.arange(np_), [len(m) for m in p.member_rows]
                )
                status[members] = s[src]
                limit_o[members] = l[src]
                remaining[members] = r[src]
                reset[members] = t[src]
                err[members[dropped[src]]] = ERR_DROPPED
            else:
                rows_f = rowmap[p.rows]
                status[rows_f] = s[:np_]
                limit_o[rows_f] = l[:np_]
                remaining[rows_f] = r[:np_]
                reset[rows_f] = t[:np_]
                err[rows_f[dropped[:np_]]] = ERR_DROPPED
        from gubernator_tpu.ops.engine import _fold_cascades_host

        # cascade verdicts fold host-side on the mesh-global path (the
        # replica/owner fork re-orders rows, so no in-trace fold ran)
        _fold_cascades_host(hb.behavior, status, remaining, reset, err)
        rc = ResponseColumns(
            status=status, limit=limit_o, remaining=remaining,
            reset_time=reset, err=err,
        )
        return rc, delta

    def _global_hb(self, hb: HostBatch, home: int, now: Optional[int] = None):
        """The GLOBAL core over a packed batch: requests whose owner shard IS
        the home device run the owner path against the authoritative table and
        queue a broadcast (reference getLocalRateLimit + QueueUpdate,
        gubernator.go:653-690); everything else is answered from the home
        replica and its hits are queued for the owner (getGlobalRateLimit,
        gubernator.go:401-429). Returns per-row response arrays."""
        self._ensure_global_plane()
        n = hb.fp.shape[0]
        is_owner_here, queue, hb2, hb3 = self._global_fork(hb, home)
        self._apply_queue(queue)

        status = np.zeros(n, dtype=np.int32)
        limit = np.zeros(n, dtype=np.int64)
        remaining = np.zeros(n, dtype=np.int64)
        reset = np.zeros(n, dtype=np.int64)
        dropped = np.zeros(n, dtype=bool)
        self._global_passes(hb2, status, limit, remaining, reset, dropped,
                            table_attr="replica", home=home)
        # owner rows run the authoritative path on the primary shard — with
        # the Store contract honored there (write-through + miss rehydrate,
        # like the reference's owner-side getLocalRateLimit)
        self._global_passes(hb3, status, limit, remaining, reset, dropped,
                            table_attr="table", home=None, now=now)
        if self.store is not None and now is not None:
            own = np.nonzero(is_owner_here & ~dropped)[0]
            if own.size:
                from gubernator_tpu.store import ChangeSet

                rev = own[::-1]
                _, pos = np.unique(hb.fp[rev], return_index=True)
                keep = rev[pos]
                self.store.on_change(
                    ChangeSet(
                        fps=hb.fp[keep],
                        created_at=now,
                        algo=hb.algo[keep],
                        status=status[keep].astype(np.int32),
                        limit=limit[keep],
                        remaining=remaining[keep],
                        reset_time=reset[keep],
                        duration=hb.duration[keep],
                        burst=hb.burst[keep],
                        stamp=hb.created_at[keep],
                    )
                )
        return status, limit, remaining, reset, dropped

    def _global_passes(
        self, hb: HostBatch, status, limit, remaining, reset, dropped,
        table_attr: str, home, now: Optional[int] = None,
    ) -> None:
        if not hb.active.any():
            return
        use_store = (
            table_attr == "table" and home is None
            and self.store is not None and now is not None
        )
        for pi, p in enumerate(self.plan(hb)):
            nrows = len(p.rows)
            batch = pad_batch(p.batch, _pad_size(nrows))
            shard = (
                np.full(batch.fp.shape[0], home, dtype=np.int64)
                if home is not None
                else None
            )
            _, (s, l, r, t, d, _h) = self._dispatch(
                batch, shard=shard, table_attr=table_attr
            )
            if pi == 0 and use_store:
                from gubernator_tpu.ops.engine import _rehydrate_misses

                def disp(b, nb):
                    _, vals = self._dispatch(
                        pad_batch(b, _pad_size(nb)), table_attr="table"
                    )
                    return vals

                s, l, r, t, d, _h = _rehydrate_misses(
                    self, p.batch, nrows, (s, l, r, t, d, _h), now, disp
                )
            if p.member_rows:
                members = np.concatenate(p.member_rows)
                src = np.repeat(
                    np.arange(nrows), [len(m) for m in p.member_rows]
                )
                status[members] = s[src]
                limit[members] = l[src]
                remaining[members] = r[src]
                reset[members] = t[src]
                dropped[members] = d[src]
            else:
                rows = p.rows
                status[rows] = s[:nrows]
                limit[rows] = l[:nrows]
                remaining[rows] = r[:nrows]
                reset[rows] = t[:nrows]
                dropped[rows] = d[:nrows]

    # ------------------------------------------------------------------- sync
    def sync(self, now_ms: Optional[int] = None) -> None:
        """One sync tick: drain ALL pending hits, in as many collective
        rounds as the fixed outbox size requires. The reference flushes its
        queue on batch-limit OR timer and never leaves a backlog behind a tick
        (global.go:125-151); a fixed one-round outbox would silently backlog
        hot global keys beyond `sync_out`.

        Deep backlogs drain through the FUSED multi-round step (one launch
        runs R rounds on-device, `_mk_sync_step_multi`) unless a Store is
        configured — the Store write-through needs each round's bc on the
        host, so durable engines stay on the single-round path."""
        first = True
        while first or self.has_pending():
            first = False
            rounds = max(
                (len(p) + self.sync_out - 1) // self.sync_out
                for p in self.pending
            )
            if self.store is not None or rounds <= 1:
                self._sync_round(now_ms)
            else:
                self._sync_rounds_fused(rounds, now_ms)

    _SYNC_FUSE_CAP = 64  # max rounds per fused launch (bounds put size)

    def _build_box(self, d: int, now: int):
        """Pop ≤ sync_out entries of home `d` into one padded outbox.
        Returns (box, popped) — `popped` is the raw (cfg, hits, reset)
        columns removed from the accumulator (None when empty), kept so a
        failed collective launch can re-merge them instead of losing the
        hits (take() hands back copies, so the box's in-place stamping
        below never writes through into them)."""
        OUT = self.sync_out
        k = min(len(self.pending[d]), OUT)
        if k:
            popped = self.pending[d].take(OUT)
            cfg, hits, reset = popped
            # collective sync mutates owner shards (and replicas) for these
            # keys — mark before the launch (engine thread, sync job)
            self._mark_dirty(cfg.fp)
            box = pad_batch(cfg, OUT)
            box.hits[:k] = hits
            box.behavior[:k] |= reset
            box.created_at[:k] = now
            # re-anchor non-Gregorian expiries to the applied-at stamp the
            # rows were just given (created + duration — the linear rule the
            # compact wire decode reconstructs in-trace; Gregorian rows keep
            # their host-resolved calendar expiry and force the full-width
            # outbox). Under frozen-clock tests created == now already, so
            # this is identity there; live, it anchors a new item's expiry
            # at apply time instead of up to one sync cadence earlier.
            ng = box.greg_interval[:k] == 0
            box.expire_new[:k] = np.where(
                ng, now + box.duration[:k], box.expire_new[:k]
            )
        else:
            popped = None
            box = pad_batch(
                HostBatch(
                    *[np.zeros(0, dtype=f.dtype)
                      for f in pack_requests([], now)[0]]
                ),
                OUT,
            )
        return box, popped

    def _requeue_popped(self, popped, exc: BaseException) -> None:
        """A collective sync launch failed AFTER the accumulators were
        popped and the tables donated into the dead computation: re-merge
        every popped box (`popped`: (home, (cfg, hits, reset)) pairs) so the
        hits survive (the reference requeues failed owner sends rather than
        dropping; service/global_manager.py does the same on the peer
        plane), and poison the engine — the donated table/replica buffers
        may be invalid, so serving must surface unhealthy (daemon
        health_check) instead of answering from them."""
        for d, (cfg, hits, reset) in popped:
            self.pending[d].merge(
                cfg, np.arange(cfg.fp.shape[0]), hits, reset
            )
        self.global_stats.send_queue_length = sum(len(p) for p in self.pending)
        self.poisoned = f"GLOBAL collective sync launch failed: {exc}"

    def _wire_boxes(self, boxes, now: int) -> bool:
        """Can this round's outboxes ride the compact wire? All-or-nothing
        per launch: one grid dtype/shape per compiled step. Accumulated
        hot-key hits ≥ 2^18 or Gregorian configs fall the round back to the
        full-width pytree put (same semantics, 12 puts instead of one)."""
        if self.wire != "compact":
            return False
        from gubernator_tpu.ops.wire import wire_encodable

        return all(wire_encodable(b, now) for b in boxes)

    def _sync_rounds_fused(self, rounds_needed: int, now_ms: Optional[int]) -> None:
        """Drain up to R rounds in ONE launch: stack R outboxes per device,
        run the fused step. R pads to a power of two so one compile serves
        every backlog ≤ R (padded rounds carry all-inactive outboxes and
        apply nothing)."""
        self._ensure_global_plane()
        now = now_ms if now_ms is not None else ms_now()
        R = 2
        while R < rounds_needed and R < self._SYNC_FUSE_CAP:
            R *= 2
        # padded rounds all carry the same all-inactive outbox — build it
        # once (np.stack copies on assembly, so sharing the object is safe)
        empty_box = None
        popped = []  # (home, cfg/hits/reset) columns popped this drain

        def box(d: int) -> HostBatch:
            nonlocal empty_box
            if len(self.pending[d]) == 0:
                if empty_box is None:
                    empty_box, _ = self._build_box(d, now)
                return empty_box
            b, p = self._build_box(d, now)
            if p is not None:
                popped.append((d, p))
            return b

        boxes = [[box(d) for d in range(self.n_shards)] for _r in range(R)]
        wire = self._wire_boxes(
            [boxes[r][d] for r in range(R) for d in range(self.n_shards)], now
        )
        step = self._sync_multi.get((R, wire))
        if step is None:
            step = self._sync_multi[(R, wire)] = _mk_sync_step_multi(
                self.mesh, self.n_shards, R, write=self.write_mode, wire=wire
            )
        try:
            if wire:
                from gubernator_tpu.ops import wire as wire_mod

                OUT = self.sync_out
                grid = np.zeros(
                    (self.n_shards, R, wire_mod.WIRE_LANES, OUT + 1),
                    dtype=np.int32,
                )
                for r in range(R):
                    for d in range(self.n_shards):
                        b = boxes[r][d]
                        if b is empty_box:  # zeros already; base only
                            wire_mod.stamp_base(grid[d, r], now)
                        else:
                            wire_mod.pack_wire_full(b, now, out=grid[d, r])
                dev = jax.device_put(grid, self._batch_sharding)
            else:
                stacked = HostBatch(
                    *[
                        np.stack(
                            [
                                np.stack([boxes[r][d][k] for r in range(R)])
                                for d in range(self.n_shards)
                            ]
                        )
                        for k in range(len(boxes[0][0]))
                    ]
                )  # leaves (D, R, OUT)
                dev = jax.tree.map(
                    lambda x: jax.device_put(
                        jnp.asarray(x), self._batch_sharding
                    ),
                    stacked,
                )
            self.table, self.replica, counters = step(
                self.table, self.replica, dev
            )
        except Exception as exc:
            self._requeue_popped(popped, exc)
            raise
        c = np.asarray(counters)
        # count the rounds that carried work, not the pow2 padding — the
        # gubernator_mesh_sync_rounds series must read the same for
        # identical traffic whichever drain path ran
        self.global_stats.sync_rounds += min(rounds_needed, R)
        self.global_stats.broadcasts_applied += int(c[:, 0].sum())
        self.global_stats.updates_installed += int(c[:, 1].sum())
        self.global_stats.send_queue_length = sum(len(p) for p in self.pending)

    def warm_sync_steps(self, now_ms: Optional[int] = None) -> None:
        """Pre-trace the collective sync steps — the single-round step plus
        every fused R variant — with empty outboxes (all-inactive rounds
        apply nothing; only the compile caches change). Without this the
        first deep backlog compiles a fused variant ON the engine thread
        mid-tick, stalling all serving behind a cold XLA compile. Engine
        thread only (mutates the donated tables through no-op steps). The
        caller should reset global_stats afterwards — warm rounds are not
        traffic. Compact-wire engines warm BOTH outbox formats: a round
        whose accumulated hits overflow the narrow layout falls back to
        the pytree step, and that compile must not land mid-tick either."""
        self._ensure_global_plane()
        modes = ("compact", "full") if self.wire == "compact" else (self.wire,)
        saved = self.wire
        try:
            for mode in modes:
                self.wire = mode
                self._sync_round(now_ms)
                R = 2
                while R <= self._SYNC_FUSE_CAP:
                    self._sync_rounds_fused(R, now_ms)
                    R *= 2
        finally:
            self.wire = saved

    def _sync_round(self, now_ms: Optional[int] = None) -> None:
        """One collective hit-sync + broadcast round. The outbox ships as
        ONE compact int32 wire grid when every box is representable
        (ops/wire.py — one put instead of twelve at ~a fifth the bytes),
        falling back to the HostBatch pytree put otherwise."""
        self._ensure_global_plane()
        now = now_ms if now_ms is not None else ms_now()
        built = [self._build_box(d, now) for d in range(self.n_shards)]
        boxes = [b for b, _p in built]
        popped = [(d, p) for d, (_b, p) in enumerate(built) if p is not None]
        wire = self._wire_boxes(boxes, now)
        if wire and self._sync_step_wire is None:
            self._sync_step_wire = _mk_sync_step(
                self.mesh, self.n_shards, self.sync_out,
                write=self.write_mode, wire=True,
            )
        try:
            if wire:
                from gubernator_tpu.ops import wire as wire_mod

                grid = np.zeros(
                    (self.n_shards, wire_mod.WIRE_LANES, self.sync_out + 1),
                    dtype=np.int32,
                )
                for d, b in enumerate(boxes):
                    wire_mod.pack_wire_full(b, now, out=grid[d])
                dev_box = jax.device_put(grid, self._batch_sharding)
                self.table, self.replica, counters, bc = self._sync_step_wire(
                    self.table, self.replica, dev_box
                )
            else:
                stacked = HostBatch(
                    *[np.stack([b[k] for b in boxes]) for k in range(len(boxes[0]))]
                )
                dev_box = jax.tree.map(
                    lambda x: jax.device_put(jnp.asarray(x), self._batch_sharding),
                    stacked,
                )
                self.table, self.replica, counters, bc = self._sync_step(
                    self.table, self.replica, dev_box
                )
        except Exception as exc:
            # the popped hit boxes must survive a failed launch (ADVICE r5):
            # re-merge them and mark the engine unhealthy — the donated
            # tables went into the dead computation
            self._requeue_popped(popped, exc)
            raise
        c = np.asarray(counters)
        self.global_stats.sync_rounds += 1
        self.global_stats.broadcasts_applied += int(c[:, 0].sum())
        self.global_stats.updates_installed += int(c[:, 1].sum())
        self.global_stats.send_queue_length = sum(len(p) for p in self.pending)
        if self.store is not None:
            # owner-reconciled GLOBAL state writes through (reference fires
            # OnChange inside the owner's getLocalRateLimit on the GLOBAL
            # apply path too); bc is lazy — only materialized here
            from gubernator_tpu.store import ChangeSet

            flat = lambda x: np.asarray(x).reshape(-1)
            active = flat(bc.active)
            rows = np.nonzero(active)[0]
            if rows.size:
                self.store.on_change(
                    ChangeSet(
                        fps=flat(bc.fp)[rows],
                        created_at=now,
                        algo=flat(bc.algo)[rows],
                        status=flat(bc.status)[rows].astype(np.int32),
                        limit=flat(bc.limit)[rows],
                        remaining=flat(bc.remaining)[rows],
                        reset_time=flat(bc.reset_time)[rows],
                        duration=flat(bc.duration)[rows],
                        burst=flat(bc.burst)[rows],
                        stamp=flat(bc.stamp)[rows],
                    )
                )
