"""Sharded table telemetry: the per-device scan as one mesh collective.

Same fused statistics pass as ops/telemetry.py, run per shard under
shard_map so (a) each device streams only its own (NB, 128) table slice —
no cross-device gather of 100M-key state just to count it — and (b) the
per-device vectors come back stacked (D, VEC_LEN), which is what makes
shard *imbalance* observable: a Zipf-hot shard shows up as one row's live
count diverging long before its buckets start evicting live keys.

Every stats-vector entry is additive over disjoint row sets (ops/telemetry
layout contract), so the host sums the D rows for table-wide totals and
keeps column 0 (per-shard live counts) for the debug plane.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from jax.sharding import Mesh

from gubernator_tpu.ops.telemetry import (
    PendingScan,
    _scan_body,
    block_width,
)
from gubernator_tpu.ops.table2 import K
from gubernator_tpu.parallel.mesh import shard_map_compat, shard_spec


def make_sharded_scan(mesh: Mesh, n_buckets: int, layout=None):
    """Jitted all-shards telemetry step: (D, NB, ROW_layout) rows →
    (D, VEC_LEN) per-shard stats vectors. The table is NOT donated — the
    scan is a pure read racing nothing (it runs issued from the engine
    thread like every other table access)."""
    blk = block_width(n_buckets)

    def per_device(rows: jnp.ndarray, now: jnp.ndarray):
        return _scan_body(rows[0], now[0, 0], blk, layout)[None]

    spec = shard_spec(mesh)
    fn = shard_map_compat(
        per_device, mesh=mesh, in_specs=(spec, spec), out_specs=spec,
        check_vma=False,
    )
    return jax.jit(fn)


def sharded_scan_begin(engine, now_ms: int) -> PendingScan:
    """Launch the mesh telemetry scan over a ShardedEngine's table without
    fetching (engine-thread half; finish with ops.telemetry.finish_scan).
    The compiled step is cached on the engine — the geometry never changes
    between scans."""
    rows = engine.table.rows
    D, nb = int(rows.shape[0]), int(rows.shape[1])
    fn = getattr(engine, "_telemetry_fn", None)
    if fn is None or getattr(engine, "_telemetry_layout", None) is not (
        engine.table.layout
    ):
        fn = engine._telemetry_fn = make_sharded_scan(
            engine.mesh, nb, layout=engine.table.layout
        )
        engine._telemetry_layout = engine.table.layout
    now = jax.device_put(
        jnp.full((D, 1), now_ms, dtype=jnp.int64), engine._batch_sharding
    )
    vec = fn(rows, now)
    return PendingScan(
        vec, now_ms, capacity=D * nb * K, n_buckets=D * nb, per_shard=True
    )
