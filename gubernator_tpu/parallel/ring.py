"""Ownership exchange over the mesh: collective vs hand-rolled ring.

`a2a.make_a2a_decide` needs one primitive: every device holds a (D, …)
send buffer whose block d is destined for device d; deliver each block and
hand back a (D, …) recv buffer whose block s came from device s. The seed
paid ONE monolithic `lax.all_to_all` per direction — correct, but opaque to
XLA's scheduler: the whole exchange serializes before any owner-side work
can start, and on multi-host meshes the single collective's cost is set by
the slowest (DCN) edge.

This module adds a hand-rolled RING schedule for the same primitive
(GUBER_A2A_IMPL=ring|collective|auto):

* hop k (k = 1..D-1): device d sends block (d+k) mod D directly to device
  (d+k) mod D and receives block from (d-k) mod D — after D-1 hops every
  block has moved exactly once, and the recv layout is byte-identical to
  `all_to_all(split_axis=0, concat_axis=0)` by construction;
* hops are DOUBLE-BUFFERED: hop k+1's transfer starts before hop k's
  completion wait, so transfer (k+1) overlaps the receive-side merge of
  hop k instead of the hops serializing end-to-end.

Two lowerings share that schedule:

* **TPU** — a Pallas kernel (`_ring_pallas`): per-hop
  `pltpu.make_async_remote_copy` with two send/recv DMA-semaphore slots
  alternating per hop parity (the SNIPPETS [1]-[3] remote-DMA pattern, cf.
  the jax Pallas TPU distributed-programming recipe). The send buffer
  stays in HBM (memory_space ANY); the DMA engines move blocks while the
  core is free — this is what lets hop N+1's DMA ride under hop N's
  owner-side work.
* **CPU / parity oracle** — per-hop `lax.ppermute` shifts
  (`_ring_shifts`): the same hop decomposition expressed in XLA
  collectives, runnable on the simulated CPU meshes, byte-identical to
  the Pallas schedule AND to the all_to_all oracle. This is the lowering
  the parity suites (tests/test_ring_exchange.py, ci mesh_smoke) pin.

`GUBER_A2A_IMPL=auto` (default) picks ring on TPU backends — per-hop
overlap where there is real DMA hardware — and collective elsewhere, so
CPU test meshes keep the seed's exact lowering unless a suite opts in.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from gubernator_tpu.parallel.mesh import (
    devices_per_host,
    mesh_hosts,
    shard_axes,
)

A2A_IMPLS = ("auto", "ring", "collective")


def a2a_impl(override: "str | None" = None) -> str:
    """Resolve the exchange implementation: explicit override, then
    GUBER_A2A_IMPL, then auto (ring on TPU, collective elsewhere). Read at
    trace time like the sparse-write knobs — flipping the env re-selects on
    the next compile, no restart."""
    impl = override or os.environ.get("GUBER_A2A_IMPL", "auto")
    if impl not in A2A_IMPLS:
        raise ValueError(
            f"GUBER_A2A_IMPL must be one of {A2A_IMPLS}, got {impl!r}"
        )
    if impl == "auto":
        return "ring" if jax.default_backend() == "tpu" else "collective"
    return impl


def exchange(block: jnp.ndarray, mesh: Mesh, impl: str) -> jnp.ndarray:
    """Deliver per-destination blocks (leading axis = destination device)
    and return per-source blocks (leading axis = source device). Must be
    called INSIDE a shard_map over `mesh`'s axes. The recv layout is
    identical for every impl — `impl` is a schedule choice, never a
    semantics one."""
    D = int(mesh.devices.size)
    if D == 1 or impl == "collective":
        if D == 1:
            return block
        return jax.lax.all_to_all(
            block, shard_axes(mesh), split_axis=0, concat_axis=0
        )
    if impl != "ring":
        raise ValueError(f"unknown exchange impl {impl!r}")
    if jax.default_backend() == "tpu":
        return _ring_pallas(block, mesh)
    return _ring_shifts(block, shard_axes(mesh), D)


# ------------------------------------------------ ring: portable lowering


def _ring_shifts(
    block: jnp.ndarray, axes, D: int, hops: "int | None" = None
) -> jnp.ndarray:
    """The ring schedule in XLA collectives: hop k is one shift-k ppermute
    moving each device's block (me+k) directly to its owner. XLA schedules
    hop k+1's permute concurrently with hop k's recv-buffer update (the
    dynamic_update_slice below) — the collective-level rendering of the
    Pallas kernel's start-before-wait. `hops` truncates the loop (bench
    probes time k-hop prefixes to expose per-hop cost); full exchanges use
    hops=None = D-1."""
    me = jax.lax.axis_index(axes)
    own = jax.lax.dynamic_index_in_dim(block, me, axis=0, keepdims=True)
    out = jax.lax.dynamic_update_slice_in_dim(
        jnp.zeros_like(block), own, me, axis=0
    )
    n_hops = D - 1 if hops is None else min(hops, D - 1)
    for k in range(1, n_hops + 1):
        # my block for the device k steps ahead…
        blk = jax.lax.dynamic_index_in_dim(
            block, (me + k) % D, axis=0, keepdims=False
        )
        # …rides the shift-k permutation; the block landing here left
        # (me - k) mod D, which addressed it to me
        got = jax.lax.ppermute(
            blk, axes, perm=[(i, (i + k) % D) for i in range(D)]
        )
        out = jax.lax.dynamic_update_slice_in_dim(
            out, got[None], (me - k) % D, axis=0
        )
    return out


# ------------------------------------------------ ring: TPU Pallas lowering


def _ring_kernel(in_ref, out_ref, local_sem, send_sem, recv_sem, *, D, axes, dl):
    """Per-device body: D-1 remote-DMA hops, two semaphore slots alternating
    per hop parity so hop k+1's DMA starts before hop k's wait (hop k+2
    cannot start before hop k completed — its slot is still armed — which
    is exactly the depth-2 pipeline the staging ring already assumes)."""
    from jax.experimental.pallas import tpu as pltpu

    me = _linear_axis_index(axes, dl)

    def coords(t):
        # device_id as mesh coordinates, matching the mesh's axis order
        if isinstance(axes, tuple):
            return (t // dl, t % dl)
        return (t,)

    def rdma(k):
        t = (me + k) % D
        return pltpu.make_async_remote_copy(
            src_ref=in_ref.at[t],
            # slot index on the RECEIVER is the sender's id: device t files
            # my block under out[me], the all_to_all source-major layout
            dst_ref=out_ref.at[me],
            send_sem=send_sem.at[(k - 1) % 2],
            recv_sem=recv_sem.at[(k - 1) % 2],
            device_id=coords(t),
            device_id_type=pltpu.DeviceIdType.MESH,
        )

    # own block never crosses the wire: local async copy, overlapped with
    # every hop, waited last
    local = pltpu.make_async_copy(in_ref.at[me], out_ref.at[me], local_sem)
    local.start()
    if D > 1:
        rdma(1).start()
        for k in range(1, D):
            if k + 1 < D:
                rdma(k + 1).start()  # double-buffer: next hop in flight…
            rdma(k).wait()  # …while this hop's arrival completes
    local.wait()


def _linear_axis_index(axes, dl: int):
    if isinstance(axes, tuple):
        host, dev = axes
        return jax.lax.axis_index(host) * dl + jax.lax.axis_index(dev)
    return jax.lax.axis_index(axes)


def _ring_pallas(block: jnp.ndarray, mesh: Mesh) -> jnp.ndarray:
    """pl.pallas_call wrapper for the ring kernel: send/recv buffers live in
    HBM (memory space ANY — the DMA engines address them directly), two DMA
    semaphores per direction in scratch. TPU backends only; the portable
    `_ring_shifts` lowering carries the identical schedule elsewhere."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    D = int(mesh.devices.size)
    any_space = getattr(pltpu, "ANY", None)
    if any_space is None:  # jax 0.4.x spells it TPUMemorySpace.ANY
        any_space = pltpu.TPUMemorySpace.ANY
    kernel = functools.partial(
        _ring_kernel, D=D, axes=shard_axes(mesh), dl=devices_per_host(mesh)
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        in_specs=[pl.BlockSpec(memory_space=any_space)],
        out_specs=pl.BlockSpec(memory_space=any_space),
        scratch_shapes=(
            [pltpu.SemaphoreType.DMA]  # local-copy completion
            + [pltpu.SemaphoreType.DMA((2,))] * 2  # send/recv, 2 slots each
        ),
    )
    compiler_params = None
    if hasattr(pltpu, "CompilerParams"):
        compiler_params = pltpu.CompilerParams(
            has_side_effects=True, collective_id=0
        )
    elif hasattr(pltpu, "TPUCompilerParams"):
        compiler_params = pltpu.TPUCompilerParams(
            has_side_effects=True, collective_id=0
        )
    kw = {}
    if compiler_params is not None:
        kw["compiler_params"] = compiler_params
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(block.shape, block.dtype),
        grid_spec=grid_spec,
        **kw,
    )(block)


# ------------------------------------------------------- bench/exp probes


def make_exchange_probe(
    mesh: Mesh,
    block_shape: tuple,
    impl: str,
    hops: "int | None" = None,
    dtype=jnp.int32,
):
    """Jitted exchange-only step for the pod-scaling bench and the MULTICHIP
    dryrun: (D, *block_shape) sharded array → exchanged array. For the ring
    impl `hops` truncates the schedule (hops=1, 2, … expose the marginal
    per-hop cost — the "per-hop exchange ms" column); the collective impl
    ignores `hops` (it has no hop structure to truncate). The probe moves
    the same bytes as a real a2a dispatch of that geometry, so its wall
    time is the exchange leg of the stage split."""
    from gubernator_tpu.parallel.mesh import shard_spec

    D = int(mesh.devices.size)
    axes = shard_axes(mesh)

    def per_device(x):
        x = x[0]
        if D == 1:
            out = x
        elif impl == "collective":
            out = jax.lax.all_to_all(x, axes, split_axis=0, concat_axis=0)
        elif jax.default_backend() == "tpu" and hops is None:
            out = _ring_pallas(x, mesh)
        else:
            out = _ring_shifts(x, axes, D, hops=hops)
        return out[None]

    from gubernator_tpu.parallel.mesh import shard_map_compat

    spec = shard_spec(mesh)
    fn = shard_map_compat(
        per_device, mesh=mesh, in_specs=(spec,), out_specs=spec,
        check_vma=False,
    )
    return jax.jit(fn)
