from gubernator_tpu.parallel.mesh import make_mesh
from gubernator_tpu.parallel.sharded import ShardedEngine

__all__ = ["make_mesh", "ShardedEngine"]
