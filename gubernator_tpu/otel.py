"""OTLP/HTTP JSON trace export — spans actually land in a collector.

The reference instruments everything with the OpenTelemetry SDK and exports
wherever the standard OTEL_* envs point (reference daemon.go:136,
cmd/gubernator/main.go:90-97, docs/tracing.md:43-54). This build's tracing
core (gubernator_tpu.tracing) is SDK-free, so the exporter speaks the OTLP
1.x HTTP+JSON encoding directly (https://opentelemetry.io/docs/specs/otlp/)
— any OTLP-capable collector (otel-collector, Jaeger, Tempo, ...) accepts
it with zero extra dependencies. Enabled by OTEL_EXPORTER_OTLP_ENDPOINT /
OTEL_EXPORTER_OTLP_TRACES_ENDPOINT; service name from OTEL_SERVICE_NAME.

Spans batch on a daemon thread (never the serving path): `record` appends
to a bounded buffer, the worker flushes every couple of seconds or at the
batch cap, and export failures are counted and dropped — tracing must never
take the service down with it.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
from collections import deque
from typing import List, Optional

log = logging.getLogger("gubernator_tpu.otel")

MAX_BUFFER = 8192  # spans held before the oldest drop (backpressure-free)


def _attr_value(v) -> dict:
    """Python value → OTLP JSON AnyValue (ints are strings per the OTLP 1.x
    JSON mapping of int64)."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def parse_resource_attributes(raw: str) -> dict:
    """OTEL_RESOURCE_ATTRIBUTES parser: comma-separated key=value pairs with
    percent-encoded values (the W3C Baggage subset the OTEL spec mandates).
    Malformed pairs are skipped — resource decoration must never stop the
    exporter from coming up."""
    from urllib.parse import unquote

    out: dict = {}
    for pair in raw.split(","):
        if "=" not in pair:
            continue
        k, _, v = pair.partition("=")
        k = k.strip()
        if k:
            out[k] = unquote(v.strip())
    return out


class OTLPJsonExporter:
    def __init__(
        self,
        endpoint: str,
        service_name: str = "gubernator-tpu",
        flush_interval_s: float = 2.0,
        max_batch: int = 512,
        append_path: bool = True,
        resource_attributes: Optional[dict] = None,
    ):
        # OTLP spec: the generic endpoint gets the per-signal path appended;
        # a signal-specific endpoint is used VERBATIM (append_path=False)
        ep = endpoint.rstrip("/")
        if append_path and not ep.endswith("/v1/traces"):
            ep = ep + "/v1/traces"
        self.endpoint = ep
        self.service_name = service_name
        # extra resource attributes (OTEL_RESOURCE_ATTRIBUTES): what lets a
        # shared collector tell multi-daemon cluster nodes apart
        self.resource_attributes = dict(resource_attributes or {})
        self.flush_interval_s = flush_interval_s
        self.max_batch = max_batch
        self.exported = 0
        self.dropped = 0
        self.export_errors = 0
        # drop-oldest in O(1): record() runs on the serving thread and must
        # not memmove thousands of entries when the collector is down
        self._buf: "deque[dict]" = deque(maxlen=MAX_BUFFER)
        self._lock = threading.Lock()
        self._kick = threading.Event()
        self._closed = False
        self._worker = threading.Thread(
            target=self._run, name="otel-export", daemon=True
        )
        self._worker.start()

    # ------------------------------------------------------------- recording
    def record(
        self,
        name: str,
        span,
        parent_span_id: str,
        start_ns: int,
        end_ns: int,
        attributes: Optional[dict] = None,
        links=(),
        kind: int = 2,
    ) -> None:
        """tracing.end_scope / tracing.record_span feed finished spans here
        (serving thread — must stay O(1) and never block). `kind` defaults
        to SPAN_KIND_SERVER (request scopes wrap RPC handling); stage spans
        pass SPAN_KIND_INTERNAL (1). `links` carries SpanContexts of related
        spans in OTHER traces — the batch-aware causality edge."""
        entry = {
            "traceId": span.trace_id,
            "spanId": span.span_id,
            "name": name,
            "kind": kind,
            "startTimeUnixNano": str(start_ns),
            "endTimeUnixNano": str(end_ns),
        }
        if parent_span_id:
            entry["parentSpanId"] = parent_span_id
        if attributes:
            entry["attributes"] = [
                {"key": k, "value": _attr_value(v)}
                for k, v in attributes.items()
            ]
        if links:
            entry["links"] = [
                {"traceId": l.trace_id, "spanId": l.span_id} for l in links
            ]
        with self._lock:
            if len(self._buf) == MAX_BUFFER:
                self.dropped += 1  # deque(maxlen) evicts the oldest
            self._buf.append(entry)
            if len(self._buf) >= self.max_batch:
                self._kick.set()

    # -------------------------------------------------------------- flushing
    def _drain(self) -> List[dict]:
        with self._lock:
            out = list(self._buf)
            self._buf.clear()
        return out

    def _payload(self, spans: List[dict]) -> bytes:
        resource_attrs = [
            {
                "key": "service.name",
                "value": {"stringValue": self.service_name},
            }
        ] + [
            {"key": k, "value": _attr_value(v)}
            for k, v in self.resource_attributes.items()
            if k != "service.name"
        ]
        return json.dumps(
            {
                "resourceSpans": [
                    {
                        "resource": {"attributes": resource_attrs},
                        "scopeSpans": [
                            {
                                "scope": {"name": "gubernator_tpu"},
                                "spans": spans,
                            }
                        ],
                    }
                ]
            }
        ).encode()

    def _post(self, spans: List[dict]) -> None:
        if not spans:
            return
        req = urllib.request.Request(
            self.endpoint,
            data=self._payload(spans),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=5.0):
                pass
            self.exported += len(spans)
        except Exception:
            # counted + dropped, never retried and never raised into the
            # serving path (the reference's exporter failures log and move on)
            self.export_errors += 1
            log.debug("OTLP export to %s failed", self.endpoint, exc_info=True)

    def _post_batched(self, spans: List[dict]) -> None:
        # max_batch caps the spans per POST too, not just the kick
        # threshold — a collector's request-size limit must not reject a
        # whole backlog at once
        for i in range(0, len(spans), self.max_batch):
            self._post(spans[i : i + self.max_batch])

    def _run(self) -> None:
        while not self._closed:
            self._kick.wait(timeout=self.flush_interval_s)
            self._kick.clear()
            self._post_batched(self._drain())

    def flush(self) -> None:
        """Synchronous flush of everything recorded so far (tests, shutdown)."""
        self._post_batched(self._drain())

    def close(self) -> None:
        self._closed = True
        self._kick.set()
        self._worker.join(timeout=5.0)
        self.flush()


def exporter_from_env(env=None):
    """Build an exporter when the standard OTEL_* envs ask for one, else
    None (reference semantics: exporters configured by OTEL_* envs,
    docs/tracing.md:43-54)."""
    import os

    env = os.environ if env is None else env
    traces_ep = env.get("OTEL_EXPORTER_OTLP_TRACES_ENDPOINT", "")
    generic_ep = env.get("OTEL_EXPORTER_OTLP_ENDPOINT", "")
    if not traces_ep and not generic_ep:
        return None
    # resource attributes: OTEL_RESOURCE_ATTRIBUTES decorates every span so
    # multi-daemon clusters sharing one collector stay distinguishable;
    # OTEL_SERVICE_NAME takes precedence over a service.name entry (the
    # OTEL SDK precedence rule)
    attrs = parse_resource_attributes(env.get("OTEL_RESOURCE_ATTRIBUTES", ""))
    service = (
        env.get("OTEL_SERVICE_NAME", "")
        or attrs.pop("service.name", "")
        or "gubernator-tpu"
    )
    attrs.pop("service.name", None)
    return OTLPJsonExporter(
        traces_ep or generic_ep,
        service_name=service,
        # per OTLP spec the signal-specific endpoint is used verbatim; only
        # the generic endpoint gets /v1/traces appended
        append_path=not traces_ep,
        resource_attributes=attrs,
    )
