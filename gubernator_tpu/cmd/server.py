"""Server binary — boot one daemon from the environment and serve until
SIGTERM/SIGINT (reference cmd/gubernator/main.go:50-131).

Flags mirror the reference's two: --config (env file) and --debug.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal
import sys
from typing import Optional

log = logging.getLogger("gubernator_tpu")

LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}


def setup_logging(level: str, debug: bool = False) -> None:
    logging.basicConfig(
        stream=sys.stderr,
        level=logging.DEBUG if debug else LEVELS.get(level.lower(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )


async def serve(
    config_file: str = "",
    debug: bool = False,
    stop: Optional[asyncio.Event] = None,
    ready=None,
):
    """Spawn a daemon and run until `stop` (or a signal) fires. `ready` is
    called with the live Daemon once listeners answer — the test seam, and the
    WaitForConnect analog (reference daemon.go:493-530)."""
    from gubernator_tpu.config import setup_daemon_config
    from gubernator_tpu.service.daemon import Daemon

    conf = setup_daemon_config(config_file)
    setup_logging(conf.log_level, debug)
    daemon = await Daemon.spawn(conf)
    log.info(
        "gubernator-tpu serving: grpc=%s http=%s instance=%s",
        conf.grpc_address, conf.http_address, conf.instance_id,
    )
    stop = stop or asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, RuntimeError):  # pragma: no cover
            pass
    if ready is not None:
        res = ready(daemon)
        if asyncio.iscoroutine(res):
            await res
    try:
        await stop.wait()
    finally:
        log.info("caught signal; shutting down")
        await daemon.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="gubernator-tpu", description="TPU-native rate-limiting daemon"
    )
    p.add_argument("--config", default="", help="environment config file")
    p.add_argument("--debug", action="store_true", help="enable debug logging")
    args = p.parse_args(argv)
    try:
        asyncio.run(serve(args.config, args.debug))
    except KeyboardInterrupt:  # pragma: no cover
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
