"""Entry-point binaries (the reference's cmd/ tree, L8):

* ``python -m gubernator_tpu``               — server daemon (cmd/gubernator)
* ``python -m gubernator_tpu.cmd.cli``       — load generator (cmd/gubernator-cli)
* ``python -m gubernator_tpu.cmd.cluster``   — local in-process cluster
                                               (cmd/gubernator-cluster)
* ``python -m gubernator_tpu.cmd.healthcheck`` — k8s probe (cmd/healthcheck)
"""
