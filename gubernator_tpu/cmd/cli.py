"""Load-generator CLI — the benchmarking driver (reference
cmd/gubernator-cli/main.go:51-227).

Generates a corpus of random token-bucket limits (2000 by default, limit
1-1000, duration 500ms-6s, BATCHING), then replays GetRateLimits against an
endpoint with bounded concurrency and an optional open-loop request rate,
logging OVER_LIMIT responses. Adds what the reference's CLI lacks: a latency
histogram (p50/p99/max) and a --seconds bound so runs terminate.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import random
import string
import sys
import time
from typing import List

log = logging.getLogger("gubernator-cli")


def random_string(n: int = 10) -> str:
    # reference client.go RandomString
    return "".join(random.choices(string.ascii_letters + string.digits, k=n))


def make_rate_limits(count: int):
    """The reference's corpus: random limits/durations (main.go:120-132)."""
    from gubernator_tpu.proto import gubernator_pb2 as pb

    return [
        pb.RateLimitReq(
            name=f"gubernator-cli-{i}",
            unique_key=random_string(10),
            hits=1,
            limit=random.randint(1, 999),
            duration=random.randint(500, 6000),
            behavior=pb.BATCHING,
            algorithm=pb.TOKEN_BUCKET,
        )
        for i in range(count)
    ]


class OpenLoopLimiter:
    """Paces request starts at `rate`/s independent of completions (the
    golang.org/x/time/rate analog the reference CLI uses, main.go:135-141)."""

    def __init__(self, rate: float):
        self.interval = 1.0 / rate
        self._next = time.perf_counter()

    async def wait(self) -> None:
        now = time.perf_counter()
        self._next = max(self._next + self.interval, now - 10 * self.interval)
        delay = self._next - now
        if delay > 0:
            await asyncio.sleep(delay)


RESERVOIR_CAP = 100_000


class Stats:
    """Latencies go into a bounded reservoir sample (uniform over the run) so
    endless soak runs report percentiles in O(1) memory; max is exact."""

    def __init__(self):
        self.requests = 0
        self.checks = 0
        self.over_limit = 0
        self.errors = 0
        self.latencies: List[float] = []
        self.max_latency = 0.0
        self._observed = 0

    def observe(self, latency_s: float) -> None:
        self.max_latency = max(self.max_latency, latency_s)
        self._observed += 1
        if len(self.latencies) < RESERVOIR_CAP:
            self.latencies.append(latency_s)
        else:
            j = random.randrange(self._observed)
            if j < RESERVOIR_CAP:
                self.latencies[j] = latency_s

    def report(self, elapsed: float) -> dict:
        lat = sorted(self.latencies)

        def pct(p):
            return lat[min(len(lat) - 1, int(p * len(lat)))] * 1e3 if lat else 0.0

        return {
            "elapsed_s": round(elapsed, 2),
            "requests": self.requests,
            "checks": self.checks,
            "checks_per_sec": round(self.checks / max(elapsed, 1e-9), 1),
            "over_limit": self.over_limit,
            "errors": self.errors,
            "latency_ms": {
                "p50": round(pct(0.50), 2),
                "p99": round(pct(0.99), 2),
                "max": round(self.max_latency * 1e3, 2),
            },
        }


async def run(args, stats: Stats) -> None:
    from gubernator_tpu.client import V1Client
    from gubernator_tpu.proto import gubernator_pb2 as pb

    client = V1Client(args.endpoint, timeout_s=args.timeout)
    corpus = make_rate_limits(args.limits)
    limiter = OpenLoopLimiter(args.rate) if args.rate > 0 else None
    sem = asyncio.Semaphore(args.concurrency)
    deadline = time.perf_counter() + args.seconds if args.seconds else None
    stop = asyncio.Event()

    async def send(batch) -> None:
        async with sem:
            t0 = time.perf_counter()
            try:
                resp = await client.get_rate_limits(batch)
            except Exception as exc:
                stats.errors += 1
                if not args.quiet:
                    log.error("GetRateLimits: %s", exc)
                return
            stats.observe(time.perf_counter() - t0)
            stats.requests += 1
            stats.checks += len(batch)
            for item, r in zip(batch, resp.responses):
                if r.status == pb.OVER_LIMIT:
                    stats.over_limit += 1
                    if not args.quiet:
                        log.info("Overlimit! name=%s", item.name)

    tasks: set = set()
    try:
        while not stop.is_set():
            for i in range(0, len(corpus), args.checks):
                if deadline and time.perf_counter() > deadline:
                    stop.set()
                    break
                if limiter:
                    await limiter.wait()
                else:
                    # natural backpressure: don't build an unbounded task pile
                    while len(tasks) > args.concurrency * 2:
                        _, tasks = await asyncio.wait(
                            tasks, return_when=asyncio.FIRST_COMPLETED
                        )
                t = asyncio.create_task(send(corpus[i : i + args.checks]))
                tasks.add(t)
                t.add_done_callback(tasks.discard)
            if args.once:
                break
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
    finally:
        await client.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="gubernator-cli", description="gubernator-tpu load generator"
    )
    p.add_argument("-e", "--endpoint", default="", help="gRPC endpoint address")
    p.add_argument("--config", default="", help="environment config file")
    p.add_argument("--concurrency", type=int, default=1, help="concurrent requests")
    p.add_argument(
        "--timeout", type=float, default=0.1, help="request timeout seconds"
    )
    p.add_argument("--checks", type=int, default=1, help="rate checks per request")
    p.add_argument(
        "--rate", type=float, default=0, help="open-loop request rate, 0 = closed loop"
    )
    p.add_argument("--limits", type=int, default=2000, help="distinct rate limits")
    p.add_argument(
        "--seconds", type=float, default=0, help="stop after N seconds (0 = endless)"
    )
    p.add_argument("--once", action="store_true", help="one pass over the corpus")
    p.add_argument("-q", "--quiet", action="store_true")
    args = p.parse_args(argv)
    logging.basicConfig(
        stream=sys.stderr,
        level=logging.ERROR if args.quiet else logging.INFO,
        format="%(asctime)s %(levelname)s %(message)s",
    )
    if not args.endpoint:
        import os

        if args.config:
            from gubernator_tpu.config import load_config_file

            load_config_file(args.config)
        args.endpoint = os.environ.get("GUBER_GRPC_ADDRESS", "")
    if not args.endpoint:
        log.error(
            "please provide a GRPC endpoint via -e, from a config file via "
            "--config, or set the env GUBER_GRPC_ADDRESS"
        )
        return 1

    stats = Stats()
    t0 = time.perf_counter()
    try:
        asyncio.run(run(args, stats))
    except KeyboardInterrupt:
        pass
    import json

    print(json.dumps(stats.report(time.perf_counter() - t0)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
