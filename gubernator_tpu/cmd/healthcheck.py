"""Health probe binary for k8s liveness/readiness (reference
cmd/healthcheck/main.go:34-105): GET /v1/HealthCheck with retries, exit code
2 when the daemon answers but is unhealthy, 1 on transport errors.
"""

from __future__ import annotations

import json
import os
import sys
import time
import urllib.error
import urllib.request


class NotHealthy(Exception):
    pass


def check(url: str, attempts: int, delay_s: float = 0.5, out=sys.stdout) -> None:
    """Raises NotHealthy if the daemon reports unhealthy, URLError and friends
    on transport failure; returns on success."""
    last: Exception = RuntimeError("no attempts made")
    for i in range(max(attempts, 1)):
        req_url = f"http://{url}/v1/HealthCheck"
        print(f'checking "{req_url}": attempt={i}', file=out)
        try:
            with urllib.request.urlopen(req_url, timeout=2.0) as resp:
                hc = json.loads(resp.read().decode())
        except Exception as exc:  # noqa: BLE001 - retried, rethrown at the end
            last = exc
            if i < attempts - 1:
                time.sleep(delay_s)
            continue
        if hc.get("status") != "healthy":
            last = NotHealthy(
                f"not healthy: status={hc.get('status')!r} "
                f"message={hc.get('message')!r} peer_count={hc.get('peer_count')} "
                f"advertise_address={hc.get('advertise_address')!r}"
            )
            if i < attempts - 1:
                time.sleep(delay_s)
            continue
        return
    raise last


def main(argv=None) -> int:
    url = os.environ.get("GUBER_HTTP_ADDRESS") or "localhost:1050"
    attempts_str = os.environ.get("GUBER_HTTP_RETRY_COUNT", "")
    try:
        attempts = int(attempts_str) if attempts_str else 1
    except ValueError:
        print(f"invalid GUBER_HTTP_RETRY_COUNT: {attempts_str!r}")
        return 1
    try:
        check(url, attempts)
    except NotHealthy as exc:
        print(exc)
        return 2
    except Exception as exc:  # noqa: BLE001
        print(exc)
        return 1
    print("is healthy")
    return 0


if __name__ == "__main__":
    sys.exit(main())
