"""Health probe binary for k8s liveness/readiness (reference
cmd/healthcheck/main.go:34-105): GET /v1/HealthCheck with retries, exit code
2 when the daemon answers but is unhealthy, 1 on transport errors.
"""

from __future__ import annotations

import json
import os
import ssl
import sys
import time
import urllib.error
import urllib.request


class NotHealthy(Exception):
    pass


def check(
    url: str,
    attempts: int,
    delay_s: float = 0.5,
    out=sys.stdout,
    scheme: str = "http",
    ca_file: str = "",
    cert_file: str = "",
    key_file: str = "",
    strict: bool = False,
) -> None:
    """Raises NotHealthy if the daemon reports unhealthy, URLError and friends
    on transport failure; returns on success. A "degraded" status (peer
    errors / open circuit breakers — the instance still serves every
    request, see docs/robustness.md) passes unless `strict`: restarting a
    pod because its PEERS are unreachable only amplifies a partition. With
    TLS, probe over https trusting `ca_file`; `cert_file`/`key_file` present
    a client certificate so the probe also works against an mTLS gateway
    when no status listener is configured."""
    ok = ("healthy",) if strict else ("healthy", "degraded")
    ctx = None
    if scheme == "https":
        ctx = ssl.create_default_context(cafile=ca_file or None)
        ctx.check_hostname = False  # probes hit pod IPs, not SAN hostnames
        if not ca_file:
            ctx.verify_mode = ssl.CERT_NONE
        if cert_file and key_file:
            ctx.load_cert_chain(cert_file, key_file)
    last: Exception = RuntimeError("no attempts made")
    for i in range(max(attempts, 1)):
        req_url = f"{scheme}://{url}/v1/HealthCheck"
        print(f'checking "{req_url}": attempt={i}', file=out)
        try:
            with urllib.request.urlopen(req_url, timeout=2.0, context=ctx) as resp:
                hc = json.loads(resp.read().decode())
        except Exception as exc:  # noqa: BLE001 - retried, rethrown at the end
            last = exc
            if i < attempts - 1:
                time.sleep(delay_s)
            continue
        if hc.get("status") not in ok:
            last = NotHealthy(
                f"not healthy: status={hc.get('status')!r} "
                f"message={hc.get('message')!r} peer_count={hc.get('peer_count')} "
                f"advertise_address={hc.get('advertise_address')!r}"
            )
            if i < attempts - 1:
                time.sleep(delay_s)
            continue
        if hc.get("status") == "degraded":
            print(
                f"degraded (passing): message={hc.get('message')!r}", file=out
            )
        return
    raise last


def main(argv=None) -> int:
    # prefer the status listener (serves health without client certs in mTLS
    # clusters); fall back to the main gateway address
    url = (
        os.environ.get("GUBER_STATUS_HTTP_ADDRESS")
        or os.environ.get("GUBER_HTTP_ADDRESS")
        or "localhost:1050"
    )
    from gubernator_tpu.config import _get_bool

    tls_on = bool(os.environ.get("GUBER_TLS_CERT")) or _get_bool(
        os.environ, "GUBER_TLS_AUTO", False
    )
    scheme = "https" if tls_on else "http"
    ca_file = os.environ.get("GUBER_TLS_CA", "")
    # only the main gateway enforces client auth; the status listener never
    # does — presenting the server pair (peers share it, tls.go:138-238)
    # makes the probe work against either
    probing_status = bool(os.environ.get("GUBER_STATUS_HTTP_ADDRESS"))
    cert_file = "" if probing_status else os.environ.get("GUBER_TLS_CERT", "")
    key_file = "" if probing_status else os.environ.get("GUBER_TLS_KEY", "")
    attempts_str = os.environ.get("GUBER_HTTP_RETRY_COUNT", "")
    try:
        attempts = int(attempts_str) if attempts_str else 1
    except ValueError:
        print(f"invalid GUBER_HTTP_RETRY_COUNT: {attempts_str!r}")
        return 1
    try:
        check(
            url, attempts, scheme=scheme, ca_file=ca_file,
            cert_file=cert_file, key_file=key_file,
            strict=_get_bool(os.environ, "GUBER_HEALTHCHECK_STRICT", False),
        )
    except NotHealthy as exc:
        print(exc)
        return 2
    except Exception as exc:  # noqa: BLE001
        print(exc)
        return 1
    print("is healthy")
    return 0


if __name__ == "__main__":
    sys.exit(main())
