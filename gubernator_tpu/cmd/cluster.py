"""Local in-process cluster binary for client development (reference
cmd/gubernator-cluster/main.go:30-56): boots N daemons on consecutive local
ports, wires them with explicit set_peers, and serves until interrupted.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal
import sys

log = logging.getLogger("gubernator-cluster")


async def start_cluster(n: int, base_port: int, host: str = "127.0.0.1"):
    from gubernator_tpu.config import BehaviorConfig, DaemonConfig
    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.types import PeerInfo

    daemons = []
    for i in range(n):
        conf = DaemonConfig(
            grpc_address=f"{host}:{base_port + 2 * i}",
            http_address=f"{host}:{base_port + 2 * i + 1}",
            behaviors=BehaviorConfig(global_sync_wait_ms=50.0),
        )
        daemons.append(await Daemon.spawn(conf))
    peers = [d.peer_info() for d in daemons]
    for d in daemons:
        d.set_peers([PeerInfo(**vars(p)) for p in peers])
    return daemons


async def serve(n: int, base_port: int, stop=None, ready=None) -> None:
    daemons = await start_cluster(n, base_port)
    for d in daemons:
        log.info("node grpc=%s http=%s", d.conf.grpc_address, d.conf.http_address)
    stop = stop or asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, RuntimeError):  # pragma: no cover
            pass
    if ready is not None:
        res = ready(daemons)
        if asyncio.iscoroutine(res):
            await res
    try:
        await stop.wait()
    finally:
        await asyncio.gather(*(d.close() for d in daemons))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="gubernator-cluster")
    p.add_argument("-n", "--nodes", type=int, default=6)
    p.add_argument("--base-port", type=int, default=9090)
    args = p.parse_args(argv)
    logging.basicConfig(stream=sys.stderr, level=logging.INFO)
    log.info("starting %d-node local cluster...", args.nodes)
    try:
        asyncio.run(serve(args.nodes, args.base_port))
    except KeyboardInterrupt:  # pragma: no cover
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
