import sys

from gubernator_tpu.cmd.server import main

sys.exit(main())
