"""Key fingerprinting.

The reference hashes the request key twice: once to pick the owning peer
(consistent hash over fnv1a, reference replicated_hash.go:104-119) and once to
pick a worker shard (63-bit xxhash, reference workers.go:185-189). The TPU
build collapses both into one 64-bit xxhash fingerprint computed host-side:

* high bits select the owning device shard (parallel/, M3+);
* `fp mod capacity` selects the HBM bucket within a shard (ops/kernel2.py).

Strings never reach the device — only fingerprints do. fp == 0 is reserved as
the empty-slot sentinel, so real fingerprints are remapped away from 0.
"""

from __future__ import annotations

import xxhash

_SEED = 0x6775626572  # arbitrary fixed seed; must be identical across peers
_MASK63 = (1 << 63) - 1


def fingerprint(name: str, unique_key: str) -> int:
    """63-bit fingerprint of a rate limit's hash key (name + "_" + key,
    composition per reference client.go:39-41). 63 bits so it fits a
    non-negative int64 — the TPU X64-emulation pass can't bitcast u64⇄s64, and
    the reference itself uses a 63-bit xxhash for worker sharding
    (workers.go:155-157). Never returns 0 (the empty-slot sentinel)."""
    h = xxhash.xxh64_intdigest(name + "_" + unique_key, seed=_SEED) & _MASK63
    return h if h != 0 else 1
