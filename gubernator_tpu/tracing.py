"""Trace propagation across the peer mesh — the MetadataCarrier analog.

The reference injects W3C TraceContext into `RateLimitReq.Metadata` on the
forwarding side and extracts it on the owner so one client request is a single
distributed trace across daemons (reference metadata_carrier.go:19-40,
peer_client.go:140-142, gubernator.go:522-524). OTEL itself is not a baked-in
dependency here, so this module implements the W3C `traceparent` header format
directly (https://www.w3.org/TR/trace-context/) over a contextvar, plus an
optional span-event hook embedders can point at their own tracer.
"""

from __future__ import annotations

import contextvars
import secrets
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Mapping, Optional

TRACEPARENT_KEY = "traceparent"
_FLAG_SAMPLED = 0x01


@dataclass(frozen=True)
class SpanContext:
    trace_id: str  # 32 lowercase hex chars
    span_id: str  # 16 lowercase hex chars
    flags: int = _FLAG_SAMPLED

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags:02x}"


_current: contextvars.ContextVar[Optional[SpanContext]] = contextvars.ContextVar(
    "gubernator_tpu_span", default=None
)

# the most recently ENDED scope's span in this context: transport-layer
# metrics (grpc_request_duration) observe AFTER the handler's scope closed,
# so this is how a request-duration bucket gets the request's trace_id as
# its OpenMetrics exemplar
_last_ended: contextvars.ContextVar[Optional[SpanContext]] = (
    contextvars.ContextVar("gubernator_tpu_last_span", default=None)
)


def last_ended_span() -> Optional[SpanContext]:
    return _last_ended.get()

# embedder hook: called with (name, SpanContext) whenever a scope starts;
# wire this to a real tracer (OTEL etc.) if you have one
span_hook: Optional[Callable[[str, SpanContext], None]] = None

# optional exporter: an object with record(name, span, parent_span_id,
# start_ns, end_ns, *, attributes=None, links=(), kind=...); end_scope and
# record_span feed it finished spans. Wired by the daemon from the standard
# OTEL_* envs (gubernator_tpu.otel.OTLPJsonExporter).
exporter = None


def set_exporter(exp) -> None:
    global exporter
    exporter = exp


@dataclass
class Scope:
    """One open scope (returned by start_scope, consumed by end_scope):
    carries what the exporter needs to emit a finished span."""

    token: object
    name: str
    span: SpanContext
    parent_span_id: str
    start_ns: int
    attributes: Optional[dict] = None


# ---------------------------------------------------------------- span links
# Batching breaks parent-child causality: a request span cannot parent the
# dispatch span that served it (one dispatch serves many requests, and it
# outlives none of them cleanly). OTLP span LINKS restore the edge — the
# batcher registers "request span → dispatch span" links here while the
# request scope is still open, and end_scope attaches them to the finished
# span. Bounded: an abandoned scope (exceptions, exporter off) must not leak.
_links_lock = threading.Lock()
_pending_links: "Dict[str, List[SpanContext]]" = {}
_MAX_LINK_SPANS = 4096  # open spans tracked
_MAX_LINKS_PER_SPAN = 16  # a request split across local/global/forward rows


def add_span_link(span: Optional[SpanContext], target: Optional[SpanContext]) -> None:
    """Register a link from `span` (whose scope is still open — e.g. the
    request scope awaiting its batch slice) to `target` (e.g. the dispatch
    span that served it). Attached when the span's scope ends."""
    if span is None or target is None:
        return
    with _links_lock:
        lst = _pending_links.setdefault(span.span_id, [])
        if len(lst) < _MAX_LINKS_PER_SPAN:
            lst.append(target)
        while len(_pending_links) > _MAX_LINK_SPANS:
            _pending_links.pop(next(iter(_pending_links)))


def take_span_links(span_id: str) -> List[SpanContext]:
    with _links_lock:
        return _pending_links.pop(span_id, [])


def record_span(
    name: str,
    span: SpanContext,
    parent_span_id: str,
    start_ns: int,
    end_ns: int,
    attributes: Optional[dict] = None,
    links=(),
    kind: int = 1,
) -> None:
    """Emit one already-finished span straight to the exporter — the scope
    machinery (contextvar set/reset) is wrong for spans whose lifetime
    crosses threads and requests, like a batcher flush and its pipeline
    stage children. No-op without an exporter or when sampled out."""
    if exporter is not None and span.flags & 0x01:
        exporter.record(
            name, span, parent_span_id, start_ns, end_ns,
            attributes=attributes, links=links, kind=kind,
        )


def current_span() -> Optional[SpanContext]:
    return _current.get()


def new_span(parent: Optional[SpanContext] = None) -> SpanContext:
    """A child of `parent` (same trace), or a fresh root."""
    return SpanContext(
        trace_id=parent.trace_id if parent else secrets.token_hex(16),
        span_id=secrets.token_hex(8),
        flags=parent.flags if parent else _FLAG_SAMPLED,
    )


def start_scope(name: str, parent: Optional[SpanContext] = None):
    """Begin a scope: set the current span (child of parent or of the ambient
    span) and return a Scope to pass to end_scope. The
    tracing.StartNamedScope analog."""
    import time

    eff_parent = parent if parent is not None else _current.get()
    span = new_span(eff_parent)
    if span_hook is not None:
        span_hook(name, span)
    token = _current.set(span)
    return Scope(
        token=token,
        name=name,
        span=span,
        parent_span_id=eff_parent.span_id if eff_parent else "",
        start_ns=time.time_ns(),
    )


def end_scope(scope) -> None:
    if isinstance(scope, Scope):
        _current.reset(scope.token)
        _last_ended.set(scope.span)
        # pop pending links unconditionally — an unsampled or unexported
        # scope must not strand registry entries
        links = take_span_links(scope.span.span_id)
        # honor the W3C sampled flag: traces sampled out upstream
        # (traceparent ...-00) must not produce orphan partial traces here
        if exporter is not None and scope.span.flags & 0x01:
            import time

            exporter.record(
                scope.name, scope.span, scope.parent_span_id,
                scope.start_ns, time.time_ns(),
                attributes=scope.attributes, links=links,
            )
    else:  # raw contextvars token (embedders on the old surface)
        _current.reset(scope)


def parse_traceparent(value: str) -> Optional[SpanContext]:
    """Parse a W3C traceparent header; None on anything malformed (invalid
    inbound context must not break serving)."""
    parts = value.strip().lower().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    try:
        int(version, 16), int(trace_id, 16), int(span_id, 16)
        f = int(flags, 16)
    except ValueError:
        return None
    return SpanContext(trace_id=trace_id, span_id=span_id, flags=f)


def inject(metadata) -> None:
    """Write the current span into a RateLimitReq.metadata map (the carrier's
    Set side, metadata_carrier.go:33-36). No-op when there is no active span."""
    span = _current.get()
    if span is not None:
        metadata[TRACEPARENT_KEY] = span.to_traceparent()


def extract(metadata: Mapping[str, str]) -> Optional[SpanContext]:
    """Read a span from a RateLimitReq.metadata map (the carrier's Get side,
    metadata_carrier.go:24-31)."""
    raw = metadata.get(TRACEPARENT_KEY, "")
    return parse_traceparent(raw) if raw else None
