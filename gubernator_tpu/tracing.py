"""Trace propagation across the peer mesh — the MetadataCarrier analog.

The reference injects W3C TraceContext into `RateLimitReq.Metadata` on the
forwarding side and extracts it on the owner so one client request is a single
distributed trace across daemons (reference metadata_carrier.go:19-40,
peer_client.go:140-142, gubernator.go:522-524). OTEL itself is not a baked-in
dependency here, so this module implements the W3C `traceparent` header format
directly (https://www.w3.org/TR/trace-context/) over a contextvar, plus an
optional span-event hook embedders can point at their own tracer.
"""

from __future__ import annotations

import contextvars
import secrets
from dataclasses import dataclass
from typing import Callable, Mapping, Optional

TRACEPARENT_KEY = "traceparent"
_FLAG_SAMPLED = 0x01


@dataclass(frozen=True)
class SpanContext:
    trace_id: str  # 32 lowercase hex chars
    span_id: str  # 16 lowercase hex chars
    flags: int = _FLAG_SAMPLED

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags:02x}"


_current: contextvars.ContextVar[Optional[SpanContext]] = contextvars.ContextVar(
    "gubernator_tpu_span", default=None
)

# embedder hook: called with (name, SpanContext) whenever a scope starts;
# wire this to a real tracer (OTEL etc.) if you have one
span_hook: Optional[Callable[[str, SpanContext], None]] = None

# optional exporter: an object with record(name, span, parent_span_id,
# start_ns, end_ns); end_scope feeds it finished spans. Wired by the daemon
# from the standard OTEL_* envs (gubernator_tpu.otel.OTLPJsonExporter).
exporter = None


def set_exporter(exp) -> None:
    global exporter
    exporter = exp


@dataclass
class Scope:
    """One open scope (returned by start_scope, consumed by end_scope):
    carries what the exporter needs to emit a finished span."""

    token: object
    name: str
    span: SpanContext
    parent_span_id: str
    start_ns: int


def current_span() -> Optional[SpanContext]:
    return _current.get()


def new_span(parent: Optional[SpanContext] = None) -> SpanContext:
    """A child of `parent` (same trace), or a fresh root."""
    return SpanContext(
        trace_id=parent.trace_id if parent else secrets.token_hex(16),
        span_id=secrets.token_hex(8),
        flags=parent.flags if parent else _FLAG_SAMPLED,
    )


def start_scope(name: str, parent: Optional[SpanContext] = None):
    """Begin a scope: set the current span (child of parent or of the ambient
    span) and return a Scope to pass to end_scope. The
    tracing.StartNamedScope analog."""
    import time

    eff_parent = parent if parent is not None else _current.get()
    span = new_span(eff_parent)
    if span_hook is not None:
        span_hook(name, span)
    token = _current.set(span)
    return Scope(
        token=token,
        name=name,
        span=span,
        parent_span_id=eff_parent.span_id if eff_parent else "",
        start_ns=time.time_ns(),
    )


def end_scope(scope) -> None:
    if isinstance(scope, Scope):
        _current.reset(scope.token)
        # honor the W3C sampled flag: traces sampled out upstream
        # (traceparent ...-00) must not produce orphan partial traces here
        if exporter is not None and scope.span.flags & 0x01:
            import time

            exporter.record(
                scope.name, scope.span, scope.parent_span_id,
                scope.start_ns, time.time_ns(),
            )
    else:  # raw contextvars token (embedders on the old surface)
        _current.reset(scope)


def parse_traceparent(value: str) -> Optional[SpanContext]:
    """Parse a W3C traceparent header; None on anything malformed (invalid
    inbound context must not break serving)."""
    parts = value.strip().lower().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    try:
        int(version, 16), int(trace_id, 16), int(span_id, 16)
        f = int(flags, 16)
    except ValueError:
        return None
    return SpanContext(trace_id=trace_id, span_id=span_id, flags=f)


def inject(metadata) -> None:
    """Write the current span into a RateLimitReq.metadata map (the carrier's
    Set side, metadata_carrier.go:33-36). No-op when there is no active span."""
    span = _current.get()
    if span is not None:
        metadata[TRACEPARENT_KEY] = span.to_traceparent()


def extract(metadata: Mapping[str, str]) -> Optional[SpanContext]:
    """Read a span from a RateLimitReq.metadata map (the carrier's Get side,
    metadata_carrier.go:24-31)."""
    raw = metadata.get(TRACEPARENT_KEY, "")
    return parse_traceparent(raw) if raw else None
