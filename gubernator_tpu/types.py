"""Core enums and request/response types.

API parity with the reference proto surface (reference gubernator.proto:63-210):
same enum values, same field names (snake_case), same semantics. These are the
host-side (Python) representations; the device-side batch layout lives in
ops/batch.py.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, Optional


class Algorithm(enum.IntEnum):
    # reference gubernator.proto:64-68
    TOKEN_BUCKET = 0
    LEAKY_BUCKET = 1


class Behavior(enum.IntFlag):
    """Bitflag behaviors (reference gubernator.proto:71-142).

    BATCHING is the implicit default (value 0 — "here because proto requires
    it"); NO_BATCHING opts a request out of the forwarding batch window.
    """

    BATCHING = 0
    NO_BATCHING = 1
    GLOBAL = 2
    DURATION_IS_GREGORIAN = 4
    RESET_REMAINING = 8
    MULTI_REGION = 16
    DRAIN_OVER_LIMIT = 32


class Status(enum.IntEnum):
    # reference gubernator.proto:192-195
    UNDER_LIMIT = 0
    OVER_LIMIT = 1


class Gregorian(enum.IntEnum):
    """Valid `duration` values when DURATION_IS_GREGORIAN is set
    (reference interval.go:74-81)."""

    MINUTES = 0
    HOURS = 1
    DAYS = 2
    WEEKS = 3  # rejected, like the reference
    MONTHS = 4
    YEARS = 5


def has_behavior(behavior: int, flag: int) -> bool:
    """reference behavior.go HasBehavior equivalent."""
    return (int(behavior) & int(flag)) != 0


# Millisecond duration helpers (reference gubernator.proto:157-162).
SECOND = 1000
MINUTE = 60 * SECOND
HOUR = 60 * MINUTE


@dataclass
class RateLimitRequest:
    """One rate-limit check. Field-for-field parity with reference
    RateLimitReq (gubernator.proto:144-190)."""

    name: str = ""
    unique_key: str = ""
    hits: int = 1
    limit: int = 0
    duration: int = 0  # milliseconds, or a Gregorian enum when flagged
    algorithm: int = Algorithm.TOKEN_BUCKET
    behavior: int = 0
    burst: int = 0  # leaky bucket burst; 0 → defaults to limit
    metadata: Optional[Dict[str, str]] = None
    created_at: Optional[int] = None  # epoch ms; stamped at ingress if unset

    def hash_key(self) -> str:
        # reference client.go:39-41 — cache key is name + "_" + unique_key
        return self.name + "_" + self.unique_key


@dataclass
class RateLimitResponse:
    """Field-for-field parity with reference RateLimitResp
    (gubernator.proto:197-210)."""

    status: int = Status.UNDER_LIMIT
    limit: int = 0
    remaining: int = 0
    reset_time: int = 0  # epoch ms when the limit is reset
    error: str = ""
    metadata: Dict[str, str] = field(default_factory=dict)


@dataclass
class PeerInfo:
    """reference peers.go PeerInfo equivalent."""

    grpc_address: str = ""
    http_address: str = ""
    data_center: str = ""
    is_owner: bool = False

    def hash_key(self) -> str:
        return self.grpc_address


@dataclass
class HitEvent:
    """One owner-side hit: the request and the response it produced — the
    audit/sampling hook payload (reference config.go:128-135,
    gubernator.go:676-688). Delivered on the daemon's event channel when one
    is configured; fields are pb messages (RateLimitReq / RateLimitResp)."""

    request: object
    response: object
