"""Core enums and request/response types.

API parity with the reference proto surface (reference gubernator.proto:63-210):
same enum values, same field names (snake_case), same semantics. These are the
host-side (Python) representations; the device-side batch layout lives in
ops/batch.py.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, Optional


class Algorithm(enum.IntEnum):
    # reference gubernator.proto:64-68
    TOKEN_BUCKET = 0
    LEAKY_BUCKET = 1
    # ---- extensions beyond the reference enum (docs/algorithms.md).
    # Values are part of the proto surface (proto/gubernator.proto); a peer
    # running an older build answers requests carrying them with a per-item
    # "invalid rate limit algorithm" error row instead of failing the batch.
    GCRA = 2  # virtual-scheduling (theoretical arrival time) rate limiting
    SLIDING_WINDOW = 3  # previous+current window interpolation counters
    CONCURRENCY_LEASE = 4  # inflight acquire/release with TTL reclamation


# highest algorithm value this build's kernel speaks — anything above is a
# per-item validation error (ops/batch.ERR_ALGORITHM), the forward-compat
# contract for mixed-version clusters
MAX_ALGORITHM = int(Algorithm.CONCURRENCY_LEASE)


# ---- cascaded multi-limit checks (docs/algorithms.md "Cascades").
# A cascade request expands into one engine row per limit level (per-user,
# per-tenant, global, …) sharing a request carrier; the level rides the
# behavior word's high bits so it survives every packed-ingress layout and
# the a2a ownership exchange unchanged. Level 0 = the carrier (or any
# standalone request); levels >= 1 are member rows that immediately follow
# their carrier in batch order.
CASCADE_LEVEL_SHIFT = 8
CASCADE_LEVEL_MASK = 0xFF
# deepest level the compact wire can carry (2 spare lane bits — ops/wire.py);
# deeper cascades ride the full-width grids with identical semantics
CASCADE_WIRE_MAX_LEVEL = 3


def cascade_level(behavior: int) -> int:
    """The cascade level encoded in a behavior word (0 = carrier/standalone)."""
    return (int(behavior) >> CASCADE_LEVEL_SHIFT) & CASCADE_LEVEL_MASK


def with_cascade_level(behavior: int, level: int) -> int:
    """Behavior word with the cascade level field set."""
    if not (0 <= level <= CASCADE_LEVEL_MASK):
        raise ValueError(f"cascade level {level} out of range")
    return (int(behavior) & ~(CASCADE_LEVEL_MASK << CASCADE_LEVEL_SHIFT)) | (
        level << CASCADE_LEVEL_SHIFT
    )


# ---- priority tiers (docs/robustness.md "Overload & QoS").
# A 2-bit priority field rides the behavior word's bits 6-7 — the two
# client-facing flag bits the frozen reference enum (values 1..32) leaves
# free below the internal cascade-level field (bits 8-15). Tier 0 is the
# default (best-effort); higher tiers are shed LAST under overload
# (service/batcher.py shed policy) and sized first by the lease plane.
# Like cascade levels, the field survives every packed-ingress layout:
# the compact wire carries it in dedicated lane bits (ops/wire.py) and the
# kernel echoes it in the egress flags, so a decision's tier is visible to
# the batcher and the metrics plane without any host-side side table.
PRIORITY_SHIFT = 6
PRIORITY_MASK = 0x3
PRIORITY_TIERS = 4  # tiers 0..3; 3 = most important, shed last


def priority_tier(behavior: int) -> int:
    """The priority tier encoded in a behavior word (0 = best-effort)."""
    return (int(behavior) >> PRIORITY_SHIFT) & PRIORITY_MASK


def with_priority(behavior: int, tier: int) -> int:
    """Behavior word with the priority tier field set."""
    if not (0 <= tier <= PRIORITY_MASK):
        raise ValueError(f"priority tier {tier} out of range")
    return (int(behavior) & ~(PRIORITY_MASK << PRIORITY_SHIFT)) | (
        tier << PRIORITY_SHIFT
    )


class Behavior(enum.IntFlag):
    """Bitflag behaviors (reference gubernator.proto:71-142).

    BATCHING is the implicit default (value 0 — "here because proto requires
    it"); NO_BATCHING opts a request out of the forwarding batch window.
    """

    BATCHING = 0
    NO_BATCHING = 1
    GLOBAL = 2
    DURATION_IS_GREGORIAN = 4
    RESET_REMAINING = 8
    MULTI_REGION = 16
    DRAIN_OVER_LIMIT = 32


class Status(enum.IntEnum):
    # reference gubernator.proto:192-195
    UNDER_LIMIT = 0
    OVER_LIMIT = 1


class Gregorian(enum.IntEnum):
    """Valid `duration` values when DURATION_IS_GREGORIAN is set
    (reference interval.go:74-81)."""

    MINUTES = 0
    HOURS = 1
    DAYS = 2
    WEEKS = 3  # rejected, like the reference
    MONTHS = 4
    YEARS = 5


def has_behavior(behavior: int, flag: int) -> bool:
    """reference behavior.go HasBehavior equivalent."""
    return (int(behavior) & int(flag)) != 0


# Millisecond duration helpers (reference gubernator.proto:157-162).
SECOND = 1000
MINUTE = 60 * SECOND
HOUR = 60 * MINUTE


@dataclass
class CascadeLevel:
    """One additional limit level of a cascaded multi-limit check
    (proto CascadeLevel — docs/algorithms.md "Cascades")."""

    name: str = ""
    unique_key: str = ""
    limit: int = 0
    duration: int = 0  # milliseconds (never Gregorian)
    algorithm: int = Algorithm.TOKEN_BUCKET
    burst: int = 0


@dataclass
class RateLimitRequest:
    """One rate-limit check. Field-for-field parity with reference
    RateLimitReq (gubernator.proto:144-190) plus the cascade extension."""

    name: str = ""
    unique_key: str = ""
    hits: int = 1
    limit: int = 0
    duration: int = 0  # milliseconds, or a Gregorian enum when flagged
    algorithm: int = Algorithm.TOKEN_BUCKET
    behavior: int = 0
    burst: int = 0  # leaky/GCRA burst; 0 → defaults to limit
    metadata: Optional[Dict[str, str]] = None
    created_at: Optional[int] = None  # epoch ms; stamped at ingress if unset
    # additional limit levels checked atomically with this request (the
    # request's own fields are level 0); served via the daemon surface —
    # the embedded engine API evaluates levels but callers must expand
    # them into rows themselves (service/wire.expand_cascades)
    cascade: Optional[list] = None  # List[CascadeLevel]

    def hash_key(self) -> str:
        # reference client.go:39-41 — cache key is name + "_" + unique_key
        return self.name + "_" + self.unique_key


@dataclass
class RateLimitResponse:
    """Field-for-field parity with reference RateLimitResp
    (gubernator.proto:197-210), plus the retry_after extension."""

    status: int = Status.UNDER_LIMIT
    limit: int = 0
    remaining: int = 0
    reset_time: int = 0  # epoch ms when the limit is reset
    error: str = ""
    metadata: Dict[str, str] = field(default_factory=dict)
    # ms until a DENIED request conforms, computed from reset_time against
    # the serving clock. For GCRA denials reset_time is the EXACT
    # TAT-derived conforming instant (ops/math.py gcra_lanes), so a client
    # honoring retry_after_ms backs off precisely as long as needed — the
    # pb path additionally surfaces it as metadata["retry_after_ms"]
    # (the frozen proto schema has no field for it). 0 for allowed rows.
    retry_after_ms: int = 0


def retry_after_ms(status: int, reset_time: int, now_ms: int) -> int:
    """The retry_after surface rule: denied rows report the ms until their
    reset/conforming instant (clamped at 0), allowed rows 0."""
    if status != Status.OVER_LIMIT:
        return 0
    return max(0, int(reset_time) - int(now_ms))


@dataclass
class PeerInfo:
    """reference peers.go PeerInfo equivalent."""

    grpc_address: str = ""
    http_address: str = ""
    data_center: str = ""
    is_owner: bool = False

    def hash_key(self) -> str:
        return self.grpc_address


@dataclass
class HitEvent:
    """One owner-side hit: the request and the response it produced — the
    audit/sampling hook payload (reference config.go:128-135,
    gubernator.go:676-688). Delivered on the daemon's event channel when one
    is configured; fields are pb messages (RateLimitReq / RateLimitResp)."""

    request: object
    response: object
