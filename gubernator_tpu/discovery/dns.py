"""DNS peer discovery — poll A/AAAA records of one or more FQDNs.

Mirrors reference dns.go:160-277: poll at a fixed cadence, build the peer list
from resolved IPs (the FQDN doubles as the datacenter label in multi-DC mode,
dns.go:112-136), mark self by address match, and NEVER clear the peer list on
an empty/failed response — a resolver blip must not drop the cluster
(dns.go:253-264).

The resolver is injectable so tests run against an in-process fake
(reference dns_test.go:81-294 does the same with a local DNS server).
"""

from __future__ import annotations

import asyncio
import socket
from typing import Callable, List, Optional

from gubernator_tpu.types import PeerInfo


def system_resolver(fqdn: str, port: str) -> List[str]:
    """Resolve A/AAAA records → ip:port list (blocking; called in executor)."""
    out = []
    for family, _, _, _, sockaddr in socket.getaddrinfo(
        fqdn, None, proto=socket.IPPROTO_TCP
    ):
        ip = sockaddr[0]
        if family == socket.AF_INET6:
            out.append(f"[{ip}]:{port}")
        else:
            out.append(f"{ip}:{port}")
    return sorted(set(out))


class DNSPool:
    """Polling discovery pool; calls on_update(peers) when the set changes."""

    def __init__(
        self,
        fqdn: str,
        poll_ms: float,
        on_update: Callable[[List[PeerInfo]], None],
        self_address: str,
        http_address: str = "",
        data_center: str = "",
        resolver: Optional[Callable[[str, str], List[str]]] = None,
    ):
        # multiple FQDNs comma-separated; each may carry its own DC label as
        # fqdn=dc (multi-DC mode, reference dns.go:112-136 uses the FQDN
        # itself; the explicit label keeps tests deterministic)
        self.fqdns = [f.strip() for f in fqdn.split(",") if f.strip()]
        self.poll_s = max(poll_ms / 1e3, 0.01)
        self.on_update = on_update
        self.self_address = self_address
        self.http_address = http_address
        self.data_center = data_center
        self.resolver = resolver or system_resolver
        self._task: Optional[asyncio.Task] = None
        self._last: List[str] = []
        self._closed = False
        port = self_address.rsplit(":", 1)[-1] if ":" in self_address else "1051"
        self.port = port

    async def start(self) -> None:
        await self._poll_once()
        self._task = asyncio.create_task(self._loop(), name="dns-pool")

    async def _loop(self) -> None:
        while not self._closed:
            await asyncio.sleep(self.poll_s)
            await self._poll_once()

    async def _poll_once(self) -> None:
        loop = asyncio.get_running_loop()
        addrs: List[tuple] = []
        for entry in self.fqdns:
            fqdn, _, dc = entry.partition("=")
            try:
                got = await loop.run_in_executor(
                    None, self.resolver, fqdn, self.port
                )
            except Exception:
                got = []
            addrs.extend((a, dc or self.data_center) for a in got)
        if not addrs:
            return  # keep the stale list (reference dns.go:253-264)
        flat = sorted(a for a, _ in addrs)
        if flat == self._last:
            return
        self._last = flat
        peers = [
            PeerInfo(
                grpc_address=a,
                http_address=self.http_address,
                data_center=dc,
                is_owner=(a == self.self_address),
            )
            for a, dc in addrs
        ]
        self.on_update(peers)

    async def close(self) -> None:
        self._closed = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
