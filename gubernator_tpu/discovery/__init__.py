"""Peer discovery pools (reference §L6): "none" (explicit set_peers, the
test-cluster mode, reference daemon.go:258-262), DNS polling (dns.py), etcd
lease registration (etcd.py), member-list gossip (memberlist.py), and
Kubernetes EndpointSlices/Pods (kubernetes.py). All speak plain
sockets/HTTP — no infrastructure client libraries required."""

from gubernator_tpu.discovery.dns import DNSPool, system_resolver
from gubernator_tpu.discovery.etcd import EtcdPool
from gubernator_tpu.discovery.kubernetes import K8sPool
from gubernator_tpu.discovery.memberlist import MemberlistPool

__all__ = ["DNSPool", "EtcdPool", "K8sPool", "MemberlistPool", "system_resolver"]
