"""Peer discovery pools: "none" (explicit set_peers, the test-cluster mode,
reference daemon.go:258-262) and DNS polling (dns.py). The reference's etcd /
k8s / memberlist pools depend on infrastructure clients that are out of scope
for the TPU build; DNS + none cover its own test suite's needs."""

from gubernator_tpu.discovery.dns import DNSPool, system_resolver

__all__ = ["DNSPool", "system_resolver"]
