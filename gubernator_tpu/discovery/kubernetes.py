"""Kubernetes peer discovery — EndpointSlices (default) or Pods.

Mirrors reference kubernetes.go:79-114 + 214-313: watch the objects that
track the gubernator Service, extract ready addresses with **pure functions**
(unit-testable on fixture JSON, as the reference tests them), mark self by
pod IP, and rebuild the peer list on every change. Not-ready endpoints are
skipped UNLESS they are self — a booting pod must still see itself
(kubernetes.go:281-289).

Speaks the Kubernetes REST API directly over aiohttp with **list + watch**
(the reference's SharedIndexInformer pattern, kubernetes.go:79-114): the list
records a resourceVersion, a watch stream from that version turns every
ADDED/MODIFIED/DELETED event into a fresh list+extract, and a low-cadence
poll remains as the informer-resync fallback. No kubernetes client library
is required. In-cluster config comes from the standard service-account
mount; the API URL/token are injectable and tests run an in-process fake
API server.
"""

from __future__ import annotations

import asyncio
import logging
import ssl
from typing import Callable, List, Optional

import aiohttp

from gubernator_tpu.types import PeerInfo

log = logging.getLogger("gubernator_tpu.k8s")

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


# ------------------------------------------------------------ pure extraction


def extract_peers_from_endpoint_slices(
    slices: List[dict], pod_ip: str, pod_port: str
) -> List[PeerInfo]:
    """EndpointSlice JSON objects → peers (reference
    ExtractPeersFromEndpointSlices, kubernetes.go:266-313)."""
    peer_map = {}
    for slice_ in slices:
        if slice_.get("addressType", "IPv4") != "IPv4":
            continue
        for endpoint in slice_.get("endpoints") or []:
            addrs = endpoint.get("addresses") or []
            if not addrs:
                continue
            ip = addrs[0]
            conditions = endpoint.get("conditions") or {}
            is_ready = conditions.get("ready") is not False
            is_owner = ip == pod_ip
            if not is_ready and not is_owner:
                continue
            peer = PeerInfo(grpc_address=f"{ip}:{pod_port}", is_owner=is_owner)
            existing = peer_map.get(ip)
            if existing is not None:
                if not existing.is_owner and is_owner:
                    peer_map[ip] = peer
                continue
            peer_map[ip] = peer
    return list(peer_map.values())


def extract_peers_from_pods(
    pods: List[dict], pod_ip: str, pod_port: str
) -> List[PeerInfo]:
    """Pod JSON objects → peers (reference ExtractPeersFromPods,
    kubernetes.go:214-245): a pod counts when Running with condition
    Ready=True, or when it is self."""
    out = []
    for pod in pods:
        status = pod.get("status") or {}
        ip = status.get("podIP", "")
        if not ip:
            continue
        is_owner = ip == pod_ip
        ready = status.get("phase") == "Running" and any(
            c.get("type") == "Ready" and c.get("status") == "True"
            for c in status.get("conditions") or []
        )
        if not ready and not is_owner:
            continue
        out.append(PeerInfo(grpc_address=f"{ip}:{pod_port}", is_owner=is_owner))
    return out


# --------------------------------------------------------------------- pool


class K8sPool:
    def __init__(
        self,
        on_update: Callable[[List[PeerInfo]], None],
        pod_ip: str,
        pod_port: str,
        namespace: str = "default",
        selector: str = "",  # REQUIRED label selector (the reference keys
        # endpointslices on kubernetes.io/service-name, kubernetes.go:181-193)
        mechanism: str = "endpointslices",  # or "pods"
        api_url: str = "",  # override for tests; default in-cluster
        token: str = "",
        poll_ms: float = 5_000.0,
        ca_file: str = "",
    ):
        if mechanism not in ("endpointslices", "pods"):
            raise ValueError(f"unknown k8s watch mechanism {mechanism!r}")
        self.on_update = on_update
        self.pod_ip = pod_ip
        self.pod_port = pod_port
        self.namespace = namespace
        self.selector = selector
        self.mechanism = mechanism
        self.poll_s = max(poll_ms / 1e3, 0.01)
        self._api_url = api_url
        self._token = token
        self._ca_file = ca_file
        self._session: Optional[aiohttp.ClientSession] = None
        self._task: Optional[asyncio.Task] = None
        self._watch_task: Optional[asyncio.Task] = None
        self._closed = False
        self._last: Optional[List[str]] = None
        self._rv: str = ""  # list resourceVersion the watch resumes from
        # serializes _poll_once between the watch and resync loops (a stale
        # in-flight list must not clobber a fresher watch-triggered update)
        self._poll_lock = asyncio.Lock()

    def _in_cluster(self) -> None:
        """Default to the standard in-cluster config (env + SA mount)."""
        import os

        if not self._api_url:
            host = os.environ.get("KUBERNETES_SERVICE_HOST", "")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "not in a cluster: KUBERNETES_SERVICE_HOST unset and no "
                    "api_url override"
                )
            self._api_url = f"https://{host}:{port}"
        if not self._token and os.path.exists(f"{SA_DIR}/token"):
            with open(f"{SA_DIR}/token") as f:
                self._token = f.read().strip()
        if not self._ca_file and os.path.exists(f"{SA_DIR}/ca.crt"):
            self._ca_file = f"{SA_DIR}/ca.crt"

    @property
    def _path(self) -> str:
        if self.mechanism == "endpointslices":
            return f"/apis/discovery.k8s.io/v1/namespaces/{self.namespace}/endpointslices"
        return f"/api/v1/namespaces/{self.namespace}/pods"

    async def _list(self) -> Optional[List[dict]]:
        params = {"labelSelector": self.selector} if self.selector else {}
        headers = (
            {"Authorization": f"Bearer {self._token}"} if self._token else {}
        )
        try:
            async with self._session.get(
                f"{self._api_url}{self._path}",
                params=params,
                headers=headers,
                timeout=aiohttp.ClientTimeout(10),
            ) as resp:
                resp.raise_for_status()
                body = await resp.json()
                rv = (body.get("metadata") or {}).get("resourceVersion")
                if rv:
                    self._rv = rv
                return body.get("items", [])
        except Exception:
            return None  # keep the stale peer list over a transient API error

    async def _poll_once(self) -> None:
        async with self._poll_lock:
            await self._poll_once_locked()

    async def _poll_once_locked(self) -> None:
        items = await self._list()
        if items is None:
            return
        if self.mechanism == "endpointslices":
            peers = extract_peers_from_endpoint_slices(
                items, self.pod_ip, self.pod_port
            )
        else:
            peers = extract_peers_from_pods(items, self.pod_ip, self.pod_port)
        key = sorted(p.grpc_address for p in peers)
        if key == self._last:
            return
        self._last = key
        self.on_update(peers)

    async def _loop(self) -> None:
        while not self._closed:
            await asyncio.sleep(self.poll_s)
            try:
                await self._poll_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("k8s poll failed")

    async def _watch_loop(self) -> None:
        """list+watch (reference kubernetes.go:79-114 informer pattern): a
        watch stream from the last list's resourceVersion; every membership
        event triggers a fresh list+extract, so propagation is event-latency
        while correctness never depends on replaying incremental events.
        Reconnects with backoff; the resync poll covers stream outages."""
        import json

        backoff = 0.05
        while not self._closed:
            try:
                params = {"watch": "1"}
                if self.selector:
                    params["labelSelector"] = self.selector
                if self._rv:
                    params["resourceVersion"] = self._rv
                headers = (
                    {"Authorization": f"Bearer {self._token}"}
                    if self._token
                    else {}
                )
                async with self._session.get(
                    f"{self._api_url}{self._path}",
                    params=params,
                    headers=headers,
                    timeout=aiohttp.ClientTimeout(total=None),
                ) as resp:
                    resp.raise_for_status()
                    backoff = 0.05
                    async for line in resp.content:
                        if self._closed:
                            return
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            ev = json.loads(line)
                        except ValueError:
                            continue
                        obj = ev.get("object") or {}
                        rv = (obj.get("metadata") or {}).get("resourceVersion")
                        if rv:
                            self._rv = rv
                        if ev.get("type") in ("ADDED", "MODIFIED", "DELETED"):
                            await self._poll_once()
                        elif ev.get("type") == "ERROR":
                            self._rv = ""  # expired RV: next watch relists
                            break
            except asyncio.CancelledError:
                raise
            except Exception:
                if self._closed:
                    return
                # the resourceVersion may be the reason the watch was
                # rejected (HTTP 410 on an expired RV); drop it so the next
                # attempt starts from current state instead of retrying a
                # dead version forever
                self._rv = ""
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 5.0)

    async def start(self) -> None:
        self._in_cluster()
        ssl_ctx = None
        if self._api_url.startswith("https") and self._ca_file:
            ssl_ctx = ssl.create_default_context(cafile=self._ca_file)
        self._session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(ssl=ssl_ctx)
        )
        await self._poll_once()
        self._task = asyncio.create_task(self._loop(), name="k8s-pool")
        self._watch_task = asyncio.create_task(
            self._watch_loop(), name="k8s-watch"
        )

    async def close(self) -> None:
        self._closed = True
        for t in (self._task, self._watch_task):
            if t is not None:
                t.cancel()
                try:
                    await t
                except asyncio.CancelledError:
                    pass
        if self._session is not None:
            await self._session.close()
