"""Etcd peer discovery — register self under a key prefix with a kept-alive
lease; watch the prefix for the peer set.

Mirrors reference etcd.go:221-315: each node PUTs its PeerInfo JSON at
`<prefix><advertise-address>` bound to a TTL lease (30 s default), keeps the
lease alive at TTL/2 cadence, re-grants + re-registers if the lease is lost,
and on close deletes its key and revokes the lease so peers see it disappear
immediately. Peer changes surface through a **watch stream** on the prefix
(reference etcd.go:173-219) — each event triggers a fresh range read, so
membership changes propagate at event latency, not poll cadence; the range
poll stays on as a low-cadence fallback that also observes lease expiry
through an outage of the stream.

Speaks etcd's v3 HTTP/JSON gateway (`/v3/kv/*`, `/v3/lease/*`; keys/values
are base64 in JSON), so no etcd client library is required; the endpoint is
injectable and tests run an in-process fake.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
from typing import Callable, Dict, List, Optional

import aiohttp

from gubernator_tpu.types import PeerInfo

log = logging.getLogger("gubernator_tpu.etcd")

DEFAULT_PREFIX = "/gubernator/peers/"  # reference etcd.go etcdKeyPrefix


def _b64(s: str) -> str:
    return base64.b64encode(s.encode()).decode()


def _unb64(s: str) -> str:
    return base64.b64decode(s).decode()


def unmarshall_peer(value: str) -> PeerInfo:
    """PeerInfo from a stored JSON value; a bare address string is accepted
    for interop with old registrations (reference etcd.go:162-170)."""
    try:
        d = json.loads(value)
        return PeerInfo(
            grpc_address=d.get("grpc_address") or d.get("GRPCAddress", ""),
            http_address=d.get("http_address") or d.get("HTTPAddress", ""),
            data_center=d.get("data_center") or d.get("DataCenter", ""),
        )
    except (ValueError, AttributeError):
        return PeerInfo(grpc_address=value)


class EtcdPool:
    def __init__(
        self,
        endpoint: str,  # http(s)://host:port of any etcd gateway
        on_update: Callable[[List[PeerInfo]], None],
        peer_info: PeerInfo,
        key_prefix: str = DEFAULT_PREFIX,
        lease_ttl_s: int = 30,
        poll_ms: float = 2_000.0,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.on_update = on_update
        self.peer_info = peer_info
        self.key_prefix = key_prefix
        self.lease_ttl_s = lease_ttl_s
        self.poll_s = max(poll_ms / 1e3, 0.01)
        self.lease_id: Optional[int] = None
        self._session: Optional[aiohttp.ClientSession] = None
        self._tasks: List[asyncio.Task] = []
        self._closed = False
        self._last: Optional[List[str]] = None
        # serializes _poll_once between the watch and poll loops: without it
        # a slow stale range read can land after a fresher watch-triggered
        # one and re-publish an outdated peer list
        self._poll_lock = asyncio.Lock()

    @property
    def _key(self) -> str:
        return self.key_prefix + self.peer_info.grpc_address

    def _prefix_range_end(self) -> str:
        """etcd successor key covering everything under the prefix."""
        return self.key_prefix[:-1] + chr(ord(self.key_prefix[-1]) + 1)

    async def _post(self, path: str, body: dict) -> dict:
        async with self._session.post(
            f"{self.endpoint}{path}", json=body, timeout=aiohttp.ClientTimeout(5)
        ) as resp:
            resp.raise_for_status()
            return await resp.json()

    # ------------------------------------------------------------- register
    async def _register(self) -> None:
        """Grant a lease and PUT our PeerInfo bound to it (etcd.go:221-266)."""
        got = await self._post("/v3/lease/grant", {"TTL": self.lease_ttl_s})
        self.lease_id = int(got["ID"])
        value = json.dumps(
            dict(
                grpc_address=self.peer_info.grpc_address,
                http_address=self.peer_info.http_address,
                data_center=self.peer_info.data_center,
            )
        )
        await self._post(
            "/v3/kv/put",
            {"key": _b64(self._key), "value": _b64(value), "lease": self.lease_id},
        )

    async def _keepalive_loop(self) -> None:
        """Refresh the lease at TTL/2; on failure re-grant + re-register
        (the reference re-registers on keepalive channel loss,
        etcd.go:286-315)."""
        while not self._closed:
            await asyncio.sleep(self.lease_ttl_s / 2)
            try:
                got = await self._post(
                    "/v3/lease/keepalive", {"ID": self.lease_id}
                )
                ttl = int(got.get("result", {}).get("TTL", 0))
                if ttl <= 0:
                    raise RuntimeError("lease lost")
            except asyncio.CancelledError:
                raise
            except Exception:
                if self._closed:
                    return
                log.warning("etcd keepalive failed; re-registering")
                try:
                    await self._register()
                except Exception:
                    log.exception("etcd re-register failed")

    # ----------------------------------------------------------------- watch
    async def _collect_peers(self) -> Optional[Dict[str, PeerInfo]]:
        try:
            got = await self._post(
                "/v3/kv/range",
                {
                    "key": _b64(self.key_prefix),
                    "range_end": _b64(self._prefix_range_end()),
                },
            )
        except Exception:
            return None  # transient outage: keep the stale list
        out: Dict[str, PeerInfo] = {}
        for kv in got.get("kvs", []):
            info = unmarshall_peer(_unb64(kv["value"]))
            if info.grpc_address:
                out[info.grpc_address] = info
        return out

    async def _poll_loop(self) -> None:
        while not self._closed:
            try:
                await self._poll_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("etcd poll failed")
            await asyncio.sleep(self.poll_s)

    async def _poll_once(self) -> None:
        async with self._poll_lock:
            await self._poll_once_locked()

    async def _poll_once_locked(self) -> None:
        peers = await self._collect_peers()
        if peers is None:
            return
        key = sorted(peers)
        if key == self._last:
            return
        self._last = key
        for info in peers.values():
            info.is_owner = info.grpc_address == self.peer_info.grpc_address
        self.on_update(list(peers.values()))

    async def _watch_loop(self) -> None:
        """Hold a watch stream on the key prefix (reference etcd.go:173-219,
        via the v3 gateway's server-streaming /v3/watch). Events are change
        NOTIFIERS: each one triggers a range re-read, so watch-vs-state
        consistency never depends on replaying incremental events. Reconnects
        with backoff; the poll loop covers any stream outage."""
        body = {
            "create_request": {
                "key": _b64(self.key_prefix),
                "range_end": _b64(self._prefix_range_end()),
            }
        }
        backoff = 0.05
        while not self._closed:
            try:
                async with self._session.post(
                    f"{self.endpoint}/v3/watch",
                    json=body,
                    timeout=aiohttp.ClientTimeout(total=None),
                ) as resp:
                    resp.raise_for_status()
                    backoff = 0.05
                    async for line in resp.content:
                        if self._closed:
                            return
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            msg = json.loads(line)
                        except ValueError:
                            continue
                        if msg.get("result", {}).get("events"):
                            await self._poll_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                if self._closed:
                    return
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 5.0)

    # ------------------------------------------------------------- lifecycle
    async def start(self) -> None:
        self._session = aiohttp.ClientSession()
        await self._register()
        await self._poll_once()
        self._tasks = [
            asyncio.create_task(self._keepalive_loop(), name="etcd-keepalive"),
            asyncio.create_task(self._poll_loop(), name="etcd-poll"),
            asyncio.create_task(self._watch_loop(), name="etcd-watch"),
        ]

    async def close(self) -> None:
        """Deregister: delete our key + revoke the lease (etcd.go:297-309)."""
        if self._closed:
            return
        self._closed = True
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        try:
            await self._post("/v3/kv/deleterange", {"key": _b64(self._key)})
            if self.lease_id is not None:
                await self._post("/v3/lease/revoke", {"ID": self.lease_id})
        except Exception:
            pass  # best effort; the lease TTL cleans up
        if self._session is not None:
            await self._session.close()
