"""Memberlist peer discovery — a self-contained anti-entropy gossip pool.

The reference embeds hashicorp/memberlist (SWIM gossip over UDP/TCP) and
carries each node's PeerInfo as JSON metadata; join retries against seed
nodes, and join/leave/update events rebuild the peer map (reference
memberlist.go:93-192, 228-301). This re-implementation keeps the same
observable behavior with a deliberately simple protocol:

* full-state **push-pull over TCP**: every gossip tick each node syncs its
  member table with one random known peer (and with every seed at join);
  entries are (name → PeerInfo JSON, incarnation, heartbeat) and merge by
  (incarnation, heartbeat) dominance — the anti-entropy half of SWIM, which
  is what drives hashicorp's convergence too.
* **liveness by heartbeat age**: a node bumps its own heartbeat every tick;
  entries not refreshed within `suspect_ticks` ticks are dropped (the
  probe/suspect machinery collapses into this because state rides the same
  sync channel).
* **graceful leave**: close() pushes a tombstone (incarnation bump + dead
  flag) to known peers, the NotifyLeave analog.

Encryption: an optional AES-256/192/128-GCM keyring (the reference's
SecretKey/keyring, memberlist.go:149-167) seals every state blob —
`GUBER_MEMBERLIST_SECRET_KEYS` takes comma-separated base64 keys, the FIRST
encrypts outbound gossip and ALL decrypt inbound (key rotation: add the new
key everywhere, promote it to first, drop the old). With a keyring set,
plaintext blobs are rejected (GossipVerifyIncoming semantics); without one,
sealed blobs are undecodable noise. The message format is one (optionally
sealed) JSON object per connection, length-prefixed by socket EOF.
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from gubernator_tpu.types import PeerInfo

log = logging.getLogger("gubernator_tpu.memberlist")

MAX_STATE_BYTES = 1 << 20
ENC_MAGIC = b"GUBENC1\x00"  # sealed-blob marker + format version
_ENC_AAD = b"gubernator-memberlist-v1"


@dataclass
class Member:
    name: str  # advertise address — unique node id
    peer: dict  # PeerInfo fields (grpc_address, http_address, data_center)
    incarnation: int = 0
    heartbeat: int = 0
    dead: bool = False
    age_ticks: int = 0  # local staleness counter (not gossiped)

    def dominates(self, other: "Member") -> bool:
        return (self.incarnation, self.heartbeat, self.dead) > (
            other.incarnation,
            other.heartbeat,
            other.dead,
        )


class MemberlistPool:
    """Gossip discovery pool; calls on_update(peers) when membership changes."""

    def __init__(
        self,
        bind_address: str,
        known_nodes: List[str],
        on_update: Callable[[List[PeerInfo]], None],
        peer_info: PeerInfo,
        advertise_address: str = "",
        gossip_interval_ms: float = 500.0,
        suspect_ticks: int = 6,
        secret_keys: Optional[List[bytes]] = None,
    ):
        for k in secret_keys or []:
            if len(k) not in (16, 24, 32):
                raise ValueError(
                    "memberlist secret keys must be 16, 24 or 32 bytes "
                    f"(got {len(k)})"
                )
        self.secret_keys = list(secret_keys or [])
        self.bind_address = bind_address
        self.advertise_address = advertise_address or bind_address
        self.known_nodes = [n for n in known_nodes if n]
        self.on_update = on_update
        self.interval_s = max(gossip_interval_ms / 1e3, 0.01)
        self.suspect_ticks = suspect_ticks
        self.name = self.advertise_address
        self._self = Member(
            name=self.name,
            peer=dict(
                grpc_address=peer_info.grpc_address,
                http_address=peer_info.http_address,
                data_center=peer_info.data_center,
            ),
            incarnation=0,
        )
        self._members: Dict[str, Member] = {self.name: self._self}
        self._server: Optional[asyncio.AbstractServer] = None
        self._task: Optional[asyncio.Task] = None
        self._closed = False
        self._last_published: Optional[List[str]] = None
        self.gossip_port: Optional[int] = None

    # ---------------------------------------------------------------- state
    def _state_blob(self) -> bytes:
        rows = [
            dict(
                name=m.name,
                peer=m.peer,
                incarnation=m.incarnation,
                heartbeat=m.heartbeat,
                dead=m.dead,
            )
            for m in self._members.values()
        ]
        blob = json.dumps({"from": self.name, "members": rows}).encode()
        return self._seal(blob)

    # ------------------------------------------------------------ encryption
    def _seal(self, blob: bytes) -> bytes:
        """AES-GCM-seal with the primary key (reference memberlist.go:149-167
        keyring); identity when no keyring is configured."""
        if not self.secret_keys:
            return blob
        import os as _os

        from cryptography.hazmat.primitives.ciphers.aead import AESGCM

        nonce = _os.urandom(12)
        ct = AESGCM(self.secret_keys[0]).encrypt(nonce, blob, _ENC_AAD)
        return ENC_MAGIC + nonce + ct

    def _unseal(self, raw: bytes) -> Optional[bytes]:
        """Inverse of _seal; None = reject (plaintext under a keyring,
        sealed without one, or no key authenticates — the
        GossipVerifyIncoming/Outgoing contract)."""
        sealed = raw.startswith(ENC_MAGIC)
        if not self.secret_keys:
            return None if sealed else raw
        if not sealed:
            return None  # keyring on → plaintext gossip is rejected
        from cryptography.exceptions import InvalidTag
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM

        nonce = raw[len(ENC_MAGIC) : len(ENC_MAGIC) + 12]
        ct = raw[len(ENC_MAGIC) + 12 :]
        for key in self.secret_keys:  # any keyring member may authenticate
            try:
                return AESGCM(key).decrypt(nonce, ct, _ENC_AAD)
            except InvalidTag:
                continue
        return None

    def _merge(self, blob: dict) -> None:
        changed = False
        for row in blob.get("members", []):
            name = row.get("name")
            if not name:
                continue
            inc = Member(
                name=name,
                peer=row.get("peer", {}),
                incarnation=int(row.get("incarnation", 0)),
                heartbeat=int(row.get("heartbeat", 0)),
                dead=bool(row.get("dead", False)),
            )
            if name == self.name:
                # someone claims we're dead/stale — refute by out-incarnating
                # (the memberlist Alive/refute rule)
                if inc.dead and inc.incarnation >= self._self.incarnation:
                    self._self.incarnation = inc.incarnation + 1
                    changed = True
                continue
            cur = self._members.get(name)
            if cur is None or inc.dominates(cur):
                inc.age_ticks = 0
                if cur is None and not inc.dead:
                    log.info("%s: join %s", self.name, name)
                self._members[name] = inc
                changed = True
        if changed:
            self._publish()

    def _publish(self) -> None:
        alive = [m for m in self._members.values() if not m.dead]
        key = sorted(m.name for m in alive)
        if key == self._last_published:
            return
        self._last_published = key
        peers = [
            PeerInfo(
                grpc_address=m.peer.get("grpc_address", m.name),
                http_address=m.peer.get("http_address", ""),
                data_center=m.peer.get("data_center", ""),
                is_owner=(m.name == self.name),
            )
            for m in alive
        ]
        self.on_update(peers)

    # ------------------------------------------------------------- transport
    @staticmethod
    async def _read_blob(reader) -> bytes:
        """Read the peer's whole state blob (terminated by write_eof). A bare
        read() returns after the FIRST segment, so multi-segment blobs (any
        non-trivial member count) would parse partially — loop to EOF."""
        chunks = []
        total = 0
        while total <= MAX_STATE_BYTES:
            chunk = await reader.read(1 << 16)
            if not chunk:
                break
            chunks.append(chunk)
            total += len(chunk)
        return b"".join(chunks)

    async def _handle(self, reader, writer) -> None:
        """Push-pull: read the remote table, merge, answer with ours."""
        try:
            raw = await asyncio.wait_for(self._read_blob(reader), 5.0)
            blob = self._unseal(raw)
            if blob is None:
                return  # unauthenticated gossip is dropped silently
            remote = json.loads(blob.decode())
            writer.write(self._state_blob())
            await writer.drain()
            writer.write_eof()
            self._merge(remote)
        except (asyncio.TimeoutError, ValueError, OSError):
            pass
        finally:
            writer.close()

    async def _push_pull(self, addr: str) -> bool:
        host, _, port = addr.rpartition(":")
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host or "127.0.0.1", int(port)), 2.0
            )
        except (OSError, asyncio.TimeoutError, ValueError):
            return False
        try:
            writer.write(self._state_blob())
            await writer.drain()
            writer.write_eof()
            raw = await asyncio.wait_for(self._read_blob(reader), 5.0)
            blob = self._unseal(raw)
            if blob is None:
                return False
            self._merge(json.loads(blob.decode()))
            return True
        except (OSError, asyncio.TimeoutError, ValueError):
            return False
        finally:
            writer.close()

    # ------------------------------------------------------------- lifecycle
    async def start(self) -> None:
        host, _, port = self.bind_address.rpartition(":")
        self._server = await asyncio.start_server(
            self._handle, host or "127.0.0.1", int(port)
        )
        self.gossip_port = self._server.sockets[0].getsockname()[1]
        if self.bind_address.endswith(":0"):
            self.bind_address = f"{host or '127.0.0.1'}:{self.gossip_port}"
            if self.advertise_address.endswith(":0"):
                self.advertise_address = self.bind_address
                self.name = self._self.name = self.advertise_address
                self._members = {self.name: self._self}
        # join: sync with every seed, retrying like the reference's 300 ms
        # join loop (memberlist.go:178-192); non-fatal if all are down — the
        # gossip loop keeps trying
        for seed in self.known_nodes:
            if seed != self.advertise_address:
                await self._push_pull(seed)
        self._publish()
        self._task = asyncio.create_task(self._loop(), name="memberlist-gossip")

    async def _loop(self) -> None:
        while not self._closed:
            await asyncio.sleep(self.interval_s)
            try:
                self._tick()
                targets = [
                    m.name for m in self._members.values()
                    if m.name != self.name and not m.dead
                ] or [s for s in self.known_nodes if s != self.advertise_address]
                if targets:
                    await self._push_pull(random.choice(targets))
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("gossip tick failed")

    def _tick(self) -> None:
        self._self.heartbeat += 1
        expired = []
        reap = []
        for m in self._members.values():
            if m.name == self.name:
                continue
            m.age_ticks += 1
            if not m.dead and m.age_ticks > self.suspect_ticks:
                expired.append(m.name)
            elif m.dead and m.age_ticks > 8 * self.suspect_ticks:
                # the tombstone has gossiped long enough — reap it, or the
                # state blob grows forever under identity churn (pod restarts
                # mint fresh names) and eventually overflows MAX_STATE_BYTES,
                # wedging every future push-pull
                reap.append(m.name)
        for name in expired:
            log.info("%s: suspect-timeout %s", self.name, name)
            self._members[name].dead = True
        for name in reap:
            del self._members[name]
        if expired:
            self._publish()

    async def close(self) -> None:
        """Graceful leave: tombstone ourselves and tell live peers."""
        if self._closed:
            return
        self._closed = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        self._self.incarnation += 1
        self._self.dead = True
        for m in list(self._members.values()):
            if m.name != self.name and not m.dead:
                await self._push_pull(m.name)
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
