"""Hot-set tiering: the host-RAM shadow table behind the HBM hot set.

HBM is the capacity ceiling the ROADMAP's elastic-fleet item names ("1B
tracked keys per pod with HBM holding the hot ~10%"): the decide path's
state array must fit HBM, so today a table sized for the hot set silently
DISCARDS the displaced row's state on every live eviction — a permissive
re-grant the next time that key shows up. This package turns eviction
into a tiering event instead:

* **demote-on-evict** — the decide kernels return the evicted rows as a
  sidecar riding the response fetch (kernel2/pallas_probe `evictees=`)
  and the engine appends them to the shadow;
* **demote-on-idle** — a background sweep (tier/manager.py, telemetry
  cadence) pulls rows idle past GUBER_TIER_IDLE_MS out of HBM
  (table2.extract_idle_rows + tombstone) into the shadow;
* **fault-back** — host staging probes the shadow for the batch's
  fingerprints (exact dict hit, off the hot path for misses); hits are
  removed from the shadow and installed through the conservative merge
  (kernel2.merge2) BEFORE the decide dispatch, so a promoted stale row
  can only UNDER-grant — the same pinned conservatism as checkpoint
  replay, handoff, and region sync.

Capacity now scales with TRACKED keys (host RAM + optional spill file)
while decisions/s tracks the HOT set (HBM). Losing the shadow (no spill,
kill -9) degrades exactly to today's eviction behavior — state loss, and
over-admission bounded by the per-key limits — never worse.

See docs/tiering.md.
"""

from gubernator_tpu.tier.shadow import ROW_BYTES, ShadowTable  # noqa: F401
