"""TierManager: the daemon's hot-set tiering plane.

Inert unless GUBER_TIER_ENABLED — then it owns the ShadowTable, arms the
engine's evict capture + fault-back (engine.shadow), runs the
demote-on-idle sweep on the telemetry cadence, writes tombstone frames
into the delta log so demoted rows do not resurrect on warm restart
(service/checkpoint.append_tombstones), and feeds the gubernator_tier_*
metric families + /v1/debug/tier.

Sweep ordering (the crash-safety argument, docs/tiering.md):

  1. ONE engine-thread job extracts idle rows AND tombstones them out of
     HBM (EngineRunner.tier_demote_idle — no decide interleaves, so the
     demoted copy is exactly the state that left the table);
  2. the rows enter the shadow (RAM) and, when a spill file is
     configured, flush to it durably;
  3. only THEN the tombstone frame is appended to the delta log.

A death between (1) and (3) leaves the row's last state frame replayable
with no tombstone — restart resurrects it into HBM, which is the
conservative direction (the state survives; the capacity win of one
sweep is re-earned). A death after (3) finds the row in the spill, and
fault-back serves it from there.
"""

from __future__ import annotations

import asyncio
import logging

import numpy as np

log = logging.getLogger("gubernator_tpu.tier")

# rows demoted per sweep at most — bounds the engine-thread job the sweep
# enqueues (extract fetch + tombstone); the remainder demotes next sweep
SWEEP_MAX_ROWS = 1 << 16


class TierManager:
    def __init__(self, daemon):
        self.daemon = daemon
        conf = daemon.conf
        self.enabled = bool(getattr(conf, "tier_enabled", False))
        self.idle_ms = float(getattr(conf, "tier_idle_ms", 60_000.0))
        # sweep on the telemetry cadence (the ISSUE contract); a disabled
        # telemetry loop falls back to its default 5 s so tiering does
        # not silently stop demoting
        self.sweep_s = (conf.telemetry_interval_ms or 5_000.0) / 1e3
        self.shadow = None
        self.sweeps = 0
        self.last_sweep_demoted = 0
        if self.enabled:
            from gubernator_tpu.tier.shadow import ShadowTable

            self.shadow = ShadowTable(
                max_bytes=int(conf.tier_shadow_bytes),
                spill_path=conf.tier_spill_path or None,
            )

    # ----------------------------------------------------------------- boot
    def attach(self) -> None:
        """Arm the engine (evict capture + fault-back) and index an
        existing spill file. Must run AFTER the checkpoint restore (the
        delta replay — including tombstone frames — settles HBM first)
        and before the listeners serve."""
        if not self.enabled:
            return
        loaded = self.shadow.load()
        if loaded:
            log.info("tier shadow spill indexed %d rows", loaded)
        eng = self.daemon.engine
        if hasattr(eng, "attach_shadow"):
            eng.attach_shadow(self.shadow)
        else:
            eng.shadow = self.shadow
        log.info(
            "hot-set tiering armed: idle_ms=%d shadow_bytes=%d spill=%s",
            int(self.idle_ms), self.shadow.max_bytes,
            self.daemon.conf.tier_spill_path or "(none)",
        )

    # ----------------------------------------------------------------- sweep
    async def loop(self) -> None:
        while not self.daemon._shutting_down:
            await asyncio.sleep(self.sweep_s)
            try:
                await self.sweep_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # pragma: no cover - defensive
                log.exception("tier sweep tick failed")

    async def sweep_once(self) -> dict:
        """One demote-on-idle round; returns a summary for tests/debug."""
        daemon = self.daemon
        now, fps, rows = await daemon.runner.tier_demote_idle(
            int(self.idle_ms), SWEEP_MAX_ROWS
        )
        self.sweeps += 1
        self.last_sweep_demoted = int(fps.shape[0])
        out = {"demoted": self.last_sweep_demoted}
        if fps.shape[0]:
            self.shadow.offer(fps, rows, now, reason="idle")
            self.shadow.flush(now)
            # removal record for warm restart — AFTER the shadow holds
            # the rows (module docstring ordering)
            await daemon.checkpointer.append_tombstones(fps)
        self.observe()
        return out

    # --------------------------------------------------------------- status
    def observe(self) -> None:
        """Refresh the gubernator_tier_* families from shadow counters
        (delta-inc for the monotone ones, set for the gauges)."""
        if not self.enabled:
            return
        m = self.daemon.metrics
        st = self.shadow.stats()
        m.tier_shadow_rows.set(st["ram_rows"])
        m.tier_shadow_bytes.set(st["nominal_bytes"])
        if "spill" in st:
            m.tier_spilled_rows.set(st["spill"]["indexed_rows"])
        last = getattr(self, "_last", None) or {}
        for key, counter, labels in (
            ("demoted_evict", m.tier_demoted, {"reason": "evict"}),
            ("demoted_idle", m.tier_demoted, {"reason": "idle"}),
            ("promoted", m.tier_promoted, None),
            ("shed", m.tier_shed, None),
            ("promote_returned", m.tier_promote_returned, None),
        ):
            d = st[key] - last.get(key, 0)
            if d > 0:
                (counter.labels(**labels) if labels else counter).inc(d)
        self._last = {
            k: st[k]
            for k in ("demoted_evict", "demoted_idle", "promoted", "shed",
                      "promote_returned")
        }

    def debug(self) -> dict:
        """/v1/debug/tier snapshot."""
        out = {
            "enabled": self.enabled,
            "idle_ms": self.idle_ms,
            "sweep_interval_s": self.sweep_s,
            "sweeps": self.sweeps,
            "last_sweep_demoted": self.last_sweep_demoted,
        }
        if self.enabled:
            out["shadow"] = self.shadow.stats()
            out["evicted_live_total"] = self.daemon.engine.stats.evicted_unexpired
        return out

    def close(self, now_ms: int) -> None:
        """Shutdown flush (sync — runs in an executor off the loop):
        persist unspilled shadow rows so a graceful restart faults them
        back from disk."""
        if self.enabled and self.shadow is not None:
            self.shadow.flush(now_ms)
