"""Host-RAM shadow table: fp-keyed canonical 64 B rows + optional spill.

The shadow is the demotion target for rows leaving HBM (evictee sidecar,
idle sweep) and the fault-back source during host staging. Design points:

* **Canonical rows.** Entries are always the 16-field full-width slot row
  (ops/layout.py conversion contract): demotes unpack the table's own
  layout at the boundary, promotes re-enter through `merge_rows` which
  packs back — so a row that lived in a packed table round-trips
  bit-exactly and cross-layout restarts stay sound.
* **Byte bound.** `max_bytes` bounds the RAM set at the nominal
  ROW_BYTES (64) per row — the state bytes themselves, the figure the
  tier_smoke gate checks. Over-budget entries shed oldest-demoted-first
  (LRU over demote/refresh time): to the spill file when one is
  configured (lossless), else dropped and counted — exactly today's
  eviction loss, never worse.
* **Conservative conflicts.** A demote for a fingerprint already
  shadowed merges host-side with the merge2 rules (remaining=min,
  expiry=max, aux=max-same-algo, OVER sticks, newest-stamp config) —
  a duplicated or reordered demote can only tighten.
* **Spill file.** DeltaLog frame format (store.py — CRC-framed raw-LE
  full-layout rows), append-only with an in-memory fp → byte-offset
  index for O(1) single-row fault-back reads; compacts when garbage
  dominates. Spill writes are BATCHED (`flush()`, sweep cadence) so the
  serving-path evict capture never pays an fsync. Promote REMOVALS are
  RAM-only: after a restart a promoted row may be re-promoted stale,
  which the conservative merge renders harmless (under-grant only).
"""

from __future__ import annotations

import logging
import os
import struct
import tempfile
import threading
import zlib
from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np

from gubernator_tpu.ops.table2 import (
    BURST,
    DUR_HI,
    DUR_LO,
    EXP_HI,
    EXP_LO,
    F,
    FLAGS,
    LIMIT,
    REM_I,
    REMF_HI,
    REMF_LO,
    STAMP_HI,
    STAMP_LO,
)
from gubernator_tpu.store import (
    DELTA_LOG_MAGIC,
    _FRAME_HEADER,
    encode_delta_frame,
    read_delta_frames,
)

log = logging.getLogger("gubernator_tpu.tier")

ROW_BYTES = F * 4  # canonical full-width slot row: the shadow's unit cost


def _join(slots: np.ndarray, lo: int, hi: int) -> np.ndarray:
    return (slots[:, hi].astype(np.int64) << 32) | (
        slots[:, lo].astype(np.int64) & 0xFFFFFFFF
    )


def _split(vals: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    lo_u = vals & 0xFFFFFFFF
    lo = np.where(lo_u >= (1 << 31), lo_u - (1 << 32), lo_u).astype(np.int32)
    return lo, (vals >> 32).astype(np.int32)


def _remf_f64(slots: np.ndarray) -> np.ndarray:
    return (
        slots[:, REMF_HI].view(np.float32).astype(np.float64)
        + slots[:, REMF_LO].view(np.float32).astype(np.float64)
    )


def merge_canonical_rows(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Host-side conservative merge of same-fingerprint canonical rows —
    the numpy twin of kernel2.merge2's exists-branch (remaining=min,
    expiry=max, aux=max when algorithms agree else config winner's,
    OVER sticks, newest-stamp config wins). (n, 16) × (n, 16) → (n, 16);
    used for shadow offer conflicts and spill-load dedup, so a duplicated
    demote can only tighten what a later promote installs."""
    a = np.ascontiguousarray(a, dtype=np.int32)
    b = np.ascontiguousarray(b, dtype=np.int32)
    out = a.copy()
    st_a, st_b = _join(a, STAMP_LO, STAMP_HI), _join(b, STAMP_LO, STAMP_HI)
    keep_a = st_a > st_b  # config carrier: the newer stamp's side
    for f_ in (LIMIT, BURST, DUR_LO, DUR_HI):
        out[:, f_] = np.where(keep_a, a[:, f_], b[:, f_])
    algo = np.where(keep_a, a[:, FLAGS] & 0xFF, b[:, FLAGS] & 0xFF)
    status = np.maximum(a[:, FLAGS] >> 8, b[:, FLAGS] >> 8)
    out[:, FLAGS] = algo | (status << 8)
    out[:, REM_I] = np.minimum(a[:, REM_I], b[:, REM_I])
    exp = np.maximum(_join(a, EXP_LO, EXP_HI), _join(b, EXP_LO, EXP_HI))
    out[:, EXP_LO], out[:, EXP_HI] = _split(exp)
    stamp = np.maximum(st_a, st_b)
    out[:, STAMP_LO], out[:, STAMP_HI] = _split(stamp)
    # raw aux pair (GCRA TAT / window prev): max tightens when the two
    # sides agree on the algorithm, else the config winner's raw value;
    # the float lane keeps its unconditional min (merge2's own rule)
    aux_a, aux_b = _join(a, REMF_LO, REMF_HI), _join(b, REMF_LO, REMF_HI)
    same = (a[:, FLAGS] & 0xFF) == (b[:, FLAGS] & 0xFF)
    aux = np.where(
        same, np.maximum(aux_a, aux_b), np.where(keep_a, aux_a, aux_b)
    )
    rem_f = np.minimum(_remf_f64(a), _remf_f64(b))
    f_hi = rem_f.astype(np.float32)
    f_lo = (rem_f - f_hi.astype(np.float64)).astype(np.float32)
    aux_lo, aux_hi = _split(aux)
    is_aux = (algo == 2) | (algo == 3)  # GCRA | sliding window
    out[:, REMF_HI] = np.where(is_aux, aux_hi, f_hi.view(np.int32))
    out[:, REMF_LO] = np.where(is_aux, aux_lo, f_lo.view(np.int32))
    return out


class _SpillFile:
    """Append-only DeltaLog-format spill with an fp → byte-offset index.

    One frame per flush; each indexed row is read back with a single
    seek + 64 B read. Compaction rewrites the live rows into a fresh
    file (atomic replace) when garbage dominates. NOT thread-safe on its
    own — the owning ShadowTable's lock serializes every call."""

    COMPACT_MIN_BYTES = 1 << 22  # don't bother below 4 MiB
    _ROW = ROW_BYTES

    def __init__(self, path: str):
        self.path = path
        self.index: dict = {}  # fp -> absolute byte offset of the row
        self.payload_bytes = 0  # all row bytes ever appended (garbage incl.)
        self.read_errors = 0
        self.loaded_rows = 0

    # ------------------------------------------------------------- loading
    def load(self) -> int:
        """Rebuild the index from an existing spill file (boot). Later
        frames supersede earlier ones; a torn tail is ignored (the clean
        prefix is what the scan yields). Returns indexed rows."""
        scan = read_delta_frames(self.path)
        if scan.error:
            log.warning("tier spill %s: %s — keeping the clean prefix",
                        self.path, scan.error)
        off = len(DELTA_LOG_MAGIC)
        for _epoch, _now, slots, layout in scan.frames:
            payload_off = off + _FRAME_HEADER.size
            n = slots.shape[0]
            width = slots.shape[1] * 4
            if getattr(layout, "F", None) == F:
                fps = (slots[:, 1].astype(np.int64) << 32) | (
                    slots[:, 0].astype(np.int64) & 0xFFFFFFFF
                )
                for i in range(n):
                    if fps[i] != 0:
                        self.index[int(fps[i])] = payload_off + i * self._ROW
            off = payload_off + n * width
        self.payload_bytes = max(0, off - len(DELTA_LOG_MAGIC))
        self.loaded_rows = len(self.index)
        return self.loaded_rows

    # ------------------------------------------------------------ appending
    def append(self, fps: np.ndarray, rows: np.ndarray, now_ms: int) -> None:
        """Append one frame of canonical rows; index every row."""
        n = int(fps.shape[0])
        if n == 0:
            return
        frame = encode_delta_frame(0, now_ms, rows.astype(np.int32))
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        os.makedirs(d, exist_ok=True)
        fresh = not os.path.exists(self.path) or os.path.getsize(
            self.path
        ) == 0
        with open(self.path, "ab") as f:
            if fresh:
                f.write(DELTA_LOG_MAGIC)
            base = f.tell() + _FRAME_HEADER.size
            f.write(frame)
            f.flush()
            os.fsync(f.fileno())
        for i in range(n):
            self.index[int(fps[i])] = base + i * self._ROW
        self.payload_bytes += n * self._ROW

    # -------------------------------------------------------------- reading
    def read(self, fp: int) -> Optional[np.ndarray]:
        """One indexed row ((16,) int32) or None. Validates the stored
        fingerprint — a mismatch (torn/foreign file) drops the entry."""
        off = self.index.get(fp)
        if off is None:
            return None
        try:
            with open(self.path, "rb") as f:
                f.seek(off)
                buf = f.read(self._ROW)
        except OSError:
            self.read_errors += 1
            self.index.pop(fp, None)
            return None
        if len(buf) < self._ROW:
            self.read_errors += 1
            self.index.pop(fp, None)
            return None
        row = np.frombuffer(buf, dtype="<i4").astype(np.int32)
        got = (int(row[1]) << 32) | (int(row[0]) & 0xFFFFFFFF)
        if got != fp:
            self.read_errors += 1
            self.index.pop(fp, None)
            return None
        return row

    def discard(self, fp: int) -> None:
        self.index.pop(fp, None)

    # ----------------------------------------------------------- compaction
    def maybe_compact(self, now_ms: int) -> bool:
        """Rewrite live rows into a fresh file when garbage dominates
        (> half the payload) and the file is worth the I/O."""
        live = len(self.index) * self._ROW
        if self.payload_bytes < self.COMPACT_MIN_BYTES:
            return False
        if live * 2 > self.payload_bytes:
            return False
        fps = np.fromiter(self.index.keys(), dtype=np.int64,
                          count=len(self.index))
        rows = np.zeros((fps.shape[0], F), dtype=np.int32)
        keep = np.zeros(fps.shape[0], dtype=bool)
        for i, fp in enumerate(fps):
            row = self.read(int(fp))
            if row is not None:
                rows[i] = row
                keep[i] = True
        fps, rows = fps[keep], rows[keep]
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".gubtpu-spill-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(DELTA_LOG_MAGIC)
                base = f.tell() + _FRAME_HEADER.size
                if fps.shape[0]:
                    f.write(encode_delta_frame(0, now_ms, rows))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self.index = {
            int(fps[i]): base + i * self._ROW for i in range(fps.shape[0])
        }
        self.payload_bytes = fps.shape[0] * self._ROW
        return True

    def size_bytes(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0


class ShadowTable:
    """The host-side tier: fp → canonical 64 B row, byte-bounded RAM set
    with LRU shed-to-spill (or shed-and-count), batched durable spill,
    and exact-match fault-back probes. Thread-safe (one lock): offers
    arrive from fetch threads (evict capture) and the sweep task, probes
    from prep threads, flushes from the tier manager."""

    def __init__(self, max_bytes: int, spill_path: Optional[str] = None):
        if max_bytes <= 0:
            raise ValueError("shadow max_bytes must be positive")
        self.max_bytes = int(max_bytes)
        self._rows: "OrderedDict[int, np.ndarray]" = OrderedDict()
        self._unspilled: set = set()  # fps RAM-newer than the spill file
        self.spill = _SpillFile(spill_path) if spill_path else None
        self._lock = threading.Lock()
        # Bloom pre-filter over everything ever shadowed: the fault-back
        # probe runs per BATCH on the serving path, and for hot-set
        # traffic every fingerprint misses — the vectorized two-probe
        # reject makes a full-batch miss cost microseconds instead of a
        # per-fp dict walk. Removals never clear bits (promotes leave
        # false positives, which the dict then rejects exactly), so the
        # filter only ever errs toward the slow-but-correct path. Sized
        # ~16 bits per row the byte budget can hold, clamped to
        # [2^16, 2^30] bits.
        bits = 16 * max(1, self.max_bytes // ROW_BYTES)
        p = 1 << 16
        while p < bits and p < (1 << 30):
            p *= 2
        self._bloom_mask = np.uint64(p - 1)
        self._bloom = np.zeros(p >> 6, dtype=np.uint64)
        # counters (cumulative; the metrics layer diffs them)
        self.demoted_evict = 0
        self.demoted_idle = 0
        self.promoted = 0
        # promote rows handed BACK (claim dropped after retries — > K
        # same-bucket promotes in one batch): their decide that batch may
        # have fresh-granted; the bound docs/tiering.md documents
        self.promote_returned = 0
        self.shed = 0  # rows dropped with no spill — today's eviction loss
        self.probes = 0
        self.probe_hits = 0
        self.expired_dropped = 0
        self.conflicts_merged = 0

    # --------------------------------------------------------- bloom filter

    def _bloom_hashes(self, fps: np.ndarray):
        x = np.asarray(fps, dtype=np.int64).view(np.uint64)
        with np.errstate(over="ignore"):
            h1 = (x * np.uint64(0x9E3779B97F4A7C15)) >> np.uint64(17)
            h2 = (x * np.uint64(0xC2B2AE3D27D4EB4F)) >> np.uint64(17)
        return h1 & self._bloom_mask, h2 & self._bloom_mask

    def _bloom_add(self, fps: np.ndarray) -> None:
        for h in self._bloom_hashes(fps):
            np.bitwise_or.at(
                self._bloom, (h >> np.uint64(6)).astype(np.int64),
                np.uint64(1) << (h & np.uint64(63)),
            )

    def _bloom_maybe(self, fps: np.ndarray) -> np.ndarray:
        h1, h2 = self._bloom_hashes(fps)
        one = np.uint64(1)
        g = lambda h: (
            self._bloom[(h >> np.uint64(6)).astype(np.int64)]
            >> (h & np.uint64(63))
        ) & one
        return (g(h1) & g(h2)).astype(bool)

    # ------------------------------------------------------------- geometry
    @property
    def ram_rows(self) -> int:
        return len(self._rows)

    @property
    def nominal_bytes(self) -> int:
        """RAM set cost at ROW_BYTES per row — the bounded figure."""
        return len(self._rows) * ROW_BYTES

    @property
    def tracked_rows(self) -> int:
        """Rows reachable for fault-back: RAM ∪ spill-only."""
        n = len(self._rows)
        if self.spill is not None:
            n += sum(
                1 for fp in self.spill.index if fp not in self._rows
            )
        return n

    # --------------------------------------------------------------- demote
    def offer(self, fps: np.ndarray, rows: np.ndarray, now_ms: int,
              reason: str = "evict") -> int:
        """Accept a demote batch of canonical rows. Expired rows are
        dropped (dead state must not resurrect); conflicts merge
        conservatively; the RAM byte bound is enforced after insert
        (shed-to-spill, else shed-and-count). Returns rows accepted."""
        n = int(fps.shape[0])
        if n == 0:
            return 0
        rows = np.ascontiguousarray(rows, dtype=np.int32)
        exp = _join(rows, EXP_LO, EXP_HI)
        live = exp >= now_ms
        accepted = 0
        with self._lock:
            self.expired_dropped += int((~live).sum())
            for i in np.nonzero(live)[0]:
                fp = int(fps[i])
                if fp == 0:
                    continue
                row = rows[i]
                cur = self._rows.get(fp)
                if cur is not None:
                    row = merge_canonical_rows(row[None], cur[None])[0]
                    self.conflicts_merged += 1
                self._rows[fp] = row
                self._rows.move_to_end(fp)
                self._unspilled.add(fp)
                accepted += 1
            if accepted:
                self._bloom_add(fps[live])
            if reason == "idle":
                self.demoted_idle += accepted
            elif reason == "return":
                self.promote_returned += accepted
            else:
                self.demoted_evict += accepted
            self._enforce_bound(now_ms)
        return accepted

    def _enforce_bound(self, now_ms: int) -> None:
        """Pop oldest RAM entries past the byte budget (lock held). With a
        spill the popped rows are appended there first (lossless); without
        one they are shed — counted state loss, identical to the
        pre-tiering eviction behavior."""
        over = len(self._rows) - self.max_bytes // ROW_BYTES
        if over <= 0:
            return
        popped_fps = np.empty(over, dtype=np.int64)
        popped_rows = np.empty((over, F), dtype=np.int32)
        for j in range(over):
            fp, row = self._rows.popitem(last=False)
            popped_fps[j] = fp
            popped_rows[j] = row
            self._unspilled.discard(fp)
        if self.spill is not None:
            self.spill.append(popped_fps, popped_rows, now_ms)
        else:
            self.shed += over

    def flush(self, now_ms: int) -> int:
        """Write RAM entries newer than the spill file out to it (sweep
        cadence / shutdown). No-op without a spill. Returns rows written."""
        if self.spill is None:
            return 0
        with self._lock:
            fps = [fp for fp in self._unspilled if fp in self._rows]
            if not fps:
                self._unspilled.clear()
                return 0
            arr_fps = np.asarray(fps, dtype=np.int64)
            arr_rows = np.stack([self._rows[fp] for fp in fps])
            self.spill.append(arr_fps, arr_rows, now_ms)
            self._unspilled.clear()
            self.spill.maybe_compact(now_ms)
            return len(fps)

    def load(self) -> int:
        """Boot: index an existing spill file (rows stay on disk; they
        fault back lazily). Returns indexed rows."""
        if self.spill is None:
            return 0
        with self._lock:
            n = self.spill.load()
            if n:
                self._bloom_add(
                    np.fromiter(self.spill.index.keys(), dtype=np.int64,
                                count=len(self.spill.index))
                )
            return n

    # ------------------------------------------------------------ fault-back
    def take(self, fps: np.ndarray, now_ms: int):
        """Exact-match probe-and-REMOVE for a batch of fingerprints:
        (found_fps (m,) i64, rows (m, 16) i32). Misses cost one dict
        lookup each (two with a spill) — the off-hot-path contract.
        Expired entries are dropped, not promoted."""
        n = int(fps.shape[0])
        if n == 0:
            return np.empty(0, dtype=np.int64), np.empty((0, F), np.int32)
        # vectorized Bloom reject: a batch with no shadowed key pays a
        # few numpy ops, never a per-fp dict walk (the hot-set contract)
        maybe = self._bloom_maybe(fps)
        if not maybe.any():
            with self._lock:
                self.probes += n
            return np.empty(0, dtype=np.int64), np.empty((0, F), np.int32)
        out_fps = []
        out_rows = []
        fp_list = np.asarray(fps, dtype=np.int64)[maybe].tolist()
        with self._lock:
            self.probes += n
            seen = set()
            for fp in fp_list:
                if fp == 0 or fp in seen:
                    continue
                seen.add(fp)
                row = self._rows.pop(fp, None)
                if row is None and self.spill is not None:
                    row = self.spill.read(fp)
                if row is None:
                    continue
                self._unspilled.discard(fp)
                if self.spill is not None:
                    self.spill.discard(fp)
                exp = (int(row[EXP_HI]) << 32) | (int(row[EXP_LO]) & 0xFFFFFFFF)
                if exp < now_ms:
                    self.expired_dropped += 1
                    continue
                out_fps.append(fp)
                out_rows.append(row)
            self.probe_hits += len(out_fps)
            # taken rows ARE promoted by contract: the caller installs
            # them through the conservative merge before its dispatch
            self.promoted += len(out_fps)
        if not out_fps:
            return np.empty(0, dtype=np.int64), np.empty((0, F), np.int32)
        return (
            np.asarray(out_fps, dtype=np.int64),
            np.stack(out_rows).astype(np.int32),
        )

    def contains(self, fps: np.ndarray) -> np.ndarray:
        """Non-destructive membership mask (RAM ∪ spill index) — the
        miss re-check's cheap gate (ops/engine._shadow_rehydrate)."""
        n = int(fps.shape[0])
        out = np.zeros(n, dtype=bool)
        fp_list = np.asarray(fps, dtype=np.int64).tolist()
        with self._lock:
            rows = self._rows
            idx = self.spill.index if self.spill is not None else None
            for i, fp in enumerate(fp_list):
                if fp == 0:
                    continue
                out[i] = fp in rows or (idx is not None and fp in idx)
        return out

    # ---------------------------------------------------------------- status
    def stats(self) -> dict:
        with self._lock:
            out = {
                "ram_rows": len(self._rows),
                "nominal_bytes": len(self._rows) * ROW_BYTES,
                "max_bytes": self.max_bytes,
                "demoted_evict": self.demoted_evict,
                "demoted_idle": self.demoted_idle,
                "promoted": self.promoted,
                "promote_returned": self.promote_returned,
                "shed": self.shed,
                "probes": self.probes,
                "probe_hits": self.probe_hits,
                "expired_dropped": self.expired_dropped,
                "conflicts_merged": self.conflicts_merged,
            }
            if self.spill is not None:
                out["spill"] = {
                    "path": self.spill.path,
                    "indexed_rows": len(self.spill.index),
                    "file_bytes": self.spill.size_bytes(),
                    "read_errors": self.spill.read_errors,
                }
            return out
