"""Daemon configuration: GUBER_* environment variables + optional config file.

Mirrors the reference's env-driven config system (reference config.go:302-547):
every knob is a `GUBER_*` env var, optionally seeded from a `key=value` file
(reference config.go:703-726 loads the file INTO the environment first, so env
set by the file and real env resolve through one path). Defaults match the
reference's (reference config.go:137-158) where a counterpart exists.
"""

from __future__ import annotations

import enum
import os
import random
import re
import socket
import string
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class DegradationPolicy(str, enum.Enum):
    """What a non-owner answers when the owner is unreachable (breaker open
    or forward retries exhausted) — see docs/robustness.md.

    ERROR: today's reference-compatible behavior — the item carries an
    "Error while fetching rate limit from peer: ..." response.
    LOCAL: best-effort local check against this daemon's own store; the
    response is real (non-error) but marked via metadata["degraded"]="true"
    so clients know it may not reflect the owner's authoritative state.
    """

    ERROR = "error"
    LOCAL = "local"


class ConfigError(ValueError):
    """Invalid configuration — message says which key and why (the reference
    returns actionable errors from SetupDaemonConfig, config.go:359-363)."""


def load_config_file(path: str, env: Optional[Dict[str, str]] = None) -> None:
    """Parse a `key=value` file and set the pairs into the environment
    (reference config.go:703-726: `fromEnvFile`). Lines starting with # and
    blank lines are ignored; existing env vars are NOT overridden (real env
    wins, same as the reference)."""
    env_map = os.environ if env is None else env
    with open(path) as f:
        for ln, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if "=" not in line:
                raise ConfigError(f"{path}:{ln}: expected key=value, got {line!r}")
            k, _, v = line.partition("=")
            k, v = k.strip(), v.strip()
            if k and k not in env_map:
                env_map[k] = v


def _get(env, key: str, default: str = "") -> str:
    return env.get(key, default)


def _get_int(env, key: str, default: int) -> int:
    raw = env.get(key)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        raise ConfigError(f"{key}: expected integer, got {raw!r}")


def _get_float_ms(env, key: str, default_ms: float) -> float:
    """Duration in milliseconds (reference uses Go durations; we accept a
    plain number = ms, or with a s/ms/us suffix)."""
    raw = env.get(key)
    if raw is None or raw == "":
        return default_ms
    m = re.fullmatch(r"\s*([0-9.]+)\s*(us|ms|s|m)?\s*", raw)
    if not m:
        raise ConfigError(f"{key}: expected duration, got {raw!r}")
    val = float(m.group(1))
    unit = m.group(2) or "ms"
    return val * {"us": 1e-3, "ms": 1.0, "s": 1e3, "m": 60e3}[unit]


def _get_fraction(env, key: str, default: float) -> float:
    raw = env.get(key)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        raise ConfigError(f"{key}: expected a number, got {raw!r}")


def _get_bool(env, key: str, default: bool = False) -> bool:
    raw = env.get(key, "")
    if raw == "":
        return default
    return raw.lower() in ("1", "true", "yes", "on")


def instance_id(env=None) -> str:
    """Stable-ish instance id: env override or random tag (reference
    config.go:746-783 also tries the docker cgroup; not meaningful here)."""
    env = os.environ if env is None else env
    iid = env.get("GUBER_INSTANCE_ID", "")
    if iid:
        return iid
    return "".join(random.choices(string.hexdigits.lower(), k=12))


@dataclass
class BehaviorConfig:
    """Batching / GLOBAL cadence knobs (reference config.go:49-70; defaults
    config.go:137-146)."""

    batch_timeout_ms: float = 500.0  # forwarding RPC timeout (BatchTimeout 500ms)
    batch_wait_ms: float = 0.5  # coalescing window (BatchWait 500µs)
    batch_limit: int = 1000  # max items per forwarded batch (BatchLimit)
    # per-DEVICE-dispatch row cap for the front-door batcher: oversized
    # flushes split into whole sub-batches (one oversized enqueue dispatches
    # alone). Bigger caps amortize kernel fixed costs, smaller caps bound
    # per-dispatch latency; no reference analog (device batches replace the
    # worker channels)
    coalesce_limit: int = 16384
    # concurrent device dispatches the front door keeps in flight (issue of
    # N+1 overlaps compute of N and fetch of N-1); 1 = the serial door
    pipeline_inflight: int = 4
    # --- serving plane (docs/latency.md "Serving plane") ------------------
    # parser/responder flush workers on the front door: each forms a chunk,
    # dispatches it, and slices its coalesced response back in parallel
    # with the others; 0 = one per pipeline_inflight slot
    front_workers: int = 0
    # adaptive batch window: close the coalesce window on accumulated
    # rows/bytes (or an idle engine) instead of always sleeping
    # batch_wait_ms; the wall clock remains the ceiling. false restores the
    # fixed-tick window
    adaptive_batch: bool = True
    # rows that close the adaptive window early (0 = coalesce_limit)
    batch_close_rows: int = 0
    # accumulated request wire bytes that close the adaptive window early
    batch_close_bytes: int = 1 << 20
    # bounded front-door ring: enqueues past this many pending rows wait
    # for drain progress (backpressure) instead of growing the queue
    # without limit (0 = 8 × coalesce_limit)
    batch_queue_rows: int = 0
    # --- overload plane (docs/robustness.md "Overload & QoS") -------------
    # per-item enqueue deadline in ms: arms the front-door overload plane —
    # queue waits are bounded by min(this, the caller's remaining gRPC
    # deadline), a full ring or an infeasible wait sheds lowest-tier-first
    # with a fast per-item overload error instead of blocking, and the
    # dispatch order becomes tier-major. 0 (default) = disarmed: the legacy
    # unbounded-backpressure door
    overload_deadline_ms: float = 0.0
    # derive the enqueue deadline from MEASURED dispatch speed instead of a
    # hard-coded guess: GUBER_OVERLOAD_DEADLINE_MS=auto arms the overload
    # plane with deadline = max(overload_retry_ms,
    # OVERLOAD_AUTO_DEADLINE_MULT × EWMA of stage_duration{stage="issue"}),
    # re-evaluated per enqueue — one knob that tracks real device speed on
    # both backends (docs/robustness.md "Overload & QoS")
    overload_deadline_auto: bool = False
    # fair admission: one tenant (key-fingerprint bucket) may hold at most
    # this fraction of the bounded ring once the queue is ≥ half full;
    # excess rows from that tenant shed with reason="fairness"
    overload_tenant_share: float = 0.5
    # fingerprint buckets for tenant accounting (rounded up to a pow2)
    overload_tenant_buckets: int = 64
    # reset_time hint stamped on shed responses (client retry backoff)
    overload_retry_ms: int = 25
    # device-resident request ring (service/ring.py; docs/latency.md
    # "Dispatch budget"): all-wire flushes are staged into a fixed ring of
    # compact wire-grid slots and consumed in ticket order by a persistent
    # serving loop — on TPU this kills the per-flush dispatch round-trip;
    # the CPU build runs a functional emulation of the same protocol. Off
    # (default) = the direct per-flush dispatch every PR before this one
    # shipped.
    ring_enable: bool = False
    # ring depth in slots: submits past this many published-but-unconsumed
    # batches wait (bounded backpressure, no drops, FIFO order)
    ring_slots: int = 64
    # consume tier (docs/latency.md "Launch budget"): "auto" resolves per
    # backend (fused on TPU, host on CPU); "host" = one XLA launch per
    # published slot; "fused" = ONE jitted while_loop launch drains up to
    # ring_drain_k published slots (ops/ring_drain.py); "persistent" =
    # staged Pallas fence-claim tier (runs the fused drain with a watchdog
    # until the device run validates the resident loop)
    ring_issue: str = "auto"
    # max published slots one fused drain launch retires (the launch-
    # amortization factor; clamped to ring_slots)
    ring_drain_k: int = 8
    # fixed device slot width in rows for the fused tiers; chunks wider
    # than this ride the per-slot host path. 0 = auto-size to the first
    # fused chunk's padded dispatch size
    ring_slot_width: int = 0
    # warm-up breadth: "" compiles only the 1-row shapes (fast spawn);
    # "pow2" additionally compiles every pow2 coalesce shape up to
    # coalesce_limit (token graph), "pow2-mixed" both math graphs — without
    # this, the first request that produces a new coalesced batch geometry
    # pays a multi-second XLA compile on the request path
    warm_shapes: str = ""

    global_timeout_ms: float = 500.0  # GLOBAL rpc timeout (GlobalTimeout)
    global_sync_wait_ms: float = 100.0  # hit-sync cadence (GlobalSyncWait)
    global_batch_limit: int = 1000  # GlobalBatchLimit
    global_peer_concurrency: int = 100  # GlobalPeerRequestsConcurrency
    # inter-slice GLOBAL hit batches ride the compact wire codec
    # (SyncGlobalsWire RPC, service/wire.sync_wire_pb — 20 B/entry of
    # numeric config + one string blob instead of nested RateLimitReq
    # messages) when the batch is representable; off forces the classic
    # GetPeerRateLimits proto path everywhere (the parity oracle)
    global_wire_sync: bool = True

    force_global: bool = False  # reference config.go:65-66

    # --- peer fault tolerance (docs/robustness.md) -------------------------
    # consecutive RPC failures toward one peer that trip its breaker OPEN
    peer_breaker_errors: int = 5
    # jittered-exponential open-state cooldown: first trip cools for
    # ~base/2..base, doubling per consecutive trip up to the cap
    peer_breaker_backoff_base_ms: float = 500.0
    peer_breaker_backoff_cap_ms: float = 30_000.0
    # concurrent HALF_OPEN probe RPCs allowed while testing a tripped peer
    peer_breaker_probes: int = 1
    # owner-unreachable answer policy: "error" | "local" (DegradationPolicy)
    degradation_policy: str = DegradationPolicy.ERROR.value
    # failed GLOBAL hit batches re-merge into the pending queue this many
    # times before the hits are dropped (0 restores the reference's
    # drop-on-error, global.go:190-195)
    global_requeue_retries: int = 3
    # total pending-hit keys the requeue path may grow the queue to; beyond
    # it, failed batches drop (bounds memory during long partitions)
    global_queue_cap: int = 10_000

    # --- multi-region replication (docs/robustness.md "Multi-region
    # active-active") ---------------------------------------------------
    # cross-region sync cadence; 0 inherits global_sync_wait_ms
    region_sync_wait_ms: float = 0.0
    # per-RPC deadline for region replication sends; 0 derives
    # max(global_timeout_ms, 2000) — deliberately GENEROUS: the plane is
    # asynchronous (nothing user-facing waits on it), and a deadline that
    # cancels a receiver mid-apply turns a slow round into a duplicate
    # delivery on retry (under-granting, but needless)
    region_timeout_ms: float = 0.0
    # failed cross-region delta batches re-merge into the pending queue
    # this many times before dropping (the over-admission bound after a
    # partition longer than retries × sync_wait grows by the dropped
    # deltas — size this to the longest partition you want to ride out)
    region_requeue_retries: int = 3
    # pending-delta keys PER DESTINATION REGION the requeue path may grow
    # to; beyond it, failed batches drop (bounds memory during partitions)
    region_queue_cap: int = 10_000
    # encodable delta batches ride the compact SyncRegionsWire codec and
    # reconcile through the conservative merge kernel; off forces the
    # classic GetPeerRateLimits proto path everywhere (legacy DRAIN
    # semantics — the parity oracle and the pre-upgrade behavior)
    region_wire_sync: bool = True

    # --- topology-change handoff (docs/robustness.md "Topology change &
    # drain") -----------------------------------------------------------
    # move owned live rows to their new ring owners on set_peers rebalance
    # and on graceful drain (off restores the reference's state-stranding
    # behavior: moved keys answer fresh at the new owner until TTL)
    handoff_enabled: bool = True
    # wall-clock budget for one handoff round (rebalance or drain); chunks
    # still unacked at the deadline stay in the table (drain snapshots them)
    handoff_deadline_ms: float = 5_000.0
    # rows per TransferState chunk (4096 rows ≈ 300 KiB on the wire, under
    # the 1 MiB peer-channel receive cap with headroom)
    handoff_chunk_rows: int = 4096


@dataclass
class DaemonConfig:
    """Everything a daemon needs to boot (reference DaemonConfig,
    config.go:197-284)."""

    grpc_address: str = "localhost:1051"
    http_address: str = "localhost:1050"
    # optional extra HTTP listener serving /metrics + health ONLY, and (when
    # TLS is on) WITHOUT requiring client certificates — so probes and
    # scrapers work in mTLS clusters (reference HTTPStatusListenAddress,
    # daemon.go:324-352)
    status_http_address: str = ""
    advertise_address: str = ""  # defaults to grpc_address
    data_center: str = ""
    instance_id: str = ""

    # per-RPC item cap on the V1 wire surface (reference hard-codes 1000,
    # gubernator.go:41-42 — the wire-compatible default; raising it lets a
    # client ship engine-sized batches in one RPC instead of paying proto
    # framing per 1000 rows). The rejection string keeps the reference's
    # exact wording either way.
    max_batch_size: int = 1000
    # total limit levels (the request itself + its cascade entries) one
    # cascaded check may carry (GUBER_CASCADE_MAX_LEVELS;
    # docs/algorithms.md "Cascades"). Cascades up to 4 levels ride the
    # compact wire; deeper ones fall back to the full-width grids.
    cascade_max_levels: int = 8
    cache_size: int = 50_000  # CacheSize (config.go:151) → table capacity
    # auto-grow: double the device table when live keys pass 60% of capacity
    # (0 = fixed size like the reference's LRU; >0 = growth ceiling in slots)
    cache_max_size: int = 0
    engine: str = "local"  # "local" (one device) | "sharded" (mesh)
    # sharded request routing: "auto" (device on TPU backends, host
    # elsewhere — parallel/sharded.default_shard_route) | "host" (ownership
    # grid built host-side) | "device" (arrival-order rows, on-mesh
    # all_to_all exchange — the multi-host-scale path, parallel/a2a.py)
    shard_route: str = "auto"
    # sharded duplicate-key handling: "auto" (device on TPU backends) |
    # "host" (pass-planner group-by, exact sequential same-key semantics) |
    # "device" (in-trace aggregation — hits summed, RESET OR-ed, newest
    # config wins; O(1) host planning, kernel2.dedup_packed_cols)
    shard_dedup: str = "auto"
    # ownership-exchange schedule for route="device" dispatches
    # (parallel/ring.py): "auto" (ring on TPU backends, collective
    # elsewhere) | "ring" (hand-rolled per-hop remote-DMA/ppermute
    # schedule, double-buffered hops) | "collective" (one monolithic
    # lax.all_to_all per direction — the parity oracle). Byte-identical
    # results either way; GUBER_A2A_IMPL.
    a2a_impl: str = "auto"
    # fold the mesh's devices into this many (simulated) host rows — the
    # 2-D (host, device) topology used by multi-host tests/CI on one
    # machine (GUBER_MESH_HOSTS; 0 = from the runtime: process_count on a
    # real pod slice, 1 host otherwise). Read by parallel/mesh.make_mesh
    # through the environment, surfaced here for validation + visibility.
    mesh_hosts: int = 0
    # table-walk kernel for decide dispatches (ops/plan.default_probe_kernel;
    # GUBER_PROBE_KERNEL): "auto" (= xla until the device record flips it) |
    # "xla" (row gather + sweep/sparse write) | "pallas" (the fused
    # double-buffered probe→decide→write megakernel, ops/pallas_probe.py —
    # interpret-mode on CPU backends)
    probe_kernel: str = "auto"
    # table-walk kernel for the NON-decide walks — GLOBAL installs,
    # region/handoff merges, tiering promotes (ops/plan.default_walk_kernel;
    # GUBER_WALK_KERNEL): "auto" (= xla until the device bench's fused-vs-
    # two-pass wall flips it) | "xla" (two-pass gather + sweep/sparse
    # write) | "pallas" (the fused probe→install/merge→write walk,
    # ops/pallas_probe.walk2_pallas_impl). Independent of probe_kernel so
    # the latency-critical decide path and the throughput walks can flip
    # separately.
    walk_kernel: str = "auto"
    workers: int = 0  # 0 = auto; host-side executor width

    behaviors: BehaviorConfig = field(default_factory=BehaviorConfig)

    # peer discovery (reference config.go:359-363: {none, dns, k8s, etcd,
    # member-list})
    peer_discovery_type: str = "none"
    dns_fqdn: str = ""
    dns_poll_ms: float = 5_000.0

    # etcd discovery (reference etcd.go; GUBER_ETCD_*)
    etcd_endpoint: str = ""  # http(s)://host:port of the v3 JSON gateway
    etcd_key_prefix: str = "/gubernator/peers/"
    etcd_lease_ttl_s: int = 30
    etcd_poll_ms: float = 2_000.0

    # member-list gossip discovery (reference memberlist.go; GUBER_MEMBERLIST_*)
    memberlist_address: str = ""  # gossip bind address (host:port)
    memberlist_advertise_address: str = ""
    memberlist_known_nodes: str = ""  # comma-separated seed gossip addresses
    memberlist_gossip_interval_ms: float = 500.0
    # comma-separated base64 AES keys (16/24/32 bytes each); first encrypts
    # outbound gossip, all decrypt inbound (rotation). Empty = plaintext
    # (reference SecretKey/keyring, memberlist.go:149-167)
    memberlist_secret_keys: str = ""

    # kubernetes discovery (reference kubernetes.go; GUBER_K8S_*)
    k8s_namespace: str = "default"
    k8s_pod_ip: str = ""
    k8s_pod_port: str = ""
    k8s_selector: str = ""  # endpoints/pods label selector
    k8s_mechanism: str = "endpointslices"  # or "pods"
    k8s_api_url: str = ""  # override for tests; default in-cluster
    k8s_poll_ms: float = 5_000.0

    # TLS (reference tls.go); empty = plaintext
    tls_ca_file: str = ""
    tls_cert_file: str = ""
    tls_key_file: str = ""
    tls_auto: bool = False  # auto self-signed CA + cert (AutoTLS)
    tls_client_auth: str = ""  # "", "require", "verify"

    # checkpoint/resume (SURVEY §5.4): snapshot file for the Loader hook
    checkpoint_path: str = ""
    # incremental checkpointing (docs/durability.md): background cadence of
    # the dirty-block delta plane. 0 (default) keeps the seed behavior —
    # restore on boot, one full snapshot on graceful shutdown; > 0 appends
    # CRC-framed delta frames of blocks dirtied since the last epoch to the
    # delta log every interval, bounding kill -9 loss to one interval of
    # writes. Requires checkpoint_path.
    checkpoint_interval_ms: float = 0.0
    # compact the delta log into a fresh base snapshot after this many
    # frames (bounds replay length and log growth)
    checkpoint_compact_frames: int = 64
    # delta-log file; default <checkpoint_path>.delta
    checkpoint_delta_path: str = ""

    # --- hot-set tiering (gubernator_tpu/tier/; docs/tiering.md) --------
    # demote evicted/idle rows to a host-RAM shadow table and fault them
    # back through the conservative merge — capacity scales with TRACKED
    # keys while HBM holds the hot set. Off (default) = the pre-tiering
    # behavior: live evictions silently discard state.
    tier_enabled: bool = False
    # rows idle (no update) past this horizon demote out of HBM on the
    # background sweep (telemetry cadence)
    tier_idle_ms: float = 60_000.0
    # RAM budget for the shadow's resident rows (64 B canonical rows);
    # over-budget rows shed to the spill file when configured, else drop
    # (counted — exactly today's eviction loss)
    tier_shadow_bytes: int = 1 << 28
    # optional spill file (DeltaLog frame format): makes demotions durable
    # across restarts and lets the shadow overflow RAM losslessly
    tier_spill_path: str = ""

    # background device-table telemetry cadence (ops/telemetry.py; the scan
    # overlaps serving and feeds gubernator_tpu_table_* + /v1/debug/table);
    # 0 disables the loop (the debug endpoint then scans on demand)
    telemetry_interval_ms: float = 5_000.0
    # serve the /v1/debug/{table,pipeline,peers,global} JSON snapshots on
    # the HTTP listeners (docs/observability.md); off hides the plane on
    # deployments that treat internals as sensitive
    debug_endpoints: bool = True

    # --- edge quota leases (service/lease_manager.py; docs/leases.md) ----
    # ceiling on Σ outstanding leased tokens per key, as a fraction of the
    # key's limit — sizes the documented over-admission bound (a lease is
    # admission delegated to the edge; what's out there is what a
    # partitioned/crashed client can still admit)
    lease_max_fraction: float = 0.5
    # lease TTL clamp: requested TTLs resolve into [min, max]; shorter TTLs
    # reclaim crashed clients' tokens faster at more renew RPCs
    lease_min_ttl_ms: float = 100.0
    lease_max_ttl_ms: float = 30_000.0
    # tier-aware lease sizing (docs/robustness.md "Overload & QoS"): scale
    # lease grants by the requester's priority tier — tier 3 keeps the full
    # computed grant, each tier below loses 25% (tier 0 gets 25%), and under
    # key pressure the response carries a shrink_to hint sized the same
    # way so edges release quota before their TTL. Off (default) preserves
    # tier-blind grants
    lease_priority_scaling: bool = False
    # absolute per-key cap on Σ outstanding leased tokens (0 = only the
    # fraction cap applies) — for huge limits where even a small fraction
    # delegates more than an edge fleet should hold
    lease_max_outstanding: int = 0

    # accepted client created_at skew (ms); requests outside now±tolerance are
    # clamped and counted (gubernator_created_at_clamped_count)
    created_at_tolerance_ms: float = 5 * 60 * 1000.0

    # delay before graceful termination starts, giving load balancers time
    # to de-register (reference config.go:215-217, daemon.go:389-391)
    graceful_termination_delay_s: float = 0.0

    log_level: str = "info"
    # optional runtime metric collectors, comma-separated: "os" (process
    # RSS/fds/CPU) and/or "python" (GC + platform; "golang" alias) —
    # reference flags.go:19-57 FlagOSMetrics/FlagGolangMetrics
    metric_flags: str = ""
    # bound gRPC connection lifetime so load balancers re-balance
    # (reference GRPCMaxConnectionAgeSeconds, config.go:351; 0 = unbounded)
    grpc_max_conn_age_s: float = 0.0

    def memberlist_keyring(self):
        """Decoded AES keyring from GUBER_MEMBERLIST_SECRET_KEYS — the ONE
        strict parser (validate() calls this, so embedders skipping
        validate() get the same rejection of malformed keys)."""
        import base64
        import binascii

        out = []
        for part in self.memberlist_secret_keys.split(","):
            part = part.strip()
            if not part:
                continue
            try:
                key = base64.b64decode(part, validate=True)
            except (ValueError, binascii.Error):
                raise ConfigError(
                    "GUBER_MEMBERLIST_SECRET_KEYS: entries must be base64"
                )
            if len(key) not in (16, 24, 32):
                raise ConfigError(
                    "GUBER_MEMBERLIST_SECRET_KEYS: keys must decode to "
                    f"16, 24 or 32 bytes (got {len(key)})"
                )
            out.append(key)
        return out

    def __post_init__(self):
        if not self.advertise_address:
            self.advertise_address = self.grpc_address
        if not self.instance_id:
            self.instance_id = instance_id()

    def validate(self) -> None:
        if self.peer_discovery_type not in (
            "none", "dns", "etcd", "member-list", "k8s",
        ):
            raise ConfigError(
                f"GUBER_PEER_DISCOVERY_TYPE: unknown type "
                f"{self.peer_discovery_type!r}; must be one of: none, dns, "
                "etcd, member-list, k8s"
            )
        if self.peer_discovery_type == "dns" and not self.dns_fqdn:
            raise ConfigError("GUBER_DNS_FQDN is required when GUBER_PEER_DISCOVERY_TYPE=dns")
        if self.peer_discovery_type == "etcd" and not self.etcd_endpoint:
            raise ConfigError(
                "GUBER_ETCD_ENDPOINT is required when GUBER_PEER_DISCOVERY_TYPE=etcd"
            )
        if self.peer_discovery_type == "member-list" and not self.memberlist_address:
            raise ConfigError(
                "GUBER_MEMBERLIST_ADDRESS is required when "
                "GUBER_PEER_DISCOVERY_TYPE=member-list"
            )
        if self.memberlist_secret_keys:
            self.memberlist_keyring()  # the strict parser raises ConfigError
            try:
                from cryptography.hazmat.primitives.ciphers.aead import (  # noqa: F401
                    AESGCM,
                )
            except ImportError:
                raise ConfigError(
                    "GUBER_MEMBERLIST_SECRET_KEYS requires the "
                    "'cryptography' package"
                )
        if self.k8s_mechanism not in ("endpointslices", "pods"):
            raise ConfigError(
                "GUBER_K8S_WATCH_MECHANISM must be endpointslices or pods"
            )
        if self.peer_discovery_type == "k8s" and not self.k8s_pod_ip:
            # self-recognition (not-ready-self inclusion, owner marking) keys
            # on the pod IP; an empty value silently breaks it
            raise ConfigError(
                "GUBER_K8S_POD_IP is required when GUBER_PEER_DISCOVERY_TYPE="
                "k8s (set it from the downward API: status.podIP)"
            )
        if self.peer_discovery_type == "k8s" and not self.k8s_selector:
            # without a selector the pool would list EVERY workload in the
            # namespace and forward rate-limit RPCs to unrelated pods
            raise ConfigError(
                "GUBER_K8S_ENDPOINTS_SELECTOR is required when "
                "GUBER_PEER_DISCOVERY_TYPE=k8s (e.g. "
                "kubernetes.io/service-name=gubernator for endpointslices, "
                "app=gubernator for pods)"
            )
        if self.engine not in ("local", "sharded"):
            raise ConfigError(f"GUBER_ENGINE: must be local or sharded, got {self.engine!r}")
        if self.shard_route not in ("auto", "host", "device"):
            raise ConfigError(
                f"GUBER_SHARD_ROUTE: must be auto, host or device, got "
                f"{self.shard_route!r}"
            )
        if self.shard_dedup not in ("auto", "host", "device"):
            raise ConfigError(
                f"GUBER_SHARD_DEDUP: must be auto, host or device, got "
                f"{self.shard_dedup!r}"
            )
        if self.a2a_impl not in ("auto", "ring", "collective"):
            raise ConfigError(
                f"GUBER_A2A_IMPL: must be auto, ring or collective, got "
                f"{self.a2a_impl!r}"
            )
        if self.mesh_hosts < 0:
            raise ConfigError(
                "GUBER_MESH_HOSTS must be >= 0 (0 = topology from the runtime)"
            )
        if self.probe_kernel not in ("auto", "xla", "pallas"):
            raise ConfigError(
                f"GUBER_PROBE_KERNEL: must be auto, xla or pallas, got "
                f"{self.probe_kernel!r}"
            )
        if self.walk_kernel not in ("auto", "xla", "pallas"):
            raise ConfigError(
                f"GUBER_WALK_KERNEL: must be auto, xla or pallas, got "
                f"{self.walk_kernel!r}"
            )
        if self.cache_size <= 0:
            raise ConfigError("GUBER_CACHE_SIZE must be positive")
        if self.behaviors.batch_limit <= 0 or self.behaviors.batch_limit > 1000:
            # the reference hard-caps batches at 1000 (gubernator.go:41-42)
            raise ConfigError("GUBER_BATCH_LIMIT must be in (0, 1000]")
        if self.behaviors.pipeline_inflight <= 0:
            raise ConfigError("GUBER_PIPELINE_INFLIGHT must be >= 1")
        if self.behaviors.coalesce_limit <= 0:
            raise ConfigError("GUBER_BATCH_COALESCE_LIMIT must be positive")
        if self.max_batch_size <= 0:
            raise ConfigError("GUBER_MAX_BATCH_SIZE must be positive")
        if not (2 <= self.cascade_max_levels <= 256):
            raise ConfigError(
                "GUBER_CASCADE_MAX_LEVELS must be in [2, 256] (the level "
                "field is 8 bits)"
            )
        if self.behaviors.front_workers < 0:
            raise ConfigError("GUBER_FRONT_WORKERS must be >= 0 (0 = auto)")
        if self.behaviors.batch_close_rows < 0:
            raise ConfigError("GUBER_BATCH_CLOSE_ROWS must be >= 0 (0 = auto)")
        if self.behaviors.batch_close_bytes <= 0:
            raise ConfigError("GUBER_BATCH_CLOSE_BYTES must be positive")
        if self.behaviors.batch_queue_rows < 0:
            raise ConfigError("GUBER_BATCH_QUEUE_ROWS must be >= 0 (0 = auto)")
        if self.behaviors.overload_deadline_ms < 0:
            raise ConfigError(
                "GUBER_OVERLOAD_DEADLINE_MS must be >= 0 (0 = overload "
                "plane disarmed)"
            )
        if not (0.0 < self.behaviors.overload_tenant_share <= 1.0):
            raise ConfigError(
                "GUBER_OVERLOAD_TENANT_SHARE must be in (0, 1] (the ring "
                "fraction one tenant bucket may hold)"
            )
        if self.behaviors.overload_tenant_buckets <= 0:
            raise ConfigError(
                "GUBER_OVERLOAD_TENANT_BUCKETS must be positive"
            )
        if self.behaviors.overload_retry_ms <= 0:
            raise ConfigError("GUBER_OVERLOAD_RETRY_MS must be positive")
        if self.behaviors.ring_slots < 2:
            raise ConfigError(
                "GUBER_RING_SLOTS must be >= 2 (a 1-slot ring serializes "
                "staging against consumption — no overlap to buy)"
            )
        if self.behaviors.ring_issue not in (
            "auto", "host", "fused", "persistent"
        ):
            raise ConfigError(
                "GUBER_RING_ISSUE must be auto, host, fused or persistent, "
                f"got {self.behaviors.ring_issue!r}"
            )
        if self.behaviors.ring_drain_k < 1:
            raise ConfigError(
                "GUBER_RING_DRAIN_K must be >= 1 (published slots one "
                "fused drain launch may retire)"
            )
        if self.behaviors.ring_slot_width < 0:
            raise ConfigError(
                "GUBER_RING_SLOT_WIDTH must be >= 0 (0 = auto-size to the "
                "first fused chunk)"
            )
        if self.behaviors.peer_breaker_errors <= 0:
            raise ConfigError("GUBER_PEER_BREAKER_ERRORS must be >= 1")
        if self.behaviors.peer_breaker_probes <= 0:
            raise ConfigError("GUBER_PEER_BREAKER_PROBES must be >= 1")
        if self.behaviors.peer_breaker_backoff_base_ms <= 0:
            raise ConfigError("GUBER_PEER_BREAKER_BACKOFF_BASE must be positive")
        if (
            self.behaviors.peer_breaker_backoff_cap_ms
            < self.behaviors.peer_breaker_backoff_base_ms
        ):
            raise ConfigError(
                "GUBER_PEER_BREAKER_BACKOFF_CAP must be >= the backoff base"
            )
        if self.behaviors.degradation_policy not in (
            DegradationPolicy.ERROR.value,
            DegradationPolicy.LOCAL.value,
        ):
            raise ConfigError(
                "GUBER_DEGRADATION_POLICY must be error or local, got "
                f"{self.behaviors.degradation_policy!r}"
            )
        if self.behaviors.global_requeue_retries < 0:
            raise ConfigError("GUBER_GLOBAL_REQUEUE_RETRIES must be >= 0")
        if self.behaviors.global_queue_cap <= 0:
            raise ConfigError("GUBER_GLOBAL_QUEUE_CAP must be positive")
        if self.behaviors.region_sync_wait_ms < 0:
            raise ConfigError(
                "GUBER_REGION_SYNC_WAIT must be >= 0 (0 = inherit "
                "GUBER_GLOBAL_SYNC_WAIT)"
            )
        if self.behaviors.region_timeout_ms < 0:
            raise ConfigError(
                "GUBER_REGION_TIMEOUT must be >= 0 (0 = derived from "
                "GUBER_GLOBAL_TIMEOUT)"
            )
        if self.behaviors.region_requeue_retries < 0:
            raise ConfigError("GUBER_REGION_REQUEUE_RETRIES must be >= 0")
        if self.behaviors.region_queue_cap <= 0:
            raise ConfigError("GUBER_REGION_QUEUE_CAP must be positive")
        if self.behaviors.handoff_deadline_ms <= 0:
            raise ConfigError("GUBER_HANDOFF_DEADLINE must be positive")
        if self.behaviors.handoff_chunk_rows <= 0:
            raise ConfigError("GUBER_HANDOFF_CHUNK_ROWS must be positive")
        if not (0.0 < self.lease_max_fraction <= 1.0):
            raise ConfigError(
                "GUBER_LEASE_MAX_FRACTION must be in (0, 1] (the fraction "
                "of a limit that may be delegated to edge leases)"
            )
        if self.lease_min_ttl_ms <= 0:
            raise ConfigError("GUBER_LEASE_MIN_TTL_MS must be positive")
        if self.lease_max_ttl_ms < self.lease_min_ttl_ms:
            raise ConfigError(
                "GUBER_LEASE_MAX_TTL_MS must be >= GUBER_LEASE_MIN_TTL_MS"
            )
        if self.lease_max_outstanding < 0:
            raise ConfigError(
                "GUBER_LEASE_MAX_OUTSTANDING must be >= 0 (0 = fraction "
                "cap only)"
            )
        if self.tls_client_auth not in ("", "require", "verify"):
            raise ConfigError("GUBER_TLS_CLIENT_AUTH must be require or verify")
        if self.created_at_tolerance_ms <= 0:
            raise ConfigError("GUBER_CREATED_AT_TOLERANCE must be positive")
        if self.telemetry_interval_ms < 0:
            raise ConfigError(
                "GUBER_TELEMETRY_INTERVAL_MS must be >= 0 (0 = disabled)"
            )
        if self.checkpoint_interval_ms < 0:
            raise ConfigError(
                "GUBER_CHECKPOINT_INTERVAL_MS must be >= 0 (0 = shutdown-"
                "snapshot only)"
            )
        if self.checkpoint_interval_ms > 0 and not self.checkpoint_path:
            raise ConfigError(
                "GUBER_CHECKPOINT_INTERVAL_MS requires GUBER_CHECKPOINT_PATH "
                "(the delta log lives beside the base snapshot)"
            )
        if self.checkpoint_delta_path and not self.checkpoint_path:
            raise ConfigError(
                "GUBER_CHECKPOINT_DELTA_PATH requires GUBER_CHECKPOINT_PATH"
            )
        if self.checkpoint_compact_frames <= 0:
            raise ConfigError(
                "GUBER_CHECKPOINT_COMPACT_FRAMES must be >= 1"
            )
        if self.tier_idle_ms <= 0:
            raise ConfigError(
                "GUBER_TIER_IDLE_MS must be positive (the demote-on-idle "
                "horizon)"
            )
        if self.tier_shadow_bytes < 64:
            raise ConfigError(
                "GUBER_TIER_SHADOW_BYTES must hold at least one 64 B "
                "canonical row"
            )
        if self.tier_enabled and self.tier_spill_path and not os.path.isdir(
            os.path.dirname(os.path.abspath(self.tier_spill_path))
        ):
            # fail at boot, not at the first sweep: a typo'd spill dir
            # would silently downgrade durability to RAM-only
            raise ConfigError(
                "GUBER_TIER_SPILL_PATH parent directory does not exist"
            )


def setup_daemon_config(
    config_file: str = "", env: Optional[Dict[str, str]] = None
) -> DaemonConfig:
    """Build a validated DaemonConfig from env (+ optional file), the analog of
    SetupDaemonConfig (reference config.go:302-547)."""
    env = dict(os.environ) if env is None else env
    if config_file:
        load_config_file(config_file, env)

    host = socket.gethostname() or "localhost"
    conf = DaemonConfig(
        grpc_address=_get(env, "GUBER_GRPC_ADDRESS", "localhost:1051"),
        http_address=_get(env, "GUBER_HTTP_ADDRESS", "localhost:1050"),
        status_http_address=_get(env, "GUBER_STATUS_HTTP_ADDRESS", ""),
        advertise_address=_get(env, "GUBER_ADVERTISE_ADDRESS", ""),
        data_center=_get(env, "GUBER_DATA_CENTER", ""),
        instance_id=_get(env, "GUBER_INSTANCE_ID", ""),
        max_batch_size=_get_int(env, "GUBER_MAX_BATCH_SIZE", 1000),
        cascade_max_levels=_get_int(env, "GUBER_CASCADE_MAX_LEVELS", 8),
        cache_size=_get_int(env, "GUBER_CACHE_SIZE", 50_000),
        cache_max_size=_get_int(env, "GUBER_CACHE_MAX_SIZE", 0),
        engine=_get(env, "GUBER_ENGINE", "local"),
        shard_route=_get(env, "GUBER_SHARD_ROUTE", "auto"),
        shard_dedup=_get(env, "GUBER_SHARD_DEDUP", "auto"),
        a2a_impl=_get(env, "GUBER_A2A_IMPL", "auto"),
        mesh_hosts=_get_int(env, "GUBER_MESH_HOSTS", 0),
        probe_kernel=_get(env, "GUBER_PROBE_KERNEL", "auto"),
        walk_kernel=_get(env, "GUBER_WALK_KERNEL", "auto"),
        workers=_get_int(env, "GUBER_WORKER_COUNT", 0),
        behaviors=BehaviorConfig(
            batch_timeout_ms=_get_float_ms(env, "GUBER_BATCH_TIMEOUT", 500.0),
            batch_wait_ms=_get_float_ms(env, "GUBER_BATCH_WAIT", 0.5),
            batch_limit=_get_int(env, "GUBER_BATCH_LIMIT", 1000),
            coalesce_limit=_get_int(env, "GUBER_BATCH_COALESCE_LIMIT", 16384),
            pipeline_inflight=_get_int(env, "GUBER_PIPELINE_INFLIGHT", 4),
            front_workers=_get_int(env, "GUBER_FRONT_WORKERS", 0),
            adaptive_batch=_get_bool(env, "GUBER_ADAPTIVE_BATCH", True),
            batch_close_rows=_get_int(env, "GUBER_BATCH_CLOSE_ROWS", 0),
            batch_close_bytes=_get_int(
                env, "GUBER_BATCH_CLOSE_BYTES", 1 << 20
            ),
            batch_queue_rows=_get_int(env, "GUBER_BATCH_QUEUE_ROWS", 0),
            # GUBER_OVERLOAD_DEADLINE_MS=auto arms the plane with the
            # measured-dispatch-speed deadline (service/batcher.py derives
            # it from the issue-stage EWMA) instead of a fixed number
            overload_deadline_ms=(
                0.0
                if _get(env, "GUBER_OVERLOAD_DEADLINE_MS", "")
                .strip().lower() == "auto"
                else _get_float_ms(env, "GUBER_OVERLOAD_DEADLINE_MS", 0.0)
            ),
            overload_deadline_auto=(
                _get(env, "GUBER_OVERLOAD_DEADLINE_MS", "")
                .strip().lower() == "auto"
            ),
            overload_tenant_share=_get_fraction(
                env, "GUBER_OVERLOAD_TENANT_SHARE", 0.5
            ),
            overload_tenant_buckets=_get_int(
                env, "GUBER_OVERLOAD_TENANT_BUCKETS", 64
            ),
            overload_retry_ms=_get_int(env, "GUBER_OVERLOAD_RETRY_MS", 25),
            ring_enable=_get_bool(env, "GUBER_RING_ENABLE", False),
            ring_slots=_get_int(env, "GUBER_RING_SLOTS", 64),
            ring_issue=_get(env, "GUBER_RING_ISSUE", "auto"),
            ring_drain_k=_get_int(env, "GUBER_RING_DRAIN_K", 8),
            ring_slot_width=_get_int(env, "GUBER_RING_SLOT_WIDTH", 0),
            warm_shapes=_get(env, "GUBER_WARM_SHAPES", ""),
            global_timeout_ms=_get_float_ms(env, "GUBER_GLOBAL_TIMEOUT", 500.0),
            global_sync_wait_ms=_get_float_ms(env, "GUBER_GLOBAL_SYNC_WAIT", 100.0),
            global_batch_limit=_get_int(env, "GUBER_GLOBAL_BATCH_LIMIT", 1000),
            global_peer_concurrency=_get_int(
                env, "GUBER_GLOBAL_PEER_CONCURRENCY", 100
            ),
            global_wire_sync=_get_bool(env, "GUBER_GLOBAL_WIRE_SYNC", True),
            force_global=_get_bool(env, "GUBER_FORCE_GLOBAL", False),
            peer_breaker_errors=_get_int(env, "GUBER_PEER_BREAKER_ERRORS", 5),
            peer_breaker_backoff_base_ms=_get_float_ms(
                env, "GUBER_PEER_BREAKER_BACKOFF_BASE", 500.0
            ),
            peer_breaker_backoff_cap_ms=_get_float_ms(
                env, "GUBER_PEER_BREAKER_BACKOFF_CAP", 30_000.0
            ),
            peer_breaker_probes=_get_int(env, "GUBER_PEER_BREAKER_PROBES", 1),
            degradation_policy=_get(
                env, "GUBER_DEGRADATION_POLICY", DegradationPolicy.ERROR.value
            ),
            global_requeue_retries=_get_int(
                env, "GUBER_GLOBAL_REQUEUE_RETRIES", 3
            ),
            global_queue_cap=_get_int(env, "GUBER_GLOBAL_QUEUE_CAP", 10_000),
            region_sync_wait_ms=_get_float_ms(
                env, "GUBER_REGION_SYNC_WAIT", 0.0
            ),
            region_timeout_ms=_get_float_ms(env, "GUBER_REGION_TIMEOUT", 0.0),
            region_requeue_retries=_get_int(
                env, "GUBER_REGION_REQUEUE_RETRIES", 3
            ),
            region_queue_cap=_get_int(env, "GUBER_REGION_QUEUE_CAP", 10_000),
            region_wire_sync=_get_bool(env, "GUBER_REGION_WIRE_SYNC", True),
            handoff_enabled=_get_bool(env, "GUBER_HANDOFF_ENABLED", True),
            handoff_deadline_ms=_get_float_ms(
                env, "GUBER_HANDOFF_DEADLINE", 5_000.0
            ),
            handoff_chunk_rows=_get_int(env, "GUBER_HANDOFF_CHUNK_ROWS", 4096),
        ),
        peer_discovery_type=_get(env, "GUBER_PEER_DISCOVERY_TYPE", "none"),
        dns_fqdn=_get(env, "GUBER_DNS_FQDN", ""),
        dns_poll_ms=_get_float_ms(env, "GUBER_DNS_POLL", 5_000.0),
        etcd_endpoint=_get(env, "GUBER_ETCD_ENDPOINT", ""),
        etcd_key_prefix=_get(env, "GUBER_ETCD_KEY_PREFIX", "/gubernator/peers/"),
        etcd_lease_ttl_s=_get_int(env, "GUBER_ETCD_LEASE_TTL", 30),
        etcd_poll_ms=_get_float_ms(env, "GUBER_ETCD_POLL", 2_000.0),
        memberlist_address=_get(env, "GUBER_MEMBERLIST_ADDRESS", ""),
        memberlist_advertise_address=_get(
            env, "GUBER_MEMBERLIST_ADVERTISE_ADDRESS", ""
        ),
        memberlist_known_nodes=_get(env, "GUBER_MEMBERLIST_KNOWN_NODES", ""),
        memberlist_gossip_interval_ms=_get_float_ms(
            env, "GUBER_MEMBERLIST_GOSSIP_INTERVAL", 500.0
        ),
        memberlist_secret_keys=_get(env, "GUBER_MEMBERLIST_SECRET_KEYS", ""),
        k8s_namespace=_get(env, "GUBER_K8S_NAMESPACE", "default"),
        k8s_pod_ip=_get(env, "GUBER_K8S_POD_IP", ""),
        k8s_pod_port=_get(env, "GUBER_K8S_POD_PORT", ""),
        k8s_selector=_get(env, "GUBER_K8S_ENDPOINTS_SELECTOR", ""),
        k8s_mechanism=_get(env, "GUBER_K8S_WATCH_MECHANISM", "endpointslices"),
        k8s_api_url=_get(env, "GUBER_K8S_API_URL", ""),
        k8s_poll_ms=_get_float_ms(env, "GUBER_K8S_POLL", 5_000.0),
        tls_ca_file=_get(env, "GUBER_TLS_CA", ""),
        tls_cert_file=_get(env, "GUBER_TLS_CERT", ""),
        tls_key_file=_get(env, "GUBER_TLS_KEY", ""),
        tls_auto=_get_bool(env, "GUBER_TLS_AUTO", False),
        tls_client_auth=_get(env, "GUBER_TLS_CLIENT_AUTH", ""),
        checkpoint_path=_get(env, "GUBER_CHECKPOINT_PATH", ""),
        checkpoint_interval_ms=_get_float_ms(
            env, "GUBER_CHECKPOINT_INTERVAL_MS", 0.0
        ),
        checkpoint_compact_frames=_get_int(
            env, "GUBER_CHECKPOINT_COMPACT_FRAMES", 64
        ),
        checkpoint_delta_path=_get(env, "GUBER_CHECKPOINT_DELTA_PATH", ""),
        tier_enabled=_get_bool(env, "GUBER_TIER_ENABLED", False),
        tier_idle_ms=_get_float_ms(env, "GUBER_TIER_IDLE_MS", 60_000.0),
        tier_shadow_bytes=_get_int(
            env, "GUBER_TIER_SHADOW_BYTES", 1 << 28
        ),
        tier_spill_path=_get(env, "GUBER_TIER_SPILL_PATH", ""),
        telemetry_interval_ms=_get_float_ms(
            env, "GUBER_TELEMETRY_INTERVAL_MS", 5_000.0
        ),
        debug_endpoints=_get_bool(env, "GUBER_DEBUG_ENDPOINTS", True),
        lease_max_fraction=_get_fraction(env, "GUBER_LEASE_MAX_FRACTION", 0.5),
        lease_min_ttl_ms=_get_float_ms(env, "GUBER_LEASE_MIN_TTL_MS", 100.0),
        lease_max_ttl_ms=_get_float_ms(
            env, "GUBER_LEASE_MAX_TTL_MS", 30_000.0
        ),
        lease_max_outstanding=_get_int(
            env, "GUBER_LEASE_MAX_OUTSTANDING", 0
        ),
        lease_priority_scaling=_get_bool(
            env, "GUBER_PRIORITY_LEASE_SCALING", False
        ),
        created_at_tolerance_ms=_get_float_ms(
            env, "GUBER_CREATED_AT_TOLERANCE", 5 * 60 * 1000.0
        ),
        graceful_termination_delay_s=_get_float_ms(
            env, "GUBER_GRACEFUL_TERMINATION_DELAY", 0.0
        )
        / 1e3,
        log_level=_get(env, "GUBER_LOG_LEVEL", "info"),
        metric_flags=_get(env, "GUBER_METRIC_FLAGS", ""),
        grpc_max_conn_age_s=float(
            _get_int(env, "GUBER_GRPC_MAX_CONN_AGE_SEC", 0)
        ),
    )
    # hostname convenience: GUBER_GRPC_ADDRESS=:1051 binds all interfaces but
    # advertises the hostname (reference net.go ResolveHostIP analog)
    if conf.advertise_address.startswith(":"):
        conf.advertise_address = f"{host}{conf.advertise_address}"
    conf.validate()
    return conf
