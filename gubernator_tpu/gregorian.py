"""Gregorian-calendar expiration, host-side.

Calendar math cannot live in a jitted kernel (data-dependent, irregular), so —
exactly like the reference, which computes it inline per request
(reference interval.go:84-148, algorithms.go:127-132,214-219,337-353) — the
front door resolves DURATION_IS_GREGORIAN requests into absolute expiry
timestamps and interval lengths before the batch reaches the device.

Semantics parity with reference interval.go:
* expiration = end of the current minute/hour/day/month/year, in epoch ms
  (inclusive end: last representable instant truncated to ms);
* interval duration = full length of that calendar interval in ms;
* GregorianWeeks is rejected (reference interval.go:88-89 does the same).

Local time: the reference uses the process's local timezone (Go time package
default). We use the host's local timezone via datetime.astimezone().
"""

from __future__ import annotations

import datetime as _dt

from gubernator_tpu.types import Gregorian

_MS = 1000


class GregorianError(ValueError):
    pass


def _local(now_ms: int) -> _dt.datetime:
    return _dt.datetime.fromtimestamp(now_ms / 1000.0).astimezone()


def _to_ms(d: _dt.datetime) -> int:
    return int(d.timestamp() * 1000)


def gregorian_duration(now_ms: int, d: int) -> int:
    """Full length of the calendar interval containing `now`, in ms
    (reference interval.go:84-110)."""
    if d == Gregorian.MINUTES:
        return 60_000
    if d == Gregorian.HOURS:
        return 3_600_000
    if d == Gregorian.DAYS:
        return 86_400_000
    if d == Gregorian.MONTHS:
        n = _local(now_ms)
        begin = n.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
        end = _add_months(begin, 1)
        return _to_ms(end) - _to_ms(begin)
    if d == Gregorian.YEARS:
        n = _local(now_ms)
        begin = n.replace(month=1, day=1, hour=0, minute=0, second=0, microsecond=0)
        end = begin.replace(year=begin.year + 1)
        return _to_ms(end) - _to_ms(begin)
    if d == Gregorian.WEEKS:
        raise GregorianError("`duration = GregorianWeeks` not supported")
    raise GregorianError(
        "behavior DURATION_IS_GREGORIAN is set; but `duration` is not a valid "
        "gregorian interval"
    )


def gregorian_expiration(now_ms: int, d: int) -> int:
    """Epoch-ms expiry = end of the calendar interval containing `now`
    (reference interval.go:112-148). The reference returns the interval end
    minus one nanosecond, truncated to ms — i.e. the last whole millisecond
    strictly inside the interval."""
    n = _local(now_ms)
    if d == Gregorian.MINUTES:
        begin = n.replace(second=0, microsecond=0)
        return _to_ms(begin) + 60_000 - 1
    if d == Gregorian.HOURS:
        begin = n.replace(minute=0, second=0, microsecond=0)
        return _to_ms(begin) + 3_600_000 - 1
    if d == Gregorian.DAYS:
        begin = n.replace(hour=0, minute=0, second=0, microsecond=0)
        return _to_ms(begin) + 86_400_000 - 1
    if d == Gregorian.MONTHS:
        begin = n.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
        return _to_ms(_add_months(begin, 1)) - 1
    if d == Gregorian.YEARS:
        begin = n.replace(month=1, day=1, hour=0, minute=0, second=0, microsecond=0)
        return _to_ms(begin.replace(year=begin.year + 1)) - 1
    if d == Gregorian.WEEKS:
        raise GregorianError("`duration = GregorianWeeks` not supported")
    raise GregorianError(
        "behavior DURATION_IS_GREGORIAN is set; but `duration` is not a valid "
        "gregorian interval"
    )


def _add_months(d: _dt.datetime, months: int) -> _dt.datetime:
    month = d.month - 1 + months
    year = d.year + month // 12
    month = month % 12 + 1
    return d.replace(year=year, month=month)
