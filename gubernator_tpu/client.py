"""Client library — dial helper + typed client for the V1 service.

The analog of the reference's Go client helpers (reference client.go:44-105)
plus its Python client's role (python/gubernator). Builds raw grpc.aio unary
calls over the repo pb2 messages, so no generated service stubs are required.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence, Union

import grpc

from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.types import RateLimitRequest, RateLimitResponse

GET_RATE_LIMITS = "/pb.gubernator.V1/GetRateLimits"
HEALTH_CHECK = "/pb.gubernator.V1/HealthCheck"
LIVE_CHECK = "/pb.gubernator.V1/LiveCheck"
LEASE_QUOTA = "/pb.gubernator.V1/LeaseQuota"


def response_retry_after_ms(resp: "pb.RateLimitResp") -> int:
    """The denied row's backoff hint as a first-class value.

    The frozen proto schema carries retry_after only as
    metadata["retry_after_ms"] (PR 11); this is the one place that knows the
    spelling, so callers (the edge library's per-check fallback among them)
    never string-key spelunk. 0 for allowed rows or pre-retry_after peers."""
    raw = resp.metadata.get("retry_after_ms", "")
    if raw:
        try:
            return max(0, int(raw))
        except ValueError:
            return 0
    return 0


def response_from_pb(resp: "pb.RateLimitResp") -> RateLimitResponse:
    """pb.RateLimitResp → typed RateLimitResponse with `retry_after_ms`
    populated as a first-class field (types.RateLimitResponse)."""
    return RateLimitResponse(
        status=int(resp.status),
        limit=int(resp.limit),
        remaining=int(resp.remaining),
        reset_time=int(resp.reset_time),
        error=resp.error,
        metadata=dict(resp.metadata),
        retry_after_ms=response_retry_after_ms(resp),
    )


def to_pb(r: Union[RateLimitRequest, Dict, "pb.RateLimitReq"]) -> "pb.RateLimitReq":
    if isinstance(r, pb.RateLimitReq):
        return r
    if isinstance(r, dict):
        return pb.RateLimitReq(**r)
    msg = pb.RateLimitReq(
        name=r.name,
        unique_key=r.unique_key,
        hits=r.hits,
        limit=r.limit,
        duration=r.duration,
        algorithm=int(r.algorithm),
        behavior=int(r.behavior),
        burst=r.burst,
    )
    if r.created_at:
        msg.created_at = r.created_at
    if r.metadata:
        for k, v in r.metadata.items():
            msg.metadata[k] = v
    if getattr(r, "cascade", None):
        for lvl in r.cascade:
            msg.cascade.add(
                name=lvl.name,
                unique_key=lvl.unique_key,
                limit=lvl.limit,
                duration=lvl.duration,
                algorithm=int(lvl.algorithm),
                burst=lvl.burst,
            )
    return msg


class V1Client:
    """Async client for one daemon (DialV1Server analog, client.go:44-66).

    `channels` > 1 opens that many HTTP/2 connections and round-robins
    GetRateLimits across them — one gRPC channel serializes every response
    onto a single TCP stream, which caps a hot client well below what the
    server can produce (HTTP/2 flow control + head-of-line blocking on the
    shared connection). The per-method callables are built once per channel,
    not per call."""

    def __init__(
        self,
        address: str,
        credentials: Optional[grpc.ChannelCredentials] = None,
        timeout_s: float = 5.0,
        channels: int = 1,
    ):
        if channels < 1:
            raise ValueError("channels must be >= 1")
        self.address = address
        self.timeout_s = timeout_s

        def dial(i: int):
            # distinct channel args defeat grpc's global subchannel sharing
            # — with identical args every "channel" can ride one TCP
            # connection and the fan-out buys nothing
            opts = [("gubernator.client_channel", i)]
            if credentials is not None:
                return grpc.aio.secure_channel(address, credentials, options=opts)
            return grpc.aio.insecure_channel(address, options=opts)

        self._channels = [dial(i) for i in range(channels)]
        self._calls = [
            ch.unary_unary(
                GET_RATE_LIMITS,
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.GetRateLimitsResp.FromString,
            )
            for ch in self._channels
        ]
        self._rr = 0

    @property
    def _channel(self):
        """First channel (back-compat for callers poking the raw channel)."""
        return self._channels[0]

    def _next_call(self):
        self._rr = (self._rr + 1) % len(self._calls)
        return self._calls[self._rr]

    async def get_rate_limits(
        self,
        requests: Sequence[Union[RateLimitRequest, Dict, "pb.RateLimitReq"]],
        timeout_s: Optional[float] = None,
    ) -> "pb.GetRateLimitsResp":
        req = pb.GetRateLimitsReq(requests=[to_pb(r) for r in requests])
        return await self._next_call()(req, timeout=timeout_s or self.timeout_s)

    async def check(
        self,
        requests: Sequence[Union[RateLimitRequest, Dict, "pb.RateLimitReq"]],
        timeout_s: Optional[float] = None,
    ) -> List[RateLimitResponse]:
        """get_rate_limits returning typed responses with retry_after_ms as
        a first-class field — callers back off without metadata spelunking."""
        resp = await self.get_rate_limits(requests, timeout_s=timeout_s)
        return [response_from_pb(r) for r in resp.responses]

    async def lease_quota(
        self, req: "pb.LeaseQuotaReq", timeout_s: Optional[float] = None
    ) -> "pb.LeaseQuotaResp":
        """One edge quota-lease operation (acquire / renew / return —
        docs/leases.md); the edge.LocalLimiter drives this."""
        call = self._channel.unary_unary(
            LEASE_QUOTA,
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.LeaseQuotaResp.FromString,
        )
        return await call(req, timeout=timeout_s or self.timeout_s)

    async def health_check(self, timeout_s: Optional[float] = None) -> "pb.HealthCheckResp":
        call = self._channel.unary_unary(
            HEALTH_CHECK,
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.HealthCheckResp.FromString,
        )
        return await call(pb.HealthCheckReq(), timeout=timeout_s or self.timeout_s)

    async def live_check(self, timeout_s: Optional[float] = None) -> "pb.LiveCheckResp":
        call = self._channel.unary_unary(
            LIVE_CHECK,
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.LiveCheckResp.FromString,
        )
        return await call(pb.LiveCheckReq(), timeout=timeout_s or self.timeout_s)

    async def close(self) -> None:
        for ch in self._channels:
            await ch.close()


def random_peer(peers: List[str]) -> str:
    """reference client.go RandomPeer."""
    return random.choice(peers)
