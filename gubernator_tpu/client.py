"""Client library — dial helper + typed client for the V1 service.

The analog of the reference's Go client helpers (reference client.go:44-105)
plus its Python client's role (python/gubernator). Builds raw grpc.aio unary
calls over the repo pb2 messages, so no generated service stubs are required.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence, Union

import grpc

from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.types import RateLimitRequest

GET_RATE_LIMITS = "/pb.gubernator.V1/GetRateLimits"
HEALTH_CHECK = "/pb.gubernator.V1/HealthCheck"
LIVE_CHECK = "/pb.gubernator.V1/LiveCheck"


def to_pb(r: Union[RateLimitRequest, Dict, "pb.RateLimitReq"]) -> "pb.RateLimitReq":
    if isinstance(r, pb.RateLimitReq):
        return r
    if isinstance(r, dict):
        return pb.RateLimitReq(**r)
    msg = pb.RateLimitReq(
        name=r.name,
        unique_key=r.unique_key,
        hits=r.hits,
        limit=r.limit,
        duration=r.duration,
        algorithm=int(r.algorithm),
        behavior=int(r.behavior),
        burst=r.burst,
    )
    if r.created_at:
        msg.created_at = r.created_at
    if r.metadata:
        for k, v in r.metadata.items():
            msg.metadata[k] = v
    if getattr(r, "cascade", None):
        for lvl in r.cascade:
            msg.cascade.add(
                name=lvl.name,
                unique_key=lvl.unique_key,
                limit=lvl.limit,
                duration=lvl.duration,
                algorithm=int(lvl.algorithm),
                burst=lvl.burst,
            )
    return msg


class V1Client:
    """Async client for one daemon (DialV1Server analog, client.go:44-66).

    `channels` > 1 opens that many HTTP/2 connections and round-robins
    GetRateLimits across them — one gRPC channel serializes every response
    onto a single TCP stream, which caps a hot client well below what the
    server can produce (HTTP/2 flow control + head-of-line blocking on the
    shared connection). The per-method callables are built once per channel,
    not per call."""

    def __init__(
        self,
        address: str,
        credentials: Optional[grpc.ChannelCredentials] = None,
        timeout_s: float = 5.0,
        channels: int = 1,
    ):
        if channels < 1:
            raise ValueError("channels must be >= 1")
        self.address = address
        self.timeout_s = timeout_s

        def dial(i: int):
            # distinct channel args defeat grpc's global subchannel sharing
            # — with identical args every "channel" can ride one TCP
            # connection and the fan-out buys nothing
            opts = [("gubernator.client_channel", i)]
            if credentials is not None:
                return grpc.aio.secure_channel(address, credentials, options=opts)
            return grpc.aio.insecure_channel(address, options=opts)

        self._channels = [dial(i) for i in range(channels)]
        self._calls = [
            ch.unary_unary(
                GET_RATE_LIMITS,
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.GetRateLimitsResp.FromString,
            )
            for ch in self._channels
        ]
        self._rr = 0

    @property
    def _channel(self):
        """First channel (back-compat for callers poking the raw channel)."""
        return self._channels[0]

    def _next_call(self):
        self._rr = (self._rr + 1) % len(self._calls)
        return self._calls[self._rr]

    async def get_rate_limits(
        self,
        requests: Sequence[Union[RateLimitRequest, Dict, "pb.RateLimitReq"]],
        timeout_s: Optional[float] = None,
    ) -> "pb.GetRateLimitsResp":
        req = pb.GetRateLimitsReq(requests=[to_pb(r) for r in requests])
        return await self._next_call()(req, timeout=timeout_s or self.timeout_s)

    async def health_check(self, timeout_s: Optional[float] = None) -> "pb.HealthCheckResp":
        call = self._channel.unary_unary(
            HEALTH_CHECK,
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.HealthCheckResp.FromString,
        )
        return await call(pb.HealthCheckReq(), timeout=timeout_s or self.timeout_s)

    async def live_check(self, timeout_s: Optional[float] = None) -> "pb.LiveCheckResp":
        call = self._channel.unary_unary(
            LIVE_CHECK,
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.LiveCheckResp.FromString,
        )
        return await call(pb.LiveCheckReq(), timeout=timeout_s or self.timeout_s)

    async def close(self) -> None:
        for ch in self._channels:
            await ch.close()


def random_peer(peers: List[str]) -> str:
    """reference client.go RandomPeer."""
    return random.choice(peers)
