"""Native host ingress/egress — build + load the _guberhost C++ extension.

`load()` returns the extension module (building it with g++ on first use) or
None when no toolchain is available; callers keep a pure-Python fallback.
The build is a single translation unit against Python.h only — no
libprotobuf, no numpy C API (buffers cross as bytes; numpy wraps them with
np.frombuffer zero-copy).
"""

from __future__ import annotations

import logging
import os
import subprocess
import sysconfig
from typing import Optional

log = logging.getLogger("gubernator_tpu.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "guberhost.cpp")
_mod = None
_tried = False


def _so_path() -> str:
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(_DIR, f"_guberhost{suffix}")


def build(force: bool = False) -> Optional[str]:
    """Compile the extension in-place; returns the .so path or None."""
    so = _so_path()
    if (
        not force
        and os.path.exists(so)
        and os.path.getmtime(so) >= os.path.getmtime(_SRC)
    ):
        return so
    include = sysconfig.get_paths()["include"]
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
        f"-I{include}", "-o", so, _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError) as exc:
        detail = getattr(exc, "stderr", b"") or b""
        log.warning(
            "native guberhost build failed (%s): %s — using the Python path",
            exc, detail.decode(errors="replace")[:500],
        )
        return None
    return so


def load():
    """The extension module, building if needed; None if unavailable."""
    global _mod, _tried
    if _mod is not None or _tried:
        return _mod
    _tried = True
    if os.environ.get("GUBER_NATIVE", "").lower() in ("0", "false", "off"):
        return None
    if build() is None:
        return None
    try:
        from gubernator_tpu.native import _guberhost  # type: ignore

        _mod = _guberhost
    except ImportError as exc:  # pragma: no cover - toolchain-specific
        log.warning("native guberhost import failed: %s", exc)
        _mod = None
    return _mod
