// guberhost — native host ingress/egress for gubernator-tpu.
//
// The serving hot path's host-side cost is per-item Python work: protobuf
// message traversal, string hashing, and response object construction
// (~1-2 µs/item), which caps a host at ~1M checks/s regardless of kernel
// speed. This module parses the GetRateLimitsReq WIRE BYTES directly into
// flat column buffers (consumed via np.frombuffer), computes both hashes
// (63-bit seeded XXH64 fingerprint — ops/hashing.py parity; fnv1a_32 ring
// point — peers/hash_ring.py parity) in the same pass, and serializes
// GetRateLimitsResp straight from response columns.
//
// Wire schema parsed (proto/gubernator.proto):
//   GetRateLimitsReq { repeated RateLimitReq requests = 1; }
//   RateLimitReq { name=1 str; unique_key=2 str; hits=3; limit=4;
//                  duration=5; algorithm=6; behavior=7; burst=8;
//                  metadata=9 (skipped); created_at=10 }
//   GetRateLimitsResp { repeated RateLimitResp responses = 1; }
//   RateLimitResp { status=1; limit=2; remaining=3; reset_time=4;
//                   error=5 str; metadata=6 }
//
// No libprotobuf dependency: varint/length-delimited framing is ~60 lines.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

// ------------------------------------------------------------------ XXH64
// Standard XXH64 (public algorithm; matches python-xxhash output).

static const uint64_t P1 = 11400714785074694791ULL;
static const uint64_t P2 = 14029467366897019727ULL;
static const uint64_t P3 = 1609587929392839161ULL;
static const uint64_t P4 = 9650029242287828579ULL;
static const uint64_t P5 = 2870177450012600261ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}
static inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86/ARM)
}
static inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}
static inline uint64_t xxh_round(uint64_t acc, uint64_t input) {
  acc += input * P2;
  acc = rotl64(acc, 31);
  return acc * P1;
}
static inline uint64_t xxh_merge(uint64_t acc, uint64_t val) {
  acc ^= xxh_round(0, val);
  return acc * P1 + P4;
}

static uint64_t xxh64(const uint8_t* p, size_t len, uint64_t seed) {
  const uint8_t* end = p + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed, v4 = seed - P1;
    const uint8_t* limit = end - 32;
    do {
      v1 = xxh_round(v1, read64(p)); p += 8;
      v2 = xxh_round(v2, read64(p)); p += 8;
      v3 = xxh_round(v3, read64(p)); p += 8;
      v4 = xxh_round(v4, read64(p)); p += 8;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = xxh_merge(h, v1); h = xxh_merge(h, v2);
    h = xxh_merge(h, v3); h = xxh_merge(h, v4);
  } else {
    h = seed + P5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    h ^= xxh_round(0, read64(p));
    h = rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= (uint64_t)read32(p) * P1;
    h = rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p) * P5;
    h = rotl64(h, 11) * P1;
    p++;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

static inline uint32_t fnv1a_32(const uint8_t* p, size_t len) {
  uint32_t h = 2166136261u;
  for (size_t i = 0; i < len; i++) {
    h ^= p[i];
    h *= 16777619u;
  }
  return h;
}

// ------------------------------------------------------------ proto frames

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= (uint64_t)(b & 0x7F) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }

  bool skip(uint32_t wt) {
    switch (wt) {
      case 0: varint(); return ok;
      case 1: if (end - p < 8) return ok = false; p += 8; return true;
      case 2: {
        uint64_t n = varint();
        if (!ok || (uint64_t)(end - p) < n) return ok = false;
        p += n;
        return true;
      }
      case 5: if (end - p < 4) return ok = false; p += 4; return true;
      default: return ok = false;
    }
  }
};

static const uint64_t FP_SEED = 0x6775626572ULL;  // hashing.py _SEED
static const uint64_t MASK63 = (1ULL << 63) - 1;

// err codes — ops/batch.py ERR_*
enum { ERR_OK = 0, ERR_EMPTY_KEY = 1, ERR_EMPTY_NAME = 2 };

// Compact-wire layout constants — MUST mirror ops/wire.py (DUR_BITS,
// HITS_BITS, behavior bit budget). The parser pre-packs each item into the
// 5-lane int32 ingress row IN THE SAME PASS so the serving path can stage a
// dispatch grid without ever materializing per-column int64 arrays; the
// created_at delta (lane 4 bits 18-27) is left zero — the flush loop ORs it
// in once the batch base is known; bits 28-29 carry the priority tier. Lane 3 is duration[0:27] | algo << 27
// (3 bits — five in-kernel algorithms) | cascade_level << 30; the parser
// always emits level 0 (cascade requests take the pb path — see field 11
// below).
static const int64_t WIRE_DUR_MASK = (1LL << 27) - 1;   // ops/wire.DUR_BITS
static const int64_t WIRE_HITS_MASK = (1LL << 18) - 1;  // ops/wire.HITS_BITS
static const int64_t WIRE_I32_MAX = 2147483647LL;
// RESET_REMAINING | DRAIN_OVER_LIMIT | kernel-inert bits | the 2-bit
// priority tier (ops/wire.py _ENCODABLE_BEHAVIOR); anything else
// (Gregorian, unknown) → full-width
static const int32_t WIRE_ENC_BEHAVIOR = 8 | 32 | 1 | 2 | 16 | 64 | 128;
// known client-facing behavior bits: flag values 1..32 plus the 2-bit
// priority tier at bits 6-7 (types.PRIORITY_SHIFT) — anything above is
// masked at ingress: the behavior word's high bits carry the INTERNAL
// cascade level (types.CASCADE_LEVEL_SHIFT), which clients must not be
// able to forge
static const int32_t BEHAVIOR_CLIENT_MASK = 255;
// highest algorithm enum this build speaks (types.MAX_ALGORITHM); larger
// values are per-item errors on the full path, so never fused
static const int32_t MAX_ALGORITHM = 4;

struct Item {
  const uint8_t* name = nullptr; size_t name_len = 0;
  const uint8_t* key = nullptr; size_t key_len = 0;
  const uint8_t* traceparent = nullptr; size_t traceparent_len = 0;
  int64_t hits = 0, limit = 0, duration = 0, burst = 0, created_at = 0;
  int32_t algorithm = 0, behavior = 0;
  bool has_cascade = false;  // repeated CascadeLevel cascade = 11 present
  size_t start = 0, len = 0;  // byte span of the item message in the input
};

// metadata map entry {1: key str, 2: value str} — only "traceparent" is
// routing-relevant (trace propagation; docs/tracing.md)
static void parse_metadata_entry(const uint8_t* p, const uint8_t* end,
                                 Item& it) {
  Cursor c{p, end};
  const uint8_t* k = nullptr; size_t klen = 0;
  const uint8_t* v = nullptr; size_t vlen = 0;
  while (c.p < c.end && c.ok) {
    uint64_t tag = c.varint();
    if (!c.ok) return;
    uint32_t field = (uint32_t)(tag >> 3), wt = (uint32_t)(tag & 7);
    if ((field == 1 || field == 2) && wt == 2) {
      uint64_t n = c.varint();
      if (!c.ok || (uint64_t)(c.end - c.p) < n) return;
      if (field == 1) { k = c.p; klen = n; } else { v = c.p; vlen = n; }
      c.p += n;
    } else if (!c.skip(wt)) {
      return;
    }
  }
  if (k && v && klen == 11 && memcmp(k, "traceparent", 11) == 0) {
    it.traceparent = v;
    it.traceparent_len = vlen;
  }
}

static bool parse_item(Cursor& c, Item& it) {
  while (c.p < c.end && c.ok) {
    uint64_t tag = c.varint();
    if (!c.ok) return false;
    uint32_t field = (uint32_t)(tag >> 3), wt = (uint32_t)(tag & 7);
    switch (field) {
      case 1: case 2: {  // name / unique_key
        if (wt != 2) return false;
        uint64_t n = c.varint();
        if (!c.ok || (uint64_t)(c.end - c.p) < n) return false;
        if (field == 1) { it.name = c.p; it.name_len = n; }
        else { it.key = c.p; it.key_len = n; }
        c.p += n;
        break;
      }
      case 3: it.hits = (int64_t)c.varint(); break;
      case 4: it.limit = (int64_t)c.varint(); break;
      case 5: it.duration = (int64_t)c.varint(); break;
      case 6: it.algorithm = (int32_t)c.varint(); break;
      case 7: it.behavior = (int32_t)c.varint(); break;
      case 8: it.burst = (int64_t)c.varint(); break;
      case 9: {  // metadata map entry
        if (wt != 2) return false;
        uint64_t n = c.varint();
        if (!c.ok || (uint64_t)(c.end - c.p) < n) return false;
        parse_metadata_entry(c.p, c.p + n, it);
        c.p += n;
        break;
      }
      case 10: it.created_at = (int64_t)c.varint(); break;
      case 11:  // repeated CascadeLevel cascade — flag it; the daemon
                // materializes the pb item and expands the levels itself
        it.has_cascade = true;
        if (!c.skip(wt)) return false;
        break;
      default:
        if (!c.skip(wt)) return false;
    }
  }
  return c.ok;
}

// parse_get_rate_limits(data: bytes)
//   -> (n, fp, algo, behavior, hits, limit, burst, duration, created_at,
//       err, ring_hash, spans, traceparent, lanes, enc, casc)
// Buffer layouts (np.frombuffer): fp/hits/limit/burst/duration/created_at
// int64; algo/behavior int32; err int8; ring_hash uint32; spans int64 pairs
// (start, len) of each item's bytes for lazy pb materialization; lanes a
// (5, n) row-major int32 pre-packed compact-wire image (ops/wire.py lanes,
// created-delta field zero); enc int8 per-item compact-wire encodability;
// casc int8 per-item "carries a cascade field" flag (such batches take the
// pb path, where the daemon expands the levels).
// The scan + fill loops run with the GIL RELEASED — N front-door workers
// parse concurrently (service/daemon.py door pool).
static PyObject* parse_get_rate_limits(PyObject*, PyObject* args) {
  Py_buffer buf;
  if (!PyArg_ParseTuple(args, "y*", &buf)) return nullptr;
  const uint8_t* data = (const uint8_t*)buf.buf;

  std::vector<Item> items;
  items.reserve(64);
  bool ok = true;
  Py_BEGIN_ALLOW_THREADS;
  Cursor top{data, data + buf.len};
  while (top.p < top.end && top.ok) {
    uint64_t tag = top.varint();
    if (!top.ok) break;
    uint32_t field = (uint32_t)(tag >> 3), wt = (uint32_t)(tag & 7);
    if (field == 1 && wt == 2) {
      uint64_t n = top.varint();
      if (!top.ok || (uint64_t)(top.end - top.p) < n) { top.ok = false; break; }
      Item it;
      it.start = (size_t)(top.p - data);
      it.len = (size_t)n;
      Cursor ic{top.p, top.p + n};
      if (!parse_item(ic, it)) { top.ok = false; break; }
      items.push_back(it);
      top.p += n;
    } else if (!top.skip(wt)) {
      break;
    }
  }
  ok = top.ok;
  Py_END_ALLOW_THREADS;
  if (!ok) {
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "malformed GetRateLimitsReq");
    return nullptr;
  }

  size_t n = items.size();
  // first propagated trace context in the batch (the daemon adopts one
  // scope per request, same as the pb path's first-match extraction)
  PyObject* tp = nullptr;
  for (size_t i = 0; i < n && !tp; i++) {
    if (items[i].traceparent) {
      tp = PyUnicode_DecodeUTF8((const char*)items[i].traceparent,
                                (Py_ssize_t)items[i].traceparent_len,
                                "replace");
      if (!tp) PyErr_Clear();
    }
  }
  if (!tp) {
    tp = Py_None;
    Py_INCREF(Py_None);
  }
  PyObject* out = PyTuple_New(16);
  PyObject* fp_b = PyBytes_FromStringAndSize(nullptr, n * 8);
  PyObject* algo_b = PyBytes_FromStringAndSize(nullptr, n * 4);
  PyObject* beh_b = PyBytes_FromStringAndSize(nullptr, n * 4);
  PyObject* hits_b = PyBytes_FromStringAndSize(nullptr, n * 8);
  PyObject* lim_b = PyBytes_FromStringAndSize(nullptr, n * 8);
  PyObject* burst_b = PyBytes_FromStringAndSize(nullptr, n * 8);
  PyObject* dur_b = PyBytes_FromStringAndSize(nullptr, n * 8);
  PyObject* ca_b = PyBytes_FromStringAndSize(nullptr, n * 8);
  PyObject* err_b = PyBytes_FromStringAndSize(nullptr, n);
  PyObject* ring_b = PyBytes_FromStringAndSize(nullptr, n * 4);
  PyObject* span_b = PyBytes_FromStringAndSize(nullptr, n * 16);
  PyObject* lanes_b = PyBytes_FromStringAndSize(nullptr, n * 5 * 4);
  PyObject* enc_b = PyBytes_FromStringAndSize(nullptr, n);
  PyObject* casc_b = PyBytes_FromStringAndSize(nullptr, n);
  if (!out || !fp_b || !algo_b || !beh_b || !hits_b || !lim_b || !burst_b ||
      !dur_b || !ca_b || !err_b || !ring_b || !span_b || !lanes_b || !enc_b ||
      !casc_b) {
    PyBuffer_Release(&buf);
    Py_XDECREF(out);
    return nullptr;
  }
  int64_t* fp = (int64_t*)PyBytes_AS_STRING(fp_b);
  int32_t* algo = (int32_t*)PyBytes_AS_STRING(algo_b);
  int32_t* beh = (int32_t*)PyBytes_AS_STRING(beh_b);
  int64_t* hits = (int64_t*)PyBytes_AS_STRING(hits_b);
  int64_t* lim = (int64_t*)PyBytes_AS_STRING(lim_b);
  int64_t* burst = (int64_t*)PyBytes_AS_STRING(burst_b);
  int64_t* dur = (int64_t*)PyBytes_AS_STRING(dur_b);
  int64_t* ca = (int64_t*)PyBytes_AS_STRING(ca_b);
  int8_t* err = (int8_t*)PyBytes_AS_STRING(err_b);
  uint32_t* ring = (uint32_t*)PyBytes_AS_STRING(ring_b);
  int64_t* span = (int64_t*)PyBytes_AS_STRING(span_b);
  int32_t* lanes = (int32_t*)PyBytes_AS_STRING(lanes_b);
  int8_t* enc = (int8_t*)PyBytes_AS_STRING(enc_b);
  int8_t* casc = (int8_t*)PyBytes_AS_STRING(casc_b);

  Py_BEGIN_ALLOW_THREADS;
  std::string hk;
  for (size_t i = 0; i < n; i++) {
    const Item& it = items[i];
    algo[i] = it.algorithm;
    // client-facing flag bits only: the high bits are the internal cascade
    // level field, which must never arrive from the wire
    beh[i] = it.behavior & BEHAVIOR_CLIENT_MASK;
    casc[i] = it.has_cascade ? 1 : 0;
    hits[i] = it.hits;
    lim[i] = it.limit;
    burst[i] = it.burst;
    dur[i] = it.duration;
    ca[i] = it.created_at;
    span[2 * i] = (int64_t)it.start;
    span[2 * i + 1] = (int64_t)it.len;
    fp[i] = 0;
    ring[i] = 0;
    lanes[i] = lanes[n + i] = lanes[2 * n + i] = lanes[3 * n + i] =
        lanes[4 * n + i] = 0;
    if (it.key_len == 0) { err[i] = ERR_EMPTY_KEY; enc[i] = 1; continue; }
    if (it.name_len == 0) { err[i] = ERR_EMPTY_NAME; enc[i] = 1; continue; }
    err[i] = ERR_OK;
    hk.clear();
    hk.append((const char*)it.name, it.name_len);
    hk.push_back('_');
    hk.append((const char*)it.key, it.key_len);
    uint64_t h =
        xxh64((const uint8_t*)hk.data(), hk.size(), FP_SEED) & MASK63;
    fp[i] = (int64_t)(h ? h : 1);
    ring[i] = fnv1a_32((const uint8_t*)hk.data(), hk.size());
    // compact-wire encodability, the ops/wire.wire_encodable checks the
    // parser can settle per-item (created_at skew is batch-relative — the
    // flush loop checks it). Validation-error fields (|limit|/|burst|
    // beyond int32) ALSO fall back: the full path turns them into
    // per-item errors the fused path has no pack stage to produce.
    bool e = (beh[i] & ~WIRE_ENC_BEHAVIOR) == 0 &&
             it.duration >= 0 && it.duration <= WIRE_DUR_MASK &&
             it.hits >= 0 && it.hits <= WIRE_HITS_MASK &&
             it.limit >= 0 && it.limit <= WIRE_I32_MAX &&
             it.burst >= -WIRE_I32_MAX && it.burst <= WIRE_I32_MAX &&
             (it.algorithm >= 0 && it.algorithm <= MAX_ALGORITHM) &&
             // burst lane rules: token ignores burst; leaky/GCRA default
             // burst 0 → limit in-trace (explicit bursts → full-width);
             // window/lease never read burst (keep 0 for byte fidelity)
             (it.algorithm == 0 || it.burst == 0) &&
             !it.has_cascade;
    enc[i] = e ? 1 : 0;
    // pre-packed 5-lane int32 row (ops/wire.pack_wire_rows layout);
    // lane 4's created-delta bits stay 0 until the flush stamps them
    uint64_t ufp = (uint64_t)fp[i];
    lanes[i] = (int32_t)(uint32_t)(ufp & 0xFFFFFFFFu);
    lanes[n + i] = (int32_t)(uint32_t)(ufp >> 32);
    lanes[2 * n + i] = (int32_t)it.limit;
    lanes[3 * n + i] = (int32_t)(uint32_t)(
        ((uint64_t)(it.duration & WIRE_DUR_MASK)) |
        ((uint64_t)(uint32_t)it.algorithm << 27));
    uint32_t l4 = (uint32_t)(it.hits & WIRE_HITS_MASK);
    l4 |= (uint32_t)((it.behavior >> 6) & 3) << 28;  // priority tier
    if (it.behavior & 8) l4 |= 1u << 30;   // RESET_REMAINING
    if (it.behavior & 32) l4 |= 1u << 31;  // DRAIN_OVER_LIMIT
    lanes[4 * n + i] = (int32_t)l4;
  }
  Py_END_ALLOW_THREADS;
  PyBuffer_Release(&buf);

  PyTuple_SET_ITEM(out, 0, PyLong_FromSize_t(n));
  PyTuple_SET_ITEM(out, 1, fp_b);
  PyTuple_SET_ITEM(out, 2, algo_b);
  PyTuple_SET_ITEM(out, 3, beh_b);
  PyTuple_SET_ITEM(out, 4, hits_b);
  PyTuple_SET_ITEM(out, 5, lim_b);
  PyTuple_SET_ITEM(out, 6, burst_b);
  PyTuple_SET_ITEM(out, 7, dur_b);
  PyTuple_SET_ITEM(out, 8, ca_b);
  PyTuple_SET_ITEM(out, 9, err_b);
  PyTuple_SET_ITEM(out, 10, ring_b);
  PyTuple_SET_ITEM(out, 11, span_b);
  PyTuple_SET_ITEM(out, 12, tp);
  PyTuple_SET_ITEM(out, 13, lanes_b);
  PyTuple_SET_ITEM(out, 14, enc_b);
  PyTuple_SET_ITEM(out, 15, casc_b);
  return out;
}

// ------------------------------------------------------------- encode side

static inline void put_varint(std::string& out, uint64_t v) {
  while (v >= 0x80) {
    out.push_back((char)(v | 0x80));
    v >>= 7;
  }
  out.push_back((char)v);
}
static inline void put_tag(std::string& out, uint32_t field, uint32_t wt) {
  put_varint(out, ((uint64_t)field << 3) | wt);
}

// encode_responses(status_i64, limit_i64, remaining_i64, reset_i64,
//                  errors: dict[int, str], now_ms: int = -1)
//                  -> bytes(GetRateLimitsResp)
// The column buffers are raw little-endian int64 — any buffer-protocol
// object works (contiguous numpy int64 arrays pass ZERO-COPY; no .tobytes()
// round trip). Error strings are gathered under the GIL up front; the
// varint/field assembly then runs with the GIL RELEASED so N responder
// workers encode concurrently. With now_ms >= 0, DENIED rows additionally
// carry metadata["retry_after_ms"] = max(0, reset_time - now_ms) — for
// GCRA denials reset_time is the exact TAT-derived conforming instant
// (ops/math.py), so clients honoring it back off precisely.
static PyObject* encode_responses(PyObject*, PyObject* args) {
  Py_buffer sb, lb, rb, tb;
  PyObject* errs;
  long long now_ms = -1;
  if (!PyArg_ParseTuple(args, "y*y*y*y*O|L", &sb, &lb, &rb, &tb, &errs,
                        &now_ms))
    return nullptr;
  size_t n = (size_t)(sb.len / 8);
  const int64_t* st = (const int64_t*)sb.buf;
  const int64_t* li = (const int64_t*)lb.buf;
  const int64_t* re = (const int64_t*)rb.buf;
  const int64_t* rt = (const int64_t*)tb.buf;

  // sparse {row: message} dict → C-side (row, utf8) list, GIL held
  std::vector<std::pair<size_t, std::string>> errv;
  bool bad = false;
  if (errs != Py_None) {
    PyObject *key, *val;
    Py_ssize_t pos = 0;
    while (PyDict_Next(errs, &pos, &key, &val)) {
      size_t row = (size_t)PyLong_AsSize_t(key);
      if (row == (size_t)-1 && PyErr_Occurred()) { bad = true; break; }
      Py_ssize_t elen;
      const char* ep = PyUnicode_AsUTF8AndSize(val, &elen);
      if (!ep) { bad = true; break; }
      if (elen) errv.emplace_back(row, std::string(ep, (size_t)elen));
    }
  }
  if (bad) {
    PyBuffer_Release(&sb); PyBuffer_Release(&lb);
    PyBuffer_Release(&rb); PyBuffer_Release(&tb);
    return nullptr;
  }
  std::vector<const std::string*> err_at(errv.empty() ? 0 : n, nullptr);
  for (const auto& kv : errv)
    if (kv.first < n) err_at[kv.first] = &kv.second;

  std::string out;
  Py_BEGIN_ALLOW_THREADS;
  out.reserve(n * 24);
  std::string item;
  for (size_t i = 0; i < n; i++) {
    item.clear();
    if (st[i]) { put_tag(item, 1, 0); put_varint(item, (uint64_t)st[i]); }
    if (li[i]) { put_tag(item, 2, 0); put_varint(item, (uint64_t)li[i]); }
    if (re[i]) { put_tag(item, 3, 0); put_varint(item, (uint64_t)re[i]); }
    if (rt[i]) { put_tag(item, 4, 0); put_varint(item, (uint64_t)rt[i]); }
    if (!err_at.empty() && err_at[i]) {
      put_tag(item, 5, 2);
      put_varint(item, err_at[i]->size());
      item += *err_at[i];
    }
    if (now_ms >= 0 && st[i] == 1) {
      // metadata map entry {1: "retry_after_ms", 2: decimal-ms}
      static const char RA_KEY[] = "retry_after_ms";
      long long d = rt[i] - now_ms;
      if (d < 0) d = 0;
      char vbuf[24];
      int vlen = snprintf(vbuf, sizeof vbuf, "%lld", d);
      std::string entry;
      put_tag(entry, 1, 2);
      put_varint(entry, sizeof(RA_KEY) - 1);
      entry.append(RA_KEY, sizeof(RA_KEY) - 1);
      put_tag(entry, 2, 2);
      put_varint(entry, (uint64_t)vlen);
      entry.append(vbuf, (size_t)vlen);
      put_tag(item, 6, 2);
      put_varint(item, entry.size());
      item += entry;
    }
    put_tag(out, 1, 2);
    put_varint(out, item.size());
    out += item;
  }
  Py_END_ALLOW_THREADS;
  PyBuffer_Release(&sb);
  PyBuffer_Release(&lb);
  PyBuffer_Release(&rb);
  PyBuffer_Release(&tb);
  return PyBytes_FromStringAndSize(out.data(), (Py_ssize_t)out.size());
}

// fingerprint64(data: bytes) -> int — parity check hook for tests
static PyObject* fingerprint64(PyObject*, PyObject* args) {
  Py_buffer buf;
  if (!PyArg_ParseTuple(args, "y*", &buf)) return nullptr;
  uint64_t h = xxh64((const uint8_t*)buf.buf, (size_t)buf.len, FP_SEED) & MASK63;
  PyBuffer_Release(&buf);
  return PyLong_FromUnsignedLongLong(h ? h : 1);
}

static PyObject* fnv1a32_py(PyObject*, PyObject* args) {
  Py_buffer buf;
  if (!PyArg_ParseTuple(args, "y*", &buf)) return nullptr;
  uint32_t h = fnv1a_32((const uint8_t*)buf.buf, (size_t)buf.len);
  PyBuffer_Release(&buf);
  return PyLong_FromUnsignedLong(h);
}

static PyMethodDef methods[] = {
    {"parse_get_rate_limits", parse_get_rate_limits, METH_VARARGS,
     "GetRateLimitsReq wire bytes -> column buffers"},
    {"encode_responses", encode_responses, METH_VARARGS,
     "response columns -> GetRateLimitsResp wire bytes"},
    {"fingerprint64", fingerprint64, METH_VARARGS, "seeded 63-bit XXH64"},
    {"fnv1a32", fnv1a32_py, METH_VARARGS, "fnv1a 32-bit"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef mod = {PyModuleDef_HEAD_INIT, "_guberhost",
                                 "native host ingress/egress", -1, methods};

PyMODINIT_FUNC PyInit__guberhost(void) { return PyModule_Create(&mod); }
