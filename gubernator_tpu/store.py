"""Persistence hooks: checkpoint snapshots + Loader/Store interfaces.

The reference never persists by default; `Loader` (startup/shutdown snapshot)
and `Store` (continuous write-through) are embedding hooks the server wires
when asked (reference store.go:49-78, workers.go:335-540). The TPU analogs:

* snapshot = ONE device→host DMA of the whole packed-row table (Table2.rows)
  written to disk; restore = one host→device put. The reference streams
  CacheItems one by one through channels; here the state array IS the cache,
  so checkpointing is a bulk array copy — structurally simpler and faster.
* Store = a host-side write-through hook with the reference's full contract
  (store.go:63-78, algorithms.go:45-51): after every dispatch `on_change`
  receives the per-key stored state (algo/status/limit/remaining/reset/
  duration — the same schema UpdatePeerGlobals installs from), and on a
  device-reported cache miss the engine consults `get_many` and re-hydrates
  found entries into the table before the decision stands — so evicted or
  restart-lost items warm back from a durable store exactly like the
  reference's `Store.Get` path. Keys are fingerprints (raw keys never reach
  the device, hashing.py); embedders mapping back to names keep a key→fp
  index.
"""

from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

SNAPSHOT_MAGIC = "GUBTPU1"


def save_snapshot(path: str, rows: np.ndarray) -> None:
    """Atomically write a table snapshot (tmp + rename, so a crash mid-write
    never leaves a torn file for the next boot)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".gubtpu-snap-")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, magic=np.frombuffer(
                SNAPSHOT_MAGIC.encode(), dtype=np.uint8
            ), rows=rows)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_snapshot(path: str) -> np.ndarray:
    with np.load(path) as z:
        magic = bytes(z["magic"]).decode()
        if magic != SNAPSHOT_MAGIC:
            raise ValueError(f"{path}: not a gubernator-tpu snapshot")
        return z["rows"]


@dataclass
class ChangeSet:
    """One dispatch's worth of state changes: parallel per-key arrays (one
    row per unique fingerprint, the LAST occurrence's state when a batch hits
    a key several times). The schema matches UpdatePeerGlobals installs —
    sufficient to reconstruct the item (reference store.go:29-43)."""

    fps: np.ndarray  # int64 fingerprints touched
    created_at: int  # dispatch timestamp (ms)
    algo: Optional[np.ndarray] = None  # int32 Algorithm per row
    status: Optional[np.ndarray] = None  # int32 UNDER/OVER_LIMIT
    limit: Optional[np.ndarray] = None  # int64
    remaining: Optional[np.ndarray] = None  # int64
    reset_time: Optional[np.ndarray] = None  # int64 ms
    duration: Optional[np.ndarray] = None  # int64 ms
    burst: Optional[np.ndarray] = None  # int64 (leaky burst; limit default)
    stamp: Optional[np.ndarray] = None  # int64 ms item UpdatedAt/CreatedAt


class Store:
    """Write-through hook interface (reference store.go:63-78). Subclass and
    pass to LocalEngine/daemon wiring. `on_change` fires after every dispatch
    with per-key stored state; `get_many` is consulted for fingerprints the
    device reported as cache misses (evicted/expired/restart-lost) — found
    rows are re-hydrated into the table and the decision re-applied against
    them (reference algorithms.go:45-51). `remove` exists for interface
    parity; the engine never calls it (expiry is lazy on-device)."""

    def on_change(self, change: ChangeSet) -> None:  # pragma: no cover
        pass

    def get_many(self, fps: np.ndarray, now_ms: int):  # pragma: no cover
        """Return None (no hydration) or a dict of parallel arrays over
        `fps`: {found: bool, algo, status, limit, remaining, reset_time,
        duration} — rows with found=False are ignored."""
        return None

    def remove(self, fp: int) -> None:  # pragma: no cover
        pass


class Loader:
    """Startup/shutdown snapshot interface (reference store.go:49-60)."""

    def load(self) -> Optional[np.ndarray]:  # pragma: no cover
        """Return table rows to restore, or None."""
        return None

    def save(self, rows: np.ndarray) -> None:  # pragma: no cover
        pass


class FileLoader(Loader):
    """Loader backed by a snapshot file — what GUBER_CHECKPOINT_PATH wires."""

    def __init__(self, path: str):
        self.path = path

    def load(self) -> Optional[np.ndarray]:
        if os.path.exists(self.path):
            return load_snapshot(self.path)
        return None

    def save(self, rows: np.ndarray) -> None:
        save_snapshot(self.path, rows)


class MemoryLoader(Loader):
    """In-memory Loader for tests/embedders (the MockLoader analog, reference
    store.go:80-109): `save()` keeps the snapshot on the instance; a new
    daemon restoring from it continues the old counts."""

    def __init__(self, rows: Optional[np.ndarray] = None):
        self.rows = rows
        self.load_called = 0
        self.save_called = 0

    def load(self) -> Optional[np.ndarray]:
        self.load_called += 1
        return self.rows

    def save(self, rows: np.ndarray) -> None:
        self.save_called += 1
        self.rows = rows


class RecordingStore(Store):
    """Write-through Store that records every ChangeSet (the MockStore
    analog, reference store.go:111-150)."""

    def __init__(self):
        self.changes: list = []

    def on_change(self, change: ChangeSet) -> None:
        self.changes.append(change)

    @property
    def touched_fps(self) -> set:
        return {int(fp) for c in self.changes for fp in c.fps}


class DictStore(Store):
    """Durable-store mock with the FULL reference contract (store.go:80-150):
    `on_change` writes per-key state through to a host dict, `get_many`
    serves it back for evicted/lost keys. Tests and embedders use this to
    exercise evict-then-rehydrate (reference store_test.go:127)."""

    def __init__(self):
        # fp → (algo, status, limit, remaining, reset, duration, burst, stamp)
        self.rows: dict = {}
        self.get_calls = 0
        self.hydrated = 0

    def on_change(self, change: ChangeSet) -> None:
        for i in range(change.fps.shape[0]):
            self.rows[int(change.fps[i])] = (
                int(change.algo[i]),
                int(change.status[i]),
                int(change.limit[i]),
                int(change.remaining[i]),
                int(change.reset_time[i]),
                int(change.duration[i]),
                int(change.burst[i]),
                int(change.stamp[i]),
            )

    def get_many(self, fps: np.ndarray, now_ms: int):
        self.get_calls += 1
        n = fps.shape[0]
        found = np.zeros(n, dtype=bool)
        cols = np.zeros((8, n), dtype=np.int64)
        for i in range(n):
            row = self.rows.get(int(fps[i]))
            if row is not None:
                found[i] = True
                cols[:, i] = row
        if not found.any():
            return None
        self.hydrated += int(found.sum())
        return dict(
            found=found,
            algo=cols[0].astype(np.int32),
            status=cols[1].astype(np.int32),
            limit=cols[2],
            remaining=cols[3],
            reset_time=cols[4],
            duration=cols[5],
            burst=cols[6],
            stamp=cols[7],
        )

    def remove(self, fp: int) -> None:
        self.rows.pop(int(fp), None)
