"""Persistence hooks: checkpoint snapshots + Loader/Store interfaces.

The reference never persists by default; `Loader` (startup/shutdown snapshot)
and `Store` (continuous write-through) are embedding hooks the server wires
when asked (reference store.go:49-78, workers.go:335-540). The TPU analogs:

* snapshot = ONE device→host DMA of the whole packed-row table (Table2.rows)
  written to disk; restore = one host→device put. The reference streams
  CacheItems one by one through channels; here the state array IS the cache,
  so checkpointing is a bulk array copy — structurally simpler and faster.
* Store = a host-side hook invoked with batch-level change sets after each
  dispatch (fingerprints only — the device holds state; embedders needing the
  full mapping keep their own key→fp index, since raw keys never reach the
  device by design, hashing.py).
"""

from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

SNAPSHOT_MAGIC = "GUBTPU1"


def save_snapshot(path: str, rows: np.ndarray) -> None:
    """Atomically write a table snapshot (tmp + rename, so a crash mid-write
    never leaves a torn file for the next boot)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".gubtpu-snap-")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, magic=np.frombuffer(
                SNAPSHOT_MAGIC.encode(), dtype=np.uint8
            ), rows=rows)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_snapshot(path: str) -> np.ndarray:
    with np.load(path) as z:
        magic = bytes(z["magic"]).decode()
        if magic != SNAPSHOT_MAGIC:
            raise ValueError(f"{path}: not a gubernator-tpu snapshot")
        return z["rows"]


@dataclass
class ChangeSet:
    """One dispatch's worth of state changes, host-visible form."""

    fps: np.ndarray  # int64 fingerprints touched
    created_at: int  # dispatch timestamp (ms)


class Store:
    """Write-through hook interface (reference store.go:63-78). Subclass and
    pass to LocalEngine/daemon wiring; `on_change` fires after every dispatch
    with the touched fingerprints. `get`/`remove` have no device analog —
    misses are resolved by the table itself — but exist for interface parity
    with embedders porting reference Store implementations."""

    def on_change(self, change: ChangeSet) -> None:  # pragma: no cover
        pass


class Loader:
    """Startup/shutdown snapshot interface (reference store.go:49-60)."""

    def load(self) -> Optional[np.ndarray]:  # pragma: no cover
        """Return table rows to restore, or None."""
        return None

    def save(self, rows: np.ndarray) -> None:  # pragma: no cover
        pass


class FileLoader(Loader):
    """Loader backed by a snapshot file — what GUBER_CHECKPOINT_PATH wires."""

    def __init__(self, path: str):
        self.path = path

    def load(self) -> Optional[np.ndarray]:
        if os.path.exists(self.path):
            return load_snapshot(self.path)
        return None

    def save(self, rows: np.ndarray) -> None:
        save_snapshot(self.path, rows)


class MemoryLoader(Loader):
    """In-memory Loader for tests/embedders (the MockLoader analog, reference
    store.go:80-109): `save()` keeps the snapshot on the instance; a new
    daemon restoring from it continues the old counts."""

    def __init__(self, rows: Optional[np.ndarray] = None):
        self.rows = rows
        self.load_called = 0
        self.save_called = 0

    def load(self) -> Optional[np.ndarray]:
        self.load_called += 1
        return self.rows

    def save(self, rows: np.ndarray) -> None:
        self.save_called += 1
        self.rows = rows


class RecordingStore(Store):
    """Write-through Store that records every ChangeSet (the MockStore
    analog, reference store.go:111-150)."""

    def __init__(self):
        self.changes: list = []

    def on_change(self, change: ChangeSet) -> None:
        self.changes.append(change)

    @property
    def touched_fps(self) -> set:
        return {int(fp) for c in self.changes for fp in c.fps}
