"""Persistence hooks: checkpoint snapshots + Loader/Store interfaces.

The reference never persists by default; `Loader` (startup/shutdown snapshot)
and `Store` (continuous write-through) are embedding hooks the server wires
when asked (reference store.go:49-78, workers.go:335-540). The TPU analogs:

* snapshot = ONE device→host DMA of the whole packed-row table (Table2.rows)
  written to disk; restore = one host→device put. The reference streams
  CacheItems one by one through channels; here the state array IS the cache,
  so checkpointing is a bulk array copy — structurally simpler and faster.
* Store = a host-side write-through hook with the reference's full contract
  (store.go:63-78, algorithms.go:45-51): after every dispatch `on_change`
  receives the per-key stored state (algo/status/limit/remaining/reset/
  duration — the same schema UpdatePeerGlobals installs from), and on a
  device-reported cache miss the engine consults `get_many` and re-hydrates
  found entries into the table before the decision stands — so evicted or
  restart-lost items warm back from a durable store exactly like the
  reference's `Store.Get` path. Keys are fingerprints (raw keys never reach
  the device, hashing.py); embedders mapping back to names keep a key→fp
  index.
"""

from __future__ import annotations

import os
import struct
import tempfile
import zlib
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

import numpy as np

SNAPSHOT_MAGIC = "GUBTPU1"


def save_snapshot(path: str, rows: np.ndarray, epoch: int = 0,
                  layout_name: str = "full") -> None:
    """Atomically write a table snapshot (tmp + rename, so a crash mid-write
    never leaves a torn file for the next boot). `epoch` records the last
    checkpoint epoch the snapshot includes (0 on the classic full-snapshot
    path) so warm restart can skip already-compacted delta frames.
    `layout_name` records the slot layout the rows bytes are in
    (ops/layout.py) — "full" writes a file byte-identical to the
    pre-layout format."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".gubtpu-snap-")
    try:
        with os.fdopen(fd, "wb") as f:
            extra = {}
            if layout_name != "full":
                # only non-default layouts write the key: full snapshots
                # stay byte-identical to every pre-layout file
                extra["layout"] = np.frombuffer(
                    layout_name.encode(), dtype=np.uint8
                )
            np.savez_compressed(f, magic=np.frombuffer(
                SNAPSHOT_MAGIC.encode(), dtype=np.uint8
            ), rows=rows, epoch=np.int64(epoch), **extra)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_snapshot(path: str) -> np.ndarray:
    with np.load(path) as z:
        magic = bytes(z["magic"]).decode()
        if magic != SNAPSHOT_MAGIC:
            raise ValueError(f"{path}: not a gubernator-tpu snapshot")
        return z["rows"]


def load_snapshot_meta(path: str) -> "Tuple[np.ndarray, int, str]":
    """(rows, epoch, layout_name) — epoch is 0 and layout "full" for
    snapshots written before the respective planes existed."""
    with np.load(path) as z:
        magic = bytes(z["magic"]).decode()
        if magic != SNAPSHOT_MAGIC:
            raise ValueError(f"{path}: not a gubernator-tpu snapshot")
        epoch = int(z["epoch"]) if "epoch" in z.files else 0
        layout = (
            bytes(z["layout"]).decode() if "layout" in z.files else "full"
        )
        return z["rows"], epoch, layout


# ------------------------------------------------------------- delta log
#
# The incremental-checkpoint append log (docs/durability.md): CRC-framed
# packed slot rows — the table's own (N, F) int32 slot-field layout, the
# same raw-LE buffer format the TransferState handoff wire uses — appended
# beside the base snapshot by service/checkpoint.CheckpointManager. Warm
# restart replays base + frames through kernel2.merge2 (remaining=min,
# expiry=max, OVER sticks), so a torn tail, a duplicated frame, or a crash
# between compaction steps can only UNDER-grant, never over-grant.

DELTA_LOG_MAGIC = b"GUBTPUDL"  # 8-byte file header
FRAME_MAGIC = 0x46445547  # "GUDF" little-endian
# frame version doubles as the SLOT-LAYOUT byte: version = 1 + layout.code
# (ops/layout.py), so a full-layout frame is version 1 — byte-identical to
# every log written before packed layouts existed — and a reader that
# predates a layout refuses its frames (scan stops at the unknown version,
# the conservative prefix rule) instead of misparsing the rows.
FRAME_VERSION = 1
# frame header: magic u32, version u32, n_rows u32, epoch i64, now_ms i64,
# payload crc32 u32
_FRAME_HEADER = struct.Struct("<IIIqqI")
_SLOT_FIELDS = 16  # full-layout fields/row (VERSION 1); packed versions
# derive theirs from the layout registry

# TOMBSTONE frames (hot-set tiering, docs/tiering.md): a demote-on-idle
# removes a live row from HBM after shadowing it — without a removal
# record, warm-restart replay of an OLDER state frame would resurrect the
# row (harmless for admission — the resurrected bytes equal the shadowed
# copy and the fault-back merge is idempotent — but it silently undoes the
# demotion's capacity win and double-homes the state). A tombstone frame
# carries just the removed fingerprints ((N, 2) int32 lo/hi rows) and
# replays as tombstone_fps IN FILE ORDER, so state-frame → tombstone →
# later-state sequences resolve exactly. The version byte lives in its own
# range (0x40) — a pre-tiering reader stops its scan at the unknown
# version (the conservative prefix rule) instead of misparsing 8 B rows as
# 64 B slots.
TOMBSTONE_FRAME_VERSION = 0x40
_TOMBSTONE_FIELDS = 2


class _TombstoneKind:
    """Sentinel standing in the DeltaScan frame tuple's layout position
    for tombstone frames (the 4-tuple shape every consumer already
    unpacks stays intact; replay branches on identity)."""

    name = "tombstone"

    def __repr__(self):  # pragma: no cover - debugging nicety
        return "<tombstone-frame>"


TOMBSTONE = _TombstoneKind()


def _frame_layout(version: int):
    from gubernator_tpu.ops.layout import layout_by_code

    if version == TOMBSTONE_FRAME_VERSION:
        return TOMBSTONE
    return layout_by_code(version - 1)


def fps_from_slots(slots: np.ndarray) -> np.ndarray:
    """Fingerprints encoded in packed slot rows (fields FP_LO/FP_HI — the
    0/1 position is a cross-layout invariant, ops/layout.py) — the reason
    delta frames need no separate fp column."""
    from gubernator_tpu.ops.table2 import FP_HI, FP_LO

    lo = slots[:, FP_LO].astype(np.int64) & 0xFFFFFFFF
    hi = slots[:, FP_HI].astype(np.int64)
    return (hi << 32) | lo


def encode_delta_frame(epoch: int, now_ms: int, slots: np.ndarray,
                       layout=None) -> bytes:
    """One CRC-framed delta: header + raw little-endian (N, F_layout) int32
    slot rows — live rows of dirty blocks only, vs the base snapshot's
    every-slot-of-every-bucket. 64 B/row under the full layout, 32 B/row
    under the packed ones (the frame's version byte carries the layout)."""
    if layout is None:
        from gubernator_tpu.ops.layout import FULL

        if slots.shape[1] != FULL.F:
            raise ValueError(
                "packed slot rows need an explicit layout for framing"
            )
        layout = FULL
    if slots.shape[1] != layout.F:
        raise ValueError(
            f"slot rows are {slots.shape[1]} fields wide but layout "
            f"{layout.name} has {layout.F}"
        )
    payload = np.ascontiguousarray(slots, dtype="<i4").tobytes()
    header = _FRAME_HEADER.pack(
        FRAME_MAGIC, 1 + layout.code, slots.shape[0], epoch, now_ms,
        zlib.crc32(payload),
    )
    return header + payload


def encode_tombstone_frame(epoch: int, now_ms: int,
                           fps: np.ndarray) -> bytes:
    """One CRC-framed tombstone record: removed fingerprints as (N, 2)
    int32 lo/hi rows under the dedicated version byte (see
    TOMBSTONE_FRAME_VERSION)."""
    fps = np.asarray(fps, dtype=np.int64)
    rows = np.empty((fps.shape[0], _TOMBSTONE_FIELDS), dtype=np.int32)
    lo = fps & 0xFFFFFFFF
    rows[:, 0] = np.where(lo >= (1 << 31), lo - (1 << 32), lo).astype(
        np.int32
    )
    rows[:, 1] = (fps >> 32).astype(np.int32)
    payload = np.ascontiguousarray(rows, dtype="<i4").tobytes()
    header = _FRAME_HEADER.pack(
        FRAME_MAGIC, TOMBSTONE_FRAME_VERSION, rows.shape[0], epoch, now_ms,
        zlib.crc32(payload),
    )
    return header + payload


class DeltaScan:
    """Result of reading a delta log: the valid frame prefix plus what (if
    anything) was skipped. A torn tail (crash mid-append) or a corrupt
    frame stops the scan — replaying a prefix is always safe under merge2
    semantics, while resynchronizing past a corrupt length field is not."""

    def __init__(self):
        # (epoch, now_ms, slots, layout) — slots in the frame's own
        # layout; tombstone frames carry (N, 2) fp rows with the
        # TOMBSTONE sentinel in the layout position
        self.frames: List[Tuple[int, int, np.ndarray, object]] = []
        self.skipped_bytes = 0
        self.clean_bytes = 0  # file prefix (log header + clean frames)
        self.error: Optional[str] = None

    @property
    def rows(self) -> int:
        return sum(f[2].shape[0] for f in self.frames)


def read_delta_frames(path: str) -> DeltaScan:
    """Scan a delta log: every complete, CRC-clean frame in order. Never
    raises on damage — a truncated or corrupt tail is recorded on the
    returned DeltaScan and the clean prefix is still usable."""
    scan = DeltaScan()
    if not os.path.exists(path):
        return scan
    with open(path, "rb") as f:
        head = f.read(len(DELTA_LOG_MAGIC))
        if head != DELTA_LOG_MAGIC:
            scan.error = "bad delta-log header"
            scan.skipped_bytes = os.path.getsize(path)
            return scan
        while True:
            pos = f.tell()
            scan.clean_bytes = pos
            hdr = f.read(_FRAME_HEADER.size)
            if not hdr:
                break  # clean end
            if len(hdr) < _FRAME_HEADER.size:
                scan.error = "truncated frame header"
                scan.skipped_bytes = os.path.getsize(path) - pos
                break
            magic, version, n_rows, epoch, now_ms, crc = _FRAME_HEADER.unpack(hdr)
            if magic != FRAME_MAGIC:
                scan.error = f"bad frame magic at offset {pos}"
                scan.skipped_bytes = os.path.getsize(path) - pos
                break
            try:
                layout = _frame_layout(version)
            except ValueError:
                scan.error = f"unknown frame version {version} at offset {pos}"
                scan.skipped_bytes = os.path.getsize(path) - pos
                break
            fields = (
                _TOMBSTONE_FIELDS if layout is TOMBSTONE else layout.F
            )
            payload = f.read(n_rows * fields * 4)
            if len(payload) < n_rows * fields * 4:
                scan.error = "truncated frame payload"
                scan.skipped_bytes = os.path.getsize(path) - pos
                break
            if zlib.crc32(payload) != crc:
                scan.error = f"frame CRC mismatch at offset {pos}"
                scan.skipped_bytes = os.path.getsize(path) - pos
                break
            slots = np.frombuffer(payload, dtype="<i4").reshape(
                n_rows, fields
            ).astype(np.int32)
            scan.frames.append((epoch, now_ms, slots, layout))
    return scan


class DeltaLog:
    """Append-only delta-frame log beside the base snapshot.

    `append` opens/writes/fsyncs per call (checkpoint cadence, not request
    cadence); `reset` atomically replaces the file with an empty header —
    compaction writes the new base FIRST (atomic rename), so a crash
    between the two steps leaves old deltas atop a newer base, which the
    conservative replay merge renders harmless (and the epoch filter skips
    outright)."""

    def __init__(self, path: str):
        self.path = path

    def append(self, epoch: int, now_ms: int, slots: np.ndarray,
               layout=None) -> int:
        """Append one frame; returns bytes written (header included).
        `layout` tags the slot rows' layout (full inferred for 16-field
        rows)."""
        frame = encode_delta_frame(epoch, now_ms, slots, layout=layout)
        fresh = not os.path.exists(self.path) or (
            os.path.getsize(self.path) == 0
        )
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        os.makedirs(d, exist_ok=True)
        with open(self.path, "ab") as f:
            if fresh:
                f.write(DELTA_LOG_MAGIC)
            f.write(frame)
            f.flush()
            os.fsync(f.fileno())
        return len(frame) + (len(DELTA_LOG_MAGIC) if fresh else 0)

    def append_tombstones(self, epoch: int, now_ms: int,
                          fps: np.ndarray) -> int:
        """Append one tombstone frame (demote-on-idle removals — see
        TOMBSTONE_FRAME_VERSION). Returns bytes written."""
        frame = encode_tombstone_frame(epoch, now_ms, fps)
        fresh = not os.path.exists(self.path) or (
            os.path.getsize(self.path) == 0
        )
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        os.makedirs(d, exist_ok=True)
        with open(self.path, "ab") as f:
            if fresh:
                f.write(DELTA_LOG_MAGIC)
            f.write(frame)
            f.flush()
            os.fsync(f.fileno())
        return len(frame) + (len(DELTA_LOG_MAGIC) if fresh else 0)

    def scan(self) -> DeltaScan:
        return read_delta_frames(self.path)

    def repair(self, scan: DeltaScan) -> None:
        """Truncate a damaged log to `scan`'s clean prefix, fsynced.

        Appends land at the physical end of the file, but the scan stops
        at the first bad frame — so without this, every frame written
        after a torn tail sits behind the damage where no replay can
        reach it until the next compaction. restore() repairs before
        serving so subsequent appends extend a scannable log. A prefix
        with no usable log header rewrites the log empty (atomically)
        instead."""
        if scan.skipped_bytes <= 0 or not os.path.exists(self.path):
            return
        if scan.clean_bytes < len(DELTA_LOG_MAGIC):
            self.reset()
            return
        with open(self.path, "r+b") as f:
            f.truncate(scan.clean_bytes)
            f.flush()
            os.fsync(f.fileno())

    def reset(self) -> None:
        """Truncate to an empty log (post-compaction), atomically."""
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".gubtpu-delta-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(DELTA_LOG_MAGIC)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def size_bytes(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def frame_count(self) -> int:
        return len(self.scan().frames)


@dataclass
class ChangeSet:
    """One dispatch's worth of state changes: parallel per-key arrays (one
    row per unique fingerprint, the LAST occurrence's state when a batch hits
    a key several times). The schema matches UpdatePeerGlobals installs —
    sufficient to reconstruct the item (reference store.go:29-43)."""

    fps: np.ndarray  # int64 fingerprints touched
    created_at: int  # dispatch timestamp (ms)
    algo: Optional[np.ndarray] = None  # int32 Algorithm per row
    status: Optional[np.ndarray] = None  # int32 UNDER/OVER_LIMIT
    limit: Optional[np.ndarray] = None  # int64
    remaining: Optional[np.ndarray] = None  # int64
    reset_time: Optional[np.ndarray] = None  # int64 ms
    duration: Optional[np.ndarray] = None  # int64 ms
    burst: Optional[np.ndarray] = None  # int64 (leaky burst; limit default)
    stamp: Optional[np.ndarray] = None  # int64 ms item UpdatedAt/CreatedAt


class Store:
    """Write-through hook interface (reference store.go:63-78). Subclass and
    pass to LocalEngine/daemon wiring. `on_change` fires after every dispatch
    with per-key stored state; `get_many` is consulted for fingerprints the
    device reported as cache misses (evicted/expired/restart-lost) — found
    rows are re-hydrated into the table and the decision re-applied against
    them (reference algorithms.go:45-51). `remove` exists for interface
    parity; the engine never calls it (expiry is lazy on-device)."""

    def on_change(self, change: ChangeSet) -> None:  # pragma: no cover
        pass

    def get_many(self, fps: np.ndarray, now_ms: int):  # pragma: no cover
        """Return None (no hydration) or a dict of parallel arrays over
        `fps`: {found: bool, algo, status, limit, remaining, reset_time,
        duration} — rows with found=False are ignored."""
        return None

    def remove(self, fp: int) -> None:  # pragma: no cover
        pass


class Loader:
    """Startup/shutdown snapshot interface (reference store.go:49-60)."""

    def load(self) -> Optional[np.ndarray]:  # pragma: no cover
        """Return table rows to restore, or None."""
        return None

    def save(self, rows: np.ndarray) -> None:  # pragma: no cover
        pass


class FileLoader(Loader):
    """Loader backed by a snapshot file — what GUBER_CHECKPOINT_PATH wires."""

    def __init__(self, path: str):
        self.path = path

    def load(self) -> Optional[np.ndarray]:
        if os.path.exists(self.path):
            return load_snapshot(self.path)
        return None

    def save(self, rows: np.ndarray, layout_name: str = "full") -> None:
        save_snapshot(self.path, rows, layout_name=layout_name)


class MemoryLoader(Loader):
    """In-memory Loader for tests/embedders (the MockLoader analog, reference
    store.go:80-109): `save()` keeps the snapshot on the instance; a new
    daemon restoring from it continues the old counts."""

    def __init__(self, rows: Optional[np.ndarray] = None):
        self.rows = rows
        self.load_called = 0
        self.save_called = 0

    def load(self) -> Optional[np.ndarray]:
        self.load_called += 1
        return self.rows

    def save(self, rows: np.ndarray) -> None:
        self.save_called += 1
        self.rows = rows


class RecordingStore(Store):
    """Write-through Store that records every ChangeSet (the MockStore
    analog, reference store.go:111-150)."""

    def __init__(self):
        self.changes: list = []

    def on_change(self, change: ChangeSet) -> None:
        self.changes.append(change)

    @property
    def touched_fps(self) -> set:
        return {int(fp) for c in self.changes for fp in c.fps}


class DictStore(Store):
    """Durable-store mock with the FULL reference contract (store.go:80-150):
    `on_change` writes per-key state through to a host dict, `get_many`
    serves it back for evicted/lost keys. Tests and embedders use this to
    exercise evict-then-rehydrate (reference store_test.go:127)."""

    def __init__(self):
        # fp → (algo, status, limit, remaining, reset, duration, burst, stamp)
        self.rows: dict = {}
        self.get_calls = 0
        self.hydrated = 0

    def on_change(self, change: ChangeSet) -> None:
        for i in range(change.fps.shape[0]):
            self.rows[int(change.fps[i])] = (
                int(change.algo[i]),
                int(change.status[i]),
                int(change.limit[i]),
                int(change.remaining[i]),
                int(change.reset_time[i]),
                int(change.duration[i]),
                int(change.burst[i]),
                int(change.stamp[i]),
            )

    def get_many(self, fps: np.ndarray, now_ms: int):
        self.get_calls += 1
        n = fps.shape[0]
        found = np.zeros(n, dtype=bool)
        cols = np.zeros((8, n), dtype=np.int64)
        for i in range(n):
            row = self.rows.get(int(fps[i]))
            if row is not None:
                found[i] = True
                cols[:, i] = row
        if not found.any():
            return None
        self.hydrated += int(found.sum())
        return dict(
            found=found,
            algo=cols[0].astype(np.int32),
            status=cols[1].astype(np.int32),
            limit=cols[2],
            remaining=cols[3],
            reset_time=cols[4],
            duration=cols[5],
            burst=cols[6],
            stamp=cols[7],
        )

    def remove(self, fp: int) -> None:
        self.rows.pop(int(fp), None)
