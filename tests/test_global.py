"""GLOBAL-behavior convergence tests — the analog of the reference's
TestGlobalBehavior suite (functional_test.go:1760-2167), which asserts exact
broadcast/update counts via metrics scraping and verifies every peer converges
to the same remaining."""

import numpy as np
import pytest

import jax

from gubernator_tpu.parallel import make_mesh
from gubernator_tpu.parallel.global_sync import GlobalShardedEngine
from gubernator_tpu.parallel.mesh import shard_of
from gubernator_tpu.hashing import fingerprint
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest, Status, MINUTE


def greq(key, hits=1, limit=100, behavior=Behavior.GLOBAL, created_at=None,
         algorithm=Algorithm.TOKEN_BUCKET):
    return RateLimitRequest(
        name="glob", unique_key=key, hits=hits, limit=limit, duration=MINUTE,
        algorithm=algorithm, behavior=behavior, created_at=created_at,
    )


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def owner_of(key: str, n: int = 8) -> int:
    return int(shard_of(np.array([fingerprint("glob", key)], dtype=np.int64), n)[0])


def non_owner_of(key: str, n: int = 8) -> int:
    return (owner_of(key, n) + 1) % n


def test_global_hits_flow_to_owner_and_broadcast_back(mesh, frozen_now):
    eng = GlobalShardedEngine(mesh, capacity_per_shard=1024, sync_out=64)
    t = frozen_now
    key = "gk1"
    home = non_owner_of(key)

    # 5 hits arrive at a NON-owner: answered locally, queued for the owner
    for i in range(5):
        (r,) = eng.check([greq(key, created_at=t)], now_ms=t, home_shard=home)
        assert r.status == Status.UNDER_LIMIT
    assert eng.global_stats.hits_queued == 5
    assert eng.global_stats.send_queue_length == 1  # aggregated per key

    # sync tick: owner applies the aggregated 5 hits, broadcasts to replicas
    eng.sync(now_ms=t)
    assert eng.global_stats.sync_rounds == 1
    assert eng.global_stats.broadcasts_applied == 1
    assert eng.global_stats.updates_installed == 7  # every non-owner installs
    assert eng.global_stats.send_queue_length == 0

    # the authoritative state on the owner reflects all 5 hits: a zero-hit
    # probe routed through the normal (owner) path reports remaining 95
    (r,) = eng.check([greq(key, hits=0, behavior=0, created_at=t)], now_ms=t)
    assert r.remaining == 95

    # every replica converges: a GLOBAL read at ANY home shard sees 95
    for home2 in range(8):
        (r,) = eng.check([greq(key, hits=0, created_at=t)], now_ms=t, home_shard=home2)
        assert r.remaining == 95, f"replica at shard {home2} did not converge"


def test_global_over_limit_converges(mesh, frozen_now):
    # reference TestGlobalRateLimitsPeerOverLimit (functional_test.go:1094):
    # spend within the limit, sync, then over-ask — the owner applies the
    # accumulated hits with DRAIN_OVER_LIMIT forced (gubernator.go:526-532)
    eng = GlobalShardedEngine(mesh, capacity_per_shard=1024, sync_out=64)
    t = frozen_now
    key = "gk-over"
    home = non_owner_of(key)
    (r,) = eng.check([greq(key, hits=3, limit=5, created_at=t)], now_ms=t,
                     home_shard=home)
    assert r.remaining == 2 and r.status == Status.UNDER_LIMIT
    eng.sync(now_ms=t)
    # replica over-ask: rejected locally without consuming, hits still queued
    (r,) = eng.check([greq(key, hits=3, limit=5, created_at=t)], now_ms=t,
                     home_shard=home)
    assert r.status == Status.OVER_LIMIT and r.remaining == 2
    eng.sync(now_ms=t)
    # owner applied 3 > 2 with DRAIN forced → drained to 0, everywhere
    for home2 in range(8):
        (r,) = eng.check([greq(key, hits=0, limit=5, created_at=t)], now_ms=t,
                         home_shard=home2)
        assert r.remaining == 0, f"shard {home2}"
    (r,) = eng.check([greq(key, hits=1, limit=5, created_at=t)], now_ms=t,
                     home_shard=home)
    assert r.status == Status.OVER_LIMIT


def test_global_hits_from_multiple_homes_aggregate(mesh, frozen_now):
    eng = GlobalShardedEngine(mesh, capacity_per_shard=1024, sync_out=64)
    t = frozen_now
    key = "gk-multi"
    # hits land on several different non-owner homes before one sync
    homes = [h for h in range(8) if h != owner_of(key)][:4]
    for h in homes:
        eng.check([greq(key, hits=2, created_at=t)], now_ms=t, home_shard=h)
    eng.sync(now_ms=t)
    # owner must have applied 4 homes x 2 hits = 8
    (r,) = eng.check([greq(key, hits=0, behavior=0, created_at=t)], now_ms=t)
    assert r.remaining == 92


def test_global_leaky_bucket(mesh, frozen_now):
    eng = GlobalShardedEngine(mesh, capacity_per_shard=1024, sync_out=64)
    t = frozen_now
    key = "gk-leaky"
    home = non_owner_of(key)
    (r,) = eng.check(
        [greq(key, hits=4, limit=10, algorithm=Algorithm.LEAKY_BUCKET, created_at=t)],
        now_ms=t, home_shard=home,
    )
    assert r.remaining == 6
    eng.sync(now_ms=t)
    for home2 in range(8):
        (r,) = eng.check(
            [greq(key, hits=0, limit=10, algorithm=Algorithm.LEAKY_BUCKET,
                  created_at=t)],
            now_ms=t, home_shard=home2,
        )
        assert r.remaining == 6


def test_zero_hit_global_not_queued(mesh, frozen_now):
    # reference global.go:85-89: Hits == 0 is never queued
    eng = GlobalShardedEngine(mesh, capacity_per_shard=1024, sync_out=64)
    t = frozen_now
    eng.check([greq("gk-z", hits=0, created_at=t)], now_ms=t, home_shard=1)
    assert eng.global_stats.hits_queued == 0
    assert eng.global_stats.send_queue_length == 0


def test_mixed_global_and_plain(mesh, frozen_now):
    eng = GlobalShardedEngine(mesh, capacity_per_shard=1024, sync_out=64)
    t = frozen_now
    out = eng.check(
        [greq("gm1", created_at=t),
         RateLimitRequest(name="glob", unique_key="plain1", hits=1, limit=7,
                          duration=MINUTE, created_at=t),
         greq("gm2", created_at=t)],
        now_ms=t, home_shard=2,
    )
    assert out[0].remaining == 99
    assert out[1].remaining == 6
    assert out[2].remaining == 99


def test_pipelined_hooks_match_serial_path(mesh, frozen_now):
    """The prepare/issue/finish hooks (the pipelined front-door path for
    GLOBAL batches — replaces round 4's can_pipeline veto) must produce the
    same responses, queue state, and counters as the serial check_columns
    on a twin engine, for a mixed GLOBAL + plain batch with duplicates."""
    from gubernator_tpu.ops.batch import columns_from_requests
    from gubernator_tpu.ops.engine import (
        finish_check_columns,
        issue_check_columns,
        prepare_check_columns,
    )

    t = frozen_now
    reqs = (
        [greq(f"pk{i}", behavior=0, created_at=t) for i in range(4)]
        + [greq(f"gk{i}", created_at=t) for i in range(6)]
        + [greq("gk0", hits=2, created_at=t)]  # duplicate GLOBAL key
        + [greq("pk0", behavior=0, created_at=t)]  # duplicate plain key
    )
    cols = columns_from_requests(reqs)

    serial = GlobalShardedEngine(mesh, capacity_per_shard=1024, sync_out=64)
    rc_serial = serial.check_columns(cols, now_ms=t)

    piped = GlobalShardedEngine(mesh, capacity_per_shard=1024, sync_out=64)
    pending = prepare_check_columns(piped, cols, now_ms=t)
    from gubernator_tpu.parallel.global_sync import GlobalPending

    assert isinstance(pending, GlobalPending)  # GLOBAL rows → custom pending
    pending = issue_check_columns(piped, pending)
    rc_piped, delta = finish_check_columns(piped, pending, lambda fn: fn())
    piped.stats.merge(delta)

    np.testing.assert_array_equal(rc_piped.status, rc_serial.status)
    np.testing.assert_array_equal(rc_piped.remaining, rc_serial.remaining)
    np.testing.assert_array_equal(rc_piped.reset_time, rc_serial.reset_time)
    np.testing.assert_array_equal(rc_piped.err, rc_serial.err)

    # queue state equal: same homes, same per-key accumulated hits
    for ps, pp in zip(serial.pending, piped.pending):
        assert len(ps) == len(pp)
        if len(ps):
            np.testing.assert_array_equal(
                np.sort(ps.hb.fp), np.sort(pp.hb.fp)
            )
            order_s, order_p = np.argsort(ps.hb.fp), np.argsort(pp.hb.fp)
            np.testing.assert_array_equal(
                ps.hits[order_s], pp.hits[order_p]
            )
    assert serial.global_stats.hits_queued == piped.global_stats.hits_queued
    assert serial.stats.cache_hits == piped.stats.cache_hits
    assert serial.stats.cache_misses == piped.stats.cache_misses
    assert serial.stats.checks == piped.stats.checks

    # both sides reconcile identically at the next sync tick
    serial.sync(now_ms=t)
    piped.sync(now_ms=t)
    assert (
        serial.global_stats.broadcasts_applied
        == piped.global_stats.broadcasts_applied
    )
    assert (
        serial.global_stats.updates_installed
        == piped.global_stats.updates_installed
    )


def test_pipelined_hooks_pure_local_falls_through(mesh, frozen_now):
    """Batches without GLOBAL rows return None from prepare_columns and ride
    the generic pipelined path."""
    from gubernator_tpu.ops.batch import columns_from_requests
    from gubernator_tpu.ops.engine import PendingCheck, prepare_check_columns

    eng = GlobalShardedEngine(mesh, capacity_per_shard=1024)
    cols = columns_from_requests(
        [greq(f"k{i}", behavior=0, created_at=frozen_now) for i in range(4)]
    )
    pending = prepare_check_columns(eng, cols, now_ms=frozen_now)
    assert isinstance(pending, PendingCheck)


def test_fused_sync_drain_matches_serial_rounds(mesh, frozen_now):
    """A deep backlog drains through the fused multi-round step (ONE launch
    runs R rounds on-device); tables, replica state, and reconcile counters
    must match an identical engine drained round-by-round."""
    import jax.numpy as jnp

    from gubernator_tpu.ops.batch import columns_from_requests

    t = frozen_now

    def load(eng):
        # queue 3x sync_out entries per round-robin home → multi-round drain
        for batch in range(3):
            reqs = [
                greq(f"fk{batch}_{i}", hits=2, created_at=t) for i in range(64)
            ]
            eng.check_columns(columns_from_requests(reqs), now_ms=t)

    serial = GlobalShardedEngine(mesh, capacity_per_shard=1024, sync_out=16)
    fused = GlobalShardedEngine(mesh, capacity_per_shard=1024, sync_out=16)
    load(serial)
    load(fused)
    assert serial.global_stats.send_queue_length == \
        fused.global_stats.send_queue_length > 16

    # serial: force round-by-round; fused: the sync() fast path
    while serial.has_pending():
        serial._sync_round(now_ms=t)
    fused.sync(now_ms=t)

    assert not fused.has_pending()
    # padded no-op rounds are excluded from the counter: identical traffic
    # reports identical sync_rounds whichever drain path ran
    assert serial.global_stats.sync_rounds == fused.global_stats.sync_rounds
    assert (
        serial.global_stats.broadcasts_applied
        == fused.global_stats.broadcasts_applied
    )
    assert (
        serial.global_stats.updates_installed
        == fused.global_stats.updates_installed
    )
    assert bool(jnp.array_equal(serial.table.rows, fused.table.rows))
    assert bool(jnp.array_equal(serial.replica.rows, fused.replica.rows))

    # post-drain responses agree from any home (replica-served reads)
    probe = [greq("fk1_3", hits=0, created_at=t)]
    for home in range(8):
        (a,) = serial.check(probe, now_ms=t, home_shard=home)
        (b,) = fused.check(probe, now_ms=t, home_shard=home)
        assert (a.status, a.remaining) == (b.status, b.remaining)


def test_warm_sync_steps_pretraces_fused_variants(mesh, frozen_now):
    """warm_sync_steps compiles the single-round + every fused-R sync step
    with empty no-op outboxes, leaving state and counters untouched after
    the caller's reset — the first deep backlog must not compile on the
    serving path."""
    from gubernator_tpu.parallel.global_sync import GlobalStats

    eng = GlobalShardedEngine(mesh, capacity_per_shard=1024, sync_out=16)
    eng.warm_sync_steps(now_ms=frozen_now)
    # fused steps key by (rounds, compact-wire?); compact engines warm BOTH
    # outbox formats per R, full-width ones just their own — either way
    # every R variant must be pre-traced
    assert sorted({r for r, _w in eng._sync_multi}) == [2, 4, 8, 16, 32, 64]
    eng.global_stats = GlobalStats()

    # a warm engine still reconciles correctly (state untouched by no-ops)
    key = "wk1"
    home = non_owner_of(key)
    for _ in range(3):
        eng.check([greq(key, created_at=frozen_now)], now_ms=frozen_now,
                  home_shard=home)
    eng.sync(now_ms=frozen_now)
    assert eng.global_stats.broadcasts_applied == 1
    (r,) = eng.check(
        [greq(key, hits=0, created_at=frozen_now)], now_ms=frozen_now,
        home_shard=owner_of(key),
    )
    assert r.remaining == 97


def test_store_engine_sync_stays_serial(mesh, frozen_now):
    """Store-configured engines must drain round-by-round: the fused step
    returns no per-round bc, and the Store write-through depends on it —
    every reconciled entry must reach on_change even on a deep backlog."""
    from gubernator_tpu.ops.batch import columns_from_requests
    from gubernator_tpu.store import RecordingStore

    t = frozen_now
    store = RecordingStore()
    eng = GlobalShardedEngine(
        mesh, capacity_per_shard=1024, sync_out=16, store=store
    )
    # queue a backlog deeper than one round per home
    for batch in range(3):
        reqs = [greq(f"sk{batch}_{i}", hits=1, created_at=t) for i in range(64)]
        eng.check_columns(columns_from_requests(reqs), now_ms=t)
    # the fused-vs-serial choice keys on PER-HOME depth, not the global sum
    assert max(len(p) for p in eng.pending) > eng.sync_out
    # check-time deliveries (owner-here rows write through immediately,
    # like the reference's owner-side getLocalRateLimit OnChange)
    n_check = sum(len(ch.fps) for ch in store.changes)
    eng.sync(now_ms=t)
    assert not eng.has_pending()
    assert not eng._sync_multi  # fused variants never built
    # the sync drain delivers every reconciled entry EXACTLY once via the
    # per-round bc — the raw count catches double deliveries the set alone
    # would hide (owner-here keys legitimately appear a second time: their
    # check-time apply was its own state change)
    synced_fps = [
        fp for ch in store.changes for fp in np.asarray(ch.fps).tolist()
    ][n_check:]
    assert len(synced_fps) == 192
    assert len(set(synced_fps)) == 192
    assert store.touched_fps >= set(synced_fps)


def test_sync_launch_failure_requeues_hits_and_poisons(mesh, frozen_now):
    """A collective sync launch that dies AFTER the accumulators were popped
    must not lose the hits (ADVICE r5): the popped boxes re-merge into
    pending, and the engine is marked poisoned so health surfaces unhealthy
    instead of serving from the donated (now-suspect) tables."""
    eng = GlobalShardedEngine(mesh, capacity_per_shard=1024, sync_out=64)
    t = frozen_now
    for i in range(6):
        eng.check([greq(f"rq{i}", hits=2, created_at=t)], now_ms=t,
                  home_shard=i % 8)
    queued_before = eng.global_stats.send_queue_length
    assert queued_before == 6
    # per-home breakdown must survive the failure round-trip exactly
    pending_before = [len(p) for p in eng.pending]
    per_key_hits = {
        int(fp): int(h)
        for p in eng.pending if len(p)
        for fp, h in zip(p.hb.fp, p.hits)
    }

    eng._ensure_global_plane()

    class Boom(RuntimeError):
        pass

    def dead_step(*_a, **_k):
        raise Boom("donated launch died")

    # stub BOTH outbox formats: which one the round takes depends on the
    # engine's wire mode (compact ships the int32 grid step)
    eng._sync_step = dead_step
    eng._sync_step_wire = dead_step
    with pytest.raises(Boom):
        eng._sync_round(now_ms=t)

    assert [len(p) for p in eng.pending] == pending_before
    assert eng.global_stats.send_queue_length == queued_before
    after = {
        int(fp): int(h)
        for p in eng.pending if len(p)
        for fp, h in zip(p.hb.fp, p.hits)
    }
    assert after == per_key_hits
    assert eng.poisoned is not None and "sync" in eng.poisoned

    # a healthy step afterwards drains the re-merged hits (fresh engine
    # state validates the re-merge kept well-formed columns)
    eng._sync_step = None
    eng._sync_step_wire = None
    eng._ensure_global_plane()
    eng.sync(now_ms=t)
    assert eng.global_stats.send_queue_length == 0
    assert eng.global_stats.broadcasts_applied == 6
