"""route="device" / dedup="device" parity suite on the 8-device CPU mesh.

The TPU serving default (arrival-order rows + on-mesh a2a exchange +
in-trace duplicate aggregation) must be semantically interchangeable with
the host-planned paths it replaces:

* dedup="device" ≍ the host planner's aggregate-everything plan
  (plan_passes with max_exact=1 — the reference's GLOBAL hot-key
  aggregation, global.go:109-123) for responses, live state, and stats;
* route="device" ≍ route="host" under either dedup mode, including
  Zipf-skewed batches that force per-pair exchange overflow (retries +
  terminal host fallback);
* the GLOBAL owner/replica fork (GlobalShardedEngine) behaves identically
  whichever side of the mesh does routing and dedup.

Tables are compared CANONICALLY (slots sorted within each bucket): lane
assignment follows batch row order, and the dedup paths legitimately place
a key's carrier at a different row position than the host oracle — slot
order inside a bucket is internal state, not an API surface.
"""

import numpy as np
import pytest

import jax

from gubernator_tpu.ops.batch import columns_from_requests
from gubernator_tpu.parallel import ShardedEngine, make_mesh
from gubernator_tpu.parallel.global_sync import GlobalShardedEngine
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest, MINUTE


def req(key, hits=1, limit=100, duration=MINUTE,
        algorithm=Algorithm.TOKEN_BUCKET, behavior=Behavior.BATCHING,
        created_at=None):
    return RateLimitRequest(
        name="rd", unique_key=key, hits=hits, limit=limit, duration=duration,
        algorithm=algorithm, behavior=behavior, created_at=created_at,
    )


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "tests require the 8-device CPU mesh"
    return make_mesh(8)


def canon(rows: np.ndarray) -> np.ndarray:
    """Sort each bucket's slots by fingerprint — canonical live state."""
    from gubernator_tpu.ops.table2 import F, K

    D, NB, _ = rows.shape
    s = rows.reshape(D, NB, K, F)
    key = (s[..., 1].astype(np.int64) << 32) | (
        s[..., 0].astype(np.int64) & 0xFFFFFFFF
    )
    order = np.argsort(key, axis=2, kind="stable")
    return np.take_along_axis(s, order[..., None], axis=2)


def assert_resp_equal(want, got, ctx=""):
    for i, (a, b) in enumerate(zip(want, got)):
        assert (a.status, a.remaining, a.reset_time, a.error) == (
            b.status, b.remaining, b.reset_time, b.error,
        ), f"{ctx} row {i}: {a} != {b}"


def mixed_corpus(rng, t, step, n=200, keys=70):
    """Token/leaky mix with duplicates, varying hits, RESET flags."""
    ks = rng.integers(0, keys, size=n)
    return [
        req(
            f"m{k}",
            hits=1 + int(k) % 3,
            limit=1000,
            algorithm=(Algorithm.TOKEN_BUCKET if k % 3
                       else Algorithm.LEAKY_BUCKET),
            behavior=(Behavior.RESET_REMAINING if k % 11 == 1
                      else Behavior.BATCHING),
            created_at=t + step,
        )
        for k in ks
    ]


@pytest.mark.parametrize("route", ["host", "device"])
def test_device_dedup_matches_host_aggregate_oracle(mesh, frozen_now, route):
    """In-trace dedup vs the host aggregation oracle, per route: responses,
    stats, and canonical live state all equal across multi-step mixed
    traffic."""
    t = frozen_now
    oracle = ShardedEngine(mesh, capacity_per_shard=2048, route=route,
                           dedup="host", max_exact_passes=1)
    dev = ShardedEngine(mesh, capacity_per_shard=2048, route=route,
                        dedup="device")
    rng = np.random.default_rng(5)
    for step in range(3):
        reqs = mixed_corpus(rng, t, step)
        want = oracle.check(reqs, now_ms=t + step)
        got = dev.check(reqs, now_ms=t + step)
        assert_resp_equal(want, got, f"route={route} step={step}")
    np.testing.assert_array_equal(canon(oracle.snapshot()),
                                  canon(dev.snapshot()))
    assert oracle.stats.cache_hits == dev.stats.cache_hits
    assert oracle.stats.cache_misses == dev.stats.cache_misses
    assert oracle.stats.over_limit == dev.stats.over_limit
    assert oracle.stats.checks == dev.stats.checks


def test_route_parity_zipf_overflow(mesh, frozen_now):
    """Zipf-skewed duplicate-heavy batches through route="device" vs
    route="host" (both dedup="device"): skew concentrates rows on hot
    owners and forces per-pair exchange overflow; the retry chain plus the
    terminal host-grid fallback must make routing invisible — identical
    responses, zero errors, identical per-key totals."""
    t = frozen_now
    host_eng = ShardedEngine(mesh, capacity_per_shard=4096, route="host",
                             dedup="device")
    dev_eng = ShardedEngine(mesh, capacity_per_shard=4096, route="device",
                            dedup="device")
    rng = np.random.default_rng(13)
    z = np.minimum(rng.zipf(1.1, size=2048) - 1, 1023)
    reqs = [req(f"z{k}", hits=1, limit=1 << 20, created_at=t) for k in z]
    want = host_eng.check(reqs, now_ms=t)
    got = dev_eng.check(reqs, now_ms=t)
    assert_resp_equal(want, got, "zipf")
    assert all(r.error == "" for r in got)
    # per-key consumption identical on both engines (hits=0 probe)
    uniq, counts = np.unique(z, return_counts=True)
    probe = [req(f"z{k}", hits=0, limit=1 << 20, created_at=t) for k in uniq]
    again_h = host_eng.check(probe, now_ms=t)
    again_d = dev_eng.check(probe, now_ms=t)
    assert_resp_equal(again_h, again_d, "zipf probe")
    for k, c, r in zip(uniq, counts, again_d):
        assert r.remaining == (1 << 20) - c, f"key z{k}"
    np.testing.assert_array_equal(canon(host_eng.snapshot()),
                                  canon(dev_eng.snapshot()))


def test_global_fork_parity_device_route_and_dedup(mesh, frozen_now):
    """The GLOBAL owner/replica fork through the device-routed, in-trace
    dedup path vs the host-planned aggregate oracle: replica answers, owner
    applies, queued hits, and the post-sync converged state must all agree
    (same rotating home sequence — one GLOBAL batch per check call)."""
    t = frozen_now
    oracle = GlobalShardedEngine(mesh, capacity_per_shard=2048, route="host",
                                 dedup="host", max_exact_passes=1,
                                 sync_out=256)
    dev = GlobalShardedEngine(mesh, capacity_per_shard=2048, route="device",
                              dedup="device", sync_out=256)
    rng = np.random.default_rng(23)
    for step in range(3):
        ks = rng.integers(0, 40, size=120)
        reqs = [
            req(
                f"g{k}",
                hits=1 + int(k) % 2,
                limit=500,
                behavior=(Behavior.GLOBAL if k % 2 else Behavior.BATCHING),
                created_at=t + step,
            )
            for k in ks
        ]
        cols = columns_from_requests(reqs)
        want = oracle.check_columns(cols, now_ms=t + step)
        got = dev.check_columns(cols, now_ms=t + step)
        np.testing.assert_array_equal(want.status, got.status, f"step {step}")
        np.testing.assert_array_equal(want.remaining, got.remaining)
        np.testing.assert_array_equal(want.reset_time, got.reset_time)
        np.testing.assert_array_equal(want.err, got.err)
    assert (
        oracle.global_stats.send_queue_length
        == dev.global_stats.send_queue_length
    )
    oracle.sync(now_ms=t + 3)
    dev.sync(now_ms=t + 3)
    # post-sync convergence: the owner-reconciled authoritative tables agree
    np.testing.assert_array_equal(canon(oracle.snapshot()),
                                  canon(dev.snapshot()))
    probe = columns_from_requests(
        [req(f"g{k}", hits=0, limit=500, behavior=Behavior.GLOBAL,
             created_at=t + 3) for k in range(0, 40, 2)]
    )
    want = oracle.check_columns(probe, now_ms=t + 3)
    got = dev.check_columns(probe, now_ms=t + 3)
    np.testing.assert_array_equal(want.remaining, got.remaining)


def test_pipelined_dedup_matches_serial(mesh, frozen_now):
    """The prepare/issue/finish split with in-trace dedup (member rows
    decoded through finish_staged's FLAG_MEMBER accounting) must equal the
    serial dedup path — responses, stats, and state."""
    from gubernator_tpu.ops.engine import (
        finish_check_columns,
        issue_check_columns,
        prepare_check_columns,
    )

    t = frozen_now
    rng = np.random.default_rng(31)
    serial = ShardedEngine(mesh, capacity_per_shard=2048, route="device",
                           dedup="device")
    piped = ShardedEngine(mesh, capacity_per_shard=2048, route="device",
                          dedup="device")
    for step in range(3):
        cols = columns_from_requests(mixed_corpus(rng, t, step, n=160))
        want = serial.check_columns(cols, now_ms=t + step)
        pending = issue_check_columns(
            piped, prepare_check_columns(piped, cols, now_ms=t + step)
        )
        # in-trace dedup plans exactly ONE pass — the host group-by is gone
        assert len(pending.passes) == 1
        got, delta = finish_check_columns(piped, pending, fixup=lambda fn: fn())
        piped.stats.merge(delta)
        np.testing.assert_array_equal(got.status, want.status)
        np.testing.assert_array_equal(got.remaining, want.remaining)
        np.testing.assert_array_equal(got.err, want.err)
    assert serial.stats.cache_hits == piped.stats.cache_hits
    assert serial.stats.cache_misses == piped.stats.cache_misses
    np.testing.assert_array_equal(canon(serial.snapshot()),
                                  canon(piped.snapshot()))


def test_stage_timing_and_egress_recycling(mesh, frozen_now):
    """The ingress accounting the bench and shard_* metrics read: staging
    time accumulates per dispatch, take_stage_deltas drains it, and fetched
    egress buffers are banked for donation reuse."""
    t = frozen_now
    eng = ShardedEngine(mesh, capacity_per_shard=1024, route="device",
                        dedup="device")
    reqs = [req(f"s{i}", created_at=t) for i in range(64)]
    eng.check(reqs, now_ms=t)
    assert eng.stage_dispatches >= 1
    d = eng.take_stage_deltas()
    assert set(d) == {"route", "pack", "put", "wire_pack", "wire_decode"}
    assert d["pack"] + d["wire_pack"] >= 0 and d["put"] > 0
    # drained: a second take with no traffic reads zero
    assert all(v == 0.0 for v in eng.take_stage_deltas().values())
    # egress bank primed by the fetch; the next same-shape dispatch pops it
    assert any(len(v) for v in eng._egress.values())
    banked = {k: len(v) for k, v in eng._egress.items()}
    eng.check(reqs, now_ms=t)
    assert {k: len(v) for k, v in eng._egress.items()} == banked
