"""Peer fault tolerance against REAL failing RPCs — the chaos-proxy suite.

Each scenario boots an in-process cluster whose peer plane is fronted by
ChaosProxy instances (tests/cluster.py chaos=True, tests/chaos.py) and
injects faults at the TCP layer, driving the breaker / degraded-fallback /
requeue machinery through actual gRPC failures rather than mocks. The fast
smoke + acceptance scenarios run in tier-1; multi-cycle partition/recovery
runs are @pytest.mark.slow.
"""

import asyncio
import functools
import time

import pytest

from gubernator_tpu.client import V1Client
from gubernator_tpu.config import BehaviorConfig, DegradationPolicy
from gubernator_tpu.service.breaker import BreakerState
from gubernator_tpu.types import Behavior, RateLimitRequest

from tests.chaos import ChaosProxy
from tests.cluster import Cluster, metric_value, scrape, wait_for


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


def chaos_behaviors(**over) -> BehaviorConfig:
    """Short cadences so fault scenarios resolve in test time: RPC timeouts
    of 400 ms (the 'slow failure' the breaker converts into fast ones), a
    2-failure trip threshold, and sub-second breaker cooldowns."""
    kw = dict(
        batch_wait_ms=1.0,
        global_sync_wait_ms=50.0,
        batch_timeout_ms=400.0,
        global_timeout_ms=400.0,
        peer_breaker_errors=2,
        peer_breaker_backoff_base_ms=300.0,
        peer_breaker_backoff_cap_ms=600.0,
        global_requeue_retries=200,  # survive the whole injected partition
    )
    kw.update(over)
    return BehaviorConfig(**kw)


def req(key, name="chaos", hits=1, limit=100, behavior=0):
    return RateLimitRequest(
        name=name,
        unique_key=key,
        hits=hits,
        limit=limit,
        duration=60_000,
        behavior=behavior,
    )


# ------------------------------------------------------------ proxy smoke


@async_test
async def test_chaos_proxy_modes_smoke():
    """Fast tier-1 smoke of every proxy mode against a plain TCP echo
    server — no daemons involved."""

    async def echo(reader, writer):
        try:
            while data := await reader.read(1024):
                writer.write(data)
                await writer.drain()
        except (ConnectionError, OSError):
            pass
        finally:
            writer.close()

    server = await asyncio.start_server(echo, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    proxy = await ChaosProxy().start()
    proxy.set_target("127.0.0.1", port)

    async def round_trip():
        r, w = await asyncio.open_connection("127.0.0.1", proxy.port)
        w.write(b"ping")
        await w.drain()
        got = await asyncio.wait_for(r.read(4), timeout=2.0)
        w.close()
        return got

    try:
        # pass
        assert await round_trip() == b"ping"
        # delay: still correct, measurably slower
        proxy.set_mode("delay", delay_s=0.1)
        t0 = time.perf_counter()
        assert await round_trip() == b"ping"
        assert time.perf_counter() - t0 >= 0.1
        # drop: connection dies immediately
        proxy.set_mode("drop")
        with pytest.raises((ConnectionError, asyncio.IncompleteReadError, OSError)):
            r, w = await asyncio.open_connection("127.0.0.1", proxy.port)
            w.write(b"x")
            await w.drain()
            if await asyncio.wait_for(r.read(4), timeout=2.0) == b"":
                raise ConnectionResetError("closed")
        # error: established, reset after first bytes
        proxy.set_mode("error")
        r, w = await asyncio.open_connection("127.0.0.1", proxy.port)
        w.write(b"x")
        await w.drain()
        got = await asyncio.wait_for(r.read(4), timeout=2.0)
        assert got == b""  # reset, no echo
        w.close()
        # blackhole: established, nothing ever comes back
        proxy.set_mode("blackhole")
        r, w = await asyncio.open_connection("127.0.0.1", proxy.port)
        w.write(b"x")
        await w.drain()
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(r.read(4), timeout=0.3)
        w.close()
        # heal: back to transparent
        proxy.heal()
        assert await round_trip() == b"ping"
    finally:
        await proxy.stop()
        server.close()
        await server.wait_closed()


# ------------------------------------------- acceptance: blackholed owner


@async_test
async def test_blackholed_owner_breaker_degraded_local_and_recovery():
    """The ISSUE's acceptance scenario in one 3-node pass:
    (a) once the owner's breaker opens, forwarded checks stop waiting on
        RPC timeouts (post-open latency << pre-open latency);
    (b) under DegradationPolicy.LOCAL clients get non-error decisions
        marked metadata["degraded"];
    (c) after the proxy heals, the half-open probe closes the breaker and
        requeued GLOBAL hits (not dropped) reach the owner."""
    c = await Cluster.start(
        3,
        chaos=True,
        behaviors=chaos_behaviors(
            degradation_policy=DegradationPolicy.LOCAL.value
        ),
    )
    owner = c.find_owning_daemon("chaos", "k1")
    non_owner = c.non_owning_daemons("chaos", "k1")[0]
    client = V1Client(non_owner.conf.grpc_address)
    try:
        # warm the forwarding path while the proxies are transparent (also
        # pays any first-compile cost off the measured path)
        r = (await client.get_rate_limits([req("k1")])).responses[0]
        assert r.error == "" and "degraded" not in r.metadata
        assert r.remaining == 99

        # ---- blackhole the owner's peer plane
        c.proxy_for(owner).set_mode("blackhole")
        owner_addr = owner.conf.advertise_address
        breaker = non_owner._peer_clients[owner_addr].breaker

        # (b) pre-open: requests ride real RPC timeouts, then degrade to a
        # LOCAL decision — non-error, marked degraded
        t0 = time.perf_counter()
        r = (await client.get_rate_limits([req("k1")])).responses[0]
        pre_open_s = time.perf_counter() - t0
        assert r.error == ""
        assert r.metadata["degraded"] == "true"
        # the non-owner's replica never saw the forwarded hit, so its local
        # answer is its own store's view
        assert r.remaining == 99
        assert breaker.state is BreakerState.OPEN  # 2 failures tripped it
        assert pre_open_s >= 0.4  # paid at least one real RPC timeout

        # (a) post-open: fail-fast — no RPC, no timeout wait
        t0 = time.perf_counter()
        r = (await client.get_rate_limits([req("k1")])).responses[0]
        post_open_s = time.perf_counter() - t0
        assert r.error == "" and r.metadata["degraded"] == "true"
        assert post_open_s < pre_open_s / 2, (pre_open_s, post_open_s)

        s = await scrape(non_owner)
        assert metric_value(s, "gubernator_degraded_response_count_total") >= 2
        assert (
            metric_value(
                s, "gubernator_circuit_breaker_state", peer=owner_addr
            )
            == 2.0  # OPEN
        )

        # (c) queue GLOBAL hits at the non-owner toward the dead owner —
        # the hit-sync fails/fast-fails and REQUEUES instead of dropping
        gkey, gname = "gk-requeue", "chaosg"
        gowner = c.find_owning_daemon(gname, gkey)
        gnon = [d for d in c.daemons if d is not gowner][0]
        if gowner is not owner:
            # make the blackholed daemon the GLOBAL owner for determinism:
            # reuse the already-dead owner by sending from one of ITS keys'
            # non-owners — simplest is to blackhole gowner's proxy too
            c.proxy_for(gowner).set_mode("blackhole")
        gclient = V1Client(gnon.conf.grpc_address)
        resp = (
            await gclient.get_rate_limits(
                [req(gkey, name=gname, hits=7, behavior=Behavior.GLOBAL)]
            )
        ).responses[0]
        assert resp.error == ""  # GLOBAL answers locally regardless
        await gclient.close()

        async def requeued():
            s = await scrape(gnon)
            return metric_value(s, "gubernator_global_requeue_count_total")

        await wait_for(requeued, timeout_s=10)

        # ---- heal everything
        for p in c.proxies:
            p.heal()

        # the cooldown elapses, a half-open probe succeeds, the breaker
        # closes, and the requeued hits finally land on the owner
        async def owner_got_hits():
            s = await scrape(gowner)
            return metric_value(
                s, "gubernator_broadcast_counter_total", condition="broadcast"
            )

        await wait_for(owner_got_hits, timeout_s=15)
        gc2 = V1Client(gowner.conf.grpc_address)
        rg = (
            await gc2.get_rate_limits(
                [req(gkey, name=gname, hits=0, behavior=Behavior.GLOBAL)]
            )
        ).responses[0]
        await gc2.close()
        assert rg.remaining == 93  # the 7 requeued hits arrived, not dropped

        # the breaker only re-learns from traffic: zero-hit reads keep
        # probing until the cooldown elapses, the half-open probe succeeds
        # against the healed proxy, and forwarding turns authoritative again
        async def recovered():
            r = (await client.get_rate_limits([req("k1", hits=0)])).responses[0]
            return r.error == "" and "degraded" not in r.metadata

        await wait_for(recovered, timeout_s=10)
        assert breaker.state is BreakerState.CLOSED
    finally:
        await client.close()
        await c.stop()


# --------------------------------------- satellite: owner death mid-flight


@async_test
async def test_forward_owner_killed_returns_reference_error_and_retries():
    """Owner daemon killed (closed) mid-flight → the non-owner's forward
    path retries, returns the reference-format error response, and
    increments batch_send_retries (previously untested under real peer
    death). Default policy (ERROR) — no degraded masking."""
    c = await Cluster.start(2)
    owner = c.find_owning_daemon("killed", "k1")
    non_owner = c.non_owning_daemons("killed", "k1")[0]
    client = V1Client(non_owner.conf.grpc_address)
    try:
        # healthy first: the forward path works
        r = (await client.get_rate_limits([req("k1", name="killed")])).responses[0]
        assert r.error == "" and r.remaining == 99

        await owner.close()  # real peer death: listeners gone

        r = (await client.get_rate_limits([req("k1", name="killed")])).responses[0]
        assert r.error.startswith("Error while fetching rate limit from peer:")
        assert "degraded" not in r.metadata

        s = await scrape(non_owner)
        assert metric_value(s, "gubernator_batch_send_retries_total") >= 1.0
        assert (
            metric_value(
                s,
                "gubernator_check_error_counter_total",
                error="forward",
            )
            >= 1.0
        )

        # health: peer errors + (eventually) an open breaker surface as
        # DEGRADED — distinguishable from unhealthy — with per-peer
        # breaker state + recent errors in the response
        hc = await non_owner.health_check()
        assert hc.status == "degraded"
        entry = {p.grpc_address: p for p in hc.local_peers}[
            owner.conf.advertise_address
        ]
        assert entry.breaker_state in ("closed", "half-open", "open")
        assert len(entry.recent_errors) >= 1

        # the probe binary treats degraded as passing (restarting a pod
        # because its PEERS died only amplifies an outage)…
        import io

        from gubernator_tpu.cmd.healthcheck import NotHealthy, check

        out = io.StringIO()
        await asyncio.get_running_loop().run_in_executor(
            None,
            lambda: check(
                non_owner.conf.http_address, attempts=1, delay_s=0, out=out
            ),
        )
        assert "degraded (passing)" in out.getvalue()
        # …unless strict
        with pytest.raises(NotHealthy):
            await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: check(
                    non_owner.conf.http_address,
                    attempts=1,
                    delay_s=0,
                    out=io.StringIO(),
                    strict=True,
                ),
            )
    finally:
        await client.close()
        await c.stop()


# ----------------------------------------------------- slow: multi-cycle


@pytest.mark.slow
@async_test
async def test_repeated_partition_recovery_cycles():
    """Long scenario: three partition/heal cycles against the same owner.
    Every cycle must re-trip the breaker, keep serving degraded-local
    decisions, then recover to authoritative forwarding — proving the
    half-open path doesn't wedge after repeated trips."""
    c = await Cluster.start(
        3,
        chaos=True,
        behaviors=chaos_behaviors(
            degradation_policy=DegradationPolicy.LOCAL.value
        ),
    )
    owner = c.find_owning_daemon("chaos", "cyc")
    non_owner = c.non_owning_daemons("chaos", "cyc")[0]
    breaker = non_owner._peer_clients[owner.conf.advertise_address].breaker
    client = V1Client(non_owner.conf.grpc_address)
    try:
        forwarded = 0
        for cycle in range(3):
            # healthy: forwarded, counted at the owner
            r = (await client.get_rate_limits([req("cyc")])).responses[0]
            forwarded += 1
            assert r.error == "" and "degraded" not in r.metadata
            assert r.remaining == 100 - forwarded, f"cycle {cycle}"

            c.proxy_for(owner).set_mode("blackhole")
            # drive until the breaker trips, then assert degraded fast-path
            r = (await client.get_rate_limits([req("cyc")])).responses[0]
            assert r.metadata["degraded"] == "true"
            assert breaker.state is BreakerState.OPEN, f"cycle {cycle}"
            for _ in range(3):
                r = (await client.get_rate_limits([req("cyc")])).responses[0]
                assert r.error == "" and r.metadata["degraded"] == "true"

            c.proxy_for(owner).heal()

            async def recovered():
                r = (await client.get_rate_limits([req("cyc", hits=0)])).responses[0]
                return "degraded" not in r.metadata and r.error == ""

            await wait_for(recovered, timeout_s=10)
            assert breaker.state is BreakerState.CLOSED, f"cycle {cycle}"
    finally:
        await client.close()
        await c.stop()
