"""Fused Pallas probe→decide→write megakernel suite (ops/pallas_probe.py).

The acceptance surface of the probe tentpole:

* `GUBER_PROBE_KERNEL=pallas` is BIT-IDENTICAL to the XLA gather+write
  path (`decide2_impl`, the oracle) across all three slot layouts ×
  all five algorithms × the nasty claim corners — bucket-full eviction,
  same-target dedup (owner wins), expired-slot reclaim, negative-hit
  release on a missing key, RESET/DRAIN behaviors, inactive padding —
  responses, stats AND raw table bytes, through multi-step aging;
* the carry machinery (bucket runs straddling grid-block boundaries) is
  exercised with tiny GUBER_PROBE_BLK values and engineered collisions;
* the knob threads through LocalEngine and the 8-device shard_map mesh
  (ShardedEngine route/dedup="device") unchanged;
* the layout-aware sparse-write crossover is pinned at the boundary
  (packed rows halve bytes → sparse survives to 2× the dirty coverage);
* the HBM bytes/decision roofline model is monotone and layout-scaled.

Everything runs the interpret-mode lowering (CPU CI), the same execution
CI's probe_smoke gates.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

from gubernator_tpu.ops.batch import ReqBatch, RequestColumns
from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.ops.kernel2 import decide2_impl, resolve_write
from gubernator_tpu.ops.layout import FULL, GCRA32, TOKEN32
from gubernator_tpu.ops.pallas_probe import hbm_bytes_per_decision
from gubernator_tpu.ops.table2 import new_table2

NOW = 1_700_000_000_000

RESP_FIELDS = ("status", "limit", "remaining", "reset_time", "cache_hit",
               "dropped")


def mkreq(rng, n, n_active=None, algos=(0,), hits=None, behavior=0,
          limit=100, dur=60_000, now=NOW, bucket_pool=None, pool_nb=64,
          greg=0):
    """Unique-fp request batch; `bucket_pool` concentrates fps into that
    many hash buckets of a pool_nb-bucket table (collision pressure)."""
    n_active = n if n_active is None else n_active
    if bucket_pool:
        base = rng.integers(1, pool_nb, size=bucket_pool, dtype=np.int64)
        fp = base[rng.integers(0, bucket_pool, size=2 * n)] + pool_nb * \
            rng.integers(1, 1 << 40, size=2 * n, dtype=np.int64)
    else:
        fp = rng.integers(1, 1 << 62, size=2 * n, dtype=np.int64)
    fp = np.unique(fp)
    while fp.shape[0] < n:
        fp = np.unique(np.concatenate(
            [fp, rng.integers(1, 1 << 62, size=n, dtype=np.int64)]
        ))
    fp = fp[:n]
    rng.shuffle(fp)
    h = (np.asarray(hits, dtype=np.int64) if hits is not None
         else rng.integers(-2, 4, size=n).astype(np.int64))
    if h.ndim == 0:
        h = np.full(n, h, dtype=np.int64)
    algo = np.array([algos[i % len(algos)] for i in range(n)], dtype=np.int32)
    return ReqBatch(
        fp=jnp.asarray(fp),
        algo=jnp.asarray(algo),
        behavior=jnp.full(n, behavior, dtype=jnp.int32),
        hits=jnp.asarray(h),
        limit=jnp.full(n, limit, dtype=jnp.int64),
        burst=jnp.full(n, limit, dtype=jnp.int64),
        duration=jnp.full(n, dur, dtype=jnp.int64),
        created_at=jnp.full(n, now, dtype=jnp.int64),
        expire_new=jnp.full(n, now + dur, dtype=jnp.int64),
        greg_interval=jnp.full(n, greg, dtype=jnp.int64),
        duration_eff=jnp.full(n, dur, dtype=jnp.int64),
        active=jnp.asarray(np.arange(n) < n_active),
    )


def assert_parity(cap, req, math="mixed", layout=None, steps=3,
                  step_ms=20_000):
    """Drive both probe kernels over the same traffic and assert response,
    stats and raw-table-byte identity at every step."""
    tx = new_table2(cap, layout=layout)
    tp = new_table2(cap, layout=layout)
    for s in range(steps):
        r = req._replace(
            created_at=req.created_at + s * step_ms,
            expire_new=req.expire_new + s * step_ms,
        )
        tx, rx, sx = decide2_impl(tx, r, write="xla", math=math)
        tp, rp, sp = decide2_impl(tp, r, write="xla", math=math,
                                  probe="pallas")
        act = np.asarray(r.active)
        for f in RESP_FIELDS:
            np.testing.assert_array_equal(
                np.asarray(getattr(rx, f)), np.asarray(getattr(rp, f)),
                err_msg=f"step {s}: RespBatch.{f}",
            )
        # aux/rem_store are broadcast-plane echoes, defined for ACTIVE rows
        # (inactive rows carry deterministic-garbage lanes in both paths)
        for f in ("aux", "rem_store"):
            np.testing.assert_array_equal(
                np.asarray(getattr(rx, f))[act],
                np.asarray(getattr(rp, f))[act],
                err_msg=f"step {s}: RespBatch.{f}",
            )
        for f in sx._fields:
            assert int(getattr(sx, f)) == int(getattr(sp, f)), \
                f"step {s}: BatchStats.{f}"
        np.testing.assert_array_equal(
            np.asarray(tx.rows), np.asarray(tp.rows),
            err_msg=f"step {s}: table bytes",
        )


# ------------------------------------------------- algorithm × layout parity


@pytest.mark.parametrize("algo,math", [
    (0, "token"), (1, "mixed"), (2, "gcra"), (3, "int"), (4, "int"),
])
def test_parity_per_algorithm(algo, math):
    rng = np.random.default_rng(algo + 1)
    assert_parity(512, mkreq(rng, 128, algos=(algo,)), math=math, steps=4)


def test_parity_mixed_batch_all_algorithms():
    rng = np.random.default_rng(42)
    assert_parity(512, mkreq(rng, 128, algos=(0, 1, 2, 3, 4)), math="mixed",
                  steps=4)


@pytest.mark.parametrize("lay,algo,math", [
    (GCRA32, 2, "gcra"), (TOKEN32, 0, "token"),
])
def test_parity_packed_layouts(lay, algo, math):
    rng = np.random.default_rng(9)
    assert_parity(512, mkreq(rng, 128, algos=(algo,)), math=math, layout=lay,
                  steps=4)
    # and under collision pressure (carry + eviction on packed rows)
    req = mkreq(rng, 128, algos=(algo,), bucket_pool=6, pool_nb=16)
    assert_parity(128, req, math=math, layout=lay, steps=4)


# --------------------------------------------------------- claim corners


def test_parity_bucket_full_eviction(monkeypatch):
    """More unique keys per bucket than K=8 lanes: rank-overflow drops,
    soonest-expiring eviction of LIVE lanes, multi-evict bursts."""
    monkeypatch.setenv("GUBER_PROBE_BLK", "32")
    rng = np.random.default_rng(2)
    req = mkreq(rng, 256, algos=(0, 2), bucket_pool=4, pool_nb=8, hits=1)
    assert_parity(64, req, math="int", steps=4)


def test_parity_same_target_dedup(monkeypatch):
    """Owner-vs-inserter lane collisions (the sorted-dup rule): aged state
    makes owners' lanes expired/evictable, so fresh inserters pick them."""
    monkeypatch.setenv("GUBER_PROBE_BLK", "16")
    rng = np.random.default_rng(3)
    req = mkreq(rng, 128, algos=(0,), bucket_pool=8, pool_nb=16,
                dur=5_000, hits=1)
    assert_parity(128, req, math="token", steps=5, step_ms=4_000)


def test_parity_expired_slot_reclaim():
    """Steps larger than the duration: every slot expires between steps and
    is reclaimed through the vacant-first candidate order."""
    rng = np.random.default_rng(4)
    req = mkreq(rng, 128, algos=(0, 2, 3, 4), bucket_pool=8, pool_nb=16,
                dur=5_000, hits=2)
    assert_parity(128, req, math="int", steps=4, step_ms=30_000)


def test_parity_negative_hit_release_on_missing_key():
    """The PR-13 miss-safety corner: releases against keys with no live
    state must not install for the extension algorithms."""
    rng = np.random.default_rng(5)
    req = mkreq(rng, 128, algos=(2, 3, 4), hits=-3)
    assert_parity(512, req, math="int", steps=3)


def test_parity_reset_and_drain_behaviors():
    rng = np.random.default_rng(6)
    assert_parity(
        512, mkreq(rng, 128, algos=(0, 2), behavior=8), math="int", steps=3
    )  # RESET_REMAINING removes
    assert_parity(
        512, mkreq(rng, 128, algos=(0, 1, 2, 3, 4), behavior=16, hits=60),
        math="mixed", steps=3,
    )  # DRAIN_OVER_LIMIT
    req = mkreq(rng, 128, algos=(0,), behavior=4, hits=1)
    req = req._replace(greg_interval=jnp.full(128, 86_400_000, jnp.int64))
    assert_parity(512, req, math="mixed", steps=3)  # Gregorian token rows


def test_parity_inactive_padding_rows():
    rng = np.random.default_rng(7)
    assert_parity(512, mkreq(rng, 128, n_active=70), math="mixed", steps=3)
    # all-padding warm batch
    assert_parity(512, mkreq(rng, 64, n_active=0), math="token", steps=2)


def test_parity_block_boundary_carries(monkeypatch):
    """Bucket runs straddling grid blocks: tiny blocks force multi-block
    carries, deferred-inserter patches and carry flushes at every shape."""
    rng = np.random.default_rng(8)
    for blk in ("8", "16", "64", "1024"):
        monkeypatch.setenv("GUBER_PROBE_BLK", blk)
        req = mkreq(rng, 96, n_active=77, algos=(0, 2, 4), bucket_pool=9,
                    pool_nb=32)
        assert_parity(256, req, math="int", steps=3)
    monkeypatch.delenv("GUBER_PROBE_BLK")


def test_parity_single_bucket_whole_batch(monkeypatch):
    """Degenerate carry: EVERY request hashes to one bucket — the run spans
    every grid block, so the carry lives from block 0 to the last flush."""
    monkeypatch.setenv("GUBER_PROBE_BLK", "8")
    rng = np.random.default_rng(10)
    req = mkreq(rng, 64, algos=(0,), bucket_pool=1, pool_nb=4, hits=1)
    assert_parity(32, req, math="token", steps=3)


# ------------------------------------------------------------- engine layer


def cols(fp, algo, hits=1, limit=64, now=NOW):
    n = fp.shape[0]
    h = (np.asarray(hits, dtype=np.int64) if np.ndim(hits)
         else np.full(n, hits, dtype=np.int64))
    return RequestColumns(
        fp=fp.astype(np.int64),
        algo=np.full(n, algo, dtype=np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=h,
        limit=np.full(n, limit, dtype=np.int64),
        burst=np.zeros(n, dtype=np.int64),
        duration=np.full(n, 8_000, dtype=np.int64),
        created_at=np.full(n, now, dtype=np.int64),
        err=np.zeros(n, dtype=np.int8),
    )


def rc_equal(a, b):
    for f in ("status", "limit", "remaining", "reset_time", "err"):
        np.testing.assert_array_equal(getattr(a, f), getattr(b, f),
                                      err_msg=f)


def test_local_engine_probe_parity():
    """GUBER_PROBE_KERNEL threads through the serving engine: identical
    responses and identical raw table bytes, wire path included."""
    rng = np.random.default_rng(11)
    ex = LocalEngine(capacity=1 << 12, write_mode="xla", probe="xla")
    ep = LocalEngine(capacity=1 << 12, write_mode="xla", probe="pallas")
    assert ep.probe_mode == "pallas"
    fp = rng.integers(1, (1 << 63) - 1, size=512, dtype=np.int64)
    t = NOW
    for step in range(3):
        t += int(rng.integers(500, 4_000))
        sel = fp.copy()
        if step == 1:
            sel[256:] = sel[:256]  # duplicate keys → planner passes
        c = cols(sel, (0, 2)[step % 2], hits=rng.integers(0, 3, size=512))
        rc_equal(ex.check_columns(c, now_ms=t), ep.check_columns(c, now_ms=t))
    np.testing.assert_array_equal(ex.snapshot(), ep.snapshot())


def test_probe_env_resolution(monkeypatch):
    from gubernator_tpu.ops.plan import default_probe_kernel

    monkeypatch.delenv("GUBER_PROBE_KERNEL", raising=False)
    assert default_probe_kernel() == "xla"  # auto = today's kernel
    monkeypatch.setenv("GUBER_PROBE_KERNEL", "pallas")
    assert default_probe_kernel() == "pallas"
    assert LocalEngine(capacity=1 << 10).probe_mode == "pallas"
    monkeypatch.setenv("GUBER_PROBE_KERNEL", "bogus")
    with pytest.raises(ValueError):
        default_probe_kernel()
    with pytest.raises(ValueError):
        LocalEngine(capacity=1 << 10, probe="bogus")
    with pytest.raises(ValueError):
        decide2_impl(new_table2(256), mkreq(np.random.default_rng(0), 16),
                     probe="bogus")


def test_config_probe_kernel_validation():
    from gubernator_tpu.config import ConfigError, DaemonConfig

    conf = DaemonConfig(probe_kernel="pallas")
    conf.validate()
    with pytest.raises(ConfigError):
        DaemonConfig(probe_kernel="nope").validate()


def test_sharded_mesh_probe_parity():
    """The PR-8 shard_map mesh path composes unchanged: the megakernel runs
    per device shard inside the routed program (8-device CPU mesh,
    route/dedup=device — the TPU serving defaults)."""
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.sharded import ShardedEngine

    mesh = make_mesh(8)
    kw = dict(capacity_per_shard=1 << 10, write_mode="xla",
              route="device", dedup="device")
    ex = ShardedEngine(mesh, probe="xla", **kw)
    ep = ShardedEngine(mesh, probe="pallas", **kw)
    assert ep.probe_mode == "pallas"
    rng = np.random.default_rng(12)
    fp = rng.integers(1, (1 << 63) - 1, size=1024, dtype=np.int64)
    t = NOW
    for step in range(3):
        t += int(rng.integers(100, 2_000))
        sel = fp.copy()
        if step == 2:
            sel[512:] = sel[:512]  # duplicates → in-trace dedup carriers
        c = cols(sel, 2, hits=rng.integers(0, 4, size=1024), limit=32, now=t)
        rc_equal(ex.check_columns(c, now_ms=t), ep.check_columns(c, now_ms=t))
    np.testing.assert_array_equal(ex.snapshot(), ep.snapshot())


# --------------------------------------- layout-aware write crossover


def test_sparse_crossover_is_layout_aware(monkeypatch):
    """The crossover is byte-denominated: a geometry whose worst-case dirty
    coverage sits just past the FULL-layout bound still resolves sparse on
    a 32 B packed layout (half the bytes per row → twice the row budget)."""
    monkeypatch.setenv("GUBER_WRITE_SPARSE_BLK", "64")
    monkeypatch.setenv("GUBER_WRITE_SPARSE_CROSSOVER", "4")
    # batch 128 → g = 128 grid steps × blk = 64 rows = 8192 rows worst-case
    # dirty coverage. With crossover 4 the sweep fallback fires when
    # scaled_coverage·4 ≥ NB: full scales ×1 → fires for NB ≤ 32768; packed
    # ×0.5 → fires only for NB ≤ 16384. NB = 24576 (12 × 2048) sits in the
    # boundary band where the two layouts DECIDE DIFFERENTLY.
    nb, batch = 12 * 2048, 128
    assert resolve_write("sparse", nb, batch, FULL) == "sweep"
    assert resolve_write("sparse", nb, batch, GCRA32) == "sparse"
    assert resolve_write("sparse", nb, batch, TOKEN32) == "sparse"
    # defaulted layout keeps the pre-layout behavior bit-for-bit
    assert resolve_write("sparse", nb, batch) == "sweep"
    # far side of the boundary: both layouts agree again
    assert resolve_write("sparse", 1 << 21, 128, FULL) == "sparse"
    assert resolve_write("sparse", 1 << 11, 1 << 17, GCRA32) == "sweep"


def test_hbm_bytes_per_decision_model():
    nb, b = 1 << 17, 4096
    # packed rows halve every term
    for write in ("sweep", "xla"):
        full_b = hbm_bytes_per_decision(FULL, b, nb, write)
        gcra_b = hbm_bytes_per_decision(GCRA32, b, nb, write)
        assert gcra_b == pytest.approx(full_b / 2)
    # the fused kernel is batch-proportional: 2 rows/decision worst case
    assert hbm_bytes_per_decision(FULL, b, nb, "sweep", probe="pallas") == \
        2 * FULL.row * 4
    # the sweep amortizes the whole table over the batch; sparse (when it
    # resolves) touches strictly fewer bytes than the sweep
    sw = hbm_bytes_per_decision(FULL, b, nb, "sweep")
    sp = hbm_bytes_per_decision(FULL, b, nb, "sparse")
    assert sp <= sw
    assert hbm_bytes_per_decision(FULL, b, nb, "sweep") > \
        hbm_bytes_per_decision(FULL, 2 * b, nb, "sweep")
