"""Pins for the a2a exchange-capacity geometry and overflow contract.

pair_capacity is the single source of truth for the (D, C) exchange buffers
(parallel/a2a.py step 2); the curve is pinned here so tuning the capacity
factor later (GUBER_A2A_CAPACITY_SIGMA) is a deliberate, test-visible act —
and the overflow→FLAG_DROPPED|FLAG_UNPROCESSED contract is pinned so a
capacity change can never silently turn retryable drops into lost requests.
"""

import numpy as np
import pytest

import jax

from gubernator_tpu.ops.batch import fingerprint_columns, pack_requests
from gubernator_tpu.parallel import ShardedEngine, make_mesh
from gubernator_tpu.parallel.a2a import a2a_capacity_sigma, pair_capacity
from gubernator_tpu.parallel.mesh import shard_of
from gubernator_tpu.types import RateLimitRequest, MINUTE


def req(key, hits=1, limit=10, created_at=None):
    return RateLimitRequest(
        name="cap", unique_key=key, hits=hits, limit=limit, duration=MINUTE,
        created_at=created_at,
    )


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "tests require the 8-device CPU mesh"
    return make_mesh(8)


def test_pair_capacity_curve_pinned():
    """The mean+5σ/pow2 curve at the default sigma. These exact values are
    what the exchange compiles against; changing GUBER_A2A_CAPACITY_SIGMA
    (or the +8 slack, or the pow2 floor) must update this table."""
    assert a2a_capacity_sigma() == 5.0
    expected = {
        (8, 8): 16,
        (16, 8): 32,
        (64, 8): 32,
        (256, 8): 128,
        (1024, 8): 256,
        (16384, 8): 4096,
        (8, 1): 32,
        (1024, 1): 2048,
    }
    got = {k: pair_capacity(*k) for k in expected}
    assert got == expected


def test_pair_capacity_properties():
    for D in (1, 2, 4, 8, 16):
        prev = 0
        for c in (8, 16, 64, 256, 1024, 4096, 16384):
            C = pair_capacity(c, D)
            # pow2 ≥ 8 (shape reuse), covers the mean (D·C ≥ c), monotone
            assert C >= 8 and (C & (C - 1)) == 0
            assert D * C >= c, (c, D, C)
            assert C >= prev
            prev = C


def test_pair_capacity_sigma_knob(monkeypatch):
    """The env knob moves the curve (read per trace, host-side) without
    touching the pow2/slack structure."""
    base = pair_capacity(1024, 8)
    monkeypatch.setenv("GUBER_A2A_CAPACITY_SIGMA", "0")
    low = pair_capacity(1024, 8)
    monkeypatch.setenv("GUBER_A2A_CAPACITY_SIGMA", "20")
    high = pair_capacity(1024, 8)
    assert low <= base <= high
    assert low == 256   # int(128) + 8 → pow2
    assert high == 512  # int(128 + 20·11.31…) + 8 → pow2


def _same_owner_keys(n_want: int, mesh) -> list:
    """Keys whose fingerprints all route to one shard (the overflow corpus:
    every source device's block sends its whole c rows to one destination
    pair, exceeding C)."""
    N = 8000
    names = np.array(["cap"] * N, dtype=object)
    keys = np.array([f"k{i}" for i in range(N)], dtype=object)
    fps, _ = fingerprint_columns(names, keys)
    shards = shard_of(fps, 8)
    target = int(shards[0])
    picked = [f"k{i}" for i in range(N) if int(shards[i]) == target][:n_want]
    assert len(picked) == n_want
    return picked


def test_overflow_drop_contract_matches_pair_capacity(mesh, frozen_now):
    """Entering the dispatch at terminal depth (no retries, no host
    fallback) surfaces raw exchange overflow: the number of dropped rows
    must equal the per-pair excess over pair_capacity exactly, every drop
    must carry BOTH flags (dropped → not persisted, unprocessed → never
    probed), and the drops must be observable in the dedicated counter."""
    from gubernator_tpu.ops.engine import _pad_size

    t = frozen_now
    eng = ShardedEngine(mesh, capacity_per_shard=4096, route="device",
                        dedup="host")
    picked = _same_owner_keys(512, mesh)
    hb, _errs = pack_requests([req(k, created_at=t) for k in picked], t)
    n = hb.fp.shape[0]
    D = 8
    c = _pad_size(max(1, -(-n // D)), floor=8)
    C = pair_capacity(c, D)
    # per-source-device excess: rows n..c of each block overflow their
    # single destination pair (row i lands on source device i // c)
    per_src = np.bincount(np.arange(n) // c, minlength=D)
    expected_drops = int(np.maximum(per_src - C, 0).sum())
    _, (s, l, r, tt, dropped, h) = eng._dispatch(
        hb, depth=3, count=np.asarray(hb.active)
    )
    assert int(dropped.sum()) == expected_drops
    assert expected_drops > 0  # the corpus must actually force overflow
    assert eng.stats.unprocessed_dropped == expected_drops
    assert eng.stats.dropped == expected_drops
    # rows that DID fit were persisted exactly once
    ok = ~dropped
    assert (r[ok] == 9).all()


def test_overflow_retries_recover_and_dedup_relieves_capacity(mesh, frozen_now):
    """Full-path flood: retries (host-grid fallback at terminal depth) must
    resolve every row. With in-trace dedup, a hot DUPLICATE flood at the
    same owner stops pressuring capacity entirely: each source block
    collapses the duplicates to one carrier (≤ 1 slot per pair), so zero
    exchange drops — the MoE "token dropping" analog only sees unique keys."""
    t = frozen_now
    # distinct-key flood: capacity overflow happens, retries absorb it
    eng = ShardedEngine(mesh, capacity_per_shard=4096, route="device",
                        dedup="device")
    picked = _same_owner_keys(512, mesh)
    out = eng.check([req(k, created_at=t) for k in picked], now_ms=t)
    assert all(r.error == "" for r in out)
    assert all(r.remaining == 9 for r in out)

    # duplicate flood of ONE owned key: per-source dedup leaves ≤ 8 carriers
    # mesh-wide, far under capacity → no unprocessed drops at depth 0
    eng2 = ShardedEngine(mesh, capacity_per_shard=4096, route="device",
                         dedup="device")
    hot = picked[0]
    out = eng2.check(
        [req(hot, hits=1, limit=1 << 20, created_at=t) for _ in range(512)],
        now_ms=t,
    )
    assert all(r.error == "" for r in out)
    # aggregate semantics: every duplicate shares the post-sum response
    assert len({r.remaining for r in out}) == 1
    assert out[0].remaining == (1 << 20) - 512
    assert eng2.stats.unprocessed_dropped == 0
    assert eng2.stats.dropped == 0
