"""Event channel + trace propagation tests (reference TestEventChannel
functional_test.go:2169 and the MetadataCarrier propagation,
metadata_carrier.go / peer_client.go:140-142)."""

import asyncio
import functools

import pytest

from gubernator_tpu import tracing
from gubernator_tpu.client import V1Client
from gubernator_tpu.types import Behavior, RateLimitRequest

from tests.cluster import Cluster, daemon_config, wait_for


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


def req(key, name="ev", hits=1, limit=10, **kw):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit, duration=60_000, **kw
    )


# ------------------------------------------------------------- event channel


@async_test
async def test_event_channel_fires_for_owner_hits():
    from gubernator_tpu.service.daemon import Daemon

    events: asyncio.Queue = asyncio.Queue()
    d = await Daemon.spawn(daemon_config(), event_channel=events)
    client = V1Client(d.conf.grpc_address)
    try:
        await client.get_rate_limits([req("a"), req("b", hits=3)])
        got = [await asyncio.wait_for(events.get(), 5) for _ in range(2)]
        by_key = {e.request.unique_key: e for e in got}
        assert set(by_key) == {"a", "b"}
        assert by_key["a"].response.remaining == 9
        assert by_key["b"].response.remaining == 7
        assert by_key["b"].request.hits == 3
    finally:
        await client.close()
        await d.close()


@async_test
async def test_event_channel_fires_on_owner_for_forwarded_hits():
    """Forwarded items raise the event on the OWNER daemon, not the
    forwarder (the reference's event fires inside getLocalRateLimit)."""
    channels = {}

    async def start(n):
        ds = []
        from gubernator_tpu.service.daemon import Daemon
        from gubernator_tpu.types import PeerInfo

        for i in range(n):
            q = asyncio.Queue()
            dd = await Daemon.spawn(daemon_config(), event_channel=q)
            channels[dd.conf.advertise_address] = q
            ds.append(dd)
        peers = [dd.peer_info() for dd in ds]
        for dd in ds:
            dd.set_peers([PeerInfo(**vars(p)) for p in peers])
        return Cluster(ds)

    c = await start(3)
    try:
        owner = c.find_owning_daemon("ev", "fwd-key")
        non_owner = c.non_owning_daemons("ev", "fwd-key")[0]
        client = V1Client(non_owner.conf.grpc_address)
        try:
            resp = await client.get_rate_limits([req("fwd-key")])
            assert resp.responses[0].error == ""
        finally:
            await client.close()
        ev = await asyncio.wait_for(
            channels[owner.conf.advertise_address].get(), 5
        )
        assert ev.request.unique_key == "fwd-key"
        assert channels[non_owner.conf.advertise_address].empty()
    finally:
        await c.stop()


# ------------------------------------------------------------------ tracing


def test_traceparent_roundtrip_and_malformed():
    span = tracing.new_span()
    meta = {}
    tok = tracing._current.set(span)
    try:
        tracing.inject(meta)
    finally:
        tracing._current.reset(tok)
    got = tracing.extract(meta)
    assert got == span

    assert tracing.parse_traceparent("nonsense") is None
    assert tracing.parse_traceparent("00-" + "0" * 32 + "-" + "1" * 16 + "-01") is None
    assert tracing.parse_traceparent("00-" + "a" * 32 + "-" + "0" * 16 + "-01") is None
    assert tracing.parse_traceparent("zz-" + "a" * 32 + "-" + "b" * 16 + "-xx") is None
    ok = tracing.parse_traceparent("00-" + "a" * 32 + "-" + "b" * 16 + "-01")
    assert ok is not None and ok.trace_id == "a" * 32


@async_test
async def test_trace_propagates_to_owner_across_forwarding():
    """A client-supplied traceparent must arrive at the owner daemon with the
    same trace_id (one distributed trace per request)."""
    c = await Cluster.start(3)
    seen = []
    old_hook = tracing.span_hook
    tracing.span_hook = lambda name, span: seen.append((name, span))
    try:
        non_owner = c.non_owning_daemons("trace", "tkey")[0]
        client = V1Client(non_owner.conf.grpc_address)
        trace_id = "ab" * 16
        try:
            resp = await client.get_rate_limits(
                [
                    req(
                        "tkey",
                        name="trace",
                        metadata={"traceparent": f"00-{trace_id}-{'cd' * 8}-01"},
                    )
                ]
            )
            assert resp.responses[0].error == ""
        finally:
            await client.close()
        await wait_for(
            lambda: asyncio.sleep(0, [n for n, s in seen if n == "GetPeerRateLimits"])
        )
        peer_scopes = [s for n, s in seen if n == "GetPeerRateLimits"]
        assert any(s.trace_id == trace_id for s in peer_scopes), (
            f"owner never saw trace {trace_id}: {seen}"
        )
        ingress = [s for n, s in seen if n == "GetRateLimits"]
        assert any(s.trace_id == trace_id for s in ingress)
    finally:
        tracing.span_hook = old_hook
        await c.stop()


@async_test
async def test_otlp_exporter_lands_spans_in_collector():
    """With OTEL_* envs set, finished scopes export as OTLP/HTTP JSON spans
    a real collector accepts (reference docs/tracing.md:43-54: exporters are
    configured by standard OTEL envs). Driven through real daemons: a
    forwarded request produces spans from BOTH daemons under ONE trace."""
    from aiohttp import web

    from gubernator_tpu.otel import OTLPJsonExporter

    received = []

    async def v1_traces(request):
        received.append(await request.json())
        return web.json_response({})

    app = web.Application()
    app.router.add_post("/v1/traces", v1_traces)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    url = f"http://127.0.0.1:{runner.addresses[0][1]}"

    exporter = OTLPJsonExporter(url, service_name="guber-test")
    old = tracing.exporter
    tracing.set_exporter(exporter)
    c = await Cluster.start(2)
    try:
        non_owner = c.non_owning_daemons("otel", "okey")[0]
        client = V1Client(non_owner.conf.grpc_address)
        trace_id = "12" * 16
        try:
            resp = await client.get_rate_limits(
                [req("okey", name="otel",
                     metadata={"traceparent": f"00-{trace_id}-{'34' * 8}-01"})]
            )
            assert resp.responses[0].error == ""
        finally:
            await client.close()
        # run_in_executor scopes may close a beat later; flush OFF the
        # event loop (the fake collector serves on this loop)
        await asyncio.sleep(0.05)
        await asyncio.get_running_loop().run_in_executor(None, exporter.flush)
        spans = [
            sp
            for body in received
            for rs in body["resourceSpans"]
            for ss in rs["scopeSpans"]
            for sp in ss["spans"]
        ]
        assert spans, "collector received no spans"
        svc = received[0]["resourceSpans"][0]["resource"]["attributes"][0]
        assert svc["value"]["stringValue"] == "guber-test"
        ours = [sp for sp in spans if sp["traceId"] == trace_id]
        names = {sp["name"] for sp in ours}
        # the non-owner's ingress scope AND the owner's peer-RPC scope share
        # the client's trace — one distributed trace across daemons
        assert "GetRateLimits" in names and "GetPeerRateLimits" in names
        for sp in ours:
            assert int(sp["endTimeUnixNano"]) > int(sp["startTimeUnixNano"])
    finally:
        tracing.set_exporter(old)
        exporter.close()
        await c.stop()
        await runner.cleanup()
