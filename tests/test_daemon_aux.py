"""Config system, DNS discovery, checkpoint/resume, TLS — the daemon's
auxiliary subsystems (reference config.go / dns.go / store.go / tls.go)."""

import asyncio
import functools
import os

import pytest

from gubernator_tpu.client import V1Client
from gubernator_tpu.config import (
    ConfigError,
    DaemonConfig,
    load_config_file,
    setup_daemon_config,
)
from gubernator_tpu.types import RateLimitRequest

from tests.cluster import daemon_config


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


def req(key, hits=1, limit=5):
    return RateLimitRequest(
        name="aux", unique_key=key, hits=hits, limit=limit, duration=60_000
    )


# -------------------------------------------------------------------- config


def test_config_from_env():
    env = {
        "GUBER_GRPC_ADDRESS": "127.0.0.1:9999",
        "GUBER_HTTP_ADDRESS": "127.0.0.1:9998",
        "GUBER_CACHE_SIZE": "12345",
        "GUBER_BATCH_WAIT": "2ms",
        "GUBER_GLOBAL_SYNC_WAIT": "1s",
        "GUBER_BATCH_LIMIT": "500",
        "GUBER_DATA_CENTER": "dc-west",
        "GUBER_FORCE_GLOBAL": "true",
    }
    conf = setup_daemon_config(env=env)
    assert conf.grpc_address == "127.0.0.1:9999"
    assert conf.cache_size == 12345
    assert conf.behaviors.batch_wait_ms == 2.0
    assert conf.behaviors.global_sync_wait_ms == 1000.0
    assert conf.behaviors.batch_limit == 500
    assert conf.data_center == "dc-west"
    assert conf.behaviors.force_global is True
    assert conf.advertise_address == "127.0.0.1:9999"


def test_config_file_seeds_env_but_real_env_wins(tmp_path):
    f = tmp_path / "guber.conf"
    f.write_text(
        "# comment\n\nGUBER_CACHE_SIZE=777\nGUBER_DATA_CENTER = dc-file\n"
    )
    env = {"GUBER_DATA_CENTER": "dc-env"}
    conf = setup_daemon_config(config_file=str(f), env=env)
    assert conf.cache_size == 777  # from file
    assert conf.data_center == "dc-env"  # real env wins (config.go:703-726)


def test_config_validation_errors():
    with pytest.raises(ConfigError, match="GUBER_PEER_DISCOVERY_TYPE"):
        setup_daemon_config(env={"GUBER_PEER_DISCOVERY_TYPE": "etcd"})
    with pytest.raises(ConfigError, match="GUBER_DNS_FQDN"):
        setup_daemon_config(env={"GUBER_PEER_DISCOVERY_TYPE": "dns"})
    with pytest.raises(ConfigError, match="GUBER_BATCH_LIMIT"):
        setup_daemon_config(env={"GUBER_BATCH_LIMIT": "5000"})
    with pytest.raises(ConfigError, match="integer"):
        setup_daemon_config(env={"GUBER_CACHE_SIZE": "lots"})
    with pytest.raises(ConfigError, match="key=value"):
        import tempfile

        with tempfile.NamedTemporaryFile("w", suffix=".conf", delete=False) as f:
            f.write("not-a-pair\n")
        try:
            load_config_file(f.name, {})
        finally:
            os.unlink(f.name)


# ----------------------------------------------------------------- discovery


@async_test
async def test_dns_pool_with_fake_resolver():
    """DNS pool against an injected resolver (reference dns_test.go:81-294):
    peer set follows record changes; empty answers never clear the list
    (dns.go:253-264)."""
    from gubernator_tpu.discovery.dns import DNSPool

    answers = {"cluster.test": ["10.0.0.1", "10.0.0.2"]}
    calls = []

    def resolver(fqdn, port):
        calls.append(fqdn)
        return [f"{ip}:{port}" for ip in answers.get(fqdn, [])]

    seen = []
    pool = DNSPool(
        fqdn="cluster.test",
        poll_ms=20.0,
        on_update=lambda peers: seen.append([p.grpc_address for p in peers]),
        self_address="10.0.0.1:1051",
        resolver=resolver,
    )
    await pool.start()
    try:
        assert seen == [["10.0.0.1:1051", "10.0.0.2:1051"]]
        # a record appears → update fires once with the new set
        answers["cluster.test"] = ["10.0.0.1", "10.0.0.2", "10.0.0.3"]
        await asyncio.sleep(0.08)
        assert seen[-1] == ["10.0.0.1:1051", "10.0.0.2:1051", "10.0.0.3:1051"]
        n_updates = len(seen)
        # resolver failure → stale list kept, no update fired
        answers["cluster.test"] = []
        await asyncio.sleep(0.08)
        assert len(seen) == n_updates
    finally:
        await pool.close()


@async_test
async def test_daemon_boots_from_env_with_dns():
    """Daemon boots from env alone (discovery=dns, fake-resolved to self)."""
    from unittest import mock

    from gubernator_tpu.discovery import dns as dns_mod
    from gubernator_tpu.service.daemon import Daemon

    conf = setup_daemon_config(
        env={
            "GUBER_GRPC_ADDRESS": "127.0.0.1:0",
            "GUBER_HTTP_ADDRESS": "127.0.0.1:0",
            "GUBER_PEER_DISCOVERY_TYPE": "dns",
            "GUBER_DNS_FQDN": "self.test",
            "GUBER_DNS_POLL": "50ms",
            "GUBER_CACHE_SIZE": "4096",
        }
    )

    def resolver(fqdn, port):
        return [f"127.0.0.1:{port}"]

    with mock.patch.object(dns_mod, "system_resolver", resolver):
        d = await Daemon.spawn(conf)
    try:
        # resolver returned self → single-peer cluster, serving locally
        client = V1Client(d.conf.grpc_address, timeout_s=15.0)
        resp = await client.get_rate_limits([req("dns1")])
        assert resp.responses[0].remaining == 4
        assert d.local_peers()[0].is_owner
        await client.close()
    finally:
        await d.close()


# ---------------------------------------------------------------- checkpoint


@async_test
async def test_checkpoint_survives_restart(tmp_path):
    """Kill/restart a daemon with GUBER_CHECKPOINT_PATH: remaining counts
    survive (reference TestLoader, store_test.go:76)."""
    from gubernator_tpu.service.daemon import Daemon

    snap = str(tmp_path / "table.ckpt")
    conf = daemon_config()
    conf.checkpoint_path = snap
    d = await Daemon.spawn(conf)
    client = V1Client(d.conf.grpc_address, timeout_s=15.0)
    resp = await client.get_rate_limits([req("ck1", hits=3, limit=10)])
    assert resp.responses[0].remaining == 7
    await client.close()
    await d.close()  # checkpoint written on graceful shutdown
    assert os.path.exists(snap)

    d2 = await Daemon.spawn(conf)  # restores on boot
    client = V1Client(d2.conf.grpc_address, timeout_s=15.0)
    try:
        resp = await client.get_rate_limits([req("ck1", hits=1, limit=10)])
        assert resp.responses[0].remaining == 6  # 10 - 3 (restored) - 1
    finally:
        await client.close()
        await d2.close()


def test_snapshot_rejects_garbage(tmp_path):
    import numpy as np

    from gubernator_tpu.store import load_snapshot, save_snapshot

    p = tmp_path / "x.ckpt"
    np.savez(p, magic=np.frombuffer(b"NOTGUB!", dtype=np.uint8), rows=np.zeros(3))
    with pytest.raises(ValueError, match="not a gubernator-tpu snapshot"):
        load_snapshot(str(p) + ".npz")  # np.savez appends .npz
    save_snapshot(str(p), np.arange(12, dtype=np.int32).reshape(3, 4))
    assert load_snapshot(str(p)).tolist()[1] == [4, 5, 6, 7]


# ----------------------------------------------------------------------- tls


@async_test
async def test_auto_tls_daemon():
    """AutoTLS: self-signed CA + cert generated at boot; a client presenting
    that CA connects; the gRPC listener speaks TLS (reference tls_test.go)."""
    import grpc

    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.service.tls import bundle_from_config

    conf = daemon_config()
    conf.tls_auto = True
    conf.http_address = ""  # gRPC-only for this test
    d = await Daemon.spawn(conf)
    try:
        bundle = bundle_from_config(d.conf)
        creds = grpc.ssl_channel_credentials(root_certificates=bundle.ca_pem)
        client = V1Client(d.conf.grpc_address, credentials=creds, timeout_s=15.0)
        resp = await client.get_rate_limits([req("tls1")])
        assert resp.responses[0].remaining == 4
        await client.close()
        # plaintext client must NOT work against the TLS port
        plain = V1Client(d.conf.grpc_address, timeout_s=2.0)
        with pytest.raises(grpc.aio.AioRpcError):
            await plain.get_rate_limits([req("tls2")])
        await plain.close()
    finally:
        await d.close()


@async_test
async def test_tls_http_gateway_and_status_listener(tmp_path):
    """With TLS on, the HTTP gateway serves HTTPS under the daemon's
    client-auth mode, and the separate status listener serves health +
    /metrics over TLS WITHOUT client certs (reference
    HTTPStatusListenAddress, daemon.go:150-155, 324-352) — previously /v1
    JSON and /metrics left the host in the clear while gRPC was mTLS."""
    import ssl

    import aiohttp

    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.service.tls import generate_self_signed

    bundle = generate_self_signed(("127.0.0.1",))
    ca = tmp_path / "ca.pem"; ca.write_bytes(bundle.ca_pem)
    crt = tmp_path / "crt.pem"; crt.write_bytes(bundle.cert_pem)
    key = tmp_path / "key.pem"; key.write_bytes(bundle.key_pem)

    conf = daemon_config(
        tls_ca_file=str(ca), tls_cert_file=str(crt), tls_key_file=str(key),
        tls_client_auth="verify", status_http_address="127.0.0.1:0",
    )
    d = await Daemon.spawn(conf)
    try:
        gw = f"https://{d.conf.http_address}"
        status = f"https://{d.conf.status_http_address}"
        trust = ssl.create_default_context(cadata=bundle.ca_pem.decode())
        trust.check_hostname = False
        mtls = ssl.create_default_context(cadata=bundle.ca_pem.decode())
        mtls.check_hostname = False
        mtls.load_cert_chain(str(crt), str(key))

        async with aiohttp.ClientSession() as s:
            # status listener: CA-trust only, no client cert → works
            async with s.get(f"{status}/metrics", ssl=trust) as r:
                assert r.status == 200
                assert b"gubernator_" in await r.read()
            async with s.get(f"{status}/v1/HealthCheck", ssl=trust) as r:
                assert r.status == 200
            # the status listener has NO rate-limit surface
            async with s.post(
                f"{status}/v1/GetRateLimits", json={"requests": []}, ssl=trust
            ) as r:
                assert r.status == 404
            # main gateway: requires a client certificate
            with pytest.raises(aiohttp.ClientError):
                async with s.get(f"{gw}/metrics", ssl=trust) as r:
                    await r.read()
            # with the client cert, the full JSON surface works over TLS
            async with s.post(
                f"{gw}/v1/GetRateLimits",
                json={"requests": [{"name": "t", "unique_key": "h",
                                    "hits": 1, "limit": 5,
                                    "duration": 60000}]},
                ssl=mtls,
            ) as r:
                assert r.status == 200
                body = await r.json()
                assert body["responses"][0]["remaining"] == "4"
            # plaintext against the TLS gateway fails
            with pytest.raises(aiohttp.ClientError):
                async with s.get(
                    f"http://{d.conf.http_address}/metrics"
                ) as r:
                    await r.read()
    finally:
        await d.close()


@async_test
async def test_mtls_cluster_forwards_between_peers(tmp_path):
    """mTLS (client_auth=verify): two daemons share a CA-signed cert from
    files; forwarding works peer-to-peer over mutual TLS, and a client
    WITHOUT a cert is rejected (reference tls_test.go:238 mTLS cluster)."""
    import grpc

    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.service.tls import generate_self_signed
    from gubernator_tpu.types import PeerInfo

    bundle = generate_self_signed(("127.0.0.1",))
    ca = tmp_path / "ca.pem"; ca.write_bytes(bundle.ca_pem)
    crt = tmp_path / "crt.pem"; crt.write_bytes(bundle.cert_pem)
    key = tmp_path / "key.pem"; key.write_bytes(bundle.key_pem)

    daemons = []
    for _ in range(2):
        conf = daemon_config(
            tls_ca_file=str(ca), tls_cert_file=str(crt), tls_key_file=str(key),
            tls_client_auth="verify", http_address="",
        )
        daemons.append(await Daemon.spawn(conf))
    peers = [d.peer_info() for d in daemons]
    for d in daemons:
        d.set_peers([PeerInfo(**vars(p)) for p in peers])
    try:
        creds = grpc.ssl_channel_credentials(
            root_certificates=bundle.ca_pem,
            private_key=bundle.key_pem,
            certificate_chain=bundle.cert_pem,
        )
        # find a key owned by daemon 1 and send it to daemon 0 → forwarded
        # over the mTLS peer channel
        for i in range(50):
            k = f"mtls-{i}"
            owner = daemons[0].get_peer("t_" + k)
            if owner.grpc_address == daemons[1].conf.advertise_address:
                break
        client = V1Client(daemons[0].conf.grpc_address, credentials=creds, timeout_s=15.0)
        try:
            resp = await client.get_rate_limits(
                [dict(name="t", unique_key=k, hits=1, limit=5, duration=60_000)]
            )
            assert resp.responses[0].error == ""
            assert resp.responses[0].remaining == 4
        finally:
            await client.close()
        # a client with the CA but NO client cert must be rejected
        noauth = V1Client(
            daemons[0].conf.grpc_address,
            credentials=grpc.ssl_channel_credentials(root_certificates=bundle.ca_pem),
            timeout_s=3.0,
        )
        with pytest.raises(grpc.aio.AioRpcError):
            await noauth.get_rate_limits([req("x")])
        await noauth.close()
    finally:
        for d in daemons:
            await d.close()


@async_test
async def test_tls_hot_cert_reload(tmp_path):
    """Rotating the PEM files on disk takes effect without a restart: new
    handshakes serve the new certificate (reference keypairReloader,
    tls.go:295-362)."""
    import os

    import grpc

    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.service.tls import generate_self_signed

    b1 = generate_self_signed(("127.0.0.1",))
    crt = tmp_path / "crt.pem"; crt.write_bytes(b1.cert_pem)
    key = tmp_path / "key.pem"; key.write_bytes(b1.key_pem)
    conf = daemon_config(
        tls_cert_file=str(crt), tls_key_file=str(key), http_address="",
    )
    d = await Daemon.spawn(conf)
    try:
        c1 = V1Client(
            d.conf.grpc_address,
            credentials=grpc.ssl_channel_credentials(root_certificates=b1.ca_pem),
            timeout_s=15.0,
        )
        assert (await c1.get_rate_limits([req("r1")])).responses[0].remaining == 4
        await c1.close()

        # rotate: a DIFFERENT CA signs the new pair
        b2 = generate_self_signed(("127.0.0.1",))
        crt.write_bytes(b2.cert_pem)
        key.write_bytes(b2.key_pem)
        future = __import__("time").time() + 2
        os.utime(crt, (future, future))
        os.utime(key, (future, future))

        # a client trusting ONLY the new CA now connects...
        c2 = V1Client(
            d.conf.grpc_address,
            credentials=grpc.ssl_channel_credentials(root_certificates=b2.ca_pem),
            timeout_s=15.0,
        )
        assert (await c2.get_rate_limits([req("r2")])).responses[0].remaining == 4
        await c2.close()
        # ...and one trusting only the OLD CA is refused
        c3 = V1Client(
            d.conf.grpc_address,
            credentials=grpc.ssl_channel_credentials(root_certificates=b1.ca_pem),
            timeout_s=3.0,
        )
        with pytest.raises(grpc.aio.AioRpcError):
            await c3.get_rate_limits([req("r3")])
        await c3.close()
    finally:
        await d.close()


@async_test
async def test_mtls_rotation_rewires_peer_channels(tmp_path, monkeypatch):
    """Rotating the CA+cert of a verify-mode cluster: the watcher rebuilds
    peer-client credentials and re-dials, so forwarding keeps working after
    the old CA stops being trusted."""
    import os
    import time as _time

    import grpc

    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.service.tls import generate_self_signed
    from gubernator_tpu.types import PeerInfo

    monkeypatch.setattr(Daemon, "cert_watch_interval_s", 0.1)
    b1 = generate_self_signed(("127.0.0.1",))
    ca = tmp_path / "ca.pem"; ca.write_bytes(b1.ca_pem)
    crt = tmp_path / "crt.pem"; crt.write_bytes(b1.cert_pem)
    key = tmp_path / "key.pem"; key.write_bytes(b1.key_pem)

    daemons = []
    for _ in range(2):
        conf = daemon_config(
            tls_ca_file=str(ca), tls_cert_file=str(crt), tls_key_file=str(key),
            tls_client_auth="verify", http_address="",
        )
        daemons.append(await Daemon.spawn(conf))
    peers = [d.peer_info() for d in daemons]
    for d in daemons:
        d.set_peers([PeerInfo(**vars(p)) for p in peers])
    try:
        # rotate everything to a fresh CA
        b2 = generate_self_signed(("127.0.0.1",))
        future = _time.time() + 2
        for p, data in [(ca, b2.ca_pem), (crt, b2.cert_pem), (key, b2.key_pem)]:
            p.write_bytes(data)
            os.utime(p, (future, future))
        await asyncio.sleep(0.5)  # a few watcher ticks

        creds = grpc.ssl_channel_credentials(
            root_certificates=b2.ca_pem,
            private_key=b2.key_pem,
            certificate_chain=b2.cert_pem,
        )
        for i in range(50):
            k = f"rot-{i}"
            if (
                daemons[0].get_peer("t_" + k).grpc_address
                == daemons[1].conf.advertise_address
            ):
                break
        client = V1Client(
            daemons[0].conf.grpc_address, credentials=creds, timeout_s=15.0
        )
        try:
            resp = await client.get_rate_limits(
                [dict(name="t", unique_key=k, hits=1, limit=5, duration=60_000)]
            )
            # the forwarded hop succeeded over the ROTATED mTLS pair
            assert resp.responses[0].error == ""
            assert resp.responses[0].remaining == 4
        finally:
            await client.close()
    finally:
        for d in daemons:
            await d.close()


@async_test
async def test_graceful_termination_delay_keeps_serving():
    """GUBER_GRACEFUL_TERMINATION_DELAY: liveness fails immediately on close
    while requests still serve during the delay window (reference
    daemon.go:389-391 LB de-registration)."""
    from gubernator_tpu.service.daemon import Daemon

    conf = daemon_config()
    conf.graceful_termination_delay_s = 0.6
    d = await Daemon.spawn(conf)
    client = V1Client(d.conf.grpc_address, timeout_s=15.0)
    try:
        await client.get_rate_limits([req("gt")])
        t0 = asyncio.get_running_loop().time()
        closer = asyncio.create_task(d.close())
        await asyncio.sleep(0.1)
        # liveness already failing (LBs de-register)...
        with pytest.raises(RuntimeError):
            d.live_check()
        # ...but traffic still serves inside the delay window
        r = await client.get_rate_limits([req("gt")])
        assert r.responses[0].error == ""
        await closer
        assert asyncio.get_running_loop().time() - t0 >= 0.6
    finally:
        await client.close()


@async_test
async def test_memory_loader_and_recording_store_hooks():
    """Custom Loader/Store hooks through the daemon lifecycle — the
    reference's embedding pattern (TestLoader/TestStore, store_test.go:76,127
    over in-tree MockLoader/MockStore)."""
    from gubernator_tpu.hashing import fingerprint
    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.store import MemoryLoader, RecordingStore

    loader = MemoryLoader()
    store = RecordingStore()
    d = await Daemon.spawn(daemon_config(), store=store, loader=loader)
    client = V1Client(d.conf.grpc_address)
    try:
        await client.get_rate_limits(
            [dict(name="ld", unique_key="k1", hits=3, limit=9, duration=60_000)]
        )
    finally:
        await client.close()
        await d.close()
    assert loader.load_called == 1
    assert loader.save_called == 1  # shutdown snapshot landed in memory
    assert fingerprint("ld", "k1") in store.touched_fps

    # a fresh daemon restoring from the SAME loader continues the counts
    d2 = await Daemon.spawn(daemon_config(), loader=loader)
    client = V1Client(d2.conf.grpc_address)
    try:
        r = await client.get_rate_limits(
            [dict(name="ld", unique_key="k1", hits=0, limit=9, duration=60_000)]
        )
        assert r.responses[0].remaining == 6  # 9 - 3 survived via MemoryLoader
    finally:
        await client.close()
        await d2.close()


def test_example_conf_parses_and_validates():
    """example.conf documents every knob; loading it must parse cleanly and
    produce a valid config (all entries are commented defaults, and any
    uncommented sample must round-trip)."""
    import os

    path = os.path.join(os.path.dirname(__file__), "..", "example.conf")
    env = {}
    load_config_file(path, env)
    conf = setup_daemon_config(env=env)
    conf.validate()


def test_coalesce_limit_env_reaches_the_batcher():
    from gubernator_tpu.service.daemon import Daemon

    conf = setup_daemon_config(
        env={
            "GUBER_GRPC_ADDRESS": "127.0.0.1:0",
            "GUBER_HTTP_ADDRESS": "",
            "GUBER_BATCH_COALESCE_LIMIT": "4096",
            "GUBER_CACHE_SIZE": "4096",
        }
    )
    d = Daemon(conf)  # batcher wiring happens in __init__, no spawn needed
    assert d.batcher.coalesce_limit == 4096
    d.runner.close()
    with pytest.raises(ConfigError):
        setup_daemon_config(env={"GUBER_BATCH_COALESCE_LIMIT": "0"})


@async_test
async def test_coalesce_limit_caps_dispatch_size():
    """The limit is a real per-dispatch cap: concurrent enqueues exceeding it
    split into multiple kernel dispatches of whole sub-batches."""
    from gubernator_tpu.ops.batch import columns_from_requests
    from gubernator_tpu.ops.engine import LocalEngine
    from gubernator_tpu.service.batcher import Batcher
    from gubernator_tpu.service.runner import EngineRunner

    engine = LocalEngine(capacity=4096)
    runner = EngineRunner(engine)
    sizes = []
    orig = runner.check  # the batcher's (pipelined) entry point

    async def spy(cols, now_ms=None, span=None):
        sizes.append(cols.fp.shape[0])
        return await orig(cols, now_ms=now_ms, span=span)

    runner.check = spy
    b = Batcher(runner, batch_wait_ms=5.0, coalesce_limit=32)
    reqs = lambda tag, n: columns_from_requests(
        [
            RateLimitRequest(
                name="cl", unique_key=f"{tag}-{i}", hits=1, limit=100,
                duration=60_000,
            )
            for i in range(n)
        ]
    )
    outs = await asyncio.gather(
        b.check(reqs("a", 20)), b.check(reqs("b", 20)), b.check(reqs("c", 20))
    )
    assert [o.status.shape[0] for o in outs] == [20, 20, 20]
    assert all(o.err.max() == 0 for o in outs)
    assert max(sizes) <= 32  # whole sub-batches, never past the cap
    assert len(sizes) >= 2  # really split
    await b.drain()
    runner.close()


def test_metric_flags_collectors():
    """GUBER_METRIC_FLAGS opts into process/runtime collector families
    (reference flags.go:19-57 FlagOSMetrics/FlagGolangMetrics wired at
    daemon.go:293-306) — the flag must actually grow /metrics, not just
    parse."""
    from gubernator_tpu.service.metrics import DaemonMetrics

    base = DaemonMetrics().render().decode()
    assert "process_open_fds" not in base
    assert "python_gc_objects_collected" not in base

    both = DaemonMetrics(metric_flags="os,python").render().decode()
    assert "gubernator_process_open_fds" in both
    assert "gubernator_process_resident_memory_bytes" in both
    assert "python_gc_objects_collected_total" in both
    assert "python_info" in both

    # "golang" is accepted as an alias for the runtime collectors, and
    # unknown flags are ignored (logged), matching getEnvMetricFlags
    alias = DaemonMetrics(metric_flags="golang,bogus").render().decode()
    assert "python_gc_objects_collected_total" in alias
    assert "gubernator_process_open_fds" not in alias


@async_test
async def test_warm_shapes_pow2():
    """GUBER_WARM_SHAPES=pow2 pre-compiles every pow2 coalesce geometry at
    spawn so no production batch shape compiles on the request path; warm-up
    traffic must not leak into stats, and real requests still serve."""
    from gubernator_tpu.service.daemon import Daemon

    conf = daemon_config()
    conf.behaviors.warm_shapes = "pow2"
    conf.behaviors.coalesce_limit = 64  # 16..64 → 3 shapes, keeps CI fast
    d = await Daemon.spawn(conf)
    client = V1Client(d.conf.grpc_address)
    try:
        assert d.engine.stats.checks == 0  # warm-up is not traffic
        rs = await client.get_rate_limits(
            [req(f"w{i}") for i in range(40)]  # coalesces into a pow2 shape
        )
        assert len(rs.responses) == 40
        assert all(r.error == "" for r in rs.responses)
        # the pipelined door applies the stats delta fire-and-forget on the
        # engine thread AFTER replying — flush it before asserting
        await asyncio.get_running_loop().run_in_executor(
            d.runner._exec, lambda: None
        )
        assert d.engine.stats.checks == 40
    finally:
        await client.close()
        await d.close()
