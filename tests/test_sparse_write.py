"""Block-sparse write parity suite (ops/kernel2._write_sparse).

`write="sparse"` must be bit-identical to `write="xla"` (and the dense
sweep) in BOTH table state and responses: the sparse grid only changes
which blocks the Pallas pipeline streams, never what lands in them.
Exercised on the CPU interpret lowering (the XLA-emulated path tier-1
runs): random token/leaky/mixed traffic, conflict-heavy same-bucket
batches, block-boundary slots (bucket 0, bucket BLK-1, the last block),
the sharded mesh path, and the GLOBAL collective-sync install path on the
virtual 8-device mesh.

Every parity config asserts `resolve_write` actually resolved "sparse" —
a table too small for the coverage crossover would silently fall back to
the sweep and test nothing.
"""

import numpy as np
import pytest

from gubernator_tpu.ops.batch import RequestColumns
from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.ops.kernel2 import (
    resolve_write,
    sparse_geometry,
    sweep_geometry,
)
from gubernator_tpu.parallel import make_mesh
from gubernator_tpu.parallel.global_sync import GlobalShardedEngine
from gubernator_tpu.parallel.sharded import ShardedEngine
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest, SECOND

NOW = 1_700_000_000_000
# 2^15 buckets: large enough that a ≤64-row pass stays under the sparse
# coverage crossover (64 steps × 64 rows × 4 ≪ 32768), small enough for CPU
CAP = 1 << 18


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def _engines(**kw):
    return {
        w: LocalEngine(capacity=CAP, write_mode=w, **kw)
        for w in ("xla", "sweep", "sparse")
    }


def _assert_parity(engines, reqs, now):
    outs = {w: e.check(reqs, now_ms=now) for w, e in engines.items()}
    for w in ("sweep", "sparse"):
        for i, (a, b) in enumerate(zip(outs["xla"], outs[w])):
            assert (a.status, a.limit, a.remaining, a.reset_time, a.error) == (
                b.status, b.limit, b.remaining, b.reset_time, b.error,
            ), f"write={w} row {i}"


def _assert_tables_equal(engines):
    base = np.asarray(engines["xla"].table.rows)
    for w in ("sweep", "sparse"):
        assert np.array_equal(base, np.asarray(engines[w].table.rows)), w


def _random_requests(rng, n, keyspace, now, algo=None):
    reqs = []
    for _ in range(n):
        a = algo
        if a is None:
            a = (
                Algorithm.TOKEN_BUCKET
                if rng.random() < 0.5
                else Algorithm.LEAKY_BUCKET
            )
        behavior = 0
        r = rng.random()
        if r < 0.15:
            behavior |= Behavior.RESET_REMAINING
        if 0.15 <= r < 0.3:
            behavior |= Behavior.DRAIN_OVER_LIMIT
        reqs.append(
            RateLimitRequest(
                name="sp",
                unique_key=f"k{rng.integers(keyspace)}",
                hits=int(rng.integers(0, 4)),
                limit=int(rng.integers(1, 20)),
                duration=int(rng.integers(1, 5)) * SECOND,
                algorithm=a,
                behavior=behavior,
                created_at=now,
            )
        )
    return reqs


def test_sparse_resolves_sparse_at_parity_geometry():
    """Tripwire: if this fails, every parity test below is testing the
    dense sweep twice instead of the sparse grid."""
    eng = LocalEngine(capacity=CAP)
    nb = eng.table.rows.shape[0]
    # engine pads ≤64-row passes to 64
    assert resolve_write("sparse", nb, 64) == "sparse"


@pytest.mark.parametrize("algo", [None, Algorithm.TOKEN_BUCKET,
                                  Algorithm.LEAKY_BUCKET])
def test_sparse_parity_random_traffic(algo):
    """token-only / leaky-only / mixed random streams: responses and final
    table state bit-identical across all three write modes."""
    rng = np.random.default_rng(3 if algo is None else int(algo))
    engines = _engines()
    now = NOW
    for _ in range(4):
        reqs = _random_requests(rng, 48, keyspace=70, now=now, algo=algo)
        _assert_parity(engines, reqs, now)
        now += int(rng.integers(0, 2500))
    _assert_tables_equal(engines)
    ex = engines["xla"].stats
    for w in ("sweep", "sparse"):
        s = engines[w].stats
        assert (s.cache_hits, s.cache_misses, s.over_limit) == (
            ex.cache_hits, ex.cache_misses, ex.over_limit,
        ), w


def _cols(fps, now, hits=1):
    n = fps.shape[0]
    return RequestColumns(
        fp=np.asarray(fps, dtype=np.int64),
        algo=np.zeros(n, dtype=np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=np.full(n, hits, dtype=np.int64),
        limit=np.full(n, 100, dtype=np.int64),
        burst=np.zeros(n, dtype=np.int64),
        duration=np.full(n, 60_000, dtype=np.int64),
        created_at=np.full(n, now, dtype=np.int64),
        err=np.zeros(n, dtype=np.int8),
    )


def _cols_parity(engines, fps, now):
    outs = {
        w: e.check_columns(_cols(fps, now), now_ms=now)
        for w, e in engines.items()
    }
    for w in ("sweep", "sparse"):
        for f in outs["xla"]._fields:
            np.testing.assert_array_equal(
                getattr(outs["xla"], f), getattr(outs[w], f),
                err_msg=f"write={w} col {f}",
            )


def test_sparse_parity_conflict_heavy_same_bucket():
    """12 distinct keys forced into ONE bucket (direct fp injection:
    bucket = fp % NB): inserts overflow the 8 lanes, the claim dedup and
    retry/eviction machinery fires, and every write mode must persist the
    same survivors."""
    engines = _engines()
    nb = engines["xla"].table.rows.shape[0]
    target_bucket = 7
    fps = np.array([target_bucket + nb * k for k in range(1, 13)],
                   dtype=np.int64)
    now = NOW
    for step in range(3):
        _cols_parity(engines, fps, now)
        now += 1000
    _assert_tables_equal(engines)


def test_sparse_parity_block_boundary_slots():
    """Targets pinned to sparse-block edges: bucket 0 (slot 0), bucket
    BLK-1 (last bucket of block 0), the first bucket of the last block, and
    bucket NB-1 (the table's final row) — the off-by-one surface of the
    dirty-block index math."""
    engines = _engines()
    nb = engines["xla"].table.rows.shape[0]
    blk, _u, _g = sparse_geometry(nb, 64)
    buckets = [0, blk - 1, nb - blk, nb - 1]
    fps = []
    for b in buckets:
        for k in range(1, 4):  # several keys per boundary bucket
            fps.append((b + nb * k) or nb)  # fp 0 is the empty sentinel
    fps = np.array(fps, dtype=np.int64)
    now = NOW
    for step in range(3):
        _cols_parity(engines, fps, now)
        now += 1000
    _assert_tables_equal(engines)


def test_sparse_parity_sharded_mesh(mesh):
    """The sharded path (one table shard per device, shard_map dispatch)
    with write_mode="sparse" matches "xla" row-for-row on the virtual
    8-device CPU mesh."""
    kw = dict(capacity_per_shard=CAP)
    ex = ShardedEngine(mesh, write_mode="xla", **kw)
    es = ShardedEngine(mesh, write_mode="sparse", **kw)
    rng = np.random.default_rng(11)
    now = NOW
    for _ in range(3):
        reqs = _random_requests(rng, 64, keyspace=90, now=now)
        rx = ex.check(reqs, now_ms=now)
        rs = es.check(reqs, now_ms=now)
        for i, (a, b) in enumerate(zip(rx, rs)):
            assert (a.status, a.remaining, a.reset_time, a.error) == (
                b.status, b.remaining, b.reset_time, b.error,
            ), f"row {i}"
        now += 1500
    assert np.array_equal(ex.snapshot(), es.snapshot())


def test_sparse_parity_global_install(mesh):
    """The GLOBAL plane end-to-end with write_mode="sparse": replica
    answers, owner applies, and the collective sync's broadcast INSTALL all
    run the sparse write and must converge to the same authoritative and
    replica state as "xla"."""
    kw = dict(capacity_per_shard=CAP, sync_out=64)
    ex = GlobalShardedEngine(mesh, write_mode="xla", **kw)
    es = GlobalShardedEngine(mesh, write_mode="sparse", **kw)
    now = NOW
    reqs = [
        RateLimitRequest(
            name="g", unique_key=f"gk{i}", hits=1, limit=10,
            duration=60_000, behavior=Behavior.GLOBAL, created_at=now,
        )
        for i in range(24)
    ]
    for eng in (ex, es):
        for home in (0, 3):
            eng.check(reqs, now_ms=now, home_shard=home)
        eng.sync(now_ms=now)
    # post-sync: answers come from replica installs written sparse vs xla
    rx = ex.check(reqs, now_ms=now + 10, home_shard=5)
    rs = es.check(reqs, now_ms=now + 10, home_shard=5)
    for i, (a, b) in enumerate(zip(rx, rs)):
        assert (a.status, a.remaining, a.reset_time) == (
            b.status, b.remaining, b.reset_time,
        ), f"row {i}"
    ex.sync(now_ms=now + 10)
    es.sync(now_ms=now + 10)
    assert np.array_equal(ex.snapshot(), es.snapshot())
    assert np.array_equal(
        np.asarray(ex.replica.rows), np.asarray(es.replica.rows)
    )
    gx, gs = ex.global_stats, es.global_stats
    assert (gx.broadcasts_applied, gx.updates_installed) == (
        gs.broadcasts_applied, gs.updates_installed,
    )


def test_sparse_geometry_bounds():
    for nb, batch in [(1 << 15, 64), (1 << 18, 4096), (1 << 21, 16384),
                      (512, 16), (2048 * 3, 1024)]:
        blk, u, g = sparse_geometry(nb, batch)
        assert nb % blk == 0, (nb, batch)
        assert blk * u <= 1 << 19
        assert u & (u - 1) == 0 or u == batch
        assert g == min(nb // blk, batch)
        if batch >= u:
            assert batch % u == 0


def test_resolve_write_crossover(monkeypatch):
    # big batch over a small table → worst-case coverage crosses → sweep
    assert resolve_write("sparse", 1 << 11, 1 << 17) == "sweep"
    # serving shape over a big table → sparse
    assert resolve_write("sparse", 1 << 21, 4096) == "sparse"
    # other modes pass through untouched
    assert resolve_write("sweep", 1 << 11, 1 << 17) == "sweep"
    assert resolve_write("xla", 1 << 21, 64) == "xla"
    with pytest.raises(ValueError):
        resolve_write("bogus", 1 << 21, 64)
    # the crossover knob moves the boundary: an absurdly strict factor
    # pushes even the serving shape back to the sweep
    monkeypatch.setenv("GUBER_WRITE_SPARSE_CROSSOVER", "1e9")
    assert resolve_write("sparse", 1 << 21, 4096) == "sweep"
    monkeypatch.setenv("GUBER_WRITE_SPARSE_CROSSOVER", "1")
    assert resolve_write("sparse", 1 << 21, 16384) == "sparse"


def test_sparse_geometry_matches_probe_window_contract():
    """The probe marks window overflow with the SAME (blk, u) the write
    uses; sanity-pin that sparse geometry never hands the probe a window
    smaller than the dense floor (64) for pow2 batches ≥ 64."""
    for nb in (1 << 15, 1 << 18, 1 << 21):
        for batch in (64, 1024, 4096):
            _blk, u, _g = sparse_geometry(nb, batch)
            assert u >= min(64, batch)
            _dblk, du = sweep_geometry(nb, batch)
            assert du >= min(64, batch)
