"""Topology-change survivability: device-side ownership handoff.

Covers the handoff stack bottom-up: the extract/merge/tombstone device ops
(ops/table2, kernel2.merge2) and their conservative-merge invariant, the
fp→ring-point ownership sidecar (peers/ownership.py), the vectorized
ring-successor lookup, the set_peers churn satellites (breaker preservation,
dropped-client drain leak), the TransferState RPC's idempotency, and the
cluster-level flows: scale-out rebalance, graceful drain + hand-back on a
rolling restart, and breaker-gated chunk retry against real injected faults
(tests/chaos.py). The long multi-restart chaos scenario is tier-1; see
test_chaos.py for the PR-1 fault-tolerance suite it builds on.
"""

import asyncio
import functools
import time

import numpy as np
import pytest

import gubernator_tpu  # noqa: F401  (x64 on)
from gubernator_tpu.client import V1Client
from gubernator_tpu.config import BehaviorConfig, ConfigError, DaemonConfig
from gubernator_tpu.ops.batch import RequestColumns
from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.ops.table2 import F, LIMIT, REM_I, STAMP_HI, STAMP_LO
from gubernator_tpu.peers.hash_ring import ReplicatedConsistentHash
from gubernator_tpu.peers.ownership import OwnershipIndex
from gubernator_tpu.service.breaker import BreakerState
from gubernator_tpu.types import PeerInfo, RateLimitRequest

from tests.cluster import Cluster, metric_value, scrape, wait_for

NOW = 1_700_000_000_000


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


def req(key, name="ho", hits=1, limit=10, burst=0, duration=600_000):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit, burst=burst,
        duration=duration,
    )


def cols(fp, hits=3, limit=10, algo=None, duration=600_000, now=NOW):
    n = fp.shape[0]
    if algo is None:
        algo = (np.arange(n) % 2).astype(np.int32)  # token + leaky mix
    return RequestColumns(
        fp=fp.astype(np.int64),
        algo=algo,
        behavior=np.zeros(n, dtype=np.int32),
        hits=np.full(n, hits, dtype=np.int64),
        limit=np.full(n, limit, dtype=np.int64),
        burst=np.zeros(n, dtype=np.int64),
        duration=np.full(n, duration, dtype=np.int64),
        created_at=np.full(n, now, dtype=np.int64),
        err=np.zeros(n, dtype=np.int8),
    )


# ------------------------------------------------- device ops: extract/merge


def test_extract_merge_tombstone_roundtrip():
    """Extract packs exactly the live rows; merging them into a fresh table
    reproduces the counters (token AND leaky); tombstone removes them at the
    source. The no-fault row-parity the chaos acceptance asserts, at the
    engine level."""
    src = LocalEngine(capacity=4096, write_mode="xla")
    n = 200
    fp = np.arange(1, n + 1, dtype=np.int64) * 7919
    src.check_columns(cols(fp), now_ms=NOW)
    fps, slots = src.extract_live(NOW)
    assert fps.shape == (n,) and slots.shape == (n, F)
    assert set(fps.tolist()) == set(fp.tolist())

    dst = LocalEngine(capacity=4096, write_mode="xla")
    assert dst.merge_rows(fps, slots, now_ms=NOW) == n
    rc = dst.check_columns(cols(fp, hits=0), now_ms=NOW)
    assert (rc.remaining == 7).all()

    assert src.tombstone_fps(fps) == n
    assert src.live_count(NOW) == 0
    fps2, _ = src.extract_live(NOW)
    assert fps2.shape[0] == 0
    # tombstoning missing fps is a no-op, not an eviction
    assert dst.tombstone_fps(np.asarray([999_999_999], dtype=np.int64)) == 0
    assert dst.live_count(NOW) == n


def test_conservative_merge_never_grants_capacity():
    """The invariant that makes transfers retry-safe: remaining=min. A
    duplicated chunk, a crossed transfer, or a stale source row can never
    raise remaining above the receiver's current state."""
    eng = LocalEngine(capacity=1024, write_mode="xla")
    fp = np.asarray([1234567], dtype=np.int64)
    eng.check_columns(cols(fp, hits=3, algo=np.zeros(1, np.int32)), now_ms=NOW)
    stale_fps, stale_slots = eng.extract_live(NOW)  # remaining = 7

    # spend 4 more → remaining 3; merging the stale (remaining 7) snapshot
    # back must NOT resurrect capacity
    eng.check_columns(cols(fp, hits=4, algo=np.zeros(1, np.int32)), now_ms=NOW)
    eng.merge_rows(stale_fps, stale_slots, now_ms=NOW)
    rc = eng.check_columns(cols(fp, hits=0, algo=np.zeros(1, np.int32)), now_ms=NOW)
    assert int(rc.remaining[0]) == 3

    # idempotent replay: merging twice is the same as once
    eng.merge_rows(stale_fps, stale_slots, now_ms=NOW)
    rc = eng.check_columns(cols(fp, hits=0, algo=np.zeros(1, np.int32)), now_ms=NOW)
    assert int(rc.remaining[0]) == 3


def test_merge_duplicate_fps_single_slot():
    """A crossed transfer can carry the same fingerprint twice in one chunk:
    duplicates must merge sequentially (the claim machinery's unique-fp
    contract) — never land in two slots, where the stale copy could later
    resurrect capacity."""
    src = LocalEngine(capacity=1024, write_mode="xla")
    fp = np.asarray([555], dtype=np.int64)
    src.check_columns(cols(fp, algo=np.zeros(1, np.int32)), now_ms=NOW)
    fps, slots = src.extract_live(NOW)
    dst = LocalEngine(capacity=1024, write_mode="xla")
    assert dst.merge_rows(
        np.concatenate([fps, fps]), np.concatenate([slots, slots]), now_ms=NOW
    ) == 2
    assert dst.live_count(NOW) == 1
    rc = dst.check_columns(cols(fp, hits=0, algo=np.zeros(1, np.int32)), now_ms=NOW)
    assert int(rc.remaining[0]) == 7


def test_merge_newest_config_wins_and_expired_dropped():
    eng = LocalEngine(capacity=1024, write_mode="xla")
    fp = np.asarray([42424242], dtype=np.int64)
    eng.check_columns(cols(fp, hits=2, limit=10, algo=np.zeros(1, np.int32)), now_ms=NOW)
    fps, slots = eng.extract_live(NOW)

    # incoming row with a NEWER stamp and a different limit: config follows
    # the newer stamp, remaining stays min (read back via the stored slot —
    # response `limit` always echoes the request's)
    newer = slots.copy()
    newer[0, LIMIT] = 50
    stamp = NOW + 5_000
    newer[0, STAMP_LO] = np.int64(stamp).astype(np.int32)  # low 32, wrapped
    newer[0, STAMP_HI] = np.int32(stamp >> 32)
    eng.merge_rows(fps, newer, now_ms=NOW)
    _, stored = eng.extract_live(NOW)
    assert int(stored[0, LIMIT]) == 50
    assert int(stored[0, REM_I]) == 8  # min(8, 8): capacity not re-granted

    # an OLDER stamp must not roll the config back
    older = slots.copy()
    older[0, LIMIT] = 5
    eng.merge_rows(fps, older, now_ms=NOW)
    _, stored = eng.extract_live(NOW)
    assert int(stored[0, LIMIT]) == 50
    assert int(stored[0, REM_I]) == 8

    # fully expired incoming rows are dropped, not resurrected
    dst = LocalEngine(capacity=1024, write_mode="xla")
    assert dst.merge_rows(fps, slots, now_ms=NOW + 700_000) == 0
    assert dst.live_count(NOW + 700_000) == 0


def test_sharded_extract_merge_tombstone_parity():
    """Same surface on the 8-device CPU mesh: extract from a sharded source,
    conservative-merge into a sharded destination, tombstone at the source
    — zero rows lost (the ci/bench_cpu.py handoff smoke's correctness
    half)."""
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.sharded import ShardedEngine

    mesh = make_mesh(8)
    src = ShardedEngine(mesh, capacity_per_shard=1 << 12, write_mode="xla")
    rng = np.random.default_rng(11)
    n = 700
    fp = rng.integers(1, (1 << 63) - 1, size=n, dtype=np.int64)
    src.check_columns(cols(fp), now_ms=NOW)
    fps, slots = src.extract_live(NOW)
    assert set(fps.tolist()) == set(fp.tolist())

    dst = ShardedEngine(mesh, capacity_per_shard=1 << 12, write_mode="xla")
    assert dst.merge_rows(fps, slots, now_ms=NOW) == n
    rc = dst.check_columns(cols(fp, hits=0), now_ms=NOW)
    assert (rc.remaining == 7).all()
    # replay (idempotent) + conservative floor after further spend
    dst.check_columns(cols(fp, hits=2), now_ms=NOW)
    dst.merge_rows(fps, slots, now_ms=NOW)
    rc = dst.check_columns(cols(fp, hits=0), now_ms=NOW)
    assert (rc.remaining == 5).all()
    assert src.tombstone_fps(fps) == n
    assert src.live_count(NOW) == 0


# -------------------------------------------------- sidecar + ring successor


def test_ownership_index_record_lookup_prune():
    idx = OwnershipIndex()
    fps = np.asarray([3, 1, 2], dtype=np.int64)
    pts = np.asarray([30, 10, 20], dtype=np.uint32)
    idx.record(fps, pts)
    assert len(idx) == 3
    points, found = idx.points_for(np.asarray([2, 9, 1], dtype=np.int64))
    assert found.tolist() == [True, False, True]
    assert points.tolist() == [20, 0, 10]
    idx.discard(np.asarray([1], dtype=np.int64))
    assert len(idx) == 2
    assert idx.prune(np.asarray([3], dtype=np.int64)) == 1
    assert len(idx) == 1
    # record_keys matches the picker's own hash function
    ring = ReplicatedConsistentHash()
    idx.record_keys([7], ["a_b"], ring.hash_fn)
    points, found = idx.points_for(np.asarray([7], dtype=np.int64))
    assert found[0] and int(points[0]) == ring.hash_fn(b"a_b")


def test_owners_of_exclude_matches_get_exclude():
    """The vectorized drain lookup (owners_of(points, exclude)) must agree
    with the scalar route-around primitive (get(key, exclude)) — the drain
    hands rows exactly to the owners the surviving ring will resolve."""
    ring = ReplicatedConsistentHash()
    peers = [PeerInfo(grpc_address=f"10.0.0.{i}:80") for i in range(4)]
    for p in peers:
        ring.add(p)
    keys = [f"name_k{i}" for i in range(64)]
    points = np.asarray([ring.hash_fn(k.encode()) for k in keys], np.uint32)
    gone = frozenset({peers[1].grpc_address})
    vec = ring.owners_of(points, exclude=gone)
    for k, owner in zip(keys, vec):
        assert owner.grpc_address == ring.get(k, gone).grpc_address
        assert owner.grpc_address not in gone
    with pytest.raises(RuntimeError):
        ring.owners_of(points, exclude=frozenset(p.grpc_address for p in peers))


# --------------------------------------------------- set_peers satellites


def test_handoff_config_knobs():
    from gubernator_tpu.config import setup_daemon_config

    conf = setup_daemon_config(env={
        "GUBER_HANDOFF_DEADLINE": "2s",
        "GUBER_HANDOFF_CHUNK_ROWS": "128",
        "GUBER_HANDOFF_ENABLED": "false",
    })
    assert conf.behaviors.handoff_deadline_ms == 2000.0
    assert conf.behaviors.handoff_chunk_rows == 128
    assert conf.behaviors.handoff_enabled is False
    with pytest.raises(ConfigError):
        DaemonConfig(
            behaviors=BehaviorConfig(handoff_chunk_rows=0)
        ).validate()
    with pytest.raises(ConfigError):
        DaemonConfig(
            behaviors=BehaviorConfig(handoff_deadline_ms=0)
        ).validate()


def test_set_peers_no_loop_queues_dropped_clients_for_drain():
    """Satellite: with no running event loop, set_peers used to swallow the
    RuntimeError and LEAK dropped PeerClient channels. They now queue and
    close on the next loop entry."""
    conf = DaemonConfig(
        grpc_address="127.0.0.1:19251", cache_size=1024,
    )
    d = None
    try:
        from gubernator_tpu.service.daemon import Daemon

        d = Daemon(conf)
        peers = [
            PeerInfo(grpc_address="127.0.0.1:19251"),
            PeerInfo(grpc_address="127.0.0.1:19252"),
            PeerInfo(grpc_address="127.0.0.1:19253"),
        ]
        d.set_peers([PeerInfo(**vars(p)) for p in peers])
        clients = list(d._peer_clients.values())
        assert len(clients) == 2
        # shrink with NO loop running: clients must queue, not leak
        d.set_peers([PeerInfo(**vars(peers[0]))])
        assert len(d._orphaned_clients) == 2
        assert not any(c._closed for c in clients)

        async def enter_loop():
            # next loop entry: any set_peers flushes the orphan queue
            d.set_peers([PeerInfo(**vars(peers[0]))])
            await asyncio.sleep(0.05)

        asyncio.run(enter_loop())
        assert d._orphaned_clients == []
        assert all(c._closed for c in clients)
    finally:
        if d is not None:
            d.runner.close()


@async_test
async def test_set_peers_churn_reuses_clients_and_preserves_breakers():
    """Satellite: repeated add/remove cycles must reuse PeerClients by
    address while present, and a peer that flaps OUT and back IN must keep
    its breaker state — a flapping discovery backend must not reset open
    breakers to closed."""
    c = await Cluster.start(3, handoff_enabled=False)
    d0 = c.daemons[0]
    addr1 = c.daemons[1].conf.advertise_address
    try:
        all_peers = [d.peer_info() for d in c.daemons]
        client_before = d0._peer_clients[addr1]
        # same peer set again: client objects are reused by address
        d0.set_peers([PeerInfo(**vars(p)) for p in all_peers])
        assert d0._peer_clients[addr1] is client_before

        # trip the breaker, then flap the peer out and back in
        breaker = client_before.breaker
        for _ in range(10):
            breaker.record_failure()
        assert breaker.state is BreakerState.OPEN
        without = [p for p in all_peers if p.grpc_address != addr1]
        for cycle in range(3):
            d0.set_peers([PeerInfo(**vars(p)) for p in without])
            assert addr1 not in d0._peer_clients
            d0.set_peers([PeerInfo(**vars(p)) for p in all_peers])
            got = d0._peer_clients[addr1]
            assert got.breaker is breaker, f"cycle {cycle}"
            assert got.breaker.state is BreakerState.OPEN, f"cycle {cycle}"
        await asyncio.sleep(0.05)  # orphaned clients drain on the loop
    finally:
        await c.stop()


# ------------------------------------------------------ TransferState RPC


@async_test
async def test_transfer_state_idempotent_and_validated():
    from gubernator_tpu.proto import handoff_pb2 as handoff_pb
    from gubernator_tpu.service.wire import transfer_chunk_pb

    c = await Cluster.start(1)
    d = c.daemons[0]
    try:
        src = LocalEngine(capacity=1024, write_mode="xla")
        fp = np.arange(1, 33, dtype=np.int64) * 101
        now = d.now_ms()
        src.check_columns(cols(fp, now=now), now_ms=now)
        fps, slots = src.extract_live(now)
        pts = np.arange(fps.shape[0], dtype=np.uint32)
        req_pb = transfer_chunk_pb("t-1", 0, 1, "src:1", now, fps, pts, slots)

        r1 = await d.transfer_state(req_pb)
        assert r1.merged == 32 and not r1.duplicate
        # the receiver recorded the rows' ring points for onward routing
        points, found = d.ownership.points_for(fps)
        assert found.all() and (points == pts).all()
        # replayed chunk: answered from the ledger, no double merge
        r2 = await d.transfer_state(req_pb)
        assert r2.duplicate and r2.merged == 32
        assert await d.runner.live_count() == 32

        # malformed buffers fail loudly instead of merging garbage
        bad = handoff_pb.TransferStateReq()
        bad.CopyFrom(req_pb)
        bad.transfer_id = "t-2"
        bad.fps = bad.fps[:-8]
        with pytest.raises(ValueError):
            await d.transfer_state(bad)
    finally:
        await c.stop()


# ------------------------------------------------------- cluster-level flows


@async_test
async def test_scale_out_rebalance_moves_state():
    """set_peers diff path: adding a daemon launches a device-side extract
    at the old owners, and keys whose ring owner moved keep their counters
    at the new owner (conservative-merged, not answered fresh)."""
    from gubernator_tpu.service.daemon import Daemon
    from tests.cluster import daemon_config

    c = await Cluster.start(2)
    client = V1Client(c.daemons[0].conf.grpc_address)
    extra = None
    try:
        keys = [f"mv{i}" for i in range(24)]
        rs = (await client.get_rate_limits(
            [req(k, hits=4) for k in keys]
        )).responses
        assert all(r.error == "" and r.remaining == 6 for r in rs)

        extra = await Daemon.spawn(daemon_config())
        c.daemons.append(extra)
        peers = [d.peer_info() for d in c.daemons]
        for d in c.daemons:
            d.set_peers([PeerInfo(**vars(p)) for p in peers])
        await c.settle_handoffs()

        # keys now owned by the NEW daemon must still carry their counters
        moved = [
            k for k in keys if c.find_owning_daemon("ho", k) is extra
        ]
        assert moved, "expected some keys to move to the new daemon"
        rs = (await client.get_rate_limits(
            [req(k, hits=0) for k in moved]
        )).responses
        assert all(r.remaining == 6 for r in rs), [r.remaining for r in rs]
        s = await scrape(extra)
        assert metric_value(
            s, "gubernator_handoff_rows_total", phase="merged"
        ) >= len(moved)
    finally:
        await client.close()
        if extra is not None and extra not in c.daemons:
            await extra.close()
        await c.stop()


@async_test
async def test_drain_restart_preserves_state_no_fault():
    """Graceful drain + hand-back (the rolling-restart building block),
    no-fault case: counters survive a full stop/start of their owner, the
    drained daemon advertises "leaving" while it drains, and the cluster's
    transfer row-counts are in parity (no chunk lost)."""
    c = await Cluster.start(3)
    client = V1Client(c.daemons[1].conf.grpc_address)
    try:
        keys, i = [], 0
        while len(keys) < 6:
            k = f"dr{i}"
            i += 1
            if c.find_owning_daemon("ho", k) is c.daemons[0]:
                keys.append(k)
        rs = (await client.get_rate_limits(
            [req(k, hits=3) for k in keys]
        )).responses
        assert all(r.error == "" and r.remaining == 7 for r in rs)

        # health flips to "leaving" the moment the drain starts
        statuses = []

        async def probe_leaving():
            statuses.append((await c.daemons[0].health_check()).status)

        c.daemons[0]._leaving = True
        await probe_leaving()
        c.daemons[0]._leaving = False
        assert statuses == ["leaving"]

        await c.drain_restart(0)

        rs = (await client.get_rate_limits(
            [req(k, hits=0) for k in keys]
        )).responses
        assert all(r.remaining == 7 for r in rs), [r.remaining for r in rs]

        # no-fault parity: every extracted row was merged somewhere, every
        # transferred row was tombstoned at its source (the restarted
        # daemon's own counters died with it; survivors' must balance)
        phases = {p: 0.0 for p in (
            "extracted", "transferred", "merged", "tombstoned"
        )}
        for d in c.daemons:
            s = await scrape(d)
            for p in phases:
                phases[p] += metric_value(
                    s, "gubernator_handoff_rows_total", phase=p
                )
        assert phases["merged"] >= len(keys)  # drain + hand-back both merge
        assert phases["extracted"] == phases["transferred"] == phases[
            "tombstoned"
        ]
    finally:
        await client.close()
        await c.stop()


@async_test
async def test_drain_chunk_retry_against_blackhole_then_heal():
    """Breaker-driven retry of failed transfer chunks: a blackholed
    destination makes chunks fail (and retry) until the proxy heals inside
    the deadline — after which every row lands; nothing is lost."""
    c = await Cluster.start(
        2,
        chaos=True,
        behaviors=BehaviorConfig(
            batch_wait_ms=1.0,
            batch_timeout_ms=300.0,
            global_timeout_ms=300.0,
            peer_breaker_errors=2,
            peer_breaker_backoff_base_ms=100.0,
            peer_breaker_backoff_cap_ms=200.0,
            handoff_deadline_ms=8_000.0,
            handoff_chunk_rows=8,
        ),
    )
    d0, d1 = c.daemons
    client = V1Client(d0.conf.grpc_address)
    try:
        keys, i = [], 0
        while len(keys) < 10:
            k = f"bh{i}"
            i += 1
            if c.find_owning_daemon("ho", k) is d0:
                keys.append(k)
        await client.get_rate_limits([req(k, hits=3) for k in keys])
        live_before = await d0.runner.live_count()

        # blackhole the destination, heal it mid-drain
        c.proxy_for(d1).set_mode("blackhole")

        async def heal_later():
            await asyncio.sleep(1.0)
            c.proxy_for(d1).heal()

        heal = asyncio.create_task(heal_later())
        stats = await d0.handoff.drain()
        await heal
        assert stats["extracted"] == len(keys)
        assert stats["transferred"] == len(keys)  # retried through the fault
        assert stats["snapshotted"] == 0
        s = await scrape(d0)
        assert metric_value(s, "gubernator_handoff_chunk_retries_total") >= 1
        assert await d0.runner.live_count() == live_before - len(keys)
        assert await d1.runner.live_count() >= len(keys)
    finally:
        await client.close()
        await c.stop()


@async_test
async def test_drain_deadline_snapshots_unacked_remainder():
    """A destination that never heals: the drain gives up at the deadline,
    keeps the unacked rows in the table (they reach the shutdown checkpoint)
    and counts them `snapshotted`."""
    c = await Cluster.start(
        2,
        chaos=True,
        behaviors=BehaviorConfig(
            batch_wait_ms=1.0,
            batch_timeout_ms=200.0,
            peer_breaker_errors=2,
            peer_breaker_backoff_base_ms=100.0,
            peer_breaker_backoff_cap_ms=200.0,
            handoff_deadline_ms=900.0,
            handoff_chunk_rows=8,
        ),
    )
    d0, d1 = c.daemons
    client = V1Client(d0.conf.grpc_address)
    try:
        keys, i = [], 0
        while len(keys) < 6:
            k = f"dl{i}"
            i += 1
            if c.find_owning_daemon("ho", k) is d0:
                keys.append(k)
        await client.get_rate_limits([req(k, hits=3) for k in keys])
        live_before = await d0.runner.live_count()
        c.proxy_for(d1).set_mode("blackhole")
        t0 = time.perf_counter()
        stats = await d0.handoff.drain()
        assert time.perf_counter() - t0 < 5.0  # bounded by the deadline
        assert stats["extracted"] == len(keys)
        assert stats["transferred"] == 0
        assert stats["snapshotted"] == len(keys)
        # nothing tombstoned: the rows survive into the shutdown checkpoint
        assert await d0.runner.live_count() == live_before
    finally:
        await client.close()
        await c.stop()


# --------------------------------------- acceptance: rolling restart, chaos


@async_test
async def test_rolling_restart_under_traffic_bounded_over_admission():
    """The ISSUE's acceptance scenario: a 3-daemon cluster under continuous
    traffic, every daemon drained and restarted in turn, a chaos delay
    injected mid-handoff on one cycle. Every key's total admissions stay
    within one configured burst of the limit (the conservative-merge bound —
    without handoff each ownership move re-grants a full fresh bucket), and
    traffic never sees errors."""
    LIMIT_N, BURST = 25, 25
    c = await Cluster.start(
        3,
        chaos=True,
        behaviors=BehaviorConfig(
            batch_wait_ms=1.0,
            batch_timeout_ms=2_000.0,
            global_timeout_ms=2_000.0,
            handoff_deadline_ms=8_000.0,
        ),
    )
    keys = [f"rr{i}" for i in range(12)]
    admitted = {k: 0 for k in keys}
    errors: list = []
    lost = [0]  # batches whose response was lost mid-close (the server may
    # have admitted them — at-least-once from the client's view)
    draining = {"i": -1}
    stop = asyncio.Event()

    async def traffic():
        clients = {}
        try:
            while not stop.is_set():
                alive = [
                    d for j, d in enumerate(c.daemons) if j != draining["i"]
                ]
                d = alive[int(time.monotonic() * 1000) % len(alive)]
                cl = clients.get(d.conf.grpc_address)
                if cl is None:
                    cl = clients[d.conf.grpc_address] = V1Client(
                        d.conf.grpc_address
                    )
                try:
                    rs = (await cl.get_rate_limits(
                        [req(k, hits=1, limit=LIMIT_N, burst=BURST)
                         for k in keys]
                    )).responses
                except Exception:
                    lost[0] += 1  # transport race with a closing daemon
                else:
                    for k, r in zip(keys, rs):
                        if r.error:
                            errors.append(r.error)
                        elif r.status == 0:  # UNDER_LIMIT → admitted
                            admitted[k] += 1
                await asyncio.sleep(0.05)
        finally:
            for cl in clients.values():
                await cl.close()

    task = asyncio.create_task(traffic())
    try:
        await asyncio.sleep(0.2)  # some budget spent before the first drain
        for i in range(3):
            draining["i"] = i
            if i == 1:
                # chaos: slow one survivor's peer plane mid-handoff — chunk
                # sends ride the delay and still land inside the deadline
                c.proxy_for(c.daemons[2]).set_mode("delay", delay_s=0.05)
            await c.drain_restart(i)
            if i == 1:
                c.proxy_for(c.daemons[2]).heal()
            draining["i"] = -1
            await asyncio.sleep(0.3)
        # run until every key is exhausted (all daemons serving)
        async def all_over():
            cl = V1Client(c.daemons[0].conf.grpc_address)
            try:
                rs = (await cl.get_rate_limits(
                    [req(k, hits=0, limit=LIMIT_N, burst=BURST)
                     for k in keys]
                )).responses
                return all(r.remaining == 0 for r in rs)
            finally:
                await cl.close()

        await wait_for(all_over, timeout_s=30)
    finally:
        stop.set()
        await task
        await c.stop()

    # the occasional in-flight forward can race a de-registration; sustained
    # errors mean the routing/handoff plumbing is broken
    assert len(errors) <= 3, errors[:5]
    for k in keys:
        # conservative-merge bound: within one configured burst of the
        # limit. WITHOUT handoff each of the six ownership moves could
        # re-grant a fresh bucket (worst case ≈ limit × moves). Only the
        # UPPER bound is a sound invariant: at-least-once delivery (a
        # response lost mid-close, a forward retried after the owner
        # already applied it) spends server-side budget the client never
        # counts, so admitted can legitimately fall a few short of the
        # limit — and wait_for(all_over) already proved every bucket
        # exhausted server-side. Both failure modes only push admitted
        # DOWN; over-admission cannot hide behind them.
        assert admitted[k] <= LIMIT_N + BURST, (k, admitted[k], lost[0])
        assert admitted[k] >= LIMIT_N // 2, (k, admitted[k])  # sanity
