"""Hash-table mechanics: probing, collisions, expiry reclaim, eviction.

The analog of the reference's cache tests (lrucache_test.go) — but eviction here
is expiry-stamp-based (SURVEY.md §7) rather than LRU, so the assertions target:
slots reclaimed after expiry, soonest-expiring victim chosen when full, and the
unexpired-eviction alarm counter (reference lrucache.go:138-149).
"""

import numpy as np
import pytest

from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.ops.table2 import live_count2 as live_count
from gubernator_tpu.types import RateLimitRequest, Status, MINUTE, SECOND


def req(key, hits=1, limit=10, duration=MINUTE, created_at=None, name="tbl"):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit, duration=duration,
        created_at=created_at,
    )


def test_many_keys_one_by_one_fill_and_persist(frozen_now):
    eng = LocalEngine(capacity=128)
    t = frozen_now
    for i in range(60):
        (r,) = eng.check([req(f"k{i}", created_at=t)], now_ms=t)
        assert r.remaining == 9
    # all 60 keys retained; second round decrements each
    for i in range(60):
        (r,) = eng.check([req(f"k{i}", created_at=t)], now_ms=t)
        assert r.remaining == 8, f"key k{i} lost state"
    assert live_count(eng.table, t) == 60


def test_expired_slots_are_reclaimed(frozen_now):
    eng = LocalEngine(capacity=64)
    t = frozen_now
    out = eng.check([req(f"a{i}", duration=SECOND, created_at=t) for i in range(30)], now_ms=t)
    assert all(r.status == Status.UNDER_LIMIT for r in out)
    assert live_count(eng.table, t) == 30
    # a second wave after expiry reuses the dead slots: no drops, no unexpired
    # evictions, and the old keys are gone
    t2 = t + 2 * SECOND
    out = eng.check([req(f"b{i}", duration=SECOND, created_at=t2) for i in range(30)], now_ms=t2)
    assert all(r.status == Status.UNDER_LIMIT for r in out)
    assert eng.stats.dropped == 0
    assert eng.stats.evicted_unexpired == 0
    (r,) = eng.check([req("a0", duration=SECOND, created_at=t2)], now_ms=t2)
    assert r.remaining == 9  # fresh bucket — original a0 state expired


def test_unexpired_eviction_when_full(frozen_now):
    # capacity 8: fill it with live keys, then insert more one at a time —
    # each new key must evict a live victim and count it
    eng = LocalEngine(capacity=8)
    t = frozen_now
    for i in range(8):
        eng.check([req(f"full{i}", created_at=t)], now_ms=t)
    assert live_count(eng.table, t) == 8
    before = eng.stats.evicted_unexpired
    for i in range(4):
        (r,) = eng.check([req(f"extra{i}", created_at=t)], now_ms=t)
        assert r.status == Status.UNDER_LIMIT
    assert eng.stats.evicted_unexpired == before + 4
    assert live_count(eng.table, t) == 8  # still full, evictions replaced


def test_colliding_keys_coexist_via_probing(frozen_now):
    # with capacity C, keys whose fingerprints share fp % C land in the same
    # probe window; linear probing must keep them all live. Use a tiny table
    # and enough keys that collisions are guaranteed.
    eng = LocalEngine(capacity=16)
    t = frozen_now
    keys = [f"c{i}" for i in range(12)]
    for k in keys:
        eng.check([req(k, created_at=t)], now_ms=t)
    # every key retained despite shared windows
    for k in keys:
        (r,) = eng.check([req(k, hits=0, created_at=t)], now_ms=t)
        assert r.remaining == 9, f"key {k} lost"


def test_oversubscribed_single_batch_answers_all(frozen_now):
    # 64 inserts into 16 slots in one call: every request gets a correct
    # decision; the engine's claim-retry loop persists what fits, later
    # inserts evict earlier ones (expiry-stamp eviction ≈ the reference's LRU
    # thrash under over-capacity), and the alarm counter fires.
    eng = LocalEngine(capacity=16)
    t = frozen_now
    out = eng.check([req(f"x{i}", created_at=t) for i in range(64)], now_ms=t)
    assert all(r.status == Status.UNDER_LIMIT for r in out)
    assert live_count(eng.table, t) == 16  # table full, not corrupted
    assert eng.stats.evicted_unexpired > 0


def test_store_and_reread_across_many_batches(frozen_now):
    # steady-state churn: repeated mixed batches keep per-key counters exact
    eng = LocalEngine(capacity=512)
    t = frozen_now
    rng = np.random.default_rng(7)
    counts = {}
    for _ in range(20):
        ks = rng.choice(100, size=32, replace=False)
        out = eng.check([req(f"m{k}", limit=1000, created_at=t) for k in ks], now_ms=t)
        for k, r in zip(ks, out):
            counts[k] = counts.get(k, 0) + 1
            assert r.remaining == 1000 - counts[k]
