"""Device-resident request ring suite (service/ring.py) + the warm_up
zero-compiles gate.

The acceptance surface of the always-on-chip tentpole's serving half:

* the ring protocol is correct on its own terms — slot claim/publish
  ordering (stage before the ingress fence), sequence-number fencing
  (`seq_in`/`seq_out` carry ticket+1, launches walk tickets strictly in
  order), bounded backpressure (never more than S outstanding, no drops,
  no reordering), zero-loss drain, RingClosed to racing submitters;
* the daemon integration is byte-identical to the direct dispatch path
  (same runner surface by construction) and feeds the
  `dispatch_launches_total{path="ring"}` / `ring_occupancy` telemetry;
* `Daemon.warm_up` leaves ZERO compiles for the warmed shapes — including
  the fused install/merge walk graphs when GUBER_WALK_KERNEL=pallas —
  verified through jax.monitoring compile events, so no production
  dispatch of a warmed shape ever pays a trace on the request path.
"""

import asyncio
import os
import time

import numpy as np
import pytest

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

from gubernator_tpu.service.ring import RequestRing, RingClosed

# one fresh XLA compile fires exactly one of these events; cached
# executions fire none (verified against jax 0.4.x)
COMPILE_EVENT = "/jax/compilation_cache/compile_requests_use_cache"


class StubRunner:
    """The minimal runner surface the ring drives: check_wire + the stage
    observer. Echoes the submitted payload so reordering is detectable,
    and tracks concurrent in-flight dispatches so the occupancy bound is
    assertable."""

    def __init__(self, delay=0.0, fail_on=None, fuse=True):
        self.delay = delay
        self.fail_on = fail_on  # payload value that raises
        self.fuse = fuse  # False => check_wire returns None (fallback)
        self.launch_order = []
        self.active = 0
        self.max_active = 0
        self.check_calls = 0

    def _observe_stage(self, stage, t0, span=None):
        pass

    async def check_wire(self, parts, now_ms=None, span=None,
                         launch_path="xla"):
        assert launch_path == "ring"
        if not self.fuse:
            return None
        self.active += 1
        self.max_active = max(self.max_active, self.active)
        self.launch_order.append(parts[0])
        try:
            if self.delay:
                await asyncio.sleep(self.delay)
            if self.fail_on is not None and parts[0] == self.fail_on:
                raise RuntimeError(f"boom on {parts[0]}")
            return ("rc", parts[0])
        finally:
            self.active -= 1

    async def check(self, cols, now_ms=None, span=None, launch_path="xla"):
        assert launch_path == "ring"
        self.check_calls += 1
        return ("cols-rc", cols)


# ------------------------------------------------------------ ring protocol


def test_ring_rejects_degenerate_sizes():
    with pytest.raises(ValueError):
        RequestRing(StubRunner(), slots=1)
    with pytest.raises(ValueError):
        RequestRing(StubRunner(), slots=0)


def test_ring_orders_launches_and_echoes_results():
    """Launch order is strictly ticket order even under racing submitters,
    and every submitter gets ITS chunk's response back."""
    async def go():
        r = StubRunner(delay=0.001)
        ring = RequestRing(r, slots=4)
        outs = await asyncio.gather(*(ring.submit([i]) for i in range(24)))
        return r, ring, outs

    r, ring, outs = asyncio.run(go())
    assert r.launch_order == sorted(r.launch_order)  # ticket order
    assert [o[1] for o in outs] == list(range(24))  # no cross-wiring
    d = ring.debug()
    assert d["published"] == d["consumed"] == d["launches"] == 24
    assert d["occupancy"] == 0


def test_ring_backpressure_bounds_occupancy_without_drops():
    """More submitters than slots: submits WAIT (no drops), in-flight
    dispatches never exceed S, FIFO order is preserved."""
    async def go():
        r = StubRunner(delay=0.002)
        ring = RequestRing(r, slots=3)
        outs = await asyncio.gather(*(ring.submit([i]) for i in range(32)))
        return r, ring, outs

    r, ring, outs = asyncio.run(go())
    assert r.max_active <= 3  # occupancy bound held
    assert ring.max_occupancy <= 3
    assert ring.backpressure_waits > 0  # the bound actually engaged
    assert [o[1] for o in outs] == list(range(32))  # nothing dropped/reordered
    assert ring.debug()["launches"] == 32


def test_ring_sequence_fences():
    """seq_in/seq_out carry ticket+1 per slot (never 0 for a used slot),
    and after full retirement the egress fence has caught the ingress."""
    async def go():
        ring = RequestRing(StubRunner(), slots=4)
        await asyncio.gather(*(ring.submit([i]) for i in range(11)))
        return ring

    ring = asyncio.run(go())
    # 11 tickets over 4 slots: slot s last carried the highest ticket
    # t ≡ s (mod 4) below 11, fence word t+1
    for s in range(4):
        last = max(t for t in range(11) if t % 4 == s)
        assert int(ring.seq_in[s]) == last + 1
        assert int(ring.seq_out[s]) == last + 1


def test_ring_drain_is_zero_loss_and_closes_intake():
    """drain() retires every published ticket before parking the loop; a
    submitter racing the drain gets RingClosed (the batcher's cue to fall
    back to the direct path — no request lost either way)."""
    async def go():
        r = StubRunner(delay=0.005)
        ring = RequestRing(r, slots=4)
        pending = [asyncio.create_task(ring.submit([i])) for i in range(8)]
        await asyncio.sleep(0.006)  # some in flight, some queued
        await ring.drain()
        outs = await asyncio.gather(*pending, return_exceptions=True)
        late = None
        try:
            await ring.submit(["late"])
        except RingClosed as exc:
            late = exc
        return ring, outs, late

    ring, outs, late = asyncio.run(go())
    ok = [o for o in outs if not isinstance(o, Exception)]
    closed = [o for o in outs if isinstance(o, RingClosed)]
    assert len(ok) + len(closed) == 8  # every submit resolved, one way
    assert len(ok) == ring.debug()["launches"]  # published == launched
    assert [o[1] for o in ok] == sorted(o[1] for o in ok)  # order kept
    assert isinstance(late, RingClosed)
    assert ring.debug()["closed"]


def test_ring_drain_without_traffic():
    async def go():
        ring = RequestRing(StubRunner(), slots=2)
        await ring.drain()  # never started: must not hang
        with pytest.raises(RingClosed):
            await ring.submit(["x"])
        return ring

    ring = asyncio.run(go())
    assert ring.debug()["published"] == 0


def test_ring_nonfusable_chunk_falls_back_to_columns_path():
    """A chunk check_wire rejects rides runner.check (the columns path)
    INSIDE the ring dispatch — same as Batcher._dispatch's fallback."""
    import gubernator_tpu.service.ring as ring_mod

    async def go(monkey_concat):
        ring_mod.concat_columns, orig = monkey_concat, ring_mod.concat_columns
        try:
            r = StubRunner(fuse=False)
            ring = RequestRing(r, slots=2)

            class P:
                cols = "c0"

            out = await ring.submit([P()])
            return r, ring, out
        finally:
            ring_mod.concat_columns = orig

    r, ring, out = asyncio.run(go(lambda cols_list: cols_list[0]))
    assert r.check_calls == 1
    assert out == ("cols-rc", "c0")
    assert ring.fallbacks == 1


def test_ring_dispatch_error_propagates_to_submitter():
    """A failing dispatch resolves ONLY its own submitter's poll with the
    error; later tickets still retire cleanly."""
    async def go():
        r = StubRunner(delay=0.001, fail_on=2)
        ring = RequestRing(r, slots=4)
        outs = await asyncio.gather(
            *(ring.submit([i]) for i in range(6)), return_exceptions=True
        )
        return ring, outs

    ring, outs = asyncio.run(go())
    assert isinstance(outs[2], RuntimeError)
    good = [o for i, o in enumerate(outs) if i != 2]
    assert [o[1] for o in good] == [0, 1, 3, 4, 5]
    assert ring.debug()["consumed"] == 6  # the failed slot still retired


# ------------------------------------------------------- daemon integration


NOW = None  # wall clock at corpus build: inside created_at tolerance


def _corpus(reqs, rows, tag):
    from gubernator_tpu.proto import gubernator_pb2 as pb

    now = int(time.time() * 1000)
    return [
        pb.GetRateLimitsReq(
            requests=[
                pb.RateLimitReq(
                    name="ring", unique_key=f"{tag}r{r}i{i}", hits=1,
                    limit=1 << 20, duration=3_600_000, created_at=now,
                )
                for i in range(rows)
            ]
        ).SerializeToString()
        for r in range(reqs)
    ]


def _conf(**beh):
    from gubernator_tpu.config import BehaviorConfig, DaemonConfig

    beh.setdefault("batch_wait_ms", 1.0)
    beh.setdefault("front_workers", 4)
    return DaemonConfig(
        grpc_address="127.0.0.1:0", http_address="", cache_size=1 << 14,
        behaviors=BehaviorConfig(**beh),
    )


def test_daemon_ring_byte_identity(monkeypatch):
    """The whole point: a ring-fed daemon serves byte-identical responses
    to a direct-dispatch daemon over the same corpus, while the launch
    counter splits by path and the drain retires everything."""
    monkeypatch.setenv("GUBER_WIRE_COMPACT", "1")
    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.service.metrics import parse_metrics

    async def go():
        dr = await Daemon.spawn(_conf(ring_enable=True, ring_slots=4))
        dd = await Daemon.spawn(_conf())
        datas = _corpus(16, 48, "x")
        r1 = await asyncio.gather(*(dr.get_rate_limits_raw(x) for x in datas))
        r2 = await asyncio.gather(*(dd.get_rate_limits_raw(x) for x in datas))
        scrape = parse_metrics(dr.metrics.render().decode())
        ringdbg = dr.ring.debug()
        nring = dr.batcher.ring_dispatches
        await dr.close()
        await dd.close()
        return r1, r2, scrape, ringdbg, nring, dr.ring.debug()

    r1, r2, scrape, dbg, nring, post = asyncio.run(go())
    assert r1 == r2  # byte-identical, request by request
    assert nring > 0 and dbg["launches"] == nring
    assert dbg["occupancy"] == 0  # everything retired before close
    launches = scrape["gubernator_tpu_dispatch_launches_total"]
    assert launches[(("path", "ring"),)] == nring
    assert (("path", "xla"),) in launches  # warm_up rode the direct path
    stages = scrape["gubernator_tpu_stage_duration_count"]
    assert stages[(("stage", "ring_put"),)] >= nring
    assert stages[(("stage", "ring_poll"),)] >= nring
    assert post["closed"]  # daemon.close drained the ring


def test_ring_config_env_plumbing():
    from gubernator_tpu.config import setup_daemon_config

    conf = setup_daemon_config(env={
        "GUBER_GRPC_ADDRESS": "127.0.0.1:0", "GUBER_HTTP_ADDRESS": "",
        "GUBER_RING_ENABLE": "1", "GUBER_RING_SLOTS": "8",
        "GUBER_WALK_KERNEL": "pallas",
    })
    assert conf.behaviors.ring_enable is True
    assert conf.behaviors.ring_slots == 8
    assert conf.walk_kernel == "pallas"


# ------------------------------------------------------ warm_up zero compiles


def _warm_shapes_again(d):
    """Re-drive the exact dispatch surface warm_up traced, with DIFFERENT
    values (shape-cache, not value-cache): the decide variants, the
    1-row install, and — when the fused walks are armed — the 1-row
    merge."""
    from gubernator_tpu.ops.batch import RequestColumns
    from gubernator_tpu.ops.table2 import F as F_FULL

    async def go():
        for algos in ([0], [2], [2, 3], [1]):
            n = len(algos)
            await d.runner.check_columns(RequestColumns(
                fp=np.arange(7, 7 + n, dtype=np.int64),
                algo=np.asarray(algos, dtype=np.int32),
                behavior=np.zeros(n, dtype=np.int32),
                hits=np.ones(n, dtype=np.int64),
                limit=np.full(n, 5, dtype=np.int64),
                burst=np.zeros(n, dtype=np.int64),
                duration=np.full(n, 1000, dtype=np.int64),
                created_at=np.zeros(n, dtype=np.int64),
                err=np.zeros(n, dtype=np.int8),
            ))
        await d.runner.install_columns(
            fp=np.asarray([9], dtype=np.int64),
            algo=np.zeros(1, dtype=np.int32),
            status=np.zeros(1, dtype=np.int32),
            limit=np.full(1, 3, dtype=np.int64),
            remaining=np.ones(1, dtype=np.int64),
            reset_time=np.full(1, 2, dtype=np.int64),
            duration=np.full(1, 2, dtype=np.int64),
            now_ms=2,
        )
        if getattr(d.engine, "walk_mode", "xla") == "pallas":
            await d.runner.merge_rows(
                np.asarray([11], dtype=np.int64),
                np.zeros((1, F_FULL), dtype=np.int32),
            )

    return go()


@pytest.mark.parametrize("walk", ["xla", "pallas"])
def test_warm_up_leaves_zero_compiles(monkeypatch, walk):
    """After Daemon.spawn (which runs warm_up), re-dispatching every warmed
    shape triggers ZERO fresh XLA compiles — including the fused
    install/merge walk graphs under GUBER_WALK_KERNEL=pallas (the
    always-on contract: no production dispatch of a warmed shape ever
    traces on the request path)."""
    import jax.monitoring as jm

    monkeypatch.setenv("GUBER_WALK_KERNEL", walk)
    from gubernator_tpu.service.daemon import Daemon

    compiles = []
    armed = [False]

    def listener(event, **kw):
        if armed[0] and event == COMPILE_EVENT:
            compiles.append(event)

    async def go():
        import jax
        import jax.numpy as jnp

        d = await Daemon.spawn(_conf())
        if walk == "pallas":
            assert d.engine.walk_mode == "pallas"
        jm.register_event_listener(listener)
        armed[0] = True
        try:
            await _warm_shapes_again(d)
            warm_compiles = list(compiles)
            # positive control: a fresh jitted function MUST fire the
            # compile event — proves the listener actually observes
            # compiles, so the empty assertion above means something
            jax.jit(lambda x: x * 3 + 1)(jnp.arange(8)).block_until_ready()
            canary_fired = len(compiles) > len(warm_compiles)
        finally:
            armed[0] = False
        await d.close()
        return warm_compiles, canary_fired

    try:
        warm_compiles, canary_fired = asyncio.run(go())
    finally:
        armed[0] = False
    assert canary_fired, "compile-event canary did not fire"
    assert warm_compiles == [], (
        f"warm_up left {len(warm_compiles)} shapes compiling on the "
        "request path"
    )
