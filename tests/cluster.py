"""In-process multi-daemon test cluster — the reference's central fixture.

Boots N real daemons in one process on 127.0.0.1 ephemeral ports with
discovery "none" and explicit set_peers, short batch/global cadences for test
speed (reference cluster/cluster.go:123-201; the functional suite's TestMain
boots 10 daemons the same way, functional_test.go:2465-2491). Helpers locate
the consistent-hash owner of a key so tests target owner vs non-owner
deterministically (cluster/cluster.go:72-110).
"""

from __future__ import annotations

import asyncio
from typing import List, Optional

from gubernator_tpu.config import BehaviorConfig, DaemonConfig
from gubernator_tpu.service.daemon import Daemon
from gubernator_tpu.types import PeerInfo


def daemon_config(dc: str = "", **overrides) -> DaemonConfig:
    conf = DaemonConfig(
        grpc_address="127.0.0.1:0",
        http_address="127.0.0.1:0",
        data_center=dc,
        cache_size=8192,
        behaviors=BehaviorConfig(
            batch_wait_ms=1.0,
            global_sync_wait_ms=50.0,  # reference cluster uses 50ms sync
            batch_timeout_ms=5000.0,  # CPU-jit compiles can stall first calls
            global_timeout_ms=5000.0,
        ),
    )
    for k, v in overrides.items():
        setattr(conf, k, v)
    return conf


class Cluster:
    def __init__(self, daemons: List[Daemon], proxies: Optional[list] = None):
        self.daemons = daemons
        # chaos=True: proxies[i] fronts daemons[i]'s peer traffic
        self.proxies = proxies or [None] * len(daemons)

    @classmethod
    async def start(
        cls,
        n: int,
        dcs: Optional[List[str]] = None,
        chaos: bool = False,
        **overrides,
    ):
        """Start n daemons (optionally with per-daemon datacenter labels) and
        wire them together with explicit set_peers.

        chaos=True fronts each daemon's PEER plane with a ChaosProxy
        (tests/chaos.py): the daemon advertises the proxy's port, so every
        other daemon's forwards/hit-syncs/broadcasts flow through it and
        tests inject faults per-peer at runtime. Direct client traffic
        (V1Client at conf.grpc_address) bypasses the proxy."""
        dcs = dcs or [""] * n
        proxies = [None] * n
        daemons = []
        for i in range(n):
            conf_kw = dict(overrides)
            if chaos:
                from tests.chaos import ChaosProxy

                proxies[i] = await ChaosProxy().start()
                # advertise the proxy: ring identity and peer dialing both
                # key on advertise_address, so ownership stays consistent
                # across daemons while the transport detours via the proxy
                conf_kw["advertise_address"] = proxies[i].address
            daemons.append(
                await Daemon.spawn(daemon_config(dc=dcs[i], **conf_kw))
            )
            if chaos:
                host, _, port = daemons[i].conf.grpc_address.rpartition(":")
                proxies[i].set_target(host, int(port))
        peers = [d.peer_info() for d in daemons]
        for d in daemons:
            # fresh PeerInfo copies: set_peers mutates is_owner per daemon
            d.set_peers([PeerInfo(**vars(p)) for p in peers])
        return cls(daemons, proxies)

    def proxy_for(self, daemon: Daemon):
        """The ChaosProxy fronting `daemon`'s peer traffic."""
        return self.proxies[self.daemons.index(daemon)]

    def find_owning_daemon(self, name: str, key: str) -> Daemon:
        """reference cluster.FindOwningDaemon (cluster/cluster.go:81-110)."""
        hk = name + "_" + key
        owner = self.daemons[0].get_peer(hk)
        for d in self.daemons:
            if d.conf.advertise_address == owner.grpc_address:
                return d
        raise AssertionError(f"no daemon owns {hk}")

    def non_owning_daemons(self, name: str, key: str) -> List[Daemon]:
        owner = self.find_owning_daemon(name, key)
        return [d for d in self.daemons if d is not owner]

    async def restart(self, i: int) -> Daemon:
        """Stop and respawn daemon i with the same config (reference
        cluster.Restart, cluster/cluster.go:139-148)."""
        old = self.daemons[i]
        conf = old.conf
        await old.close()
        new = await Daemon.spawn(conf)
        self.daemons[i] = new
        peers = [d.peer_info() for d in self.daemons]
        for d in self.daemons:
            d.set_peers([PeerInfo(**vars(p)) for p in peers])
        return new

    async def crash_restart(self, i: int) -> Daemon:
        """kill -9 analog (docs/durability.md): daemon i dies UNCLEANLY —
        no drain, no GLOBAL flush, no shutdown checkpoint (Daemon.abort)
        — and a replacement spawns on the same config, recovering only
        what the incremental checkpoint plane already persisted. The
        durability chaos tests bound over-admission across this edge."""
        old = self.daemons[i]
        conf = old.conf
        await old.abort()
        new = await Daemon.spawn(conf)
        self.daemons[i] = new
        peers = [d.peer_info() for d in self.daemons]
        for d in self.daemons:
            d.set_peers([PeerInfo(**vars(p)) for p in peers])
        return new

    async def drain_restart(self, i: int, mid_handoff=None) -> Daemon:
        """Rolling-restart step with graceful state handoff (the reference
        has no analog — docs/robustness.md "Topology change & drain"):

        1. the surviving daemons drop daemon i from their peer set (the
           discovery/LB view once its health flips to "leaving");
        2. daemon i drains — flushes GLOBAL queues, hands every owned live
           row to its ring successor, snapshots the unacked remainder —
           then closes;
        3. a replacement spawns on the same config and every daemon re-adds
           it: the survivors' rebalance diff hands the moved rows BACK.

        `mid_handoff` (async callable) runs between de-registration and the
        drain — the hook chaos tests use to inject faults mid-handoff."""
        old = self.daemons[i]
        survivors = [d for j, d in enumerate(self.daemons) if j != i]
        peers_without = [d.peer_info() for d in survivors]
        for d in survivors:
            d.set_peers([PeerInfo(**vars(p)) for p in peers_without])
        if mid_handoff is not None:
            await mid_handoff()
        await old.stop(drain=True)
        new = await Daemon.spawn(old.conf)
        self.daemons[i] = new
        peers = [d.peer_info() for d in self.daemons]
        for d in self.daemons:
            d.set_peers([PeerInfo(**vars(p)) for p in peers])
        await self.settle_handoffs()
        return new

    async def settle_handoffs(self) -> None:
        """Wait for every daemon's in-flight rebalance handoff tasks (the
        set_peers diff launches them fire-and-forget)."""
        for d in self.daemons:
            while d._handoff_tasks:
                await asyncio.gather(
                    *list(d._handoff_tasks), return_exceptions=True
                )

    async def stop(self) -> None:
        await asyncio.gather(*(d.close() for d in self.daemons))
        await asyncio.gather(
            *(p.stop() for p in self.proxies if p is not None)
        )


async def scrape(daemon: Daemon) -> dict:
    """GET the daemon's real /metrics endpoint and parse it — convergence
    assertions go through the wire, exactly like the reference's
    getMetrics/expfmt technique (functional_test.go:2245-2267)."""
    import aiohttp

    from gubernator_tpu.service.metrics import parse_metrics

    url = f"http://{daemon.conf.http_address}/metrics"
    async with aiohttp.ClientSession() as s:
        async with s.get(url) as resp:
            assert resp.status == 200
            return parse_metrics(await resp.text())


def metric_value(scraped: dict, name: str, **labels) -> float:
    fam = scraped.get(name, {})
    want = tuple(sorted(labels.items()))
    for labelset, value in fam.items():
        if all(kv in labelset for kv in want):
            return value
    return 0.0


async def wait_for(predicate, timeout_s: float = 5.0, interval_s: float = 0.05):
    """Poll an async predicate until truthy (waitForBroadcast analog,
    functional_test.go:2328-2385)."""
    deadline = asyncio.get_running_loop().time() + timeout_s
    while True:
        val = await predicate()
        if val:
            return val
        if asyncio.get_running_loop().time() > deadline:
            raise TimeoutError("condition not met before timeout")
        await asyncio.sleep(interval_s)
