"""Entry-point binary tests (reference cmd/gubernator/main_test.go smoke +
healthcheck/cli behavior)."""

import asyncio
import functools
import io
import json

import pytest


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


@async_test
async def test_server_binary_boots_from_env(tmp_path, monkeypatch):
    """The server main: env-file config, serve, graceful stop (the analog of
    cmd/gubernator/main_test.go:27 smoke-testing the built binary)."""
    from gubernator_tpu.cmd.server import serve

    conf_file = tmp_path / "server.conf"
    conf_file.write_text(
        "# gubernator-tpu config\n"
        "GUBER_GRPC_ADDRESS=127.0.0.1:0\n"
        "GUBER_HTTP_ADDRESS=127.0.0.1:0\n"
        "GUBER_CACHE_SIZE=4096\n"
    )
    monkeypatch.delenv("GUBER_GRPC_ADDRESS", raising=False)
    stop = asyncio.Event()
    got = {}

    async def ready(daemon):
        from gubernator_tpu.client import V1Client

        client = V1Client(daemon.conf.grpc_address)
        try:
            resp = await client.get_rate_limits(
                [dict(name="boot", unique_key="k", hits=1, limit=3, duration=60_000)]
            )
            got["remaining"] = resp.responses[0].remaining
            hc = await client.health_check()
            got["status"] = hc.status
        finally:
            await client.close()
        stop.set()

    await asyncio.wait_for(serve(str(conf_file), stop=stop, ready=ready), timeout=60)
    assert got == {"remaining": 2, "status": "healthy"}


@async_test
async def test_cluster_binary_and_healthcheck_probe():
    from gubernator_tpu.cmd.cluster import serve
    from gubernator_tpu.cmd.healthcheck import NotHealthy, check

    import socket

    # the cluster binary uses fixed consecutive ports; pick a free region
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        base_port = s.getsockname()[1] + 10

    stop = asyncio.Event()
    result = {}

    async def ready(daemons):
        # all nodes up, peered, and healthy through the real HTTP listener
        def probe(url):
            out = io.StringIO()
            check(url, attempts=3, delay_s=0.05, out=out)
            return out.getvalue()

        loop = asyncio.get_running_loop()
        text = await loop.run_in_executor(
            None, probe, daemons[0].conf.http_address
        )
        result["attempts"] = text.count("checking")
        result["peers"] = [len(d.local_peers()) for d in daemons]
        # unreachable port → transport error, not NotHealthy
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            dead_port = s.getsockname()[1]
        with pytest.raises(Exception) as ei:
            await loop.run_in_executor(
                None,
                lambda: check(
                    f"127.0.0.1:{dead_port}", attempts=1, delay_s=0, out=io.StringIO()
                ),
            )
        result["transport_is_not_healthy"] = isinstance(ei.value, NotHealthy)
        stop.set()

    await asyncio.wait_for(
        serve(3, base_port=base_port, stop=stop, ready=ready), timeout=120
    )
    assert result["attempts"] == 1  # healthy on first attempt
    assert result["peers"] == [3, 3, 3]
    assert result["transport_is_not_healthy"] is False


@async_test
async def test_load_generator_cli_against_daemon(capsys):
    """One corpus pass of the load generator against a live daemon."""
    from tests.cluster import daemon_config

    from gubernator_tpu.cmd import cli
    from gubernator_tpu.service.daemon import Daemon

    d = await Daemon.spawn(daemon_config())
    try:
        args = cli.main.__wrapped__ if hasattr(cli.main, "__wrapped__") else None
        # drive run() directly (main() owns its own event loop)
        ns = type(
            "Args",
            (),
            dict(
                endpoint=d.conf.grpc_address,
                concurrency=4,
                timeout=5.0,
                checks=10,
                rate=0,
                limits=40,
                seconds=0,
                once=True,
                quiet=True,
            ),
        )()
        stats = cli.Stats()
        await asyncio.wait_for(cli.run(ns, stats), timeout=60)
        assert stats.checks == 40
        assert stats.requests == 4
        assert stats.errors == 0
        rep = stats.report(1.0)
        assert rep["latency_ms"]["p99"] >= rep["latency_ms"]["p50"] > 0
    finally:
        await d.close()


def test_healthcheck_main_exit_codes(monkeypatch, capsys):
    from gubernator_tpu.cmd import healthcheck

    # transport failure → exit 1
    monkeypatch.setenv("GUBER_HTTP_ADDRESS", "127.0.0.1:1")
    monkeypatch.setenv("GUBER_HTTP_RETRY_COUNT", "1")
    assert healthcheck.main() == 1
    monkeypatch.setenv("GUBER_HTTP_RETRY_COUNT", "bogus")
    assert healthcheck.main() == 1


def test_cli_corpus_and_limiter():
    from gubernator_tpu.cmd.cli import OpenLoopLimiter, make_rate_limits

    corpus = make_rate_limits(50)
    assert len(corpus) == 50
    assert all(1 <= r.limit <= 999 for r in corpus)
    assert all(500 <= r.duration <= 6000 for r in corpus)
    assert len({r.name for r in corpus}) == 50

    async def paced():
        lim = OpenLoopLimiter(200.0)
        import time

        t0 = time.perf_counter()
        for _ in range(10):
            await lim.wait()
        return time.perf_counter() - t0

    took = asyncio.run(paced())
    assert took >= 0.03  # ~10 * 5ms, generous for slow CI
