"""Durability at scale: incremental device-side checkpointing, crash-safe
warm restart, and the kill -9 recovery bound (docs/durability.md).

Layers under test:

* ops/checkpoint.py — the epoch tracker's dirty-block bookkeeping and the
  device-side dirty-block extract (local + 8-device mesh, parity vs the
  numpy live-slot oracle);
* store.py — CRC-framed delta frames: roundtrip, corrupt-frame and
  torn-tail skip (the clean prefix always replays);
* kernel2.merge2 replay — base + deltas reconstruct the pre-crash state
  byte-for-byte for clean frames, and a STALE frame can only tighten
  admission (never over-grant — the invariant the whole design leans on);
* service/checkpoint.py + daemon — background loop, debug/metrics surface,
  geometry-mismatch/corrupt-snapshot cold starts, shutdown that survives a
  failing Loader, and the chaos recovery bound: a kill -9'd daemon
  (Cluster.crash_restart → Daemon.abort) recovers within one checkpoint
  interval's writes of its pre-crash state.
"""

import asyncio
import functools
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from gubernator_tpu.config import ConfigError, setup_daemon_config
from gubernator_tpu.ops.batch import RequestColumns
from gubernator_tpu.ops.checkpoint import (
    EpochTracker,
    extract_begin,
    finish_extract,
)
from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.ops.table2 import decode_live_slots
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.store import (
    DeltaLog,
    fps_from_slots,
    load_snapshot_meta,
    save_snapshot,
)
from tests.cluster import daemon_config

NOW = 1_700_000_000_000


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


def cols(fps, hits=1, limit=1 << 20, behavior=None):
    n = fps.shape[0]
    return RequestColumns(
        fp=fps,
        algo=np.zeros(n, dtype=np.int32),
        behavior=(
            behavior if behavior is not None else np.zeros(n, dtype=np.int32)
        ),
        hits=np.full(n, hits, dtype=np.int64),
        limit=np.full(n, limit, dtype=np.int64),
        burst=np.zeros(n, dtype=np.int64),
        duration=np.full(n, 3_600_000, dtype=np.int64),
        created_at=np.full(n, NOW, dtype=np.int64),
        err=np.zeros(n, dtype=np.int8),
    )


def install(eng, fps, remaining=37, limit=100):
    n = fps.shape[0]
    o = np.ones(n, dtype=np.int64)
    return eng.install_columns(
        fp=fps,
        algo=np.zeros(n, dtype=np.int32),
        status=np.zeros(n, dtype=np.int32),
        limit=o * limit,
        remaining=o * remaining,
        reset_time=o * (NOW + 3_600_000),
        duration=o * 3_600_000,
        now_ms=NOW,
    )


def live_map(rows, now=NOW):
    """fp → slot bytes for every live slot (the byte-parity oracle)."""
    slots, fp, _exp = decode_live_slots(np.asarray(rows), now)
    return {int(f): s.tobytes() for f, s in zip(fp, slots)}


def unique_fps(rng, n):
    return np.unique(
        rng.integers(1, (1 << 63) - 1, size=n * 2, dtype=np.int64)
    )[:n]


# ------------------------------------------------------------ epoch tracker


def test_epoch_tracker_marks_and_takes():
    tr = EpochTracker(1024, blk=8)
    assert tr.nblk == 128
    fps = np.asarray([1, 9, 1024 + 1, 8 * 50 + 3], dtype=np.int64)
    tr.mark(fps)
    # buckets 1, 9, 1, 403 → blocks 0, 1, 0, 50
    epoch, gids = tr.take()
    assert epoch == 1
    assert gids.tolist() == [0, 1, 50]
    # take cleared; fp == 0 (padding) is ignored
    tr.mark(np.zeros(4, dtype=np.int64))
    epoch, gids = tr.take()
    assert epoch == 2 and gids.size == 0
    # remark re-arms a failed epoch's dirt
    tr.remark(np.asarray([7, 9]))
    assert tr.dirty_blocks == 2
    _, gids = tr.take()
    assert gids.tolist() == [7, 9]
    tr.mark_all()
    assert tr.dirty_blocks == tr.nblk


def test_epoch_tracker_sharded_and_rebuild():
    tr = EpochTracker(1024, n_shards=4, blk=8)
    from gubernator_tpu.parallel.mesh import shard_of

    fps = np.asarray([(7 << 32) | 5, (2 << 32) | 900], dtype=np.int64)
    tr.mark(fps)
    _, gids = tr.take()
    shards = shard_of(fps, 4)
    want = sorted(
        int(s) * tr.nblk + int((f % 1024) // 8) for s, f in zip(shards, fps)
    )
    assert gids.tolist() == want
    # rebuild (resize): epoch lineage continues, everything dirty
    tr2 = tr.rebuild(2048)
    assert tr2.epoch == tr.epoch and tr2.dirty_blocks == tr2.nblk * 4


def test_tracker_blk_divides_small_tables():
    # 32-bucket table with the default blk=8 → 4 blocks; blk larger than
    # the table clamps
    tr = EpochTracker(32)
    assert tr.nblk * tr.blk == 32
    tr = EpochTracker(4, blk=64)
    assert tr.blk == 4 and tr.nblk == 1


# ---------------------------------------------------------------- delta log


def test_delta_frame_roundtrip(tmp_path):
    log = DeltaLog(str(tmp_path / "x.delta"))
    rng = np.random.default_rng(0)
    s1 = rng.integers(-(2**31), 2**31 - 1, size=(10, 16)).astype(np.int32)
    s2 = rng.integers(-(2**31), 2**31 - 1, size=(7, 16)).astype(np.int32)
    assert log.append(1, NOW, s1) > s1.nbytes
    log.append(2, NOW + 5, s2)
    scan = log.scan()
    assert scan.error is None and len(scan.frames) == 2
    (e1, t1, r1, l1), (e2, t2, r2, l2) = scan.frames
    assert (e1, t1) == (1, NOW) and (e2, t2) == (2, NOW + 5)
    np.testing.assert_array_equal(r1, s1)
    np.testing.assert_array_equal(r2, s2)
    assert scan.rows == 17
    # reset truncates atomically to an empty (header-only) log
    log.reset()
    assert log.frame_count() == 0


def test_delta_log_crc_corruption_keeps_clean_prefix(tmp_path):
    log = DeltaLog(str(tmp_path / "x.delta"))
    rng = np.random.default_rng(1)
    frames = [
        rng.integers(-(2**31), 2**31 - 1, size=(5, 16)).astype(np.int32)
        for _ in range(3)
    ]
    offsets = [0]
    for i, s in enumerate(frames):
        log.append(i + 1, NOW, s)
        offsets.append(log.size_bytes())
    # flip one payload byte inside frame 2
    with open(log.path, "r+b") as f:
        f.seek(offsets[2] - 3)
        b = f.read(1)
        f.seek(offsets[2] - 3)
        f.write(bytes([b[0] ^ 0xFF]))
    scan = log.scan()
    assert len(scan.frames) == 1 and "CRC" in scan.error
    np.testing.assert_array_equal(scan.frames[0][2], frames[0])
    assert scan.skipped_bytes > 0


def test_delta_log_truncated_tail(tmp_path):
    log = DeltaLog(str(tmp_path / "x.delta"))
    rng = np.random.default_rng(2)
    s = rng.integers(-(2**31), 2**31 - 1, size=(64, 16)).astype(np.int32)
    log.append(1, NOW, s)
    clean = log.size_bytes()
    log.append(2, NOW, s)
    # crash mid-append: cut the second frame's payload short
    with open(log.path, "r+b") as f:
        f.truncate(clean + 40)
    scan = log.scan()
    assert len(scan.frames) == 1 and "truncated" in scan.error
    # a header-only tail (payload never started) also skips cleanly
    with open(log.path, "r+b") as f:
        f.truncate(clean + 10)
    scan = log.scan()
    assert len(scan.frames) == 1 and "truncated" in scan.error
    # garbage header magic stops the scan too
    with open(log.path, "r+b") as f:
        f.truncate(clean)
        f.seek(clean)
        f.write(b"\x00" * 64)
    scan = log.scan()
    assert len(scan.frames) == 1 and "magic" in scan.error


def test_delta_log_repair_extends_after_torn_tail(tmp_path):
    """A damaged log must be truncated to its clean prefix before new
    appends — otherwise fresh frames land after the corrupt bytes, where
    the prefix scan can never reach them."""
    log = DeltaLog(str(tmp_path / "x.delta"))
    rng = np.random.default_rng(3)
    s1 = rng.integers(-(2**31), 2**31 - 1, size=(6, 16)).astype(np.int32)
    s2 = rng.integers(-(2**31), 2**31 - 1, size=(9, 16)).astype(np.int32)
    log.append(1, NOW, s1)
    clean = log.size_bytes()
    log.append(2, NOW, s2)
    with open(log.path, "r+b") as f:  # crash mid-append: torn tail
        f.truncate(log.size_bytes() - 8)
    # appending WITHOUT repair strands the new frame behind the tear
    log.append(3, NOW, s2)
    scan = log.scan()
    assert scan.error and len(scan.frames) == 1
    assert scan.clean_bytes == clean
    # repair truncates to the clean prefix; appends then extend a
    # scannable log
    log.repair(scan)
    assert log.size_bytes() == clean
    log.append(3, NOW, s2)
    scan = log.scan()
    assert scan.error is None and len(scan.frames) == 2
    np.testing.assert_array_equal(scan.frames[1][2], s2)
    # a log whose own header is damaged repairs to empty
    with open(log.path, "r+b") as f:
        f.seek(0)
        f.write(b"\x00" * 4)
    scan = log.scan()
    assert scan.error and scan.clean_bytes == 0
    log.repair(scan)
    scan = log.scan()
    assert scan.error is None and len(scan.frames) == 0
    log.append(4, NOW, s1)
    assert log.frame_count() == 1


# ------------------------------------------------------------- extract pass


def test_extract_dirty_local_parity():
    eng = LocalEngine(capacity=1 << 14, write_mode="xla")
    rng = np.random.default_rng(3)
    fps = unique_fps(rng, 4000)
    install(eng, fps)
    NB = eng.table.rows.shape[0]
    tr = EpochTracker(NB)
    tr.mark(fps)
    _, gids = tr.take()
    got_fps, got_slots = finish_extract(
        extract_begin(eng.table.rows, gids, tr.blk, NOW)
    )
    want = live_map(eng.table.rows)
    got = {int(f): s.tobytes() for f, s in zip(got_fps, got_slots)}
    assert got == want  # byte parity against the live-slot oracle


def test_extract_dirty_is_incremental():
    """Only the touched blocks' rows come back — the batch-proportional
    contract (cost ∝ write rate, not table size)."""
    eng = LocalEngine(capacity=1 << 16, write_mode="xla")
    rng = np.random.default_rng(4)
    fps = unique_fps(rng, 10_000)
    install(eng, fps)
    NB = eng.table.rows.shape[0]
    eng.ckpt = EpochTracker(NB)
    eng.ckpt.take()  # drop the install's dirt
    touched = fps[:64]
    eng.check_columns(cols(touched), now_ms=NOW)
    _, gids = eng.ckpt.take()
    got_fps, _ = finish_extract(
        extract_begin(eng.table.rows, gids, eng.ckpt.blk, NOW)
    )
    assert set(touched.tolist()) <= set(got_fps.tolist())
    # amplification bound: ≤ dirty blocks × blk × K slots, ≪ the table
    assert got_fps.shape[0] <= gids.shape[0] * eng.ckpt.blk * 8
    assert got_fps.shape[0] < fps.shape[0] // 2


def test_engine_paths_mark_dirty():
    """Every mutation surface feeds the tracker: sync check, pipelined
    issue, install, merge, tombstone; restore marks everything."""
    from gubernator_tpu.ops.engine import (
        finish_check_columns,
        issue_check_columns,
        prepare_check_columns,
    )

    eng = LocalEngine(capacity=1 << 12, write_mode="xla")
    NB = eng.table.rows.shape[0]
    eng.ckpt = EpochTracker(NB)
    rng = np.random.default_rng(5)
    fps = unique_fps(rng, 32)
    eng.check_columns(cols(fps[:8]), now_ms=NOW)
    assert eng.ckpt.dirty_blocks > 0
    eng.ckpt.take()
    # pipelined: marking happens at ISSUE (engine-thread job), not prepare
    pend = prepare_check_columns(eng, cols(fps[8:16]), now_ms=NOW)
    assert eng.ckpt.dirty_blocks == 0
    pend = issue_check_columns(eng, pend)
    assert eng.ckpt.dirty_blocks > 0
    finish_check_columns(eng, pend, lambda fn: fn())
    eng.ckpt.take()
    install(eng, fps[16:24])
    assert eng.ckpt.dirty_blocks > 0
    _, gids = eng.ckpt.take()
    got_fps, got_slots = finish_extract(
        extract_begin(eng.table.rows, gids, eng.ckpt.blk, NOW)
    )
    assert set(fps[16:24].tolist()) <= set(got_fps.tolist())
    # merge + tombstone mark too
    eng.merge_rows(got_fps, got_slots, now_ms=NOW)
    assert eng.ckpt.dirty_blocks > 0
    eng.ckpt.take()
    eng.tombstone_fps(fps[16:24])
    assert eng.ckpt.dirty_blocks > 0
    eng.ckpt.take()
    eng.restore(eng.snapshot())
    assert eng.ckpt.dirty_blocks == eng.ckpt.nblk


def test_extract_dirty_sharded_parity():
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.sharded import ShardedEngine

    eng = ShardedEngine(
        make_mesh(8), capacity_per_shard=1 << 12, write_mode="xla"
    )
    rng = np.random.default_rng(6)
    fps = unique_fps(rng, 6000)
    install(eng, fps)
    eng.ckpt = EpochTracker(
        int(eng.table.rows.shape[-2]), n_shards=eng.n_shards
    )
    eng.ckpt.mark(fps)
    _, gids = eng.ckpt.take()
    got_fps, got_slots = eng.checkpoint_finish(eng.checkpoint_begin(gids, NOW))
    want = live_map(eng.table.rows)
    got = {int(f): s.tobytes() for f, s in zip(got_fps, got_slots)}
    assert got == want
    # incremental: touch a subset, extract covers it and stays partial
    eng.check_columns(cols(fps[:128]), now_ms=NOW)
    _, gids = eng.ckpt.take()
    got_fps, _ = eng.checkpoint_finish(eng.checkpoint_begin(gids, NOW))
    assert set(fps[:128].tolist()) <= set(got_fps.tolist())
    assert got_fps.shape[0] < fps.shape[0]


# ------------------------------------------------------------------- replay


def test_replay_parity_local(tmp_path):
    """Base + delta frames replayed through merge2 reconstruct the source
    table's live rows byte-for-byte (clean frames, no RESET traffic)."""
    log = DeltaLog(str(tmp_path / "x.delta"))
    src = LocalEngine(capacity=1 << 14, write_mode="xla")
    src.ckpt = EpochTracker(src.table.rows.shape[0])
    rng = np.random.default_rng(7)
    fps = unique_fps(rng, 3000)
    # epoch 1: first wave of traffic → base snapshot
    src.check_columns(cols(fps[:2000], hits=3), now_ms=NOW)
    base_path = str(tmp_path / "base.npz")
    save_snapshot(base_path, src.snapshot(), epoch=src.ckpt.take()[0])
    # epochs 2..4: more traffic → delta frames
    for i in range(3):
        sl = fps[2000 + 300 * i: 2300 + 300 * i]
        src.check_columns(cols(sl, hits=2), now_ms=NOW + 1 + i)
        src.check_columns(cols(fps[:200], hits=1), now_ms=NOW + 1 + i)
        epoch, gids = src.ckpt.take()
        _fps, slots = finish_extract(
            extract_begin(src.table.rows, gids, src.ckpt.blk, NOW + 1 + i)
        )
        log.append(epoch, NOW + 1 + i, slots)
    # restore: base, then frames with epoch > base epoch
    dst = LocalEngine(capacity=1 << 14, write_mode="xla")
    rows, base_epoch, _layout = load_snapshot_meta(base_path)
    dst.restore(rows)
    for epoch, now_ms, slots, _lay in log.scan().frames:
        assert epoch > base_epoch
        dst.merge_rows(fps_from_slots(slots), slots, now_ms=now_ms)
    assert live_map(dst.table.rows, NOW + 4) == live_map(
        src.table.rows, NOW + 4
    )


def test_replay_never_over_grants():
    """A STALE frame (higher remaining) replayed over newer state cannot
    re-grant capacity, and OVER_LIMIT sticks — merge2 semantics asserted
    on the replay path."""
    eng = LocalEngine(capacity=1 << 10, write_mode="xla")
    eng.ckpt = EpochTracker(eng.table.rows.shape[0])
    fp = np.asarray([12345], dtype=np.int64)
    # stale frame: 3 hits consumed (remaining 7)
    eng.check_columns(cols(fp, hits=3, limit=10), now_ms=NOW)
    _, gids = eng.ckpt.take()
    _f, stale = finish_extract(
        extract_begin(eng.table.rows, gids, eng.ckpt.blk, NOW)
    )
    # newer state: 4 more consumed (remaining 3)
    eng.check_columns(cols(fp, hits=4, limit=10), now_ms=NOW + 10)
    eng.merge_rows(fps_from_slots(stale), stale, now_ms=NOW + 20)
    rc = eng.check_columns(cols(fp, hits=0, limit=10), now_ms=NOW + 30)
    assert int(rc.remaining[0]) == 3  # min wins: stale 7 did not resurrect
    # OVER sticks: an OVER frame replayed onto an UNDER table pins OVER
    # (exhaust, then overdraw — a rejected burst alone stores UNDER, like
    # the reference: the stored status only flips once the bucket is dry)
    eng2 = LocalEngine(capacity=1 << 10, write_mode="xla")
    eng2.ckpt = EpochTracker(eng2.table.rows.shape[0])
    eng2.check_columns(cols(fp, hits=10, limit=10), now_ms=NOW)
    eng2.check_columns(cols(fp, hits=1, limit=10), now_ms=NOW)  # → OVER
    _, gids = eng2.ckpt.take()
    _f, over = finish_extract(
        extract_begin(eng2.table.rows, gids, eng2.ckpt.blk, NOW)
    )
    eng3 = LocalEngine(capacity=1 << 10, write_mode="xla")
    eng3.check_columns(cols(fp, hits=1, limit=10), now_ms=NOW)  # UNDER
    eng3.merge_rows(fps_from_slots(over), over, now_ms=NOW + 1)
    rc = eng3.check_columns(cols(fp, hits=0, limit=10), now_ms=NOW + 2)
    assert int(rc.status[0]) == 1  # OVER stuck


def test_replay_expired_frames_drop():
    """Rows already expired at replay time must not resurrect."""
    eng = LocalEngine(capacity=1 << 10, write_mode="xla")
    eng.ckpt = EpochTracker(eng.table.rows.shape[0])
    fp = np.asarray([777], dtype=np.int64)
    c = cols(fp, hits=1, limit=10)._replace(
        duration=np.asarray([1000], dtype=np.int64)
    )
    eng.check_columns(c, now_ms=NOW)
    _, gids = eng.ckpt.take()
    _f, slots = finish_extract(
        extract_begin(eng.table.rows, gids, eng.ckpt.blk, NOW)
    )
    dst = LocalEngine(capacity=1 << 10, write_mode="xla")
    merged = dst.merge_rows(fps_from_slots(slots), slots, now_ms=NOW + 10_000)
    assert merged == 0 and dst.live_count(NOW + 10_000) == 0


# ----------------------------------------------------------- daemon plane


def ckpt_config(tmp_path, interval_ms=10_000.0, **over):
    conf = daemon_config(**over)
    conf.checkpoint_path = str(tmp_path / "base.npz")
    conf.checkpoint_interval_ms = interval_ms
    return conf


@async_test
async def test_daemon_checkpoint_loop_and_debug(tmp_path):
    """The background loop writes frames while serving; metrics families
    populate and /v1/debug/durability reports the plane's state."""
    import aiohttp

    from gubernator_tpu.service.daemon import Daemon
    from tests.cluster import metric_value, scrape, wait_for

    d = await Daemon.spawn(ckpt_config(tmp_path, interval_ms=25.0))
    try:
        for i in range(4):
            await d.get_rate_limits([
                pb.RateLimitReq(
                    name="dur", unique_key=f"k{i}", hits=1, limit=100,
                    duration=3_600_000,
                )
            ])
        await wait_for(
            lambda: asyncio.sleep(0, d.checkpointer.last_epoch > 0
                                  and d.checkpointer._log.size_bytes() > 8)
        )
        scraped = await scrape(d)
        assert metric_value(
            scraped, "gubernator_tpu_checkpoint_rows_total", kind="delta"
        ) >= 4
        assert metric_value(
            scraped, "gubernator_tpu_checkpoint_bytes_total", kind="delta"
        ) > 0
        async with aiohttp.ClientSession() as s:
            url = f"http://{d.conf.http_address}/v1/debug/durability"
            async with s.get(url) as resp:
                assert resp.status == 200
                js = await resp.json()
        assert js["enabled"] is True
        assert js["last_epoch"] >= 1
        assert js["delta_log_bytes"] > 8
        assert js["last_error"] is None
        assert js["pending_dirty_blocks"] >= 0
    finally:
        await d.close()
    # graceful close compacted: base carries everything, log is empty
    _rows, epoch, _layout = load_snapshot_meta(str(tmp_path / "base.npz"))
    assert epoch >= 1
    assert DeltaLog(str(tmp_path / "base.npz") + ".delta").frame_count() == 0


@async_test
async def test_kill9_recovery_bound(tmp_path):
    """THE chaos acceptance: a daemon kill -9'd mid-traffic recovers from
    base + deltas, serves, and over-admits at most the writes admitted
    after the last checkpoint epoch — never under-counting in the safe
    direction (recovered remaining ≤ true remaining)."""
    from tests.cluster import Cluster

    cluster = await Cluster.start(
        1, checkpoint_path=str(tmp_path / "base.npz"),
        checkpoint_interval_ms=60_000.0,  # ticks driven manually below
    )
    d = cluster.daemons[0]
    LIMIT = 1000

    async def hit(n):
        r = await d.get_rate_limits([
            pb.RateLimitReq(
                name="chaos", unique_key="k", hits=n, limit=LIMIT,
                duration=3_600_000,
            )
        ])
        assert not r[0].error
        return r[0]

    try:
        for _ in range(12):
            await hit(50)  # 600 consumed
        await d.checkpointer.checkpoint_once()  # durable through 600
        window = 0
        for _ in range(2):
            await hit(50)  # 100 more — the at-risk window
            window += 50
        pre = await hit(0)
        assert pre.remaining == LIMIT - 700
        d = await cluster.crash_restart(0)  # kill -9 + respawn
        post = await hit(0)
        # recovered: the checkpointed 600 are remembered (not a cold start)
        # and the bound holds: re-granted capacity == the post-checkpoint
        # window, and the safe direction never over-counts remaining
        assert post.remaining == LIMIT - 600
        assert post.remaining - pre.remaining <= window
        # drive to OVER: total admitted across both lives ≤ limit + window
        admitted = 700
        while True:
            r = await hit(50)
            if r.status == pb.OVER_LIMIT:
                break
            admitted += 50
        assert admitted <= LIMIT + window
    finally:
        await cluster.stop()


@async_test
async def test_sharded_daemon_warm_restart(tmp_path):
    """Incremental checkpointing on the mesh engine: per-shard extract,
    abort, replay — counts survive on an 8-device sharded daemon."""
    from tests.cluster import Cluster

    cluster = await Cluster.start(
        1, engine="sharded", cache_size=4096,
        checkpoint_path=str(tmp_path / "base.npz"),
        checkpoint_interval_ms=60_000.0,
    )
    d = cluster.daemons[0]
    try:
        for i in range(16):
            r = await d.get_rate_limits([
                pb.RateLimitReq(
                    name="mesh", unique_key=f"k{i}", hits=4, limit=10,
                    duration=3_600_000,
                )
            ])
            assert not r[0].error
        await d.checkpointer.checkpoint_once()
        d = await cluster.crash_restart(0)
        assert d.checkpointer.restored in ("delta", "base+delta")
        for i in range(16):
            r = await d.get_rate_limits([
                pb.RateLimitReq(
                    name="mesh", unique_key=f"k{i}", hits=0, limit=10,
                    duration=3_600_000,
                )
            ])
            assert r[0].remaining == 6, (i, r[0])
    finally:
        await cluster.stop()


@async_test
async def test_compaction_folds_frames(tmp_path):
    """After GUBER_CHECKPOINT_COMPACT_FRAMES deltas the log folds into a
    fresh base and restarts replay nothing."""
    from gubernator_tpu.service.daemon import Daemon

    conf = ckpt_config(tmp_path)
    conf.checkpoint_compact_frames = 3
    d = await Daemon.spawn(conf)
    try:
        for i in range(3):
            await d.get_rate_limits([
                pb.RateLimitReq(
                    name="cp", unique_key=f"k{i}", hits=2, limit=10,
                    duration=3_600_000,
                )
            ])
            await d.checkpointer.checkpoint_once()
        assert d.checkpointer.frames_since_compaction == 0  # compacted
        assert d.checkpointer.base_epoch >= 3
        await d.abort()
        d2 = await Daemon.spawn(conf)
        assert d2.checkpointer.restored == "base"
        assert d2.checkpointer.replayed_frames == 0
        r = await d2.get_rate_limits([
            pb.RateLimitReq(
                name="cp", unique_key="k0", hits=0, limit=10,
                duration=3_600_000,
            )
        ])
        assert r[0].remaining == 8
        await d2.close()
    finally:
        if not d._shutting_down:
            await d.close()


@async_test
async def test_geometry_mismatch_cold_start(tmp_path):
    """A snapshot whose row geometry no longer matches the configured
    table (cache_size changed across restart) logs and cold-starts
    instead of crashing engine.restore at boot — on both restore paths."""
    from gubernator_tpu.service.daemon import Daemon

    path = str(tmp_path / "base.npz")
    save_snapshot(path, np.ones((64, 128), dtype=np.int32), epoch=1)
    for interval in (0.0, 10_000.0):  # classic Loader path + incremental
        conf = daemon_config(cache_size=8192)
        conf.checkpoint_path = path
        conf.checkpoint_interval_ms = interval
        d = await Daemon.spawn(conf)  # must not raise
        try:
            assert await d.runner.live_count() == 0  # cold
            r = await d.get_rate_limits([
                pb.RateLimitReq(
                    name="g", unique_key="k", hits=1, limit=5,
                    duration=60_000,
                )
            ])
            assert r[0].remaining == 4
            assert (
                d.metrics.checkpoint_errors.labels(stage="restore")
                ._value.get() >= 1
            )
        finally:
            # close() re-snapshots at the CONFIGURED geometry, so the next
            # loop iteration needs the mismatched file back
            await d.abort()
            save_snapshot(path, np.ones((64, 128), dtype=np.int32), epoch=1)


@async_test
async def test_corrupt_snapshot_cold_start(tmp_path):
    from gubernator_tpu.service.daemon import Daemon

    path = str(tmp_path / "base.npz")
    with open(path, "wb") as f:
        f.write(b"this is not a snapshot")
    conf = ckpt_config(tmp_path)
    d = await Daemon.spawn(conf)
    try:
        assert d.checkpointer.restored == "cold"
        r = await d.get_rate_limits([
            pb.RateLimitReq(
                name="c", unique_key="k", hits=1, limit=5, duration=60_000,
            )
        ])
        assert r[0].remaining == 4
    finally:
        await d.close()


@async_test
async def test_restore_repairs_torn_delta_log(tmp_path):
    """A kill -9 can tear the delta log mid-append; restore must truncate
    it to the clean prefix before serving, or every frame the restarted
    daemon appends lands behind the corrupt bytes where replay cannot
    reach it — a SECOND kill -9 before compaction would then lose up to
    compact_frames × interval of writes, not one interval."""
    from gubernator_tpu.service.daemon import Daemon

    conf = ckpt_config(tmp_path)
    d = await Daemon.spawn(conf)
    delta_path = d.checkpointer.delta_path
    try:
        await d.get_rate_limits([
            pb.RateLimitReq(
                name="t", unique_key="k0", hits=2, limit=10,
                duration=3_600_000,
            )
        ])
        await d.checkpointer.checkpoint_once()
        await d.get_rate_limits([
            pb.RateLimitReq(
                name="t", unique_key="k1", hits=3, limit=10,
                duration=3_600_000,
            )
        ])
        await d.checkpointer.checkpoint_once()
    finally:
        await d.abort()
    # crash mid-append: tear the second frame's tail
    with open(delta_path, "r+b") as f:
        f.truncate(os.path.getsize(delta_path) - 8)

    d2 = await Daemon.spawn(conf)
    try:
        assert d2.checkpointer.restored == "delta"
        assert d2.checkpointer.replayed_frames == 1
        assert (
            d2.metrics.checkpoint_errors.labels(stage="restore")
            ._value.get() >= 1
        )
        # life 2 admits more writes and checkpoints them...
        await d2.get_rate_limits([
            pb.RateLimitReq(
                name="t", unique_key="k2", hits=4, limit=10,
                duration=3_600_000,
            )
        ])
        await d2.checkpointer.checkpoint_once()
        # ...onto a repaired, scannable log
        assert DeltaLog(delta_path).scan().error is None
    finally:
        await d2.abort()

    d3 = await Daemon.spawn(conf)
    try:
        # a SECOND unclean death still recovers life 2's writes: the frame
        # appended after the repair replays
        assert d3.checkpointer.replayed_frames == 2
        for key, want in (("k0", 8), ("k2", 6)):
            r = await d3.get_rate_limits([
                pb.RateLimitReq(
                    name="t", unique_key=key, hits=0, limit=10,
                    duration=3_600_000,
                )
            ])
            assert r[0].remaining == want, (key, r[0])
    finally:
        await d3.close()


@async_test
async def test_shutdown_completes_with_failing_loader(tmp_path):
    """Satellite: a Loader whose save() raises (disk full, unwritable
    path) must not wedge close() — _door/runner shutdown always run, the
    failure is logged + counted."""
    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.store import Loader

    class BoomLoader(Loader):
        def load(self):
            return None

        def save(self, rows):
            raise IOError("disk full")

    d = await Daemon.spawn(daemon_config(), loader=BoomLoader())
    await d.get_rate_limits([
        pb.RateLimitReq(
            name="b", unique_key="k", hits=1, limit=5, duration=60_000,
        )
    ])
    await d.close()  # must complete despite the failing save
    assert (
        d.metrics.checkpoint_errors.labels(stage="shutdown")._value.get()
        == 1
    )
    # the runner's executors really shut down (close reached them)
    with pytest.raises(RuntimeError):
        d.runner._exec.submit(lambda: None)


@async_test
async def test_unwritable_delta_path_defers_dirt(tmp_path):
    """A failed delta append re-arms the taken dirty set (remark): the
    next epoch still carries the writes once the disk recovers."""
    from gubernator_tpu.service.daemon import Daemon

    conf = ckpt_config(tmp_path)
    conf.checkpoint_delta_path = str(tmp_path / "no" / "such" / "dir.delta")
    d = await Daemon.spawn(conf)
    try:
        await d.get_rate_limits([
            pb.RateLimitReq(
                name="e", unique_key="k", hits=1, limit=5, duration=60_000,
            )
        ])
        # make the append fail: point the log at a directory path
        os.makedirs(conf.checkpoint_delta_path, exist_ok=True)
        out = await d.checkpointer.checkpoint_once()
        assert "error" in out
        assert d.checkpointer.last_error is not None
        assert d.engine.ckpt.dirty_blocks > 0  # re-armed, not lost
        assert (
            d.metrics.checkpoint_errors.labels(stage="delta")._value.get()
            >= 1
        )
        # recovery: free the path → the same dirt persists on the next tick
        os.rmdir(conf.checkpoint_delta_path)
        out = await d.checkpointer.checkpoint_once()
        assert out["rows"] >= 1 and out["bytes"] > 0
    finally:
        await d.close()


def test_config_validation():
    with pytest.raises(ConfigError, match="GUBER_CHECKPOINT_PATH"):
        setup_daemon_config(env={"GUBER_CHECKPOINT_INTERVAL_MS": "100"})
    with pytest.raises(ConfigError, match="COMPACT_FRAMES"):
        setup_daemon_config(env={
            "GUBER_CHECKPOINT_PATH": "/tmp/x.npz",
            "GUBER_CHECKPOINT_COMPACT_FRAMES": "0",
        })
    with pytest.raises(ConfigError, match="DELTA_PATH"):
        setup_daemon_config(env={"GUBER_CHECKPOINT_DELTA_PATH": "/tmp/x"})
    conf = setup_daemon_config(env={
        "GUBER_CHECKPOINT_PATH": "/tmp/x.npz",
        "GUBER_CHECKPOINT_INTERVAL_MS": "1s",
        "GUBER_CHECKPOINT_COMPACT_FRAMES": "16",
    })
    assert conf.checkpoint_interval_ms == 1000.0
    assert conf.checkpoint_compact_frames == 16


# ----------------------------------------------------- true kill -9 (slow)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.mark.slow
def test_true_kill9_subprocess(tmp_path):
    """The real thing: SIGKILL a server PROCESS mid-traffic, restart it on
    the same checkpoint dir, and verify the recovered daemon serves warm
    state (the in-process chaos tests above prove the bound; this proves
    no in-process shutdown hook was load-bearing)."""
    import urllib.request

    grpc_port, http_port = _free_port(), _free_port()
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        GUBER_GRPC_ADDRESS=f"127.0.0.1:{grpc_port}",
        GUBER_HTTP_ADDRESS=f"127.0.0.1:{http_port}",
        GUBER_CACHE_SIZE="8192",
        GUBER_CHECKPOINT_PATH=str(tmp_path / "base.npz"),
        GUBER_CHECKPOINT_INTERVAL_MS="100",
        GUBER_BATCH_WAIT="1ms",
    )

    def spawn():
        return subprocess.Popen(
            [sys.executable, "-m", "gubernator_tpu"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

    def post(payload: bytes) -> dict:
        import json

        req = urllib.request.Request(
            f"http://127.0.0.1:{http_port}/v1/GetRateLimits",
            data=payload, headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            return json.loads(resp.read())

    def wait_ready(proc, timeout=120):
        deadline = time.time() + timeout
        while time.time() < deadline:
            assert proc.poll() is None, "server died during startup"
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{http_port}/v1/HealthCheck", timeout=1
                )
                return
            except Exception:
                time.sleep(0.5)
        raise TimeoutError("server did not come up")

    body = (
        b'{"requests": [{"name": "kill9", "unique_key": "k", "hits": %d,'
        b' "limit": "100", "duration": "3600000"}]}'
    )
    proc = spawn()
    try:
        wait_ready(proc)
        for _ in range(5):
            r = post(body % 10)
            assert not r["responses"][0].get("error")
        time.sleep(1.0)  # ≥ several checkpoint intervals
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
        proc = spawn()
        wait_ready(proc)
        r = post(body % 0)
        remaining = int(r["responses"][0]["remaining"])
        # 50 hits admitted pre-kill; every checkpointed epoch survives, so
        # the recovered count is warm (< 100) and conservative (≥ 50)
        assert remaining <= 50
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
