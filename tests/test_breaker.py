"""Unit coverage for the peer fault-tolerance primitives: the circuit
breaker state machine (service/breaker.py), the ring route-around
(peers/hash_ring.py, peers/picker.py), and the GLOBAL requeue bounds
(service/global_manager.py) — all with fakes/injected clocks; the
against-real-RPCs scenarios live in tests/test_chaos.py."""

import asyncio
import functools
import random

import pytest

from gubernator_tpu.service.breaker import BreakerState, CircuitBreaker


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def make(clock, **kw):
    kw.setdefault("failure_threshold", 3)
    kw.setdefault("backoff_base_ms", 100.0)
    kw.setdefault("backoff_cap_ms", 800.0)
    return CircuitBreaker(clock=clock, rng=random.Random(7), **kw)


# ---------------------------------------------------------------- breaker


def test_breaker_trips_after_consecutive_failures():
    clk = Clock()
    cb = make(clk)
    for _ in range(2):
        cb.record_failure()
        assert cb.state is BreakerState.CLOSED and not cb.blocked
    cb.record_failure()
    assert cb.state is BreakerState.OPEN
    assert cb.blocked and not cb.allow()
    assert cb.retry_after_s() > 0


def test_breaker_success_resets_failure_streak():
    clk = Clock()
    cb = make(clk)
    cb.record_failure()
    cb.record_failure()
    cb.record_success()  # streak broken — not consecutive anymore
    cb.record_failure()
    cb.record_failure()
    assert cb.state is BreakerState.CLOSED


def test_breaker_half_open_probe_budget_and_close():
    clk = Clock()
    cb = make(clk, failure_threshold=1, probe_budget=2)
    cb.record_failure()
    assert cb.state is BreakerState.OPEN
    clk.t += 1.0  # past any first-trip cooldown (≤ base 0.1 s)
    assert not cb.blocked
    assert cb.allow() and cb.state is BreakerState.HALF_OPEN
    assert cb.allow()  # second probe fits the budget
    assert not cb.allow() and cb.blocked  # budget exhausted
    cb.record_success()
    assert cb.state is BreakerState.CLOSED and not cb.blocked


def test_breaker_probe_failure_reopens_with_doubled_backoff():
    clk = Clock()
    cb = make(clk, failure_threshold=1)
    delays = []
    for _ in range(4):
        cb.record_failure() if cb.state is BreakerState.CLOSED else None
        assert cb.state is BreakerState.OPEN
        delays.append(cb.retry_after_s())
        clk.t += cb.retry_after_s() + 1e-6
        assert cb.allow()  # half-open probe
        cb.record_failure()  # probe fails → re-open
    # equal jitter keeps each cooldown within [ceiling/2, ceiling), ceiling
    # doubling per consecutive trip up to the cap
    for i, (lo, hi) in enumerate([(0.05, 0.1), (0.1, 0.2), (0.2, 0.4), (0.4, 0.8)]):
        assert lo <= delays[i] < hi, (i, delays[i])
    # cap: many more trips never exceed backoff_cap_ms
    for _ in range(10):
        clk.t += cb.retry_after_s() + 1e-6
        assert cb.allow()
        cb.record_failure()
        assert cb.retry_after_s() <= 0.8


def test_breaker_discard_releases_probe_without_verdict():
    clk = Clock()
    cb = make(clk, failure_threshold=1, probe_budget=1)
    cb.record_failure()
    clk.t += 1.0
    assert cb.allow() and cb.state is BreakerState.HALF_OPEN
    assert cb.blocked  # probe slot taken
    cb.record_discard()  # cancelled probe: no verdict
    assert cb.state is BreakerState.HALF_OPEN and not cb.blocked
    assert cb.allow()
    cb.record_success()
    assert cb.state is BreakerState.CLOSED


def test_breaker_stale_failure_while_open_does_not_extend_cooldown():
    clk = Clock()
    cb = make(clk, failure_threshold=1)
    cb.record_failure()
    before = cb.retry_after_s()
    cb.record_failure()  # an in-flight pre-trip call failing late
    assert cb.retry_after_s() == before


def test_breaker_state_callback_fires_on_transitions():
    clk = Clock()
    seen = []
    cb = CircuitBreaker(
        failure_threshold=1, backoff_base_ms=100, clock=clk, on_state=seen.append
    )
    cb.record_failure()
    clk.t += 1.0
    cb.allow()
    cb.record_success()
    assert seen == [
        BreakerState.OPEN,
        BreakerState.HALF_OPEN,
        BreakerState.CLOSED,
    ]


# ----------------------------------------------------- ring route-around


def _ring(addrs):
    from gubernator_tpu.peers.hash_ring import ReplicatedConsistentHash
    from gubernator_tpu.types import PeerInfo

    ring = ReplicatedConsistentHash()
    for a in addrs:
        ring.add(PeerInfo(grpc_address=a))
    return ring


def test_hash_ring_exclude_routes_to_next_peer():
    ring = _ring(["h1:1", "h2:1", "h3:1"])
    owner = ring.get("k_abc")
    alt = ring.get("k_abc", frozenset({owner.grpc_address}))
    assert alt.grpc_address != owner.grpc_address
    # deterministic: the same exclusion always lands on the same fallback
    assert (
        ring.get("k_abc", frozenset({owner.grpc_address})).grpc_address
        == alt.grpc_address
    )
    # no exclusion → unchanged ownership
    assert ring.get("k_abc").grpc_address == owner.grpc_address


def test_hash_ring_all_excluded_raises():
    ring = _ring(["h1:1", "h2:1"])
    with pytest.raises(RuntimeError, match="all peers excluded"):
        ring.get("k_abc", frozenset({"h1:1", "h2:1"}))


def test_region_picker_exclude_skips_dead_regions():
    from gubernator_tpu.peers.picker import RegionPicker
    from gubernator_tpu.types import PeerInfo

    rp = RegionPicker()
    rp.add(PeerInfo(grpc_address="a:1", data_center="dc-a"))
    rp.add(PeerInfo(grpc_address="b:1", data_center="dc-b"))
    rp.add(PeerInfo(grpc_address="b:2", data_center="dc-b"))
    assert len(rp.get_clients("k")) == 2
    # excluding dc-a's only peer drops that region instead of failing
    got = rp.get_clients("k", frozenset({"a:1"}))
    assert [p.data_center for p in got] == ["dc-b"]


# ------------------------------------------------------- GLOBAL requeue


class _FakeMetric:
    def __init__(self):
        self.value = 0.0

    def inc(self, n=1):
        self.value += n

    def set(self, v):
        self.value = v

    def observe(self, v):
        pass

    def labels(self, **kw):
        return self


class _FakeMetrics:
    def __getattr__(self, name):
        m = _FakeMetric()
        setattr(self, name, m)
        return m


class _FakeBreaker:
    blocked = False


class _FakeClient:
    def __init__(self, fail=True):
        self.fail = fail
        self.breaker = _FakeBreaker()
        self.sent = []

    async def get_peer_rate_limits(self, req, timeout=None):
        if self.fail:
            raise RuntimeError("injected")
        self.sent.extend(req.requests)


class _FakeDaemon:
    """Just enough daemon for GlobalManager: one remote peer owns all keys."""

    def __init__(self, behaviors, client):
        from gubernator_tpu.types import PeerInfo

        class Conf:
            pass

        self.conf = Conf()
        self.conf.behaviors = behaviors
        self.metrics = _FakeMetrics()
        self._info = PeerInfo(grpc_address="peer:1")
        self._client = client

    def get_peer(self, key):
        return self._info

    def is_self(self, info):
        return False

    def peer_client(self, info):
        return self._client


def _manager(client, **over):
    from gubernator_tpu.config import BehaviorConfig
    from gubernator_tpu.service.global_manager import GlobalManager

    b = BehaviorConfig(**over)
    return GlobalManager(_FakeDaemon(b, client))


def _req(key, hits=1):
    from gubernator_tpu.proto import gubernator_pb2 as pb

    return pb.RateLimitReq(
        name="g", unique_key=key, hits=hits, limit=100, duration=60_000
    )


@async_test
async def test_failed_send_requeues_instead_of_dropping():
    client = _FakeClient(fail=True)
    gm = _manager(client, global_requeue_retries=3)
    gm.queue_hit("g_k1", _req("k1", hits=5))
    await gm._send_hits()
    assert "g_k1" in gm._hits and gm._hits["g_k1"].hits == 5
    assert gm._hit_attempts["g_k1"] == 1
    assert gm.metrics.global_requeued.value == 1
    # heals: the requeued batch reaches the owner and accounting clears
    client.fail = False
    await gm._send_hits()
    assert [r.hits for r in client.sent] == [5]
    assert not gm._hits and not gm._hit_attempts


@async_test
async def test_requeue_merges_with_fresh_hits():
    client = _FakeClient(fail=True)
    gm = _manager(client)
    gm.queue_hit("g_k1", _req("k1", hits=5))
    send = asyncio.ensure_future(gm._send_hits())
    # fresh hits land while the failing send is in flight… except the fake
    # fails synchronously, so emulate by queueing between sends
    await send
    gm.queue_hit("g_k1", _req("k1", hits=2))
    await gm._send_hits()  # fails again: requeued 5 already merged with 2
    assert gm._hits["g_k1"].hits == 7


@async_test
async def test_requeue_retry_cap_drops_after_exhaustion():
    client = _FakeClient(fail=True)
    gm = _manager(client, global_requeue_retries=2)
    gm.queue_hit("g_k1", _req("k1"))
    for _ in range(2):
        await gm._send_hits()
        assert "g_k1" in gm._hits
    await gm._send_hits()  # 3rd failure exceeds the cap → dropped
    assert not gm._hits and not gm._hit_attempts
    assert gm.metrics.global_requeue_dropped.value == 1


@async_test
async def test_requeue_queue_cap_bounds_memory():
    client = _FakeClient(fail=True)
    gm = _manager(client, global_queue_cap=3)
    for i in range(5):
        gm.queue_hit(f"g_k{i}", _req(f"k{i}"))
    await gm._send_hits()
    # only up to the cap re-merged; the rest dropped
    assert len(gm._hits) == 3
    assert gm.metrics.global_requeue_dropped.value == 2


@async_test
async def test_open_breaker_requeues_without_rpc():
    client = _FakeClient(fail=True)
    client.breaker.blocked = True
    gm = _manager(client)
    gm.queue_hit("g_k1", _req("k1", hits=4))
    await gm._send_hits()
    assert client.sent == []  # no RPC attempted toward the open breaker
    assert gm._hits["g_k1"].hits == 4


def test_queue_update_tracks_broadcast_queue_gauge():
    gm = _manager(_FakeClient())
    gm.queue_update("g_k1", _req("k1"))
    gm.queue_update("g_k2", _req("k2"))
    assert gm.metrics.broadcast_queue_length.value == 2


# -------------------------------------------------- peer client shutdown


@async_test
async def test_peer_client_shutdown_closes_channel_despite_drain_error():
    """A PeerError out of the final drain must not leak the channel
    (shutdown wraps the drain in try/finally)."""
    from gubernator_tpu.service.peer_client import PeerClient, PeerError
    from gubernator_tpu.types import PeerInfo

    client = PeerClient(PeerInfo(grpc_address="127.0.0.1:1"))
    closed = []

    class FakeChannel:
        async def close(self):
            closed.append(True)

    client._channel = FakeChannel()

    async def bad_drain():
        raise PeerError("127.0.0.1:1", RuntimeError("boom"))

    client._drain = bad_drain
    with pytest.raises(PeerError):
        await client.shutdown()
    assert closed == [True]
    assert client._channel is None
