"""Compact-wire parity suite (ISSUE 5): the 5-lane int32 ingress / int32
egress codec (ops/wire.py) against the full-width oracle, row-for-row.

The compact path must be an ENCODING, never a semantics change: every
engine surface that can ship it (LocalEngine, ShardedEngine host-grid and
a2a routes, both dedup modes, the GLOBAL owner/replica fork and collective
sync outbox) is compared against the same engine forced to wire="full".
Batches that the narrow layout cannot represent (created_at skew beyond the
delta budget, hits ≥ 2^18, Gregorian durations) must fall back to
full-width transparently — checked by byte accounting, not just absence of
error. Egress saturation edges (int32 clamps, the reset==0 sentinel) are
pinned directly against the codec.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gubernator_tpu.ops import wire
from gubernator_tpu.ops.batch import RequestColumns, pack_columns, pack_host_batch
from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.parallel import ShardedEngine, make_mesh
from gubernator_tpu.parallel.global_sync import GlobalShardedEngine
from gubernator_tpu.types import Behavior

NOW = 1_700_000_000_000
RESET = int(Behavior.RESET_REMAINING)
DRAIN = int(Behavior.DRAIN_OVER_LIMIT)
GLOBAL = int(Behavior.GLOBAL)
GREG = int(Behavior.DURATION_IS_GREGORIAN)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "tests require the 8-device CPU mesh"
    return make_mesh(8)


def mk_cols(
    n,
    rng,
    dup=False,
    leaky_frac=0.5,
    limit=100,
    duration=60_000,
    behavior_pool=(0, RESET, DRAIN),
    created_at=NOW,
    hits_hi=4,
):
    fp = rng.integers(1, (1 << 63) - 1, size=n, dtype=np.int64)
    if dup:
        fp[n // 2 :] = fp[: n - n // 2]
    return RequestColumns(
        fp=fp,
        algo=(rng.random(n) < leaky_frac).astype(np.int32),
        behavior=rng.choice(behavior_pool, size=n).astype(np.int32),
        hits=rng.integers(0, hits_hi, n).astype(np.int64),
        limit=np.full(n, limit, dtype=np.int64),
        burst=np.zeros(n, dtype=np.int64),
        duration=np.full(n, duration, dtype=np.int64),
        created_at=np.full(n, created_at, dtype=np.int64),
        err=np.zeros(n, dtype=np.int8),
    )


def assert_rc_equal(a, b, ctx=""):
    for f in ("status", "limit", "remaining", "reset_time", "err"):
        np.testing.assert_array_equal(
            getattr(a, f), getattr(b, f), err_msg=f"{ctx}: {f} diverged"
        )


# ------------------------------------------------------------------- codec


def test_codec_roundtrip_exact():
    """pack → in-trace decode reproduces the full 12-column ingress array
    exactly, modulo the documented narrowing (behavior keeps only the two
    math-visible bits; leaky burst reconstructs as limit, token as 0)."""
    rng = np.random.default_rng(3)
    cols = mk_cols(64, rng)
    cols.created_at[5] = NOW - 512  # delta floor
    cols.created_at[6] = NOW + 511  # delta ceiling
    hb, err = pack_columns(cols, NOW)
    assert not err.any()
    base = wire.pick_base(hb)
    assert wire.wire_encodable(hb, base)
    arr12 = np.asarray(wire.decode_wire_block(
        jnp.asarray(wire.pack_wire_full(hb, base)))[0])
    ref = pack_host_batch(hb)
    ref[2] = ref[2] & (RESET | DRAIN)  # behavior narrows to the math bits
    ref[5] = np.where(ref[1] == 1, ref[4], 0)  # burst: leaky=limit, token=0
    np.testing.assert_array_equal(arr12, ref)


def test_encodable_rejections():
    rng = np.random.default_rng(4)

    def hb_of(**kw):
        cols = mk_cols(16, rng, **kw)
        return pack_columns(cols, NOW)[0]

    base = NOW
    assert wire.wire_encodable(hb_of(), base)
    # created_at outside the ±512 ms delta window
    assert not wire.wire_encodable(hb_of(created_at=NOW + 512), base)
    assert not wire.wire_encodable(hb_of(created_at=NOW - 513), base)
    # hits beyond 18 bits
    hb = hb_of()
    hb.hits[0] = 1 << 18
    assert not wire.wire_encodable(hb, base)
    hb.hits[0] = -1
    assert not wire.wire_encodable(hb, base)
    # duration beyond 30 bits
    hb = hb_of()
    hb.duration[3] = 1 << 30
    assert not wire.wire_encodable(hb, base)
    # negative limit (kept on the full-width path's exact arithmetic)
    hb = hb_of()
    hb.limit[0] = -5
    assert not wire.wire_encodable(hb, base)
    # explicit leaky burst != limit
    hb = hb_of(leaky_frac=1.0)
    hb.burst[0] = hb.limit[0] + 1
    assert not wire.wire_encodable(hb, base)
    # token burst is math-inert → still encodable
    hb = hb_of(leaky_frac=0.0)
    hb.burst[0] = 7
    assert wire.wire_encodable(hb, base)
    # Gregorian rows carry host-resolved calendar fields
    hb = hb_of()
    hb.greg_interval[2] = 1000
    assert not wire.wire_encodable(hb, base)
    # all-inactive batches are trivially encodable (zero columns)
    hb = hb_of()
    hb.active[:] = False
    assert wire.wire_encodable(hb, base)


def test_egress_saturation_and_sentinel():
    """int32 saturation edges: remaining/limit ≥ 2^31 clamp, negative
    remaining survives down to -2^31, reset_time==0 round-trips through
    the sentinel, and far-future resets clamp instead of wrapping."""
    base = NOW
    n = 6
    packed = np.zeros((n + 2, 4), dtype=np.int64)
    packed[:n, 0] = [2**31 + 7, 5, 5, 5, 5, 5]  # limit lane
    packed[:n, 1] = [3, 2**31 + 9, -(2**31) - 9, -17, 0, 1]  # remaining
    packed[:n, 2] = [NOW + 1, NOW + 2, NOW + 3, 0, NOW + 2**40, NOW - 5]
    packed[:n, 3] = [1, 5, 4, 0, 2, 0]  # flags
    packed[n] = [4, 2, 1, 0]
    packed[n + 1] = [1, 0, 0, 0]
    enc = np.asarray(wire.encode_wire_out(jnp.asarray(packed), jnp.int64(base)))
    assert enc.dtype == np.int32
    (status, limit, rem, reset, dropped, hit), st = wire.unpack_wire_out(enc, n)
    assert limit[0] == 2**31 - 1  # saturated, not wrapped
    assert rem[1] == 2**31 - 1 and rem[2] == -(2**31)
    assert rem[3] == -17  # in-range negatives exact
    assert reset[3] == 0  # sentinel round-trip
    assert reset[5] == NOW - 5  # small negative delta exact
    # far-future reset clamps to base + (2^31 - 1), never wraps negative
    assert reset[4] == base + 2**31 - 1
    assert st == (4, 2, 1, 0)
    assert bool(hit[4]) and bool(dropped[2]) and not bool(hit[0])


def test_stack_pass_outputs_dtype_guard():
    """Mixed compact/full pass outputs must NOT fuse into one stacked
    fetch: stacking would promote int32 to int64 and destroy the dtype
    tag the host decoder dispatches on."""
    from gubernator_tpu.ops.engine import _stack_pass_outputs

    a = jnp.zeros((4, 4), dtype=jnp.int64)
    b = jnp.zeros((4, 4), dtype=jnp.int32)
    assert _stack_pass_outputs([a, b]) is None
    assert _stack_pass_outputs([b, b]) is not None


# ----------------------------------------------------------- local engine


def test_local_engine_parity_and_state():
    rng = np.random.default_rng(11)
    ec = LocalEngine(capacity=1 << 12, write_mode="xla", wire="compact")
    ef = LocalEngine(capacity=1 << 12, write_mode="xla", wire="full")
    state = rng.bit_generator.state
    got = []
    for eng in (ec, ef):
        rng.bit_generator.state = state
        for step in range(4):
            cols = mk_cols(200, rng, dup=(step % 2 == 1))
            rc = eng.check_columns(cols, now_ms=NOW + step * 1000)
            if eng is ec:
                got.append(rc)
            else:
                assert_rc_equal(got[step], rc, f"local step {step}")
    # identical responses AND identical device state, slot for slot
    np.testing.assert_array_equal(
        np.asarray(ec.table.rows), np.asarray(ef.table.rows)
    )
    assert ec.stats == ef.stats


def test_local_engine_parity_per_step():
    """Same as above but comparing per step (responses in lockstep)."""
    rng = np.random.default_rng(12)
    ec = LocalEngine(capacity=1 << 12, write_mode="xla", wire="compact")
    ef = LocalEngine(capacity=1 << 12, write_mode="xla", wire="full")
    for step in range(3):
        cols = mk_cols(128, rng, dup=(step == 2))
        assert_rc_equal(
            ec.check_columns(cols, now_ms=NOW + step),
            ef.check_columns(cols, now_ms=NOW + step),
            f"step {step}",
        )


def test_limit_i32_error_parity():
    """limit ≥ 2^31 is a front-door validation error on both paths — the
    row never reaches a kernel, compact or full."""
    rng = np.random.default_rng(13)
    cols = mk_cols(8, rng)
    cols.limit[3] = 2**31
    ec = LocalEngine(capacity=1 << 10, write_mode="xla", wire="compact")
    ef = LocalEngine(capacity=1 << 10, write_mode="xla", wire="full")
    a = ec.check_columns(cols, now_ms=NOW)
    b = ef.check_columns(cols, now_ms=NOW)
    assert a.err[3] != 0
    assert_rc_equal(a, b)


# ------------------------------------------------------------ sharded mesh


@pytest.mark.parametrize("route", ["host", "device"])
@pytest.mark.parametrize("dedup", ["host", "device"])
def test_sharded_parity(mesh, route, dedup):
    rng = np.random.default_rng(21)
    kw = dict(capacity_per_shard=1 << 10, write_mode="xla",
              route=route, dedup=dedup)
    ec = ShardedEngine(mesh, wire="compact", **kw)
    ef = ShardedEngine(mesh, wire="full", **kw)
    for step in range(3):
        cols = mk_cols(300, rng, dup=(step == 1))
        assert_rc_equal(
            ec.check_columns(cols, now_ms=NOW + step * 1000),
            ef.check_columns(cols, now_ms=NOW + step * 1000),
            f"{route}/{dedup} step {step}",
        )
    w, wf = ec.take_wire_deltas(), ef.take_wire_deltas()
    assert 0 < w["put"] < wf["put"] and 0 < w["fetch"] < wf["fetch"]


def test_sharded_fallback_on_skew(mesh):
    """A batch with created_at beyond the delta budget ships full-width
    (byte-counted) and still matches the oracle row-for-row."""
    rng = np.random.default_rng(22)
    kw = dict(capacity_per_shard=1 << 10, write_mode="xla", route="host")
    ec = ShardedEngine(mesh, wire="compact", **kw)
    ef = ShardedEngine(mesh, wire="full", **kw)
    cols = mk_cols(64, rng)
    cols.created_at[7] = NOW + 60_000  # within clamp tolerance, over budget
    ec.take_wire_deltas()
    ef.take_wire_deltas()
    assert_rc_equal(
        ec.check_columns(cols, now_ms=NOW),
        ef.check_columns(cols, now_ms=NOW),
        "skew fallback",
    )
    # identical byte footprint ⇒ the compact engine took the wide path
    assert ec.take_wire_deltas() == ef.take_wire_deltas()


def test_sharded_fallback_on_hits_overflow(mesh):
    rng = np.random.default_rng(23)
    kw = dict(capacity_per_shard=1 << 10, write_mode="xla", route="host")
    ec = ShardedEngine(mesh, wire="compact", **kw)
    ef = ShardedEngine(mesh, wire="full", **kw)
    cols = mk_cols(64, rng, hits_hi=2)
    cols.hits[0] = 1 << 20  # beyond the 18-bit wire budget
    cols.limit[:] = 1 << 30
    ec.take_wire_deltas()
    ef.take_wire_deltas()
    assert_rc_equal(
        ec.check_columns(cols, now_ms=NOW),
        ef.check_columns(cols, now_ms=NOW),
        "hits fallback",
    )
    assert ec.take_wire_deltas() == ef.take_wire_deltas()


def test_concurrent_put_parity(mesh):
    """GUBER_SHARD_PUT=concurrent (per-shard transfers assembled with
    make_array_from_single_device_arrays) is a transport strategy, not a
    semantics change."""
    rng = np.random.default_rng(24)
    kw = dict(capacity_per_shard=1 << 10, write_mode="xla")
    ea = ShardedEngine(mesh, wire="compact", **kw)
    eb = ShardedEngine(mesh, wire="compact", **kw)
    ea._put_concurrent = True
    eb._put_concurrent = False
    cols = mk_cols(500, rng)
    assert_rc_equal(
        ea.check_columns(cols, now_ms=NOW),
        eb.check_columns(cols, now_ms=NOW),
        "concurrent put",
    )


# ------------------------------------------------------------------ GLOBAL


def test_global_parity_with_sync(mesh):
    """The GLOBAL owner/replica fork + collective sync (compact outbox)
    against the full-width engine: responses, replica-served reads after
    sync, and the global counters all match."""
    rng = np.random.default_rng(31)
    kw = dict(capacity_per_shard=1 << 10, write_mode="xla", sync_out=128)
    ec = GlobalShardedEngine(mesh, wire="compact", **kw)
    ef = GlobalShardedEngine(mesh, wire="full", **kw)
    state = rng.bit_generator.state
    outs = {}
    for name, eng in (("c", ec), ("f", ef)):
        rng.bit_generator.state = state
        last = None
        for step in range(3):
            cols = mk_cols(200, rng, behavior_pool=(GLOBAL,), limit=50)
            last = eng.check_columns(cols, now_ms=NOW + step * 100)
            eng.sync(now_ms=NOW + step * 100)
        # replica re-read after the last reconcile
        rng.bit_generator.state = state
        cols = mk_cols(200, rng, behavior_pool=(GLOBAL,), limit=50)
        outs[name] = (last, eng.check_columns(cols, now_ms=NOW + 300))
    assert_rc_equal(outs["c"][0], outs["f"][0], "GLOBAL serve")
    assert_rc_equal(outs["c"][1], outs["f"][1], "GLOBAL replica re-read")
    assert ec.global_stats == ef.global_stats


def test_global_sync_outbox_falls_back_on_big_hits(mesh):
    """Accumulated hot-key hits beyond the 18-bit wire budget push the
    sync round onto the full-width pytree outbox — reconciliation must be
    identical either way."""
    rng = np.random.default_rng(32)
    kw = dict(capacity_per_shard=1 << 10, write_mode="xla", sync_out=64)
    ec = GlobalShardedEngine(mesh, wire="compact", **kw)
    ef = GlobalShardedEngine(mesh, wire="full", **kw)
    cols = mk_cols(16, rng, behavior_pool=(GLOBAL,), limit=1 << 30,
                   leaky_frac=0.0)
    cols = cols._replace(hits=np.full(16, (1 << 18) + 5, dtype=np.int64))
    for eng in (ec, ef):
        eng.check_columns(cols, now_ms=NOW)
        eng.sync(now_ms=NOW)
        # the compact engine must have taken the fallback (no wire step
        # compiled) — and both reconcile the same totals
    assert ec._sync_step_wire is None
    assert ec.global_stats == ef.global_stats
    probe = mk_cols(16, rng, behavior_pool=(GLOBAL,), limit=1 << 30)
    probe = probe._replace(fp=cols.fp, hits=np.zeros(16, dtype=np.int64),
                           algo=cols.algo)
    assert_rc_equal(
        ec.check_columns(probe, now_ms=NOW + 1),
        ef.check_columns(probe, now_ms=NOW + 1),
        "post-sync probe",
    )


# ------------------------------------------------------------------- knobs


def test_default_wire_mode_env(monkeypatch):
    monkeypatch.setenv("GUBER_WIRE_COMPACT", "1")
    assert wire.default_wire_mode() == "compact"
    monkeypatch.setenv("GUBER_WIRE_COMPACT", "0")
    assert wire.default_wire_mode() == "full"
    monkeypatch.delenv("GUBER_WIRE_COMPACT")
    # CPU backend default is full-width (TPU defaults compact)
    assert wire.default_wire_mode() == (
        "compact" if jax.default_backend() == "tpu" else "full"
    )


def test_wire_param_validation(mesh):
    with pytest.raises(ValueError):
        LocalEngine(capacity=1 << 10, wire="tight")
    with pytest.raises(ValueError):
        ShardedEngine(mesh, capacity_per_shard=1 << 10, wire="tight")
