"""v2 kernel validation: differential vs the v1 oracle + sweep-write parity.

The v1 plane kernel now lives under tests/oracle/ purely as a differential
oracle (it was the original implementation the reference-semantics tests were
written against). v2 is checked against it on randomized traffic, and the
Pallas sweep write is checked bit-exact against the XLA scatter write
(interpret mode on CPU).
"""

import numpy as np
import pytest

from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.ops.table2 import live_count2
from tests.oracle import v1_engine
from gubernator_tpu.types import (
    Algorithm,
    Behavior,
    RateLimitRequest,
    Status,
    MINUTE,
    SECOND,
)

NOW = 1_700_000_000_000


def random_requests(rng, n, keyspace, now):
    reqs = []
    for _ in range(n):
        algo = Algorithm.TOKEN_BUCKET if rng.random() < 0.5 else Algorithm.LEAKY_BUCKET
        behavior = 0
        r = rng.random()
        if r < 0.15:
            behavior |= Behavior.RESET_REMAINING
        if 0.15 <= r < 0.3:
            behavior |= Behavior.DRAIN_OVER_LIMIT
        reqs.append(
            RateLimitRequest(
                name="diff",
                unique_key=f"k{rng.integers(keyspace)}",
                hits=int(rng.integers(0, 4)),
                limit=int(rng.integers(1, 20)),
                duration=int(rng.integers(1, 5)) * SECOND,
                algorithm=algo,
                behavior=behavior,
                created_at=now,
            )
        )
    return reqs


@pytest.mark.parametrize("seed", [0, 1])
def test_v2_matches_v1_on_random_traffic(seed):
    """Same request stream, same responses, both kernels. Tables are large
    enough that eviction never triggers (eviction ordering legitimately
    differs: v1 probes coarse expiry, v2 exact — see kernel2 docstring)."""
    rng = np.random.default_rng(seed)
    e1 = v1_engine(capacity=4096)
    e2 = LocalEngine(capacity=4096)
    now = NOW
    for step in range(6):
        reqs = random_requests(rng, 64, keyspace=40, now=now)
        r1 = e1.check(reqs, now_ms=now)
        r2 = e2.check(reqs, now_ms=now)
        for i, (a, b) in enumerate(zip(r1, r2)):
            assert (a.status, a.limit, a.remaining, a.reset_time, a.error) == (
                b.status,
                b.limit,
                b.remaining,
                b.reset_time,
                b.error,
            ), f"step {step} row {i}: {reqs[i]} → v1={a} v2={b}"
        now += int(rng.integers(0, 3000))
    assert e1.stats.cache_hits == e2.stats.cache_hits
    assert e1.stats.cache_misses == e2.stats.cache_misses
    assert e1.stats.over_limit == e2.stats.over_limit


def test_sweep_write_matches_xla_write():
    """The Pallas sweep (interpret mode on CPU) must produce a bit-identical
    table to the XLA scatter write."""
    rng = np.random.default_rng(7)
    ex = LocalEngine(capacity=4096, write_mode="xla")
    es = LocalEngine(capacity=4096, write_mode="sweep")
    now = NOW
    for _ in range(3):
        reqs = random_requests(rng, 48, keyspace=60, now=now)
        rx = ex.check(reqs, now_ms=now)
        rs = es.check(reqs, now_ms=now)
        for a, b in zip(rx, rs):
            assert (a.status, a.remaining, a.reset_time) == (
                b.status,
                b.remaining,
                b.reset_time,
            )
        now += 1500
    assert np.array_equal(np.asarray(ex.table.rows), np.asarray(es.table.rows))


def test_token_math_matches_mixed_math():
    """The static token-only decision graph (engine._math_mode picks it for
    all-token batches) must be bit-identical to the mixed graph's token lanes
    — responses AND stored table. Guards against the two branches of
    ops/math._bucket_math_impl drifting apart."""
    import jax

    from gubernator_tpu.ops.batch import pack_requests, pad_batch, to_device
    from gubernator_tpu.ops.kernel2 import decide2_impl
    from gubernator_tpu.ops.table2 import new_table2

    rng = np.random.default_rng(13)
    now = NOW
    tt = new_table2(4096)
    tm = new_table2(4096)
    for step in range(3):
        import dataclasses

        reqs = [
            dataclasses.replace(r, algorithm=Algorithm.TOKEN_BUCKET)
            for r in random_requests(rng, 64, keyspace=40, now=now)
        ]
        hb, _ = pack_requests(reqs, now)
        # unique fps per dispatch (the kernel contract): keep first occurrence
        _, first = np.unique(hb.fp, return_index=True)
        sub = pad_batch(
            type(hb)(*[f[np.sort(first)] for f in hb]), 64
        )
        req = to_device(sub)
        tt, resp_t, stats_t = jax.jit(
            lambda t, b: decide2_impl(t, b, write="xla", math="token")
        )(tt, req)
        tm, resp_m, stats_m = jax.jit(
            lambda t, b: decide2_impl(t, b, write="xla", math="mixed")
        )(tm, req)
        for field in resp_t._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(resp_t, field)),
                np.asarray(getattr(resp_m, field)),
                err_msg=f"resp.{field} step {step}",
            )
        for field in stats_t._fields:
            assert int(getattr(stats_t, field)) == int(getattr(stats_m, field))
        now += 700
    assert np.array_equal(np.asarray(tt.rows), np.asarray(tm.rows))


def test_v2_bucket_overflow_evicts_soonest_expiring():
    """9 keys forced into one bucket of 8 lanes: the 9th insert evicts the
    soonest-expiring live slot (expiry-stamp eviction, reference
    lrucache.go:138-149) and the alarm counter fires."""
    eng = LocalEngine(capacity=8)  # single-bucket table (NB=8... )
    # NB is rounded to >=8 buckets; pick keys that all land in bucket 0
    from gubernator_tpu.hashing import fingerprint

    nb = eng.table.rows.shape[0]
    keys = []
    i = 0
    while len(keys) < 9:
        k = f"ov{i}"
        if fingerprint("t", k) % nb == 0:
            keys.append(k)
        i += 1
    now = NOW
    # first 8 fill the bucket with staggered expirations (key j expires at
    # now + (j+1) minutes)
    for j, k in enumerate(keys[:8]):
        (r,) = eng.check(
            [
                RateLimitRequest(
                    name="t", unique_key=k, hits=1, limit=10,
                    duration=(j + 1) * MINUTE, created_at=now,
                )
            ],
            now_ms=now,
        )
        assert r.error == "" and r.remaining == 9
    assert eng.stats.evicted_unexpired == 0
    # 9th key evicts keys[0] (soonest expiry)
    (r,) = eng.check(
        [
            RateLimitRequest(
                name="t", unique_key=keys[8], hits=1, limit=10,
                duration=MINUTE, created_at=now,
            )
        ],
        now_ms=now,
    )
    assert r.error == "" and r.remaining == 9
    assert eng.stats.evicted_unexpired == 1
    # keys[0] is gone: re-checking it starts a fresh bucket (miss)
    hits_before = eng.stats.cache_hits
    (r,) = eng.check(
        [
            RateLimitRequest(
                name="t", unique_key=keys[0], hits=1, limit=10,
                duration=MINUTE, created_at=now,
            )
        ],
        now_ms=now,
    )
    assert r.remaining == 9  # fresh, not 8
    assert eng.stats.cache_hits == hits_before
    # keys[1] survived
    (r,) = eng.check(
        [
            RateLimitRequest(
                name="t", unique_key=keys[1], hits=1, limit=10,
                duration=2 * MINUTE, created_at=now,
            )
        ],
        now_ms=now,
    )
    assert r.remaining == 8


def test_v2_live_count_and_expiry():
    eng = LocalEngine(capacity=1024)
    now = NOW
    reqs = [
        RateLimitRequest(
            name="t", unique_key=f"lc{i}", hits=1, limit=5, duration=10 * SECOND,
            created_at=now,
        )
        for i in range(50)
    ]
    eng.check(reqs, now_ms=now)
    assert live_count2(eng.table, now) == 50
    assert live_count2(eng.table, now + 11 * SECOND) == 0
    # expired slots are reclaimed lazily: re-check after expiry is a miss
    later = now + 11 * SECOND
    out = eng.check(
        [
            RateLimitRequest(
                name="t", unique_key="lc0", hits=1, limit=5, duration=10 * SECOND,
                created_at=later,
            )
        ],
        now_ms=later,
    )
    assert out[0].remaining == 4
    assert eng.stats.cache_hits == 0


def test_sweep_geometry_respects_vmem_bound():
    # a small table under a huge batch must not escape the VMEM cap through
    # the blk floor (the two-half kernel's scoped stack overflows past
    # blk*u = 2^19); u stays a power of two dividing the (pow2) batch
    from gubernator_tpu.ops.kernel2 import sweep_geometry

    for nb, batch in [(2048, 131072), (256, 1 << 20), (2048, 256),
                      (1 << 21, 131072), (1 << 21, 1 << 19)]:
        blk, u = sweep_geometry(nb, batch)
        assert blk * u <= 1 << 19, (nb, batch, blk, u)
        assert u & (u - 1) == 0 and u >= 64
        assert nb % blk == 0
        if batch >= u:
            assert batch % u == 0
