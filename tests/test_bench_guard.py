"""Bench-record defenses: timing guards under simulated jitter, and the
on-device loop's equivalence to sequential dispatches.

Round 4's recorded benchmark published an impossible 2.5e16 decisions/s
(dt=0.000s) and a weather-dominated headline; these tests pin the guard
functions that now stand between the timing loops and the published JSON
(gubernator_tpu/bench_guard.py) and the fori_loop harness the headline is
measured through (gubernator_tpu/ops/loop.py)."""

import numpy as np
import pytest

from gubernator_tpu.bench_guard import (
    MAX_SANE_RATE,
    check_dropped,
    check_work,
    slope,
)
from gubernator_tpu.ops.kernel2 import decide2
from gubernator_tpu.ops.loop import decide_loop, stack_batches
from gubernator_tpu.ops.table2 import new_table2

NOW = 1_700_000_000_000


# ------------------------------------------------------------ guard: slope


def test_slope_accepts_sane_timing():
    # 4 vs 68 iterations of a ~10 ms kernel behind a ~100 ms RTT constant
    s = slope(0.141, 0.780, 4, 68, 131072)
    assert s.reason is None
    assert s.rate == pytest.approx(64 * 131072 / (0.780 - 0.141))
    assert s.per_iter_ms == pytest.approx((0.780 - 0.141) / 64 * 1e3)


def test_slope_rejects_zero_dt():
    # round 4 config5: min-of-3 jittered host clocks made t_long <= t_short;
    # the old code floored dt at 1e-9 and published 2.5e16 dec/s
    s = slope(1.402, 1.402, 4, 28, 1 << 20)
    assert s.rate is None
    assert "floor" in s.reason


def test_slope_rejects_negative_dt():
    s = slope(1.500, 1.402, 4, 28, 1 << 20)
    assert s.rate is None


def test_slope_rejects_rtt_dominated_window():
    # 350 ms RTT constant + tiny device time: the difference resolves but
    # the run is transport-bound — grow the window, don't publish
    s = slope(0.355, 0.462, 4, 68, 1024)
    assert s.rate is None
    assert "grow the window" in s.reason


def test_slope_rejects_impossible_rate():
    # even a clean-looking dt must not publish a rate above the hardware
    s = slope(0.100, 0.151, 0, 1 << 20, 131072, min_ratio=1.0)
    assert s.rate is None
    assert "ceiling" in s.reason


def test_slope_under_jitter_never_publishes_garbage():
    """Property: under +-250 ms uniform RTT jitter on both endpoints of a
    window whose true device time is tiny, the guard either rejects or
    returns a rate within the physical ceiling — never a 1e16 artifact."""
    rng = np.random.default_rng(7)
    true_iter_s = 1e-4  # 0.1 ms device time/iter: far below jitter
    for _ in range(500):
        rtt_s = 0.100 + rng.uniform(0, 0.25)
        rtt_l = 0.100 + rng.uniform(0, 0.25)
        t_s = rtt_s + 4 * true_iter_s
        t_l = rtt_l + 28 * true_iter_s
        s = slope(t_s, t_l, 4, 28, 1 << 20)
        if s.rate is not None:
            assert s.rate <= MAX_SANE_RATE


def test_slope_accepts_when_device_time_dominates_jitter():
    """The remedy for rejection is a longer window: once the long run's
    device time dwarfs jitter, the guard accepts and the rate is within
    ~15% of truth even at worst-case +-250 ms weather."""
    true_iter_s = 0.010
    n_s, n_l = 4, 404
    worst = []
    for rtt_s, rtt_l in [(0.35, 0.10), (0.10, 0.35), (0.35, 0.35)]:
        t_s = rtt_s + n_s * true_iter_s
        t_l = rtt_l + n_l * true_iter_s
        s = slope(t_s, t_l, n_s, n_l, 131072)
        assert s.reason is None
        worst.append(abs(s.rate - 131072 / true_iter_s) / (131072 / true_iter_s))
    assert max(worst) < 0.15


def test_check_work():
    assert check_work(100, 100) is None
    r = check_work(99, 100)
    assert r is not None and "99" in r


def test_check_dropped():
    """Write-path proof of work: hit/miss reconciliation can't see a write
    that probes rows but never persists them (dropped rows still count as
    probed) — the drop guard can."""
    # healthy window: zero or rare drops pass
    assert check_dropped(0, 1_000_000) is None
    assert check_dropped(9999, 1_000_000) is None
    # a broken write path (e.g. a sparse grid landing updates in the wrong
    # blocks) surfaces as a drop storm and must refuse the record
    r = check_dropped(500_000, 1_000_000)
    assert r is not None and "persist" in r
    # tolerance is a knob (latency cases may tighten it)
    assert check_dropped(2, 1000, max_frac=0.001) is not None
    # degenerate windows don't divide by zero
    assert check_dropped(0, 0) is None


def test_check_transport():
    """Transport-dominance gate (ISSUE 5): a window's transfer share must
    be accountable against its reported bytes at a plausible bandwidth —
    so a compact-wire 'win' can't be faked by timing drift in either
    direction."""
    from gubernator_tpu.bench_guard import check_transport

    # 10 MB in 10 ms → 1 GB/s: a sane PCIe/tunnel window
    assert check_transport(0.010, 10_000_000) is None
    # nothing claimed against the wire → nothing to gate
    assert check_transport(0.0, 0) is None
    assert check_transport(5.0, 0) is None
    # impossible-fast: 10 GB in 1 ms → 1e13 B/s — the bytes were never
    # moved in the measured time
    r = check_transport(0.001, 10_000_000_000)
    assert r is not None and "ceiling" in r
    # drift: 1 KB 'transfer' taking 5 s — the time is not transport
    r = check_transport(5.0, 1024)
    assert r is not None and "drift" in r
    # bytes claimed against a zero-length window
    r = check_transport(0.0, 1024)
    assert r is not None and "no time" in r
    # band edges are knobs (CI disables the drift side on slow runners)
    assert check_transport(5.0, 1024, min_bandwidth=0.0) is None
    # negative byte counts are accounting bugs, not windows
    assert check_transport(0.1, -5) is not None


# ------------------------------------------------- on-device loop harness


def _mk_batch(fps, now=NOW, limit=1000):
    import jax.numpy as jnp

    from gubernator_tpu.ops.batch import ReqBatch

    b = fps.shape[0]
    z = np.zeros(b, dtype=np.int64)
    return ReqBatch(
        fp=jnp.asarray(fps),
        algo=jnp.zeros(b, dtype=jnp.int32),
        behavior=jnp.zeros(b, dtype=jnp.int32),
        hits=jnp.ones(b, dtype=jnp.int64),
        limit=jnp.full(b, limit, dtype=jnp.int64),
        burst=jnp.asarray(z),
        duration=jnp.full(b, 60_000, dtype=jnp.int64),
        created_at=jnp.full(b, now, dtype=jnp.int64),
        expire_new=jnp.full(b, now + 60_000, dtype=jnp.int64),
        greg_interval=jnp.asarray(z),
        duration_eff=jnp.full(b, 60_000, dtype=jnp.int64),
        active=jnp.ones(b, dtype=bool),
    )


def test_decide_loop_matches_sequential_dispatches():
    """k fori_loop iterations == k host-driven dispatches, bit-exact on the
    table and exact on the accumulated counters (the loop is the same
    decide2_impl graph; only the launch structure differs)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    B, K_ITERS = 256, 5
    batches = [
        _mk_batch(rng.integers(1, 1 << 62, size=B, dtype=np.int64))
        for _ in range(3)
    ]
    stacked = stack_batches(batches)

    t_loop = new_table2(1 << 12)
    t_loop, acc = decide_loop(
        t_loop, stacked, jnp.int32(K_ITERS), write="xla", math="token"
    )

    t_seq = new_table2(1 << 12)
    hits = misses = over = dropped = 0
    for i in range(K_ITERS):
        t_seq, _resp, st = decide2(
            t_seq, batches[i % 3], write="xla", math="token"
        )
        hits += int(st.cache_hits)
        misses += int(st.cache_misses)
        over += int(st.over_limit)
        dropped += int(st.dropped)

    assert bool(jnp.array_equal(t_loop.rows, t_seq.rows))
    assert [int(x) for x in acc] == [hits, misses, over, dropped]
    # proof-of-work identity the bench asserts before publishing
    assert check_work(int(acc[0] + acc[1]), K_ITERS * B) is None


def test_decide_loop_traced_k_no_retrace():
    """k is a traced scalar: two different trip counts reuse one compile
    (the tunnel pays minutes per compile; adaptive window sizing depends
    on k not being static)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(4)
    batches = [_mk_batch(rng.integers(1, 1 << 62, size=64, dtype=np.int64))]
    stacked = stack_batches(batches)
    tbl = new_table2(1 << 10)
    n0 = decide_loop._cache_size()
    tbl, acc1 = decide_loop(tbl, stacked, jnp.int32(2), write="xla", math="token")
    tbl, acc2 = decide_loop(tbl, stacked, jnp.int32(7), write="xla", math="token")
    assert decide_loop._cache_size() - n0 <= 1
    assert int(acc1[0] + acc1[1]) == 2 * 64
    assert int(acc2[0] + acc2[1]) == 7 * 64
