"""Overload plane (docs/robustness.md "Overload & QoS").

Four contracts under test:

* **zero priority inversions** — when capacity pressure forces sheds, the
  rows that fall are always the lowest queued tier (preempt-before-shed);
  the batcher's `priority_inversions` counter is the runtime proof and
  must stay exactly 0;
* **fair admission** — once the bounded ring is under pressure, one
  tenant (fingerprint bucket) cannot hold more than its share of the
  window; the abuser sheds with a fast per-item overload row, the
  victims keep being admitted;
* **deadline honesty** — an item whose enqueue deadline passes while it
  waits is shed, never served (the answer would arrive after the caller
  stopped listening);
* **lease QoS** — with GUBER_PRIORITY_LEASE_SCALING on, grants scale with
  the requester's tier, pressured keys push shrink_to hints, and the
  edge LocalLimiter honors a hint by clamping its grant target and
  returning the excess ahead of the TTL.
"""

import asyncio
import functools
import time

import numpy as np

from gubernator_tpu.client import V1Client
from gubernator_tpu.edge import LocalLimiter
from gubernator_tpu.ops.batch import ERR_OVERLOAD, RequestColumns, ResponseColumns
from gubernator_tpu.ops.engine import ms_now
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.service import deadline as deadline_mod
from gubernator_tpu.service.batcher import Batcher
from gubernator_tpu.service.daemon import Daemon
from gubernator_tpu.types import priority_tier, with_cascade_level, with_priority

from tests.cluster import daemon_config

NOW = ms_now()


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


def _cols(rows: int, tier: int = 0, base: int = 0, fp0: int = 0) -> RequestColumns:
    """A column batch at one priority tier; fp0 pins the leading
    fingerprint (= the batcher's tenant bucket) when nonzero."""
    fp = np.arange(base + 1, base + rows + 1, dtype=np.int64)
    if fp0:
        fp[0] = fp0
    return RequestColumns(
        fp=fp,
        algo=np.zeros(rows, dtype=np.int32),
        behavior=np.full(rows, with_priority(0, tier), dtype=np.int32),
        hits=np.ones(rows, dtype=np.int64),
        limit=np.full(rows, 100, dtype=np.int64),
        burst=np.zeros(rows, dtype=np.int64),
        duration=np.full(rows, 60_000, dtype=np.int64),
        created_at=np.full(rows, NOW, dtype=np.int64),
        err=np.zeros(rows, dtype=np.int8),
    )


class GatedRunner:
    """Echo runner that blocks the FIRST dispatch on an event — the
    saturated-engine stand-in the overload tests queue behind."""

    def __init__(self):
        self.gate = asyncio.Event()
        self.dispatch_rows = []
        self.dispatch_tiers = []  # leading row's tier, per dispatch

    async def check_wire(self, parts, span=None):
        return None

    async def check(self, cols, now_ms=None, span=None):
        self.dispatch_rows.append(cols.fp.shape[0])
        self.dispatch_tiers.append(priority_tier(int(cols.behavior[0])))
        if len(self.dispatch_rows) == 1:
            await self.gate.wait()
        n = cols.fp.shape[0]
        return ResponseColumns(
            status=np.zeros(n, dtype=np.int32),
            limit=cols.limit.copy(),
            remaining=cols.limit - cols.hits,
            reset_time=np.zeros(n, dtype=np.int64),
            err=np.zeros(n, dtype=np.int8),
        )


def _shed_all(rc: ResponseColumns) -> bool:
    return bool(
        (np.asarray(rc.err) == ERR_OVERLOAD).all()
        and (np.asarray(rc.status) == 1).all()
    )


def _served_all(rc: ResponseColumns) -> bool:
    return bool((np.asarray(rc.err) == 0).all())


@async_test
async def test_preemption_sheds_lowest_tier_zero_inversions():
    """A saturated ring + a high-tier arrival: the queued tier-0 batch is
    preempted (fast overload answer), the tier-3 batch is admitted and
    served, and the inversion counter stays 0."""
    runner = GatedRunner()
    b = Batcher(
        runner, batch_wait_ms=0.5, coalesce_limit=64, workers=1,
        adaptive=True, max_queue_rows=64, overload_deadline_ms=2_000.0,
    )
    first = asyncio.ensure_future(b.check(_cols(16)))
    await asyncio.sleep(0.05)  # worker picked it up; engine gated
    low = asyncio.ensure_future(b.check(_cols(64, tier=0, base=100)))
    await asyncio.sleep(0.05)  # fills the ring
    high = asyncio.ensure_future(b.check(_cols(32, tier=3, base=300)))
    await asyncio.sleep(0.05)
    runner.gate.set()
    r_first, r_low, r_high = await asyncio.gather(first, low, high)
    assert _served_all(r_first)
    assert _shed_all(r_low), "queued tier-0 rows must be preempted"
    assert _served_all(r_high), "tier-3 arrival must be served"
    assert b.shed_rows["preempted"] == 64
    assert b.shed_by_tier[0] == 64 and b.shed_by_tier[3] == 0
    assert b.priority_inversions == 0
    # preempted rows never reached the engine
    assert sum(runner.dispatch_rows) == 16 + 32
    # shed responses carry a retry hint in reset_time
    assert (np.asarray(r_low.reset_time) > 0).all()
    await b.drain()


@async_test
async def test_fair_admission_caps_abusive_tenant():
    """Under queue pressure one tenant bucket may hold at most
    tenant_share of the ring: the abuser's second batch sheds with
    reason="fairness", a different tenant is still admitted."""
    runner = GatedRunner()
    b = Batcher(
        runner, batch_wait_ms=0.5, coalesce_limit=128, workers=1,
        adaptive=True, max_queue_rows=128, overload_deadline_ms=5_000.0,
        tenant_share=0.25, tenant_buckets=64,
    )
    first = asyncio.ensure_future(b.check(_cols(16)))
    await asyncio.sleep(0.05)
    # abuser bucket: leading fp pinned to 5 → bucket 5 for every batch
    abuse1 = asyncio.ensure_future(b.check(_cols(64, base=1_000, fp0=5)))
    await asyncio.sleep(0.05)  # 64 pending = half the ring → pressured
    abuse2 = asyncio.ensure_future(b.check(_cols(32, base=2_000, fp0=5)))
    victim = asyncio.ensure_future(b.check(_cols(16, base=3_000, fp0=7)))
    await asyncio.sleep(0.05)
    runner.gate.set()
    r1, ra1, ra2, rv = await asyncio.gather(first, abuse1, abuse2, victim)
    assert _served_all(r1) and _served_all(ra1)
    assert _shed_all(ra2), "abuser beyond its share must shed"
    assert _served_all(rv), "other tenants must keep being admitted"
    assert b.shed_rows["fairness"] == 32
    assert b.priority_inversions == 0
    await b.drain()


@async_test
async def test_deadline_expired_items_shed_not_served():
    """An item whose deadline passes while queued behind a stalled engine
    is answered with the overload row and NEVER dispatched."""
    runner = GatedRunner()
    b = Batcher(
        runner, batch_wait_ms=0.5, coalesce_limit=64, workers=1,
        adaptive=True, max_queue_rows=1024, overload_deadline_ms=100.0,
    )
    first = asyncio.ensure_future(b.check(_cols(16)))
    await asyncio.sleep(0.05)
    stale = asyncio.ensure_future(b.check(_cols(32, base=100)))
    await asyncio.sleep(0.3)  # stale's 100 ms deadline passes in-queue
    runner.gate.set()
    r_first, r_stale = await asyncio.gather(first, stale)
    assert _served_all(r_first)
    assert _shed_all(r_stale), "expired work must be shed, not served"
    assert b.shed_rows["deadline"] == 32
    assert runner.dispatch_rows == [16], "expired rows must not dispatch"
    await b.drain()


@async_test
async def test_inbound_grpc_deadline_bounds_queue_wait():
    """Without the overload knob, a caller's inbound gRPC deadline alone
    bounds the queue wait (service/deadline.py contextvar)."""
    runner = GatedRunner()
    b = Batcher(
        runner, batch_wait_ms=0.5, coalesce_limit=64, workers=1,
        adaptive=True, max_queue_rows=1024,
    )
    assert not b.armed  # knob off: legacy door + inbound bounding only
    first = asyncio.ensure_future(b.check(_cols(16)))
    await asyncio.sleep(0.05)
    deadline_mod.set_inbound_deadline(0.1)
    stale = asyncio.ensure_future(b.check(_cols(8, base=100)))
    deadline_mod.set_inbound_deadline(None)
    await asyncio.sleep(0.3)
    runner.gate.set()
    _, r_stale = await asyncio.gather(first, stale)
    assert _shed_all(r_stale)
    assert b.shed_rows["deadline"] == 8
    await b.drain()


@async_test
async def test_tier_rides_wire_and_dispatch_order():
    """Priority bits survive the behavior word round trip and armed
    dispatch order is tier-major, FIFO within a tier."""
    assert priority_tier(with_priority(0, 3)) == 3
    assert priority_tier(with_priority(8, 2)) == 2  # RESET preserved below
    runner = GatedRunner()
    b = Batcher(
        runner, batch_wait_ms=0.5, coalesce_limit=16, workers=1,
        adaptive=True, max_queue_rows=1024, overload_deadline_ms=5_000.0,
    )
    first = asyncio.ensure_future(b.check(_cols(16)))
    await asyncio.sleep(0.05)
    lo = asyncio.ensure_future(b.check(_cols(16, tier=0, base=100)))
    hi = asyncio.ensure_future(b.check(_cols(16, tier=2, base=200)))
    await asyncio.sleep(0.05)
    runner.gate.set()
    await asyncio.gather(first, lo, hi)
    # coalesce_limit 16 → one chunk per entry; tier 2 dispatched before 0
    # even though it enqueued after
    assert runner.dispatch_rows == [16, 16, 16]
    assert runner.dispatch_tiers == [0, 2, 0]
    assert b.admitted_by_tier[2] == 16 and b.admitted_by_tier[0] == 32
    assert b.priority_inversions == 0
    await b.drain()


def _cascade_cols(rows: int, level: int, base: int = 0, fp0: int = 0) -> RequestColumns:
    """A column batch whose rows each carry `level` cascade levels — the
    expensive traffic the cost-weighted door must account at more than one
    unit per row."""
    c = _cols(rows, base=base, fp0=fp0)
    return c._replace(
        behavior=np.full(rows, with_cascade_level(0, level), dtype=np.int32)
    )


@async_test
async def test_cost_weighted_fairness_stops_cascade_starvation():
    """Equal ROW budgets, unequal device cost: a cascade-heavy tenant
    (level-3 rows ≈ 4 kernel rows each) exhausts its fairness share by
    COST and sheds, while the cheap single-row tenant keeps being
    admitted. The control run — identical row counts, no cascades — never
    pressures the door, proving it was the cost weighting (not the row
    counts) that capped the abuser."""
    runner = GatedRunner()
    b = Batcher(
        runner, batch_wait_ms=0.5, coalesce_limit=128, workers=1,
        adaptive=True, max_queue_rows=128, overload_deadline_ms=5_000.0,
        tenant_share=0.25, tenant_buckets=64,
    )
    first = asyncio.ensure_future(b.check(_cols(16)))
    await asyncio.sleep(0.05)  # worker picked it up; engine gated
    # cascade tenant (bucket 5): 16 rows × (1 + 3 levels) = 64 cost units
    # — only 16 ROWS, an eighth of the ring, but half its cost capacity
    casc1 = asyncio.ensure_future(b.check(_cascade_cols(16, 3, base=1_000, fp0=5)))
    await asyncio.sleep(0.05)  # 64 pending cost = half the ring → pressured
    # 8 more cascade rows = 32 cost: bucket 5 would hold 96 > share (32)
    casc2 = asyncio.ensure_future(b.check(_cascade_cols(8, 3, base=2_000, fp0=5)))
    # the cheap tenant (bucket 7) stays admissible under the same pressure
    victim = asyncio.ensure_future(b.check(_cols(16, base=3_000, fp0=7)))
    await asyncio.sleep(0.05)
    runner.gate.set()
    r1, rc1, rc2, rv = await asyncio.gather(first, casc1, casc2, victim)
    assert _served_all(r1) and _served_all(rc1)
    assert _shed_all(rc2), "cascade tenant beyond its COST share must shed"
    assert _served_all(rv), "cheap single-row traffic must not starve"
    assert b.shed_rows["fairness"] == 8
    assert b.priority_inversions == 0
    await b.drain()

    # control: the SAME row counts without cascade levels never even
    # pressure the door (16+8 rows ≪ the 64-row pressure point) — under
    # the old row-weighted accounting the abuser above was this invisible
    runner2 = GatedRunner()
    b2 = Batcher(
        runner2, batch_wait_ms=0.5, coalesce_limit=128, workers=1,
        adaptive=True, max_queue_rows=128, overload_deadline_ms=5_000.0,
        tenant_share=0.25, tenant_buckets=64,
    )
    first2 = asyncio.ensure_future(b2.check(_cols(16)))
    await asyncio.sleep(0.05)
    p1 = asyncio.ensure_future(b2.check(_cols(16, base=1_000, fp0=5)))
    await asyncio.sleep(0.05)
    p2 = asyncio.ensure_future(b2.check(_cols(8, base=2_000, fp0=5)))
    await asyncio.sleep(0.05)
    runner2.gate.set()
    rf, rp1, rp2 = await asyncio.gather(first2, p1, p2)
    assert _served_all(rf) and _served_all(rp1) and _served_all(rp2)
    assert b2.shed_rows["fairness"] == 0
    await b2.drain()


@async_test
async def test_auto_deadline_tracks_issue_ewma():
    """GUBER_OVERLOAD_DEADLINE_MS=auto arms the door with a deadline
    derived from the runner's issue-stage EWMA
    (OVERLOAD_AUTO_DEADLINE_MULT × issue_ewma, floored at shed_retry_ms)
    — re-evaluated per enqueue as the EWMA moves."""
    from gubernator_tpu.service.batcher import OVERLOAD_AUTO_DEADLINE_MULT

    runner = GatedRunner()
    b = Batcher(
        runner, batch_wait_ms=0.5, coalesce_limit=64, workers=1,
        adaptive=True, max_queue_rows=1024, overload_deadline_auto=True,
        shed_retry_ms=25,
    )
    assert b.armed  # auto arms the full overload plane
    # no EWMA yet (cold runner): the shed_retry floor keeps the door sane
    d0 = b._item_deadline()
    assert d0 is not None
    assert abs((d0 - time.monotonic()) - 0.025) < 0.01
    # a measured issue stage moves the deadline with it
    runner.issue_ewma = 0.002
    d1 = b._item_deadline()
    want = OVERLOAD_AUTO_DEADLINE_MULT * 0.002
    assert abs((d1 - time.monotonic()) - want) < 0.05
    await b.drain()


# ------------------------------------------------------------- lease QoS


@async_test
async def test_lease_grants_scale_with_tier():
    """GUBER_PRIORITY_LEASE_SCALING: same ask, tier 3 gets the full slice,
    tier 0 a quarter; pressured keys push shrink_to at low tiers."""
    conf = daemon_config()
    conf.lease_priority_scaling = True
    conf.lease_max_fraction = 0.5  # cap = 500 of the 1 000 limit
    d = await Daemon.spawn(conf)
    try:
        def req(key, tokens, tier, lease_id=""):
            return pb.LeaseQuotaReq(
                name="qos", unique_key=key, tokens=tokens, limit=1_000,
                duration=60_000, ttl_ms=2_000, lease_id=lease_id,
                behavior=with_priority(0, tier),
            )

        r3 = await d.lease_quota(req("k-hi", 400, 3))
        r0 = await d.lease_quota(req("k-lo", 400, 0))
        assert r3.granted == 400  # tier 3: full ask (≤ cap 500)
        assert r0.granted == 100  # tier 0: a quarter of the ask
        assert r3.shrink_to == 0 and r0.shrink_to == 0  # no pressure yet

        # pressure k-lo past 80% of its 500-token cap, then renew at tier 0:
        # the response must carry a shrink hint below the outstanding
        ra = await d.lease_quota(req("k-lo", 1_000, 3))
        assert ra.granted > 0
        rb = await d.lease_quota(req("k-lo", 4, 0, lease_id=r0.lease_id))
        assert rb.shrink_to > 0, "pressured low-tier lease must be asked to shrink"
        assert rb.shrink_to < 100 + rb.granted
        # tier 3 under the same pressure is never asked to shrink
        rc = await d.lease_quota(req("k-lo", 4, 3, lease_id=ra.lease_id))
        assert rc.shrink_to == 0
    finally:
        await d.close()


class _ShrinkClient(V1Client):
    """Stub lease endpoint: grants normally, then starts pushing a
    shrink_to hint — no network, the LocalLimiter drives this directly."""

    def __init__(self):
        super().__init__("127.0.0.1:1")  # lazy channel: never connected
        self.calls = 0
        self.shrink_to = 0
        self.returned = 0

    async def lease_quota(self, req, timeout_s=None):
        self.calls += 1
        self.returned += int(req.return_tokens)
        return pb.LeaseQuotaResp(
            lease_id="L1", granted=int(req.tokens),
            expires_at=ms_now() + 60_000, limit=req.limit,
            remaining=req.limit, shrink_to=self.shrink_to,
        )


@async_test
async def test_local_limiter_honors_push_shrink_hint():
    """A shrink_to hint clamps the edge's grant target and the next
    renewal returns the excess budget instead of holding it to the TTL."""
    client = _ShrinkClient()
    # waste_fraction=10: disable adaptive halving so any giveback in this
    # test is attributable to the shrink hint alone
    lim = LocalLimiter(
        client, "edge", "u1", limit=1_000, duration=60_000,
        ttl_ms=60_000, initial_grant=64, waste_fraction=10.0,
    )
    await lim.start()
    assert lim.budget == 64 and lim.stats.shrinks == 0
    client.shrink_to = 8
    await lim._renew_once()  # hint arrives with this renewal's response
    assert lim.stats.shrinks == 1
    assert lim._grant <= 8, "grant target must clamp to the hint"

    async def excess_returned():
        deadline = time.perf_counter() + 5
        while time.perf_counter() < deadline:
            if client.returned > 0:
                return True
            await asyncio.sleep(0.05)
        return False

    assert await excess_returned(), "excess budget must return early"
    await lim.close()
    await client.close()
