"""Pass planner + per-request error isolation tests."""

import numpy as np
import pytest

from gubernator_tpu.ops.batch import pack_requests
from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.ops.plan import plan_passes
from gubernator_tpu.types import (
    Algorithm,
    Behavior,
    Gregorian,
    RateLimitRequest,
    Status,
    MINUTE,
)


def req(key, hits=1, limit=100, behavior=0, algorithm=Algorithm.TOKEN_BUCKET, name="t"):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit, duration=MINUTE,
        algorithm=algorithm, behavior=behavior,
    )


def test_invalid_items_do_not_fail_the_batch(frozen_now):
    # reference gubernator.go:215-224 answers per-item errors
    eng = LocalEngine(capacity=256)
    out = eng.check(
        [
            req("ok1"),
            RateLimitRequest(name="t", unique_key="", hits=1, limit=5, duration=MINUTE),
            RateLimitRequest(name="", unique_key="k", hits=1, limit=5, duration=MINUTE),
            req("ok2"),
        ],
        now_ms=frozen_now,
    )
    assert out[0].error == "" and out[0].remaining == 99
    assert out[1].error == "field 'unique_key' cannot be empty"
    assert out[2].error == "field 'namespace' cannot be empty"
    assert out[3].error == "" and out[3].remaining == 99


def test_bad_gregorian_is_per_request_error(frozen_now):
    eng = LocalEngine(capacity=256)
    out = eng.check(
        [
            req("good"),
            req("bad", behavior=Behavior.DURATION_IS_GREGORIAN),  # duration=MINUTE: invalid enum
            req("also-good"),
        ],
        now_ms=frozen_now,
    )
    assert out[0].error == "" and out[1].error != "" and out[2].error == ""
    assert "gregorian" in out[1].error.lower()


def test_hot_key_aggregation_merges_only_reset_remaining(frozen_now):
    # behaviors of aggregated duplicates must not leak into the carrier row
    # (only RESET_REMAINING merges, reference global.go:117-121)
    b, errs = pack_requests(
        [req("hot", behavior=Behavior.DRAIN_OVER_LIMIT) for _ in range(10)]
        + [req("hot", behavior=Behavior.RESET_REMAINING)]
        + [req("hot")],  # newest: carrier, no flags
        frozen_now,
    )
    passes = plan_passes(b, max_exact=2)
    assert len(passes) == 2
    agg = passes[-1]
    assert agg.batch.behavior[0] == int(Behavior.RESET_REMAINING)
    assert agg.batch.hits[0] == 11  # everything after occurrence 0 summed
    assert len(agg.member_rows[0]) == 11


def test_aggregated_members_share_response(frozen_now):
    eng = LocalEngine(capacity=256, max_exact_passes=2)
    out = eng.check([req("hk", hits=1, limit=100) for _ in range(50)], now_ms=frozen_now)
    # pass 0: first occurrence consumes 1 → 99; aggregate pass: 49 more → 50
    assert out[0].remaining == 99
    assert all(r.remaining == 50 for r in out[1:])
    assert all(r.status == Status.UNDER_LIMIT for r in out)


def test_planner_skips_inactive_rows(frozen_now):
    b, errs = pack_requests(
        [req("a"), RateLimitRequest(name="t", unique_key="", limit=1, duration=1), req("b")],
        frozen_now,
    )
    passes = plan_passes(b)
    assert len(passes) == 1
    assert list(passes[0].rows) == [0, 2]


def test_drain_over_limit_keeps_predrain_reset_time(frozen_now):
    # reference algorithms.go:372-377,406-419: the drained rejection reports
    # the reset_time computed from the PRE-drain remaining
    eng = LocalEngine(capacity=256)
    t = frozen_now
    lk = RateLimitRequest(
        name="t", unique_key="lk", hits=5, limit=10, duration=10_000,
        algorithm=Algorithm.LEAKY_BUCKET, created_at=t,
    )
    (r,) = eng.check([lk], now_ms=t)
    assert r.remaining == 5
    drain = RateLimitRequest(
        name="t", unique_key="lk", hits=8, limit=10, duration=10_000,
        algorithm=Algorithm.LEAKY_BUCKET, behavior=Behavior.DRAIN_OVER_LIMIT,
        created_at=t,
    )
    (r,) = eng.check([drain], now_ms=t)
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 0
    # rate = 1000 ms/token; pre-drain remaining 5 → reset = t + (10-5)*1000
    assert r.reset_time == t + 5_000


def test_oversized_limit_burst_rejected(frozen_now):
    # table stores int32 carriers; the front door must reject larger values
    # with a per-request error instead of silently saturating device state
    eng = LocalEngine(capacity=256)
    out = eng.check(
        [
            req("big", limit=2**31 + 1000),
            RateLimitRequest(
                name="t", unique_key="bb", hits=1, limit=10, burst=2**40,
                duration=MINUTE, algorithm=Algorithm.LEAKY_BUCKET,
            ),
            req("fine", limit=2**31 - 1),
        ],
        now_ms=frozen_now,
    )
    assert out[0].error == "field 'limit' must fit int32"
    assert out[1].error == "field 'burst' must fit int32"
    assert out[2].error == "" and out[2].status == Status.UNDER_LIMIT


def test_created_at_clamped_to_ingress_tolerance(frozen_now):
    # a client-supplied created_at far in the future must not renew/expire
    # live buckets (the reference checks expiry against the server clock,
    # lrucache.go GetItem); deviations clamp to now ± tolerance
    from gubernator_tpu.ops.batch import CREATED_AT_TOLERANCE_MS

    b, errs = pack_requests(
        [
            RateLimitRequest(
                name="t", unique_key="skew", hits=1, limit=10, duration=MINUTE,
                created_at=frozen_now + 10 * CREATED_AT_TOLERANCE_MS,
            ),
            RateLimitRequest(
                name="t", unique_key="stale", hits=1, limit=10, duration=MINUTE,
                created_at=frozen_now - 10 * CREATED_AT_TOLERANCE_MS,
            ),
            RateLimitRequest(
                name="t", unique_key="ok", hits=1, limit=10, duration=MINUTE,
                created_at=frozen_now + 1000,
            ),
        ],
        frozen_now,
    )
    assert errs == [None, None, None]
    assert b.created_at[0] == frozen_now + CREATED_AT_TOLERANCE_MS
    assert b.created_at[1] == frozen_now - CREATED_AT_TOLERANCE_MS
    assert b.created_at[2] == frozen_now + 1000  # within tolerance: untouched


def test_peers_package_imports():
    # regression: peers/__init__ imported a module that didn't exist, leaving
    # the whole subpackage dead on arrival
    from gubernator_tpu.peers import RegionPicker, ReplicatedConsistentHash
    from gubernator_tpu.types import PeerInfo

    rp = RegionPicker()
    rp.add(PeerInfo(grpc_address="10.0.0.1:81", data_center="dc-a"))
    rp.add(PeerInfo(grpc_address="10.0.0.2:81", data_center="dc-a"))
    rp.add(PeerInfo(grpc_address="10.0.1.1:81", data_center="dc-b"))
    owners = rp.get_clients("some_key")
    assert len(owners) == 2  # one owner per region
    assert {o.data_center for o in owners} == {"dc-a", "dc-b"}
    assert rp.get_by_address("10.0.1.1:81").data_center == "dc-b"
    assert rp.size() == 3


def test_clamp_is_counted_and_configurable(frozen_now):
    from gubernator_tpu.ops import batch as batch_mod
    from gubernator_tpu.ops.batch import (
        columns_from_requests,
        created_at_tolerance_ms,
        set_created_at_tolerance_ms,
    )

    eng = LocalEngine(capacity=256)
    skewed = RateLimitRequest(
        name="t", unique_key="skew", hits=1, limit=10, duration=MINUTE,
        created_at=frozen_now - 10 * batch_mod.CREATED_AT_TOLERANCE_MS,
    )
    eng.check_columns(columns_from_requests([req("ok"), skewed]), now_ms=frozen_now)
    assert eng.stats.created_at_clamped == 1

    # widening the tolerance stops the clamping (GUBER_CREATED_AT_TOLERANCE)
    old = created_at_tolerance_ms()
    try:
        set_created_at_tolerance_ms(20 * batch_mod.CREATED_AT_TOLERANCE_MS)
        eng.check_columns(
            columns_from_requests([skewed]), now_ms=frozen_now
        )
        assert eng.stats.created_at_clamped == 1  # unchanged
    finally:
        set_created_at_tolerance_ms(old)
    with pytest.raises(ValueError):
        set_created_at_tolerance_ms(0)
