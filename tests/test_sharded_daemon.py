"""GUBER_ENGINE=sharded: one daemon serving the whole 8-device mesh through
the real gRPC front door."""

import asyncio
import functools

from gubernator_tpu.client import V1Client
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.types import Behavior, RateLimitRequest

from tests.cluster import Cluster, daemon_config, metric_value, scrape, wait_for


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


def req(key, name="sh", hits=1, limit=100, **kw):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit, duration=60_000, **kw
    )


@async_test
async def test_sharded_daemon_serves_over_grpc():
    from gubernator_tpu.parallel.sharded import ShardedEngine
    from gubernator_tpu.service.daemon import Daemon

    d = await Daemon.spawn(daemon_config(engine="sharded", cache_size=8192))
    assert isinstance(d.engine, ShardedEngine)
    assert d.engine.n_shards == 8
    client = V1Client(d.conf.grpc_address)
    try:
        # keys spread over every shard; counts persist across dispatches
        keys = [f"k{i}" for i in range(64)]
        r1 = await client.get_rate_limits([req(k, hits=2) for k in keys])
        assert all(x.error == "" and x.remaining == 98 for x in r1.responses)
        r2 = await client.get_rate_limits([req(k, hits=1) for k in keys])
        assert all(x.remaining == 97 for x in r2.responses)
        # per-item validation errors still isolate
        r3 = await client.get_rate_limits(
            [req("good"), dict(name="", unique_key="x", hits=1, limit=5, duration=60_000)]
        )
        assert r3.responses[0].error == ""
        assert "namespace" in r3.responses[1].error
        # the mesh engine really holds the keys
        assert d.engine.live_count() >= 64
    finally:
        await client.close()
        await d.close()


@async_test
async def test_sharded_daemon_device_route_serves_over_grpc():
    """GUBER_SHARD_ROUTE=device: requests ship in arrival order and the mesh
    routes them with an all_to_all exchange (parallel/a2a.py) — served
    through the same pipelined gRPC front door."""
    from gubernator_tpu.service.daemon import Daemon

    d = await Daemon.spawn(
        daemon_config(engine="sharded", cache_size=8192, shard_route="device")
    )
    assert d.engine.route == "device"
    client = V1Client(d.conf.grpc_address)
    try:
        keys = [f"dr{i}" for i in range(96)]
        r1 = await client.get_rate_limits([req(k, hits=2) for k in keys])
        assert all(x.error == "" and x.remaining == 98 for x in r1.responses)
        r2 = await client.get_rate_limits([req(k, hits=1) for k in keys])
        assert all(x.remaining == 97 for x in r2.responses)
        assert d.engine.live_count() >= 96
        # GLOBAL rows take the replica plane (host-pinned dispatches) while
        # everything else rides the a2a exchange — both under one engine
        rg = await client.get_rate_limits(
            [req("drg", hits=2, behavior=Behavior.GLOBAL)]
        )
        assert rg.responses[0].error == ""
        assert rg.responses[0].remaining == 98
        async def synced():
            return d.engine.global_stats.sync_rounds >= 1

        await wait_for(synced, timeout_s=30.0)
    finally:
        await client.close()
        await d.close()


@async_test
async def test_sharded_daemons_global_converges():
    """Two sharded daemons: GLOBAL hits at the non-owner reach the owner and
    the authoritative status installs into the non-owner's mesh (the
    update_peer_globals → install_columns path)."""
    c = await Cluster.start(2, engine="sharded", cache_size=4096)
    try:
        owner = c.find_owning_daemon("sh", "gkey")
        non_owner = c.non_owning_daemons("sh", "gkey")[0]
        client = V1Client(non_owner.conf.grpc_address)
        try:
            r = await client.get_rate_limits(
                [req("gkey", hits=4, behavior=Behavior.GLOBAL)]
            )
            assert r.responses[0].error == ""
            assert r.responses[0].remaining == 96

            async def owner_converged():
                ro = await owner.get_rate_limits(
                    [pb.RateLimitReq(
                        name="sh", unique_key="gkey", hits=0, limit=100,
                        duration=60_000,
                    )]
                )
                return ro[0].remaining == 96

            await wait_for(owner_converged, timeout_s=15)
        finally:
            await client.close()
    finally:
        await c.stop()


@async_test
async def test_standalone_mesh_global_over_grpc():
    """BASELINE config #3 as an API-served path: a standalone sharded daemon
    serves GLOBAL through the collective plane — replica answers at a rotating
    home device, hits drained by the all_gather sync tick, convergence
    asserted with EXACT mesh counters scraped over the wire (the reference's
    TestGlobalBehavior technique, functional_test.go:1760-2167)."""
    from gubernator_tpu.parallel.global_sync import GlobalShardedEngine
    from gubernator_tpu.service.daemon import Daemon

    d = await Daemon.spawn(daemon_config(engine="sharded", cache_size=8192))
    assert isinstance(d.engine, GlobalShardedEngine)
    D = d.engine.n_shards
    client = V1Client(d.conf.grpc_address)
    try:
        keys = [f"g{i}" for i in range(16)]
        r1 = await client.get_rate_limits(
            [req(k, hits=3, behavior=Behavior.GLOBAL) for k in keys]
        )
        assert all(x.error == "" and x.remaining == 97 for x in r1.responses)

        # exact counters BEFORE any convergence read (reference discipline):
        # one sync round applies every key once as owner and installs its
        # authoritative status on the other D-1 devices' replicas
        async def synced():
            m = await scrape(d)
            return metric_value(m, "gubernator_mesh_sync_rounds_total") >= 1

        await wait_for(synced, timeout_s=40)
        m = await scrape(d)
        assert metric_value(m, "gubernator_mesh_broadcasts_applied_total") == 16
        assert metric_value(m, "gubernator_mesh_updates_installed_total") == 16 * (D - 1)

        # convergence: a zero-hit GLOBAL read at EVERY home device (homes
        # rotate per dispatch) must agree on the authoritative remaining
        for _ in range(D):
            r = await client.get_rate_limits(
                [req(k, hits=0, behavior=Behavior.GLOBAL) for k in keys]
            )
            assert all(x.remaining == 97 for x in r.responses)
        # zero-hit reads are never queued (global.go:85-95): counters frozen
        m = await scrape(d)
        assert metric_value(m, "gubernator_mesh_broadcasts_applied_total") == 16

        # hits accumulated from several homes reconcile at the owner: 4
        # dispatches × 2 hits on one key → authoritative remaining 97-8=89
        for _ in range(4):
            r = await client.get_rate_limits(
                [req("g0", hits=2, behavior=Behavior.GLOBAL)]
            )
            assert r.responses[0].error == ""

        async def converged():
            if d.engine.has_pending():
                return False
            r = await client.get_rate_limits(
                [req("g0", hits=0, behavior=Behavior.GLOBAL)]
            )
            return r.responses[0].remaining == 89

        await wait_for(converged, timeout_s=40)
        for _ in range(D):  # every home's replica agrees
            r = await client.get_rate_limits(
                [req("g0", hits=0, behavior=Behavior.GLOBAL)]
            )
            assert r.responses[0].remaining == 89
    finally:
        await client.close()
        await d.close()


@async_test
async def test_sharded_daemon_checkpoint_roundtrip(tmp_path):
    from gubernator_tpu.service.daemon import Daemon

    snap = str(tmp_path / "mesh.snap")
    conf = daemon_config(engine="sharded", cache_size=4096, checkpoint_path=snap)
    d = await Daemon.spawn(conf)
    client = V1Client(d.conf.grpc_address)
    try:
        await client.get_rate_limits([req("persist", hits=7)])
    finally:
        await client.close()
        await d.close()  # checkpoints on close

    d2 = await Daemon.spawn(conf)
    client = V1Client(d2.conf.grpc_address)
    try:
        r = await client.get_rate_limits([req("persist", hits=0)])
        assert r.responses[0].remaining == 93  # survived the restart
    finally:
        await client.close()
        await d2.close()
