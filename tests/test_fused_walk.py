"""Fused install/merge walk suite (ops/pallas_probe.walk2_pallas_impl,
stage="install"/"merge").

The acceptance surface of the always-on-chip tentpole's walk half:

* `GUBER_WALK_KERNEL=pallas` is BIT-IDENTICAL to the two-pass XLA
  gather+write paths (`install2_impl`/`merge2_impl`, the oracles) across
  every slot layout a table can run (defaulted, full, gcra32, token32) —
  installed/merged masks AND raw table bytes, through collision pressure,
  bucket-full eviction and multi-step aging;
* the conservative-merge rules survive the fusion on BOTH walks because
  `merge_payload16` is shared verbatim: remaining=min, OVER sticks,
  expiry=max, newest-stamp config — asserted behaviorally, not just by
  parity;
* duplicate fingerprints inside one merge batch resolve as sequential
  passes (the engine.merge_rows unique-fp contract) identically on both
  walks;
* the knob threads through LocalEngine, the 8-device shard_map mesh
  (ShardedEngine route/dedup="device"), the region-sync receive path
  (ops/reconcile.apply_region_sync) and the handoff
  extract→merge→tombstone cycle unchanged;
* `GUBER_PROBE_MOVEMENT=dma` (the DMA-protocol emulation lowering) stays
  bit-identical on the fused walks, same as decide.

Everything runs the interpret-mode lowering (CPU CI), the same execution
CI's ring_smoke gates.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

from gubernator_tpu.ops.batch import InstallBatch, RequestColumns
from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.ops.kernel2 import install2_impl, merge2_impl
from gubernator_tpu.ops.layout import FULL, GCRA32, TOKEN32
from gubernator_tpu.ops.table2 import (
    EXP_HI,
    EXP_LO,
    FLAGS,
    REM_I,
    new_table2,
)

NOW = 1_700_000_000_000

# "all four" table configurations a walk can hit: the defaulted layout and
# the three named ones (packed layouts constrain the algorithm family).
LAYOUT_CASES = [
    pytest.param(None, (0, 1, 2, 3, 4), id="default"),
    pytest.param(FULL, (0, 1, 2, 3, 4), id="full"),
    pytest.param(GCRA32, (2,), id="gcra32"),
    pytest.param(TOKEN32, (0,), id="token32"),
]


def mkfp(rng, n, bucket_pool=None, pool_nb=64):
    """Unique fingerprints; `bucket_pool` concentrates them into that many
    hash buckets of a pool_nb-bucket table (collision pressure)."""
    if bucket_pool:
        base = rng.integers(1, pool_nb, size=bucket_pool, dtype=np.int64)
        fp = base[rng.integers(0, bucket_pool, size=2 * n)] + pool_nb * \
            rng.integers(1, 1 << 40, size=2 * n, dtype=np.int64)
    else:
        fp = rng.integers(1, 1 << 62, size=2 * n, dtype=np.int64)
    fp = np.unique(fp)
    while fp.shape[0] < n:
        fp = np.unique(np.concatenate(
            [fp, rng.integers(1, 1 << 62, size=n, dtype=np.int64)]
        ))
    fp = fp[:n]
    rng.shuffle(fp)
    return fp


def mkinst(rng, n, algos=(0,), n_active=None, limit=100, dur=60_000,
           now=NOW, bucket_pool=None, pool_nb=64, fidelity=False):
    """InstallBatch of unique-fp owner-authoritative statuses (the
    UpdatePeerGlobals receive shape). `fidelity` attaches the PR-11
    sliding-window aux/rem_store broadcast lanes."""
    n_active = n if n_active is None else n_active
    fp = mkfp(rng, n, bucket_pool, pool_nb)
    algo = np.array([algos[i % len(algos)] for i in range(n)], dtype=np.int32)
    remaining = rng.integers(0, limit + 1, size=n).astype(np.int64)
    status = (rng.integers(0, 4, size=n) == 0).astype(np.int32)  # ~25% OVER
    stamp = now - rng.integers(0, 5_000, size=n).astype(np.int64)
    active = np.arange(n) < n_active
    j = jnp.asarray
    return InstallBatch(
        fp=j(fp),
        algo=j(algo),
        status=j(status),
        limit=j(np.full(n, limit, dtype=np.int64)),
        remaining=j(remaining),
        reset_time=j(np.full(n, now + dur, dtype=np.int64)),
        duration=j(np.full(n, dur, dtype=np.int64)),
        now=j(np.full(n, now, dtype=np.int64)),
        active=j(active),
        burst=j(np.full(n, limit, dtype=np.int64)),
        stamp=j(stamp),
        aux=j(rng.integers(0, limit, size=n).astype(np.int64))
        if fidelity else None,
        rem_store=j(remaining.copy()) if fidelity else None,
    )


def assert_install_parity(cap, mk, layout=None, steps=3, step_ms=20_000):
    """Drive both install walks over the same broadcast stream and assert
    installed-mask and raw-table-byte identity at every step."""
    tx = new_table2(cap, layout=layout)
    tp = new_table2(cap, layout=layout)
    for s in range(steps):
        inst = mk(s * step_ms)
        tx, mx = install2_impl(tx, inst, write="xla")
        tp, mp = install2_impl(tp, inst, write="xla", probe="pallas")
        np.testing.assert_array_equal(
            np.asarray(mx), np.asarray(mp),
            err_msg=f"step {s}: installed mask",
        )
        np.testing.assert_array_equal(
            np.asarray(tx.rows), np.asarray(tp.rows),
            err_msg=f"step {s}: table bytes",
        )


# ------------------------------------------------------ install walk parity


@pytest.mark.parametrize("lay,algos", LAYOUT_CASES)
def test_install_parity_per_layout(lay, algos):
    rng = np.random.default_rng(21)
    assert_install_parity(
        512,
        lambda dt: mkinst(rng, 128, algos=algos, now=NOW + dt),
        layout=lay, steps=4,
    )


@pytest.mark.parametrize("lay,algos", LAYOUT_CASES)
def test_install_parity_collision_pressure(lay, algos):
    """More unique keys per bucket than K=8 lanes: the install walk evicts
    soonest-expiring LIVE lanes and drops rank overflow, identically."""
    rng = np.random.default_rng(22)
    assert_install_parity(
        64,
        lambda dt: mkinst(rng, 192, algos=algos, now=NOW + dt,
                          bucket_pool=4, pool_nb=8),
        layout=lay, steps=4,
    )


def test_install_parity_block_boundary_carries(monkeypatch):
    """Bucket runs straddling grid blocks on the install walk: tiny blocks
    force multi-block carries and carry flushes at every shape."""
    rng = np.random.default_rng(23)
    for blk in ("8", "16", "64"):
        monkeypatch.setenv("GUBER_PROBE_BLK", blk)
        assert_install_parity(
            256,
            lambda dt: mkinst(rng, 96, n_active=77, algos=(0, 2, 4),
                              now=NOW + dt, bucket_pool=9, pool_nb=32),
            steps=3,
        )


def test_install_parity_fidelity_and_padding():
    """Sliding-window broadcast fidelity lanes (aux/rem_store) and inactive
    padding rows ride the fused walk bit-identically."""
    rng = np.random.default_rng(24)
    assert_install_parity(
        512,
        lambda dt: mkinst(rng, 128, algos=(3,), now=NOW + dt, fidelity=True),
        steps=3,
    )
    assert_install_parity(
        512,
        lambda dt: mkinst(rng, 96, n_active=50, algos=(0, 1, 2, 3, 4),
                          now=NOW + dt),
        steps=3,
    )
    # all-padding warm batch (the warm_up shape)
    assert_install_parity(
        256, lambda dt: mkinst(rng, 32, n_active=0, now=NOW + dt), steps=2,
    )


def test_install_parity_expired_slot_reclaim():
    """Steps larger than the duration: every slot expires between steps and
    the install walk reclaims through the vacant-first candidate order."""
    rng = np.random.default_rng(25)
    assert_install_parity(
        128,
        lambda dt: mkinst(rng, 128, algos=(0, 2, 3), now=NOW + dt,
                          dur=5_000, bucket_pool=8, pool_nb=16),
        steps=4, step_ms=30_000,
    )


# -------------------------------------------------------- merge walk parity


def cols(fp, algo, hits=1, limit=64, now=NOW, dur=8_000):
    n = fp.shape[0]
    h = (np.asarray(hits, dtype=np.int64) if np.ndim(hits)
         else np.full(n, hits, dtype=np.int64))
    return RequestColumns(
        fp=fp.astype(np.int64),
        algo=np.full(n, algo, dtype=np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=h,
        limit=np.full(n, limit, dtype=np.int64),
        burst=np.zeros(n, dtype=np.int64),
        duration=np.full(n, dur, dtype=np.int64),
        created_at=np.full(n, now, dtype=np.int64),
        err=np.zeros(n, dtype=np.int8),
    )


def donor_rows(rng, n, algo, now=NOW, dur=8_000, cap=1 << 11):
    """Realistic live slot rows: drive serving traffic through a donor
    engine, then extract — the handoff sender's exact staging form."""
    eng = LocalEngine(capacity=cap, write_mode="xla")
    fp = mkfp(rng, n)
    eng.check_columns(
        cols(fp, algo, hits=rng.integers(0, 3, size=n), now=now, dur=dur),
        now_ms=now,
    )
    fps, slots = eng.extract_live(now_ms=now)
    assert fps.shape[0] > 0
    return fps, slots


def assert_merge_parity(cap, fps, slots, layout=None, now=NOW, steps=2,
                        step_ms=3_000, evictees=False, seed=0):
    """Merge the same transferred rows into two same-seeded tables through
    both walks; assert merged-mask, evictee and raw-table-byte identity.
    Tables are pre-seeded with half the keys (via the XLA install walk, so
    both start bit-identical) to exercise the live-lane conservatism
    branch, not just fresh installs."""
    rng = np.random.default_rng(seed + 77)
    tx = new_table2(cap, layout=layout)
    tp = new_table2(cap, layout=layout)
    n = fps.shape[0]
    j = jnp.asarray
    for s in range(steps):
        t = now + s * step_ms
        fp_p = j(fps)
        slots_p = j(slots)
        act = j(np.ones(n, dtype=bool))
        nowv = j(np.full(n, t, dtype=np.int64))
        if evictees:
            tx, mx, ex = merge2_impl(
                tx, fp_p, slots_p, nowv, act, write="xla", evictees=True,
            )
            tp, mp, ep = merge2_impl(
                tp, fp_p, slots_p, nowv, act, write="xla", evictees=True,
                probe="pallas",
            )
            np.testing.assert_array_equal(
                np.asarray(ex), np.asarray(ep),
                err_msg=f"step {s}: evictee rows",
            )
        else:
            tx, mx = merge2_impl(tx, fp_p, slots_p, nowv, act, write="xla")
            tp, mp = merge2_impl(
                tp, fp_p, slots_p, nowv, act, write="xla", probe="pallas",
            )
        np.testing.assert_array_equal(
            np.asarray(mx), np.asarray(mp), err_msg=f"step {s}: merged mask",
        )
        np.testing.assert_array_equal(
            np.asarray(tx.rows), np.asarray(tp.rows),
            err_msg=f"step {s}: table bytes",
        )
        # next step: perturb the incoming rows so the repeated merge hits
        # the live-lane tighten branch with different winners
        pert = slots.copy()
        pert[:, REM_I] = np.maximum(
            pert[:, REM_I] - rng.integers(0, 5, size=n).astype(np.int32), 0
        )
        slots = pert


@pytest.mark.parametrize("algo", [0, 2, 3])
def test_merge_parity_per_algorithm(algo):
    rng = np.random.default_rng(31 + algo)
    fps, slots = donor_rows(rng, 256, algo)
    assert_merge_parity(1 << 11, fps, slots, steps=3, seed=algo)


@pytest.mark.parametrize("lay,algo", [
    pytest.param(GCRA32, 2, id="gcra32"), pytest.param(TOKEN32, 0,
                                                       id="token32"),
])
def test_merge_parity_packed_receiver(lay, algo):
    """A packed receiver merging full-width transferred rows (the
    cross-layout handoff): the fused walk packs through the same canonical
    conversion."""
    rng = np.random.default_rng(35)
    fps, slots = donor_rows(rng, 192, algo)
    assert_merge_parity(512, fps, slots, layout=lay, steps=3)


def test_merge_parity_collision_and_evictees(monkeypatch):
    """Merge under bucket-full pressure with evictee collection: displaced
    LIVE rows ride home identically (the tiering promote contract)."""
    monkeypatch.setenv("GUBER_PROBE_BLK", "16")
    rng = np.random.default_rng(36)
    fp = mkfp(rng, 192, bucket_pool=4, pool_nb=8)
    eng = LocalEngine(capacity=1 << 11, write_mode="xla")
    eng.check_columns(cols(fp, 0, hits=1), now_ms=NOW)
    fps, slots = eng.extract_live(now_ms=NOW)
    assert_merge_parity(64, fps, slots, steps=3, evictees=True)


def test_merge_parity_expired_incoming_rows():
    """Incoming rows whose expiry predates the receiver clock are inert on
    both walks (the merge2_impl active-gate, applied before routing)."""
    rng = np.random.default_rng(37)
    fps, slots = donor_rows(rng, 128, 0, dur=2_000)
    assert_merge_parity(512, fps, slots, now=NOW + 10_000, steps=2)


# --------------------------------------------- conservatism, behaviorally


def _engines(cap=256, **kw):
    return (LocalEngine(capacity=cap, write_mode="xla", walk="xla", **kw),
            LocalEngine(capacity=cap, write_mode="xla", walk="pallas", **kw))


def _install_one(e, fp, status, remaining, stamp, dur=60_000, algo=0,
                 now=NOW, limit=100):
    one = lambda v, dt: np.array([v], dtype=dt)
    e.install_columns(
        one(fp, np.int64), one(algo, np.int32), one(status, np.int32),
        one(limit, np.int64), one(remaining, np.int64),
        one(now + dur, np.int64), one(dur, np.int64), now_ms=now,
        stamp=one(stamp, np.int64),
    )


def test_merge_conservatism_over_sticks_min_remaining():
    """remaining=min and OVER-sticks survive the fusion: a generous
    incoming row can never re-grant capacity a stored OVER denied."""
    fp = 0x5EED_F00D
    donor = LocalEngine(capacity=256, write_mode="xla")
    _install_one(donor, fp, status=0, remaining=80, stamp=NOW + 5)
    dfps, drows = donor.extract_live(now_ms=NOW)
    outs = []
    for e in _engines():
        _install_one(e, fp, status=1, remaining=20, stamp=NOW)
        assert e.merge_rows(dfps, drows, now_ms=NOW + 10) == 1
        found, rows = e.read_state(np.array([fp], dtype=np.int64))
        assert found[0]
        outs.append(rows[0])
    xla_row, pal_row = outs
    np.testing.assert_array_equal(xla_row, pal_row)
    assert int(xla_row[REM_I]) == 20  # min(stored 20, incoming 80)
    assert (int(xla_row[FLAGS]) >> 8) & 0xFF == 1  # OVER sticks


def test_merge_conservatism_expiry_max_and_newest_config():
    """expiry=max (state lives at least as long) and newest-stamp config
    (the later limit wins) — identical on both walks."""
    fp = 0xC0FF_EE11
    donor = LocalEngine(capacity=256, write_mode="xla")
    _install_one(donor, fp, status=0, remaining=150, stamp=NOW + 9,
                 dur=120_000, limit=200)
    dfps, drows = donor.extract_live(now_ms=NOW)
    outs = []
    for e in _engines():
        _install_one(e, fp, status=0, remaining=50, stamp=NOW, dur=60_000)
        assert e.merge_rows(dfps, drows, now_ms=NOW + 10) == 1
        found, rows = e.read_state(np.array([fp], dtype=np.int64))
        assert found[0]
        outs.append(rows[0])
    np.testing.assert_array_equal(outs[0], outs[1])
    exp = (int(outs[0][EXP_HI]) << 32) | (int(outs[0][EXP_LO]) & 0xFFFFFFFF)
    assert exp == NOW + 120_000  # max of the two expiries
    assert int(outs[0][REM_I]) == 50  # min still tightens
    from gubernator_tpu.ops.table2 import LIMIT

    assert int(outs[0][LIMIT]) == 200  # newest stamp's config won


def test_merge_duplicate_fps_sequential_passes():
    """Duplicate fingerprints inside one merge batch resolve as sequential
    passes (the unique-fp contract): both walks land the same final state
    and the same merged count."""
    rng = np.random.default_rng(41)
    fps, slots = donor_rows(rng, 96, 0)
    # duplicate every key, second copy strictly tighter (smaller remaining)
    dup_rows = slots.copy()
    dup_rows[:, REM_I] = np.maximum(dup_rows[:, REM_I] - 7, 0)
    all_fps = np.concatenate([fps, fps])
    all_rows = np.concatenate([slots, dup_rows])
    counts, snaps = [], []
    for e in _engines(cap=1 << 11):
        counts.append(e.merge_rows(all_fps, all_rows, now_ms=NOW + 5))
        snaps.append(e.snapshot())
    assert counts[0] == counts[1]
    np.testing.assert_array_equal(snaps[0], snaps[1])
    # and the tighter duplicate won: stored remaining is the min copy
    e = _engines(cap=1 << 11)[0]
    e.merge_rows(all_fps, all_rows, now_ms=NOW + 5)
    found, rows = e.read_state(fps)
    np.testing.assert_array_equal(
        rows[found, REM_I], dup_rows[found, REM_I]
    )


# ----------------------------------------------------------- engine layer


def test_local_engine_walk_parity():
    """GUBER_WALK_KERNEL threads through the serving engine: identical
    install counts, merge counts and raw table bytes."""
    rng = np.random.default_rng(51)
    ex, ep = _engines(cap=1 << 12)
    assert ep.walk_mode == "pallas"
    n = 256
    fp = mkfp(rng, n)
    algo = np.array([(0, 2, 3)[i % 3] for i in range(n)], dtype=np.int32)
    kw = dict(
        limit=np.full(n, 100, dtype=np.int64),
        remaining=rng.integers(0, 101, size=n).astype(np.int64),
        reset_time=np.full(n, NOW + 60_000, dtype=np.int64),
        duration=np.full(n, 60_000, dtype=np.int64),
        now_ms=NOW,
    )
    status = (rng.integers(0, 3, size=n) == 0).astype(np.int32)
    cx = ex.install_columns(fp, algo, status, **kw)
    cp = ep.install_columns(fp, algo, status, **kw)
    assert cx == cp == n
    np.testing.assert_array_equal(ex.snapshot(), ep.snapshot())
    # a follow-up merge of perturbed extracted rows stays identical
    fps, slots = ex.extract_live(now_ms=NOW)
    slots = ex._slots_to_full(slots)
    slots[:, REM_I] = np.maximum(slots[:, REM_I] - 3, 0)
    assert ex.merge_rows(fps, slots, now_ms=NOW + 50) == \
        ep.merge_rows(fps, slots, now_ms=NOW + 50)
    np.testing.assert_array_equal(ex.snapshot(), ep.snapshot())


def test_handoff_cycle_walk_parity():
    """The topology-change cycle — extract → merge (receiver) → tombstone
    (source) → re-merge a duplicated transfer — lands bit-identically, and
    the duplicate grants nothing extra (docs/robustness.md)."""
    rng = np.random.default_rng(52)
    src = LocalEngine(capacity=1 << 11, write_mode="xla")
    fp = mkfp(rng, 128)
    src.check_columns(cols(fp, 2, hits=2), now_ms=NOW)
    fps, slots = src.extract_live(now_ms=NOW)
    ex, ep = _engines(cap=1 << 11)
    for e in (ex, ep):
        assert e.merge_rows(fps, slots, now_ms=NOW + 5) == fps.shape[0]
    np.testing.assert_array_equal(ex.snapshot(), ep.snapshot())
    assert src.tombstone_fps(fps) == fps.shape[0]
    # crossed/duplicated transfer: re-merge the SAME rows later
    snap = ex.snapshot()
    for e in (ex, ep):
        e.merge_rows(fps, slots, now_ms=NOW + 500)
    np.testing.assert_array_equal(ex.snapshot(), ep.snapshot())
    np.testing.assert_array_equal(ex.snapshot(), snap)  # nothing re-granted


def test_region_sync_walk_parity():
    """The cross-region receive path (ops/reconcile.apply_region_sync →
    read_state + merge_rows) rides the fused merge walk unchanged — full
    and packed sender layouts both."""
    from gubernator_tpu.ops.reconcile import apply_region_sync

    rng = np.random.default_rng(53)
    n = 128
    sender = LocalEngine(capacity=1 << 11, write_mode="xla")
    fp = mkfp(rng, n)
    sender.check_columns(cols(fp, 2, hits=1, limit=32), now_ms=NOW)
    sfps, sslots = sender.extract_live(now_ms=NOW)
    m = sfps.shape[0]
    cfg = {
        "limit": np.full(m, 32, dtype=np.int64),
        "duration": np.full(m, 8_000, dtype=np.int64),
        "algo": np.full(m, 2, dtype=np.int64),
        "created_at": np.full(m, NOW, dtype=np.int64),
    }
    deltas = rng.integers(1, 5, size=m).astype(np.int64)
    ex, ep = _engines(cap=1 << 11)
    for e in (ex, ep):  # receivers hold live state for half the keys
        e.check_columns(cols(sfps[: m // 2], 2, hits=1, limit=32),
                        now_ms=NOW)
        applied = apply_region_sync(
            e, sfps, deltas, cfg, sslots, sender_layout=None,
            now_ms=NOW + 20,
        )
        assert applied == m
    np.testing.assert_array_equal(ex.snapshot(), ep.snapshot())


def test_sharded_mesh_walk_parity():
    """The PR-8 shard_map mesh path composes unchanged: the fused walks run
    per device shard inside the routed install/merge programs (8-device
    CPU mesh — the TPU serving defaults)."""
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.sharded import ShardedEngine

    mesh = make_mesh(8)
    kw = dict(capacity_per_shard=1 << 10, write_mode="xla",
              route="device", dedup="device")
    ex = ShardedEngine(mesh, walk="xla", **kw)
    ep = ShardedEngine(mesh, walk="pallas", **kw)
    assert ep.walk_mode == "pallas"
    rng = np.random.default_rng(54)
    n = 512
    fp = mkfp(rng, n)
    algo = np.full(n, 2, dtype=np.int32)
    kw2 = dict(
        limit=np.full(n, 64, dtype=np.int64),
        remaining=rng.integers(0, 65, size=n).astype(np.int64),
        reset_time=np.full(n, NOW + 60_000, dtype=np.int64),
        duration=np.full(n, 60_000, dtype=np.int64),
        now_ms=NOW,
    )
    status = np.zeros(n, dtype=np.int32)
    assert ex.install_columns(fp, algo, status, **kw2) == \
        ep.install_columns(fp, algo, status, **kw2)
    np.testing.assert_array_equal(ex.snapshot(), ep.snapshot())
    fps, slots = ex.extract_live(now_ms=NOW)
    slots = ex._slots_to_full(slots)
    slots[:, REM_I] = np.maximum(slots[:, REM_I] - 2, 0)
    # duplicated fps exercise the sequential-pass path on the mesh too
    all_fps = np.concatenate([fps, fps[: 64]])
    all_rows = np.concatenate([slots, slots[: 64]])
    assert ex.merge_rows(all_fps, all_rows, now_ms=NOW + 9) == \
        ep.merge_rows(all_fps, all_rows, now_ms=NOW + 9)
    np.testing.assert_array_equal(ex.snapshot(), ep.snapshot())


# ------------------------------------------------- movement & knob plumbing


def test_fused_walk_dma_movement_parity(monkeypatch):
    """GUBER_PROBE_MOVEMENT=dma (the DMA-protocol emulation lowering) stays
    bit-identical on both fused walks, same as the decide kernel."""
    monkeypatch.setenv("GUBER_PROBE_MOVEMENT", "dma")
    rng = np.random.default_rng(61)
    assert_install_parity(
        256,
        lambda dt: mkinst(rng, 96, algos=(0, 2), now=NOW + dt,
                          bucket_pool=6, pool_nb=16),
        steps=2,
    )
    fps, slots = donor_rows(rng, 96, 0)
    assert_merge_parity(256, fps, slots, steps=2)


def test_walk_env_resolution(monkeypatch):
    from gubernator_tpu.ops.plan import default_walk_kernel

    monkeypatch.delenv("GUBER_WALK_KERNEL", raising=False)
    assert default_walk_kernel() == "xla"  # auto = today's kernel
    monkeypatch.setenv("GUBER_WALK_KERNEL", "pallas")
    assert default_walk_kernel() == "pallas"
    assert LocalEngine(capacity=1 << 10).walk_mode == "pallas"
    monkeypatch.setenv("GUBER_WALK_KERNEL", "bogus")
    with pytest.raises(ValueError):
        default_walk_kernel()
    with pytest.raises(ValueError):
        LocalEngine(capacity=1 << 10, walk="bogus")


def test_config_walk_kernel_and_ring_validation():
    from gubernator_tpu.config import (
        BehaviorConfig,
        ConfigError,
        DaemonConfig,
    )

    DaemonConfig(walk_kernel="pallas").validate()
    DaemonConfig(
        behaviors=BehaviorConfig(ring_enable=True, ring_slots=2)
    ).validate()
    with pytest.raises(ConfigError):
        DaemonConfig(walk_kernel="nope").validate()
    with pytest.raises(ConfigError):
        DaemonConfig(behaviors=BehaviorConfig(ring_slots=1)).validate()
