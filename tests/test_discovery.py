"""Discovery pool tests without real infrastructure — the reference's own
technique (fake DNS server dns_test.go:81-294; pure k8s extraction functions
kubernetes_internal_test.go:52)."""

import asyncio
import base64
import functools
import json

import pytest
from aiohttp import web

from gubernator_tpu.types import PeerInfo


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


async def wait_until(pred, timeout_s=10.0, interval_s=0.05):
    deadline = asyncio.get_running_loop().time() + timeout_s
    while True:
        if pred():
            return
        if asyncio.get_running_loop().time() > deadline:
            raise TimeoutError("condition not met")
        await asyncio.sleep(interval_s)


# ------------------------------------------------------------------ memberlist


@async_test
async def test_memberlist_three_nodes_converge_and_leave():
    from gubernator_tpu.discovery.memberlist import MemberlistPool

    seen = {}

    def updater(name):
        def cb(peers):
            seen[name] = sorted(p.grpc_address for p in peers)

        return cb

    pools = []
    # node 0 is the seed
    p0 = MemberlistPool(
        bind_address="127.0.0.1:0",
        known_nodes=[],
        on_update=updater("n0"),
        peer_info=PeerInfo(grpc_address="10.0.0.1:1051", data_center="dc-a"),
        gossip_interval_ms=50.0,
    )
    await p0.start()
    pools.append(p0)
    seed = p0.advertise_address
    for i, name in enumerate(["n1", "n2"], start=1):
        p = MemberlistPool(
            bind_address="127.0.0.1:0",
            known_nodes=[seed],
            on_update=updater(name),
            peer_info=PeerInfo(grpc_address=f"10.0.0.{i + 1}:1051"),
            gossip_interval_ms=50.0,
        )
        await p.start()
        pools.append(p)

    want = ["10.0.0.1:1051", "10.0.0.2:1051", "10.0.0.3:1051"]
    try:
        await wait_until(
            lambda: all(seen.get(n) == want for n in ("n0", "n1", "n2"))
        )
        # graceful leave propagates as a tombstone
        await pools[2].close()
        await wait_until(
            lambda: seen["n0"] == want[:2] and seen["n1"] == want[:2]
        )
    finally:
        for p in pools[:2]:
            await p.close()


@async_test
async def test_memberlist_detects_dead_peer_by_heartbeat_timeout():
    from gubernator_tpu.discovery.memberlist import MemberlistPool

    seen = {}
    p0 = MemberlistPool(
        bind_address="127.0.0.1:0",
        known_nodes=[],
        on_update=lambda ps: seen.__setitem__(
            "n0", sorted(p.grpc_address for p in ps)
        ),
        peer_info=PeerInfo(grpc_address="10.0.0.1:1051"),
        gossip_interval_ms=50.0,
        suspect_ticks=4,
    )
    await p0.start()
    p1 = MemberlistPool(
        bind_address="127.0.0.1:0",
        known_nodes=[p0.advertise_address],
        on_update=lambda ps: None,
        peer_info=PeerInfo(grpc_address="10.0.0.2:1051"),
        gossip_interval_ms=50.0,
    )
    await p1.start()
    try:
        await wait_until(
            lambda: seen.get("n0") == ["10.0.0.1:1051", "10.0.0.2:1051"]
        )
        # hard-kill node 1 (no tombstone): cancel its loop + server
        p1._closed = True
        p1._task.cancel()
        p1._server.close()
        await wait_until(lambda: seen.get("n0") == ["10.0.0.1:1051"], timeout_s=15)
    finally:
        await p0.close()
        try:
            await p1.close()
        except Exception:
            pass


# ----------------------------------------------------------------------- etcd


class FakeEtcd:
    """Minimal in-process etcd v3 HTTP JSON gateway: kv put/range/deleterange,
    lease grant/keepalive/revoke with TTL expiry."""

    def __init__(self):
        self.kv = {}  # key(str) -> (value str, lease id)
        self.leases = {}  # id -> expires_at (loop time)
        self.next_lease = 7000
        self.watchers = []  # asyncio.Queue per open watch stream
        self.app = web.Application()
        self.app.router.add_post("/v3/kv/put", self.put)
        self.app.router.add_post("/v3/kv/range", self.range)
        self.app.router.add_post("/v3/kv/deleterange", self.deleterange)
        self.app.router.add_post("/v3/lease/grant", self.grant)
        self.app.router.add_post("/v3/lease/keepalive", self.keepalive)
        self.app.router.add_post("/v3/lease/revoke", self.revoke)
        self.app.router.add_post("/v3/watch", self.watch)
        self.runner = None
        self.url = ""

    def _notify(self):
        for q in list(self.watchers):
            q.put_nowait({"type": "PUT"})

    async def watch(self, req):
        resp = web.StreamResponse()
        await resp.prepare(req)
        q = asyncio.Queue()
        self.watchers.append(q)
        await resp.write(b'{"result":{"created":true}}\n')
        try:
            while True:
                ev = await q.get()
                await resp.write(
                    json.dumps({"result": {"events": [ev]}}).encode() + b"\n"
                )
        except (asyncio.CancelledError, ConnectionResetError):
            pass
        finally:
            self.watchers.remove(q)
        return resp

    def _gc(self):
        now = asyncio.get_running_loop().time()
        dead = {lid for lid, exp in self.leases.items() if exp < now}
        for lid in dead:
            del self.leases[lid]
        self.kv = {
            k: (v, lid)
            for k, (v, lid) in self.kv.items()
            if lid is None or lid in self.leases
        }

    async def put(self, req):
        b = await req.json()
        key = base64.b64decode(b["key"]).decode()
        val = base64.b64decode(b["value"]).decode()
        self.kv[key] = (val, b.get("lease"))
        self._notify()
        return web.json_response({})

    async def range(self, req):
        self._gc()
        b = await req.json()
        key = base64.b64decode(b["key"]).decode()
        end = base64.b64decode(b.get("range_end", b["key"])).decode()
        kvs = [
            {
                "key": base64.b64encode(k.encode()).decode(),
                "value": base64.b64encode(v.encode()).decode(),
            }
            for k, (v, _) in sorted(self.kv.items())
            if key <= k < end
        ]
        return web.json_response({"kvs": kvs, "count": str(len(kvs))})

    async def deleterange(self, req):
        b = await req.json()
        key = base64.b64decode(b["key"]).decode()
        self.kv.pop(key, None)
        self._notify()
        return web.json_response({})

    async def grant(self, req):
        b = await req.json()
        lid = self.next_lease
        self.next_lease += 1
        self.leases[lid] = asyncio.get_running_loop().time() + float(b["TTL"])
        return web.json_response({"ID": str(lid), "TTL": str(b["TTL"])})

    async def keepalive(self, req):
        b = await req.json()
        lid = int(b["ID"])
        if lid not in self.leases:
            return web.json_response({"result": {"TTL": "0"}})
        self.leases[lid] = asyncio.get_running_loop().time() + 30.0
        return web.json_response({"result": {"ID": str(lid), "TTL": "30"}})

    async def revoke(self, req):
        b = await req.json()
        self.leases.pop(int(b["ID"]), None)
        self._gc()
        self._notify()
        return web.json_response({})

    async def start(self):
        self.runner = web.AppRunner(self.app)
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        port = self.runner.addresses[0][1]
        self.url = f"http://127.0.0.1:{port}"

    async def stop(self):
        await self.runner.cleanup()


@async_test
async def test_etcd_pool_register_discover_deregister():
    from gubernator_tpu.discovery.etcd import EtcdPool

    fake = FakeEtcd()
    await fake.start()
    seen = {}

    def updater(name):
        def cb(peers):
            seen[name] = sorted(p.grpc_address for p in peers)

        return cb

    a = EtcdPool(
        fake.url, updater("a"),
        PeerInfo(grpc_address="10.0.0.1:1051", data_center="dc-a"),
        poll_ms=50.0,
    )
    b = EtcdPool(
        fake.url, updater("b"), PeerInfo(grpc_address="10.0.0.2:1051"),
        poll_ms=50.0,
    )
    try:
        await a.start()
        await b.start()
        want = ["10.0.0.1:1051", "10.0.0.2:1051"]
        await wait_until(lambda: seen.get("a") == want and seen.get("b") == want)
        # self-markers + DC survive the JSON roundtrip
        assert "/gubernator/peers/10.0.0.1:1051" in fake.kv
        stored = json.loads(fake.kv["/gubernator/peers/10.0.0.1:1051"][0])
        assert stored["data_center"] == "dc-a"
        # close → key deleted → the other pool converges on one peer
        await b.close()
        await wait_until(lambda: seen["a"] == ["10.0.0.1:1051"])
    finally:
        await a.close()
        await fake.stop()


@async_test
async def test_etcd_pool_lease_expiry_drops_dead_peer():
    """A crashed node's key must disappear when its lease expires (the
    keepalive stops; reference etcd.go:30s lease)."""
    from gubernator_tpu.discovery.etcd import EtcdPool

    fake = FakeEtcd()
    await fake.start()
    seen = {}
    a = EtcdPool(
        fake.url,
        lambda ps: seen.__setitem__("a", sorted(p.grpc_address for p in ps)),
        PeerInfo(grpc_address="10.0.0.1:1051"),
        poll_ms=50.0,
        lease_ttl_s=1,
    )
    b = EtcdPool(
        fake.url, lambda ps: None, PeerInfo(grpc_address="10.0.0.2:1051"),
        poll_ms=50.0, lease_ttl_s=1,
    )
    try:
        await a.start()
        await b.start()
        await wait_until(
            lambda: seen.get("a") == ["10.0.0.1:1051", "10.0.0.2:1051"]
        )
        # hard-kill b: cancel its tasks without deregistering
        b._closed = True
        for t in b._tasks:
            t.cancel()
        await wait_until(lambda: seen["a"] == ["10.0.0.1:1051"], timeout_s=15)
    finally:
        await a.close()
        await b._session.close()
        await fake.stop()


@async_test
async def test_etcd_watch_propagates_membership_sub_poll():
    """Membership changes ride the watch stream, not the poll cadence
    (reference etcd.go:173-219): with polling effectively disabled, a
    register and a deregister both propagate in well under the poll
    interval."""
    from gubernator_tpu.discovery.etcd import EtcdPool

    fake = FakeEtcd()
    await fake.start()
    seen = {}

    def cb(peers):
        seen["p"] = sorted(p.grpc_address for p in peers)

    pool = EtcdPool(
        fake.url,
        on_update=cb,
        peer_info=PeerInfo(grpc_address="127.0.0.1:1"),
        poll_ms=60_000.0,  # the poller cannot be the one propagating
    )
    pool2 = EtcdPool(
        fake.url,
        on_update=lambda ps: None,
        peer_info=PeerInfo(grpc_address="127.0.0.1:2"),
        poll_ms=60_000.0,
    )
    try:
        await pool.start()
        await wait_until(lambda: seen.get("p") == ["127.0.0.1:1"], timeout_s=5)
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        await pool2.start()  # registers → watch event → re-range
        await wait_until(
            lambda: seen.get("p") == ["127.0.0.1:1", "127.0.0.1:2"],
            timeout_s=5,
            interval_s=0.005,
        )
        assert loop.time() - t0 < 2.0  # event latency, not the 60 s poll
        t0 = loop.time()
        await pool2.close()  # deletes its key → watch event
        await wait_until(
            lambda: seen.get("p") == ["127.0.0.1:1"],
            timeout_s=5,
            interval_s=0.005,
        )
        assert loop.time() - t0 < 2.0
    finally:
        await pool.close()
        await fake.stop()


# ------------------------------------------------------------------------ k8s


def _slice(endpoints, address_type="IPv4"):
    return {"addressType": address_type, "endpoints": endpoints}


def test_extract_peers_from_endpoint_slices():
    from gubernator_tpu.discovery.kubernetes import (
        extract_peers_from_endpoint_slices,
    )

    slices = [
        _slice(
            [
                {"addresses": ["10.0.0.1"], "conditions": {"ready": True}},
                {"addresses": ["10.0.0.2"], "conditions": {"ready": False}},
                {"addresses": ["10.0.0.3"]},  # no conditions → ready
                {"addresses": []},  # ignored
            ]
        ),
        _slice([{"addresses": ["fe80::1"]}], address_type="IPv6"),  # ignored
        # duplicate of .1 in a second slice must not duplicate the peer
        _slice([{"addresses": ["10.0.0.1"], "conditions": {"ready": True}}]),
    ]
    peers = extract_peers_from_endpoint_slices(slices, "10.0.0.9", "1051")
    assert sorted(p.grpc_address for p in peers) == [
        "10.0.0.1:1051",
        "10.0.0.3:1051",
    ]
    # a NOT-ready self must still be included (kubernetes.go:281-289)
    peers = extract_peers_from_endpoint_slices(slices, "10.0.0.2", "1051")
    got = {p.grpc_address: p.is_owner for p in peers}
    assert got == {
        "10.0.0.1:1051": False,
        "10.0.0.2:1051": True,
        "10.0.0.3:1051": False,
    }


def test_extract_peers_from_pods():
    from gubernator_tpu.discovery.kubernetes import extract_peers_from_pods

    pods = [
        {
            "status": {
                "podIP": "10.0.0.1",
                "phase": "Running",
                "conditions": [{"type": "Ready", "status": "True"}],
            }
        },
        {
            "status": {
                "podIP": "10.0.0.2",
                "phase": "Pending",
                "conditions": [],
            }
        },
        {"status": {}},  # no IP yet
    ]
    peers = extract_peers_from_pods(pods, "10.0.0.9", "1051")
    assert [p.grpc_address for p in peers] == ["10.0.0.1:1051"]
    # self included even when not ready
    peers = extract_peers_from_pods(pods, "10.0.0.2", "1051")
    assert sorted(p.grpc_address for p in peers) == [
        "10.0.0.1:1051",
        "10.0.0.2:1051",
    ]


async def _fake_k8s_api(state):
    """In-process API server: list + watch on endpointslices. Returns
    (url, runner, notify) — notify() pushes a watch event to open streams."""
    state.setdefault("watchers", [])
    state.setdefault("rv", 7)
    app = web.Application()

    async def endpointslices(req):
        assert req.headers.get("Authorization") == "Bearer test-token"
        assert req.query.get("labelSelector") == "app=gubernator"
        if req.query.get("watch"):
            resp = web.StreamResponse()
            await resp.prepare(req)
            q = asyncio.Queue()
            state["watchers"].append(q)
            try:
                while True:
                    ev = await q.get()
                    await resp.write(json.dumps(ev).encode() + b"\n")
            except (asyncio.CancelledError, ConnectionResetError):
                pass
            finally:
                state["watchers"].remove(q)
            return resp
        return web.json_response(
            {"items": state["items"],
             "metadata": {"resourceVersion": str(state["rv"])}}
        )

    app.router.add_get(
        "/apis/discovery.k8s.io/v1/namespaces/default/endpointslices",
        endpointslices,
    )
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    url = f"http://127.0.0.1:{runner.addresses[0][1]}"

    def notify():
        state["rv"] += 1
        for q in list(state["watchers"]):
            q.put_nowait(
                {"type": "MODIFIED",
                 "object": {"metadata": {"resourceVersion": str(state["rv"])}}}
            )

    return url, runner, notify


@async_test
async def test_k8s_pool_against_fake_api():
    from gubernator_tpu.discovery.kubernetes import K8sPool

    state = {
        "items": [
            _slice([{"addresses": ["10.0.0.1"], "conditions": {"ready": True}}])
        ]
    }
    url, runner, _notify = await _fake_k8s_api(state)

    seen = {}
    pool = K8sPool(
        on_update=lambda ps: seen.__setitem__(
            "p", sorted(p.grpc_address for p in ps)
        ),
        pod_ip="10.0.0.1",
        pod_port="1051",
        selector="app=gubernator",
        api_url=url,
        token="test-token",
        poll_ms=50.0,
    )
    try:
        await pool.start()
        await wait_until(lambda: seen.get("p") == ["10.0.0.1:1051"])
        # a new ready endpoint appears → next poll picks it up
        state["items"][0]["endpoints"].append(
            {"addresses": ["10.0.0.2"], "conditions": {"ready": True}}
        )
        await wait_until(
            lambda: seen.get("p") == ["10.0.0.1:1051", "10.0.0.2:1051"]
        )
    finally:
        await pool.close()
        await runner.cleanup()


@async_test
async def test_k8s_watch_propagates_membership_sub_poll():
    """Membership changes ride the list+watch stream, not the resync poll
    (reference kubernetes.go:79-114 informer): with polling effectively
    disabled, an endpoint change propagates at event latency."""
    from gubernator_tpu.discovery.kubernetes import K8sPool

    state = {
        "items": [
            _slice([{"addresses": ["10.0.0.1"], "conditions": {"ready": True}}])
        ]
    }
    url, runner, notify = await _fake_k8s_api(state)
    seen = {}
    pool = K8sPool(
        on_update=lambda ps: seen.__setitem__(
            "p", sorted(p.grpc_address for p in ps)
        ),
        pod_ip="10.0.0.1",
        pod_port="1051",
        selector="app=gubernator",
        api_url=url,
        token="test-token",
        poll_ms=60_000.0,  # the resync poll cannot be the one propagating
    )
    try:
        await pool.start()
        await wait_until(lambda: seen.get("p") == ["10.0.0.1:1051"])
        await wait_until(lambda: state["watchers"], timeout_s=5)
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        state["items"][0]["endpoints"].append(
            {"addresses": ["10.0.0.2"], "conditions": {"ready": True}}
        )
        notify()  # watch event → list+extract
        await wait_until(
            lambda: seen.get("p") == ["10.0.0.1:1051", "10.0.0.2:1051"],
            timeout_s=5,
            interval_s=0.005,
        )
        assert loop.time() - t0 < 2.0  # event latency, not the 60 s resync
    finally:
        await pool.close()
        await runner.cleanup()


def test_config_validates_discovery_requirements():
    from gubernator_tpu.config import ConfigError, DaemonConfig

    with pytest.raises(ConfigError):
        DaemonConfig(peer_discovery_type="etcd").validate()
    with pytest.raises(ConfigError):
        DaemonConfig(peer_discovery_type="member-list").validate()
    with pytest.raises(ConfigError):
        DaemonConfig(peer_discovery_type="bogus").validate()
    DaemonConfig(
        peer_discovery_type="etcd", etcd_endpoint="http://127.0.0.1:2379"
    ).validate()
    DaemonConfig(
        peer_discovery_type="member-list", memberlist_address="127.0.0.1:7946"
    ).validate()
    # k8s requires a pod IP (self-recognition) and a selector — without one
    # the pool would join every workload in the namespace into the peer ring
    with pytest.raises(ConfigError):
        DaemonConfig(peer_discovery_type="k8s", k8s_selector="a=b").validate()
    with pytest.raises(ConfigError):
        DaemonConfig(peer_discovery_type="k8s", k8s_pod_ip="10.0.0.1").validate()
    DaemonConfig(
        peer_discovery_type="k8s", k8s_pod_ip="10.0.0.1",
        k8s_selector="app=gubernator",
    ).validate()


@async_test
async def test_daemons_discover_each_other_via_memberlist():
    """Full path: two daemons boot with member-list discovery and converge on
    a shared peer ring without any explicit set_peers."""
    from tests.cluster import daemon_config

    from gubernator_tpu.service.daemon import Daemon

    d0 = await Daemon.spawn(
        daemon_config(
            peer_discovery_type="member-list",
            memberlist_address="127.0.0.1:0",
            memberlist_gossip_interval_ms=50.0,
        )
    )
    seed = d0._pool.advertise_address
    d1 = await Daemon.spawn(
        daemon_config(
            peer_discovery_type="member-list",
            memberlist_address="127.0.0.1:0",
            memberlist_known_nodes=seed,
            memberlist_gossip_interval_ms=50.0,
        )
    )
    try:
        want = sorted(
            [d0.conf.advertise_address, d1.conf.advertise_address]
        )
        await wait_until(
            lambda: sorted(p.grpc_address for p in d0.local_peers()) == want
            and sorted(p.grpc_address for p in d1.local_peers()) == want,
            timeout_s=15,
        )
        # the ring agrees on ownership across both daemons
        owner0 = d0.get_peer("some_key").grpc_address
        owner1 = d1.get_peer("some_key").grpc_address
        assert owner0 == owner1
    finally:
        await d1.close()
        await d0.close()


@async_test
async def test_memberlist_aes_gcm_keyring():
    """Gossip encryption (reference SecretKey/keyring, memberlist.go:149-167):
    nodes sharing a key converge; a keyless or wrong-key node can neither
    read nor inject state; an old-keyring node still interops during
    rotation (new key first, old key still accepted)."""
    import os

    from gubernator_tpu.discovery.memberlist import MemberlistPool

    key_a = os.urandom(32)
    key_b = os.urandom(32)
    seen = {}

    def updater(name):
        return lambda ps: seen.__setitem__(
            name, sorted(p.grpc_address for p in ps)
        )

    p0 = MemberlistPool(
        bind_address="127.0.0.1:0", known_nodes=[],
        on_update=updater("n0"),
        peer_info=PeerInfo(grpc_address="10.1.0.1:1051"),
        gossip_interval_ms=50.0, secret_keys=[key_a],
    )
    await p0.start()
    seed = p0.advertise_address
    p1 = MemberlistPool(
        bind_address="127.0.0.1:0", known_nodes=[seed],
        on_update=updater("n1"),
        peer_info=PeerInfo(grpc_address="10.1.0.2:1051"),
        gossip_interval_ms=50.0, secret_keys=[key_a],
    )
    await p1.start()
    # rotation: node 2 sends with key_b but still accepts key_a
    p2 = MemberlistPool(
        bind_address="127.0.0.1:0", known_nodes=[seed],
        on_update=updater("n2"),
        peer_info=PeerInfo(grpc_address="10.1.0.3:1051"),
        gossip_interval_ms=50.0, secret_keys=[key_b, key_a],
    )
    # ... so the cluster must also accept key_b for p2's sends to land
    p0.secret_keys.append(key_b)
    p1.secret_keys.append(key_b)
    await p2.start()
    # intruders: plaintext and wrong-key nodes must stay invisible
    evil_plain = MemberlistPool(
        bind_address="127.0.0.1:0", known_nodes=[seed],
        on_update=updater("evil_plain"),
        peer_info=PeerInfo(grpc_address="10.66.0.1:1051"),
        gossip_interval_ms=50.0,
    )
    await evil_plain.start()
    evil_key = MemberlistPool(
        bind_address="127.0.0.1:0", known_nodes=[seed],
        on_update=updater("evil_key"),
        peer_info=PeerInfo(grpc_address="10.66.0.2:1051"),
        gossip_interval_ms=50.0, secret_keys=[os.urandom(32)],
    )
    await evil_key.start()
    want = ["10.1.0.1:1051", "10.1.0.2:1051", "10.1.0.3:1051"]
    try:
        await wait_until(
            lambda: all(seen.get(n) == want for n in ("n0", "n1", "n2"))
        )
        # the intruders never learned the cluster, the cluster never saw them
        assert seen.get("evil_plain", ["10.66.0.1:1051"]) == ["10.66.0.1:1051"]
        assert seen.get("evil_key", ["10.66.0.2:1051"]) == ["10.66.0.2:1051"]
        assert seen["n0"] == want
    finally:
        for p in (p0, p1, p2, evil_plain, evil_key):
            await p.close()


def test_memberlist_secret_key_validation():
    import base64
    import os

    import pytest as _pytest

    from gubernator_tpu.config import ConfigError, DaemonConfig
    from gubernator_tpu.discovery.memberlist import MemberlistPool

    with _pytest.raises(ValueError, match="16, 24 or 32"):
        MemberlistPool(
            bind_address="127.0.0.1:0", known_nodes=[],
            on_update=lambda ps: None,
            peer_info=PeerInfo(grpc_address="x:1"),
            secret_keys=[b"short"],
        )
    good = base64.b64encode(os.urandom(32)).decode()
    DaemonConfig(memberlist_secret_keys=good).validate()
    with _pytest.raises(ConfigError, match="base64"):
        DaemonConfig(memberlist_secret_keys="!!notb64!!").validate()
    with _pytest.raises(ConfigError, match="16, 24 or 32"):
        DaemonConfig(
            memberlist_secret_keys=base64.b64encode(b"tooshort").decode()
        ).validate()
