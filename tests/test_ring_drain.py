"""Fused multi-slot ring drain suite (ops/ring_drain.py + service/ring.py
fused issue loop) — the kill-the-launch-tax tentpole's acceptance surface.

* **Byte parity.** A fused-drain daemon (GUBER_RING_ISSUE=fused) serves
  byte-identical responses to a direct-dispatch daemon over the same corpus
  under heavy submitter concurrency — the fused while_loop walks the same
  decide2_wire_cols graph per slot, in the same ticket order, so the bytes
  cannot differ.
* **Amortization.** The launch counter proves the point of the PR: strictly
  fewer drain launches than retired slots (`dispatch_launches_total{path=
  "fused"}` + ring drain counters).
* **Zero-loss drain.** drain() racing live fused launches loses nothing:
  every submitter resolves (served or RingClosed→direct fallback).
* **Backpressure.** K < occupancy just means more drains per window — the
  slot-count bound still holds, nothing drops or reorders.
* **Fence protocol.** The staged persistent-kernel claim loop (tier B)
  matches the numpy oracle in Pallas interpreter mode: publish gaps, ring
  wrap, and the K bound all honored.
"""

import asyncio
import os
import time

import numpy as np
import pytest

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")


def _corpus(reqs, rows, tag):
    from gubernator_tpu.proto import gubernator_pb2 as pb

    now = int(time.time() * 1000)
    return [
        pb.GetRateLimitsReq(
            requests=[
                pb.RateLimitReq(
                    name="drain", unique_key=f"{tag}r{r}i{i}", hits=1,
                    limit=1 << 20, duration=3_600_000, created_at=now,
                )
                for i in range(rows)
            ]
        ).SerializeToString()
        for r in range(reqs)
    ]


def _conf(**beh):
    from gubernator_tpu.config import BehaviorConfig, DaemonConfig

    beh.setdefault("batch_wait_ms", 1.0)
    beh.setdefault("front_workers", 4)
    return DaemonConfig(
        grpc_address="127.0.0.1:0", http_address="", cache_size=1 << 14,
        behaviors=BehaviorConfig(**beh),
    )


# ------------------------------------------------------------- byte parity


def test_fused_drain_byte_identity_under_concurrency(monkeypatch):
    """24 concurrent 64-row submitters through the fused-drain ring vs the
    direct path: responses byte-identical request by request, multiple
    slots retired per launch (the launch tax actually amortized), and the
    fused launch counter exported."""
    monkeypatch.setenv("GUBER_WIRE_COMPACT", "1")
    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.service.metrics import parse_metrics

    async def go():
        # coalesce_limit == the per-request row count: every request is its
        # own ring slot, so concurrent submitters actually FILL slots and
        # the drain has groups to retire (one giant coalesced chunk would
        # trivially be a single launch either way)
        df = await Daemon.spawn(_conf(
            ring_enable=True, ring_slots=8, ring_issue="fused",
            ring_drain_k=8, coalesce_limit=64, front_workers=8,
        ))
        dd = await Daemon.spawn(_conf())
        datas = _corpus(24, 64, "p")
        r1 = await asyncio.gather(*(df.get_rate_limits_raw(x) for x in datas))
        r2 = await asyncio.gather(*(dd.get_rate_limits_raw(x) for x in datas))
        scrape = parse_metrics(df.metrics.render().decode())
        dbg = df.ring.debug()
        await df.close()
        await dd.close()
        return r1, r2, scrape, dbg

    r1, r2, scrape, dbg = asyncio.run(go())
    assert r1 == r2  # byte-identical, request by request
    assert dbg["issue_mode"] == "fused"
    assert dbg["drained_slots"] >= 2
    assert dbg["launches"] == dbg["published"] == dbg["consumed"]
    # the tentpole: strictly fewer launches than retired slots
    assert dbg["drain_launches"] < dbg["drained_slots"]
    launches = scrape["gubernator_tpu_dispatch_launches_total"]
    assert launches[(("path", "fused"),)] == dbg["drain_launches"]
    slots = scrape["gubernator_tpu_ring_drain_slots_sum"]
    assert slots[()] == dbg["drained_slots"]
    assert dbg["occupancy"] == 0


# ---------------------------------------------------------- zero-loss drain


def test_drain_zero_loss_through_midflight_fused_launch(monkeypatch):
    """drain() called while fused launches are in flight: every submitter
    resolves with a real verdict (ring-served or direct fallback after
    RingClosed) — no request is lost, and the ring parks closed."""
    monkeypatch.setenv("GUBER_WIRE_COMPACT", "1")
    from gubernator_tpu.service.daemon import Daemon

    async def go():
        d = await Daemon.spawn(_conf(
            ring_enable=True, ring_slots=4, ring_issue="fused",
            ring_drain_k=4,
        ))
        datas = _corpus(16, 32, "z")
        pending = [
            asyncio.create_task(d.get_rate_limits_raw(x)) for x in datas
        ]
        await asyncio.sleep(0.01)  # some fused launches in flight
        await d.ring.drain()
        outs = await asyncio.gather(*pending)
        dbg = d.ring.debug()
        await d.close()
        return outs, dbg

    outs, dbg = asyncio.run(go())
    assert len(outs) == 16 and all(isinstance(o, bytes) for o in outs)
    assert dbg["closed"]
    assert dbg["occupancy"] == 0  # nothing stranded in a slot
    assert dbg["published"] == dbg["consumed"]


# ------------------------------------------------------------- backpressure


def test_backpressure_when_drain_k_below_occupancy(monkeypatch):
    """drain_k=2 against 8 slots and 32 submitters: each launch retires at
    most K slots, so retirement takes multiple drains — but the occupancy
    bound, FIFO ticket order, and byte results are all unaffected."""
    monkeypatch.setenv("GUBER_WIRE_COMPACT", "1")
    from gubernator_tpu.service.daemon import Daemon

    async def go():
        df = await Daemon.spawn(_conf(
            ring_enable=True, ring_slots=8, ring_issue="fused",
            ring_drain_k=2, coalesce_limit=16, front_workers=8,
        ))
        dd = await Daemon.spawn(_conf())
        datas = _corpus(32, 16, "b")
        r1 = await asyncio.gather(*(df.get_rate_limits_raw(x) for x in datas))
        r2 = await asyncio.gather(*(dd.get_rate_limits_raw(x) for x in datas))
        dbg = df.ring.debug()
        maxocc = df.ring.max_occupancy
        await df.close()
        await dd.close()
        return r1, r2, dbg, maxocc

    r1, r2, dbg, maxocc = asyncio.run(go())
    assert r1 == r2  # nothing dropped, nothing reordered
    assert dbg["drain_k"] == 2
    assert maxocc <= 8  # the slot bound held while K throttled retirement
    assert dbg["launches"] == dbg["published"] == dbg["consumed"]
    if dbg["drained_slots"] > 2:
        # K bounds the group: more drains than slots/K is impossible
        assert dbg["drain_launches"] >= dbg["drained_slots"] / 2


# -------------------------------------------------- persistent fence kernel


def _publish(seq_in, tickets):
    for t in tickets:
        seq_in[t % seq_in.shape[0]] = t + 1
    return seq_in


@pytest.mark.parametrize(
    "case",
    [
        # (slots, published tickets, start, k) — contiguous, gap, wrap, k-bound
        (4, [0, 1, 2], 0, 4),
        (4, [0, 2, 3], 0, 4),          # gap at ticket 1: claim stops at 1
        (4, [4, 5, 6, 7], 4, 4),       # second lap of the ring
        (8, list(range(6)), 0, 2),     # k < published: claim exactly k
        (4, [], 0, 4),                 # nothing published: claim nothing
        (4, [1, 2], 0, 4),             # head not published: claim nothing
    ],
)
def test_fence_claim_kernel_matches_oracle(case):
    """Tier B's claim loop (interpreter mode) against the numpy oracle:
    identical claimed count, identical claimed payload, identical seq_out
    fence words — publish gaps stop the claim, the ring wraps, K bounds."""
    from gubernator_tpu.ops.ring_drain import fence_claim_ref, make_fence_claim

    slots, tickets, start, k = case
    width = 6
    rng = np.random.default_rng(42 + slots + len(tickets))
    grids = rng.integers(-5, 100, size=(slots, 5, width + 1), dtype=np.int32)
    seq_in = _publish(np.zeros(slots, dtype=np.int32), tickets)
    seq_out = np.zeros(slots, dtype=np.int32)

    n_ref, bank_ref, seq_out_ref = fence_claim_ref(
        seq_in, seq_out.copy(), grids, start, k
    )
    fn = make_fence_claim(slots, width, k_max=k, interpret=True)
    ctl = np.asarray([start, k], dtype=np.int32)
    seq_out_dev, bank_dev, n_dev = fn(
        seq_in, seq_out.copy(), grids, ctl
    )

    assert int(n_dev[0]) == n_ref
    np.testing.assert_array_equal(np.asarray(seq_out_dev), seq_out_ref)
    # only the claimed prefix of the bank is defined
    np.testing.assert_array_equal(
        np.asarray(bank_dev)[:n_ref], bank_ref[:n_ref]
    )


def test_fused_config_env_plumbing():
    from gubernator_tpu.config import setup_daemon_config

    conf = setup_daemon_config(env={
        "GUBER_GRPC_ADDRESS": "127.0.0.1:0", "GUBER_HTTP_ADDRESS": "",
        "GUBER_RING_ENABLE": "1", "GUBER_RING_ISSUE": "fused",
        "GUBER_RING_DRAIN_K": "4", "GUBER_RING_SLOT_WIDTH": "128",
        "GUBER_OVERLOAD_DEADLINE_MS": "auto",
    })
    assert conf.behaviors.ring_issue == "fused"
    assert conf.behaviors.ring_drain_k == 4
    assert conf.behaviors.ring_slot_width == 128
    assert conf.behaviors.overload_deadline_auto is True
    assert conf.behaviors.overload_deadline_ms == 0.0
