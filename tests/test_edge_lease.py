"""Edge quota leases (docs/leases.md) — the client-side admission plane.

Three layers under test:

* kernel — negative-hit (release/return) miss-safety: a return against an
  unknown or expired key must neither install fresh state nor push
  remaining past the limit (ops/math.py neg_miss + clamps);
* server — LeaseQuota grants account against the real limit through the
  normal decide path, Σ outstanding is capped per key, returns refund
  bounded by the lease record, TTL reclaims silently-dead leases, and
  GLOBAL / MULTI_REGION behaviors see leased consumption as ordinary hits;
* edge — LocalLimiter admits at memory speed, renews adaptively (double on
  exhaustion, shrink on waste), degrades to per-check RPCs honoring
  retry_after_ms, stays exact under thread concurrency, and keeps the
  over-admission bound across a daemon kill/restart (admissions ≤ limit +
  outstanding-at-crash).
"""

import asyncio
import functools
import os
import tempfile
import threading

import numpy as np
import pytest

from gubernator_tpu.client import (
    V1Client,
    response_from_pb,
    response_retry_after_ms,
)
from gubernator_tpu.edge import LocalLimiter
from gubernator_tpu.ops.batch import RequestColumns
from gubernator_tpu.ops.engine import LocalEngine, ms_now
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.service.lease_manager import LEDGER_SUFFIX
from gubernator_tpu.types import Behavior

from tests.cluster import Cluster, daemon_config, wait_for

NOW = ms_now()
MINUTE = 60_000


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


def _cols(fps, hits, algo=0, limit=100, dur=MINUTE, now=NOW, burst=0):
    n = len(fps)
    return RequestColumns(
        fp=np.asarray(fps, dtype=np.int64),
        algo=np.full(n, algo, dtype=np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=np.asarray(hits, dtype=np.int64),
        limit=np.full(n, limit, dtype=np.int64),
        burst=np.full(n, burst, dtype=np.int64),
        duration=np.full(n, dur, dtype=np.int64),
        created_at=np.full(n, now, dtype=np.int64),
        err=np.zeros(n, dtype=np.int8),
    )


# ------------------------------------------------------- kernel miss-safety


def test_release_of_unknown_key_installs_nothing():
    """A lease release (hits < 0) for a key the table never saw must NOT
    claim a slot and write fresh state — pre-fix it installed a zero-
    inflight lease row with a full TTL."""
    e = LocalEngine(capacity=4096)
    rc = e.check_columns(_cols([11], [-3], algo=4), now_ms=NOW)
    assert rc.status[0] == 0 and rc.remaining[0] == 100
    assert e.live_count(NOW) == 0


def test_release_of_expired_key_does_not_resurrect():
    """A late release after TTL reclamation already freed the lease slot
    must not resurrect it with a fresh TTL."""
    e = LocalEngine(capacity=4096)
    e.check_columns(_cols([13], [2], algo=4, dur=10), now_ms=NOW)
    rc = e.check_columns(
        _cols([13], [-2], algo=4, dur=10), now_ms=NOW + 50_000
    )
    assert rc.remaining[0] == 100
    assert e.live_count(NOW + 50_000) == 0


def test_over_release_clamps_extension_algorithms():
    """Releasing more than is held clamps at the limit for the EXTENSION
    lanes (GCRA / window / lease) — a release can never mint tokens there.
    Token and leaky keep the reference's credit-banking semantics
    (functional_test.go:297): negative hits may raise remaining past the
    limit, which the parity suite pins."""
    for algo in (2, 3, 4):  # gcra, window, lease
        e = LocalEngine(capacity=4096)
        e.check_columns(_cols([21], [5], algo=algo), now_ms=NOW)
        rc = e.check_columns(_cols([21], [-50], algo=algo), now_ms=NOW + 1)
        assert rc.remaining[0] == 100, f"algo {algo}: {rc.remaining[0]}"
        rc = e.check_columns(_cols([21], [0], algo=algo), now_ms=NOW + 2)
        assert rc.remaining[0] <= 100, f"algo {algo} stored past limit"
    for algo in (0, 1):  # token, leaky: reference banking preserved
        e = LocalEngine(capacity=4096)
        e.check_columns(_cols([22], [5], algo=algo), now_ms=NOW)
        rc = e.check_columns(_cols([22], [-50], algo=algo), now_ms=NOW + 1)
        assert rc.remaining[0] == 145, f"algo {algo}: {rc.remaining[0]}"


def test_partial_token_return_refunds_exactly():
    e = LocalEngine(capacity=4096)
    e.check_columns(_cols([31], [10]), now_ms=NOW)
    rc = e.check_columns(_cols([31], [-4]), now_ms=NOW + 1)
    assert rc.remaining[0] == 94


def test_miss_return_in_mixed_batch_installs_only_live_rows():
    """One mixed-graph batch (a leaky row forces math='mixed'): the lease
    and window miss-returns remove, the real hits install."""
    e = LocalEngine(capacity=4096)
    cols = _cols([41, 42, 43], [-3, -2, 1], algo=4)
    cols = cols._replace(algo=np.array([4, 3, 1], dtype=np.int32))
    rc = e.check_columns(cols, now_ms=NOW)
    assert rc.remaining[0] == 100 and rc.remaining[1] == 100
    assert e.live_count(NOW) == 1  # only the leaky hit row


@async_test
async def test_daemon_layer_miss_safe_return():
    """End to end: a return RPC for an unknown key answers a full bucket
    and leaves the table empty — no fresh-slot install from a return."""
    d = (await Cluster.start(1)).daemons[0]
    try:
        r = (await d.get_rate_limits([pb.RateLimitReq(
            name="ret", unique_key="ghost", hits=-5, limit=100,
            duration=MINUTE, algorithm=int(pb.CONCURRENCY_LEASE),
        )]))[0]
        assert r.status == pb.UNDER_LIMIT and r.remaining == 100
        assert await d.runner.live_count() == 0
    finally:
        await d.close()


# ------------------------------------------------------------ server plane


@async_test
async def test_lease_quota_grant_accounts_against_real_limit():
    d = (await Cluster.start(1)).daemons[0]
    try:
        c = V1Client(d.conf.grpc_address)
        r = await c.lease_quota(pb.LeaseQuotaReq(
            name="api", unique_key="t1", tokens=20, limit=100,
            duration=MINUTE, ttl_ms=5_000,
        ))
        assert r.granted == 20 and r.lease_id and r.outstanding == 20
        assert r.expires_at > d.now_ms()
        chk = (await c.get_rate_limits([pb.RateLimitReq(
            name="api", unique_key="t1", hits=0, limit=100,
            duration=MINUTE,
        )])).responses[0]
        assert chk.remaining == 80  # the grant IS hits on the real limit
        # the outstanding ledger rides a CONCURRENCY_LEASE row on the
        # derived key — visible through the ordinary check surface
        led = (await c.get_rate_limits([pb.RateLimitReq(
            name="api" + LEDGER_SUFFIX, unique_key="t1", hits=0, limit=50,
            duration=5_000, algorithm=int(pb.CONCURRENCY_LEASE),
        )])).responses[0]
        assert led.remaining == 30  # cap 50 (fraction 0.5), 20 out
        # return 5 unused → refunded to the real limit, ledger shrinks
        r2 = await c.lease_quota(pb.LeaseQuotaReq(
            name="api", unique_key="t1", return_tokens=5, limit=100,
            duration=MINUTE, lease_id=r.lease_id,
        ))
        assert r2.outstanding == 15 and r2.remaining == 85
        dbg = d.debug_leases()
        assert dbg["outstanding_tokens_total"] == 15
        assert dbg["over_admission_bound"] == 15
        assert dbg["ops"]["returns"] == 1
        await c.close()
    finally:
        await d.close()


@async_test
async def test_lease_cap_and_exhaustion_fall_back():
    """Σ outstanding per key is capped at max_fraction × limit; an
    exhausted lane answers granted=0 with a retry hint (the client then
    serves via per-check RPCs)."""
    d = (await Cluster.start(1)).daemons[0]
    try:
        c = V1Client(d.conf.grpc_address)
        r1 = await c.lease_quota(pb.LeaseQuotaReq(
            name="cap", unique_key="k", tokens=1000, limit=100,
            duration=MINUTE, ttl_ms=5_000,
        ))
        assert r1.granted == 50  # fraction cap: 0.5 × 100
        r2 = await c.lease_quota(pb.LeaseQuotaReq(
            name="cap", unique_key="k", tokens=10, limit=100,
            duration=MINUTE, ttl_ms=5_000,
        ))
        assert r2.granted == 0 and r2.outstanding == 50
        assert r2.retry_after_ms >= 0
        assert d.lease_manager.denies == 1
        # per-check RPCs still work against the remaining half
        chk = (await c.get_rate_limits([pb.RateLimitReq(
            name="cap", unique_key="k", hits=1, limit=100, duration=MINUTE,
        )])).responses[0]
        assert chk.status == pb.UNDER_LIMIT
        await c.close()
    finally:
        await d.close()


@async_test
async def test_lease_forged_return_cannot_mint_tokens():
    """A return with no/unknown lease id refunds nothing — other traffic's
    consumed tokens stay consumed."""
    d = (await Cluster.start(1)).daemons[0]
    try:
        c = V1Client(d.conf.grpc_address)
        await c.get_rate_limits([pb.RateLimitReq(
            name="forge", unique_key="k", hits=40, limit=100,
            duration=MINUTE,
        )])
        r = await c.lease_quota(pb.LeaseQuotaReq(
            name="forge", unique_key="k", return_tokens=40, limit=100,
            duration=MINUTE, lease_id="deadbeef",
        ))
        assert r.granted == 0
        chk = (await c.get_rate_limits([pb.RateLimitReq(
            name="forge", unique_key="k", hits=0, limit=100,
            duration=MINUTE,
        )])).responses[0]
        assert chk.remaining == 60  # nothing refunded
        assert d.lease_manager.unknown_returns == 1
        # a lease id minted for ANOTHER key refunds nothing either — the
        # record must match (name, unique_key), not just exist
        other = await c.lease_quota(pb.LeaseQuotaReq(
            name="other", unique_key="x", tokens=5, limit=100,
            duration=MINUTE,
        ))
        assert other.granted == 5
        r2 = await c.lease_quota(pb.LeaseQuotaReq(
            name="forge", unique_key="k", return_tokens=40, limit=100,
            duration=MINUTE, lease_id=other.lease_id,
        ))
        assert r2.granted == 0
        chk = (await c.get_rate_limits([pb.RateLimitReq(
            name="forge", unique_key="k", hits=0, limit=100,
            duration=MINUTE,
        )])).responses[0]
        assert chk.remaining == 60  # still nothing refunded
        assert d.lease_manager.unknown_returns == 2
        # the other key's lease accounting is untouched
        assert d.lease_manager._leases[other.lease_id].outstanding == 5
        # and a fresh acquire that arrives WITH a foreign lease id mints
        # its own id instead of clobbering the foreign record
        r3 = await c.lease_quota(pb.LeaseQuotaReq(
            name="forge", unique_key="k", tokens=4, limit=100,
            duration=MINUTE, lease_id=other.lease_id,
        ))
        assert r3.granted == 4 and r3.lease_id != other.lease_id
        assert d.lease_manager._leases[other.lease_id].outstanding == 5
        assert d.lease_manager._leases[r3.lease_id].outstanding == 4
        await c.close()
    finally:
        await d.close()


@async_test
async def test_lease_ttl_reclaims_ledger_without_scan():
    """An unrenewed lease's ledger tokens flow back by TTL eviction alone
    (the PR-10 reclamation rule): after expiry, a fresh acquire gets the
    full cap again — consumed real-limit tokens stay consumed
    (conservative)."""
    d = (await Cluster.start(1)).daemons[0]
    try:
        c = V1Client(d.conf.grpc_address)
        r1 = await c.lease_quota(pb.LeaseQuotaReq(
            name="ttl", unique_key="k", tokens=50, limit=100,
            duration=MINUTE, ttl_ms=150,
        ))
        assert r1.granted == 50

        async def reclaimed():
            r = await c.lease_quota(pb.LeaseQuotaReq(
                name="ttl", unique_key="k", tokens=50, limit=100,
                duration=MINUTE, ttl_ms=150,
            ))
            return r.granted == 50

        await wait_for(reclaimed, timeout_s=5)
        dbg = d.debug_leases()
        assert dbg["ops"]["expirations"] >= 1
        # real-limit consumption is NOT refunded by expiry — conservative
        chk = (await c.get_rate_limits([pb.RateLimitReq(
            name="ttl", unique_key="k", hits=0, limit=100, duration=MINUTE,
        )])).responses[0]
        assert chk.remaining == 0
        await c.close()
    finally:
        await d.close()


@async_test
async def test_lease_grant_rides_global_behavior():
    """A GLOBAL-flagged lease grant is queued/broadcast like ordinary
    GLOBAL hits — every daemon's view of the key converges to the grant."""
    c = await Cluster.start(2)
    a, b = c.daemons
    try:
        cl = V1Client(a.conf.grpc_address)
        r = await cl.lease_quota(pb.LeaseQuotaReq(
            name="gl", unique_key="k", tokens=30, limit=100,
            duration=MINUTE, behavior=int(Behavior.GLOBAL), ttl_ms=5_000,
        ))
        assert r.granted == 30

        async def converged():
            outs = []
            for dmn in (a, b):
                resp = (await dmn.get_rate_limits([pb.RateLimitReq(
                    name="gl", unique_key="k", hits=0, limit=100,
                    duration=MINUTE, behavior=int(Behavior.GLOBAL),
                )]))[0]
                outs.append(resp.remaining)
            return all(v == 70 for v in outs)

        await wait_for(converged, timeout_s=10)
        await cl.close()
    finally:
        await c.stop()


@async_test
async def test_lease_grant_replicates_multi_region():
    """A MULTI_REGION lease grant replicates through the region merge
    plane — the remote region's view converges to limit - granted, so the
    existing convergence bounds hold for leased consumption verbatim."""
    c = await Cluster.start(2, dcs=["dc-a", "dc-b"])
    a, b = c.daemons
    try:
        cl = V1Client(a.conf.grpc_address)
        r = await cl.lease_quota(pb.LeaseQuotaReq(
            name="mrl", unique_key="k", tokens=25, limit=100,
            duration=MINUTE, behavior=int(Behavior.MULTI_REGION),
            ttl_ms=5_000,
        ))
        assert r.granted == 25

        async def converged():
            resp = (await b.get_rate_limits([pb.RateLimitReq(
                name="mrl", unique_key="k", hits=0, limit=100,
                duration=MINUTE, behavior=int(Behavior.MULTI_REGION),
            )]))[0]
            return resp.remaining == 75

        await wait_for(converged, timeout_s=10)
        await cl.close()
    finally:
        await c.stop()


@async_test
async def test_retry_after_first_class_in_client():
    """V1Client surfaces retry_after_ms as a typed field — no metadata
    string spelunking (PR-11 put it in pb metadata only)."""
    d = (await Cluster.start(1)).daemons[0]
    try:
        c = V1Client(d.conf.grpc_address)
        req = pb.RateLimitReq(
            name="ra", unique_key="k", hits=1, limit=1, duration=MINUTE,
        )
        await c.get_rate_limits([req])
        denied = (await c.check([req]))[0]
        assert denied.status == 1
        assert denied.retry_after_ms > 0
        assert denied.retry_after_ms <= MINUTE
        # the raw helpers agree with the typed field
        raw = (await c.get_rate_limits([req])).responses[0]
        assert response_retry_after_ms(raw) > 0
        assert response_from_pb(raw).retry_after_ms == \
            response_retry_after_ms(raw)
        await c.close()
    finally:
        await d.close()


# -------------------------------------------------------------- edge plane


@async_test
async def test_local_limiter_admits_locally_and_falls_back():
    d = (await Cluster.start(1)).daemons[0]
    try:
        lim = LocalLimiter(
            d.conf.grpc_address, "edge", "u1", limit=100, duration=MINUTE,
            ttl_ms=5_000, initial_grant=10,
        )
        await lim.start()
        assert lim.budget == 10
        for _ in range(10):
            assert lim.allow()
        assert not lim.allow()  # budget gone, renewal in flight
        ok, _ = await lim.check()  # falls back to the per-check RPC
        assert ok
        assert lim.stats.rpc_checks >= 1
        total = lim.stats.local_admits + lim.stats.rpc_admits
        await lim.close()
        chk = (await d.get_rate_limits([pb.RateLimitReq(
            name="edge", unique_key="u1", hits=0, limit=100,
            duration=MINUTE,
        )]))[0]
        assert total <= 100 - chk.remaining  # admissions ≤ consumed
    finally:
        await d.close()


@async_test
async def test_local_limiter_adaptive_sizing():
    """Exhaustion before renewal doubles the grant; an idle lease shrinks
    and returns the excess."""
    d = (await Cluster.start(1)).daemons[0]
    try:
        lim = LocalLimiter(
            d.conf.grpc_address, "adapt", "u", limit=10_000,
            duration=MINUTE, ttl_ms=400, initial_grant=8,
        )
        await lim.start()
        # burn grants as fast as they arrive → exhaustion → doubling
        for _ in range(200):
            lim.allow()
            await asyncio.sleep(0)

        async def doubled():
            while lim.allow():
                pass
            return lim.stats.grants >= 2 and any(
                g > 8 for g in lim.stats.grant_sizes
            )

        await wait_for(doubled, timeout_s=10)
        # now go idle: the next renewals shrink and give tokens back
        peak = max(lim.stats.grant_sizes)

        async def shrunk():
            return (
                lim.stats.tokens_returned > 0
                and lim.stats.grant_sizes[-1] < peak
            )

        await wait_for(shrunk, timeout_s=10)
        await lim.close()
    finally:
        await d.close()


@async_test
async def test_local_limiter_thread_concurrency_exact():
    """Many threads admitting against one lease: the budget accounting
    stays exact (admits + unreturned budget + returns == granted) and
    total admissions never exceed server-side consumption."""
    d = (await Cluster.start(1)).daemons[0]
    try:
        lim = LocalLimiter(
            d.conf.grpc_address, "conc", "u", limit=5_000, duration=MINUTE,
            ttl_ms=300, initial_grant=64,
        )
        await lim.start()
        admitted = [0] * 8
        stop = threading.Event()

        def worker(i):
            while not stop.is_set():
                if lim.allow():
                    admitted[i] += 1
                else:
                    stop.wait(0.001)  # yield so renewals get loop cycles

        loop = asyncio.get_running_loop()
        futs = [
            loop.run_in_executor(None, worker, i) for i in range(8)
        ]
        await asyncio.sleep(1.5)  # several renewals race the admitters
        stop.set()
        await asyncio.gather(*futs)
        await asyncio.sleep(0.05)
        total = sum(admitted)
        assert total == lim.stats.local_admits
        assert total > 0 and lim.stats.grants >= 2
        # exact conservation: every granted token is admitted, still held,
        # or was returned
        assert (
            lim.stats.local_admits + lim.budget + lim.stats.tokens_returned
            == lim.stats.tokens_granted
        )
        await lim.close()
        chk = (await d.get_rate_limits([pb.RateLimitReq(
            name="conc", unique_key="u", hits=0, limit=5_000,
            duration=MINUTE,
        )]))[0]
        assert total <= 5_000 - chk.remaining
    finally:
        await d.close()


@async_test
async def test_local_limiter_daemon_restart_bound():
    """kill -9 + warm restart mid-lease: the client keeps admitting only
    its outstanding budget while the daemon is down (never past lease
    expiry), the restarted daemon remembers consumption through the
    checkpoint plane, and total admissions ≤ limit + outstanding-at-crash."""
    tmp = tempfile.mkdtemp()
    LIMIT = 100
    c = await Cluster.start(
        1,
        checkpoint_path=os.path.join(tmp, "ckpt.bin"),
        checkpoint_interval_ms=25.0,
    )
    try:
        lim = LocalLimiter(
            c.daemons[0].conf.grpc_address, "boom", "k", limit=LIMIT,
            duration=10 * MINUTE, ttl_ms=20_000, initial_grant=30,
        )
        await lim.start()
        assert lim.stats.tokens_granted == 30
        for _ in range(10):
            assert lim.allow()
        outstanding_at_crash = lim.budget
        assert outstanding_at_crash == 20
        # let the incremental checkpoint cover every grant write
        await asyncio.sleep(0.3)
        await c.crash_restart(0)
        # the lease outlives the restart: the edge may keep admitting its
        # outstanding slice (that IS the documented over-admission)
        while lim.allow():
            pass
        # drain whatever the restarted daemon will still lease or serve
        for _ in range(3 * LIMIT):
            ok, _ = await lim.check()
            await asyncio.sleep(0)
        total = lim.stats.local_admits + lim.stats.rpc_admits
        assert total <= LIMIT + outstanding_at_crash, (
            f"admitted {total} > limit {LIMIT} + "
            f"outstanding {outstanding_at_crash}"
        )
        # and the plane did NOT collapse to zero either: the restarted
        # daemon serves (lease or per-check) from the remembered budget
        assert total >= outstanding_at_crash
        await lim.close()
    finally:
        await c.stop()
