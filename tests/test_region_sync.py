"""Multi-region active-active replication (docs/robustness.md).

The region plane rebuilt on the conservative-merge kernel: per-key hit
deltas ride the compact SyncRegionsWire codec to each remote region's owner,
which reconciles through kernel2.merge2 (ops/reconcile.py) — never the
serving path. These tests pin the three contracts:

* exactness — with every delta delivered once, each region's per-key state
  converges to the exact union of all regions' hits;
* conservatism — duplicated delivery (requeue at-least-once), crossed
  layouts, and stale sender rows can only UNDER-grant, never over;
* partition tolerance — a blackholed inter-region link opens the breaker,
  the partitioned region keeps serving locally with zero request errors,
  the staleness gauge grows monotonically, and after heal the requeued
  backlog drains through the merge until both regions reconverge.
"""

import asyncio
import functools
import time

import grpc
import numpy as np
import pytest

from gubernator_tpu.config import BehaviorConfig
from gubernator_tpu.ops.batch import RequestColumns
from gubernator_tpu.ops.engine import LocalEngine, ms_now
from gubernator_tpu.ops.reconcile import apply_region_sync
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.service.peer_client import PeerError
from gubernator_tpu.types import Behavior, PeerInfo

from tests.cluster import Cluster, metric_value, scrape, wait_for

NOW = ms_now()
MINUTE = 60_000


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


def _cols(fps, hits, limit=100, dur=MINUTE, algo=0, now=NOW):
    n = len(fps)
    return RequestColumns(
        fp=np.asarray(fps, dtype=np.int64),
        algo=np.full(n, algo, dtype=np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=np.full(n, hits, dtype=np.int64),
        limit=np.full(n, limit, dtype=np.int64),
        burst=np.zeros(n, dtype=np.int64),
        duration=np.full(n, dur, dtype=np.int64),
        created_at=np.full(n, now, dtype=np.int64),
        err=np.zeros(n, dtype=np.int8),
    )


def _cfg(algo=0, limit=100, dur=MINUTE, now=NOW, n=1):
    return {
        "limit": np.full(n, limit, dtype=np.int64),
        "duration": np.full(n, dur, dtype=np.int64),
        "algo": np.full(n, algo, dtype=np.int64),
        "created_at": np.full(n, now, dtype=np.int64),
    }


def _ship(src: LocalEngine, dst: LocalEngine, fp: int, delta: int,
          algo=0, now=NOW):
    """One region→region delta hop through the real staging read + merge."""
    fps = np.array([fp], dtype=np.int64)
    _found, slots = src.read_state(fps, raw=True)
    return apply_region_sync(
        dst, fps, np.array([delta], dtype=np.int64), _cfg(algo, now=now),
        slots, src.table.layout, now_ms=now,
    )


# --------------------------------------------------------------- unit layer


def test_reconcile_exact_union_token():
    """Concurrent hits in two regions converge to the exact union after one
    delta exchange each way — the op-based-CRDT exactness contract."""
    A, B = LocalEngine(capacity=4096), LocalEngine(capacity=4096)
    assert A.check_columns(_cols([42], 3), now_ms=NOW).remaining[0] == 97
    assert B.check_columns(_cols([42], 4), now_ms=NOW).remaining[0] == 96
    assert _ship(A, B, 42, 3, now=NOW + 1) == 1
    assert _ship(B, A, 42, 4, now=NOW + 1) == 1
    ra = A.check_columns(_cols([42], 0), now_ms=NOW + 2).remaining[0]
    rb = B.check_columns(_cols([42], 0), now_ms=NOW + 2).remaining[0]
    assert ra == rb == 93  # 100 - (3 + 4)


def test_reconcile_duplicate_delivery_under_grants_only():
    """At-least-once delivery (a requeue after a lost ack) re-applies the
    delta — the merge turns that into UNDER-grant, never over."""
    A, B = LocalEngine(capacity=4096), LocalEngine(capacity=4096)
    A.check_columns(_cols([7], 5), now_ms=NOW)
    B.check_columns(_cols([7], 2), now_ms=NOW)
    _ship(A, B, 7, 5, now=NOW + 1)
    exact = B.check_columns(_cols([7], 0), now_ms=NOW + 2).remaining[0]
    assert exact == 93
    _ship(A, B, 7, 5, now=NOW + 3)  # duplicate
    dup = B.check_columns(_cols([7], 0), now_ms=NOW + 4).remaining[0]
    assert dup <= exact  # tightened, never loosened


def test_reconcile_gcra_matches_union_oracle():
    """GCRA deltas advance the receiver's stored TAT by delta·T — the
    merged state answers exactly like one engine that saw the union."""
    A, B = LocalEngine(capacity=4096), LocalEngine(capacity=4096)
    O = LocalEngine(capacity=4096)
    A.check_columns(_cols([9], 10, algo=2), now_ms=NOW)
    B.check_columns(_cols([9], 5, algo=2), now_ms=NOW)
    O.check_columns(_cols([9], 15, algo=2), now_ms=NOW)
    _ship(A, B, 9, 10, algo=2, now=NOW + 1)
    rb = B.check_columns(_cols([9], 0, algo=2), now_ms=NOW + 2).remaining[0]
    ro = O.check_columns(_cols([9], 0, algo=2), now_ms=NOW + 2).remaining[0]
    assert rb == ro


def test_reconcile_over_limit_clamps_and_over_sticks():
    """A delta beyond the bucket clamps remaining at 0 and sets OVER, which
    the merge keeps sticky."""
    B = LocalEngine(capacity=4096)
    B.check_columns(_cols([11], 1), now_ms=NOW)
    fps = np.array([11], dtype=np.int64)
    apply_region_sync(
        B, fps, np.array([500], dtype=np.int64), _cfg(), None, None,
        now_ms=NOW + 1,
    )
    rc = B.check_columns(_cols([11], 0), now_ms=NOW + 2)
    assert rc.remaining[0] == 0
    assert rc.status[0] == 1  # OVER_LIMIT


def test_reconcile_absent_key_bootstraps_from_sender_row():
    """A receiver that never saw the key adopts the sender's stored row
    (which already embodies the delta plus any older history)."""
    A, C = LocalEngine(capacity=4096), LocalEngine(capacity=4096)
    A.check_columns(_cols([13], 9), now_ms=NOW)
    _ship(A, C, 13, 9, now=NOW + 1)
    assert C.check_columns(
        _cols([13], 0), now_ms=NOW + 2
    ).remaining[0] == 91


def test_reconcile_cross_layout_sender_converts_through_full():
    """Packed (token32/gcra32) senders ship rows at their native width; the
    receiver converts through the canonical full row before merge2 — a
    mixed-layout fleet can neither corrupt nor over-grant (PR-11 single
    conversion point, satellite bugfix)."""
    for lay, algo in (("token32", 0), ("gcra32", 2)):
        P = LocalEngine(capacity=4096, layout=lay)
        Q = LocalEngine(capacity=4096)  # full receiver
        O = LocalEngine(capacity=4096)
        P.check_columns(_cols([77], 9, algo=algo), now_ms=NOW)
        Q.check_columns(_cols([77], 4, algo=algo), now_ms=NOW)
        O.check_columns(_cols([77], 13, algo=algo), now_ms=NOW)
        assert P.table.layout.F == 8  # really shipped packed
        _ship(P, Q, 77, 9, algo=algo, now=NOW + 1)
        rq = Q.check_columns(
            _cols([77], 0, algo=algo), now_ms=NOW + 2
        ).remaining[0]
        ro = O.check_columns(
            _cols([77], 0, algo=algo), now_ms=NOW + 2
        ).remaining[0]
        assert rq == ro, f"{lay}: {rq} != oracle {ro}"
        # and the reverse hop: full sender → packed receiver
        _ship(Q, P, 77, 4, algo=algo, now=NOW + 3)
        rp = P.check_columns(
            _cols([77], 0, algo=algo), now_ms=NOW + 4
        ).remaining[0]
        assert rp == ro, f"{lay} reverse: {rp} != oracle {ro}"


def test_region_codec_split_and_roundtrip():
    """Per-item encodability split: plain deltas ride the compact codec,
    resets / Gregorian / lease releases / metadata carriers spill to the
    proto fallback — and the lane image decodes back exactly."""
    from gubernator_tpu.service.wire import (
        split_region_encodable, sync_regions_arrays, sync_regions_pb,
    )

    ok = pb.RateLimitReq(
        name="mr", unique_key="k1", hits=5, limit=100, duration=MINUTE,
        behavior=int(Behavior.MULTI_REGION), created_at=NOW,
    )
    reset = pb.RateLimitReq(
        name="mr", unique_key="k2", hits=1, limit=100, duration=MINUTE,
        behavior=int(Behavior.MULTI_REGION | Behavior.RESET_REMAINING),
        created_at=NOW,
    )
    greg = pb.RateLimitReq(
        name="mr", unique_key="k3", hits=1, limit=100, duration=1,
        behavior=int(
            Behavior.MULTI_REGION | Behavior.DURATION_IS_GREGORIAN
        ),
        created_at=NOW,
    )
    release = pb.RateLimitReq(
        name="mr", unique_key="k4", hits=-2, limit=100, duration=MINUTE,
        algorithm=4, behavior=int(Behavior.MULTI_REGION), created_at=NOW,
    )
    skewed = pb.RateLimitReq(
        name="mr", unique_key="k5", hits=1, limit=100, duration=MINUTE,
        behavior=int(Behavior.MULTI_REGION), created_at=NOW + 10_000,
    )
    pairs = [
        ("mr_k1", ok), ("mr_k2", reset), ("mr_k3", greg),
        ("mr_k4", release), ("mr_k5", skewed),
    ]
    enc, fb = split_region_encodable(pairs)
    assert [k for k, _ in enc] == ["mr_k1"]
    assert [k for k, _ in fb] == ["mr_k2", "mr_k3", "mr_k4", "mr_k5"]
    req = sync_regions_pb(enc, "127.0.0.1:1", "dc-a")
    fps, deltas, cfg, hks, slots, lay, cums = sync_regions_arrays(req)
    assert cums is None  # no cum ledger passed = pre-dedup shape
    from gubernator_tpu.hashing import fingerprint

    assert fps[0] == fingerprint("mr", "k1")
    assert deltas[0] == 5 and hks == ["mr_k1"] and slots is None
    assert int(cfg["limit"][0]) == 100
    assert int(cfg["duration"][0]) == MINUTE
    assert int(cfg["created_at"][0]) == NOW


def test_dedup_source_deltas_rules():
    """The receiver-side ledger math (ops/reconcile.dedup_source_deltas):
    exact-duplicate skip, partial overlap, sender reset, and the
    dropped-batch cap — every branch errs toward applying LESS."""
    from gubernator_tpu.ops.reconcile import (
        commit_source_cums, dedup_source_deltas,
    )

    fps = np.array([1, 2, 3, 4], dtype=np.int64)
    ledger: dict = {}
    d0 = np.array([5, 3, 7, 2], dtype=np.int64)
    c0 = np.array([5, 3, 7, 2], dtype=np.int64)
    assert (dedup_source_deltas(ledger, fps, d0, c0) == d0).all()
    commit_source_cums(ledger, fps, c0)
    # exact re-ship: skipped EXACTLY
    assert (dedup_source_deltas(ledger, fps, d0, c0) == 0).all()
    # partial overlap: key 1 re-ships 5 old + 4 new (delta 9, cum 9)
    d1 = np.array([9], dtype=np.int64)
    c1 = np.array([9], dtype=np.int64)
    assert dedup_source_deltas(ledger, fps[:1], d1, c1)[0] == 4
    # dropped-batch gap: cum jumped past delta (sender dropped a batch) —
    # apply only what THIS batch carries, never fabricate the gap
    d2 = np.array([2], dtype=np.int64)
    c2 = np.array([50], dtype=np.int64)
    assert dedup_source_deltas(ledger, fps[:1], d2, c2)[0] == 2
    # sender reset (restart / ledger cap): counter went backwards — apply
    # the delta as shipped and re-baseline
    d3 = np.array([3], dtype=np.int64)
    c3 = np.array([3], dtype=np.int64)
    assert dedup_source_deltas(ledger, fps[:1], d3, c3)[0] == 3
    commit_source_cums(ledger, fps[:1], c3)
    assert ledger[1] == 3
    # no cums (pre-dedup sender): deltas pass through verbatim
    assert (dedup_source_deltas(ledger, fps, d0, None) == d0).all()


@async_test
async def test_duplicate_delivery_skipped_exactly():
    """ROADMAP multi-region follow-up (d): a re-shipped batch after a lost
    ack is skipped EXACTLY by the per-source cumulative counters — the
    receiver's state is bit-stable under duplicate delivery, not merely
    under-granting."""
    from gubernator_tpu.service.wire import (
        split_region_encodable, sync_regions_pb,
    )

    c = await Cluster.start(1, dcs=["dc-b"])
    d = c.daemons[0]
    try:
        async def remaining():
            return (await d.get_rate_limits([pb.RateLimitReq(
                name="dup", unique_key="k", hits=0, limit=100,
                duration=MINUTE,
            )]))[0].remaining

        def batch(hits, cum):
            it = pb.RateLimitReq(
                name="dup", unique_key="k", hits=hits, limit=100,
                duration=MINUTE, behavior=int(Behavior.MULTI_REGION),
                created_at=d.now_ms(),
            )
            enc, fb = split_region_encodable([("dup_k", it)])
            assert enc and not fb
            return sync_regions_pb(
                enc, "sender:1", "dc-a",
                cums=np.array([cum], dtype=np.int64),
            )

        req = batch(5, 5)
        await d.sync_regions_wire(req)
        assert await remaining() == 95
        # the lost-ack retry: same batch again, twice
        await d.sync_regions_wire(req)
        await d.sync_regions_wire(req)
        assert await remaining() == 95  # EXACT, not merely ≤
        assert d.region_manager.dedup_skipped == 10
        assert d.region_manager.debug()["wire"]["dedup_skipped_hits"] == 10
        # a requeue FOLDED with fresh hits (delta 5 old + 3 new, cum 8):
        # only the 3 unseen hits apply
        await d.sync_regions_wire(batch(8, 8))
        assert await remaining() == 92
        # pre-dedup sender (no cums): legacy at-least-once under-grant
        it = pb.RateLimitReq(
            name="dup", unique_key="k", hits=2, limit=100,
            duration=MINUTE, behavior=int(Behavior.MULTI_REGION),
            created_at=d.now_ms(),
        )
        enc, _ = split_region_encodable([("dup_k", it)])
        legacy = sync_regions_pb(enc, "old:1", "dc-a")
        await d.sync_regions_wire(legacy)
        assert await remaining() == 90
        await d.sync_regions_wire(legacy)
        assert await remaining() <= 90  # under-grant only, never over
    finally:
        await c.stop()


@async_test
async def test_sender_ships_cumulative_counters():
    """The sender's per-(region, key) cumulative ledger increments at
    queue time only (requeues don't double-count) and rides every
    compact-wire batch — two-region traffic converges exactly AND the
    counters on the wire match the queued totals."""
    c = await Cluster.start(2, dcs=["dc-a", "dc-b"])
    a, b = c.daemons
    try:
        for hits in (3, 4):
            r = (await a.get_rate_limits([_mr("ck", hits)]))[0]
            assert not r.error

        async def landed():
            return (await b.get_rate_limits(
                [_mr("ck", 0)]
            ))[0].remaining == 93

        await wait_for(landed, timeout_s=10)
        # sender-side cumulative for dc-b reflects every queued hit
        assert a.region_manager._cum["dc-b"]["mr_ck"] == 7
        # receiver-side ledger committed the same cum under a's address
        src_ledgers = b.region_manager._recv_cum
        assert any(
            7 in led.values() for led in src_ledgers.values()
        ), src_ledgers
    finally:
        await c.stop()


# ---------------------------------------------------------------- e2e layer


def _beh(**kw):
    base = dict(
        batch_wait_ms=1.0,
        global_sync_wait_ms=50.0,
        batch_timeout_ms=5000.0,
        global_timeout_ms=5000.0,
    )
    base.update(kw)
    return BehaviorConfig(**base)


def _mr(key, hits, limit=100, name="mr", behavior=int(Behavior.MULTI_REGION)):
    return pb.RateLimitReq(
        name=name, unique_key=key, hits=hits, limit=limit,
        duration=MINUTE, behavior=behavior,
    )


@async_test
async def test_two_region_convergence_via_merge_wire():
    """Two-region active-active: concurrent hits in both regions converge
    to the exact union through the compact merge codec (zero proto
    fallbacks), and never ping-pong back."""
    c = await Cluster.start(2, dcs=["dc-a", "dc-b"])
    a, b = c.daemons
    try:
        out = await a.get_rate_limits([_mr("k1", 3)])
        assert out[0].error == "" and out[0].remaining == 97
        out = await b.get_rate_limits([_mr("k1", 4)])
        assert out[0].error == "" and out[0].remaining == 96

        async def converged():
            ra = (await a.get_rate_limits([_mr("k1", 0)]))[0].remaining
            rb = (await b.get_rate_limits([_mr("k1", 0)]))[0].remaining
            return ra == rb == 93

        await wait_for(converged, timeout_s=10)
        # compact-wire engagement, no fallbacks, merge receive accounting
        assert a.region_manager.wire_sent >= 1
        assert b.region_manager.wire_sent >= 1
        assert a.region_manager.wire_fallback == 0
        assert b.region_manager.wire_fallback == 0
        assert a.region_manager.rows_merged >= 1
        assert b.region_manager.rows_merged >= 1
        # no ping-pong: two extra sync intervals change nothing
        await asyncio.sleep(0.2)
        assert (await a.get_rate_limits([_mr("k1", 0)]))[0].remaining == 93
        assert (await b.get_rate_limits([_mr("k1", 0)]))[0].remaining == 93
        # staleness drained
        assert a.region_manager.oldest_delta_age_s() == 0.0
    finally:
        await c.stop()


@async_test
async def test_non_encodable_items_ride_proto_fallback():
    """RESET_REMAINING cannot travel through a min-merge; it rides the
    classic proto path (legacy DRAIN semantics) and still lands."""
    c = await Cluster.start(2, dcs=["dc-a", "dc-b"])
    a, b = c.daemons
    try:
        await a.get_rate_limits([_mr("kr", 30)])

        async def replicated():
            r = (await b.get_rate_limits([_mr("kr", 0)]))[0]
            return r.remaining == 70

        await wait_for(replicated, timeout_s=10)
        out = await a.get_rate_limits([_mr(
            "kr", 1,
            behavior=int(Behavior.MULTI_REGION | Behavior.RESET_REMAINING),
        )])
        assert out[0].error == ""
        want = (await a.get_rate_limits([_mr("kr", 0)]))[0].remaining
        assert want > 70  # the reset raised A's bucket

        async def reset_landed():
            r = (await b.get_rate_limits([_mr("kr", 0)]))[0]
            return r.remaining == want
        await wait_for(reset_landed, timeout_s=10)
        assert a.region_manager.wire_fallback >= 1
    finally:
        await c.stop()


@async_test
async def test_pre_upgrade_peer_latches_proto_fallback():
    """An UNIMPLEMENTED answer (pre-region-merge peer) latches the compact
    path off for that peer; the batch re-ships as proto in the same round
    and the regions still converge."""
    c = await Cluster.start(2, dcs=["dc-a", "dc-b"])
    a, b = c.daemons
    try:
        binfo = next(iter(a._peer_clients))
        client = a._peer_clients[binfo]

        class FakeUnimplemented(Exception):
            def code(self):
                return grpc.StatusCode.UNIMPLEMENTED

        async def refuse(req, timeout=None):
            raise PeerError(binfo, FakeUnimplemented())

        client.sync_regions_wire = refuse
        await a.get_rate_limits([_mr("ku", 5)])

        async def replicated():
            r = (await b.get_rate_limits([_mr("ku", 0)]))[0]
            return r.remaining == 95

        await wait_for(replicated, timeout_s=10)
        assert client.region_wire_ok is False
        assert a.region_manager.wire_fallback >= 1
        assert a.region_manager.wire_sent == 0
    finally:
        await c.stop()


@async_test
async def test_cascade_levels_span_regions():
    """A MULTI_REGION cascade carrier replicates its own delta AND one per
    level, each under the level's own key — every level's count converges
    across regions (the GLOBAL-behavior cascade extended to regions)."""
    c = await Cluster.start(2, dcs=["dc-a", "dc-b"])
    a, b = c.daemons
    try:
        req = _mr("user1", 2, name="percall")
        req.cascade.append(pb.CascadeLevel(
            name="tenant", unique_key="t1", limit=1000, duration=MINUTE,
        ))
        out = await a.get_rate_limits([req])
        assert out[0].error == ""
        assert len(out[0].cascade) == 1

        async def both_converged():
            r1 = (await b.get_rate_limits(
                [_mr("user1", 0, name="percall")]
            ))[0]
            r2 = (await b.get_rate_limits([pb.RateLimitReq(
                name="tenant", unique_key="t1", hits=0, limit=1000,
                duration=MINUTE,
            )]))[0]
            return r1.remaining == 98 and r2.remaining == 998

        await wait_for(both_converged, timeout_s=10)
        assert a.region_manager.wire_fallback == 0
    finally:
        await c.stop()


@async_test
async def test_debug_regions_endpoint_and_health_region():
    """/v1/debug/regions schema + the region label in HealthCheckResp."""
    import aiohttp

    c = await Cluster.start(2, dcs=["dc-a", "dc-b"])
    a, b = c.daemons
    try:
        h = await a.health_check()
        assert h.region == "dc-a"
        assert (await b.health_check()).region == "dc-b"
        await a.get_rate_limits([_mr("kd", 1)])
        url = f"http://{a.conf.http_address}/v1/debug/regions"
        async with aiohttp.ClientSession() as s:
            async with s.get(url) as resp:
                assert resp.status == 200
                snap = await resp.json()
        assert snap["region"] == "dc-a"
        assert "dc-b" in snap["regions"]
        dcb = snap["regions"]["dc-b"]
        for field in (
            "queue_depth", "oldest_delta_age_s", "last_sync_age_s",
            "requeue_attempts", "peers",
        ):
            assert field in dcb
        assert dcb["peers"][0]["breaker_state"] == "closed"
        assert {"sent", "recv", "fallback", "rows_merged"} <= set(
            snap["wire"]
        )
    finally:
        await c.stop()


@pytest.mark.slow
@async_test
async def test_partition_degraded_local_then_heal_converges():
    """The headline robustness contract (ISSUE 12 acceptance): blackhole
    the inter-region link for ≥10 sync intervals; the partitioned regions
    keep answering locally with ZERO request errors and bounded latency,
    the breaker opens, the staleness gauge grows monotonically, total
    admissions stay ≤ the sum of per-region limits; after heal the backlog
    drains through the merge, staleness returns to 0, and both regions
    converge to the exact union of hits."""
    c = await Cluster.start(
        2, dcs=["dc-a", "dc-b"], chaos=True,
        behaviors=_beh(
            global_timeout_ms=150.0,
            region_timeout_ms=150.0,  # fail fast so the breaker trips
            region_requeue_retries=10_000,  # ride out the whole partition
            peer_breaker_errors=3,
            peer_breaker_backoff_base_ms=200.0,
            peer_breaker_backoff_cap_ms=1_000.0,
        ),
    )
    a, b = c.daemons
    try:
        # one exchange while healthy, so both sides hold the key
        await a.get_rate_limits([_mr("pk", 2)])
        await b.get_rate_limits([_mr("pk", 3)])

        async def warm():
            ra = (await a.get_rate_limits([_mr("pk", 0)]))[0].remaining
            rb = (await b.get_rate_limits([_mr("pk", 0)]))[0].remaining
            return ra == rb == 95

        await wait_for(warm, timeout_s=10)

        # ---- partition: blackhole BOTH directions
        for p in c.proxies:
            p.set_mode("blackhole")
        t_part = time.monotonic()
        admitted = {id(a): 0, id(b): 0}
        errors = 0
        stale_samples = []
        # ≥ 10 sync intervals (50 ms cadence) under live traffic, long
        # enough for 3 consecutive 150 ms send timeouts to trip the breaker
        while time.monotonic() - t_part < 2.0:
            for d in (a, b):
                t0 = time.monotonic()
                out = await d.get_rate_limits([_mr("pk", 1)])
                assert time.monotonic() - t0 < 1.0, "serving stalled"
                if out[0].error:
                    errors += 1
                elif out[0].status == pb.UNDER_LIMIT:
                    admitted[id(d)] += 1
            stale_samples.append(a.region_manager.oldest_delta_age_s())
            await asyncio.sleep(0.02)
        assert errors == 0, f"{errors} request errors during the partition"
        # staleness grew monotonically (requeues must not reset it)
        assert stale_samples[-1] > 0
        assert all(
            b2 >= a2 - 1e-3
            for a2, b2 in zip(stale_samples, stale_samples[1:])
        )
        # the breaker toward the dead region opened → sends fail fast
        states = {
            cl.breaker.state_name for cl in a._peer_clients.values()
        }
        assert "open" in states or "half-open" in states
        # bounded over-admission: each region admits at most its own limit
        total = 5 + admitted[id(a)] + admitted[id(b)]
        assert total <= 2 * 100  # Σ per-region limits
        for d in (a, b):
            r = (await d.get_rate_limits([_mr("pk", 0)]))[0]
            assert r.remaining >= 0

        # ---- heal: backlog drains through the merge, regions reconverge
        for p in c.proxies:
            p.heal()

        async def reconverged():
            ra = (await a.get_rate_limits([_mr("pk", 0)]))[0].remaining
            rb = (await b.get_rate_limits([_mr("pk", 0)]))[0].remaining
            want = max(0, 100 - total)
            return ra == rb == want

        await wait_for(reconverged, timeout_s=30, interval_s=0.1)
        await wait_for(
            lambda: _zero_stale(a, b), timeout_s=30, interval_s=0.1
        )
        # and the wire path carried the backlog (fallbacks stayed zero)
        assert a.region_manager.wire_fallback == 0
        assert b.region_manager.wire_fallback == 0
        s = await scrape(a)
        assert metric_value(
            s, "gubernator_region_sync_staleness_seconds"
        ) == 0.0
    finally:
        await c.stop()


async def _zero_stale(a, b):
    return (
        a.region_manager.oldest_delta_age_s() == 0.0
        and b.region_manager.oldest_delta_age_s() == 0.0
    )


@async_test
async def test_requeue_bounded_drops_counted():
    """With retries exhausted (GUBER_REGION_REQUEUE_RETRIES=0) a partition
    degrades to the reference's drop behavior: deltas drop, the drop is
    counted, the queue never grows unbounded, and staleness resets."""
    c = await Cluster.start(
        2, dcs=["dc-a", "dc-b"], chaos=True,
        behaviors=_beh(
            global_timeout_ms=200.0, region_timeout_ms=200.0,
            region_requeue_retries=0,
        ),
    )
    a, b = c.daemons
    try:
        for p in c.proxies:
            p.set_mode("blackhole")
        await a.get_rate_limits([_mr("dk", 5)])

        async def dropped():
            s = await scrape(a)
            return metric_value(
                s, "gubernator_region_requeue_dropped_count_total"
            ) >= 1

        await wait_for(dropped, timeout_s=10)
        assert a.region_manager._queue_len() == 0
        assert a.region_manager.oldest_delta_age_s() == 0.0
    finally:
        await c.stop()


@async_test
async def test_cross_layout_two_region_daemons():
    """A packed-layout (token32) region replicating to a full-layout region
    and back: both converge to the exact union — the mixed-layout fleet
    contract end-to-end over the real wire."""
    from tests.cluster import daemon_config

    confs = [daemon_config(dc="dc-a"), daemon_config(dc="dc-b")]
    from gubernator_tpu.service.daemon import Daemon

    a = await Daemon.spawn(
        confs[0], engine=LocalEngine(capacity=8192, layout="token32")
    )
    b = await Daemon.spawn(confs[1])
    try:
        peers = [a.peer_info(), b.peer_info()]
        for d in (a, b):
            d.set_peers([PeerInfo(**vars(p)) for p in peers])
        assert a.engine.table.layout.name == "token32"
        out = await a.get_rate_limits([_mr("xk", 6)])
        assert out[0].error == "" and out[0].remaining == 94
        out = await b.get_rate_limits([_mr("xk", 3)])
        assert out[0].error == "" and out[0].remaining == 97

        async def converged():
            ra = (await a.get_rate_limits([_mr("xk", 0)]))[0].remaining
            rb = (await b.get_rate_limits([_mr("xk", 0)]))[0].remaining
            return ra == rb == 91

        await wait_for(converged, timeout_s=10)
        assert a.engine.table.layout.name == "token32"  # no migration
        assert a.region_manager.wire_sent >= 1
        assert b.region_manager.wire_sent >= 1
        assert a.region_manager.wire_fallback == 0
    finally:
        await asyncio.gather(a.close(), b.close())
